"""Per-query bench watchdog: one dead backend (or injected failure) skips
that query with an error JSON line and the run CONTINUES — the failure mode
that lost Q5–Q18 in BENCH_TPU_LIVE.json must cost one query, not the run.
Also checks the measured compile_s split: warm runs re-dispatch cached
compiled fragments, so warm_compile_s ~ 0 while the cold run pays the
compiles."""

import os
import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import bench  # noqa: E402
from tidb_tpu.testkit import TestKit  # noqa: E402


@pytest.fixture(scope="module")
def tpch_tk():
    tk = TestKit()
    n = bench.gen_all(tk, 0.001)
    return tk, n


def _run(tk, n, qnames, monkeypatch, fail="", budget_s=0):
    emitted = []
    monkeypatch.setattr(bench, "_emit", lambda obj: emitted.append(obj))
    monkeypatch.setattr(bench, "_COMPLETED", [0])
    if fail:
        monkeypatch.setenv("BENCH_FAIL_QUERY", fail)
    else:
        monkeypatch.delenv("BENCH_FAIL_QUERY", raising=False)
    failures = bench._bench_loop(
        tk, qnames, 0.001, n, {"platform": "cpu", "fallback": True,
                               "sf": 0.001}, query_budget_s=budget_s)
    return failures, emitted


def test_injected_failure_skips_query_and_run_continues(tpch_tk,
                                                        monkeypatch):
    tk, n = tpch_tk
    failures, emitted = _run(tk, n, ["q1", "q3"], monkeypatch, fail="q1")
    assert failures == 1
    q1 = [e for e in emitted if e["metric"].startswith("tpch_q1")]
    assert len(q1) == 1 and "injected backend failure" in q1[0]["error"]
    # the run CONTINUED: q3 completed with a real result line
    q3 = [e for e in emitted if e["metric"].startswith("tpch_q3")]
    assert q3 and q3[-1]["value"] > 0 and "error" not in q3[-1]
    assert q3[-1]["vs_baseline"] > 0  # host reference ran too


def test_warm_compile_s_amortized(tpch_tk, monkeypatch):
    """Acceptance: warm-run compile_s < 10% of cold-run compile_s (the
    compiled-fragment cache + shape buckets make the timed runs
    dispatch-only). CPU-fallback numbers are acceptable per the issue."""
    tk, n = tpch_tk
    failures, emitted = _run(tk, n, ["q1", "q18"], monkeypatch)
    assert failures == 0
    for qname in ("q1", "q18"):
        line = [e for e in emitted
                if e["metric"] == f"tpch_{qname}_sf0.001_device_rows_per_sec"]
        assert line, f"no result line for {qname}: {emitted}"
        rec = line[0]
        # cold run pays real compiles; warm runs re-dispatch cached
        # programs
        assert rec["compile_s"] > 0, rec
        assert rec["warm_compile_s"] < 0.1 * rec["compile_s"], rec


def test_supervisor_skips_hung_query_and_run_continues(tpch_tk,
                                                       monkeypatch):
    """Layer 1 of the watchdog stack: a backend HANG (GIL-blocked in the
    real failure; an injected sleep here) inside one benchmarked query is
    abandoned by the device-runtime supervisor at the per-query budget —
    error JSON line, fresh session, and the NEXT query completes."""
    import time

    from tidb_tpu.executor import supervisor
    from tidb_tpu.utils import failpoint

    tk, n = tpch_tk
    # hang only q1's first device dispatch (past the budget); q3 must run
    # clean after — its post-fence COLD compile (~3s on XLA-CPU) must fit
    # the budget, hence 8s/12s rather than something snappier
    failpoint.enable("device-agg-exec", "1*sleep(12)")
    try:
        failures, emitted = _run(tk, n, ["q1", "q3"], monkeypatch,
                                 budget_s=8)
    finally:
        failpoint.disable("device-agg-exec")
    assert failures == 1
    q1 = [e for e in emitted if e["metric"].startswith("tpch_q1")]
    assert len(q1) == 1 and q1[0].get("watchdog") == "supervisor", q1
    assert "DeviceHangError" in q1[0]["error"]
    q3 = [e for e in emitted if e["metric"].startswith("tpch_q3")]
    assert q3 and q3[-1]["value"] > 0 and "error" not in q3[-1]
    # the abandoned worker drains once its sleep ends
    deadline = time.monotonic() + 10.0
    while supervisor.abandoned_calls() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert supervisor.abandoned_calls() == 0


def test_query_timeout_exception_is_skippable():
    # _QueryTimeout must flow through the generic error path (a skip),
    # not kill the loop
    assert issubclass(bench._QueryTimeout, Exception)


def test_arm_is_noop_without_handler():
    # a test/caller that never installed the SIGALRM handler must not arm
    # the default (process-killing) action
    assert not bench._ALARM_READY[0]
    bench._arm_query_alarm(5)  # no handler installed: must be a no-op
    import signal
    assert signal.alarm(0) == 0  # nothing pending
