"""HBM residency manager (ops/residency.py): byte-accounted, budgeted,
epoch-scoped device caches; the OOM recovery ladder (evict-all → single
retry → host degradation); the hardened device-OOM taxonomy; gauge
surfacing in EXPLAIN ANALYZE / observe / HTTP status; and the
``._device`` containment AST lint."""

import ast
import gc
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from tidb_tpu.executor import supervisor
from tidb_tpu.executor.circuit import get_breaker
from tidb_tpu.ops import device as dev
from tidb_tpu.ops import residency
from tidb_tpu.sqltypes import FieldType, TYPE_LONG
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.backoff import (
    CLASS_DEVICE, CLASS_FAULT, CLASS_TRANSPORT, classify, is_device_oom)
from tidb_tpu.utils.chunk import Column
from tidb_tpu.utils.failpoint import FailpointError, InjectedOOMError


def _int_col(n, seed=0):
    return Column(FieldType(TYPE_LONG),
                  np.arange(seed, seed + n, dtype=np.int64))


@pytest.fixture()
def clean_budget():
    residency.set_budget(0)
    yield
    residency.set_budget(0)


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t1 (id int primary key, grp int, val int)")
    tk.must_exec("create table t2 (id int primary key, ref int, amt int)")
    tk.must_exec("insert into t1 values " + ",".join(
        f"({i},{i % 5},{i * 3 % 97})" for i in range(200)))
    tk.must_exec("insert into t2 values " + ",".join(
        f"({i},{i % 200},{i * 7 % 89})" for i in range(200)))
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    tk.must_exec("set tidb_device_dispatch_rows = 1")
    yield tk
    deadline = time.monotonic() + 5.0
    while supervisor.abandoned_calls() and time.monotonic() < deadline:
        time.sleep(0.01)


AGG_Q = "select grp, sum(val) from t1 group by grp order by grp"
JOIN_Q = ("select t1.grp, sum(t2.amt) from t1 join t2 on t1.id = t2.ref "
          "group by t1.grp order by t1.grp")


# -- device-OOM taxonomy (satellite: hardened classify) ----------------------

class TestDeviceOOMTaxonomy:
    #: (exception factory, expected class, expected is_device_oom) — THE
    #: taxonomy table for the OOM ladder's admission test
    TABLE = [
        # jaxlib's canonical phrasing
        (lambda: RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                              "allocating 1073741824 bytes"),
         CLASS_DEVICE, True),
        # PJRT / TFRT allocator phrasings
        (lambda: RuntimeError("Resource exhausted: Failed to allocate "
                              "request for 2.0GiB"),
         CLASS_DEVICE, True),
        (lambda: RuntimeError("Allocation failure: OUT_OF_MEMORY on "
                              "device ordinal 0"),
         CLASS_DEVICE, True),
        (lambda: RuntimeError("Attempting to reserve 5.1G at the bottom "
                              "of memory. That was not possible. "
                              "Exceeds the amount of memory available"),
         CLASS_DEVICE, True),
        # the injected failpoint OOM mimics the canonical phrasing
        (lambda: InjectedOOMError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 8 bytes "
            "(injected by failpoint device-upload-oom)"),
         CLASS_DEVICE, True),
        # a device error that is NOT memory pressure: no evict/retry
        (lambda: _XlaLike("INTERNAL: during context [pre-optimization]: "
                          "Invalid argument"),
         CLASS_DEVICE, False),
        # a SUBCLASS of XlaRuntimeError whose leaf name says nothing —
        # the MRO walk must still classify it `device`
        (lambda: _XlaSubclass("something broke"), CLASS_DEVICE, False),
        # non-device classes never admit the OOM ladder
        (lambda: FailpointError("failpoint device-agg-exec triggered"),
         CLASS_FAULT, False),
        (lambda: ConnectionRefusedError("Connection refused"),
         CLASS_TRANSPORT, False),
    ]

    def test_taxonomy_table(self):
        for factory, want_cls, want_oom in self.TABLE:
            err = factory()
            assert classify(err) == want_cls, err
            assert is_device_oom(err) == want_oom, err

    def test_failpoint_oom_action_raises_classified_oom(self):
        with failpoint.enabled("unit-oom", "1*oom"):
            with pytest.raises(InjectedOOMError) as ei:
                failpoint.inject("unit-oom")
            assert is_device_oom(ei.value)
            assert failpoint.inject("unit-oom") is None  # N exhausted


# dynamic stand-ins for jaxlib's error types (importing jaxlib's actual
# XlaRuntimeError would couple the test to the installed jax version)
_XlaLike = type("XlaRuntimeError", (Exception,), {})
_XlaSubclass = type("BackendDiedError", (_XlaLike,), {})


# -- ledger / budget / publish-race units ------------------------------------

class TestResidencyLedger:
    def test_upload_registers_and_hits(self, clean_budget):
        residency.evict_all("test isolation")
        col = _int_col(128)
        s0 = residency.snapshot()
        dc = dev.to_device_col(col)
        s1 = residency.snapshot()
        assert s1["uploads"] == s0["uploads"] + 1
        want = dc.data.nbytes + dc.nulls.nbytes
        assert s1["hbm_bytes_cached"] - s0["hbm_bytes_cached"] == want
        dev.to_device_col(col)  # second read: cache hit, no new upload
        s2 = residency.snapshot()
        assert s2["uploads"] == s1["uploads"]
        assert s2["hits"] > s1["hits"]
        assert residency.verify_ledger()["ok"]

    def test_budget_evicts_lru_first(self, clean_budget):
        residency.evict_all("test isolation")
        cold, warm = _int_col(256), _int_col(256, seed=9)
        dev.to_device_col(cold)
        dev.to_device_col(warm)
        dev.to_device_col(cold)  # touch: `warm` is now the LRU victim
        both = residency.resident_bytes()
        s0 = residency.snapshot()
        residency.set_budget(both)  # next upload must push someone out
        newest = _int_col(256, seed=77)
        dev.to_device_col(newest)
        s1 = residency.snapshot()
        assert s1["hbm_evictions"] > s0["hbm_evictions"]
        assert residency.resident_bytes() <= both
        # LRU order: the untouched `warm` went first; `cold` survived
        assert cold._device is not None
        assert warm._device is None
        assert residency.verify_ledger()["ok"]

    def test_oversized_single_entry_is_kept(self, clean_budget):
        residency.evict_all("test isolation")
        residency.set_budget(16)  # smaller than any real upload
        col = _int_col(64)
        dc = dev.to_device_col(col)  # must not livelock or raise
        assert int(dc.data.shape[0]) == 64
        assert residency.resident_bytes() > 16
        assert residency.verify_ledger()["ok"]

    def test_publish_race_compare_and_keep(self, clean_budget):
        """The loser of a racing publish is discarded AND accounted as
        immediately evicted — never a silent untracked HBM leak (the
        pre-residency `col._device = cached` was last-wins)."""
        residency.evict_all("test isolation")
        col = _int_col(64)
        dc = dev.to_device_col(col)
        s0 = residency.snapshot()
        import jax.numpy as jnp
        loser = (jnp.zeros(64, dtype=jnp.int64), jnp.zeros(64, dtype=bool))
        kept_d, _kept_n = residency.publish(col, *loser)
        s1 = residency.snapshot()
        assert kept_d is dc.data  # incumbent wins
        assert s1["publish_races"] == s0["publish_races"] + 1
        assert s1["hbm_evictions"] == s0["hbm_evictions"] + 1
        assert s1["hbm_bytes_cached"] == s0["hbm_bytes_cached"]
        assert residency.verify_ledger()["ok"]

    def test_grow_evicts_and_reuploads(self, clean_budget):
        residency.evict_all("test isolation")
        col = _int_col(64)
        dev.to_device_col(col)
        small = residency.resident_bytes()
        dc = dev.to_device_col(col, bucket=256)
        assert int(dc.data.shape[0]) == 256
        assert residency.resident_bytes() > small
        assert residency.verify_ledger()["ok"]

    def test_grow_keeps_old_entry_until_swap(self, clean_budget):
        """A grow request misses WITHOUT evicting: the smaller cached
        entry keeps serving shorter-bucket readers until publish() swaps
        it, so a rebuild failing mid-flight (the OOM failpoint) leaves
        the column still cached."""
        residency.evict_all("test isolation")
        col = _int_col(64)
        dev.to_device_col(col)
        small = residency.resident_bytes()
        assert residency.lookup(col, 256) is None  # grow: a miss...
        assert residency.resident_bytes() == small  # ...but no evict
        assert residency.lookup(col, 64) is not None  # still serving
        with failpoint.enabled("device-upload-oom", "oom"):
            with pytest.raises(Exception):
                dev.to_device_col(col, bucket=256)  # rebuild dies
        assert residency.lookup(col, 64) is not None  # cache survived
        dc = dev.to_device_col(col, bucket=256)  # clean grow swaps
        assert int(dc.data.shape[0]) == 256
        assert residency.verify_ledger()["ok"]

    def test_recover_oom_bumps_epoch(self, clean_budget):
        """OOM recovery must invalidate epoch-stamped consumers (join
        leaf dcols) too — without the bump, a mid-flight leaf dict would
        re-pin the very buffers the evict-all freed."""
        e0 = residency.device_epoch()
        residency.recover_oom(RuntimeError("RESOURCE_EXHAUSTED: test"))
        assert residency.device_epoch() == e0 + 1
        assert residency.resident_bytes() == 0

    def test_budget_reads_global_scope(self, tk):
        """The ledger is process-wide: attach() takes the budget from the
        Domain's GLOBAL vars; a session-scoped SET must not clobber it
        (same discipline as the circuit-breaker knobs)."""
        try:
            tk.must_exec("set global tidb_device_mem_budget = 2048")
            residency.attach(tk.session)
            assert residency.effective_budget() == 2048
            tk.must_exec("set tidb_device_mem_budget = 7")  # session only
            residency.attach(tk.session)
            assert residency.effective_budget() == 2048  # global wins
        finally:
            tk.must_exec("set global tidb_device_mem_budget = 0")
            residency.set_budget(0)

    def test_gc_releases_ledger_bytes(self, clean_budget):
        residency.evict_all("test isolation")
        col = _int_col(64)
        dev.to_device_col(col)
        assert residency.resident_bytes() > 0
        del col
        gc.collect()
        assert residency.resident_bytes() == 0
        assert residency.verify_ledger()["ok"]


# -- epoch fence regression (satellite: test coverage) -----------------------

class TestEpochFence:
    def test_fence_invalidates_column_caches(self, tk):
        """Populate Column._device via a device aggregate, fence, assert
        the next query RE-UPLOADS (epoch mismatch — no pre-fence buffer
        is ever reused) and still returns correct results."""
        tk.must_query(AGG_Q)  # populate
        u_warm = residency.snapshot()["uploads"]
        tk.must_query(AGG_Q)  # warm: cached uploads serve the re-run
        assert residency.snapshot()["uploads"] == u_warm
        assert residency.resident_bytes() > 0

        epoch0 = residency.device_epoch()
        supervisor.fence("epoch regression test")
        assert residency.device_epoch() == epoch0 + 1
        assert residency.resident_bytes() == 0  # ledger cleared at fence

        rows = tk.must_query(AGG_Q).rows
        assert residency.snapshot()["uploads"] > u_warm, (
            "post-fence query served a pre-fence device buffer")
        tk.must_exec("set tidb_executor_engine = 'host'")
        assert rows == tk.must_query(AGG_Q).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")

    def test_fence_invalidates_join_leaf_caches(self, tk):
        tk.must_query(JOIN_Q)
        u_warm = residency.snapshot()["uploads"]
        tk.must_query(JOIN_Q)
        assert residency.snapshot()["uploads"] == u_warm
        supervisor.fence("join epoch regression test")
        rows = tk.must_query(JOIN_Q).rows
        assert residency.snapshot()["uploads"] > u_warm
        tk.must_exec("set tidb_executor_engine = 'host'")
        assert rows == tk.must_query(JOIN_Q).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")


# -- OOM recovery ladder (tentpole acceptance) -------------------------------

class TestOOMLadder:
    def test_transient_oom_recovers_via_evict_and_retry(self, tk):
        """ONE injected upload OOM: evict-all + single retry completes the
        query on-device — no error, no breaker charge."""
        residency.evict_all("force re-upload so the failpoint fires")
        br = get_breaker(tk.session, shape="agg")
        fail0 = br.snapshot()["failures"]
        rec0 = residency.snapshot()["hbm_oom_recoveries"]
        with failpoint.enabled("device-upload-oom", "1*oom"):
            rows = tk.must_query(AGG_Q).rows
        assert residency.snapshot()["hbm_oom_recoveries"] == rec0 + 1
        assert br.snapshot()["failures"] == fail0  # absorbed, not charged
        tk.must_exec("set tidb_executor_engine = 'host'")
        assert rows == tk.must_query(AGG_Q).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        assert residency.verify_ledger()["ok"]

    def test_persistent_oom_degrades_to_host(self, tk):
        """A persistent upload OOM walks the whole ladder: evict-all →
        retry (fails again) → breaker charge → host degradation.  The
        query COMPLETES with correct rows — never an unhandled error."""
        residency.evict_all("force re-upload so the failpoint fires")
        br = get_breaker(tk.session, shape="agg")
        fail0 = br.snapshot()["failures"]
        with failpoint.enabled("device-upload-oom", "oom"):
            rows = tk.must_query(AGG_Q).rows  # degraded, still succeeds
        assert br.snapshot()["failures"] == fail0 + 1
        tk.must_exec("set tidb_executor_engine = 'host'")
        assert rows == tk.must_query(AGG_Q).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        # after the chaos: ledger consistent, and the next clean run
        # re-populates the cache
        assert residency.verify_ledger()["ok"]
        assert tk.must_query(AGG_Q).rows == rows
        assert residency.resident_bytes() > 0

    def test_join_upload_oom_recovers(self, tk):
        residency.evict_all("force re-upload so the failpoint fires")
        rec0 = residency.snapshot()["hbm_oom_recoveries"]
        with failpoint.enabled("device-upload-oom", "1*oom"):
            rows = tk.must_query(JOIN_Q).rows
        assert residency.snapshot()["hbm_oom_recoveries"] == rec0 + 1
        tk.must_exec("set tidb_executor_engine = 'host'")
        assert rows == tk.must_query(JOIN_Q).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")


# -- gauge surfacing ---------------------------------------------------------

class TestGaugesSurfaced:
    def test_explain_observe_status_and_metrics(self, tk):
        residency.evict_all("force re-upload so the failpoint fires")
        with failpoint.enabled("device-upload-oom", "1*oom"):
            tk.must_query(AGG_Q)  # one recovery: counters all nonzero

        # EXPLAIN ANALYZE annotates the gauges on the device fragment
        rows = tk.must_query(f"explain analyze {AGG_Q}").rows
        blob = "\n".join(" ".join(str(c) for c in r) for r in rows)
        assert "hbm_bytes_cached" in blob
        assert "hbm_oom_recoveries" in blob

        # observe gauges (the Domain sink run_device registered)
        g = tk.domain.observe.gauge_snapshot()
        assert g.get("hbm_bytes_cached", 0) > 0
        assert g.get("hbm_oom_recoveries", 0) >= 1

        # HTTP /status JSON + /metrics exposition
        from tidb_tpu.server.http_status import StatusServer
        srv = StatusServer(tk.domain, port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status = json.load(urllib.request.urlopen(f"{base}/status"))
            res = status["device_residency"]
            assert res["hbm_bytes_cached"] > 0
            assert res["hbm_oom_recoveries"] >= 1
            assert res["epoch"] == residency.device_epoch()
            metrics = urllib.request.urlopen(f"{base}/metrics").read()
            assert b"hbm_bytes_cached" in metrics
            assert b"hbm_evictions" in metrics
            assert b"hbm_oom_recoveries" in metrics
        finally:
            srv.shutdown()


# -- lint: every ._device access lives in the residency module ---------------

class TestDeviceCacheLint:
    def test_device_slot_access_confined_to_residency(self):
        """Registry rule (tidb_tpu/lint rules/confinement.py): any direct
        ._device access outside ops/residency.py is unaccounted HBM
        caching; the Column constructors' = None slot inits are the one
        sanctioned exception."""
        from tidb_tpu.lint import run_rule
        findings = run_rule("device-slot-confinement")
        assert not findings, [f.to_json() for f in findings]


# -- per-tenant residency accounting (ISSUE 6 satellite) ---------------------

@pytest.fixture()
def tenant_sandbox(clean_budget):
    """Empty ledger + default group restored around each tenant test."""
    residency.evict_all("tenant test isolation")
    residency.set_group(residency.DEFAULT_GROUP)
    yield
    residency.set_group(residency.DEFAULT_GROUP)
    residency.evict_all("tenant test isolation")


class TestTenantResidency:
    def test_entries_charged_to_uploading_group(self, tenant_sandbox):
        residency.set_group("olap")
        a = _int_col(64)
        dev.to_device_col(a)
        residency.set_group("oltp")
        b = _int_col(64, seed=9)
        dev.to_device_col(b)
        s = residency.snapshot()
        assert set(s["by_group"]) == {"olap", "oltp"}
        assert s["by_group"]["olap"] > 0 and s["by_group"]["oltp"] > 0
        led = residency.verify_ledger()
        assert led["ok"] and led["by_group"] == led["by_group_recomputed"]

    def test_budget_share_enforced_per_group(self, tenant_sandbox):
        """Two active tenants split the budget: a tenant uploading past
        its share is evicted back toward it while the other tenant's
        resident set is untouched."""
        residency.set_group("hog")
        hog_cols = [_int_col(256, seed=i) for i in range(3)]
        for c in hog_cols:
            dev.to_device_col(c)
        hog_bytes = residency.resident_bytes()
        residency.set_group("meek")
        meek_col = _int_col(64, seed=99)
        dev.to_device_col(meek_col)
        meek_bytes = residency.resident_bytes() - hog_bytes
        # budget: room for meek + ~half of hog's set → hog must shrink
        residency.set_budget(hog_bytes // 2 + meek_bytes)
        residency.set_group("hog")
        dev.to_device_col(_int_col(256, seed=7))
        s = residency.snapshot()
        assert s["hbm_bytes_cached"] <= hog_bytes // 2 + meek_bytes
        # the protected tenant survived intact; the hog paid its own bill
        assert meek_col._device is not None
        assert s["by_group"].get("meek", 0) == meek_bytes
        assert residency.verify_ledger()["ok"]

    def test_self_first_eviction_order(self, tenant_sandbox):
        """An over-share uploader evicts its OWN LRU entries before
        another tenant's — even when the other tenant's entry is the
        globally oldest (plain global LRU would evict it first)."""
        residency.set_group("other")
        oldest = _int_col(128, seed=1)
        dev.to_device_col(oldest)  # globally oldest entry
        residency.set_group("self")
        mine = [_int_col(128, seed=10 + i) for i in range(3)]
        for c in mine:
            dev.to_device_col(c)
        total = residency.resident_bytes()
        per_entry = total // 4
        # room for three entries: the NEXT self upload must evict one
        residency.set_budget(total - per_entry // 2)
        dev.to_device_col(_int_col(128, seed=50))
        # the self tenant's own LRU (mine[0]) went; `other` survived
        assert oldest._device is not None, "neighbor's entry was evicted"
        assert mine[0]._device is None, "uploader's own LRU was spared"
        assert residency.verify_ledger()["ok"]

    def test_group_bytes_released_on_gc(self, tenant_sandbox):
        residency.set_group("ephemeral")
        col = _int_col(64)
        dev.to_device_col(col)
        assert residency.snapshot()["by_group"].get("ephemeral", 0) > 0
        del col
        gc.collect()
        assert residency.snapshot()["by_group"].get("ephemeral", 0) == 0
        assert residency.verify_ledger()["ok"]

    def test_concurrent_multitenant_ledger_invariant(self, tenant_sandbox):
        """Concurrent upload / evict / budget pressure from multiple
        tenants must leave the global AND per-group ledgers exactly
        recomputable from the live entries (the lock exists for this)."""
        import random
        import threading as th
        residency.set_budget(64 * 1024)
        errs = []

        def worker(tid):
            rng = random.Random(tid)
            group = f"tenant-{tid % 3}"
            residency.set_group(group)
            kept = []
            try:
                for i in range(40):
                    c = _int_col(rng.choice([32, 64, 128]),
                                 seed=tid * 1000 + i)
                    dev.to_device_col(c)
                    kept.append(c)
                    if rng.random() < 0.2 and kept:
                        kept.pop(rng.randrange(len(kept)))  # GC release
                    if rng.random() < 0.05:
                        residency.evict_all(f"chaos {tid}")
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [th.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        gc.collect()
        assert not errs
        led = residency.verify_ledger()
        assert led["ok"], f"multi-tenant ledger drift: {led}"

    def test_tenant_flows_through_real_dispatch(self, tenant_sandbox, tk):
        """The session's tidb_resource_group reaches the ledger through a
        real query dispatch (attach() bridging), including a SUPERVISED
        dispatch (worker-thread group bridging)."""
        tk.must_exec("set tidb_resource_group = 'analytics'")
        tk.must_query(AGG_Q)
        assert residency.snapshot()["by_group"].get("analytics", 0) > 0
        residency.evict_all("re-upload under supervision")
        tk.must_exec("set tidb_device_call_timeout = 30")
        try:
            tk.must_query(AGG_Q)
        finally:
            tk.must_exec("set tidb_device_call_timeout = 0")
        assert residency.snapshot()["by_group"].get("analytics", 0) > 0
        assert residency.verify_ledger()["ok"]


class _TrackingLock:
    """Context-manager proxy over the real ledger lock that counts
    acquisitions (regression instrumentation)."""

    def __init__(self, inner):
        self.inner = inner
        self.entries = 0

    def __enter__(self):
        self.inner.acquire()
        self.entries += 1
        return self

    def __exit__(self, *a):
        self.inner.release()
        return False

    def acquire(self, *a, **k):
        self.entries += 1
        return self.inner.acquire(*a, **k)

    def release(self):
        return self.inner.release()


class TestBudgetPublishUnderLock:
    """Regression (ISSUE 11 guarded-state): set_budget / attach wrote
    _BUDGET[0] with no lock while _enforce_budget_locked read it under
    _LOCK; the budget publish now happens inside the ledger lock."""

    def test_set_budget_acquires_ledger_lock(self, monkeypatch):
        tracking = _TrackingLock(residency._LOCK)
        before = residency._BUDGET[0]
        monkeypatch.setattr(residency, "_LOCK", tracking)
        try:
            residency.set_budget(12345)
            assert tracking.entries >= 1
            assert residency._BUDGET[0] == 12345
            n0 = tracking.entries
            assert residency.effective_budget() == 12345
            assert tracking.entries > n0  # reads are locked too
        finally:
            residency.set_budget(before)

    def test_attach_publishes_global_budget_under_lock(self, monkeypatch):
        class Dom:
            global_vars = {"tidb_device_mem_budget": 777}

        class Ctx:
            domain = Dom()

        tracking = _TrackingLock(residency._LOCK)
        before = residency._BUDGET[0]
        monkeypatch.setattr(residency, "_LOCK", tracking)
        try:
            residency.attach(Ctx())
            assert residency._BUDGET[0] == 777
            assert tracking.entries >= 1
        finally:
            residency.set_budget(before)
