"""Serving front end: fragment-level device admission + weighted fair
scheduling across tenants.

Why this exists (ROADMAP open item 2, ISSUE 6): everything through PR 5
hardens ONE query at a time — breaker, supervisor, residency all assume a
fragment that already owns the device.  "Millions of users" means hundreds
of concurrent sessions multiplexing one device, and without an admission
layer they contend by luck: a heavy analytical session can occupy every
dispatch slot while a point-read tenant starves, and overload surfaces as
interleaved slowness instead of a classified, bounded queue.  The
scheduling move follows "Revisiting Co-Processing for Hash Joins on the
Coupled CPU-GPU Architecture" (PAPERS.md): under load the host and the
device should serve DIFFERENT work concurrently — an admission refusal
degrades that fragment to the (always correct) host engine instead of
queueing forever or erroring.

The four layers a device fragment now passes through
(`device_exec.run_device` drives them in this order):

    1. ADMISSION (this module)    may this fragment occupy the device now?
    2. SUPERVISOR deadline        is the backend still responsive?
    3. CIRCUIT BREAKER            is this fragment shape healthy?
    4. RESIDENCY                  do its uploads fit the HBM budget?

Model — ticket, grant, release:

* Every `run_device` dispatch calls :func:`admit`, which returns a
  granted ``Ticket`` (released in run_device's ``finally``) or raises
  :class:`~tidb_tpu.errors.DeviceAdmissionError` (errno 9009, taxonomy
  class ``admission``).  run_device converts the refusal into
  ``DeviceUnsupported`` so the caller's existing fallback runs the
  fragment on the host engine — admission pressure degrades, never
  errors.
* **Fast path**: with no ticket queued anywhere and the tenant under its
  running cap, admission is one mutex acquire — the single-session hot
  path pays ~a lock, no thread handoff.
* **Queued path**: tickets enqueue per-tenant; a scheduler thread
  dequeues with WEIGHTED FAIR QUEUEING (virtual-time WFQ: each grant
  advances the tenant's virtual clock by 1/weight, the lowest clock
  eligible tenant goes next), so a tenant flooding the queue cannot
  starve another's point reads.  The queue is bounded
  (``tidb_device_sched_queue_depth``) and each wait is bounded
  (``tidb_device_admission_timeout``); both refusals are classified
  admission errors.
* **Per-tenant running caps** (``tidb_device_tenant_running_cap``): at
  most N fragments of one resource group occupy the device concurrently,
  so one tenant's heavy analytics cannot occupy every slot.
* **Small-fragment batching**: queued tickets that share a ``batch_key``
  — the (plan sig, pack sig, bucket shape) compiled-pipeline identity
  computed by the dispatch site — are granted TOGETHER with the leader as
  one scheduling charge.  The followers' dispatches hit the process-wide
  compiled-fragment cache (PR 2) and the residency upload cache
  cross-session, so N same-shaped small fragments cost one compile + one
  upload + N cheap dispatches against the shared bucket instead of N
  queue waits.

Tenancy: the session sysvar ``tidb_resource_group`` (default
``default``).  WFQ weights come from ``tidb_device_wfq_weights``
(``"grp:weight,grp2:weight"``, unlisted groups weigh 1).  Config is read
from the Domain's GLOBAL variables on every admit, same discipline as the
breaker/residency knobs: the device is process-wide, so a session-scoped
SET must not reconfigure the shared queue.

Invariant (chaos-asserted, `verify_drained`): every admitted ticket is
eventually COMPLETED, DEGRADED or cleanly REJECTED — no leaked tickets,
and the queue drains to zero once the traffic stops.

Gauges — ``sched_queue_depth``, ``sched_admission_waits_ms``,
``sched_batched_fragments``, per-tenant ``sched_degradations`` — surface
in EXPLAIN ANALYZE annotations, observe gauges, HTTP ``/status`` +
``/metrics``, and bench_serve.py lines (same plumbing as the PR 5
``hbm_*`` gauges).
"""

from __future__ import annotations

import collections
import itertools
import logging
import threading
import time
import weakref

from ..errors import DeviceAdmissionError

log = logging.getLogger("tidb_tpu.scheduler")

DEFAULT_GROUP = "default"

#: ticket states (the lifecycle the chaos invariant checks)
QUEUED, RUNNING, DONE, REJECTED = "queued", "running", "done", "rejected"

_LOCK = threading.Lock()
#: wakes the scheduler thread when a ticket enqueues or a slot frees
_WAKE = threading.Condition(_LOCK)

#: queued-waiter poll period — bounds KILL detection latency while a
#: ticket waits for its grant (same discipline as supervisor._POLL_S)
_POLL_S = 0.02

_SEQ = itertools.count(1)

#: per-group FIFO of queued tickets (insertion order preserved)
_QUEUES: "dict[str, collections.deque]" = {}
#: total queued tickets across groups (bounded by the depth knob)
_QUEUED_N = [0]
#: per-group count of tickets currently RUNNING (granted, not released)
_RUNNING: "collections.Counter" = collections.Counter()
#: WFQ virtual clocks, one per group that ever queued
_VTIME: "dict[str, float]" = {}

#: batch-key followers may overshoot the per-tenant running cap by this
#: factor (they share the leader's compiled program + uploads, so modest
#: overshoot is the price of coalescing) — but no further: each batched
#: fragment still dispatches individually, so an unbounded identical-key
#: flood must not occupy unbounded device slots
_BATCH_CAP_HEADROOM = 4

#: resolved config (refreshed from GLOBAL vars on every admit)
_CFG = {"depth": 64, "timeout_s": 5.0, "cap": 4, "weights": {}}
_CFG_RAW_WEIGHTS = [""]

#: the serving fabric's fleet hook (tidb_tpu/fabric/state.py installs a
#: _SchedFleet at worker boot): per-tenant running caps become
#: FLEET-wide (an atomic check+charge against the coordination segment)
#: and the WFQ virtual clocks are read from / advanced in the segment,
#: so a tenant flooding process A yields device time to a light tenant
#: on process B.  None in the ordinary single-process deployment — every
#: path below degrades to the local state.  Lock order: the segment's
#: flock nests INSIDE _LOCK; the segment layer never calls back out.
_FLEET = [None]


def set_fleet(hook):
    """Install (or clear, with None) the fleet coordination hook."""
    with _LOCK:
        _FLEET[0] = hook


#: _try_acquire_locked outcomes: refused / granted from local caps only
#: / granted WITH a fleet segment charge (the release side must mirror
#: exactly — releasing a charge this grant never took would eat another
#: in-flight fragment's, and the fleet cap would silently overshoot)
ACQ_NO, ACQ_LOCAL, ACQ_FLEET = 0, 1, 2


def _try_acquire_locked(group: str, cap: int) -> int:
    """One admission slot for `group` under the effective cap — local
    counts alone without a fleet, atomic segment check+charge with one.
    The local pre-filter keeps the common saturated case off the
    cross-process lock.  Returns ACQ_NO / ACQ_LOCAL / ACQ_FLEET; a
    ticket granted ACQ_FLEET must release the segment charge too
    (Ticket.fleet_charged drives release())."""
    fleet = _FLEET[0]
    if fleet is None:
        return (ACQ_LOCAL if cap <= 0 or _RUNNING[group] < cap
                else ACQ_NO)
    if cap > 0 and _RUNNING[group] >= cap:
        return ACQ_NO
    try:
        return ACQ_FLEET if fleet.try_acquire(group, cap) else ACQ_NO
    except Exception:
        log.warning("fleet admission hook failed; using local caps",
                    exc_info=True)
        return (ACQ_LOCAL if cap <= 0 or _RUNNING[group] < cap
                else ACQ_NO)


def _fleet_release_locked(group: str):
    fleet = _FLEET[0]
    if fleet is not None:
        try:
            fleet.release(group)
        except Exception as e:  # noqa: BLE001 — lease expiry reclaims it
            log.warning("fleet release hook failed for group %r "
                        "(segment lease reclaim will zero it): %s",
                        group, e)

STATS = {
    "admitted": 0,          # tickets granted (fast path + scheduled)
    "fast_grants": 0,       # granted inline without queueing
    "queued": 0,            # tickets that had to wait in the queue
    "sched_batched_fragments": 0,  # followers granted on a leader's slot
    "rejected_full": 0,     # refused: queue at depth
    "rejected_timeout": 0,  # refused: admission wait expired
    "rejected_injected": 0,  # refused: admission failpoint fired
    "sched_admission_waits_ms": 0.0,  # cumulative queued wait
}

#: per-tenant degradations: admission refusals that sent the fragment to
#: the host engine (run_device reports the degradation here after it
#: converts the refusal into DeviceUnsupported)
_DEGRADATIONS: "collections.Counter" = collections.Counter()

#: bound on tracked per-group STAT lines (this counter and its observe /
#: /metrics mirrors): group names come from a free-form session sysvar,
#: so a client SETting a fresh name per connection must not grow process
#: memory or one metric series per name forever — beyond the cap, new
#: names fold into one overflow bucket.  Scheduling state itself
#: (_QUEUES/_VTIME/_RUNNING) is pruned on drain and needs no cap.
GROUP_STATS_CAP = 64
OVERFLOW_GROUP = "__other__"


def _stats_key(counter, group: str) -> str:
    """`group`, or the overflow bucket once the counter is at cap."""
    if group in counter or len(counter) < GROUP_STATS_CAP:
        return group
    return OVERFLOW_GROUP

#: observe sinks mirroring the gauges (same pattern as ops/residency.py)
_SINKS: "weakref.WeakSet" = weakref.WeakSet()

_SCHED_THREAD = [None]


class Ticket:
    """One admitted-or-queued device fragment."""

    __slots__ = ("seq", "group", "shape", "batch_key", "state",
                 "granted", "batched", "enqueued_at", "fleet_charged")

    def __init__(self, group, shape, batch_key):
        self.seq = next(_SEQ)
        self.group = group
        self.shape = shape
        self.batch_key = batch_key
        self.state = QUEUED
        self.granted = threading.Event()
        self.batched = False      # granted as a follower on a shared key
        self.enqueued_at = 0.0
        self.fleet_charged = False  # this grant charged the segment


# -- config ------------------------------------------------------------------

def resource_group(ctx) -> str:
    """The session's resource group (``tidb_resource_group`` sysvar,
    SESSION scope — tenancy is per connection, not per process)."""
    if ctx is None:
        return DEFAULT_GROUP
    try:
        g = str(ctx.get_sysvar("tidb_resource_group")).strip()
    except Exception:
        return DEFAULT_GROUP
    return g or DEFAULT_GROUP


def _parse_weights(raw: str) -> dict:
    out = {}
    for part in raw.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, w = part.rpartition(":")
        try:
            wf = float(w)
        except ValueError:
            continue
        if name.strip() and wf > 0:
            out[name.strip()] = wf
    return out


def _refresh_cfg(ctx) -> int:
    """Resolve the scheduling knobs from the Domain's GLOBAL variables
    (shared resource: session SETs must not reconfigure the queue other
    sessions are waiting in).  Bare contexts fall back to their own
    view; no context keeps the current config.  Returns the resolved
    queue depth so the caller's disabled-check reads a value consistent
    with what was just published.

    The sysvar reads happen OUTSIDE _LOCK (get_sysvar may do arbitrary
    session work); the publish happens UNDER it.  The raw-weights memo
    and the parsed weights in particular must move together: two
    concurrent refreshes interleaving the `raw != memo` check with the
    two writes could otherwise leave the memo naming config X while the
    parsed weights are config Y — and because the memo matches, the
    stale weights would STICK until the sysvar changed again
    (regression-tested in tests/test_scheduler.py)."""
    src = None
    dom = getattr(ctx, "domain", None)
    if dom is not None:
        gv = dom.global_vars
        src = lambda name, d: gv.get(name, d)  # noqa: E731
    elif ctx is not None:
        src = lambda name, d: ctx.get_sysvar(name)  # noqa: E731
    if src is None:
        with _LOCK:
            return _CFG["depth"]
    vals = {}
    try:
        vals["depth"] = max(int(src("tidb_device_sched_queue_depth", 64)), 0)
    except Exception:
        pass
    try:
        vals["timeout_s"] = max(
            float(src("tidb_device_admission_timeout", 5.0)), 0.0)
    except Exception:
        pass
    try:
        vals["cap"] = max(int(src("tidb_device_tenant_running_cap", 4)), 0)
    except Exception:
        pass
    try:
        raw = str(src("tidb_device_wfq_weights", ""))
    except Exception:
        raw = None
    with _LOCK:
        _CFG.update(vals)
        if raw is not None and raw != _CFG_RAW_WEIGHTS[0]:
            _CFG_RAW_WEIGHTS[0] = raw
            _CFG["weights"] = _parse_weights(raw)
        return _CFG["depth"]


def _weight(group: str) -> float:
    return _CFG["weights"].get(group, 1.0)


def _cap() -> int:
    """Per-tenant running-fragment cap (0 = unlimited)."""
    return _CFG["cap"]


# -- admission ---------------------------------------------------------------

def admit(ctx, shape: str = "agg", batch_key=None) -> "Ticket | None":
    """Admit one device fragment for the calling session.

    Returns a granted :class:`Ticket` (the caller MUST pass it to
    :func:`release` when the fragment finishes — run_device does this in
    its ``finally``), or ``None`` when scheduling is disabled
    (``tidb_device_sched_queue_depth = 0``).  Raises
    :class:`DeviceAdmissionError` when the queue is full, the admission
    wait times out, or the ``device-admission`` failpoint injects a
    refusal — run_device degrades the fragment to the host engine."""
    from ..session import tracing
    # the statement's span tracer (one branch when sampling is off):
    # queue waits and batch-coalesce grants tag this span
    with tracing.span("scheduler.acquire", shape=shape) as _tsp:
        return _admit_impl(ctx, shape, batch_key, _tsp)


def _admit_impl(ctx, shape, batch_key, _tsp):
    from ..utils import failpoint
    from ..utils.failpoint import InjectedAdmissionError
    if _refresh_cfg(ctx) <= 0:
        return None
    group = resource_group(ctx)
    if _FLEET[0] is not None:
        # fleet-wide admissions odometer: the result cache's
        # admission-bypass proof (bench_serve --smoke pins this delta to
        # ZERO across a pure repeated-fragment loop — a cache hit never
        # reaches this line)
        try:
            from ..fabric import state as fabric_state
            c = fabric_state.coordinator()
            if c is not None:
                c.bump("fabric_admissions")
        except Exception:  # noqa: BLE001 — odometer only
            pass
    t_fp0 = time.monotonic()
    try:
        # chaos hook: `admission-queue-full` models a saturated queue,
        # `N*admission-wait(s)` stalls admission (counted as wait time)
        failpoint.inject("device-admission")
    except InjectedAdmissionError as e:
        with _LOCK:
            STATS["rejected_injected"] += 1
        raise DeviceAdmissionError(
            f"device admission refused for resource group '{group}': {e}",
            ) from e
    fp_wait_ms = (time.monotonic() - t_fp0) * 1000.0
    ticket = Ticket(group, shape, batch_key)
    check_killed = getattr(ctx, "check_killed", None)
    with _LOCK:
        if fp_wait_ms >= 1.0:
            STATS["sched_admission_waits_ms"] += fp_wait_ms
        cap = _cap()
        acq = (_try_acquire_locked(group, cap) if _QUEUED_N[0] == 0
               else ACQ_NO)
        if acq:
            # fast path: nothing waiting anywhere and the tenant has a
            # free slot (FLEET-wide under the fabric) — grant inline, no
            # scheduler-thread handoff
            ticket.fleet_charged = acq == ACQ_FLEET
            ticket.state = RUNNING
            ticket.granted.set()
            _RUNNING[group] += 1
            STATS["admitted"] += 1
            STATS["fast_grants"] += 1
            if _tsp is not None:
                _tsp.tags["fast"] = True
            return ticket
        if _QUEUED_N[0] >= _CFG["depth"]:
            # the depth bound is per-group FAIR at the margin (the same
            # share rule as the residency budget): one tenant's backlog
            # filling the queue must not refuse every OTHER tenant's
            # tickets before WFQ can interleave them.  A group still
            # under its share of the depth (depth split across the
            # groups queued right now) enqueues past the global bound;
            # the hard 2*depth backstop keeps the total bounded
            # regardless of how many groups arrive at once.
            n_groups = len(_QUEUES) + (0 if group in _QUEUES else 1)
            share = max(_CFG["depth"] // max(n_groups, 1), 1)
            if (_QUEUED_N[0] >= 2 * _CFG["depth"]
                    or len(_QUEUES.get(group, ())) >= share):
                STATS["rejected_full"] += 1
                ticket.state = REJECTED
                raise DeviceAdmissionError(
                    f"device admission queue full ({_QUEUED_N[0]} tickets "
                    f">= tidb_device_sched_queue_depth={_CFG['depth']}, "
                    f"resource group '{group}' at its share of the depth)")
        ticket.enqueued_at = time.monotonic()
        _QUEUES.setdefault(group, collections.deque()).append(ticket)
        _QUEUED_N[0] += 1
        STATS["queued"] += 1
        _ensure_thread()
        _WAKE.notify_all()
        timeout_s = _CFG["timeout_s"]
    _publish_gauges()
    try:
        # sliced wait polling the session's KILL flag (the PR 3
        # responsiveness discipline: a queued session must answer KILL
        # within ~a poll tick, not after the whole admission wait —
        # check_killed raises QueryInterrupted, cleaned up below)
        deadline = (ticket.enqueued_at + timeout_s if timeout_s > 0
                    else None)
        while True:
            granted = ticket.granted.wait(_POLL_S)
            if granted:
                break
            if check_killed is not None:
                check_killed()
            if deadline is not None and time.monotonic() >= deadline:
                break
        waited_ms = (time.monotonic() - ticket.enqueued_at) * 1000.0
        # queue-wait attribution: the p99-scrapeable histogram and the
        # statement's trace span (both outside _LOCK — the recorder and
        # the observe registry are never touched under the queue mutex)
        _observe_hist("admission_wait_seconds", waited_ms / 1000.0)
        if _tsp is not None:
            _tsp.tags["queued_ms"] = round(waited_ms, 1)
        with _LOCK:
            STATS["sched_admission_waits_ms"] += waited_ms
            # on timeout the ticket may STILL be granted in the race
            # window — the scheduler grants under this same lock, so the
            # is_set re-check here is authoritative
            if granted or ticket.granted.is_set():
                if _tsp is not None and ticket.batched:
                    # granted as a follower on a shared batch key: this
                    # fragment rode another ticket's scheduling slot
                    _tsp.tags["batched"] = True
                return ticket
            try:
                _QUEUES[ticket.group].remove(ticket)
                _QUEUED_N[0] -= 1
            except (KeyError, ValueError):
                pass
            _prune_group_locked(ticket.group)
            ticket.state = REJECTED
            STATS["rejected_timeout"] += 1
    except BaseException:
        # KILL / Ctrl-C while queued — or an async exception landing
        # AFTER the grant but before admit returns: the ticket must not
        # leak either way.  Return the slot a racing grant gave it, or
        # dequeue it.
        with _LOCK:
            if ticket.granted.is_set():
                if ticket.state == RUNNING:
                    ticket.state = DONE
                    _RUNNING[ticket.group] -= 1
                    if _RUNNING[ticket.group] <= 0:
                        del _RUNNING[ticket.group]
                        _prune_group_locked(ticket.group)
                    if ticket.fleet_charged:
                        _fleet_release_locked(ticket.group)
                    _WAKE.notify_all()
            else:
                try:
                    _QUEUES[ticket.group].remove(ticket)
                    _QUEUED_N[0] -= 1
                except (KeyError, ValueError):
                    pass
                _prune_group_locked(ticket.group)
                ticket.state = REJECTED
        raise
    _publish_gauges()
    raise DeviceAdmissionError(
        f"device admission wait exceeded tidb_device_admission_timeout="
        f"{timeout_s:g}s ({waited_ms:.0f}ms queued) for resource group "
        f"'{ticket.group}'")


def release(ticket: "Ticket | None"):
    """Return a granted ticket's device slot (run_device ``finally``).
    No gauge publish here: release changes only the running counts,
    which no published gauge carries — the uncontended fragment path
    stays one mutex acquire on each side."""
    if ticket is None:
        return
    with _LOCK:
        if ticket.state != RUNNING:
            return
        ticket.state = DONE
        _RUNNING[ticket.group] -= 1
        if _RUNNING[ticket.group] <= 0:
            del _RUNNING[ticket.group]
            _prune_group_locked(ticket.group)
        if ticket.fleet_charged:
            _fleet_release_locked(ticket.group)
        _WAKE.notify_all()


def note_degradation(group: str):
    """run_device reports an admission refusal it degraded to the host
    engine (the per-tenant ``sched_degradations`` gauge)."""
    with _LOCK:
        _DEGRADATIONS[_stats_key(_DEGRADATIONS, group)] += 1
    _publish_gauges()


# -- the scheduler thread ----------------------------------------------------

def _ensure_thread():
    t = _SCHED_THREAD[0]
    if t is not None and t.is_alive():
        return
    t = threading.Thread(target=_sched_loop, daemon=True,
                         name="device-scheduler")
    _SCHED_THREAD[0] = t
    t.start()


def _sched_loop():
    while True:
        with _WAKE:
            while not _grant_some_locked():
                # under the fabric a peer process's release() cannot
                # notify this condition — poll on a short tick so a
                # freed fleet-wide slot is granted within ~one tick
                _WAKE.wait(0.05 if _FLEET[0] is not None else 1.0)
        _publish_gauges()


def _eligible_locked():
    """Groups with queued tickets and a free running slot, ordered by WFQ
    virtual time (lowest first).  Under the fabric the ordering clock is
    the FLEET's (coordination segment), so two processes draining the
    same tenants interleave as one fair queue; the local-cap check stays
    a pre-filter and the authoritative fleet-cap check happens at grant
    time (_try_acquire_locked)."""
    cap = _cap()
    cands = [g for g, q in _QUEUES.items()
             if q and (cap <= 0 or _RUNNING[g] < cap)]
    fleet = _FLEET[0]
    if fleet is not None and cands:
        try:
            vts = fleet.vtimes(cands)
        except Exception as e:  # noqa: BLE001 — fall back to local clocks
            log.warning("fleet vtimes unavailable (local WFQ order): %s",
                        e)
            vts = {g: _VTIME.get(g, 0.0) for g in cands}
    else:
        vts = {g: _VTIME.get(g, 0.0) for g in cands}
    return [g for _vt, g in sorted((vts[g], g) for g in cands)], vts


def _grant_some_locked() -> bool:
    """Grant the WFQ-next queued ticket (plus its batch-key followers).
    Returns True when anything was granted (caller re-loops), False when
    the queue is empty or every queued group is at its (fleet-wide) cap."""
    elig, vts = _eligible_locked()
    cap = _cap()
    group = None
    acq = ACQ_NO
    for g in elig:
        # WFQ order, but the grant only lands if the group clears the
        # fleet-wide cap (a peer process may hold every slot) — the next
        # eligible group gets its chance rather than head-of-line block
        acq = _try_acquire_locked(g, cap)
        if acq:
            group = g
            break
    if group is None:
        return False
    leader = _QUEUES[group].popleft()
    leader.fleet_charged = acq == ACQ_FLEET
    _QUEUED_N[0] -= 1
    _prune_group_locked(group)
    # virtual-time WFQ: one grant advances the tenant's clock by
    # 1/weight; an idle tenant re-enters at the current floor so a long
    # sleep never banks unbounded credit against the active tenants
    floor = min((vts.get(g, _VTIME.get(g, 0.0))
                 for g, q in _QUEUES.items() if q),
                default=vts.get(group, _VTIME.get(group, 0.0)))
    delta = 1.0 / _weight(group)
    fleet = _FLEET[0]
    if fleet is not None:
        try:
            fleet.advance(group, delta, floor)
        except Exception as e:  # noqa: BLE001 — local clock still moves
            log.warning("fleet vtime advance failed for %r: %s", group, e)
    _VTIME[group] = max(_VTIME.get(group, 0.0), floor) + delta
    _grant_locked(leader, batched=False)
    if leader.batch_key is not None:
        # small-fragment batching: queued tickets sharing the leader's
        # compiled-pipeline identity ride this grant — their dispatches
        # reuse the shared compiled fragment + resident uploads, so
        # admitting them together costs ~one device call.  Bounded:
        # batched fragments still dispatch individually, so followers
        # stop at a small headroom over the tenant cap — a 50-deep flood
        # of identical fragments must not occupy 50 device slots
        for g, q in list(_QUEUES.items()):
            followers = [t for t in q if t.batch_key == leader.batch_key]
            for t in followers:
                if (cap > 0 and _RUNNING[t.group]
                        >= cap * _BATCH_CAP_HEADROOM):
                    break
                facq = _try_acquire_locked(
                    t.group, cap * _BATCH_CAP_HEADROOM if cap > 0 else 0)
                if not facq:
                    break
                t.fleet_charged = facq == ACQ_FLEET
                q.remove(t)
                _QUEUED_N[0] -= 1
                _grant_locked(t, batched=True)
            _prune_group_locked(g)
    return True


def _prune_group_locked(group: str):
    """Drop a group's empty queue (and its virtual clock once nothing of
    it runs either): group names come from a free-form session sysvar,
    so per-group state must not accumulate for every name ever seen —
    the WFQ floor re-entry in _grant_some_locked makes dropping an idle
    group's clock semantically free."""
    q = _QUEUES.get(group)
    if q is not None and not q:
        del _QUEUES[group]
        q = None
    if q is None and group not in _RUNNING:
        _VTIME.pop(group, None)


def _grant_locked(ticket: Ticket, batched: bool):
    ticket.state = RUNNING
    ticket.batched = batched
    _RUNNING[ticket.group] += 1
    STATS["admitted"] += 1
    if batched:
        STATS["sched_batched_fragments"] += 1
    ticket.granted.set()


# -- introspection / gauges --------------------------------------------------

def queue_depth() -> int:
    """The ``sched_queue_depth`` gauge (tickets waiting right now)."""
    with _LOCK:
        return _QUEUED_N[0]


def snapshot() -> dict:
    with _LOCK:
        return {
            "sched_queue_depth": _QUEUED_N[0],
            "running": dict(_RUNNING),
            "degradations_by_group": dict(_DEGRADATIONS),
            "vtime": dict(_VTIME),
            "depth_cfg": _CFG["depth"],
            "cap_cfg": _CFG["cap"],
            **{k: (round(v, 3) if isinstance(v, float) else v)
               for k, v in STATS.items()},
        }


def report_gauges() -> dict:
    """Surfacing policy shared by EXPLAIN ANALYZE and bench lines:
    ``sched_queue_depth`` always; waits / batched / degradations only
    once they have ever fired (pressure is the exception, not annotation
    noise on every healthy plan)."""
    s = snapshot()
    out = {"sched_queue_depth": s["sched_queue_depth"]}
    if s["sched_admission_waits_ms"]:
        out["sched_admission_waits_ms"] = round(
            s["sched_admission_waits_ms"], 1)
    if s["sched_batched_fragments"]:
        out["sched_batched_fragments"] = s["sched_batched_fragments"]
    total_deg = sum(s["degradations_by_group"].values())
    if total_deg:
        out["sched_degradations"] = total_deg
    return out


def attach(ctx):
    """Register the Domain's observe registry as a gauge sink (called by
    run_device alongside residency.attach)."""
    dom = getattr(ctx, "domain", None)
    obs = getattr(dom, "observe", None)
    if obs is not None and hasattr(obs, "set_gauge"):
        with _LOCK:
            _SINKS.add(obs)


def _observe_hist(name, value):
    """Record one latency sample into every attached observe registry
    (session/observe.py HIST_BUCKETS — the /metrics `_bucket` series).
    Runs OUTSIDE _LOCK except for the sink-list snapshot."""
    with _LOCK:
        sinks = list(_SINKS)
    for obs in sinks:
        f = getattr(obs, "observe_hist", None)
        if f is not None:
            f(name, value)


def _publish_gauges():
    with _LOCK:
        if not _SINKS:
            return
        sinks = list(_SINKS)
        vals = {
            "sched_queue_depth": _QUEUED_N[0],
            "sched_admission_waits_ms": round(
                STATS["sched_admission_waits_ms"], 1),
            "sched_batched_fragments": STATS["sched_batched_fragments"],
        }
        per_group = {f"sched_degradations:{g}": n
                     for g, n in _DEGRADATIONS.items()}
    vals.update(per_group)
    for obs in sinks:
        try:
            for k, v in vals.items():
                obs.set_gauge(k, v)
        except Exception:
            pass


def verify_drained() -> dict:
    """Chaos invariant: once traffic stops, no ticket is leaked — the
    queue is empty and nothing is left RUNNING (every admit() was paired
    with a release() or a clean rejection)."""
    with _LOCK:
        queued = _QUEUED_N[0]
        running = dict(_RUNNING)
        accounted = (STATS["rejected_full"] + STATS["rejected_timeout"]
                     + STATS["rejected_injected"] + STATS["admitted"])
        started = STATS["fast_grants"] + STATS["queued"] \
            + STATS["rejected_full"] + STATS["rejected_injected"]
        return {"ok": queued == 0 and not running,
                "queued": queued, "running": running,
                "admitted": STATS["admitted"], "accounted": accounted,
                "started": started}


def reset_for_tests():
    """Drop queues/counters (unit tests only — never under live traffic)."""
    with _LOCK:
        _QUEUES.clear()
        _QUEUED_N[0] = 0
        _RUNNING.clear()
        _VTIME.clear()
        _DEGRADATIONS.clear()
        for k in STATS:
            STATS[k] = 0.0 if k == "sched_admission_waits_ms" else 0
