"""Online ADD INDEX: F1 schema-state machine with async worker, hook-driven
concurrent DML at every state, checkpointed backfill with crash-resume, and
unique-violation rollback (reference: ddl/index.go:519-541,
ddl/backfilling.go:142, ddl/rollingback.go, ddl/callback.go hooks)."""

import pytest

from tidb_tpu.ddl_worker import DDLWorker
from tidb_tpu.errors import DupEntryError, TiDBError
from tidb_tpu.model import SchemaState
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("create table t (a int primary key, b int, c varchar(16))")
    for i in range(40):
        tk.must_exec(f"insert into t values ({i}, {i % 10}, 'v{i}')")
    return tk


def _tbl(tk):
    return tk.session.infoschema().table_by_name("test", "t")


def test_add_index_online_end_to_end(tk):
    tk.must_exec("create index idx_b on t (b)")
    tk.must_exec("admin check index t idx_b")
    idx = _tbl(tk).find_index("idx_b")
    assert idx is not None and idx.state == SchemaState.PUBLIC
    # job history records the state walk
    r = tk.must_query("admin show ddl jobs")
    job = next(row for row in r.rows if row[1] == "add_index")
    assert job[6] == "synced"
    assert int(job[5]) == 40  # row_count = backfilled rows


def test_states_walked_in_order(tk):
    events = []
    tk.session.domain.ddl_worker.on_event(
        lambda ev, job: events.append(ev))
    tk.must_exec("create index idx_c on t (c)")
    named = [e for e in events if e != "reorg_batch"]
    assert named == ["delete only", "write only", "write reorganization",
                     "public"]
    assert "reorg_batch" in events


def test_concurrent_dml_mid_backfill(tk):
    """THE acceptance test: rows inserted while the backfill is running are
    correctly indexed, ADMIN CHECK INDEX passes."""
    w = tk.session.domain.ddl_worker
    w.batch_size = 8
    tk2 = tk.new_session()
    inserted = []

    def hook(ev, job):
        if ev == "reorg_batch" and len(inserted) < 5:
            h = 1000 + len(inserted)
            tk2.must_exec(f"insert into t values ({h}, {h}, 'mid')")
            inserted.append(h)
        if ev == "write only":
            tk2.must_exec("insert into t values (2000, 1, 'wo')")
            tk2.must_exec("delete from t where a = 0")
        if ev == "delete only":
            tk2.must_exec("update t set b = 77 where a = 1")
    w.on_event(hook)
    tk.must_exec("create index idx_b on t (b)")
    assert inserted, "backfill finished before any hook insert (batch too big)"
    tk.must_exec("admin check index t idx_b")
    tk.must_exec("admin check table t")
    # index readable and correct
    tk.must_query("select count(*) from t where b = 1000").check([("1",)])
    tk.must_query("select count(*) from t where b = 77").check([("1",)])


def test_backfill_checkpoint_crash_resume(tk):
    """Kill the worker between batches; a fresh worker resumes from the
    checkpointed handle (reference: reorg handle in the job, reorg.go)."""
    db = tk.session.infoschema().schema_by_name("test")
    tbl = _tbl(tk)
    job = tk.session.ddl.enqueue_job(
        "add_index", schema_id=db.id, table_id=tbl.id,
        args={"index_name": "idx_b", "unique": False,
              "columns": [["b", None]]})
    w = DDLWorker(tk.session.domain)
    w.batch_size = 8
    # walk: delete-only, write-only, write-reorg, then TWO backfill batches
    for _ in range(5):
        done = w.step_add_index(job.id)
        assert not done
    # "crash": abandon w; a new worker picks the job up mid-reorg
    w2 = DDLWorker(tk.session.domain)
    w2.batch_size = 8
    steps = 0
    while not w2.step_add_index(job.id):
        steps += 1
        assert steps < 100
    assert steps > 0, "resume worker had nothing to do — checkpoint ignored"
    tk.must_exec("admin check index t idx_b")
    r = tk.must_query("admin show ddl jobs")
    job_row = next(row for row in r.rows if row[0] == str(job.id))
    assert job_row[6] == "synced"
    assert int(job_row[5]) == 40  # no row double-counted across the crash


def test_unique_violation_rolls_back(tk):
    """Duplicate data: the unique index add fails, the half-built index is
    removed, and the table stays consistent."""
    with pytest.raises((DupEntryError, TiDBError)) as ei:
        tk.must_exec("create unique index u_b on t (b)")  # b has dups (i%10)
    assert "Duplicate entry" in str(ei.value)
    assert _tbl(tk).find_index("u_b") is None
    tk.must_exec("admin check table t")
    # and a valid unique index still works afterwards
    tk.must_exec("create unique index u_a2 on t (c)")
    tk.must_exec("admin check index t u_a2")


def test_index_used_for_reads_after_online_add(tk):
    # grow the table so the cost model favors the index seek over the scan
    for base in (100, 200, 300, 400):
        vals = ",".join(f"({base + i}, {base + i}, 'g')" for i in range(100))
        tk.must_exec(f"insert into t values {vals}")
    tk.must_exec("create index idx_b on t (b)")
    tk.must_exec("analyze table t")
    r = tk.must_query("explain select * from t where b = 3")
    plan = "\n".join(row[0] + row[1] for row in r.rows)
    assert "idx_b" in plan or "IndexLookUp" in plan
    tk.must_query("select count(*) from t where b = 3").check([("4",)])


def test_alter_table_add_index_goes_online(tk):
    events = []
    tk.session.domain.ddl_worker.on_event(lambda ev, j: events.append(ev))
    tk.must_exec("alter table t add index idx_alter (b, c)")
    assert "write reorganization" in events
    tk.must_exec("admin check index t idx_alter")


def test_non_public_index_invisible_to_planner(tk):
    """While the job is mid-flight the planner must not read the index."""
    w = DDLWorker(tk.session.domain)
    db = tk.session.infoschema().schema_by_name("test")
    tbl = _tbl(tk)
    job = tk.session.ddl.enqueue_job(
        "add_index", schema_id=db.id, table_id=tbl.id,
        args={"index_name": "idx_part", "unique": False,
              "columns": [["b", None]]})
    w.step_add_index(job.id)   # → delete-only
    tk.must_exec("analyze table t")
    r = tk.must_query("explain select * from t where b = 3")
    plan = "\n".join(row[0] + row[1] for row in r.rows)
    assert "idx_part" not in plan
    # DML against the delete-only index keeps working
    tk.must_exec("insert into t values (700, 3, 'd')")
    tk.must_exec("delete from t where a = 700")
    # finish the job; everything consistent
    while not w.step_add_index(job.id):
        pass
    tk.must_exec("admin check index t idx_part")


# -- online DROP INDEX / ADD COLUMN (reference: ddl/index.go onDropIndex,
#    ddl/column.go onAddColumn) ---------------------------------------------


def test_drop_index_walks_states_down(tk):
    tk.must_exec("create index idx_b on t (b)")
    events = []
    tk.session.domain.ddl_worker.on_event(
        lambda ev, job: events.append((job.type, ev)))
    tk.must_exec("drop index idx_b on t")
    walked = [ev for ty, ev in events if ty == "drop_index"]
    assert walked == ["write only", "delete only", "none"]
    assert _tbl(tk).find_index("idx_b") is None
    # the key range is purged
    tk.must_query("admin check table t").check([])


def test_drop_index_mid_state_dml_stays_consistent(tk):
    """DML landing while the dropping index is write-only/delete-only must
    not corrupt anything — entries stop mattering once the object is gone,
    and a fresh same-name index sees none of them."""
    tk.must_exec("create index idx_b on t (b)")
    w = tk.session.domain.ddl_worker

    def hook(ev, job):
        if job.type == "drop_index" and ev == "write only":
            tk.must_exec("insert into t values (900, 77, 'w')")
        if job.type == "drop_index" and ev == "delete only":
            tk.must_exec("insert into t values (901, 78, 'd')")
            tk.must_exec("delete from t where a = 900")

    w.on_event(hook)
    tk.must_exec("drop index idx_b on t")
    tk.must_exec("create index idx_b on t (b)")
    tk.must_query("admin check table t").check([])
    tk.must_query("select a from t use index (idx_b) where b = 78"
                  ).check([("901",)])


def test_add_column_walks_states_up(tk):
    events = []
    tk.session.domain.ddl_worker.on_event(
        lambda ev, job: events.append((job.type, ev)))
    tk.must_exec("alter table t add column d bigint default 42")
    walked = [ev for ty, ev in events if ty == "add_column"]
    assert walked == ["delete only", "write only", "public"]
    tk.must_query("select d from t where a = 1").check([("42",)])


def test_add_column_mid_state_dml(tk):
    """Rows inserted while the column is delete-only/write-only decode
    under the final schema (write-only inserts store the value; earlier
    rows materialize the default)."""
    w = tk.session.domain.ddl_worker
    seen = []

    def hook(ev, job):
        if job.type != "add_column":
            return
        if ev == "delete only":
            tk.must_exec("insert into t values (910, 1, 'x')")
            seen.append(ev)
        elif ev == "write only":
            # the column accepts writes but is not yet readable
            tk.must_exec("insert into t values (911, 2, 'y')")
            seen.append(ev)

    w.on_event(hook)
    tk.must_exec("alter table t add column e bigint default 7")
    assert seen == ["delete only", "write only"]
    rows = tk.must_query(
        "select a, e from t where a in (910, 911) order by a").rows
    assert rows == [("910", "7"), ("911", "7")]
    tk.must_query("admin check table t").check([])
