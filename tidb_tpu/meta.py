"""KV-encoded catalog persistence (reference: meta/meta.go + structure/ —
schema metadata under the ``m`` prefix, DDL job queues, id allocators,
schema version counter)."""

from __future__ import annotations

import json

from .errors import SchemaError, TiDBError, ErrCode
from .model import DBInfo, Job, TableInfo

M = b"m"
KEY_NEXT_GLOBAL_ID = M + b":next_gid"
KEY_SCHEMA_VERSION = M + b":schema_version"
KEY_DB_PREFIX = M + b":db:"          # m:db:{id} -> DBInfo json
KEY_DBS = M + b":dbs"                # json list of db ids
KEY_TABLE_PREFIX = M + b":tbl:"      # m:tbl:{db_id}:{tid} -> TableInfo json
KEY_TABLES_OF = M + b":tbls:"        # m:tbls:{db_id} -> json list of table ids
KEY_DDL_JOB_QUEUE = M + b":ddl_jobs"         # json list of pending job jsons
KEY_DDL_HISTORY = M + b":ddl_history:"       # m:ddl_history:{job_id} -> job json
KEY_DDL_NEXT_JOB_ID = M + b":ddl_next_job_id"
KEY_AUTOID_PREFIX = M + b":autoid:"  # m:autoid:{tid} -> int
KEY_BOOTSTRAP = M + b":bootstrapped"
KEY_STATS_PREFIX = M + b":stats:"    # m:stats:{tid} -> stats json
KEY_BINDING_PREFIX = M + b":bind:"   # m:bind:{digest} -> binding json
KEY_SEQ_PREFIX = M + b":seq:"        # m:seq:{tid} -> last allocated value
KEY_DELRANGE_PREFIX = M + b":delrange:"  # m:delrange:{id} -> pending range
KEY_DROPPED_PREFIX = M + b":dropped:"    # m:dropped:{tid} -> dropped table
KEY_POLICY_PREFIX = M + b":policy:"      # m:policy:{name} -> options json


class Meta:
    """All methods operate through a kv Transaction (or anything with
    get/put/scan), mirroring the reference's meta.Meta-over-txn design."""

    def __init__(self, txn):
        self.txn = txn

    # -- low-level ----------------------------------------------------------

    def _get_json(self, key: bytes, default):
        v = self.txn.get(key)
        if v is None:
            return default
        return json.loads(v)

    def _put_json(self, key: bytes, obj):
        self.txn.put(key, json.dumps(obj).encode())

    # -- id allocation ------------------------------------------------------

    def gen_global_id(self) -> int:
        nid = self._get_json(KEY_NEXT_GLOBAL_ID, 1)
        self._put_json(KEY_NEXT_GLOBAL_ID, nid + 1)
        return nid

    def gen_global_ids(self, n: int):
        nid = self._get_json(KEY_NEXT_GLOBAL_ID, 1)
        self._put_json(KEY_NEXT_GLOBAL_ID, nid + n)
        return list(range(nid, nid + n))

    # -- schema version -----------------------------------------------------

    def schema_version(self) -> int:
        return self._get_json(KEY_SCHEMA_VERSION, 0)

    def bump_schema_version(self) -> int:
        v = self.schema_version() + 1
        self._put_json(KEY_SCHEMA_VERSION, v)
        return v

    # -- databases ----------------------------------------------------------

    def list_databases(self):
        ids = self._get_json(KEY_DBS, [])
        out = []
        for did in ids:
            d = self._get_json(KEY_DB_PREFIX + str(did).encode(), None)
            if d is not None:
                out.append(DBInfo.from_json(d))
        return out

    def get_database(self, db_id: int):
        d = self._get_json(KEY_DB_PREFIX + str(db_id).encode(), None)
        return DBInfo.from_json(d) if d else None

    def create_database(self, db: DBInfo):
        ids = self._get_json(KEY_DBS, [])
        if db.id in ids:
            raise TiDBError(f"database id {db.id} exists", code=ErrCode.DBCreateExists)
        ids.append(db.id)
        self._put_json(KEY_DBS, ids)
        self._put_json(KEY_DB_PREFIX + str(db.id).encode(), db.to_json())
        self._put_json(KEY_TABLES_OF + str(db.id).encode(), [])

    def drop_database(self, db_id: int):
        ids = self._get_json(KEY_DBS, [])
        if db_id in ids:
            ids.remove(db_id)
            self._put_json(KEY_DBS, ids)
        self.txn.delete(KEY_DB_PREFIX + str(db_id).encode())
        self.txn.delete(KEY_TABLES_OF + str(db_id).encode())

    # -- tables -------------------------------------------------------------

    def list_tables(self, db_id: int):
        tids = self._get_json(KEY_TABLES_OF + str(db_id).encode(), [])
        out = []
        for tid in tids:
            t = self._get_json(_tbl_key(db_id, tid), None)
            if t is not None:
                out.append(TableInfo.from_json(t))
        return out

    def get_table(self, db_id: int, table_id: int):
        t = self._get_json(_tbl_key(db_id, table_id), None)
        return TableInfo.from_json(t) if t else None

    def create_table(self, db_id: int, tbl: TableInfo):
        key = KEY_TABLES_OF + str(db_id).encode()
        tids = self._get_json(key, None)
        if tids is None:
            raise SchemaError(f"database id {db_id} not found")
        if tbl.id in tids:
            raise TiDBError(f"table id {tbl.id} exists", code=ErrCode.TableExists)
        tids.append(tbl.id)
        self._put_json(key, tids)
        self._put_json(_tbl_key(db_id, tbl.id), tbl.to_json())

    def update_table(self, db_id: int, tbl: TableInfo):
        self._put_json(_tbl_key(db_id, tbl.id), tbl.to_json())

    def drop_table(self, db_id: int, table_id: int):
        key = KEY_TABLES_OF + str(db_id).encode()
        tids = self._get_json(key, [])
        if table_id in tids:
            tids.remove(table_id)
            self._put_json(key, tids)
        self.txn.delete(_tbl_key(db_id, table_id))

    # -- auto increment -----------------------------------------------------

    def autoid(self, table_id: int) -> int:
        return self._get_json(KEY_AUTOID_PREFIX + str(table_id).encode(), 1)

    def set_autoid(self, table_id: int, v: int):
        self._put_json(KEY_AUTOID_PREFIX + str(table_id).encode(), v)

    def alloc_autoid_batch(self, table_id: int, n: int):
        """Batched allocation (reference: meta/autoid/autoid.go:132 — sessions
        cache a batch to avoid a meta txn per row)."""
        base = self.autoid(table_id)
        self.set_autoid(table_id, base + n)
        return base, base + n

    # -- DDL job queue (reference: meta DDLJobQueue + HistoryJob) -----------

    def gen_job_id(self) -> int:
        nid = self._get_json(KEY_DDL_NEXT_JOB_ID, 1)
        self._put_json(KEY_DDL_NEXT_JOB_ID, nid + 1)
        return nid

    def enqueue_job(self, job: Job):
        q = self._get_json(KEY_DDL_JOB_QUEUE, [])
        q.append(job.to_json())
        self._put_json(KEY_DDL_JOB_QUEUE, q)

    def peek_job(self):
        q = self._get_json(KEY_DDL_JOB_QUEUE, [])
        return Job.from_json(q[0]) if q else None

    def update_job(self, job: Job):
        q = self._get_json(KEY_DDL_JOB_QUEUE, [])
        for i, s in enumerate(q):
            if Job.from_json(s).id == job.id:
                q[i] = job.to_json()
                self._put_json(KEY_DDL_JOB_QUEUE, q)
                return
        raise TiDBError(f"ddl job {job.id} not in queue")

    def finish_job(self, job: Job):
        q = self._get_json(KEY_DDL_JOB_QUEUE, [])
        q = [s for s in q if Job.from_json(s).id != job.id]
        self._put_json(KEY_DDL_JOB_QUEUE, q)
        self.txn.put(KEY_DDL_HISTORY + str(job.id).encode(), job.to_json().encode())

    def history_jobs(self):
        out = []
        for _k, v in self.txn.scan(KEY_DDL_HISTORY, KEY_DDL_HISTORY + b"\xff"):
            out.append(Job.from_json(v.decode()))
        out.sort(key=lambda j: j.id)
        return out

    def queued_jobs(self):
        return [Job.from_json(s) for s in self._get_json(KEY_DDL_JOB_QUEUE, [])]

    # -- bootstrap flag / stats --------------------------------------------

    def bootstrapped(self) -> int:
        return self._get_json(KEY_BOOTSTRAP, 0)

    def set_bootstrapped(self, version: int):
        self._put_json(KEY_BOOTSTRAP, version)

    def stats(self, table_id: int):
        return self._get_json(KEY_STATS_PREFIX + str(table_id).encode(), None)

    def set_stats(self, table_id: int, obj):
        self._put_json(KEY_STATS_PREFIX + str(table_id).encode(), obj)

    # -- placement policies (reference: ddl/placement_policy.go; policies
    #    persist in meta and tables reference them by name — with one
    #    embedded store the constraints are catalog state, not scheduling)

    def set_placement_policy(self, name: str, options: dict,
                             display: str | None = None):
        # lookup is case-insensitive (lowercased key); the CREATED
        # spelling is preserved for display (an ALTER passes the existing
        # record's display so it cannot silently re-case the name)
        self._put_json(KEY_POLICY_PREFIX + name.lower().encode(),
                       {"display": display or name, "options": options})

    def get_placement_policy(self, name: str):
        return self._get_json(KEY_POLICY_PREFIX + name.lower().encode(),
                              None)

    def drop_placement_policy(self, name: str):
        self.txn.delete(KEY_POLICY_PREFIX + name.lower().encode())

    def placement_policies(self) -> dict:
        out = {}
        end = KEY_POLICY_PREFIX + b"\xff"
        for k, v in self.txn.scan(KEY_POLICY_PREFIX, end):
            out[k[len(KEY_POLICY_PREFIX):].decode()] = json.loads(v)
        return out

    # -- sequences (reference: meta/autoid SequenceAllocator) ----------------

    def sequence_value(self, table_id: int):
        """Current (last-allocated) sequence value, or None if never used."""
        return self._get_json(KEY_SEQ_PREFIX + str(table_id).encode(), None)

    def set_sequence_value(self, table_id: int, v: int):
        self._put_json(KEY_SEQ_PREFIX + str(table_id).encode(), v)

    def sequence_next(self, table_id: int, seq: dict) -> int:
        """Allocate the next value per the sequence definition; raises on
        exhaustion unless CYCLE (reference: ddl/sequence.go + autoid)."""
        first, _count = self.sequence_next_batch(table_id, seq, 1)
        return first

    def sequence_next_batch(self, table_id: int, seq: dict,
                            want: int) -> tuple:
        """Claim up to `want` consecutive values in ONE meta write —
        sessions cache the batch so NEXTVAL is not a meta txn per row
        (reference: autoid SequenceAllocator + the CACHE option). Returns
        (first, count); count < want when the range boundary clips the
        batch. Raises on exhaustion unless CYCLE."""
        inc = seq.get("increment", 1) or 1
        lo = seq.get("min", 1 if inc > 0 else -(1 << 62))
        hi = seq.get("max", (1 << 62) if inc > 0 else -1)
        cur = self.sequence_value(table_id)
        if cur is None:
            first = seq.get("start", lo if inc > 0 else hi)
        else:
            first = cur + inc
        if first > hi or first < lo:
            if not seq.get("cycle"):
                raise TiDBError(
                    "Sequence has run out of range values",
                    code=ErrCode.SequenceRunOut)
            first = lo if inc > 0 else hi
        avail = (hi - first) // inc + 1 if inc > 0 else \
            (first - lo) // (-inc) + 1
        count = max(min(int(want), avail), 1)
        self.set_sequence_value(table_id, first + (count - 1) * inc)
        return first, count

    # -- delayed delete-ranges + dropped tables (reference:
    #    ddl/delete_range.go gc_delete_range + RecoverTable) ----------------

    def enqueue_delete_range(self, owner_tid: int, start: bytes, end: bytes,
                             ts: int):
        rid = self.gen_global_id()
        self._put_json(KEY_DELRANGE_PREFIX + str(rid).encode(),
                       {"owner": owner_tid, "start": start.hex(),
                        "end": end.hex(), "ts": ts})

    def delete_ranges(self):
        """[(key, {owner, start, end, ts})] pending physical deletions."""
        out = []
        for k, v in self.txn.scan(KEY_DELRANGE_PREFIX,
                                  KEY_DELRANGE_PREFIX + b"\xff"):
            out.append((k, json.loads(v.decode())))
        return out

    def remove_delete_range(self, key: bytes):
        self.txn.delete(key)

    def set_dropped_table(self, db_id: int, tbl: TableInfo, drop_ts: int):
        self._put_json(KEY_DROPPED_PREFIX + str(tbl.id).encode(),
                       {"db_id": db_id, "table": tbl.to_json(),
                        "ts": drop_ts})

    def dropped_tables(self):
        out = []
        for k, v in self.txn.scan(KEY_DROPPED_PREFIX,
                                  KEY_DROPPED_PREFIX + b"\xff"):
            d = json.loads(v.decode())
            out.append((k, d["db_id"], TableInfo.from_json(d["table"]),
                        d["ts"]))
        return out

    def remove_dropped_table(self, tid: int):
        self.txn.delete(KEY_DROPPED_PREFIX + str(tid).encode())

    # -- plan bindings (reference: mysql.bind_info + bindinfo/handle.go) -----

    def set_binding(self, digest: str, rec: dict):
        self._put_json(KEY_BINDING_PREFIX + digest.encode(), rec)

    def del_binding(self, digest: str):
        self.txn.delete(KEY_BINDING_PREFIX + digest.encode())

    def list_bindings(self) -> dict:
        out = {}
        for k, v in self.txn.scan(KEY_BINDING_PREFIX,
                                  KEY_BINDING_PREFIX + b"\xff"):
            out[k[len(KEY_BINDING_PREFIX):].decode()] = json.loads(v.decode())
        return out


def _tbl_key(db_id: int, tid: int) -> bytes:
    return KEY_TABLE_PREFIX + f"{db_id}:{tid}".encode()
