"""MySQL-compatible privilege system (reference: privilege/privileges/
cache.go — grant tables mysql.user / mysql.db / mysql.tables_priv loaded
into an in-memory cache; RequestVerification at cache.go:1069; GRANT/REVOKE
execute as DML on the grant tables + cache reload, executor/grant.go).

The grant tables are REAL tables created at bootstrap (SQL-queryable like
the reference), and this module keeps the fast lookup cache in sync."""

from __future__ import annotations

import hashlib
import threading

from .errors import TiDBError, ErrCode

#: column order of the per-level priv flags
PRIVS = ("select", "insert", "update", "delete", "create", "drop",
         "index", "alter", "super", "grant")
#: db/table level: no super, but grant option IS level-scoped
#: (reference: mysql.db has Grant_priv; tables_priv lists 'Grant')
DB_PRIVS = PRIVS[:8] + ("grant",)

BOOTSTRAP_SQL = [
    """create table if not exists mysql.user (
        host varchar(255), user varchar(32),
        authentication_string varchar(128), plugin varchar(64),
        select_priv varchar(1), insert_priv varchar(1),
        update_priv varchar(1), delete_priv varchar(1),
        create_priv varchar(1), drop_priv varchar(1),
        index_priv varchar(1), alter_priv varchar(1),
        super_priv varchar(1), grant_priv varchar(1),
        primary key (host, user))""",
    """create table if not exists mysql.db (
        host varchar(255), db varchar(64), user varchar(32),
        select_priv varchar(1), insert_priv varchar(1),
        update_priv varchar(1), delete_priv varchar(1),
        create_priv varchar(1), drop_priv varchar(1),
        index_priv varchar(1), alter_priv varchar(1),
        grant_priv varchar(1),
        primary key (host, db, user))""",
    """create table if not exists mysql.tables_priv (
        host varchar(255), db varchar(64), user varchar(32),
        table_name varchar(64), table_priv varchar(255),
        primary key (host, db, user, table_name))""",
]

ROOT_ROW = ("insert into mysql.user values ('%', 'root', '', "
            "'mysql_native_password', " + ", ".join(["'Y'"] * 10) + ")")


def mysql_native_hash(password: str) -> str:
    """MySQL native_password storage format *HEX(SHA1(SHA1(pw)))."""
    if not password:
        return ""
    h = hashlib.sha1(hashlib.sha1(password.encode()).digest()).hexdigest()
    return "*" + h.upper()


#: default when CREATE USER names no plugin
DEFAULT_AUTH_PLUGIN = "mysql_native_password"
SUPPORTED_AUTH_PLUGINS = ("mysql_native_password", "caching_sha2_password")


def auth_string_for(password: str, plugin: str) -> str:
    """Stored verifier per auth plugin (reference: conn.go:810 — native
    SHA1 chain vs caching_sha2's SHA256(SHA256(p)) cache entry)."""
    if plugin == "caching_sha2_password":
        from .server.protocol import caching_sha2_verifier
        return caching_sha2_verifier(password)
    return mysql_native_hash(password)


class UserRecord:
    __slots__ = ("host", "user", "auth", "privs", "plugin")

    def __init__(self, host, user, auth, privs,
                 plugin="mysql_native_password"):
        self.host = host
        self.user = user
        self.auth = auth          # *HEX / $S$HEX or "" (empty password)
        self.privs = privs        # set of global privs
        self.plugin = plugin or "mysql_native_password"


class PrivManager:
    """In-memory cache over the grant tables (reference:
    privileges.MySQLPrivilege)."""

    def __init__(self, domain):
        self.domain = domain
        self._lock = threading.Lock()
        self.users: list[UserRecord] = []
        self.dbs: list[tuple] = []        # (host, db, user, set(privs))
        self.tables: list[tuple] = []     # (host, db, user, table, set)
        self.enabled = False   # flips on once the grant tables exist
        self.disabled = False  # sticky skip-grant-table mode (config)

    # -- load (reference: cache.go LoadAll) ---------------------------------

    def load(self):
        try:
            infos = self.domain.infoschema()
            if infos.table_by_name("mysql", "user") is None:
                return
        except Exception as e:
            # a failed reload keeps the previously-loaded grant tables;
            # log it — silently serving stale privileges must be visible
            import logging
            from .utils.backoff import classify
            logging.getLogger("tidb_tpu.privilege").warning(
                "privilege reload failed, keeping cached grant tables "
                "(%s): %s", classify(e), e)
            return
        users, dbs, tables = [], [], []
        txn = self.domain.store.begin()
        try:
            from .table import Table
            uinfo = infos.table_by_name("mysql", "user")
            for _h, row in Table(uinfo, txn).iter_rows():
                vals = _row_strs(uinfo, row)
                privs = {p for p, v in zip(PRIVS, vals[4:14]) if v == "Y"}
                users.append(UserRecord(vals[0], vals[1], vals[2], privs,
                                        plugin=vals[3]))
            dinfo = infos.table_by_name("mysql", "db")
            for _h, row in Table(dinfo, txn).iter_rows():
                vals = _row_strs(dinfo, row)
                privs = {p for p, v in zip(DB_PRIVS, vals[3:12]) if v == "Y"}
                dbs.append((vals[0], vals[1], vals[2], privs))
            tinfo = infos.table_by_name("mysql", "tables_priv")
            for _h, row in Table(tinfo, txn).iter_rows():
                vals = _row_strs(tinfo, row)
                privs = {p.strip().lower()
                         for p in vals[4].split(",") if p.strip()}
                tables.append((vals[0], vals[1], vals[2], vals[3], privs))
        finally:
            txn.rollback()
        with self._lock:
            self.users, self.dbs, self.tables = users, dbs, tables
            self.enabled = not self.disabled

    # -- auth (reference: privileges.ConnectionVerification) ---------------

    def match_user(self, user: str, host: str = "%") -> UserRecord | None:
        """Most-specific host wins: exact host, then localhost aliases,
        then the '%' wildcard (reference: cache.go connectionVerification
        host matching)."""
        with self._lock:
            exact = [u for u in self.users if u.user == user]
        candidates = [host]
        if host in ("127.0.0.1", "::1", "localhost"):
            candidates += ["localhost", "127.0.0.1"]
        for h in candidates:
            for u in exact:
                if u.host == h:
                    return u
        for u in exact:
            if u.host == "%":
                return u
        return None

    def check_password_response(self, user, salt, response,
                                host: str = "%") -> "UserRecord | None":
        """Validate a mysql_native_password challenge response against the
        stored *HEX(SHA1(SHA1(pw))) hash: response ^ SHA1(salt+stored)
        must SHA1 to the stored hash. Returns the matched record (its host
        scopes the session's privileges) or None."""
        rec = self.match_user(user, host)
        if rec is None:
            return None
        if not rec.auth:
            return rec if not response else None  # empty password
        if rec.plugin == "caching_sha2_password":
            from .server.protocol import caching_sha2_check
            return rec if caching_sha2_check(rec.auth, salt, response) \
                else None
        stored = bytes.fromhex(rec.auth[1:])
        mix = hashlib.sha1(salt + stored).digest()
        if len(response) != len(mix):
            return None
        stage1 = bytes(a ^ b for a, b in zip(response, mix))
        return rec if hashlib.sha1(stage1).digest() == stored else None

    # -- verification (reference: cache.go:1069 RequestVerification) --------

    def verify(self, user_at_host: str, db: str, table: str, priv: str):
        if not self.enabled:
            return
        user, _, host = user_at_host.partition("@")
        rec = self.match_user(user, host or "%")
        if rec is not None and ("super" in rec.privs or priv in rec.privs):
            return
        dbl = (db or "").lower()
        if dbl in ("information_schema", "performance_schema",
                   "metrics_schema") and priv == "select":
            return
        hostv = host or "%"

        def host_ok(row_host):
            return row_host == "%" or row_host == hostv
        with self._lock:
            for h, d, u, privs in self.dbs:
                if (u == user and host_ok(h) and d.lower() == dbl
                        and priv in privs):
                    return
            for h, d, u, t, privs in self.tables:
                if (u == user and host_ok(h) and d.lower() == dbl
                        and t.lower() == (table or "").lower()
                        and priv in privs):
                    return
        if table:
            raise TiDBError(
                f"{priv.upper()} command denied to user "
                f"'{user}'@'{hostv}' for table '{db}.{table}'",
                code=ErrCode.TableaccessDenied)
        raise TiDBError(
            f"Access denied for user '{user}'@'{hostv}' to database "
            f"'{db}'" if db else
            f"{priv.upper()} command denied to user '{user}'@'{hostv}'",
            code=ErrCode.DBaccessDenied if db else ErrCode.AccessDenied)

    def grants_for(self, user: str, host: str = "%") -> list[str]:
        """SHOW GRANTS lines (reference: privileges.ShowGrants)."""
        out = []
        rec = self.match_user(user, host)
        if rec is not None:
            if set(PRIVS).issubset(rec.privs):
                g = ["ALL PRIVILEGES"]
            else:
                g = [p.upper() for p in PRIVS[:9] if p in rec.privs] \
                    or ["USAGE"]
            line = f"GRANT {', '.join(g)} ON *.* TO '{user}'@'{rec.host}'"
            if "grant" in rec.privs:
                line += " WITH GRANT OPTION"
            out.append(line)
        acct_host = rec.host if rec is not None else host

        def line(privs, target, h):
            names = sorted(p for p in privs if p != "grant")
            s = (f"GRANT {', '.join(p.upper() for p in names) or 'USAGE'} "
                 f"ON {target} TO '{user}'@'{h}'")
            if "grant" in privs:
                s += " WITH GRANT OPTION"
            return s
        with self._lock:
            # scope to the ACCOUNT (user, host) — never mix grants that
            # belong to a same-named user at a different host
            for h, d, u, privs in self.dbs:
                if u == user and h == acct_host and privs:
                    out.append(line(privs, f"{d}.*", h))
            for h, d, u, t, privs in self.tables:
                if u == user and h == acct_host and privs:
                    out.append(line(privs, f"{d}.{t}", h))
        return out


def _row_strs(info, row: dict) -> list[str]:
    out = []
    for c in info.public_columns():
        v = row.get(c.id)
        if isinstance(v, (bytes, bytearray)):
            v = v.decode("utf-8", "replace")
        out.append("" if v is None else str(v))
    return out
