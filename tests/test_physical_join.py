"""Cost-based physical join selection: IndexJoin / MergeJoin / HashJoin
chosen per shape, with result parity across algorithms (reference:
planner/core/exhaust_physical_plans.go:1774 join alternatives,
find_best_task.go:359 cost choice, executor/index_lookup_join.go,
executor/merge_join.go)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database pj")
    tk.must_exec("use pj")
    # big inner with a handle pk and a non-unique secondary index
    tk.must_exec("""create table big (
        id bigint primary key, grp bigint, val bigint, key idx_grp (grp))""")
    tk.must_exec("insert into big values " + ",".join(
        f"({i}, {i % 500}, {i * 3})" for i in range(6000)))
    # small outer
    tk.must_exec("create table small (k bigint, tag varchar(10))")
    tk.must_exec("insert into small values " + ",".join(
        f"({i * 7}, 't{i}')" for i in range(30)))
    # two large tables for the merge shape
    tk.must_exec("create table la (k bigint, v bigint)")
    tk.must_exec("create table lb (k bigint, w bigint)")
    tk.must_exec("insert into la values " + ",".join(
        f"({i % 4500}, {i})" for i in range(5000)))
    tk.must_exec("insert into lb values " + ",".join(
        f"({i % 4800}, {i})" for i in range(5000)))
    for t in ("big", "small", "la", "lb"):
        tk.must_exec(f"analyze table {t}")
    return tk


def plan_of(tk, sql):
    return "\n".join(" | ".join(c or "" for c in r)
                     for r in tk.must_query("explain " + sql).rows)


def test_index_join_on_handle(tk):
    sql = ("select small.k, big.val from small, big "
           "where small.k = big.id order by small.k")
    p = plan_of(tk, sql)
    assert "IndexJoin" in p and "inner:handle" in p
    rows = tk.must_query(sql).rows
    # every small.k in [0, 6000) with k = i*7 matches; val = id*3
    assert rows == [(str(i * 7), str(i * 21)) for i in range(30)]


def test_index_join_on_secondary_index(tk):
    sql = ("select small.k, count(1) from small, big "
           "where small.k = big.grp group by small.k order by small.k")
    p = plan_of(tk, sql)
    assert "IndexJoin" in p and "inner:index:idx_grp" in p
    rows = tk.must_query(sql).rows
    # grp values 0..499, 12 rows each; small.k = 7i matches when 7i < 500
    expect = [(str(i * 7), "12") for i in range(30) if i * 7 < 500]
    assert rows == expect


def test_merge_join_for_pk_ordered_sides(tk):
    # both sides stream in key order for free (handle-ordered scans on
    # the int PK) — the only shape where cost picks merge; unsorted
    # sides would hide a huge host sort AND forfeit the device fragment
    tk.must_exec("create table pka (k bigint primary key, v bigint)")
    tk.must_exec("create table pkb (k bigint primary key, w bigint)")
    tk.must_exec("insert into pka values " + ",".join(
        f"({i}, {i * 2})" for i in range(5000)))
    tk.must_exec("insert into pkb values " + ",".join(
        f"({i * 2}, {i})" for i in range(5000)))
    tk.must_exec("analyze table pka")
    tk.must_exec("analyze table pkb")
    sql = "select count(1) from pka, pkb where pka.k = pkb.k"
    p = plan_of(tk, sql)
    assert "MergeJoin" in p, p
    # pka.k: 0..4999; pkb.k: even 0..9998 — overlap = even k < 5000
    assert int(tk.must_query(sql).rows[0][0]) == 2500


def test_unsorted_large_join_stays_hash(tk):
    # large primitive keys but neither side PK-ordered: the old cost
    # model picked merge here from the n·log n constants; the measured
    # SF10 host regression (64s -> 166s) pins this to hash now
    sql = "select count(1) from la, lb where la.k = lb.k"
    p = plan_of(tk, sql)
    assert "HashJoin" in p, p
    got = int(tk.must_query(sql).rows[0][0])
    # independent check: join cardinality computed in python
    from collections import Counter
    ca = Counter(i % 4500 for i in range(5000))
    cb = Counter(i % 4800 for i in range(5000))
    assert got == sum(ca[k] * cb[k] for k in ca)


def test_small_join_stays_hash(tk):
    p = plan_of(tk, "select count(1) from small s1, small s2 "
                    "where s1.k = s2.k")
    assert "HashJoin" in p


def test_string_keys_stay_hash(tk):
    tk.must_exec("create table sa (s varchar(10), v bigint)")
    tk.must_exec("insert into sa values " + ",".join(
        f"('s{i % 40}', {i})" for i in range(5000)))
    tk.must_exec("analyze table sa")
    p = plan_of(tk, "select count(1) from sa x, sa y where x.s = y.s")
    assert "HashJoin" in p


def test_index_join_left_outer_parity(tk):
    # left join keeps unmatched outer rows; k=42000+ has no match
    tk.must_exec("create table sl (k bigint)")
    tk.must_exec("insert into sl values (7), (14), (999999)")
    tk.must_exec("analyze table sl")
    sql = ("select sl.k, big.val from sl left join big on sl.k = big.id "
           "order by sl.k")
    p = plan_of(tk, sql)
    assert "IndexJoin" in p
    assert tk.must_query(sql).rows == [
        ("7", "21"), ("14", "42"), ("999999", None)]


def test_index_join_sees_uncommitted_rows(tk):
    tk.must_exec("begin")
    tk.must_exec("insert into big values (100000, 1, 300000)")
    tk.must_exec("insert into small values (100000, 'txn')")
    sql = ("select small.k, big.val from small, big "
           "where small.k = big.id and small.k = 100000")
    rows = tk.must_query(sql).rows
    tk.must_exec("rollback")
    assert rows == [("100000", "300000")]


def test_engine_parity_across_algorithms(tk):
    # the tpu engine path must return identical rows for plans containing
    # MergeJoin / IndexJoin nodes
    for sql in [
        "select count(1) from la, lb where la.k = lb.k",
        "select small.k, big.val from small, big where small.k = big.id "
        "order by small.k",
    ]:
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tk.must_query(sql).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        dev = tk.must_query(sql).rows
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert host == dev


def test_ignore_index_hint_steers_inner_path(tk):
    # review regression: IGNORE INDEX on the inner table must exclude that
    # index from index-join inner-path selection
    sql = ("select small.k, count(1) from small, big ignore index (idx_grp) "
           "where small.k = big.grp group by small.k order by small.k")
    p = plan_of(tk, sql)
    assert "idx_grp" not in p


class TestCostEnumeration:
    """Explicit per-variant join costing (reference:
    exhaust_physical_plans.go:1774 emits candidates,
    find_best_task.go:359 compares task costs; EXPLAIN FORMAT='verbose'
    prints estCost)."""

    @pytest.fixture()
    def ctk(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table cb1 (a bigint primary key, b bigint)")
        tk.must_exec("create table cb2 (a bigint, c bigint)")
        for lo in range(0, 9000, 3000):
            tk.must_exec("insert into cb1 values " + ",".join(
                f"({i},{i % 50})" for i in range(lo, lo + 3000)))
            tk.must_exec("insert into cb2 values " + ",".join(
                f"({(i * 37) % 9000},{i})" for i in range(lo, lo + 3000)))
        tk.must_exec("analyze table cb1")
        tk.must_exec("analyze table cb2")
        return tk

    def _verbose(self, tk, sql):
        return [(r[0], r[1]) for r in tk.must_query(
            "explain format='verbose' " + sql).rows]

    def test_all_variants_costed_and_cheapest_wins(self, ctk):
        rows = self._verbose(
            ctk, "select cb2.c, cb1.b from cb2, cb1 where cb2.a = cb1.a")
        join = next(r for r in rows if "Join" in r[0])
        # every eligible variant appears with a cost; the chosen one's
        # cost equals the minimum (merge is absent: cb2 is not
        # PK-ordered on the key, so the candidate never forms)
        assert "hash:" in join[1] and "index:" in join[1], join
        chosen = float(join[1].split()[0])
        cands = {p.split(":")[0]: float(p.split(":")[1]) for p in
                 join[1].split("{")[1].rstrip("}").split(", ")}
        assert chosen == min(cands.values())

    def test_selective_outer_flips_to_index_join(self, ctk):
        rows = self._verbose(
            ctk, "select cb2.c, cb1.b from cb2, cb1 "
                 "where cb2.a = cb1.a and cb2.c = 5")
        assert any("IndexJoin" in r[0] for r in rows), rows

    def test_costs_only_under_verbose(self, ctk):
        plain = ctk.must_query(
            "explain select cb2.c from cb2, cb1 "
            "where cb2.a = cb1.a").rows
        assert all(len(r) == 2 for r in plain)  # no cost column
