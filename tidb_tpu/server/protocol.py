"""MySQL protocol payloads: handshake, OK/ERR/EOF, column definitions and
text resultset rows (reference: server/conn.go writeInitialHandshake /
writeOK / writeError, server/column.go Dump, server/conn.go:2096
writeResultset)."""

from __future__ import annotations

import hashlib
import os
import struct

from ..sqltypes import (
    TYPE_DATE, TYPE_DATETIME, TYPE_DOUBLE, TYPE_DURATION, TYPE_FLOAT,
    TYPE_INT24, TYPE_LONG, TYPE_LONGLONG, TYPE_NEWDECIMAL, TYPE_NULL,
    TYPE_SHORT, TYPE_TIMESTAMP, TYPE_TINY, TYPE_VARCHAR, TYPE_YEAR,
)
from .packet import lenenc_int, lenenc_str

PROTOCOL_VERSION = 10
SERVER_VERSION = b"8.0.11-tidb-tpu"

# capability flags (subset)
CLIENT_LONG_PASSWORD = 0x1
CLIENT_FOUND_ROWS = 0x2
CLIENT_LONG_FLAG = 0x4
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_SSL = 0x800
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_MULTI_STATEMENTS = 0x10000
CLIENT_MULTI_RESULTS = 0x20000
CLIENT_PLUGIN_AUTH = 0x80000

SERVER_CAPABILITIES = (
    CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS | CLIENT_LONG_FLAG
    | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS
    | CLIENT_SECURE_CONNECTION | CLIENT_MULTI_STATEMENTS
    | CLIENT_MULTI_RESULTS | CLIENT_PLUGIN_AUTH)

SERVER_STATUS_AUTOCOMMIT = 0x2
SERVER_MORE_RESULTS_EXISTS = 0x8

# commands
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_SEND_LONG_DATA = 0x18
COM_STMT_CLOSE = 0x19
COM_STMT_RESET = 0x1A
COM_STMT_FETCH = 0x1C

SERVER_STATUS_IN_TRANS = 0x0001

#: COM_STMT_EXECUTE cursor flags (reference: server/conn_stmt.go)
CURSOR_TYPE_READ_ONLY = 0x01
SERVER_STATUS_CURSOR_EXISTS = 0x0040
SERVER_STATUS_LAST_ROW_SENT = 0x0080

CHARSET_UTF8MB4 = 255


def caching_sha2_scramble(password: bytes, nonce: bytes) -> bytes:
    """Client-side caching_sha2_password scramble:
    XOR(SHA256(p), SHA256(SHA256(SHA256(p)) || nonce)) (reference:
    server/conn.go:810 authCachingSha2; used by tests/minclients)."""
    import hashlib as _h
    if not password:
        return b""
    p1 = _h.sha256(password).digest()
    p2 = _h.sha256(_h.sha256(p1).digest() + nonce).digest()
    return bytes(a ^ b for a, b in zip(p1, p2))


def caching_sha2_verifier(password: str) -> str:
    """Stored verifier S = SHA256(SHA256(p)); the fast-auth check needs
    only S, which is what the reference's in-memory cache holds."""
    import hashlib as _h
    if not password:
        return ""
    return "$S$" + _h.sha256(
        _h.sha256(password.encode()).digest()).hexdigest().upper()


def caching_sha2_check(verifier: str, nonce: bytes, response: bytes) -> bool:
    """Fast-path verify: SHA256(response XOR SHA256(S || nonce)) == S."""
    import hashlib as _h
    s = bytes.fromhex(verifier[3:])
    mix = _h.sha256(s + nonce).digest()
    if len(response) != len(mix):
        return False
    p1 = bytes(a ^ b for a, b in zip(response, mix))
    return _h.sha256(p1).digest() == s


def build_auth_switch(plugin: str, salt: bytes) -> bytes:
    """AuthSwitchRequest (reference: server/conn.go writeAuthSwitchRequest)."""
    return b"\xfe" + plugin.encode() + b"\x00" + salt + b"\x00"


#: caching_sha2 fast-auth-success marker (0x01 0x03)
FAST_AUTH_SUCCESS = b"\x01\x03"


def native_password_hash(password: bytes, salt: bytes) -> bytes:
    """mysql_native_password scramble: SHA1(pwd) XOR SHA1(salt+SHA1(SHA1(pwd)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def build_handshake(conn_id: int, salt: bytes, extra_caps: int = 0) -> bytes:
    caps = SERVER_CAPABILITIES | extra_caps
    out = bytes([PROTOCOL_VERSION]) + SERVER_VERSION + b"\x00"
    out += struct.pack("<I", conn_id)
    out += salt[:8] + b"\x00"
    out += struct.pack("<H", caps & 0xFFFF)
    out += bytes([CHARSET_UTF8MB4])
    out += struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
    out += struct.pack("<H", (caps >> 16) & 0xFFFF)
    out += bytes([len(salt) + 1])
    out += b"\x00" * 10
    out += salt[8:] + b"\x00"
    out += b"mysql_native_password\x00"
    return out


def build_ok(affected=0, last_insert_id=0, status=SERVER_STATUS_AUTOCOMMIT,
             warnings=0) -> bytes:
    return (b"\x00" + lenenc_int(affected) + lenenc_int(last_insert_id)
            + struct.pack("<HH", status, warnings))


def build_eof(status=SERVER_STATUS_AUTOCOMMIT, warnings=0) -> bytes:
    return b"\xfe" + struct.pack("<HH", warnings, status)


def build_err(code: int, message: str, state: bytes = b"HY000") -> bytes:
    return (b"\xff" + struct.pack("<H", code) + b"#" + state
            + message.encode("utf-8"))


def new_salt() -> bytes:
    # 20 printable bytes, no NULs (reference: util.RandomBuf)
    out = bytearray(os.urandom(20))
    for i, b in enumerate(out):
        out[i] = 1 + (b % 125)
    return bytes(out)


def column_def(name: str, ftype, db: str = "", table: str = "") -> bytes:
    """Protocol::ColumnDefinition41."""
    tp = ftype.tp
    flen = ftype.flen if ftype.flen and ftype.flen > 0 else 255
    decimals = 0
    charset = CHARSET_UTF8MB4
    if tp in (TYPE_LONGLONG, TYPE_DOUBLE, TYPE_FLOAT, TYPE_NEWDECIMAL):
        charset = 63  # binary
        if tp == TYPE_NEWDECIMAL:
            decimals = ftype.scale
        flen = 21
    elif tp in (TYPE_DATE, TYPE_DATETIME, TYPE_TIMESTAMP):
        charset = 63
        flen = 26
    elif tp == TYPE_NULL:
        charset = 63
    out = lenenc_str(b"def")
    out += lenenc_str(db.encode())
    out += lenenc_str(table.encode())
    out += lenenc_str(table.encode())
    out += lenenc_str(name.encode())
    out += lenenc_str(name.encode())
    out += bytes([0x0C])
    out += struct.pack("<H", charset)
    out += struct.pack("<I", flen)
    out += bytes([tp & 0xFF])
    out += struct.pack("<H", ftype.flag)
    out += bytes([decimals])
    out += b"\x00\x00"
    return out


def text_row(row) -> bytes:
    """One text-protocol row: display strings, NULL = 0xFB."""
    out = b""
    for v in row:
        if v is None:
            out += b"\xfb"
        else:
            out += lenenc_str(v.encode("utf-8") if isinstance(v, str)
                              else bytes(v))
    return out


def _pack_datetime(s: str) -> bytes:
    """Pack 'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' into the binary wire form
    (length byte + packed fields, trailing zero parts trimmed)."""
    date_part, _, time_part = s.partition(" ")
    y, mo, d = (int(x) for x in date_part.split("-"))
    h = mi = sec = us = 0
    if time_part:
        hms, _, frac = time_part.partition(".")
        h, mi, sec = (int(x) for x in hms.split(":"))
        us = int(frac.ljust(6, "0")) if frac else 0
    if us:
        return (bytes([11]) + struct.pack("<H", y) + bytes([mo, d, h, mi, sec])
                + struct.pack("<I", us))
    if h or mi or sec:
        return bytes([7]) + struct.pack("<H", y) + bytes([mo, d, h, mi, sec])
    if y or mo or d:
        return bytes([4]) + struct.pack("<H", y) + bytes([mo, d])
    return bytes([0])


def _pack_duration(s: str) -> bytes:
    """Pack '[-]HH:MM:SS[.ffffff]' into the binary TIME wire form."""
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    hms, _, frac = s.partition(".")
    h, mi, sec = (int(x) for x in hms.split(":"))
    us = int(frac.ljust(6, "0")) if frac else 0
    days, h = divmod(h, 24)
    if not (days or h or mi or sec or us):
        return bytes([0])
    body = bytes([1 if neg else 0]) + struct.pack("<I", days) + bytes([h, mi, sec])
    if us:
        return bytes([12]) + body + struct.pack("<I", us)
    return bytes([8]) + body


_LENENC_TYPES = frozenset({
    TYPE_NEWDECIMAL, TYPE_VARCHAR, TYPE_NULL,
}) | {0x10, 0xF5, 0xF7, 0xF8, 0xF9, 0xFA, 0xFB, 0xFC, 0xFD, 0xFE, 0xFF}


def binary_row(row, ftypes) -> bytes:
    """One Protocol::BinaryResultsetRow: 0x00 header, NULL bitmap at bit
    offset 2, then values encoded by the advertised column type — matching
    column_def's tp byte so real binary-protocol clients (libmysqlclient,
    JDBC, mysql-connector) can parse EXECUTE results (reference:
    server/column.go Column.Dump / conn_stmt.go writeBinaryRow)."""
    n = len(row)
    bitmap = bytearray((n + 7 + 2) // 8)
    vals = b""
    for i, (v, ft) in enumerate(zip(row, ftypes)):
        if v is None:
            bit = i + 2
            bitmap[bit // 8] |= 1 << (bit % 8)
            continue
        tp = ft.tp
        unsigned = bool(ft.flag & 0x20)
        s = None
        if tp not in _LENENC_TYPES:
            s = v if isinstance(v, str) else (
                v.decode("utf-8", "surrogateescape")
                if isinstance(v, (bytes, bytearray)) else str(v))
        if tp == TYPE_TINY:
            vals += struct.pack("<B" if unsigned else "<b", int(s))
        elif tp in (TYPE_SHORT, TYPE_YEAR):
            vals += struct.pack("<H" if unsigned else "<h", int(s))
        elif tp in (TYPE_LONG, TYPE_INT24):
            vals += struct.pack("<I" if unsigned else "<i", int(s))
        elif tp == TYPE_LONGLONG:
            vals += struct.pack("<Q" if unsigned else "<q", int(s))
        elif tp == TYPE_FLOAT:
            vals += struct.pack("<f", float(s))
        elif tp == TYPE_DOUBLE:
            vals += struct.pack("<d", float(s))
        elif tp in (TYPE_DATE, TYPE_DATETIME, TYPE_TIMESTAMP):
            vals += _pack_datetime(s)
        elif tp == TYPE_DURATION:
            vals += _pack_duration(s)
        else:  # NEWDECIMAL / VARCHAR / STRING / BLOB / JSON / ENUM / SET
            vals += lenenc_str(
                v.encode("utf-8") if isinstance(v, str)
                else bytes(v) if isinstance(v, (bytes, bytearray))
                else str(v).encode("utf-8"))
    return b"\x00" + bytes(bitmap) + vals
