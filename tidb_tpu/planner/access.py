"""Physical access-path selection: PointGet / IndexLookUp / full columnar
scan, chosen by cost (reference: planner/core/find_best_task.go:359
physical search over access paths, point_get_plan.go:467 TryFastPlan,
executor/point_get.go, executor/distsql.go IndexLookUp).

The task model is {host-seek, tpu-scan}: index paths materialize a small
row set via row-at-a-time KV seeks (host), the full scan feeds the fused
vectorized device pipeline. Costing: seeks pay a per-row decode constant,
the scan pays a per-row vectorized constant — index wins only when the
consumed predicates are selective enough (estimated from ANALYZE
histograms/TopN, statistics/selectivity.py).

Access descriptors stored on DataSource.access:
    ("point_pk", handle)               pk_is_handle eq const
    ("point_index", idx, vals)         unique index, all columns eq-bound
    ("index_range", idx, lo, hi, nc)   eq-prefix (+ one range col); lo/hi
                                       are index value tuples or None
All pushed conds stay as post-filters — the index only pre-selects
candidate handles, so boundary/visibility semantics never depend on the
path taken.
"""

from __future__ import annotations

import numpy as np

from ..model import SchemaState
from ..statistics.selectivity import _col_const, estimate_selectivity
from .logical import DataSource

#: cost constants: per-row KV seek+decode vs per-row vectorized scan
SEEK_COST = 8.0
SEEK_BASE = 30.0
SCAN_ROW_COST = 1.0


def choose_access_paths(plan, ctx):
    if isinstance(plan, DataSource):
        _choose(plan, ctx)
    for c in plan.children:
        choose_access_paths(c, ctx)
    return plan


def _int_like(v):
    return isinstance(v, (int, np.integer)) and not isinstance(v, bool)


def _hint_sets(ds):
    """USE/FORCE/IGNORE INDEX hints → (allowed | None, excluded, forced)
    (reference: planner/core accessPath hint pruning)."""
    allowed, excluded = None, set()
    forced = False
    for verb, names in getattr(ds, "index_hints", []):
        lnames = {n.lower() for n in names}
        if verb in ("use", "force"):
            allowed = (allowed or set()) | lnames
            forced = forced or verb == "force"
        elif verb == "ignore":
            excluded |= lnames
    return allowed, excluded, forced


def _idx_allowed(idx, allowed, excluded):
    n = idx.name.lower()
    return (allowed is None or n in allowed) and n not in excluded


def _choose(ds: DataSource, ctx):
    ds.access = None
    ds.access_est = None
    info = ds.table_info
    if not ds.pushed_conds:
        return
    # classify pushed conds: eq consts and range bounds per schema idx
    eq, rngs, by_idx = {}, {}, {}
    for c in ds.pushed_conds:
        cc = _col_const(c)
        if cc is None:
            continue
        col, v, op = cc
        if v is None:
            continue
        if op == "eq":
            eq.setdefault(col.idx, v)
            by_idx.setdefault(col.idx, []).append(c)
        elif op in ("lt", "le", "gt", "ge") and isinstance(v, (int, float)):
            rngs.setdefault(col.idx, []).append((op, v))
            by_idx.setdefault(col.idx, []).append(c)
    allowed, excluded, forced = _hint_sets(ds)
    name2idx = {ci.name: i for i, ci in enumerate(ds.col_infos)}
    if not eq and not rngs:
        _choose_batch(ds, info, name2idx, allowed, excluded)
        return

    # 1. PointGet on the integer primary key stored as the row handle
    if info.pk_is_handle:
        pk_idx = next((i for i, ci in enumerate(ds.col_infos)
                       if ci.id == info.pk_col_id), None)
        if pk_idx is not None and pk_idx in eq and _int_like(eq[pk_idx]):
            ds.access = ("point_pk", int(eq[pk_idx]))
            ds.access_est = 1
            return

    # 2. PointGet via a unique index with every column eq-bound
    for idx in info.indexes:
        if idx.state != SchemaState.PUBLIC or not idx.unique:
            continue
        if not _idx_allowed(idx, allowed, excluded):
            continue
        vals = []
        for icol in idx.columns:
            i = name2idx.get(icol.name)
            if i is None or i not in eq:
                break
            vals.append(eq[i])
        else:
            if vals:
                ds.access = ("point_index", idx, vals)
                ds.access_est = 1
                return

    # 2.5 BatchPointGet candidates exist alongside eq/range conds too
    _choose_batch(ds, info, name2idx, allowed, excluded)
    if ds.access is not None:
        return

    # 3. cost-based index range scan vs full columnar scan
    stats = (ctx.table_stats(info.id)
             if ctx is not None and hasattr(ctx, "table_stats") else None)
    n = max((stats or {}).get("row_count", 0), 1)
    if (stats is None or n < 2) and not forced:
        return  # no stats → pseudo costing favors the vectorized scan
    best = None
    for idx in info.indexes:
        if idx.state != SchemaState.PUBLIC:
            continue
        if not _idx_allowed(idx, allowed, excluded):
            continue
        prefix, consumed_eq, consumed_rng = [], [], []
        for icol in idx.columns:
            i = name2idx.get(icol.name)
            if i is not None and i in eq:
                prefix.append(eq[i])
                consumed_eq.extend(by_idx[i])
            else:
                break
        lo_b = hi_b = None
        npos = len(prefix)
        if npos < len(idx.columns):
            i = name2idx.get(idx.columns[npos].name)
            if i is not None and i in rngs:
                for op, v in rngs[i]:
                    if op in ("gt", "ge"):
                        lo_b = v if lo_b is None else max(lo_b, v)
                    else:
                        hi_b = v if hi_b is None else min(hi_b, v)
                consumed_rng.extend(by_idx[i])
        if not prefix and lo_b is None and hi_b is None:
            continue
        consumed = consumed_eq + consumed_rng
        # multi-column eq-prefix selectivity: prefer the index's own prefix
        # NDV over the per-column independence product (reference: index
        # stats in statistics/table.go GetRowCountByIndexRanges). For a
        # single eq column the per-column TopN/CMSketch estimate is
        # strictly better (it sees skew; 1/NDV does not).
        idx_stats = ((stats or {}).get("indexes") or {}).get(str(idx.id))
        if (len(prefix) >= 2 and idx_stats
                and len(idx_stats["prefix_ndv"]) >= len(prefix)):
            eq_sel = 1.0 / max(idx_stats["prefix_ndv"][len(prefix) - 1], 1)
            sel = eq_sel * (estimate_selectivity(stats, ds.col_infos,
                                                 consumed_rng)
                            if consumed_rng else 1.0)
        else:
            sel = estimate_selectivity(stats, ds.col_infos, consumed)
        est_rows = max(n * sel, 1.0)
        cost = SEEK_BASE + est_rows * SEEK_COST
        if best is None or cost < best[0]:
            lo = (prefix + ([_idx_bound(lo_b)] if lo_b is not None else [])
                  ) or None
            hi = (prefix + ([_idx_bound(hi_b)] if hi_b is not None else [])
                  ) or None
            if lo_b is None and prefix:
                lo = list(prefix)
            if hi_b is None and prefix:
                hi = list(prefix)
            best = (cost, ("index_range", idx, lo, hi), est_rows)
    if best is None:
        return
    cost_full = n * SCAN_ROW_COST
    if forced or best[0] < cost_full:
        ds.access = best[1]
        ds.access_est = int(best[2])


def _choose_batch(ds, info, name2idx, allowed, excluded):
    """BatchPointGet: col IN (c1..cn) on the handle pk or a single-column
    unique index (reference: planner/core/point_get_plan.go
    newBatchPointGetPlan, executor/batch_point_get.go)."""
    from ..expression.core import Column as _Col
    from ..expression.core import ScalarFunc as _SF
    for c in ds.pushed_conds:
        if not (isinstance(c, _SF) and c.op == "in_set" and c.extra):
            continue
        t = c.args[0]
        if not isinstance(t, _Col):
            continue
        # dict.fromkeys dedups while keeping first-seen order: IN (3, 3)
        # must fetch the row ONCE (the post-filter passes every copy)
        values = list(dict.fromkeys(
            v.item() if isinstance(v, np.generic) else v
            for v in c.extra[0]))
        if not values or len(values) > 1024:
            continue
        if (info.pk_is_handle and t.idx < len(ds.col_infos)
                and ds.col_infos[t.idx].id == info.pk_col_id
                and all(_int_like(v) for v in values)):
            ds.access = ("batch_pk", [int(v) for v in values])
            ds.access_est = len(values)
            return
        for idx in info.indexes:
            if (idx.state == SchemaState.PUBLIC and idx.unique
                    and len(idx.columns) == 1
                    and _idx_allowed(idx, allowed, excluded)
                    and name2idx.get(idx.columns[0].name) == t.idx):
                ds.access = ("batch_index", idx, values)
                ds.access_est = len(values)
                return


def _idx_bound(v):
    """Range bound → index-codec value (floats from histograms/consts may
    bound an int column; truncate toward -inf so the inclusive scan keeps
    every candidate — post-filters trim exactly)."""
    if isinstance(v, float) and float(v).is_integer():
        return int(v)
    if isinstance(v, float):
        return int(np.floor(v))
    return v
