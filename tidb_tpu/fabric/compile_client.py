"""Worker-side client of the separated compile server.

The client owns the full remote-compile decision for one pipeline
resolution (``serve``): artifact hit (shared directory, then the
server's ``fetch`` op) → install the deserialized module with ZERO local
traces; otherwise trace locally, ship the StableHLO to the server for
the expensive XLA compile, and dispatch through the exported module so
the local "compile" is an AOT-cache deserialize.

Failure discipline (the BENCH_TPU_LIVE Q5 lesson): the client NEVER
raises out of ``serve`` — a dead socket, torn frame or server-side error
returns ``(None, classified_error)`` so the caller builds inline and the
compile-scoped breaker (9010) records the remote failure; a down-window
then short-circuits further attempts for a few seconds so a dead server
costs one timeout, not one per fragment.
"""

from __future__ import annotations

import contextlib
import logging
import socket
import threading
import time

from . import codec, compile_server as artifacts

log = logging.getLogger("tidb_tpu.fabric.compile_client")

#: how long a transport failure silences remote attempts (the breaker's
#: cooldown shapes query-visible behavior; this just stops re-dialing a
#: dead socket on every obtain in between)
DOWN_COOLDOWN_S = 5.0
CONNECT_TIMEOUT_S = 5.0
#: per-request bound — a remote compile of a big fragment is minutes on
#: a real TPU; the sync caller is already the slow path
REQUEST_TIMEOUT_S = 300.0

_LOCK = threading.Lock()
_CLIENTS: dict = {}


def get_client(address: "str | None" = None) -> "CompileClient | None":
    """The process's client for `address` (default: the fabric state's
    compile-server address), or None when no server is configured."""
    if address is None:
        from . import state
        address = state.compile_server_addr()
    if not address:
        return None
    with _LOCK:
        cli = _CLIENTS.get(address)
        if cli is None:
            cli = _CLIENTS[address] = CompileClient(address)
        return cli


class CompileClient:
    def __init__(self, address: str,
                 down_cooldown_s: float = DOWN_COOLDOWN_S):
        self.address = address
        self._down_until = 0.0
        self._down_cooldown = down_cooldown_s
        self._mu = threading.Lock()

    def healthy(self) -> bool:
        return time.monotonic() >= self._down_until

    def _mark_down(self):
        self._down_until = time.monotonic() + self._down_cooldown

    def _connect(self):
        if ":" in self.address:
            host, port = self.address.rsplit(":", 1)
            return socket.create_connection((host, int(port)),
                                            timeout=CONNECT_TIMEOUT_S)
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(CONNECT_TIMEOUT_S)
        s.connect(self.address)
        return s

    def request(self, obj: dict, timeout_s: float = REQUEST_TIMEOUT_S):
        """One round trip.  Raises DeviceCompileError (errno 9010,
        taxonomy class ``compile``) on any transport/frame/server
        failure — the caller's breaker records exactly that class."""
        from ..errors import DeviceCompileError
        from ..session import tracing
        from . import state
        ctx = tracing.wire_ctx()
        if ctx is not None:  # propagate the statement's trace across the hop
            obj["trace"] = ctx
        t0 = time.perf_counter()
        try:
            with self._mu:  # one in-flight request per client: the
                #             server serializes compiles anyway
                sock = self._connect()
                try:
                    sock.settimeout(timeout_s)
                    codec.write_frame(sock, obj)
                    resp = codec.read_frame(sock)
                finally:
                    with contextlib.suppress(OSError):
                        sock.close()
        except (OSError, codec.FrameError) as e:
            self._mark_down()
            state.bump("fabric_remote_errors")
            raise DeviceCompileError(
                f"compile server {self.address} unreachable/torn: "
                f"{type(e).__name__}: {e}") from e
        state.note_rtt((time.perf_counter() - t0) * 1000.0)
        # stitch the server's recorded subtree (attached even on a
        # server-side error reply: the failed hop still belongs in the
        # statement's timeline)
        tracing.attach_remote(resp.pop("_trace", None))
        if not resp.get("ok"):
            state.bump("fabric_remote_errors")
            raise DeviceCompileError(
                f"compile server {self.address} failed the request: "
                f"{resp.get('error', 'unknown error')}")
        return resp

    def ping(self, timeout_s: float = 5.0) -> dict:
        return self.request({"op": "ping"}, timeout_s=timeout_s)

    # -- the pipeline-resolution entry ---------------------------------------

    def serve(self, key, build, spec, shape: str, sig) -> tuple:
        """Resolve one cold pipeline via the fabric: returns
        ``(fn, None)`` on success, ``(None, classified_error)`` when the
        remote path failed (caller builds inline and charges the 9010
        breaker), ``(None, None)`` when remote is in its down-window or
        the shape can't export (caller builds inline, no charge)."""
        from ..executor.compile_service import _persist_hash
        from ..session import tracing
        from . import state
        key_hash = _persist_hash(key)
        # 1. shared artifact directory: another worker (or a previous
        #    incarnation) already compiled this — zero local traces
        fn = self._from_artifact(key_hash, artifacts.load_artifact(key_hash))
        if fn is not None:
            state.bump("fabric_artifact_hits")
            tracing.event("fabric.compile", mode="artifact")
            return fn, None
        if not self.healthy():
            return None, None
        # 2. server fetch: the artifact may exist on the server's side of
        #    a non-shared mount
        try:
            resp = self.request({"op": "fetch", "key_hash": key_hash},
                                timeout_s=10.0)
            if resp.get("found"):
                fn = self._from_artifact(key_hash, resp["module"])
                if fn is not None:
                    state.bump("fabric_artifact_hits")
                    tracing.event("fabric.compile", mode="fetch")
                    return fn, None
        except Exception as e:  # noqa: BLE001 — classified below
            return None, e
        # 3. trace locally (cheap), compile remotely (expensive)
        if spec is None or build is None:
            return None, None  # nothing to trace: caller handles it
        try:
            exp, blob = export_pipeline(build, spec)
        except Exception as e:  # noqa: BLE001 — shape opt-out, not health
            # this shape doesn't export (exotic pytree, unsupported
            # primitive): not a server health signal — build inline
            log.debug("pipeline shape %s does not export (inline "
                      "build): %s", shape, e)
            return None, None
        try:
            with tracing.span("compile.remote", shape=shape):
                self.request({"op": "compile", "key_hash": key_hash,
                              "module": blob, "shape": shape,
                              "sig": repr(sig)[:512]})
        except Exception as e:  # noqa: BLE001 — classified DeviceCompileError
            return None, e
        state.bump("fabric_remote_compiles")
        tracing.event("fabric.compile", mode="remote")
        return wrap_exported(exp), None

    @staticmethod
    def _from_artifact(key_hash: str, blob):
        if blob is None:
            return None
        try:
            from jax import export
            return wrap_exported(export.deserialize(bytearray(blob)))
        except Exception as e:  # noqa: BLE001 — corrupt artifact != fatal
            log.warning("artifact %s undeserializable (recompiling): %s",
                        key_hash, e)
            return None


def export_pipeline(build, spec) -> tuple:
    """Trace `build()`'s jitted pipeline over `spec` and serialize it.

    The export goes through a FLAT-LEAF wrapper: jax.export cannot
    serialize int-keyed dict pytrees (the pipelines' env arg), so the
    exported module takes ``tree_leaves(spec)`` positionally and
    reassembles the original tree inside — wrap_exported applies the
    mirror flattening at call time.  Tracing runs HERE (the worker owns
    the builder closures); only the XLA compile ships to the server."""
    import jax
    from jax import export
    fn = build()
    flat_spec, in_tree = jax.tree_util.tree_flatten(spec)

    def _flat(*leaves):
        return fn(*jax.tree_util.tree_unflatten(in_tree, leaves))

    exp = export.export(jax.jit(_flat))(*flat_spec)
    return exp, exp.serialize()


def wrap_exported(exp):
    """A pipeline-callable view of an Exported: same ``fn(*args)``
    convention as the jitted builders, flat-leaf calling inside.  The
    module's XLA compile happens on first call and rides the shared AOT
    cache (the compile server already populated it), and the original
    Python body is never traced here — the zero-local-traces property
    the second-worker regression pins."""
    import jax
    call = exp.call

    def fn(*args):
        return call(*jax.tree_util.tree_leaves(args))

    fn._fabric_exported = True
    return fn


def reset_for_tests():
    with _LOCK:
        _CLIENTS.clear()
