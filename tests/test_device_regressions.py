"""Device-engine regressions: host and tpu engines must agree.

Each case was a reproduced divergence (code review round 1): empty global
aggregate, NULL-vs--1 group key collision, first_row NULL preservation."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database devreg")
    tk.must_exec("use devreg")
    tk.must_exec("create table t (a bigint, b bigint)")
    tk.must_exec("insert into t values (-1, 1), (null, 2), (5, 3)")
    tk.must_exec("create table t2 (g bigint, b bigint)")
    tk.must_exec("insert into t2 values (1, null), (1, 7)")
    return tk


def both_engines(tk, sql):
    tk.must_exec("set tidb_executor_engine = 'host'")
    host = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    tpu = tk.must_query(sql).rows
    tk.must_exec("set tidb_executor_engine = 'auto'")
    assert host == tpu, f"\nhost: {host}\ntpu:  {tpu}"
    return host


def test_empty_global_agg(tk):
    rows = both_engines(
        tk, "select count(*), sum(b), min(b) from t where a > 100")
    assert rows == [("0", None, None)]


def test_null_key_not_merged_with_minus_one(tk):
    rows = both_engines(
        tk, "select a, count(*) from t group by a order by a is null, a")
    assert rows == [("-1", "1"), ("5", "1"), (None, "1")]


def test_first_row_keeps_null(tk):
    rows = both_engines(tk, "select g, b from t2 group by g")
    assert rows == [("1", None)]


def test_min_max_with_nulls_and_negatives(tk):
    rows = both_engines(
        tk, "select a, min(b), max(b), avg(b) from t group by a "
            "order by a is null, a")
    assert rows == [("-1", "1", "1", "1.0000"),
                    ("5", "3", "3", "3.0000"),
                    (None, "2", "2", "2.0000")]


class TestCountDistinctDevice:
    """COUNT(DISTINCT) on the device kernel: value-runs per group in a
    value-extended sort (ops/device.py cnt_dist), with collation-aware
    parity against the host engine (which dedups _ci strings by sort
    key — 'abc' and 'ABC' are ONE distinct value, MySQL semantics)."""

    @pytest.fixture()
    def dtk(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table cdt (g bigint, v bigint, "
                     "sv varchar(8) collate utf8mb4_general_ci)")
        vals = ",".join(
            f"({i % 4}, {(i * 7) % 23}, "
            f"'{'AbC' if i % 3 else 'aBc'}{i % 5}')" for i in range(3000))
        tk.must_exec(f"insert into cdt values {vals}")
        tk.must_exec("insert into cdt values (1, null, null)")
        return tk

    def _parity(self, tk, sql):
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tk.must_query(sql).rows
        tk.must_exec("set tidb_executor_engine = 'tpu'")
        dev = tk.must_query(sql).rows
        tk.must_exec("set tidb_executor_engine = 'auto'")
        assert host == dev, (host[:4], dev[:4])
        return host

    def test_int_count_distinct(self, dtk):
        rows = self._parity(dtk, "select g, count(distinct v), count(v) "
                                 "from cdt group by g order by g")
        assert len(rows) == 4

    def test_ci_string_count_distinct(self, dtk):
        rows = self._parity(dtk, "select g, count(distinct sv) from cdt "
                                 "group by g order by g")
        # 5 suffixes; AbC/aBc collate equal under _ci → 5 distinct
        assert all(r[1] == "5" for r in rows), rows

    def test_global_count_distinct(self, dtk):
        self._parity(dtk, "select count(distinct v), count(distinct sv), "
                          "count(*) from cdt")

    def test_nulls_excluded(self, dtk):
        rows = self._parity(dtk, "select count(distinct v) from cdt "
                                 "where g = 1")
        assert rows  # the injected NULL row never counts

    def test_null_group_key_with_garbage_data(self, dtk):
        """Rows in a NULL-keyed group carry arbitrary underlying data
        (join gathers clip to real rows); the group sort must mask the
        key under the null flag or distinct runs splinter (review r4)."""
        tk = dtk
        tk.must_exec("create table ng (k bigint, v bigint)")
        vals = ",".join(
            (f"(null, {i % 6})" if i % 2 else f"({i % 3}, {i % 6})")
            for i in range(2000))
        tk.must_exec(f"insert into ng values {vals}")
        self._parity(tk, "select k, count(distinct v), count(*) from ng "
                         "group by k order by k")


def test_engine_hint_survives_nested_subquery_eval():
    """Advisor r4 (medium): a correlated/EXISTS subquery executed
    mid-statement goes through Session.run_query -> build_executor, which
    resets the statement-scoped READ_FROM_STORAGE pin on the shared
    session; the outer statement's pin must be restored so fragments built
    after the first subquery evaluation still honor the hint."""
    from tidb_tpu.testkit import TestKit
    tk = TestKit()
    tk.must_exec("create table eh (a int, b int)")
    tk.must_exec("insert into eh values (1, 10), (2, 20)")
    sess = tk.session
    sess.stmt_engine_hint = "host"  # outer statement's pin
    from tidb_tpu.parser import parse_one
    stmt = parse_one("select min(a) from eh")
    rows, _fts = sess._expr_ctx.eval_subquery(stmt)
    assert rows
    assert sess.stmt_engine_hint == "host"
    # and the built-plan path (uncorrelated subquery reuse)
    plan = sess.plan_query(parse_one("select max(a) from eh"))
    sess.stmt_engine_hint = "host"
    rows, _fts = sess._expr_ctx.eval_built_plan(plan)
    assert rows
    assert sess.stmt_engine_hint == "host"
