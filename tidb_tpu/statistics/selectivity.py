"""Predicate selectivity estimation over ANALYZE statistics
(reference: statistics/selectivity.go Selectivity + histogram.go
BetweenRowCount/EqualRowCount).

Estimates use, in order of preference: exact TopN counts for equality,
equal-depth histogram mass for ranges (linear interpolation inside a
bucket), and NDV/default fallbacks. Conjuncts multiply with a floor —
the reference's independence assumption."""

from __future__ import annotations

import numpy as np

from ..expression.core import Column as ExprColumn, Constant, ScalarFunc

#: fallback selectivity for predicates we cannot decompose
#: (reference: planner/core/stats.go selectionFactor = 0.8)
DEFAULT_SEL = 0.8
EQ_DEFAULT_SEL = 0.01
RANGE_DEFAULT_SEL = 0.33
FLOOR = 1e-7


def _cs(stats, col_id):
    return (stats or {}).get("columns", {}).get(str(col_id))


def _const_key(v):
    if isinstance(v, (bytes, bytearray)):
        return v.decode("utf-8", "surrogateescape")
    if isinstance(v, bool):
        return int(v)
    return v


def _col_const(cond):
    """cmp(col, const) / cmp(const, col) → (col, const_value, op) with the
    comparison normalized to column-on-the-left; None when not that shape."""
    if not isinstance(cond, ScalarFunc) or len(cond.args) != 2:
        return None
    a, b = cond.args
    flip = {"lt": "gt", "gt": "lt", "le": "ge", "ge": "le"}
    if isinstance(a, ExprColumn) and isinstance(b, Constant):
        return a, b.value, cond.op
    if isinstance(b, ExprColumn) and isinstance(a, Constant):
        return b, a.value, flip.get(cond.op, cond.op)
    return None


def _eq_sel(cs, n, v):
    """Selectivity of col = v: exact TopN count, then CMSketch point
    estimate, then uniform NDV fallback (reference: histogram.go
    EqualRowCount over TopN+CMSketch)."""
    key = _const_key(v)
    topn = cs.get("topn") or []
    topn_cnt = 0
    for tv, tc in topn:
        topn_cnt += tc
        if tv == key:
            return tc / n
    cm = cs.get("cmsketch")
    if cm is not None:
        from .analyze import cm_query
        est = cm_query(cm, key)
        if est > 0:
            return min(est, n) / n
        # sketch says absent: fall through to the NDV average (an absent
        # value may still appear post-ANALYZE; never estimate zero)
    ndv = max(cs.get("ndv", 0), 1)
    rest = max(n - topn_cnt - cs.get("null_count", 0), 0)
    rest_ndv = max(ndv - len(topn), 1)
    return max(rest / rest_ndv, 0.0) / n


def _range_mass(cs, n, v, op):
    """Fraction of rows with col OP v from the histogram (cum counts with
    linear interpolation inside the containing bucket)."""
    hist = cs.get("hist")
    if hist is None:
        lo, hi = cs.get("min"), cs.get("max")
        if lo is None or hi is None or not isinstance(v, (int, float)):
            return RANGE_DEFAULT_SEL
        if hi <= lo:
            span = 1.0
        else:
            span = (float(v) - lo) / (hi - lo)
        frac_lt = min(max(span, 0.0), 1.0)
        return frac_lt if op in ("lt", "le") else 1.0 - frac_lt
    bounds = np.asarray(hist["bounds"], dtype=np.float64)
    cum = np.asarray(hist["cum"], dtype=np.float64)
    total = cum[-1] if len(cum) else 1.0
    if total <= 0:
        return 0.0
    x = float(v)
    i = int(np.searchsorted(bounds, x, side="left"))
    if i >= len(bounds):
        frac_le = 1.0
    else:
        hi_cum = cum[i]
        lo_cum = cum[i - 1] if i > 0 else 0.0
        lo_b = bounds[i - 1] if i > 0 else cs.get("min", bounds[0])
        hi_b = bounds[i]
        if hi_b <= lo_b:
            within = 1.0
        else:
            within = min(max((x - lo_b) / (hi_b - lo_b), 0.0), 1.0)
        frac_le = (lo_cum + within * (hi_cum - lo_cum)) / total
    if op in ("lt", "le"):
        return frac_le
    return 1.0 - frac_le


def cond_selectivity(stats, col_infos, cond):
    """Selectivity of one predicate over a DataSource's schema."""
    n = max((stats or {}).get("row_count", 0), 1)
    if isinstance(cond, ScalarFunc) and cond.op == "and":
        return (cond_selectivity(stats, col_infos, cond.args[0])
                * cond_selectivity(stats, col_infos, cond.args[1]))
    if isinstance(cond, ScalarFunc) and cond.op == "or":
        s = (cond_selectivity(stats, col_infos, cond.args[0])
             + cond_selectivity(stats, col_infos, cond.args[1]))
        return min(s, 1.0)
    if isinstance(cond, ScalarFunc) and cond.op == "in_set":
        t = cond.args[0]
        if isinstance(t, ExprColumn) and t.idx < len(col_infos):
            cs = _cs(stats, col_infos[t.idx].id)
            values = cond.extra[0] if cond.extra else []
            if cs:
                return min(sum(_eq_sel(cs, n, v) for v in values), 1.0)
            return min(EQ_DEFAULT_SEL * max(len(values), 1), 1.0)
        return DEFAULT_SEL
    cc = _col_const(cond)
    if cc is None:
        return DEFAULT_SEL
    col, v, op = cc
    if v is None:
        return 0.0 if op != "ne" else 1.0
    if col.idx >= len(col_infos):
        return DEFAULT_SEL
    cs = _cs(stats, col_infos[col.idx].id)
    if cs is None:
        return (EQ_DEFAULT_SEL if op == "eq"
                else RANGE_DEFAULT_SEL if op in ("lt", "le", "gt", "ge")
                else DEFAULT_SEL)
    null_frac = cs.get("null_count", 0) / n
    if op == "eq":
        return _eq_sel(cs, n, v)
    if op == "ne":
        return max(1.0 - _eq_sel(cs, n, v) - null_frac, 0.0)
    if op in ("lt", "le", "gt", "ge"):
        if not isinstance(v, (int, float)):
            return RANGE_DEFAULT_SEL
        return max(_range_mass(cs, n, v, op) - (
            null_frac if op in ("gt", "ge") else 0.0), 0.0)
    return DEFAULT_SEL


def estimate_selectivity(stats, col_infos, conds) -> float:
    """Combined selectivity of a conjunction of predicates."""
    sel = 1.0
    for c in conds:
        sel *= cond_selectivity(stats, col_infos, c)
    return max(min(sel, 1.0), FLOOR)
