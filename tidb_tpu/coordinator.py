"""Cluster coordination — the single-host analog of PD + etcd (reference:
the placement driver's TSO service `tidb-server/main.go:74` pd.Client,
etcd leader election `owner/manager.go:48,94`, the server registry
`domain/infosync/`, and the GC safepoint store `store/gcworker`).

The reference splits these roles across external services because its
nodes are separate processes; here the cluster is one process, so the
roles collapse into one in-memory, thread-safe coordinator with the SAME
API shape the distributed version needs:

- **TSO** — strictly monotonic timestamp allocation with batched leases
  (PD hands out ranges so callers don't round-trip per ts; the in-process
  version keeps that shape so a future cross-process client is a drop-in).
- **Election** — named leader campaigns with TTL leases and resignation
  (owner.Manager: DDL owner, stats owner, GC leader all campaign on keys).
- **Registry** — live server/topology records with TTL heartbeats
  (infosync's etcd registration backing CLUSTER_* memtables).
- **Safepoints** — monotonic named watermarks (service safepoints: GC,
  BR, CDC each hold one; the minimum governs collection).
- **Watch** — key-prefix watchers with event callbacks (the etcd watch
  primitive schema-version broadcast rides on, ddl/util).

Domain wires one Coordinator per store; the DDL owner loop, stats
worker, and GC worker act through it rather than ad-hoc locks, which is
exactly the seam a multi-process deployment would re-implement over
gRPC.
"""

from __future__ import annotations

import logging
import threading
import time

from .utils import failpoint

_log = logging.getLogger("tidb_tpu.coordinator")


class Lease:
    """A granted TTL lease; expired leases lose their role silently
    (the holder discovers on renew — same contract as an etcd lease)."""

    __slots__ = ("key", "holder", "deadline", "ttl_s")

    def __init__(self, key, holder, ttl_s):
        self.key = key
        self.holder = holder
        self.ttl_s = ttl_s
        self.deadline = time.monotonic() + ttl_s

    def alive(self) -> bool:
        return time.monotonic() < self.deadline


class Coordinator:
    def __init__(self, tso_batch: int = 4096):
        self._mu = threading.RLock()
        # TSO: high-water + leased ceiling (PD batches allocations)
        self._ts = int(time.time() * 1000) << 18
        self._ts_ceiling = self._ts
        self._tso_batch = tso_batch
        self._leaders: dict[str, Lease] = {}
        self._registry: dict[str, tuple[dict, Lease]] = {}
        self._safepoints: dict[str, int] = {}
        self._watchers: dict[str, list] = {}
        self._kv: dict[str, object] = {}

    # -- TSO (pd.Client.GetTS) --------------------------------------------

    def tso(self) -> int:
        """One strictly-monotonic timestamp."""
        with self._mu:
            # chaos hook: a PD-restart-style clock jump. Only FORWARD skew
            # is modeled — TSO stays strictly monotonic by contract, and
            # consumers must survive arbitrary gaps between grants
            skew = failpoint.inject("coordinator-tso-skew")
            if isinstance(skew, int) and skew > 0:
                self._ts += skew
                self._ts_ceiling = max(self._ts_ceiling, self._ts)
            if self._ts >= self._ts_ceiling:
                # lease a fresh range anchored to wall time so timestamps
                # stay roughly physical (PD's physical<<18 | logical form)
                phys = int(time.time() * 1000) << 18
                self._ts = max(self._ts, phys)
                self._ts_ceiling = self._ts + self._tso_batch
            self._ts += 1
            return self._ts

    def tso_range(self, n: int) -> tuple[int, int]:
        """[lo, hi) batch for a client-side allocator."""
        with self._mu:
            lo = self.tso()
            self._ts += n - 1
            self._ts_ceiling = max(self._ts_ceiling, self._ts)
            return lo, self._ts + 1

    # -- leader election (owner/manager.go campaign/resign) ----------------

    def campaign(self, key: str, holder: str, ttl_s: float = 45.0) -> bool:
        """Try to become leader for `key`; holders renew by re-campaigning
        before the lease lapses (renewal extends; a live foreign lease
        rejects)."""
        with self._mu:
            # chaos hooks: losing a campaign / an etcd lease lapsing out
            # from under its holder (owner/manager.go watches for both)
            if failpoint.inject("coordinator-campaign-loss"):
                _log.warning("campaign lost (injected): key=%s holder=%s",
                             key, holder)
                return False
            cur = self._leaders.get(key)
            if cur is not None and failpoint.inject("coordinator-lease-expire"):
                _log.warning("lease expired (injected): key=%s holder=%s",
                             key, cur.holder)
                cur.deadline = time.monotonic() - 1
            if cur is not None and cur.alive() and cur.holder != holder:
                return False
            self._leaders[key] = Lease(key, holder, ttl_s)
            if cur is None or cur.holder != holder or not cur.alive():
                self._notify(f"leader/{key}", holder)
            return True

    def leader(self, key: str):
        with self._mu:
            cur = self._leaders.get(key)
            return cur.holder if cur is not None and cur.alive() else None

    def resign(self, key: str, holder: str) -> bool:
        with self._mu:
            cur = self._leaders.get(key)
            if cur is None or cur.holder != holder:
                return False
            del self._leaders[key]
            self._notify(f"leader/{key}", None)
            return True

    # -- server registry (domain/infosync) ---------------------------------

    def register_server(self, server_id: str, info: dict,
                        ttl_s: float = 60.0):
        with self._mu:
            self._registry[server_id] = (dict(info),
                                         Lease(server_id, server_id, ttl_s))
            self._notify(f"server/{server_id}", info)

    def heartbeat(self, server_id: str) -> bool:
        with self._mu:
            if failpoint.inject("coordinator-heartbeat-lost"):
                _log.warning("heartbeat lost (injected): server=%s",
                             server_id)
                return False
            ent = self._registry.get(server_id)
            if ent is None:
                return False
            ent[1].deadline = time.monotonic() + ent[1].ttl_s
            return True

    def servers(self) -> dict:
        with self._mu:
            return {sid: dict(info) for sid, (info, lease)
                    in self._registry.items() if lease.alive()}

    def unregister_server(self, server_id: str):
        with self._mu:
            self._registry.pop(server_id, None)
            self._notify(f"server/{server_id}", None)

    # -- service safepoints (gc_worker safepoint upload) -------------------

    def set_safepoint(self, service: str, ts: int) -> int:
        """Advance `service`'s safepoint (never moves backward); returns
        the GLOBAL safepoint = min over services — the watermark GC may
        collect below (reference: PD service safepoints; BR/CDC pin one
        so backups never lose versions mid-flight)."""
        with self._mu:
            cur = self._safepoints.get(service, 0)
            self._safepoints[service] = max(cur, int(ts))
            return self.global_safepoint()

    def global_safepoint(self) -> int:
        with self._mu:
            return min(self._safepoints.values(), default=0)

    def clear_safepoint(self, service: str):
        """Drop a service's pin (a finished BR/CDC task releases its
        hold so GC can advance past it)."""
        with self._mu:
            self._safepoints.pop(service, None)

    def min_pin_excluding(self, service: str):
        """The lowest safepoint held by OTHER services, or None — the
        ceiling `service` may advance to without invalidating them."""
        with self._mu:
            vals = [v for k, v in self._safepoints.items() if k != service]
            return min(vals) if vals else None

    def safepoints(self) -> dict:
        with self._mu:
            return dict(self._safepoints)

    # -- kv + watch (the etcd get/put/watch triple) ------------------------

    def put(self, key: str, value):
        with self._mu:
            self._kv[key] = value
            self._notify(key, value)

    def get(self, key: str, default=None):
        with self._mu:
            return self._kv.get(key, default)

    def watch(self, prefix: str, fn):
        """fn(key, value) fires on every put/notify under `prefix`
        (value None = deletion/resignation). Returns an unsubscribe
        callable."""
        with self._mu:
            self._watchers.setdefault(prefix, []).append(fn)

        def cancel():
            with self._mu:
                lst = self._watchers.get(prefix, [])
                if fn in lst:
                    lst.remove(fn)
        return cancel

    def _notify(self, key: str, value):
        for prefix, fns in list(self._watchers.items()):
            if key.startswith(prefix):
                for fn in list(fns):
                    try:
                        fn(key, value)
                    except Exception as e:
                        # a broken watcher must not poison the bus — but a
                        # silently vanishing lease/election event was how
                        # failures disappeared entirely (satellite fix):
                        # classify and log so the slow log / operator sees
                        from .utils.backoff import classify
                        _log.warning(
                            "watcher failed (%s): key=%s err=%s",
                            classify(e), key, e)
