"""Correlated subqueries, quantified comparisons, and CTE edge cases
(code-review round 2 regressions)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("create database subq")
    tk.must_exec("use subq")
    tk.must_exec("create table t1 (a bigint, k bigint)")
    tk.must_exec("create table t2 (b decimal(10,2), k bigint)")
    tk.must_exec("insert into t1 values (1, 1), (2, 1), (3, 2)")
    tk.must_exec("insert into t2 values (1.00, 1), (2.50, 1), (3.00, 2)")
    tk.must_exec("create table emp (id bigint, dept bigint, sal bigint)")
    tk.must_exec("insert into emp values (1,10,100),(2,10,200),(3,20,50)")
    return tk


def test_correlated_in_decimal_vs_int(tk):
    # scaled-decimal internals must unify with the int target (1 = 1.00)
    r = tk.must_query(
        "select a from t1 where a in (select b from t2 where t2.k = t1.k) "
        "order by a")
    r.check([("1",), ("3",)])


def test_correlated_any_all(tk):
    r = tk.must_query(
        "select a from t1 where a > any (select b from t2 where t2.k = t1.k) "
        "order by a")
    r.check([("2",)])
    r = tk.must_query(
        "select a from t1 where a >= all (select b from t2 where t2.k = t1.k) "
        "order by a")
    r.check([("3",)])


def test_correlated_in_agg_select_list(tk):
    # outer ref inside the subquery's aggregated SELECT list / HAVING
    r = tk.must_query(
        "select id from emp e where exists (select count(*) from t1 "
        "having count(*) > e.dept - 10) order by id")
    r.check([("1",), ("2",)])  # count=3 > 0 for dept 10; 3 > 10 false for 20
    r = tk.must_query(
        "select (select max(b) + t1.a from t2) from t1 where a = 1")
    r.check([("4.00",)])


def test_recursive_cte_supported(tk):
    # round-1 rejected these; they now evaluate by fixpoint
    # (tests/test_recursive_cte.py covers the full matrix)
    tk.must_query(
        "with recursive r as (select 1 as n union all "
        "select n + 1 from r where n < 3) select * from r order by n"
    ).check([("1",), ("2",), ("3",)])


def test_cte_column_count_mismatch(tk):
    e = tk.exec_error("with c (x, y) as (select 1) select x from c")
    assert "different column counts" in str(e)


def test_with_in_derived_table(tk):
    r = tk.must_query(
        "select * from (with x as (select 1 as a) select * from x) d")
    r.check([("1",)])


def test_uncorrelated_still_works(tk):
    r = tk.must_query(
        "select a from t1 where a in (select b from t2) order by a")
    r.check([("1",), ("3",)])
    r = tk.must_query(
        "select a from t1 where exists (select * from t2 where b > 2.9) "
        "order by a")
    r.check([("1",), ("2",), ("3",)])
