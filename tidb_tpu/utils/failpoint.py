"""Failpoint-style fault injection (reference: pingcap/failpoint, used in
103 reference files; kv/fault_injection.go).

Production code calls ``inject("name")`` at interesting points; tests
activate behaviors with ``enable`` — or, better, the ``enabled`` context
manager, which cannot leak an active failpoint past the test:

    failpoint.enable("commit-after-prewrite", "panic")     # raise
    failpoint.enable("backfill-batch", "sleep(0.05)")
    failpoint.enable("scan-rows", "return(7)")
    failpoint.enable("device-upload-oom", "2*oom")
    failpoint.enable("device-admission", "admission-queue-full")
    failpoint.enable("device-admission", "2*admission-wait(0.05)")
    with failpoint.enabled("txn-before-commit", "2*panic"):
        ...

Disabled failpoints cost one dict lookup. ``inject`` returns the
``return(...)`` payload (or None), raises FailpointError for ``panic``
and InjectedOOMError for ``oom`` / ``N*oom`` (a synthetic device
RESOURCE_EXHAUSTED that utils/backoff.classify labels ``device`` and
is_device_oom recognizes — NOT a FailpointError, which would classify
``fault`` and skip the OOM-recovery ladder)."""

from __future__ import annotations

import contextlib
import re
import threading
import time


class FailpointError(Exception):
    """Raised by an enabled `panic` failpoint."""


class InjectedAdmissionError(Exception):
    """Raised by an enabled ``admission-queue-full`` failpoint: a
    synthetic scheduler refusal.  The admission layer
    (executor/scheduler.py) converts it into the real classified
    DeviceAdmissionError so the injected refusal walks the genuine
    degrade-to-host ladder.  Deliberately NOT a FailpointError: that
    would classify ``fault`` instead of ``admission``."""


class InjectedCompileError(Exception):
    """Raised by an enabled ``compile-fail`` / ``N*compile-fail``
    failpoint: a synthetic remote-compile failure (the dead-tunnel
    "Connection refused" mode from BENCH_TPU_LIVE.json, at the COMPILE
    boundary instead of the dispatch boundary).  The compile service
    (executor/compile_service.py) retries it on the ``compileRetry``
    backoff curve, then charges the compile-scoped circuit breaker and
    degrades the fragment to the host engine.  Deliberately NOT a
    FailpointError: that would classify ``fault`` instead of ``compile``
    and skip the retry/breaker ladder this failpoint exists to test."""


class InjectedSpillError(Exception):
    """Raised by an enabled ``spill-fail`` / ``N*spill-fail`` failpoint:
    a synthetic host-columnar-page spill failure (disk full / IO error
    while the hybrid hash join writes an overflow partition,
    executor/hybrid_join.py via storage/paged.SpillSet).  classify labels
    it ``fault`` so run_device records it against the join breaker and
    degrades the fragment to the host engine — and the chaos invariant
    is that the abort leaks NO spilled pages (spill_outstanding() drains
    to zero) and no residency-ledger bytes.  Deliberately NOT a
    FailpointError subclass so tests can assert the spill path
    specifically fired."""


class InjectedOOMError(Exception):
    """Raised by an enabled ``oom`` / ``N*oom`` failpoint: a synthetic
    device out-of-memory whose MESSAGE mimics jaxlib's XlaRuntimeError
    RESOURCE_EXHAUSTED phrasing, so the error taxonomy
    (utils/backoff.classify → ``device``, is_device_oom → True) treats it
    exactly like a real HBM exhaustion.  Deliberately NOT a subclass of
    FailpointError: that would classify ``fault`` and bypass the
    evict-all → retry → degrade ladder this failpoint exists to test."""


def _oom_message(name: str) -> str:
    return ("RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 "
            f"bytes (injected by failpoint {name})")


_lock = threading.Lock()
_active: dict[str, str] = {}
_hits: dict[str, int] = {}


def enable(name: str, action: str):
    with _lock:
        _active[name] = action
        _hits[name] = 0


def disable(name: str):
    with _lock:
        _active.pop(name, None)


def disable_all():
    with _lock:
        _active.clear()


@contextlib.contextmanager
def enabled(name: str, action: str):
    """Scoped activation: the failpoint is disabled on exit even when the
    body raises, so tests can't leak active failpoints into each other."""
    enable(name, action)
    try:
        yield
    finally:
        disable(name)


def list_active() -> dict[str, str]:
    """Snapshot of the currently enabled failpoints (name -> action)."""
    with _lock:
        return dict(_active)


def hits(name: str) -> int:
    with _lock:
        return _hits.get(name, 0)


def inject(name: str):
    # read + count under the SAME lock acquisition: the old lock-free
    # probe could tear against a concurrent disable() and count a hit
    # for a failpoint that no longer exists (satellite: utils/failpoint
    # race); the uncontended-lock cost is ~100ns, fine for fault points
    with _lock:
        action = _active.get(name)
        if action is None:
            return None
        _hits[name] = _hits.get(name, 0) + 1
        hit = _hits[name]
    if action == "panic":
        raise FailpointError(f"failpoint {name} triggered")
    if action == "oom":
        raise InjectedOOMError(_oom_message(name))
    m = re.fullmatch(r"(\d+)\*oom", action)
    if m:  # N*oom: synthetic device OOM for the first N hits, then no-op
        #   — models transient HBM pressure the evict+retry ladder absorbs
        if hit <= int(m.group(1)):
            raise InjectedOOMError(_oom_message(name))
        return None
    if action == "spill-fail":
        raise InjectedSpillError(
            f"spill write failed (injected by failpoint {name})")
    m = re.fullmatch(r"(\d+)\*spill-fail", action)
    if m:  # N*spill-fail: fail the first N partition spills, then
        #   succeed — models a transient disk hiccup mid-spill
        if hit <= int(m.group(1)):
            raise InjectedSpillError(
                f"spill write failed (injected by failpoint {name})")
        return None
    if action == "compile-fail":
        raise InjectedCompileError(
            "Connection refused: remote compile service unreachable "
            f"(injected by failpoint {name})")
    m = re.fullmatch(r"(\d+)\*compile-fail", action)
    if m:  # N*compile-fail: fail the first N compiles, then succeed —
        #   models a flaky remote-compile tunnel the retry curve absorbs
        if hit <= int(m.group(1)):
            raise InjectedCompileError(
                "Connection refused: remote compile service unreachable "
                f"(injected by failpoint {name})")
        return None
    m = re.fullmatch(r"(?:(\d+)\*)?compile-slow\(([\d.]+)\)", action)
    if m:  # [N*]compile-slow(s): stall the first N compiles (all when N
        #   omitted) — models a slow remote compile; under
        #   tidb_compile_timeout the supervisor abandons it like a hang
        if m.group(1) is None or hit <= int(m.group(1)):
            time.sleep(float(m.group(2)))
        return None
    if action == "admission-queue-full":
        raise InjectedAdmissionError(
            f"admission queue full (injected by failpoint {name})")
    m = re.fullmatch(r"(?:(\d+)\*)?admission-wait\(([\d.]+)\)", action)
    if m:  # [N*]admission-wait(s): stall admission for the first N hits
        #   (all hits when N omitted) — models a contended queue; the
        #   scheduler counts the stall into sched_admission_waits_ms
        if m.group(1) is None or hit <= int(m.group(1)):
            time.sleep(float(m.group(2)))
        return None
    m = re.fullmatch(r"sleep\(([\d.]+)\)", action)
    if m:
        time.sleep(float(m.group(1)))
        return None
    m = re.fullmatch(r"return\((.*)\)", action)
    if m:
        raw = m.group(1)
        try:
            return int(raw)
        except ValueError:
            return raw.strip("'\"")
    m = re.fullmatch(r"(\d+)\*panic", action)
    if m:  # N*panic: raise for the first N hits, then no-op
        if hit <= int(m.group(1)):
            raise FailpointError(f"failpoint {name} triggered")
        return None
    m = re.fullmatch(r"(\d+)\*sleep\(([\d.]+)\)", action)
    if m:  # N*sleep(s): stall the first N hits (hang injection), then
        #   no-op — lets a schedule hang ONE dispatch and run clean after
        if hit <= int(m.group(1)):
            time.sleep(float(m.group(2)))
        return None
    m = re.fullmatch(r"(\d+)\*return\((.*)\)", action)
    if m:  # N*return(v): payload for the first N hits, then no-op
        if hit <= int(m.group(1)):
            raw = m.group(2)
            try:
                return int(raw)
            except ValueError:
                return raw.strip("'\"")
        return None
    raise ValueError(f"unknown failpoint action {action!r}")
