"""Project lint engine (tidb_tpu/lint): synthetic-source fixtures per
rule (positive + negative + allowlisted), allowlist/baseline round-trip,
and the tier-1 full-repo run — CI fails on any new unallowlisted finding.
"""

import ast
import json
import subprocess
import sys

import pytest

import tidb_tpu.lint.rules  # noqa: F401 — populate the registry
from tidb_tpu.lint import (Allowlist, Context, RULES, run_repo, run_rules,
                           write_baseline)
from tidb_tpu.lint.engine import SourceFile


def make_ctx(files: dict, aux: dict | None = None) -> Context:
    """In-memory fixture tree: rel-path -> source text."""
    fs = [SourceFile(rel, rel, text, ast.parse(text))
          for rel, text in files.items()]
    fs += [SourceFile(rel, rel, text, ast.parse(text), aux=True)
           for rel, text in (aux or {}).items()]
    return Context(fs)


def run_one(rule: str, files: dict, aux: dict | None = None):
    return RULES[rule].run(make_ctx(files, aux))


# -- engine: allowlist + baseline ---------------------------------------------

class TestAllowlist:
    def test_reason_required(self, tmp_path):
        p = tmp_path / "al.txt"
        p.write_text("some-rule pat:* \n")
        with pytest.raises(ValueError):
            Allowlist.load(str(p))
        p.write_text("some-rule pat:* -- \n")
        with pytest.raises(ValueError):
            Allowlist.load(str(p))

    def test_match_suppresses_and_stale_reported(self, tmp_path):
        files = {"a.py": "try:\n    pass\nexcept Exception:\n    pass\n"}
        p = tmp_path / "al.txt"
        p.write_text(
            "exception-swallow a.py:swallow@* -- fixture reason\n"
            "exception-swallow never.py:* -- stale entry\n")
        al = Allowlist.load(str(p))
        report = run_rules(make_ctx(files), al,
                           rules=["exception-swallow"])
        assert not report.findings
        assert len(report.allowlisted) == 1
        assert report.allowlisted[0][1].reason == "fixture reason"
        assert len(report.stale) == 1
        assert not report.ok  # stale entries fail the run

    def test_stale_only_for_rules_that_ran(self, tmp_path):
        p = tmp_path / "al.txt"
        p.write_text("lock-order x:* -- other rule's entry\n")
        al = Allowlist.load(str(p))
        report = run_rules(make_ctx({"a.py": "x = 1\n"}), al,
                           rules=["exception-swallow"])
        assert report.ok  # the lock-order entry is not stale-checked

    def test_baseline_round_trip(self, tmp_path):
        files = {
            "a.py": "try:\n    pass\nexcept Exception:\n    pass\n",
            "b.py": "try:\n    pass\nexcept:\n    pass\n",
        }
        p = tmp_path / "al.txt"
        report = run_rules(make_ctx(files), Allowlist(),
                           rules=["exception-swallow"])
        assert len(report.findings) == 2
        write_baseline(report, str(p))
        al = Allowlist.load(str(p))
        report2 = run_rules(make_ctx(files), al,
                            rules=["exception-swallow"])
        assert report2.ok
        assert len(report2.allowlisted) == 2

    def test_identity_is_line_independent(self):
        src1 = "def f():\n    try:\n        pass\n" \
               "    except Exception:\n        pass\n"
        src2 = "# moved\n\n\n" + src1
        (f1,) = run_one("exception-swallow", {"a.py": src1})
        (f2,) = run_one("exception-swallow", {"a.py": src2})
        assert f1.key == f2.key
        assert f1.line != f2.line


# -- exception-swallow --------------------------------------------------------

SWALLOW = """
import logging
log = logging.getLogger("x")

def swallowed():
    try:
        work()
    except Exception:
        pass

def bare():
    try:
        work()
    except:
        return 0

def reraised():
    try:
        work()
    except Exception:
        raise

def logged():
    try:
        work()
    except Exception as e:
        log.warning("failed: %%s", e)

def classified():
    try:
        work()
    except Exception as e:
        label = classify(e)

def handed_on():
    try:
        work()
    except Exception as e:
        job.fail(str(e))

def handed_on_kw():
    try:
        work()
    except Exception as e:
        job.fail(error=str(e))

def typed():
    try:
        work()
    except ValueError:
        pass
"""


class TestExceptionSwallow:
    def test_positive_negative(self):
        out = run_one("exception-swallow", {"m.py": SWALLOW})
        idents = {f.ident for f in out}
        assert idents == {"swallow@swallowed", "swallow@bare"}

    def test_multiple_handlers_disambiguated(self):
        src = ("def f():\n"
               "    try:\n        a()\n    except Exception:\n"
               "        pass\n"
               "    try:\n        b()\n    except Exception:\n"
               "        pass\n")
        out = run_one("exception-swallow", {"m.py": src})
        assert {f.ident for f in out} == {"swallow@f", "swallow@f#1"}


# -- lock rules ---------------------------------------------------------------

CYCLE = """
import threading
_A = threading.Lock()
_B = threading.Lock()

def one():
    with _A:
        with _B:
            pass

def two():
    with _B:
        with _A:
            pass
"""

NO_CYCLE = """
import threading
_A = threading.Lock()
_B = threading.Lock()

def one():
    with _A:
        with _B:
            pass

def two():
    with _A:
        with _B:
            pass
"""

SELF_DEADLOCK = """
import threading
_A = threading.Lock()
_R = threading.RLock()

def bad():
    with _A:
        with _A:
            pass

def fine():
    with _R:
        with _R:
            pass
"""

CROSS_CALL_CYCLE = """
import threading
_A = threading.Lock()
_B = threading.Lock()

def takes_b():
    with _B:
        helper()

def helper():
    with _A:
        pass

def takes_a():
    with _A:
        with _B:
            pass
"""


class TestLockOrder:
    def test_cycle_detected(self):
        out = run_one("lock-order", {"m.py": CYCLE})
        assert len(out) == 1
        assert out[0].ident.startswith("cycle:")
        assert "m._A" in out[0].ident and "m._B" in out[0].ident

    def test_consistent_order_clean(self):
        assert run_one("lock-order", {"m.py": NO_CYCLE}) == []

    def test_self_deadlock_plain_lock_only(self):
        out = run_one("lock-order", {"m.py": SELF_DEADLOCK})
        assert [f.ident for f in out] == ["self-deadlock:m._A"]

    def test_cycle_through_call_graph(self):
        out = run_one("lock-order", {"m.py": CROSS_CALL_CYCLE})
        assert len(out) == 1 and out[0].ident.startswith("cycle:")

    def test_multi_item_with_orders(self):
        src = ("import threading\n"
               "_A = threading.Lock()\n_B = threading.Lock()\n"
               "def one():\n    with _A, _B:\n        pass\n"
               "def two():\n    with _B:\n        with _A:\n"
               "            pass\n")
        out = run_one("lock-order", {"m.py": src})
        assert len(out) == 1 and out[0].ident.startswith("cycle:")

    def test_uninventoried_self_lock_not_guessed(self):
        # class A's lock comes from a helper (not inventoried); its
        # nested with must NOT bind to class B's same-named plain Lock
        src = ("import threading\n"
               "class A:\n"
               "    def __init__(self):\n"
               "        self._mu = make_rlock()\n"
               "    def reenter(self):\n"
               "        with self._mu:\n"
               "            with self._mu:\n"
               "                pass\n"
               "class B:\n"
               "    def __init__(self):\n"
               "        self._mu = threading.Lock()\n")
        assert run_one("lock-order", {"m.py": src}) == []


BLOCKING = """
import threading
import time
_LOCK = threading.Lock()

def bad():
    with _LOCK:
        time.sleep(0.1)

def bad2(fn):
    with _LOCK:
        call_supervised(fn)

def fine():
    with _LOCK:
        x = 1
    time.sleep(0.1)

class C:
    def __init__(self):
        self._mu = threading.Lock()

    def inst_lock_ok(self):
        with self._mu:
            time.sleep(0.1)  # instance lock: out of scope for this rule
"""


class TestBlockingWhileLocked:
    def test_positive_negative(self):
        out = run_one("blocking-while-locked", {"m.py": BLOCKING})
        assert {f.ident for f in out} == {
            "blocking:sleep@bad", "blocking:call_supervised@bad2"}


# -- traced-value hazard ------------------------------------------------------

TRACED = """
import jax
from functools import partial

def body(x, n):
    if n > 0:
        return x
    return x * 2

_k = observed_jit(body)

def shaped(x):
    if x.shape[0] > 4:
        return x
    return int(x.shape[0]) + len(x)

_k2 = observed_jit(shaped)

@partial(jax.jit, static_argnames=("cap",))
def bucketed(x, cap):
    if cap > 8:
        return x
    return x

@jax.jit
def concretizes(x):
    return int(x)

def plain(x):
    if x > 0:
        return 1
    return 0
"""


class TestTracedValueHazard:
    def test_findings(self):
        out = run_one("traced-value-hazard", {"m.py": TRACED})
        idents = {f.ident for f in out}
        # body branches on traced n; concretizes int()s its arg; the
        # shape-derived branch, static_argnames branch and the un-jitted
        # plain() are all clean
        assert idents == {"branch@body", "concretize-int@concretizes"}

    def test_range_and_iteration(self):
        src = ("import jax\n"
               "@jax.jit\n"
               "def f(n, xs):\n"
               "    for i in range(n):\n"
               "        pass\n"
               "    for v in xs:\n"
               "        pass\n")
        out = run_one("traced-value-hazard", {"m.py": src})
        assert {f.ident for f in out} == {"iterate@f", "iterate@f#1"}


# -- taxonomy -----------------------------------------------------------------

ERRORS_OK = """
class ErrCode:
    BackoffExhausted = 9005
    DeviceHang = 9008

class BackoffExhaustedError(Exception):
    code = ErrCode.BackoffExhausted

class DeviceHangError(Exception):
    code = ErrCode.DeviceHang
"""

BACKOFF_OK = """
CLASS_HANG = "hang"
CLASS_OTHER = "other"

def classify(err):
    from ..errors import DeviceHangError
    if isinstance(err, DeviceHangError):
        return CLASS_HANG
    return CLASS_OTHER
"""


class TestTaxonomy:
    def test_clean(self):
        out = run_one("taxonomy-consistency",
                      {"errors.py": ERRORS_OK,
                       "utils/backoff.py": BACKOFF_OK})
        assert out == []

    def test_duplicate_engine_code(self):
        errors = ERRORS_OK + "\nclass OtherError(Exception):\n" \
            "    code = 9008\n"
        out = run_one("taxonomy-consistency",
                      {"errors.py": errors,
                       "utils/backoff.py": BACKOFF_OK})
        assert any(f.ident == "dup-code:9008" for f in out)

    def test_orphan_code(self):
        errors = ERRORS_OK.replace(
            "    DeviceHang = 9008",
            "    DeviceHang = 9008\n    Reserved = 9011")
        out = run_one("taxonomy-consistency",
                      {"errors.py": errors,
                       "utils/backoff.py": BACKOFF_OK})
        assert any(f.ident == "orphan-code:Reserved" for f in out)

    def test_dead_class_constant(self):
        backoff = BACKOFF_OK + '\nCLASS_GHOST = "ghost"\n'
        out = run_one("taxonomy-consistency",
                      {"errors.py": ERRORS_OK,
                       "utils/backoff.py": backoff})
        assert any(f.ident == "dead-class:CLASS_GHOST" for f in out)

    def test_unclassified_device_error(self):
        errors = ERRORS_OK + "\nclass DeviceGhostError(Exception):\n" \
            "    code = 9013\n"
        out = run_one("taxonomy-consistency",
                      {"errors.py": errors,
                       "utils/backoff.py": BACKOFF_OK})
        assert any(f.ident == "unclassified:DeviceGhostError"
                   for f in out)


# -- failpoint coverage -------------------------------------------------------

HARNESS = """
READ_FAULTS = {"known-point": ["panic"]}
WRITE_FAULTS = {"txn-point": ["1*panic"]}
THREADED_FAULTS = {"threaded-point": ["sleep(0.01)"]}
"""

INJECTS = """
from .utils import failpoint

def covered():
    failpoint.inject("known-point")
    failpoint.inject("txn-point")
    failpoint.inject("threaded-point")

def uncovered():
    failpoint.inject("ghost-point")

def nonliteral(name):
    failpoint.inject(name)
"""


class TestFailpointCoverage:
    def test_positive_negative(self):
        out = run_one("failpoint-coverage", {"m.py": INJECTS},
                      aux={"tests/chaos_harness.py": HARNESS})
        idents = {f.ident for f in out}
        assert idents == {"uncataloged:ghost-point",
                          "inject-nonliteral@nonliteral"}

    def test_no_harness_no_coverage_check(self):
        out = run_one("failpoint-coverage", {"m.py": INJECTS})
        assert {f.ident for f in out} == {"inject-nonliteral@nonliteral"}


# -- gauge consistency --------------------------------------------------------

GAUGE_STATUS = """
def _status(self):
    from ..executor import widget
    return {"device_widget": widget.snapshot()}
"""

GAUGE_WIDGET = """
STATS = {"widget_hits": 0, "widget_lost": 0}

def snapshot():
    return {"widget_hits": STATS["widget_hits"]}

def report_gauges():
    return {"widget_hits": STATS["widget_hits"]}

def _publish_gauges():
    vals = {"widget_hits": STATS["widget_hits"],
            "widget_lost": STATS["widget_lost"]}
    for obs in []:
        for k, v in vals.items():
            obs.set_gauge(k, v)
"""

GAUGE_EXEC = """
from . import widget

class Exec:
    def execute(self):
        self.annotate(**widget.report_gauges())
"""


class TestGaugeConsistency:
    def test_unsurfaced_found_surfaced_clean(self):
        out = run_one("gauge-consistency",
                      {"server/http_status.py": GAUGE_STATUS,
                       "executor/widget.py": GAUGE_WIDGET,
                       "executor/exec_select.py": GAUGE_EXEC})
        idents = {f.ident for f in out}
        # widget_hits reaches /status via snapshot() and EXPLAIN via the
        # report_gauges splat; widget_lost reaches neither
        assert idents == {"unsurfaced-status:widget_lost",
                          "unsurfaced-explain:widget_lost"}

    def test_annotate_kwarg_counts_as_surfaced(self):
        exec_src = GAUGE_EXEC + (
            "\n\ndef annotate_direct(self, n):\n"
            "    self.annotate(widget_lost=n)\n")
        status = GAUGE_STATUS.replace(
            '"device_widget": widget.snapshot()',
            '"device_widget": widget.snapshot(), "widget_lost": 0')
        out = run_one("gauge-consistency",
                      {"server/http_status.py": status,
                       "executor/widget.py": GAUGE_WIDGET,
                       "executor/exec_select.py": exec_src})
        assert out == []

    # -- the ISSUE 18 fleet-inventory extension: snapshot()-fed fields
    # pinned on both the publishing module and the /metrics side

    FLEET_PERF_SRC = ('def stats():\n'
                      '    return {"perf_notes": 1, "perf_merged": 2}\n')
    FLEET_STATUS_OK = ('FLEET_KEYS = ("perf_notes", "perf_merged")\n')

    def test_fleet_inventory_both_sides_clean(self):
        assert run_one("gauge-consistency",
                       {"server/http_status.py": self.FLEET_STATUS_OK,
                        "fabric/perf.py": self.FLEET_PERF_SRC}) == []

    def test_fleet_inventory_missing_status_side(self):
        out = run_one("gauge-consistency",
                      {"server/http_status.py":
                       'FLEET_KEYS = ("perf_notes",)\n',
                       "fabric/perf.py": self.FLEET_PERF_SRC})
        assert ({f.ident for f in out}
                == {"fleet-inventory-status:perf_merged"}), out

    def test_fleet_inventory_missing_source_side(self):
        out = run_one("gauge-consistency",
                      {"server/http_status.py": self.FLEET_STATUS_OK,
                       "fabric/perf.py":
                       'def stats():\n    return {"perf_notes": 1}\n'})
        assert ({f.ident for f in out}
                == {"fleet-inventory-source:perf_merged"}), out


# -- trace-coverage -----------------------------------------------------------

TRACE_COV_BAD = """
from ..ops.device import DeviceUnsupported
from ..session import tracing

def run_device(ctx, fn):
    if bad():
        raise DeviceUnsupported("degraded silently")

def _run_device_admitted(ctx):
    raise DeviceUnsupported("also silent")

def helper_not_audited(ctx):
    raise DeviceUnsupported("feature gap — out of scope")
"""

TRACE_COV_OK = """
from ..ops.device import DeviceUnsupported
from ..session import tracing

def run_device(ctx, fn):
    with tracing.span("device.dispatch"):
        if bad():
            raise DeviceUnsupported("span-wrapped")

def _run_device_admitted(ctx):
    if bad():
        tracing.event("host_degraded", reason="breaker_open")
        raise DeviceUnsupported("event precedes the raise")
    raise OtherError("not a degradation exception")
"""

TRACE_COV_EVENT_AFTER = """
from ..ops.device import DeviceUnsupported
from ..session import tracing

def run_device(ctx):
    if bad():
        raise DeviceUnsupported("event comes too late")
    tracing.event("host_degraded", reason="x")
"""


class TestTraceCoverage:
    def test_unmarked_degradation_found(self):
        out = run_one("trace-coverage",
                      {"executor/device_exec.py": TRACE_COV_BAD})
        assert len(out) == 2, out  # audited fns only, helper exempt
        assert all(f.ident.startswith("degrade@") for f in out)

    def test_span_wrap_and_event_comply(self):
        assert run_one("trace-coverage",
                       {"executor/device_exec.py": TRACE_COV_OK}) == []

    def test_event_after_raise_does_not_count(self):
        out = run_one("trace-coverage",
                      {"executor/device_exec.py": TRACE_COV_EVENT_AFTER})
        assert len(out) == 1

    def test_unaudited_file_ignored(self):
        assert run_one("trace-coverage",
                       {"executor/rogue.py": TRACE_COV_BAD}) == []


# -- codec-rpc-trace ----------------------------------------------------------

CODEC_RPC_BAD = """
from . import codec

def call(sock, req):
    codec.write_frame(sock, req)
    return codec.read_frame(sock)
"""

CODEC_RPC_OK = """
from . import codec
from ..session import tracing

def call(sock, req):
    ctx = tracing.wire_ctx()
    if ctx is not None:
        req["trace"] = ctx
    codec.write_frame(sock, req)
    resp = codec.read_frame(sock)
    tracing.attach_remote(resp.pop("_trace", None))
    return resp

def serve(sock, coord):
    req = codec.read_frame(sock)
    rtr = tracing.begin_remote(req.pop("trace", None), "op")
    codec.write_frame(sock, {"ok": True})
    return rtr
"""


class TestCodecRpcTrace:
    def test_unpropagated_rpc_found(self):
        out = run_one("codec-rpc-trace",
                      {"fabric/widget_net.py": CODEC_RPC_BAD})
        assert len(out) == 1 and out[0].ident.startswith("rpc@"), out

    def test_client_and_server_forms_comply(self):
        assert run_one("codec-rpc-trace",
                       {"fabric/widget_net.py": CODEC_RPC_OK}) == []

    def test_codec_transport_and_non_fabric_exempt(self):
        assert run_one("codec-rpc-trace",
                       {"fabric/codec.py": CODEC_RPC_BAD,
                        "executor/widget.py": CODEC_RPC_BAD}) == []


# -- guard inference + guarded-state ------------------------------------------

# fixtures live at an AUDITED rel path (rules/guards.py AUDITED) so the
# state inventory picks them up
GPATH = "executor/scheduler.py"

GUARDED = """
import threading
_LOCK = threading.Lock()
_CACHE = {}

def locked_read(k):
    with _LOCK:
        return _CACHE.get(k)

def locked_write(k, v):
    with _LOCK:
        _CACHE[k] = v

def locked_len():
    with _LOCK:
        return len(_CACHE)

def rogue_read(k):
    return _CACHE.get(k)

def rogue_write(k, v):
    _CACHE[k] = v
"""

GUARDED_CLEAN = """
import threading
_LOCK = threading.Lock()
_CACHE = {}

def locked_read(k):
    with _LOCK:
        return _CACHE.get(k)

def locked_write(k, v):
    with _LOCK:
        _CACHE[k] = v
"""

PROPAGATED = """
import threading
_LOCK = threading.Lock()
_STATS = {"n": 0}

def outer():
    with _LOCK:
        _bump_locked()

def outer2():
    with _LOCK:
        _STATS["n"] += 1

def _bump_locked():
    _STATS["n"] += 1
"""

MULTILOCK = """
import threading
_A = threading.Lock()
_B = threading.Lock()
_STATE = {}

def both(k, v):
    with _A, _B:
        _STATE[k] = v

def a_only(k):
    with _A:
        return _STATE.get(k)

def rogue(k):
    return _STATE.get(k)
"""

LOCAL_AND_INIT = """
import threading
_LOCK = threading.Lock()

class Svc:
    def __init__(self):
        self._mu = threading.Lock()
        self.table = {}

    def put(self, k, v):
        with self._mu:
            self.table[k] = v

    def get(self, k):
        with self._mu:
            return self.table.get(k)

def local_only():
    table = {}
    table["k"] = 1
    return table
"""

NO_MAJORITY = """
import threading
_LOCK = threading.Lock()
_FREE = {}

def locked_once(k):
    with _LOCK:
        return _FREE.get(k)

def free1(k):
    return _FREE.get(k)

def free2(k, v):
    _FREE[k] = v
"""


class TestGuardedState:
    def test_majority_vote_flags_minority_sites(self):
        out = run_one("guarded-state", {GPATH: GUARDED})
        assert {f.ident for f in out} == {
            "unguarded:_CACHE@rogue_read", "unguarded:_CACHE@rogue_write"}
        msgs = {f.ident: f.msg for f in out}
        assert "read of" in msgs["unguarded:_CACHE@rogue_read"]
        assert "write to" in msgs["unguarded:_CACHE@rogue_write"]

    def test_call_propagated_guard_counts(self):
        # _bump_locked's write runs under _LOCK at every resolved call
        # site, so it is guarded — no findings
        assert run_one("guarded-state", {GPATH: PROPAGATED}) == []

    def test_multi_lock_with_scope(self):
        out = run_one("guarded-state", {GPATH: MULTILOCK})
        assert [f.ident for f in out] == ["unguarded:_STATE@rogue"]

    def test_local_state_and_init_writes_exempt(self):
        assert run_one("guarded-state", {GPATH: LOCAL_AND_INIT}) == []

    def test_no_inference_without_majority(self):
        assert run_one("guarded-state", {GPATH: NO_MAJORITY}) == []

    def test_unaudited_file_ignored(self):
        assert run_one("guarded-state",
                       {"executor/rogue_module.py": GUARDED}) == []

    def test_cross_module_access_votes(self):
        clearer = (
            "from . import scheduler\n"
            "def clear_all():\n"
            "    scheduler._CACHE.clear()\n")
        out = run_one("guarded-state",
                      {GPATH: GUARDED_CLEAN,
                       "executor/supervisor.py": clearer})
        assert [f.ident for f in out] == ["unguarded:_CACHE@clear_all"]


# -- check-then-act -----------------------------------------------------------

CTA_BUG = """
import threading
_LOCK = threading.Lock()
_JOBS = {}

def submit(key, job):
    with _LOCK:
        in_flight = key in _JOBS
    if in_flight:
        return None
    with _LOCK:
        _JOBS[key] = job
    return job
"""

CTA_FIXED = CTA_BUG.replace(
    "    with _LOCK:\n        _JOBS[key] = job\n",
    "    with _LOCK:\n        if key in _JOBS:\n"
    "            return None\n        _JOBS[key] = job\n")

CTA_SAME_HOLD = """
import threading
_LOCK = threading.Lock()
_JOBS = {}

def submit(key, job):
    with _LOCK:
        if key in _JOBS:
            return None
        _JOBS[key] = job
    return job

def drain(key):
    with _LOCK:
        return _JOBS.pop(key, None)
"""

CTA_UNGUARDED_ACT = """
import threading
_LOCK = threading.Lock()
_JOBS = {}

def anchor(key):
    with _LOCK:
        return _JOBS.get(key)

def anchor2(key, v):
    with _LOCK:
        _JOBS[key] = v

def submit(key, job):
    with _LOCK:
        have = key in _JOBS
    if not have:
        _JOBS[key] = job
"""

CTA_SIBLING_RECHECK = """
import threading
_LOCK = threading.Lock()
_FLAG = [False]
_GEN = [0]

def fence_clear():
    with _LOCK:
        if not _FLAG[0]:
            return
        gen = _GEN[0]
    reinit()
    with _LOCK:
        if _GEN[0] == gen:
            _FLAG[0] = False

def arm():
    with _LOCK:
        _FLAG[0] = True
        _GEN[0] += 1
"""


class TestCheckThenAct:
    def test_split_check_and_act_flagged(self):
        out = run_one("check-then-act", {GPATH: CTA_BUG})
        assert [f.ident for f in out] == ["check-then-act:_JOBS@submit"]

    def test_recheck_in_acting_hold_clean(self):
        assert run_one("check-then-act", {GPATH: CTA_FIXED}) == []

    def test_check_and_act_in_one_hold_clean(self):
        assert run_one("check-then-act", {GPATH: CTA_SAME_HOLD}) == []

    def test_unguarded_act_after_check_flagged(self):
        out = run_one("check-then-act", {GPATH: CTA_UNGUARDED_ACT})
        assert [f.ident for f in out] == ["check-then-act:_JOBS@submit"]
        assert "no lock held" in out[0].msg

    def test_sibling_state_recheck_suppresses(self):
        # the _maybe_reinit pattern: the acting hold re-validates a
        # generation counter guarded by the same lock
        assert run_one("check-then-act", {GPATH: CTA_SIBLING_RECHECK}) == []


# -- locked-suffix-contract ---------------------------------------------------

LSC = """
import threading
_LOCK = threading.Lock()

def _drain_locked():
    pass

def good():
    with _LOCK:
        _drain_locked()

def bad():
    _drain_locked()
"""

LSC_PROPAGATED = """
import threading
_LOCK = threading.Lock()

def outer():
    with _LOCK:
        _middle_locked()

def _middle_locked():
    _inner_locked()

def _inner_locked():
    pass
"""

LSC_ACQUIRES = """
import threading
_LOCK = threading.Lock()

def _grab_locked():
    with _LOCK:
        pass

def caller():
    with _LOCK:
        _grab_locked()
"""


class TestLockedSuffixContract:
    def test_unlocked_call_flagged(self):
        out = run_one("locked-suffix-contract", {GPATH: LSC})
        assert [f.ident for f in out] == ["unlocked-call:_drain_locked@bad"]

    def test_call_propagated_lock_satisfies_contract(self):
        assert run_one("locked-suffix-contract",
                       {GPATH: LSC_PROPAGATED}) == []

    def test_acquiring_own_guard_flagged(self):
        out = run_one("locked-suffix-contract", {GPATH: LSC_ACQUIRES})
        assert any(f.ident == "acquires-guard:_grab_locked" for f in out)


# -- sysvar-scope -------------------------------------------------------------

SVS_DUAL_OK = """
def attach(ctx):
    dom = getattr(ctx, "domain", None)
    if dom is not None:
        budget = int(dom.global_vars.get("tidb_device_mem_budget", 0))
    else:
        budget = int(ctx.get_sysvar("tidb_device_mem_budget"))
    return budget
"""

SVS_SESSION_READ = """
def attach(ctx):
    return int(ctx.get_sysvar("tidb_device_mem_budget"))
"""

SVS_GLOBAL_READ = """
def group_of(dom):
    return dom.global_vars.get("tidb_resource_group", "default")
"""

SVS_DISPATCHER = """
def refresh(ctx):
    dom = getattr(ctx, "domain", None)
    if dom is not None:
        gv = dom.global_vars
        src = lambda n, d: gv.get(n, d)
    else:
        src = lambda n, d: ctx.get_sysvar(n)
    depth = src("tidb_device_sched_queue_depth", 64)
    grp = src("tidb_resource_group", "default")
    return depth, grp
"""

SVS_UNDECLARED = """
def f(ctx):
    return ctx.get_sysvar("tidb_device_mystery_knob")
"""


class TestSysvarScope:
    def test_dual_path_fallback_clean(self):
        assert run_one("sysvar-scope", {"ops/residency.py": SVS_DUAL_OK}) \
            == []

    def test_session_read_of_process_knob_flagged(self):
        out = run_one("sysvar-scope", {"ops/residency.py": SVS_SESSION_READ})
        assert [f.ident for f in out] == [
            "session-read:tidb_device_mem_budget@attach"]

    def test_global_read_of_session_knob_flagged(self):
        out = run_one("sysvar-scope", {"m.py": SVS_GLOBAL_READ})
        assert [f.ident for f in out] == [
            "global-read:tidb_resource_group@group_of"]

    def test_dual_dispatcher_scopes(self):
        out = run_one("sysvar-scope", {"m.py": SVS_DISPATCHER})
        # the process knob through the dual dispatcher is the sanctioned
        # discipline; the session knob through it reads global-first
        assert [f.ident for f in out] == [
            "global-read:tidb_resource_group@refresh"]

    def test_undeclared_serving_knob_flagged(self):
        out = run_one("sysvar-scope", {"m.py": SVS_UNDECLARED})
        assert [f.ident for f in out] == [
            "undeclared:tidb_device_mystery_knob@f"]

    def test_defining_modules_exempt(self):
        assert run_one("sysvar-scope",
                       {"session/session.py": SVS_GLOBAL_READ}) == []


# -- migrated confinement rules ----------------------------------------------

class TestConfinementRules:
    def test_jit_confinement(self):
        src = "import jax\n\ndef f(fn):\n    return jax.jit(fn)\n"
        out = run_one("jit-confinement", {"executor/rogue.py": src})
        assert [f.ident for f in out] == ["jax.jit@f"]
        # the sanctioned compile layer is rule config, not a finding
        assert run_one("jit-confinement",
                       {"executor/compile_service.py": src}) == []

    def test_jit_aot_chain(self):
        src = "import jax\nk = jax.jit(f).lower(x).compile()\n"
        out = run_one("jit-confinement", {"m.py": src})
        idents = {f.ident for f in out}
        assert "jax.jit@<module>" in idents
        assert any(i.startswith("jit-aot-") for i in idents)

    def test_device_slot_confinement(self):
        src = ("def f(col):\n    col._device = thing\n"
               "\ndef g(col):\n    col._device = None\n")
        out = run_one("device-slot-confinement", {"m.py": src})
        assert {f.ident for f in out} == {"_device@f", "_device=None@g"}
        assert run_one("device-slot-confinement",
                       {"ops/residency.py": src}) == []
        # chunk.py may None-init the slot but not otherwise touch it
        out = run_one("device-slot-confinement", {"utils/chunk.py": src})
        assert {f.ident for f in out} == {"_device@f"}

    def test_supervised_confinement(self):
        src = "def f(fn):\n    return call_supervised(fn, deadline_s=1)\n"
        out = run_one("supervised-confinement", {"m.py": src})
        assert [f.ident for f in out] == ["call_supervised@f"]
        assert run_one("supervised-confinement",
                       {"executor/scheduler.py": src}) == []

    def test_confinement_not_allowlistable(self, tmp_path):
        """An allowlist line can never quietly neutralize an
        architectural gate: the finding stays AND the entry is stale."""
        src = "import jax\n\ndef f(fn):\n    return jax.jit(fn)\n"
        p = tmp_path / "al.txt"
        p.write_text("jit-confinement executor/rogue.py:* -- nope\n")
        report = run_rules(make_ctx({"executor/rogue.py": src}),
                           Allowlist.load(str(p)),
                           rules=["jit-confinement"])
        assert len(report.findings) == 1
        assert len(report.stale) == 1
        assert not report.ok

    def test_run_device_shape(self):
        src = ("def a(ctx, fn):\n    return run_device(ctx, fn)\n"
               "\ndef b(ctx, fn):\n"
               "    return run_device(ctx, fn, shape='join')\n"
               "\ndef c(ctx, fn):\n"
               "    return x._with_pipe_stats(run_device, ctx, fn)\n")
        out = run_one("run-device-shape", {"m.py": src})
        assert {f.ident for f in out} == {
            "run_device@a", "_with_pipe_stats@c"}

    def test_shared_memory_confinement(self):
        """Every way of reaching multiprocessing.shared_memory outside
        tidb_tpu/fabric/ is a finding; the fabric package itself is the
        sanctioned layer (rule config, like the other confinements)."""
        imp_from = ("from multiprocessing import shared_memory\n"
                    "def f():\n"
                    "    return shared_memory.SharedMemory(name='x')\n")
        imp_mod = ("import multiprocessing.shared_memory\n"
                   "def g():\n"
                   "    return multiprocessing.shared_memory\n")
        ctor = ("def h():\n    return SharedMemory(name='x', create=True)\n")
        out = run_one("shared-memory-confinement",
                      {"executor/rogue.py": imp_from})
        assert any(f.ident.startswith("shm-import@") for f in out)
        assert any(f.ident.startswith("shm-ctor@") for f in out)
        out = run_one("shared-memory-confinement", {"ops/x.py": imp_mod})
        assert any(f.ident.startswith("shm-import@") for f in out)
        assert any(f.ident.startswith("shm-attr@") for f in out)
        out = run_one("shared-memory-confinement", {"session/y.py": ctor})
        assert [f.ident for f in out] == ["shm-ctor@h"]
        # the fabric package is the sanctioned coordination layer
        assert run_one("shared-memory-confinement",
                       {"fabric/coord.py": imp_from + imp_mod + ctor}) \
            == []


# -- the tier-1 gate: full-repo run is clean ----------------------------------

class TestFullRepo:
    def test_repo_clean(self):
        report = run_repo()
        assert len(report.rules_run) >= 10
        assert not report.findings, report.human()
        assert not report.stale, report.human()
        # the burn-down inventory is real: allowlisted findings exist and
        # every entry carries a reason
        assert report.allowlisted
        assert all(e.reason for _f, e in report.allowlisted)

    def test_cli_json_exit_zero(self):
        import os
        import tidb_tpu
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(tidb_tpu.__file__)))
        proc = subprocess.run(
            [sys.executable, "-m", "tidb_tpu.lint", "--json"],
            capture_output=True, text=True, timeout=300, cwd=repo_root,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["ok"] is True
        assert payload["counts"]["findings"] == 0
        assert payload["counts"]["allowlisted"] > 0
        # per-rule timings ride the JSON report (--stats data source);
        # shared-model fixpoints get their own row so no rule is
        # mischarged for building them
        assert set(payload["timings_s"]) - {"shared-models"} \
            == set(payload["rules"])
        assert "shared-models" in payload["timings_s"]

    def test_race_rules_registered_and_clean(self):
        """The ISSUE-11 zero-findings gate: the four race rules are in
        the registry and the repo is clean under each — any new
        unguarded access / split critical section / contract breach /
        mis-scoped sysvar read fails tier-1 here."""
        from tidb_tpu.lint import run_rule
        for rule in ("guarded-state", "check-then-act",
                     "locked-suffix-contract", "sysvar-scope"):
            assert rule in RULES
            findings = run_rule(rule)
            assert findings == [], "\n".join(
                f"{f.rel}:{f.line}: {f.msg}" for f in findings)

    def test_guarded_state_allowlist_entries_all_carry_reasons(self):
        """Every deliberate lock-free access is inventoried: the repo
        HAS guarded-state allowlist entries (the documentation of every
        GIL-atomic fast path), each with a reason."""
        report = run_repo(rules=["guarded-state"])
        assert report.allowlisted, "expected documented lock-free sites"
        for _f, e in report.allowlisted:
            assert e.reason

    def test_runtime_budget(self):
        """The merge gate stays cheap: a fresh full-repo run (parse +
        every rule, shared-model fixpoints included) under 20s on CPU.
        Min of two runs: a transient load spike on the CI box must not
        fail the budget, a real 2x regression fails both."""
        import time
        from tidb_tpu.lint.engine import (Allowlist as AL, collect,
                                          default_allowlist_path,
                                          run_rules as rr)
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            ctx = collect()  # fresh Context: no cached analysis models
            rr(ctx, AL.load(default_allowlist_path()))
            best = min(best, time.perf_counter() - t0)
            if best < 20.0:
                break
        assert best < 20.0, f"full-repo lint took {best:.1f}s (budget 20s)"

    def test_cli_rule_and_path_filters(self):
        import os
        import tidb_tpu
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(tidb_tpu.__file__)))
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.run(
            [sys.executable, "-m", "tidb_tpu.lint", "--rule",
             "guarded-state", "--path", "executor/*", "--json"],
            capture_output=True, text=True, timeout=300, cwd=repo_root,
            env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["rules"] == ["guarded-state"]
        assert payload["counts"]["findings"] == 0
        # path-filtered: only executor/ allowlisted findings remain, and
        # the stale check is skipped (session/ entries would look stale)
        assert all(f["file"].startswith("executor/")
                   for f in payload["allowlisted"])
        assert payload["counts"]["stale_allowlist"] == 0
        # --stats renders the timing table on the human path
        proc = subprocess.run(
            [sys.executable, "-m", "tidb_tpu.lint", "--rule",
             "lock-order", "--stats"],
            capture_output=True, text=True, timeout=300, cwd=repo_root,
            env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "lock-order" in proc.stdout and "ms" in proc.stdout

    def test_path_filter_in_engine_skips_stale(self, tmp_path):
        files = {"a.py": "try:\n    pass\nexcept Exception:\n    pass\n",
                 "b/c.py": "try:\n    pass\nexcept Exception:\n    pass\n"}
        p = tmp_path / "al.txt"
        p.write_text("exception-swallow a.py:* -- fixture\n")
        al = Allowlist.load(str(p))
        report = run_rules(make_ctx(files), al,
                           rules=["exception-swallow"], paths=["b/*"])
        assert [f.rel for f in report.findings] == ["b/c.py"]
        assert report.stale == []  # a.py's entry is filtered, not stale
