"""MODIFY/CHANGE COLUMN, AUTO_RANDOM, information_schema breadth
(reference: ddl/column.go onModifyColumn, meta/autoid AUTO_RANDOM,
infoschema/tables.go)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


class TestModifyColumn:
    def test_widen_and_index_rebuild(self, tk):
        tk.must_exec("create table t (id int primary key, v int, key iv (v))")
        tk.must_exec("insert into t values (1, 100), (2, 200)")
        tk.must_exec("alter table t modify column v bigint")
        tk.must_query("select id from t where v = 200").check([("2",)])

    def test_type_class_conversions(self, tk):
        tk.must_exec("create table t (id int primary key, v int)")
        tk.must_exec("insert into t values (1, 100)")
        tk.must_exec("alter table t modify column v varchar(20)")
        tk.must_query("select concat(v, '!') from t").check([("100!",)])
        tk.must_exec("alter table t modify column v int")
        tk.must_query("select v + 1 from t").check([("101",)])
        tk.must_exec("alter table t modify column v decimal(10,2)")
        tk.must_query("select v from t").check([("100.00",)])

    def test_change_renames_and_retypes(self, tk):
        tk.must_exec("create table t (id int primary key, s varchar(5))")
        tk.must_exec("insert into t values (1, 'a')")
        tk.must_exec("alter table t change column s name varchar(30)")
        tk.must_query("select name from t").check([("a",)])
        e = tk.exec_error("select s from t")
        assert "Unknown column" in str(e)

    def test_rename_follows_into_indexes_and_fks(self, tk):
        """Regression: CHANGE COLUMN must update IndexColumn/FK names."""
        tk.must_exec("create table parent (id int primary key)")
        tk.must_exec("create table t (a int, b varchar(10), key ia (a), "
                     "foreign key (a) references parent (id))")
        tk.must_exec("insert into t values (1, 'x')")
        tk.must_exec("alter table t change column a a2 bigint")
        ddl = tk.must_query("show create table t").rows[0][1]
        assert "KEY `ia` (`a2`)" in ddl
        assert "FOREIGN KEY (`a2`)" in ddl
        # the covering-index guard now sees the renamed column
        e = tk.exec_error("alter table t drop column a2")
        assert "covered by index" in str(e)
        tk.must_query("select b from t where a2 = 1").check([("x",)])

    def test_not_null_reorg_rejects_existing_nulls(self, tk):
        tk.must_exec("create table t (a int)")
        tk.must_exec("insert into t values (null), (1)")
        e = tk.exec_error("alter table t modify column a int not null")
        assert "NULL" in str(e)
        # schema unchanged on failure
        tk.must_query("select count(*) from t where a is null").check(
            [("1",)])

    def test_guards(self, tk):
        tk.must_exec("create table t (id int primary key, v int)")
        e = tk.exec_error("alter table t modify column id varchar(10)")
        assert "integer" in str(e)
        tk.must_exec("create table p (a int, b int) "
                     "partition by hash (a) partitions 2")
        e = tk.exec_error("alter table p modify column a bigint")
        assert "partitioning" in str(e)

    def test_partitioned_data_reorg(self, tk):
        tk.must_exec("create table p (a int, b int) "
                     "partition by hash (a) partitions 2")
        tk.must_exec("insert into p values (1,10),(2,20),(3,30)")
        tk.must_exec("alter table p modify column b varchar(8)")
        tk.must_query("select b from p where a = 2").check([("20",)])
        tk.must_query("select count(*) from p").check([("3",)])


class TestAutoRandom:
    def test_shard_bits_and_increment(self, tk):
        tk.must_exec("create table ar (id bigint primary key auto_random(5), "
                     "v int)")
        tk.must_exec("insert into ar (v) values (1), (2), (3)")
        ids = sorted(int(r[0]) for r in tk.must_query(
            "select id from ar").rows)
        assert len(set(ids)) == 3 and all(i > 0 for i in ids)
        incr = sorted(i & ((1 << 58) - 1) for i in ids)
        assert incr == [1, 2, 3]
        ddl = tk.must_query("show create table ar").rows[0][1]
        assert "AUTO_RANDOM(5)" in ddl

    def test_requires_integer_primary_key(self, tk):
        e = tk.exec_error("create table bad (id int, v bigint auto_random)")
        assert "primary key" in str(e)

    def test_table_level_primary_key_accepted(self, tk):
        tk.must_exec("create table ar (id bigint auto_random(5), v int, "
                     "primary key (id))")
        tk.must_exec("insert into ar (v) values (1)")
        assert int(tk.must_query("select id from ar").rows[0][0]) > 0

    def test_explicit_value_rebases_increment_part(self, tk):
        tk.must_exec("create table ar (id bigint primary key auto_random, "
                     "v int)")
        tk.must_exec("insert into ar values (100, 1)")
        tk.must_exec("insert into ar (v) values (2)")
        ids = [int(r[0]) for r in tk.must_query(
            "select id from ar order by v").rows]
        assert (ids[1] & ((1 << 58) - 1)) >= 101


class TestInfoSchemaBreadth:
    def test_partitions_views_sequences(self, tk):
        tk.must_exec("create table p (a int) partition by range (a) "
                     "(partition p0 values less than (10), "
                     "partition p1 values less than maxvalue)")
        tk.must_exec("create view vv as select a from p")
        tk.must_exec("create sequence sq start with 3")
        tk.must_query(
            "select partition_name, partition_method from "
            "information_schema.partitions where table_name = 'p' "
            "order by partition_ordinal_position").check(
            [("p0", "RANGE"), ("p1", "RANGE")])
        tk.must_query("select table_name, view_definition from "
                      "information_schema.views").check(
            [("vv", "SELECT `a` FROM `p`")])
        tk.must_query("select sequence_name, start, cycle from "
                      "information_schema.sequences").check(
            [("sq", "3", "0")])
        tk.must_query("select table_type from information_schema.tables "
                      "where table_name = 'vv'").check([("VIEW",)])

    def test_constraints_tables(self, tk):
        tk.must_exec("create table parent (id int primary key)")
        tk.must_exec("create table c (a int, unique key ua (a), "
                     "constraint myfk foreign key (a) references "
                     "parent (id) on delete cascade)")
        got = {tuple(r) for r in tk.must_query(
            "select constraint_name, constraint_type from "
            "information_schema.table_constraints "
            "where table_name = 'c'").rows}
        assert ("ua", "UNIQUE") in got and ("myfk", "FOREIGN KEY") in got
        tk.must_query(
            "select constraint_name, referenced_table_name, delete_rule "
            "from information_schema.referential_constraints").check(
            [("myfk", "parent", "CASCADE")])

    def test_show_create_view_and_sequence_syntax(self, tk):
        tk.must_exec("create table t (a int)")
        tk.must_exec("create view vv as select a from t")
        tk.must_exec("create sequence sq")
        assert tk.must_query("show create view vv").rows
        assert tk.must_query("show create sequence sq").rows


class TestModifyColumnEdges:
    def test_not_null_accepts_absent_column_with_default(self, tk):
        tk.must_exec("create table t (a int primary key)")
        tk.must_exec("insert into t values (1)")
        tk.must_exec("alter table t add column b int default 5")
        tk.must_exec("alter table t modify column b int not null")
        tk.must_query("select b from t").check([("5",)])

    def test_modify_applies_new_default(self, tk):
        tk.must_exec("create table t (id int primary key, v int default 1)")
        tk.must_exec("alter table t modify column v int default 7")
        tk.must_exec("insert into t (id) values (1)")
        tk.must_query("select v from t").check([("7",)])

    def test_rename_updates_other_tables_fk_refs(self, tk):
        tk.must_exec("create table parent (id int primary key)")
        tk.must_exec("create table child (a int, "
                     "foreign key (a) references parent (id))")
        tk.must_exec("alter table parent change column id pid bigint")
        ddl = tk.must_query("show create table child").rows[0][1]
        assert "REFERENCES `parent` (`pid`)" in ddl

    def test_self_referencing_fk_rename(self, tk):
        tk.must_exec("create table t (id int primary key, pid int, "
                     "foreign key (pid) references t (id))")
        tk.must_exec("alter table t change column id tid bigint")
        ddl = tk.must_query("show create table t").rows[0][1]
        assert "REFERENCES `t` (`tid`)" in ddl

    def test_rename_never_touches_other_db_same_named_table(self, tk):
        tk.must_exec("create table parent (id int primary key)")
        tk.must_exec("create database otherdb2")
        tk.must_exec("use otherdb2")
        tk.must_exec("create table parent (id int primary key)")
        tk.must_exec("create table child (a int, "
                     "foreign key (a) references parent (id))")
        tk.must_exec("use test")
        tk.must_exec("alter table parent change column id pid bigint")
        ddl = tk.must_query("show create table otherdb2.child").rows[0][1]
        assert "REFERENCES `parent` (`id`)" in ddl
