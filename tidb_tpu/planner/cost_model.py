"""One cost currency for every physical decision, with calibrated
constants (reference: planner/core/find_best_task.go costs every
operator's alternatives in one unit; the constants live in sysvars like
tidb_opt_seek_factor / tidb_opt_cpu_factor and can be tuned without code
changes — sessionctx/variable/sysvar.go).

The unit is "one vectorized scanned row" (scan_row ≡ 1.0). Everything
else — KV seeks, hash-table builds, sort comparisons, device dispatch —
is expressed as multiples of it, measured on THIS machine by
``calibrate()``: a ~30ms micro-bench at server/bench startup whose
results land in the global sysvars, so EXPLAIN costs describe the
hardware actually running the query. Tests flip plans by SETting the
sysvars — never by editing constants.
"""

from __future__ import annotations

import time

import numpy as np

#: (sysvar name, default) — defaults match the hand-tuned r4 constants so
#: an uncalibrated process plans exactly as before
COST_VARS = (
    ("tidb_opt_scan_row_cost", 1.0),      # vectorized scan, per row
    ("tidb_opt_seek_cost", 8.0),          # KV point seek + decode, per key
    ("tidb_opt_seek_base", 30.0),         # per-access-path fixed seek cost
    ("tidb_opt_hash_build_cost", 2.0),    # hash-table insert, per build row
    ("tidb_opt_merge_sort_cost", 0.05),   # sort comparison, per row·log2
    ("tidb_opt_agg_row_cost", 2.0),       # host group-by, per input row
    ("tidb_opt_device_row_cost", 0.02),   # device pipeline, per row
    # default chosen so the UNCALIBRATED breakeven equals the historical
    # 65536-row auto-mode dispatch floor: 65536*(agg 2 + scan 1 - 0.02)
    ("tidb_opt_device_dispatch_cost", 195000.0),  # per fused dispatch
)


class CostModel:
    __slots__ = ("scan_row", "seek", "seek_base", "hash_build",
                 "merge_sort", "agg_row", "device_row", "device_dispatch")

    def __init__(self, scan_row, seek, seek_base, hash_build, merge_sort,
                 agg_row, device_row, device_dispatch):
        self.scan_row = scan_row
        self.seek = seek
        self.seek_base = seek_base
        self.hash_build = hash_build
        self.merge_sort = merge_sort
        self.agg_row = agg_row
        self.device_row = device_row
        self.device_dispatch = device_dispatch

    @classmethod
    def from_ctx(cls, ctx) -> "CostModel":
        vals = []
        for name, dflt in COST_VARS:
            v = dflt
            if ctx is not None:
                # planner exposes get_sysvar(name, scope); executors and
                # sessions expose get_sysvar(name) — accept both (a silent
                # fallback to defaults here would make the calibrated
                # sysvars dead knobs)
                try:
                    v = float(ctx.get_sysvar(name, "session"))
                except TypeError:
                    try:
                        v = float(ctx.get_sysvar(name))
                    except Exception:
                        v = dflt
                except Exception:
                    v = dflt
            vals.append(v)
        return cls(*vals)

    def device_breakeven_rows(self) -> int:
        """Input size where the fused device pipeline beats the host agg —
        auto engine mode's dispatch floor, DERIVED from the calibrated
        constants instead of a hard-coded row count."""
        gain = max(self.agg_row + self.scan_row - self.device_row, 1e-9)
        return int(self.device_dispatch / gain)


def calibrate(n: int = 1 << 18, seed: int = 0) -> dict:
    """Measure the host-side constants on this machine → {sysvar: value},
    normalized to scan_row = 1.0. Device constants are deliberately NOT
    measured here (a jit round trip at startup costs seconds over a
    tunnel); their defaults came from the r4 bench's measured dispatch
    overhead and can be overridden like any sysvar."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 1 << 40, n)
    keys = rng.integers(0, n // 4, n)

    def best_of(f, reps=3):
        t = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            t = min(t, time.perf_counter() - t0)
        return t

    scan_s = best_of(lambda: (data > (1 << 39)).sum())
    scan_row_ns = max(scan_s / n, 1e-12)

    # KV point seek analog: python dict lookup + int decode (the embedded
    # store's get path is a dict probe + version walk)
    d = {int(k): i for i, k in enumerate(keys[: 1 << 14])}
    probe = [int(k) for k in keys[: 1 << 14]]

    def seeks():
        s = 0
        for k in probe:
            s += d[k]
        return s

    seek_s = best_of(seeks)
    seek_ns = seek_s / len(probe)

    hash_s = best_of(lambda: np.unique(keys, return_inverse=True))
    hash_ns = hash_s / n

    sort_s = best_of(lambda: np.argsort(data, kind="stable"))
    sort_ns = sort_s / (n * np.log2(n))

    # host group-by row cost ~ factorize + scatter-add passes
    agg_s = best_of(lambda: np.bincount(
        np.clip(keys, 0, n // 4), weights=data.astype(np.float64)))
    agg_ns = hash_ns + agg_s / n

    unit = scan_row_ns
    return {
        "tidb_opt_scan_row_cost": 1.0,
        "tidb_opt_seek_cost": round(seek_ns / unit, 3),
        "tidb_opt_seek_base": round(30 * seek_ns / unit / 8, 3),
        "tidb_opt_hash_build_cost": round(hash_ns / unit, 3),
        "tidb_opt_merge_sort_cost": round(sort_ns / unit, 4),
        "tidb_opt_agg_row_cost": round(agg_ns / unit, 3),
        # device constants converted into the measured unit from assumed
        # wall times (dispatch ~3ms sync over a local PJRT path, device
        # row throughput ~20G rows/s) — a true measurement needs a jit
        # round trip this budget can't afford; override via the sysvars
        "tidb_opt_device_dispatch_cost": round(3e6 / (unit * 1e9), 0),
        "tidb_opt_device_row_cost": round(0.05 / (unit * 1e9), 4),
    }


def apply_calibration(domain, values: dict | None = None) -> dict:
    """Run (or take) a calibration and install it as GLOBAL sysvars —
    every session planning after this prices plans with the measured
    constants. Returns what was installed."""
    vals = values if values is not None else calibrate()
    for name, v in vals.items():
        domain.global_vars[name] = str(v)
    return vals
