"""Collation support (reference: util/collate/collate.go — binary,
utf8mb4_general_ci, utf8mb4_unicode_ci collators behind sort keys;
util/collate/unicode_ci_data.go weight tables).

Case-insensitive collations compare by a precomputed sort key. Two real
collators (not the round-2 upper-case shim):

* **general_ci** (utf8mb4_general_ci / utf8_general_ci): per-character
  weights with no expansions — each character weighs as the uppercased
  base letter of its canonical decomposition (MySQL's my_unicase "sort"
  field: Ä→A, é→E, Å→A), and a character whose uppercase expands keeps
  only the first unit (ß→S, so ß = s but ß ≠ ss — the documented
  general_ci behavior).
* **unicode_ci** (utf8mb4_unicode_ci, UCA 4.0 primary strength): full case
  folding WITH expansions (ß→ss), compatibility decomposition, and
  combining-mark stripping — so ß = ss, Å = A, ⅓ = 1⁄3-ish compat forms
  collapse, accents are ignored.

The weights derive from Python's unicodedata (Unicode character database)
rather than a copied table; the observable semantics match the reference
collators for the documented cases (see tests/test_collation.py).

The sort key transform is applied wherever string ordering/equality feeds
a kernel: comparisons, GROUP BY/DISTINCT keys, join keys, ORDER BY, window
partition/order keys. Device fragments consume _ci columns through
sort-key-class dictionary codes (utils/chunk.py dict_encode_ci +
ops/device.py to_device_col), so _ci GROUP BY/filter runs on-device."""

from __future__ import annotations

import unicodedata
from functools import lru_cache

import numpy as np


#: ONE registry for SHOW CHARACTER SET / SHOW COLLATION and the
#: information_schema memtables (reference: parser/charset/charset.go) —
#: (name, description, default collation, maxlen)
CHARSETS = (
    (b"utf8mb4", b"UTF-8 Unicode", b"utf8mb4_bin", 4),
    (b"gbk", b"Chinese Internal Code Specification", b"gbk_chinese_ci", 2),
    (b"binary", b"binary", b"binary", 1),
)

#: (collation, charset, id, is_default, is_compiled, sortlen)
COLLATIONS = (
    (b"utf8mb4_bin", b"utf8mb4", 46, b"Yes", b"Yes", 1),
    (b"utf8mb4_general_ci", b"utf8mb4", 45, b"", b"Yes", 1),
    (b"utf8mb4_unicode_ci", b"utf8mb4", 224, b"", b"Yes", 8),
    (b"gbk_chinese_ci", b"gbk", 28, b"Yes", b"Yes", 1),
    (b"gbk_bin", b"gbk", 87, b"", b"Yes", 1),
    (b"binary", b"binary", 63, b"Yes", b"Yes", 1),
)


def is_ci(collate: str | None) -> bool:
    return bool(collate) and collate.endswith("_ci")


def is_unicode_ci(collate: str | None) -> bool:
    return bool(collate) and collate.endswith("_unicode_ci")


def needs_ci(ftype) -> bool:
    from ..expression import phys_kind, K_STR
    return phys_kind(ftype) == K_STR and is_ci(ftype.collate)


@lru_cache(maxsize=None)
def _general_weight(ch: str) -> str:
    """One character's general_ci weight: uppercased base letter of the
    canonical decomposition; multi-unit uppercases keep the first unit
    (ß→S). Combining marks / caseless characters weigh as themselves."""
    d = unicodedata.normalize("NFD", ch)
    base = next((c for c in d if not unicodedata.combining(c)), ch)
    u = base.upper()
    return u[0] if u else base


def _general_key(s: str) -> str:
    return "".join(_general_weight(c) for c in s)


def _unicode_key(s: str) -> str:
    """UCA primary-strength approximation: case fold with expansions
    (ß→ss), compatibility-decompose, strip combining marks, uppercase."""
    s = unicodedata.normalize("NFKD", s.casefold())
    s = "".join(c for c in s if not unicodedata.combining(c))
    s = unicodedata.normalize("NFKD", s.upper())
    return "".join(c for c in s if not unicodedata.combining(c))


def is_gbk(collate: str | None) -> bool:
    return bool(collate) and collate.startswith("gbk")


def sort_key(b: bytes, collation: str | None = None) -> bytes:
    s = b.decode("utf-8", "replace")
    if is_gbk(collation):
        # gbk_chinese_ci: order by the GBK code of the UPPERCASED text
        # (reference: util/collate/gbk_chinese_ci.go — the weight table is
        # the GBK code point order, which sorts Hanzi roughly by pinyin;
        # case folds like the reference's gbkChineseCICollator).
        # gbk_bin reaches here through key_for_compare: GBK byte order,
        # no case fold (util/collate/gbk_bin.go).
        if collation.endswith("_ci"):
            s = s.upper()
        try:
            return s.encode("gbk")
        except UnicodeEncodeError:
            # GBK-unencodable characters (the reference errors at INSERT;
            # this engine stores utf8 regardless): escape each as
            # \xff\xff + utf8 bytes — \xff never starts a valid GBK
            # sequence, so escapes sort after all GBK text and DISTINCT
            # values stay distinct (a plain 'replace' collapsed them all
            # to '?')
            out = bytearray()
            for ch in s:
                try:
                    out += ch.encode("gbk")
                except UnicodeEncodeError:
                    out += b"\xff\xff" + ch.encode("utf-8")
            return bytes(out)
    key = _unicode_key(s) if is_unicode_ci(collation) else _general_key(s)
    return key.encode("utf-8")


def sort_key_array(data: np.ndarray, collation: str | None = None) -> np.ndarray:
    out = np.empty(len(data), dtype=object)
    for i, b in enumerate(data):
        out[i] = (sort_key(b, collation)
                  if isinstance(b, (bytes, bytearray)) else b)
    return out


def key_for_compare(data: np.ndarray, ftype) -> np.ndarray:
    """data unchanged for binary collations; sort keys for _ci — and for
    gbk_bin, whose BYTE order is the GBK encoding's, not utf8's."""
    from ..expression import phys_kind, K_STR
    if needs_ci(ftype) or (phys_kind(ftype) == K_STR
                           and ftype.collate == "gbk_bin"):
        return sort_key_array(data, ftype.collate)
    return data


def ci_collation(*ftypes) -> str | None:
    """The _ci collation governing a comparison, or None. Deterministic in
    argument ORDER (min over the operands' _ci collations): both sides of
    a join key must fold under the SAME collation or equal values would
    land in different sort-key spaces. (Reference: collation coercion —
    mixing incompatible collations is a MySQL error we don't model; we
    pick one canonically instead.)"""
    cis = [ft.collate for ft in ftypes
           if ft is not None and is_ci(ft.collate)]
    return min(cis) if cis else None
