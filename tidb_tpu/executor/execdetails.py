"""Per-operator runtime statistics for EXPLAIN ANALYZE (reference:
util/execdetails/execdetails.go RuntimeStatsColl + executor/explain.go).

Executors are wrapped at build time (executor/__init__ build_executor): each
`execute()` call records inclusive wall time and output rows keyed by the
plan node's identity; fused device paths additionally annotate which engine
ran the fragment and the compile-vs-execute split (the TPU analog of the
reference's cop-task execution info)."""

from __future__ import annotations

import time


class OpStats:
    __slots__ = ("rows", "time_s", "loops", "extra", "mem_bytes")

    def __init__(self):
        self.rows = 0
        self.time_s = 0.0
        self.loops = 0
        self.extra = {}
        self.mem_bytes = 0

    def exec_info(self) -> str:
        # loops == 0 means the operator never ran standalone (it was fused
        # into a parent device fragment) — show only the annotations
        parts = ([f"time:{_fmt_dur(self.time_s)}", f"loops:{self.loops}"]
                 if self.loops else [])
        for k, v in self.extra.items():
            parts.append(f"{k}:{v}")
        return ", ".join(parts)


def _fmt_dur(s: float) -> str:
    if s >= 1.0:
        return f"{s:.2f}s"
    if s >= 1e-3:
        return f"{s * 1e3:.1f}ms"
    return f"{s * 1e6:.0f}µs"


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n} B"


class RuntimeStatsColl:
    """plan-node-id -> OpStats (reference: execdetails.RuntimeStatsColl)."""

    def __init__(self):
        self._stats: dict[int, OpStats] = {}

    def get(self, plan) -> OpStats:
        st = self._stats.get(id(plan))
        if st is None:
            st = self._stats[id(plan)] = OpStats()
        return st

    def has(self, plan) -> bool:
        return id(plan) in self._stats

    def record(self, plan, rows: int, elapsed: float, mem_bytes: int = 0):
        st = self.get(plan)
        st.rows += rows
        st.time_s += elapsed
        st.loops += 1
        st.mem_bytes = max(st.mem_bytes, mem_bytes)

    def annotate(self, plan, **kv):
        self.get(plan).extra.update(
            {k: v for k, v in kv.items() if v is not None})


def timed_execute(exe, stats: RuntimeStatsColl):
    """Wrap an executor instance's execute() (and execute_stream(): the
    sort/topN consumers pull children chunk-at-a-time and would otherwise
    bypass the wrapper) to record inclusive wall time + output rows (TiDB's
    EXPLAIN ANALYZE `time` is likewise inclusive of children)."""
    inner = exe.execute
    inner_stream = exe.execute_stream

    def run():
        t0 = time.perf_counter()
        chunk = inner()
        el = time.perf_counter() - t0
        mem = chunk.mem_bytes() if hasattr(chunk, "mem_bytes") else 0
        stats.record(exe.plan, chunk.num_rows, el, mem)
        return chunk

    def run_stream(batch_rows):
        it = inner_stream(batch_rows)
        while True:
            t0 = time.perf_counter()
            try:
                chunk = next(it)
            except StopIteration:
                return
            el = time.perf_counter() - t0
            stats.record(exe.plan, chunk.num_rows, el)
            yield chunk

    # wrap the stream only for real streaming overrides: the base-class
    # execute_stream delegates to execute(), which is already the wrapped
    # run() — wrapping both would double-count rows/time/loops
    if "execute_stream" in type(exe).__dict__:
        exe.execute_stream = run_stream
    return run
