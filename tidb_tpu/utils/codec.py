"""Order-preserving (memcomparable) datum codec.

Byte-compatible in spirit with the reference's ``util/codec/codec.go``
(flags: NIL=0x00, BYTES=0x01, INT=0x03, UINT=0x04, FLOAT=0x05): encoded keys
compare byte-wise in the same order as their decoded values, which is what
makes range scans over the KV store work. Not wire-identical to the
reference (we are not speaking to a real TiKV), but the same design.
"""

from __future__ import annotations

import struct

NIL_FLAG = 0x00
BYTES_FLAG = 0x01
COMPACT_BYTES_FLAG = 0x02
INT_FLAG = 0x03
UINT_FLAG = 0x04
FLOAT_FLAG = 0x05
MAX_FLAG = 0xFA

_SIGN_MASK = 0x8000000000000000
_ENC_GROUP_SIZE = 8
_ENC_MARKER = 0xFF
_ENC_PAD = 0x00


def encode_int(buf: bytearray, v: int) -> None:
    """Sign-flipped big-endian int64 (reference: util/codec/number.go EncodeInt)."""
    buf.append(INT_FLAG)
    buf += struct.pack(">Q", (v & 0xFFFFFFFFFFFFFFFF) ^ _SIGN_MASK)


def encode_uint(buf: bytearray, v: int) -> None:
    buf.append(UINT_FLAG)
    buf += struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF)


def encode_float(buf: bytearray, f: float) -> None:
    """IEEE bits, sign-flip transform for total order (reference: util/codec/float.go)."""
    buf.append(FLOAT_FLAG)
    u = struct.unpack(">Q", struct.pack(">d", f))[0]
    if u & _SIGN_MASK:
        u = ~u & 0xFFFFFFFFFFFFFFFF
    else:
        u |= _SIGN_MASK
    buf += struct.pack(">Q", u)


def encode_bytes(buf: bytearray, data: bytes) -> None:
    """Group-of-8 escape encoding (reference: util/codec/bytes.go EncodeBytes):
    data is chopped into 8-byte groups, each padded with 0x00 and followed by
    a marker byte 0xFF - pad_count, preserving byte-wise order."""
    buf.append(BYTES_FLAG)
    i = 0
    n = len(data)
    while True:
        group = data[i:i + _ENC_GROUP_SIZE]
        pad = _ENC_GROUP_SIZE - len(group)
        buf += group
        buf += bytes([_ENC_PAD]) * pad
        buf.append(_ENC_MARKER - pad)
        i += _ENC_GROUP_SIZE
        if pad > 0 or i > n:
            break
        if i == n:
            # full group boundary: emit one more empty group so "abc" < "abc\x00"
            buf += bytes([_ENC_PAD]) * _ENC_GROUP_SIZE
            buf.append(_ENC_MARKER - _ENC_GROUP_SIZE)
            break


def encode_nil(buf: bytearray) -> None:
    buf.append(NIL_FLAG)


def encode_max(buf: bytearray) -> None:
    buf.append(MAX_FLAG)


def decode_one(data: bytes, pos: int):
    """Decode one datum at pos; returns (value, new_pos). NULL -> None."""
    flag = data[pos]
    pos += 1
    if flag == NIL_FLAG:
        return None, pos
    if flag == INT_FLAG:
        (u,) = struct.unpack(">Q", data[pos:pos + 8])
        v = u ^ _SIGN_MASK
        if v >= 1 << 63:
            v -= 1 << 64
        return v, pos + 8
    if flag == UINT_FLAG:
        (u,) = struct.unpack(">Q", data[pos:pos + 8])
        return u, pos + 8
    if flag == FLOAT_FLAG:
        (u,) = struct.unpack(">Q", data[pos:pos + 8])
        if u & _SIGN_MASK:
            u &= ~_SIGN_MASK & 0xFFFFFFFFFFFFFFFF
        else:
            u = ~u & 0xFFFFFFFFFFFFFFFF
        return struct.unpack(">d", struct.pack(">Q", u))[0], pos + 8
    if flag == BYTES_FLAG:
        out = bytearray()
        while True:
            group = data[pos:pos + _ENC_GROUP_SIZE]
            marker = data[pos + _ENC_GROUP_SIZE]
            pos += _ENC_GROUP_SIZE + 1
            pad = _ENC_MARKER - marker
            out += group[:_ENC_GROUP_SIZE - pad]
            if pad > 0:
                break
        return bytes(out), pos
    raise ValueError(f"unknown codec flag {flag:#x}")


def encode_key(values) -> bytes:
    """Encode a tuple of python values into one memcomparable key."""
    buf = bytearray()
    for v in values:
        if v is None:
            encode_nil(buf)
        elif isinstance(v, bool):
            encode_int(buf, int(v))
        elif isinstance(v, int):
            encode_int(buf, v)
        elif isinstance(v, float):
            encode_float(buf, v)
        elif isinstance(v, (bytes, bytearray)):
            encode_bytes(buf, bytes(v))
        elif isinstance(v, str):
            encode_bytes(buf, v.encode("utf-8"))
        else:
            raise TypeError(f"cannot encode key datum of type {type(v)}")
    return bytes(buf)


def decode_key(data: bytes):
    """Decode a memcomparable key back into a list of values."""
    out = []
    pos = 0
    while pos < len(data):
        v, pos = decode_one(data, pos)
        out.append(v)
    return out


# -- varint helpers (row values, non-memcomparable) -------------------------

def write_uvarint(buf: bytearray, v: int) -> None:
    while v >= 0x80:
        buf.append((v & 0x7F) | 0x80)
        v >>= 7
    buf.append(v)


def read_uvarint(data: bytes, pos: int):
    shift = 0
    v = 0
    while True:
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if b < 0x80:
            return v, pos
        shift += 7


def write_varint(buf: bytearray, v: int) -> None:
    # zigzag, arbitrary precision: v>=0 -> 2v, v<0 -> -2v-1 (the former
    # `(v << 1) ^ (v >> 63)` corrupted wide-decimal ints >= 2^63, where
    # the arithmetic shift is no longer a sign smear)
    write_uvarint(buf, (v << 1) if v >= 0 else ((-v) << 1) - 1)


def read_varint(data: bytes, pos: int):
    u, pos = read_uvarint(data, pos)
    return ((u >> 1) ^ -(u & 1)), pos
