"""Session + Domain (reference: session/session.go ExecuteStmt loop,
domain/domain.go per-process runtime singleton).

The Domain owns the store, the schema cache, the columnar cache and the DDL
executor; Sessions own variables, the current txn and the statement loop:
parse → plan → optimize → execute, with lazy autocommit transactions
(reference: session/txn.go LazyTxn)."""

from __future__ import annotations

import datetime as _dt
import json
import random as _random
import threading
import time

import numpy as np

from ..errors import (ErrCode, SchemaError, TiDBError, WriteConflictError)
from ..errors import SchemaChangedError as _SchemaChangedError
from ..infoschema import InfoSchema, build_infoschema
from ..meta import Meta
from ..model import DBInfo
from ..parser import Parser, ast, digest as sql_digest
from ..planner import PlanBuilder, optimize
from ..planner.logical import explain_tree
from ..sqltypes import (TYPE_LONGLONG, TYPE_VARCHAR, FieldType, format_value)
from ..utils.chunk import Chunk
from . import sysvars as sv
from . import tracing


class Domain:
    """reference: domain/domain.go — schema cache + background machinery."""

    def __init__(self, store):
        from ..storage import ColumnarCache
        from .observe import Observability
        self.store = store
        self.columnar_cache = ColumnarCache(store)
        self._schema_lock = threading.Lock()
        from ..utils.rwgate import RWGate
        self.schema_gate = RWGate()  # commits(shared) vs publication(excl)
        self._infoschema: InfoSchema | None = None
        self.global_vars: dict[str, str] = {}
        self.stats: dict[int, dict] = {}      # table_id -> stats blob
        self.stats_version = 0                # bumped per stats change
        #                                       (invalidates cached plans)
        self.ddl_lock = threading.RLock()     # single-owner DDL (owner role)
        self.observe = Observability()        # slow log + stmt summary + metrics
        # conn_id -> live session, weakly: embedded users who never close()
        # must not leak ghost processlist rows (the server path still calls
        # Session.close() for prompt removal)
        import weakref
        self.sessions = weakref.WeakValueDictionary()
        from ..ddl_worker import DDLWorker
        self.ddl_worker = DDLWorker(self)   # async online-DDL owner worker
        from ..privilege import PrivManager
        self.priv = PrivManager(self)       # grant-table cache (RBAC)
        from ..statistics.worker import StatsWorker
        self.stats_worker = StatsWorker(self)  # auto-analyze loop
        from ..kv.gcworker import GCWorker
        self.gc_worker = GCWorker(self)        # MVCC safepoint GC
        self.reload_schema()
        from ..bindinfo import BindHandle
        from ..coordinator import Coordinator
        self.coordinator = Coordinator()       # PD/etcd role (TSO, election,
        #                                        registry, safepoints, watch)
        # infinite TTL for the embedded single-process deployment: nothing
        # would heartbeat an idle embedded domain, and a registry that
        # forgets its only server after 60s idle is wrong there. Server
        # mode keeps liveness real: the stats worker loop heartbeats, so a
        # wedged process still ages out of a (future) shared registry.
        self.coordinator.register_server(
            "tidb-0", {"version": "8.0.11-tpu-htap", "status_port": 10080},
            ttl_s=float("inf"))
        self.bind_handle = BindHandle(self)    # global plan bindings
        self.capture_counts: dict[str, int] = {}  # baseline capture tally
        from ..plugin import PluginRegistry
        self.plugins = PluginRegistry(self)    # audit/auth plugin SPI
        from ..telemetry import Telemetry
        self.telemetry = Telemetry(self)       # local-only usage collector
        from ..topsql import TopSQL
        self.topsql = TopSQL(self)             # per-SQL CPU attribution
        # LOCK TABLES state (reference: ddl/table_lock.go, held in-memory
        # per domain): (db, table) -> {"mode": read|write, conn_id: mode}
        self.table_locks: dict[tuple, dict] = {}
        self.table_locks_mu = threading.Lock()
        # compile-service prewarm (executor/compile_service.py): globals
        # are in-memory only, so at Domain start the opt-in is the
        # TIDB_TPU_COMPILE_PREWARM env var — recipes survive Domain
        # churn, so a re-created embedded Domain starts its ladder warm;
        # the sysvar path kicks from SET GLOBAL tidb_compile_prewarm
        from ..executor import compile_service
        compile_service.maybe_prewarm_on_start(self)
        # durable-store hookups (kv/wal.py + kv/shared_store.py): the
        # WAL reads its fsync policy from GLOBAL scope through this
        # domain, and the schema LEASE window bounds how stale this
        # worker's infoschema may run behind the fleet's published
        # schema-version cell before a statement triggers a reload
        wal = getattr(self.store.mvcc, "wal", None)
        if wal is not None:
            gv = self.global_vars
            wal.policy_source = lambda: gv.get("tidb_wal_fsync", "commit")
        if hasattr(self.store.mvcc, "on_freshness_wait"):
            # every fleet ts acquisition lands in the freshness
            # histogram (p99 is the paper's measured consistency cost;
            # /metrics renders the buckets, bench_oltp reports it)
            obs = self.observe
            self.store.mvcc.on_freshness_wait = (
                lambda s: obs.observe_hist("freshness_wait_seconds", s))
        self._schema_lease_next = 0.0

    #: seconds an infoschema may serve past the fleet's published
    #: version before the lease check re-reads the cell (the
    #: reference's schema-lease staleness bound, scaled to the segment)
    SCHEMA_LEASE_S = 0.05

    def maybe_reload_schema(self, force: bool = False):
        """Fleet schema lease: when the coordination segment's
        schema-version cell is ahead of this worker's infoschema, catch
        up the log tail (the DDL's meta writes ride it) and reload.
        One attribute check when the store has no fleet cell; at most
        one cell read per SCHEMA_LEASE_S otherwise."""
        fleet_v = getattr(self.store.mvcc, "fleet_schema_version", None)
        if fleet_v is None:
            return
        now = time.monotonic()
        if not force and now < self._schema_lease_next:
            return
        self._schema_lease_next = now + self.SCHEMA_LEASE_S
        v = fleet_v()
        if v and v > self.infoschema().version:
            self.reload_schema()

    def reload_schema(self):
        """reference: domain.Reload — full load on version change. The
        exclusive gate drains in-flight [schema-check → commit] sections
        first, so a commit can never validate against the old schema and
        land after the new one publishes (rwgate.py)."""
        txn = self.store.begin()
        try:
            m = Meta(txn)
            infos = build_infoschema(m)
        finally:
            txn.rollback()
        with self.schema_gate.exclusive():
            with self._schema_lock:
                self._infoschema = infos

    def infoschema(self) -> InfoSchema:
        with self._schema_lock:
            return self._infoschema

    def load_stats(self):
        txn = self.store.begin()
        try:
            m = Meta(txn)
            for db in m.list_databases():
                for t in m.list_tables(db.id):
                    s = m.stats(t.id)
                    if s:
                        self.stats[t.id] = s
                        self.stats_version += 1
        finally:
            txn.rollback()


def _schema_names(plan):
    """Output column names for a plan's schema (anonymous → col_i)."""
    return [r.name or f"col_{i}" for i, r in enumerate(plan.schema.refs)]


class Result:
    """Query result: column names + the result chunk."""

    def __init__(self, names=None, chunk: Chunk | None = None, affected=0,
                 last_insert_id=0, warnings=None):
        self.names = names or []
        self.chunk = chunk
        self.affected = affected
        self.last_insert_id = last_insert_id
        self.warnings = warnings or []

    @property
    def internal_rows(self):
        return self.chunk.to_rows() if self.chunk is not None else []

    @property
    def rows(self):
        """Display rows (MySQL text protocol strings)."""
        return self.chunk.to_display_rows() if self.chunk is not None else []

    @property
    def ftypes(self):
        return [c.ftype for c in self.chunk.columns] if self.chunk is not None else []


class _TempSchema:
    """InfoSchema overlay: session temporary tables shadow same-named
    catalog tables (reference: infoschema TemporaryTableAttachedInfoSchema)."""

    def __init__(self, base: InfoSchema, temp: dict):
        self._base = base
        self._temp = temp

    def table_by_name(self, db, table):
        t = self._temp.get((db.lower(), table.lower()))
        if t is not None:
            return t
        return self._base.table_by_name(db, table)

    def has_table(self, db, table):
        if (db.lower(), table.lower()) in self._temp:
            return True
        return self._base.has_table(db, table)

    def table_by_id(self, tid):
        for (db, _name), t in self._temp.items():
            if t.id == tid:
                return (self._base.schema_by_name(db), t)
        return self._base.table_by_id(tid)

    def tables_in_schema(self, db):
        out = {t.name.lower(): t for t in self._base.tables_in_schema(db)}
        for (d, name), t in self._temp.items():
            if d == db.lower():
                out[name] = t
        return sorted(out.values(), key=lambda t: t.name)

    def __getattr__(self, name):
        return getattr(self._base, name)


class _ExprCtx:
    """Context handed to ExprBuilder (sysvars, subqueries, time)."""

    def __init__(self, session):
        self.session = session
        self.params = None

    def eval_subquery(self, select, limit_one=False, outer=None):
        # mid-statement nested execution: the inner build_executor resets
        # the statement-scoped READ_FROM_STORAGE pin on the (shared)
        # session, so restore the OUTER statement's pin afterwards —
        # fragments built after the first subquery evaluation must still
        # honor the outer hint
        saved = getattr(self.session, "stmt_engine_hint", None)
        try:
            res = self.session.run_query(select, outer=outer)
        finally:
            self.session.stmt_engine_hint = saved
        fts = res.ftypes
        rows = res.internal_rows
        if limit_one:
            rows = rows[:1]
        return rows, fts

    def eval_built_plan(self, plan, limit_one=False):
        """Execute an already-built logical plan (uncorrelated subquery
        whose analysis plan is reusable)."""
        saved = getattr(self.session, "stmt_engine_hint", None)
        try:
            res = self.session.run_built_query(plan)
        finally:
            self.session.stmt_engine_hint = saved
        rows = res.internal_rows
        if limit_one:
            rows = rows[:1]
        return rows, res.ftypes

    def analyze_subquery(self, select, scope):
        """Build (and discard) the subquery's logical plan with `scope` as
        the outer name-resolution scope; correlation is recorded in
        scope.used. Returns the plan (for output types)."""
        builder = PlanBuilder(self, outer=scope)
        return builder.build(select)

    def get_sysvar(self, name, scope):
        return self.session.get_sysvar(name, scope)

    def get_uservar(self, name):
        return self.session.user_vars.get(name)

    def set_uservar(self, name, value):
        self.session.user_vars[name] = value

    def current_db(self):
        return self.session.current_db()

    def current_user(self):
        return self.session.user

    def now(self):
        return _dt.datetime.now()

    # planner hooks
    def infoschema(self):
        return self.session.infoschema()

    def mem_table(self, db, name):
        from .memtables import mem_table
        return mem_table(self.session, db, name)

    def table_rows(self, table_id):
        s = self.session.domain.stats.get(table_id)
        if s:
            return s.get("row_count", 1000)
        entry = self.session.domain.columnar_cache._entries.get(table_id)
        if entry is not None:
            return max(entry.nrows, 1)
        return 1000

    def table_stats(self, table_id):
        """ANALYZE statistics blob for CBO (planner/access.py,
        join-reorder cardinality), or None before ANALYZE."""
        return self.session.domain.stats.get(table_id)


class Session:
    """reference: session.session — one connection's state."""

    _next_conn_id = [1]
    #: the wire server creates Sessions from per-connection threads, so
    #: the read-increment below must be atomic — an unguarded `x[0] += 1`
    #: lets two simultaneous handshakes mint the SAME id, colliding in
    #: server.connections and misrouting KILL
    _conn_id_lock = threading.Lock()
    #: fleet-unique conn ids (tidb_tpu/fabric): a fabric worker sets its
    #: slot base — ``(slot + 1) << CONN_SLOT_SHIFT`` — so two serving
    #: processes can NEVER mint the same id.  KILL, processlist and
    #: slow-log attribution all resolve by conn id; with a per-process
    #: counter alone, "KILL 7" on worker B could name worker A's session.
    _conn_id_base = [0]

    @classmethod
    def set_conn_id_base(cls, base: int):
        cls._conn_id_base[0] = int(base)

    def __init__(self, domain: Domain):
        self.domain = domain
        self.store = domain.store
        self._db = "test"
        self.session_vars: dict[str, str] = {}
        self.user_vars: dict[str, object] = {}
        self.txn = None            # explicit or statement txn
        self.explicit_txn = False
        self._stmt_as_of_ts = None  # statement-level AS OF TIMESTAMP
        self._txn_as_of_ts = None   # stale READ ONLY txn's historical ts
        self.killed = False  # KILL / max_execution_time watchdog flag
        self.kill_conn = False  # KILL CONNECTION: refuse further stmts
        self.txn_read_only = False  # START TRANSACTION READ ONLY
        self.txn_stmt_history = []  # DML asts for optimistic-commit retry
        self._in_txn_retry = False
        self.session_bindings: dict[str, dict] = {}  # SESSION plan bindings
        self.binding_used = None   # normalized sql of the last matched binding
        self.bindings_version = 0  # session-binding change counter
        from ..planner.plan_cache import SessionPlanCache
        self.plan_cache = SessionPlanCache()  # prepared-plan cache
        self.plan_builds = 0       # full plan builds (test observability)
        # session-local temporary tables: (db, name) -> TableInfo
        # (reference: table/temptable)
        self.temp_tables: dict[tuple, object] = {}
        self.temp_tables_version = 0  # bumped per create/drop (plan cache)
        self.seq_lastval: dict[int, int] = {}  # sequence id -> LASTVAL
        self.seq_cache: dict[int, tuple] = {}  # sequence id -> (next, left)
        self.user = "root@%"
        self.parser = Parser()
        self.last_insert_id = 0
        self.affected_rows = 0
        self.warnings: list[str] = []
        self.prepared: dict[str, str] = {}
        with Session._conn_id_lock:
            self.conn_id = (Session._conn_id_base[0]
                            + Session._next_conn_id[0])
            Session._next_conn_id[0] += 1
        self._expr_ctx = _ExprCtx(self)
        from ..ddl import DDLExecutor
        self.ddl = DDLExecutor(self)
        self.current_sql: str | None = None   # processlist info
        self.stmt_start = 0.0
        self.mem_tracker = None               # per-statement quota tracker
        self._internal = 0                    # >0: internal SQL, skip priv
        domain.sessions[self.conn_id] = self

    def close(self):
        """Drop the session from the domain registry (processlist) and
        clean up session-local temporary tables."""
        for key in list(self.temp_tables):
            try:
                self.drop_temp_table(key)
            except Exception:
                pass
        try:
            self.unlock_tables()
        except Exception:
            pass
        self.domain.sessions.pop(self.conn_id, None)

    def drop_temp_table(self, key):
        info = self.temp_tables.pop(key, None)
        self.temp_tables_version += 1
        if info is not None:
            self.ddl._delete_table_data(info)

    # -- LOCK TABLES (reference: ddl/table_lock.go + executor lock checks) --

    def lock_tables(self, items):
        """items: [(db, name, mode)]. All-or-nothing acquisition; an
        existing foreign WRITE lock (or a foreign READ when WRITE is
        wanted) rejects with 'Table is locked' (reference error 8020)."""
        dom = self.domain
        with dom.table_locks_mu:
            for db, name, mode in items:
                holders = dom.table_locks.get((db, name), {})
                for cid, m in holders.items():
                    if cid == self.conn_id:
                        continue
                    if m == "write" or mode == "write":
                        raise TiDBError(
                            f"Table '{name}' is locked by another session",
                            code=ErrCode.TableLocked)
            self._release_locks_locked()
            for db, name, mode in items:
                dom.table_locks.setdefault((db, name), {})[
                    self.conn_id] = mode

    def unlock_tables(self):
        with self.domain.table_locks_mu:
            self._release_locks_locked()

    def _release_locks_locked(self):
        dom = self.domain
        for key in list(dom.table_locks):
            dom.table_locks[key].pop(self.conn_id, None)
            if not dom.table_locks[key]:
                del dom.table_locks[key]

    def _held_locks(self):
        with self.domain.table_locks_mu:
            return {k: v[self.conn_id]
                    for k, v in self.domain.table_locks.items()
                    if self.conn_id in v}

    def check_table_locks(self, stmt):
        """Statement-level LOCK TABLES enforcement (reference:
        executor/adapter.go checkLockTables + MySQL semantics): a session
        holding locks may only touch locked tables (writes need WRITE);
        other sessions are blocked from WRITE-locked tables entirely and
        from writing READ-locked ones."""
        if not self.domain.table_locks:
            return
        from ..priv_check import _collect_tables
        # only the DML/DDL TARGET is a write; source tables of
        # INSERT...SELECT / subqueries are reads (MySQL semantics)
        write_keys = set()
        targets = []
        if isinstance(stmt, (ast.InsertStmt, ast.TruncateTableStmt)):
            targets = [stmt.table]
        elif isinstance(stmt, (ast.UpdateStmt, ast.DeleteStmt)):
            if isinstance(stmt.table, ast.TableName) and not getattr(
                    stmt, "targets", None):
                targets = [stmt.table]
            else:
                # multi-table form: resolve target aliases to base tables
                from ..priv_check import _alias_map
                amap = _alias_map(self, stmt.table)
                if isinstance(stmt, ast.DeleteStmt):
                    for tn in stmt.targets:
                        key = (tn.as_name or tn.name).lower()
                        if key in amap:
                            db, name = amap[key]
                            write_keys.add((db.lower(), name.lower()))
                else:
                    from ..priv_check import _update_targets
                    for db, name in _update_targets(self, stmt, amap):
                        write_keys.add((db.lower(), name.lower()))
        elif isinstance(stmt, ast.DropTableStmt):
            targets = list(stmt.tables)
        elif isinstance(stmt, (ast.AlterTableStmt, ast.CreateIndexStmt,
                               ast.DropIndexStmt)):
            targets = [stmt.table]
        elif isinstance(stmt, ast.RenameTableStmt):
            targets = [old for old, _new in stmt.pairs]
        for tn in targets:
            write_keys.add(((tn.schema or self.current_db()).lower(),
                            tn.name.lower()))
        tabs = []
        _collect_tables(stmt, tabs)
        held = self._held_locks()
        infos = self.infoschema()
        for tn in tabs:
            db = (tn.schema or self.current_db()).lower()
            name = tn.name.lower()
            if not db or not infos.has_table(db, tn.name):
                continue
            key = (db, name)
            write = key in write_keys
            with self.domain.table_locks_mu:
                holders = dict(self.domain.table_locks.get(key, {}))
            mine = holders.pop(self.conn_id, None)
            foreign_write = any(m == "write" for m in holders.values())
            foreign_read = bool(holders)
            if foreign_write or (write and foreign_read):
                raise TiDBError(f"Table '{tn.name}' is locked by another "
                                "session", code=ErrCode.TableLocked)
            if held:
                if mine is None:
                    raise TiDBError(
                        f"Table '{tn.name}' was not locked with LOCK "
                        "TABLES", code=ErrCode.TableNotLocked)
                if write and mine != "write":
                    raise TiDBError(
                        f"Table '{tn.name}' was locked with a READ lock "
                        "and can't be updated",
                        code=ErrCode.TableNotLockedForWrite)

    # -- variables ----------------------------------------------------------

    def get_sysvar(self, name, scope="session"):
        reg = sv.get_registry().get(name)
        if scope == "global":
            if name in self.domain.global_vars:
                return self.domain.global_vars[name]
        else:
            if name in self.session_vars:
                return self.session_vars[name]
            if name in self.domain.global_vars:
                return self.domain.global_vars[name]
        if reg is None:
            raise TiDBError(f"Unknown system variable '{name}'",
                            code=ErrCode.UnknownSystemVariable)
        return reg.default

    def set_sysvar(self, name, value, scope="session"):
        reg = sv.get_registry().get(name)
        if reg is None:
            raise TiDBError(f"Unknown system variable '{name}'",
                            code=ErrCode.UnknownSystemVariable)
        v = reg.validate(value) if value is not None else reg.default
        if name == "tidb_snapshot" and v:
            # reject an unparseable snapshot NOW — accepting it would
            # wedge every later read behind cast errors (the reference
            # validates at SET time too, variable/varsutil.go)
            try:
                self._datetime_to_ts(v)
            except Exception:
                raise TiDBError(
                    f"Incorrect argument type to variable 'tidb_snapshot'"
                    f": '{v}'")
        if scope == "global":
            self.domain.global_vars[name] = v
            if (name == "tidb_compile_prewarm"
                    and str(v).upper() in ("ON", "1")):
                # globals are in-memory only, so the Domain-start hook
                # reads an empty dict on every boot — SET GLOBAL is the
                # moment the operator's intent actually exists; kick the
                # background prewarm NOW (executor/compile_service.py)
                from ..executor import compile_service
                compile_service.maybe_prewarm_on_start(self.domain)
        else:
            self.session_vars[name] = v

    def autocommit(self) -> bool:
        return self.get_sysvar("autocommit") == "ON"

    def current_db(self) -> str:
        return self._db

    def infoschema(self) -> InfoSchema:
        base = self.domain.infoschema()
        if not self.temp_tables:
            return base
        return _TempSchema(base, self.temp_tables)

    def expr_ctx(self):
        return self._expr_ctx

    # -- txn management (reference: session/txn.go LazyTxn) ------------------

    def txn_for_read(self):
        ts = self.stale_read_ts()
        if ts is not None:
            # stale read (reference: sessiontxn/interface.go:48 stale-read
            # providers): a historical snapshot, never the live txn
            return self.store.get_snapshot(ts)
        if self.txn is not None and self.txn.valid:
            return self.txn
        # read-only statement txn: snapshot view, nothing to commit
        return self.store.begin()

    def txn_for_write(self):
        if self.stale_read_ts() is not None or self.txn_read_only:
            raise TiDBError(
                "can not execute write statement in a read-only "
                "transaction or stale read ('tidb_snapshot'/AS OF)",
                code=ErrCode.CantExecuteInReadOnlyTxn)
        if self.txn is None or not self.txn.valid:
            self.txn = self.store.begin()
            if not self.explicit_txn and not self.autocommit():
                self.explicit_txn = True
        return self.txn

    def stale_read_ts(self):
        """The active historical read ts, or None. Priority (reference:
        sessiontxn staleness providers): statement-level AS OF TIMESTAMP >
        stale READ ONLY txn > tidb_snapshot sysvar > tidb_read_staleness."""
        if self._stmt_as_of_ts is not None:
            return self._stmt_as_of_ts
        if self._txn_as_of_ts is not None:
            return self._txn_as_of_ts
        try:
            snap = self.get_sysvar("tidb_snapshot")
        except Exception:
            snap = ""
        if snap:
            return self._datetime_to_ts(snap)
        try:
            stale_s = int(self.get_sysvar("tidb_read_staleness"))
        except Exception:
            stale_s = 0
        if stale_s < 0:
            import time as _time
            return (int((_time.time() + stale_s) * 1000) << 18) | 0x3ffff
        return None

    def set_stmt_as_of(self, expr_ast):
        """Statement-scoped AS OF TIMESTAMP from a table factor (cleared
        by run_query's finally). Mixing with an explicit txn is an error,
        like the reference."""
        if (self.txn is not None and self.txn.valid) or self.explicit_txn:
            raise TiDBError("as of timestamp can't be set in transaction",
                            code=ErrCode.AsOfInTxn)
        ts = self._eval_as_of_ts(expr_ast)
        if self._stmt_as_of_ts is not None and self._stmt_as_of_ts != ts:
            raise TiDBError(
                "can not set different time in the as of",
                code=ErrCode.AsOfInTxn)
        self._stmt_as_of_ts = ts

    def _eval_as_of_ts(self, expr_ast) -> int:
        from ..expression.builder import ExprBuilder, Schema
        b = ExprBuilder(Schema([]), self._expr_ctx)
        v = b.build(expr_ast).eval_scalar()
        if v is None:
            raise TiDBError("invalid AS OF TIMESTAMP value")
        if isinstance(v, (bytes, bytearray)):
            v = v.decode()
        return self._datetime_to_ts(v)

    def _datetime_to_ts(self, v) -> int:
        """Datetime (string or internal micros) → TSO upper bound for that
        wall instant (PD layout: unix-ms << 18 | logical)."""
        from ..sqltypes import TYPE_DATETIME, FieldType
        from ..table import cast_value
        if isinstance(v, str):
            v = cast_value(v, FieldType(tp=TYPE_DATETIME, decimal=6))
        micros = int(v)
        ms = micros // 1000
        return (ms << 18) | 0x3ffff

    def txn_dirty(self, table_id) -> bool:
        """True if the current txn holds uncommitted writes for this table
        (forces the union-scan read path)."""
        if self.txn is None or not self.txn.valid:
            return False
        if table_id in self.txn.touched_tables:
            return True
        if len(self.txn.membuf) == 0:
            return False
        from .. import tablecodec
        start, end = tablecodec.table_range(table_id)
        return bool(self.txn.membuf.range_items(start, end))

    def finish_dml(self):
        """Autocommit boundary after a DML statement."""
        if self.explicit_txn:
            return
        if self.autocommit() and self.txn is not None and self.txn.valid:
            self._commit_txn()

    def _commit_txn(self):
        txn, self.txn = self.txn, None
        from .. import tablecodec
        cache = self.domain.columnar_cache
        # capture per-table record mutations BEFORE commit (the membuffer
        # survives commit, but collecting first keeps failure paths simple)
        deltas: dict[int, list] | None = {}
        try:
            for tid in txn.touched_tables:
                pre = tablecodec.record_prefix(tid)
                muts = []
                for k, v in txn.membuf.range_items(pre, pre + b"\xff" * 9):
                    try:
                        _t, h = tablecodec.decode_record_key(k)
                    except ValueError:
                        continue
                    muts.append((h, v))
                deltas[tid] = muts
        except Exception:
            deltas = None
        if txn.schema_fps:
            # fleet half of the schema lease: a sibling worker's DDL
            # published a newer schema-version cell — reload FIRST
            # (outside the shared gate: reload takes the exclusive
            # side), then let the fingerprint check below decide whether
            # this txn's tables actually moved (ErrInfoSchemaChanged,
            # retriable) or the DDL was elsewhere (commit proceeds)
            self.domain.maybe_reload_schema(force=True)
            # F1 schema-lease guard (reference: the commit-time schema
            # check behind ErrInfoSchemaChanged + schema_amender.go's
            # role): mutations built against a table whose column/index
            # states advanced may lack maintenance the new state requires
            # (e.g. removing a delete-only index's entry) — fail the
            # commit retriably instead of corrupting the index. The
            # shared gate keeps [check → commit] atomic w.r.t. schema
            # publication (reload_schema holds the exclusive side).
            from ..errors import SchemaChangedError
            from ..table import schema_fp
            with self.domain.schema_gate.shared():
                infos_now = self.domain.infoschema()
                for tid, fp in txn.schema_fps.items():
                    info, _stats_tid = self._resolve_physical(infos_now, tid)
                    if info is None or (
                            schema_fp(info) != fp
                            and not self._try_amend_schema(txn, tid, fp,
                                                           info)):
                        txn.rollback()
                        raise SchemaChangedError(
                            "Information schema is changed during the "
                            "execution of the statement (for example, "
                            "table definition may be updated by other DDL "
                            "ran in parallel). Try again later")
                commit_ts = txn.commit()
        else:
            commit_ts = txn.commit()
        import json as _json
        # readonly observability var (reference: tidb_last_txn_info)
        self.session_vars["tidb_last_txn_info"] = _json.dumps(
            {"txn_scope": "global", "start_ts": txn.start_ts,
             "commit_ts": commit_ts})
        # commit succeeded: maintain the columnar cache incrementally
        # (reference analog: TiFlash applies raft log deltas, not rebuilds)
        infos = self.infoschema()
        for tid in txn.touched_tables:
            newv = txn.committed_versions.get(tid)
            info, stats_tid = self._resolve_physical(infos, tid)
            if deltas is not None and tid in deltas:
                # stats modify-count feed (reference: handle/update.go)
                self.domain.stats_worker.record_delta(stats_tid,
                                                      len(deltas[tid]))
            if deltas is None or info is None or newv is None:
                cache.invalidate(tid)
                continue
            try:
                cache.apply_delta(info, deltas[tid], newv)
            except Exception:
                cache.invalidate(tid)

    def _try_amend_schema(self, txn, tid, old_fp, new_info) -> bool:
        """Schema amender for the dominant mid-txn DDL case (reference:
        session/schema_amender.go, 704 LoC — amendOperationAddIndex):
        when the only schema delta on a written table is NON-UNIQUE
        indexes gaining write visibility (ADD INDEX reaching write-only/
        write-reorg/public while this optimistic txn was open), patch the
        membuffer with the missing index mutations — delete the entry the
        backfill may have written for the pre-txn row, insert the entry
        for the new row — and let the commit proceed instead of failing
        8028. Anything else (column changes, dropped/regressed indexes,
        unique additions whose duplicate check needs a global scan) keeps
        the fingerprint gate's retriable abort. Returns True when the
        txn's mutations now satisfy the CURRENT schema."""
        from .. import tablecodec
        from ..model import SchemaState
        from ..table import Table, schema_fp
        new_fp = schema_fp(new_info)
        if old_fp[0] != new_fp[0]:
            return False  # column layout moved: row encodings may be stale
        old_idx = {t[0]: t for t in old_fp[1]}
        to_amend = []
        for ix in new_info.indexes:
            prev = old_idx.pop(ix.id, None)
            prev_state = prev[1] if prev is not None else None
            if prev is not None and (prev[2] != ix.unique
                                     or ix.state < prev_state):
                return False  # changed definition or regressing state
            prev_writes = (prev_state is not None
                           and prev_state > SchemaState.DELETE_ONLY)
            if prev_writes or ix.state <= SchemaState.DELETE_ONLY:
                continue  # puts already maintained, or none required yet
            if ix.unique:
                return False
            to_amend.append(ix)
        if old_idx:
            return False  # an index this txn maintained no longer exists
        if to_amend:
            pre = tablecodec.record_prefix(tid)
            items = list(txn.membuf.range_items(pre, pre + b"\xff" * 9))
            tbl = Table(new_info, txn)

            def entry_key(ix, row, h):
                # to_amend is non-unique only: the entry key always
                # carries the handle (table.py _index_put layout)
                return tablecodec.index_key(
                    new_info.id, ix.id, tbl._index_values(ix, row), handle=h)

            for k, v in items:
                try:
                    _t, h = tablecodec.decode_record_key(k)
                except ValueError:
                    continue
                r_new = tablecodec.decode_row(v) if v is not None else None
                old_val = txn.snapshot.get(k)
                r_old = (tablecodec.decode_row(old_val)
                         if old_val is not None else None)
                for ix in to_amend:
                    if r_old is not None:
                        # the reorg backfill (running at a later snapshot)
                        # indexes the pre-txn row; our commit replaces it.
                        # Amended keys skip the prewrite ts-conflict check
                        # — the backfill's later commit on exactly these
                        # keys is the expected interleaving, not a race
                        key = entry_key(ix, r_old, h)
                        txn.delete(key)
                        txn.amend_keys.add(key)
                    if r_new is not None:
                        key = entry_key(ix, r_new, h)
                        txn.put(key, tablecodec.INDEX_VALUE_MARKER)
                        txn.amend_keys.add(key)
        txn.schema_fps[tid] = new_fp
        return True

    def _resolve_physical(self, infos, tid):
        """tid → (TableInfo view, stats table id): logical tables resolve
        directly; partition physical ids resolve to a partition view with
        stats rolling up to the logical table. (None, tid) when dropped."""
        found = infos.table_by_id(tid)
        if found is not None:
            return found[1], tid
        part = infos.partition_by_id(tid)
        if part is not None:
            from ..partition import partition_view
            _db, logical, pdef = part
            return partition_view(logical, pdef), logical.id
        return None, tid

    def _implicit_commit(self):
        """DDL and account-management statements implicitly commit the
        active transaction first (reference: MySQL implicit commit;
        session.go runs DDL outside the user txn)."""
        self.explicit_txn = False
        if self.txn is not None and self.txn.valid:
            self._commit_txn()
        else:
            self.txn = None

    def begin(self):
        if self.txn is not None and self.txn.valid:
            self._commit_txn()
        self._txn_as_of_ts = None
        self.txn = self.store.begin()
        self.explicit_txn = True
        self.txn_stmt_history = []

    def commit(self):
        self.explicit_txn = False
        self._txn_as_of_ts = None
        self.txn_read_only = False
        history, self.txn_stmt_history = self.txn_stmt_history, []
        if self.txn is not None and self.txn.valid:
            from ..errors import SchemaChangedError
            try:
                self._commit_txn()
            except (WriteConflictError, SchemaChangedError):
                # both are retriable by statement replay: the fresh attempt
                # re-resolves tables under the new schema (reference:
                # doCommitWithRetry, session.go:797)
                if self._txn_retry_disabled() or not history:
                    raise
                self._retry_txn(history)
        else:
            self.txn = None

    def _txn_retry_disabled(self) -> bool:
        try:
            v = str(self.get_sysvar("tidb_disable_txn_auto_retry"))
        except Exception:
            return True
        return v.upper() in ("ON", "1", "TRUE")

    def _retry_limit(self) -> int:
        try:
            return max(int(self.get_sysvar("tidb_retry_limit")), 0)
        except Exception:
            return 10

    def _retry_txn(self, history):
        """Optimistic-txn retry: replay the statement history on a fresh
        snapshot and re-commit (reference: session.go:797 doCommitWithRetry
        → retry with schema check).  Retries draw from the session's
        unified backoff budget (utils/backoff.Backoffer): bounded attempts
        with jittered sleeps between replays, interruptible by KILL."""
        from ..errors import BackoffExhaustedError
        from ..utils.backoff import Backoffer
        limit = max(self._retry_limit(), 1)
        bo = Backoffer.for_session(self)
        last = None
        for attempt in range(limit):
            self.txn = self.store.begin()
            self._in_txn_retry = True
            self.explicit_txn = True  # replayed DML must not autocommit
            try:
                for stmt in history:
                    self._dispatch(stmt)
                self.explicit_txn = False
                self._commit_txn()
                return
            except (WriteConflictError, _SchemaChangedError) as e:
                last = e
                if self.txn is not None and self.txn.valid:
                    self.txn.rollback()
                self.txn = None
                if attempt + 1 < limit:
                    try:
                        bo.backoff("txnRetry", e)
                    except BackoffExhaustedError as be:
                        last = be
                        break
                continue
            except Exception:
                if self.txn is not None and self.txn.valid:
                    self.txn.rollback()
                self.txn = None
                raise
            finally:
                self._in_txn_retry = False
                self.explicit_txn = False
        raise last if last is not None else TiDBError(
            "transaction retry failed", code=ErrCode.TxnRetryable)

    def rollback(self):
        self.explicit_txn = False
        self._txn_as_of_ts = None
        self.txn_read_only = False
        self.txn_stmt_history = []
        if self.txn is not None and self.txn.valid:
            self.txn.rollback()
        self.txn = None

    def _meta_txn_retry(self, body, exhaust_msg: str):
        """Run one independent meta txn (autoid/sequence allocation —
        outside the user txn) with unified conflict retry: WriteConflict
        backs off through the session's budget ("autoid" curve) and
        exhaustion surfaces as a NAMED classified error.  `body(txn)`
        commits (or rolls back a no-op) itself and returns the result."""
        from ..errors import BackoffExhaustedError
        from ..utils.backoff import Backoffer
        bo = Backoffer.for_session(self)
        while True:
            txn = self.store.begin()
            try:
                return body(txn)
            except WriteConflictError as e:
                txn.rollback()
                try:
                    bo.backoff("autoid", e)
                except BackoffExhaustedError as be:
                    raise TiDBError(exhaust_msg,
                                    code=ErrCode.BackoffExhausted) from be
            except Exception:
                txn.rollback()
                raise

    def alloc_autoid(self, table_id, n=1) -> int:
        """Independent meta txn for id allocation
        (reference: meta/autoid — batched, outside the user txn)."""
        def body(txn):
            base, _end = Meta(txn).alloc_autoid_batch(table_id, n)
            txn.commit()
            return base
        return self._meta_txn_retry(body, "autoid allocation conflict")

    def seq_next(self, info) -> int:
        """NEXTVAL: serve from the session's cached batch; refill with one
        independent meta txn per CACHE values (reference: meta/autoid
        SequenceAllocator — outside the user txn)."""
        inc = info.sequence.get("increment", 1) or 1
        st = self.seq_cache.get(info.id)
        if st is None or st[1] <= 0:
            k = max(int(info.sequence.get("cache", 1) or 1), 1)

            def body(txn):
                first, count = Meta(txn).sequence_next_batch(
                    info.id, info.sequence, k)
                txn.commit()
                return (first, count)
            st = self._meta_txn_retry(body, "sequence allocation conflict")
        v, remaining = st
        self.seq_cache[info.id] = (v + inc, remaining - 1)
        self.seq_lastval[info.id] = v
        return v

    def seq_setval(self, info, v: int) -> int:
        self.seq_cache.pop(info.id, None)  # cached batch is now stale

        def body(txn):
            Meta(txn).set_sequence_value(info.id, int(v))
            txn.commit()
            return int(v)
        return self._meta_txn_retry(body, "sequence setval conflict")

    def rebase_autoid(self, table_id, new_base: int):
        def body(txn):
            m = Meta(txn)
            if m.autoid(table_id) < new_base:
                m.set_autoid(table_id, new_base)
                txn.commit()
            else:
                txn.rollback()
        self._meta_txn_retry(body, "autoid rebase conflict")

    # -- columnar cache accessor used by executors ---------------------------

    def columnar_cache(self):
        return self.domain.columnar_cache

    # -- statement loop ------------------------------------------------------

    def execute(self, sql: str) -> list[Result]:
        """reference: session.ExecuteStmt (session.go:1637)."""
        # DIAG <kind> (session/diag.py): the direct-port diagnostics op
        # behind the cluster memtables — a diagnostics verb, not SQL
        # grammar, so it intercepts before the parser
        if sql.lstrip()[:4].upper() == "DIAG":
            from . import diag
            r = diag.maybe_handle(self, sql)
            if r is not None:
                return [r]
        # fleet schema lease (no-op outside a durable shared store): a
        # sibling worker's DDL must be visible before this statement
        # plans against the local infoschema
        self.domain.maybe_reload_schema()
        stmts = self.parser.parse(sql)
        return [self._execute_stmt(s) for s in stmts]

    def prepare(self, sql: str):
        """Binary-protocol PREPARE: parse once, return (stmt_ast,
        param_count) — '?' markers are real ParamMarker nodes, so the count
        follows SQL lexing (comments/identifiers/strings excluded).
        reference: server/driver_tidb.go Prepare."""
        stmts = self.parser.parse(sql)
        if len(stmts) != 1:
            raise TiDBError("prepared statement must be a single statement")
        return stmts[0], self.parser.param_count

    def prepared_schema(self, stmt_ast, n_params: int = 0):
        """Best-effort output schema (names, ftypes) for a prepared
        statement, derived by planning with NULL-bound parameters — the
        COM_STMT_PREPARE response must advertise the real column count
        (reference: server/conn_stmt.go writePrepare). Returns ([], [])
        for non-resultset statements or when planning needs real values."""
        if not isinstance(stmt_ast, (ast.SelectStmt, ast.SetOprStmt)):
            return [], []
        self._expr_ctx.params = [None] * n_params
        try:
            plan = self.plan_query(stmt_ast)
            return _schema_names(plan), [r.ftype for r in plan.schema.refs]
        except Exception:
            return [], []
        finally:
            self._expr_ctx.params = None

    def execute_prepared(self, stmt_ast, params: list) -> Result:
        """Binary-protocol EXECUTE over a pre-parsed statement with bound
        parameters (reference: server/conn_stmt.go handleStmtExecute)."""
        self._expr_ctx.params = list(params)
        try:
            return self._execute_stmt(stmt_ast)
        finally:
            self._expr_ctx.params = None

    def _execute_stmt(self, stmt) -> Result:
        self.warnings = []
        self.killed = False  # a KILL targets the CURRENT statement only
        if self.kill_conn:
            raise TiDBError("connection was killed",
                            code=ErrCode.QueryInterrupted)
        # a previous statement that only PLANNED (EXPLAIN, CTAS) may have
        # pinned a stale-read ts without a run_query finally to clear it
        self._stmt_as_of_ts = None
        # expensive-query watchdog (reference: util/expensivequery/
        # expensivequery.go:34,69 + MySQL semantics: TOP-LEVEL read-only
        # SELECTs only — a DML's embedded SELECT must not arm it)
        timer = None
        if isinstance(stmt, (ast.SelectStmt, ast.SetOprStmt)):
            try:
                timeout_ms = int(self.get_sysvar("max_execution_time"))
            except Exception:
                timeout_ms = 0
            if timeout_ms > 0:
                import threading as _threading
                timer = _threading.Timer(timeout_ms / 1000.0, self.kill)
                timer.daemon = True
                timer.start()
        # span tracing (session/tracing.py): sample this statement's
        # lifecycle per tidb_trace_sampling_rate (TRACE statements force
        # their own trace in _exec_trace).  Sampling off costs exactly
        # this one sysvar read + branch; no Trace is ever allocated.
        tr = None
        if not self._internal and tracing.active() is None:
            try:
                rate = float(self.get_sysvar("tidb_trace_sampling_rate"))
            except (TiDBError, ValueError, TypeError):
                rate = 0.0
            if rate > 0 and (rate >= 1.0 or _random.random() < rate):
                tr = tracing.begin("statement", origin="sampled",
                                   conn_id=self.conn_id,
                                   stmt=type(stmt).__name__)
        t0 = time.perf_counter()
        try:
            sql = stmt.restore()
        except Exception:
            sql = type(stmt).__name__
        self.current_sql = sql
        self.stmt_start = time.time()
        # advisory-lock owner identity: per-SESSION, not per-thread (an
        # in-process embedding serves many sessions on one thread)
        from ..expression.builtins_ext import set_lock_owner
        set_lock_owner(id(self))
        # per-statement memory quota (reference: stmtctx MemTracker under
        # the session tracker; tidb_mem_quota_query)
        from ..utils.memory import MemTracker
        try:
            quota = int(self.get_sysvar("tidb_mem_quota_query"))
        except Exception:
            quota = 0
        self.mem_tracker = MemTracker(f"conn{self.conn_id}", quota)
        self._expr_ctx.cte_results = {}  # recursive-CTE cache, per stmt
        res = None
        # audit plugins observe every statement (reference: the audit hook
        # in connection dispatch, server/conn.go:1094)
        if self.domain.plugins.list():
            from ..plugin import EVENT_STMT
            self.domain.plugins.audit_general(self, sql, EVENT_STMT)
        try:
            res = self._dispatch(stmt)
            if isinstance(stmt, (ast.SelectStmt, ast.SetOprStmt,
                                 ast.ExplainStmt, ast.TraceStmt,
                                 ast.ShowStmt)):
                # read-only statements: a kill landing after the last
                # operator checkpoint still cancels (result discarded).
                # Write statements are exempt — their txn may already be
                # committed, and "interrupted" after a commit would lie
                self.check_killed()
            return res
        except Exception:
            # statement-level rollback of the autocommit txn — ANY escaping
            # exception must not leave a stale txn dangling on the session
            if not self.explicit_txn and self.txn is not None and self.txn.valid:
                self.txn.rollback()
                self.txn = None
            raise
        finally:
            if timer is not None:
                timer.cancel()
            self.current_sql = None
            el = time.perf_counter() - t0
            try:
                if tr is not None:
                    tracing.finish(tr, succ=res is not None)
                thr_ms = int(self.get_sysvar("tidb_slow_log_threshold"))
                rows = (res.affected if res is not None and res.chunk is None
                        else (res.chunk.num_rows if res is not None else 0))
                # a sampled statement crossing the slow threshold keeps
                # its rendered span tree on the SlowQueryItem — the
                # causal timeline lands NEXT TO the slow entry instead
                # of needing a separate trace lookup
                trace_text = ""
                if tr is not None and el >= thr_ms / 1000.0:
                    trace_text = tracing.render_tree(tr)
                try:
                    slow_file = str(
                        self.get_sysvar("tidb_slow_query_file")).strip()
                except TiDBError:
                    slow_file = ""
                self.domain.observe.observe_stmt(
                    user=self.user, db=self._db, sql=sql,
                    digest=sql_digest(sql), latency_s=el, rows=rows,
                    succ=res is not None, slow_threshold_s=thr_ms / 1000.0,
                    trace=trace_text, slow_query_file=slow_file)
                self.domain.observe.observe_hist(
                    "statement_duration_seconds", el)
            except Exception:
                pass  # observability must never fail the statement

    def _dispatch(self, stmt) -> Result:
        if self.domain.priv.enabled and not self._internal:
            from ..priv_check import check_stmt_privileges
            check_stmt_privileges(self, stmt)
        if isinstance(stmt, (ast.CreateUserStmt, ast.DropUserStmt,
                             ast.AlterUserStmt, ast.GrantStmt,
                             ast.RevokeStmt)):
            # implicit commit: the grant-table writes and the cache reload
            # must see committed state, not the open txn's snapshot
            self._implicit_commit()
            from ..executor import priv_exec
            fn = {ast.CreateUserStmt: priv_exec.create_user,
                  ast.DropUserStmt: priv_exec.drop_user,
                  ast.AlterUserStmt: priv_exec.alter_user,
                  ast.GrantStmt: priv_exec.grant,
                  ast.RevokeStmt: priv_exec.revoke}[type(stmt)]
            fn(self, stmt)
            return Result()
        if isinstance(stmt, (ast.LockTablesStmt, ast.UnlockTablesStmt)):
            self._implicit_commit()  # LOCK/UNLOCK TABLES commit (MySQL)
            if isinstance(stmt, ast.UnlockTablesStmt):
                self.unlock_tables()
                return Result()
            items = []
            infos = self.infoschema()
            for tn, mode in stmt.items:
                db = tn.schema or self.current_db()
                infos.table_by_name(db, tn.name)  # must exist
                items.append((db.lower(), tn.name.lower(), mode))
            self.lock_tables(items)
            return Result()
        if isinstance(stmt, (ast.SelectStmt, ast.SetOprStmt, ast.InsertStmt,
                             ast.UpdateStmt, ast.DeleteStmt,
                             ast.TruncateTableStmt, ast.DropTableStmt,
                             ast.AlterTableStmt, ast.CreateIndexStmt,
                             ast.DropIndexStmt, ast.RenameTableStmt)):
            self.check_table_locks(stmt)
        if isinstance(stmt, (ast.SelectStmt, ast.SetOprStmt)):
            if (getattr(stmt, "for_update", False)
                    and (self.explicit_txn or not self.autocommit())):
                return self._run_select_for_update(stmt)
            return self.run_query(stmt)
        if isinstance(stmt, ast.InsertStmt):
            from ..executor.dml import InsertExec
            r = self._exec_dml(stmt, lambda: InsertExec(self, stmt).execute())
            self.last_insert_id = r.last_insert_id or self.last_insert_id
            return Result(affected=r.affected, last_insert_id=r.last_insert_id)
        if isinstance(stmt, ast.UpdateStmt):
            from ..executor.dml import UpdateExec
            r = self._exec_dml(stmt, lambda: UpdateExec(self, stmt).execute())
            return Result(affected=r.affected)
        if isinstance(stmt, ast.DeleteStmt):
            from ..executor.dml import DeleteExec
            r = self._exec_dml(stmt, lambda: DeleteExec(self, stmt).execute())
            return Result(affected=r.affected)
        if isinstance(stmt, ast.UseStmt):
            virtual = stmt.db.lower() in ("information_schema",
                                          "performance_schema",
                                          "metrics_schema")
            if not virtual and \
                    self.infoschema().schema_by_name(stmt.db) is None:
                raise SchemaError(f"Unknown database '{stmt.db}'",
                                  code=ErrCode.BadDB)
            self._db = stmt.db
            return Result()
        if isinstance(stmt, ast.SetStmt):
            return self._exec_set(stmt)
        if isinstance(stmt, ast.BeginStmt):
            self.txn_read_only = stmt.read_only
            if stmt.as_of is not None:
                # stale READ ONLY txn: a pinned historical read view,
                # no write txn at all (reference: sessiontxn staleness
                # provider for START TRANSACTION READ ONLY AS OF)
                if self.txn is not None and self.txn.valid:
                    self._commit_txn()
                self._txn_as_of_ts = self._eval_as_of_ts(stmt.as_of)
                self.explicit_txn = True
                self.txn_stmt_history = []
                return Result()
            self.begin()
            return Result()
        if isinstance(stmt, ast.CommitStmt):
            self.commit()
            return Result()
        if isinstance(stmt, ast.RollbackStmt):
            self.rollback()
            return Result()
        if isinstance(stmt, (ast.CreateDatabaseStmt, ast.DropDatabaseStmt,
                             ast.CreateTableStmt, ast.DropTableStmt,
                             ast.TruncateTableStmt, ast.CreateIndexStmt,
                             ast.DropIndexStmt, ast.AlterTableStmt,
                             ast.RenameTableStmt, ast.CreateViewStmt,
                             ast.CreateSequenceStmt, ast.DropSequenceStmt)):
            # DDL implicitly commits (MySQL rule) — EXCEPT CREATE/DROP
            # TEMPORARY TABLE, which MySQL exempts explicitly
            if not getattr(stmt, "temporary", False):
                self._implicit_commit()
        if isinstance(stmt, ast.ShowStmt):
            from .show import exec_show
            return exec_show(self, stmt)
        if isinstance(stmt, ast.ExplainStmt):
            return self._exec_explain(stmt)
        if isinstance(stmt, ast.CreateDatabaseStmt):
            self.ddl.create_database(stmt)
            return Result()
        if isinstance(stmt, ast.DropDatabaseStmt):
            self.ddl.drop_database(stmt)
            if self._db.lower() == stmt.name.lower():
                self._db = ""
            return Result()
        if isinstance(stmt, ast.CreateTableStmt):
            self.ddl.create_table(stmt)
            return Result()
        if isinstance(stmt, ast.CreateViewStmt):
            self.ddl.create_view(stmt)
            return Result()
        if isinstance(stmt, ast.CreateSequenceStmt):
            self.ddl.create_sequence(stmt)
            return Result()
        if isinstance(stmt, ast.DropSequenceStmt):
            self.ddl.drop_sequence(stmt)
            return Result()
        if isinstance(stmt, ast.RecoverTableStmt):
            self._implicit_commit()
            self.ddl.recover_table(stmt)
            return Result()
        if isinstance(stmt, ast.CreateBindingStmt):
            from ..bindinfo import make_binding
            key, rec = make_binding(stmt.original, stmt.hinted,
                                    db=self.current_db())
            if stmt.is_global:
                self.domain.bind_handle.create(key, rec)
            else:
                self.session_bindings[key] = rec
                self.bindings_version += 1
            return Result()
        if isinstance(stmt, ast.DropBindingStmt):
            from ..bindinfo import binding_key, normalized_sql
            key = binding_key(self.current_db(),
                              normalized_sql(stmt.original))
            if stmt.is_global:
                self.domain.bind_handle.drop(key)
            else:
                self.session_bindings.pop(key, None)
                self.bindings_version += 1
            return Result()
        if isinstance(stmt, ast.DropTableStmt):
            self.ddl.drop_table(stmt)
            return Result()
        if isinstance(stmt, ast.TruncateTableStmt):
            self.ddl.truncate_table(stmt)
            return Result()
        if isinstance(stmt, ast.CreateIndexStmt):
            self.ddl.create_index(stmt)
            return Result()
        if isinstance(stmt, ast.DropIndexStmt):
            self.ddl.drop_index(stmt)
            return Result()
        if isinstance(stmt, ast.AlterTableStmt):
            self.ddl.alter_table(stmt)
            return Result()
        if isinstance(stmt, ast.RenameTableStmt):
            self.ddl.rename_table(stmt)
            return Result()
        if isinstance(stmt, ast.AnalyzeTableStmt):
            return self._exec_analyze(stmt)
        if isinstance(stmt, ast.AdminStmt):
            return self._exec_admin(stmt)
        if isinstance(stmt, ast.PrepareStmt):
            sql = stmt.sql
            if isinstance(sql, ast.VariableExpr):
                v = self.user_vars.get(sql.name)
                sql = v.decode() if isinstance(v, bytes) else str(v or "")
            self.prepared[stmt.name] = sql
            return Result()
        if isinstance(stmt, ast.ExecuteStmt):
            return self._exec_execute(stmt)
        if isinstance(stmt, ast.DeallocateStmt):
            self.prepared.pop(stmt.name, None)
            return Result()
        if isinstance(stmt, ast.FlushStmt):
            return Result()
        if isinstance(stmt, (ast.CreatePlacementPolicyStmt,
                             ast.DropPlacementPolicyStmt)):
            # placement policies persist in meta; tables reference them by
            # name (reference: ddl/placement_policy.go). With ONE embedded
            # store the constraints are catalog state — the scheduler role
            # needs multiple stores — but the DDL surface round-trips.
            self._implicit_commit()
            return self._exec_placement_policy(stmt)
        if isinstance(stmt, ast.KillStmt):
            target = self.domain.sessions.get(stmt.conn_id)
            if target is None:
                raise TiDBError(f"Unknown thread id: {stmt.conn_id}",
                                code=ErrCode.NoSuchThread)
            target.kill(query_only=stmt.query_only)
            return Result()
        if isinstance(stmt, ast.BRIEStmt):
            self._implicit_commit()
            from .. import br
            from ..sqltypes import TYPE_LONGLONG, TYPE_VARCHAR
            if stmt.kind == "backup":
                meta = (br.physical_backup_database
                        if stmt.mode == "physical"
                        else br.backup_database)(self, stmt.db, stmt.path)
            else:
                # mode auto-detects from backupmeta; an explicit MODE
                # must match what the backup actually is
                bm = json.loads(br.open_storage(
                    stmt.path).read_text("backupmeta.json"))
                physical = bm.get("mode") == "physical"
                if stmt.mode and (stmt.mode == "physical") != physical:
                    raise TiDBError(
                        f"backup at '{stmt.path}' is "
                        f"{'physical' if physical else 'logical'}, not "
                        f"{stmt.mode}")
                meta = (br.physical_restore_database if physical
                        else br.restore_database)(
                    self, stmt.path, stmt.db, meta=bm)
            ft_s = FieldType(tp=TYPE_VARCHAR)
            ft_i = FieldType(tp=TYPE_LONGLONG)
            rows = [(t["name"].encode(), t.get("rows", t.get("kv", 0)))
                    for t in meta["tables"]]
            return Result(names=["table", "rows"],
                          chunk=Chunk.from_rows([ft_s, ft_i], rows))
        if isinstance(stmt, ast.TraceStmt):
            return self._exec_trace(stmt)
        if isinstance(stmt, ast.PlanReplayerStmt):
            return self._exec_plan_replayer(stmt)
        raise TiDBError(f"unsupported statement {type(stmt).__name__}")

    # -- DML execution with retry (reference: session.go:797
    #    doCommitWithRetry + executor/adapter.go:435 pessimistic retry) -----

    def _exec_dml(self, stmt, run):
        """Run a DML executor with the transaction-mode-appropriate
        conflict handling:
        - explicit pessimistic txn: lock written keys per statement,
          blocking on foreign locks; re-execute on a fresh for-update
          snapshot when a conflicting commit slipped in;
        - autocommit (implicit txn): retry the whole statement on commit
          conflict up to tidb_retry_limit;
        - explicit optimistic txn: record the statement for commit-time
          replay (see _retry_txn)."""
        if self.explicit_txn or not self.autocommit():
            # explicit txn OR implicit txn (autocommit=0): the first DML
            # must take the same path as the rest of the transaction
            mode = ""
            try:
                mode = str(self.get_sysvar("tidb_txn_mode")).lower()
            except Exception:
                pass
            if mode != "optimistic":
                return self._exec_dml_pessimistic(run)
            r = run()
            if not self._in_txn_retry:
                self.txn_stmt_history.append(stmt)
            return r
        from ..errors import (BackoffExhaustedError, LockedError,
                              SchemaChangedError)
        from ..utils.backoff import Backoffer
        try:
            wait_s = float(self.get_sysvar("innodb_lock_wait_timeout"))
        except Exception:
            wait_s = 50.0
        # wall-clock Backoffer: innodb_lock_wait_timeout is a hard user-
        # facing deadline — tidb_backoff_weight must not stretch it and
        # slow statement re-executions count against it, not just sleeps
        bo = Backoffer(budget_ms=wait_s * 1000, wall_clock=True,
                       check_killed=self.check_killed)
        last = None
        attempts = 0
        while True:
            try:
                return run()
            except (WriteConflictError, SchemaChangedError) as e:
                # schema change mid-statement retries like a conflict: the
                # fresh attempt re-resolves the table and rebuilds the
                # mutations under the new column/index states
                last = e
                attempts += 1
                if attempts > max(self._retry_limit(), 0):
                    raise
            except LockedError as e:
                # a pessimistic txn holds the key: wait it out through the
                # budgeted lock-wait curve (reference: client-go boTxnLock)
                last = e
                try:
                    bo.backoff("txnLock", e)
                except BackoffExhaustedError:
                    raise TiDBError(
                        "Lock wait timeout exceeded; try restarting "
                        "transaction", code=ErrCode.LockWaitTimeout)
            if self.txn is not None and self.txn.valid:
                self.txn.rollback()
            self.txn = None

    def _exec_dml_pessimistic(self, run):
        """Pessimistic statement execution: read at a fresh for_update_ts,
        buffer writes, then acquire pessimistic locks on the write set —
        waiting out foreign locks; when a conflicting commit landed after
        our for_update_ts, undo the statement's buffered writes and
        re-execute on a newer snapshot (reference: adapter.go:435
        handlePessimisticDML + UpdateForUpdateTS)."""
        from ..errors import BackoffExhaustedError, LockedError
        from ..kv.store import Snapshot
        from ..utils.backoff import Backoffer
        txn = self.txn_for_write()
        try:
            wait_s = float(self.get_sysvar("innodb_lock_wait_timeout"))
        except Exception:
            wait_s = 50.0
        orig_snapshot = txn.snapshot
        # hard wall-clock deadline, not weight-scaled (see _exec_dml)
        bo = Backoffer(budget_ms=wait_s * 1000, wall_clock=True,
                       check_killed=self.check_killed)
        last = None
        try:
            while True:
                sp = txn.membuf.savepoint()
                # frontier-fresh, not a raw TSO tick: the shared oracle
                # orders a raw ts ABOVE a peer's commit_ts even when the
                # local replica has not applied that commit yet, so a
                # raw-ts for-update read would compute from the stale
                # value while has_commit_after(for_update_ts) stays
                # silent — a cross-worker lost update.  fresh_read_ts
                # blocks until the applied LSN covers every live peer's
                # durable frontier <= ts (kv/shared_store.fresh_read_ts)
                for_update_ts = self.store._fresh_read_ts()
                txn.snapshot = Snapshot(self.store, for_update_ts,
                                        own_start_ts=txn.start_ts)
                try:
                    r = run()
                except LockedError as e:
                    # a foreign txn is mid-commit (prewrite locks visible
                    # to our read): wait it out like the lock-wait path
                    last = e
                    txn.membuf.rollback_to(sp)
                    try:
                        bo.backoff("txnLock", e)
                    except BackoffExhaustedError:
                        raise TiDBError(
                            "Lock wait timeout exceeded; try restarting "
                            "transaction", code=ErrCode.LockWaitTimeout)
                    continue
                except Exception:
                    txn.membuf.rollback_to(sp)
                    raise
                keys = txn.membuf.keys_since(sp)
                try:
                    txn.lock_keys_wait(
                        keys, for_update_ts,
                        timeout_s=max(bo.remaining_ms() / 1000, 0.001))
                    return r
                except WriteConflictError as e:
                    last = e
                    txn.membuf.rollback_to(sp)
                    try:
                        bo.backoff("txnRetry", e)
                    except BackoffExhaustedError:
                        raise e
                    continue
                except Exception:
                    # lock-wait timeout / deadlock: the statement failed —
                    # its buffered writes must not survive to commit
                    txn.membuf.rollback_to(sp)
                    raise
        finally:
            txn.snapshot = orig_snapshot

    def _run_select_for_update(self, stmt):
        """SELECT ... FOR UPDATE (reference: executor SelectLockExec):
        read on a fresh for-update snapshot, pessimistically lock the
        scanned rows of every base table (a conservative superset when
        filters could not be pushed to the scan), and execute on that same
        snapshot so the returned rows are the latest committed versions.
        Retries with a newer snapshot when a conflicting commit slips
        between snapshot and lock."""
        from .. import tablecodec
        from ..executor import build_executor
        from ..executor.exec_select import eval_conds_mask
        from ..kv.store import Snapshot
        from ..planner.logical import DataSource
        from ..table import Table
        txn = self.txn_for_write()
        plan = self.plan_query(stmt)
        try:
            wait_s = float(self.get_sysvar("innodb_lock_wait_timeout"))
        except Exception:
            wait_s = 50.0
        orig_snapshot = txn.snapshot
        last = None
        try:
            for _attempt in range(max(self._retry_limit(), 1)):
                # frontier-fresh for the same reason as
                # _exec_dml_pessimistic: FOR UPDATE promises the latest
                # committed versions, which in a fleet means waiting out
                # peers' durable frontiers, not just minting a ts
                for_update_ts = self.store._fresh_read_ts()
                txn.snapshot = Snapshot(self.store, for_update_ts,
                                        own_start_ts=txn.start_ts)
                keys = []

                def walk(p):
                    if isinstance(p, DataSource):
                        tbl = Table(p.table_info, txn, parts=p.partitions)
                        if (p.access is not None
                                and p.table_info.partition is None):
                            # drive from the chosen access path instead of
                            # a full scan (reference: SelectLockExec locks
                            # the reader's returned row keys)
                            from ..executor.exec_select import (
                                resolve_access_handles)
                            handles = resolve_access_handles(tbl, p.access)
                            for h in handles:
                                keys.append(tablecodec.record_key(
                                    p.table_info.id, int(h)))
                        else:
                            pts = (tbl.partition_tables()
                                   if p.table_info.partition is not None
                                   else [tbl])
                            for pt in pts:
                                chunk = pt.scan_columnar(
                                    col_infos=p.col_infos, with_handle=True)
                                handles = chunk.columns[-1].data
                                if p.pushed_conds:
                                    data = type(chunk)(chunk.columns[:-1])
                                    mask = eval_conds_mask(p.pushed_conds,
                                                           data)
                                    handles = handles[mask]
                                for h in handles:
                                    keys.append(tablecodec.record_key(
                                        pt.info.id, int(h)))
                    for c in p.children:
                        walk(c)
                walk(plan)
                try:
                    txn.lock_keys_wait(keys, for_update_ts,
                                       timeout_s=wait_s)
                except WriteConflictError as e:
                    last = e
                    continue
                # rows are locked: execute on the same snapshot
                exe = build_executor(plan, self._exec_ctx())
                chunk = exe.execute()
                return Result(names=_schema_names(plan), chunk=chunk)
        finally:
            txn.snapshot = orig_snapshot
        raise last if last is not None else TiDBError(
            "select-for-update retry failed", code=ErrCode.TxnRetryable)

    # -- query path ----------------------------------------------------------

    def plan_query(self, stmt, outer=None):
        undo = None
        if outer is None and isinstance(stmt, (ast.SelectStmt,
                                               ast.SetOprStmt)):
            undo = self._apply_binding(stmt)
        try:
            self.plan_builds += 1
            builder = PlanBuilder(self._expr_ctx, outer=outer)
            plan = builder.build(stmt)
            plan = optimize(plan, self._expr_ctx)
            if outer is None and isinstance(stmt, ast.SelectStmt):
                self._maybe_capture_baseline(stmt, plan)
            return plan
        finally:
            if undo:
                from ..bindinfo import undo_hints
                # restore the AST: prepared statements re-plan the same
                # object, and a dropped binding must stop applying
                undo_hints(undo)

    def _maybe_capture_baseline(self, stmt, plan):
        """Plan-baseline auto capture (reference: bindinfo/handle.go:749
        via the statement summary): with tidb_capture_plan_baselines on, a
        SELECT planned twice gets a GLOBAL binding recording the plan's
        synthesized hint set, so the choice survives restarts and stats
        drift."""
        try:
            if self._internal or self.binding_used is not None:
                return
            if str(self.get_sysvar(
                    "tidb_capture_plan_baselines")).upper() not in (
                        "ON", "1"):
                return
            if stmt.from_ is None:
                return
            from ..bindinfo import binding_key, normalized_sql, plan_hints
            norm = normalized_sql(stmt)
            key = binding_key(self.current_db(), norm)
            if self.domain.bind_handle.match(key) is not None:
                return
            seen = self.domain.capture_counts
            if len(seen) > 4096 and key not in seen:
                seen.clear()  # bounded tally; a cleared count just delays
                #               a capture by one extra planning
            seen[key] = seen.get(key, 0) + 1
            if seen[key] < 2:  # reference captures on the second execution
                return
            hints = plan_hints(plan)
            if not hints:
                return
            orig_text = stmt.restore()
            saved = stmt.hints
            try:  # render the bind text WITH the captured hints
                stmt.hints = hints
                bind_text = stmt.restore()
            finally:
                stmt.hints = saved
            rec = {"original": orig_text, "bind": bind_text,
                   "db": self.current_db().lower(),
                   "hints": [], "sql_hints": [[n, list(a)]
                                              for n, a in hints],
                   "created": time.strftime("%Y-%m-%d %H:%M:%S"),
                   "status": "enabled", "source": "capture"}
            self.domain.bind_handle.create(key, rec)
        except Exception:
            pass  # capture must never fail the statement

    def _apply_binding(self, stmt):
        """Plan-binding match at optimize time (reference:
        planner/optimize.go:147-207): transplant the matched binding's
        index hints onto the statement. Returns the undo list."""
        from ..bindinfo import (apply_hints, binding_key, hints_from_record,
                                normalized_sql)
        self.binding_used = None
        try:
            if self.get_sysvar("tidb_use_plan_baselines").upper() not in (
                    "ON", "1"):
                return None  # baselines disabled for this session
        except Exception:
            pass
        try:
            key = binding_key(self.current_db(), normalized_sql(stmt))
        except Exception:
            return None
        rec = self.session_bindings.get(key)
        if rec is None:
            rec = self.domain.bind_handle.match(key)
        if rec is not None and rec.get("status") == "enabled":
            self.binding_used = key
            from ..bindinfo import sql_hints_from_record
            return apply_hints(stmt, hints_from_record(rec),
                               sql_hints_from_record(rec))
        return None

    def run_built_query(self, logical_plan) -> Result:
        from ..executor import build_executor
        plan = optimize(logical_plan, self._expr_ctx)
        exe = build_executor(plan, self._exec_ctx())
        chunk = exe.execute()
        names = _schema_names(plan)
        return Result(names=names, chunk=chunk)

    def run_query(self, stmt, outer=None) -> Result:
        from ..executor import build_executor
        try:
            plan = cache_key = None
            if (outer is None and self._expr_ctx.params is not None
                    and isinstance(stmt, (ast.SelectStmt, ast.SetOprStmt))):
                plan, cache_key = self._cached_plan(stmt)
            if plan is None:
                with tracing.span("session.plan_query"):
                    plan = self.plan_query(stmt, outer=outer)
                if cache_key is not None:
                    from ..planner.plan_cache import collect_param_consts
                    try:
                        cap = int(self.get_sysvar(
                            "tidb_prepared_plan_cache_size"))
                    except Exception:
                        cap = 0
                    self.plan_cache.put(cache_key, plan,
                                        collect_param_consts(plan), cap)
            # when this statement is traced, wire a runtime-stats
            # collector through the executor tree so per-operator times
            # land in the span tree as events (the TRACE statement's
            # operator rows; reference: executor/trace.go reading the
            # runtime stats back into the span collector)
            coll = None
            if outer is None and tracing.active() is not None:
                from ..executor.execdetails import RuntimeStatsColl
                coll = RuntimeStatsColl()
            with tracing.span("executor.build"):
                exe = build_executor(plan, self._exec_ctx(), stats=coll)
            with tracing.span("executor.run"):
                chunk = exe.execute()
            if coll is not None:
                from ..planner.logical import explain_nodes
                for name, _info, node in explain_nodes(plan):
                    if coll.has(node):
                        st = coll.get(node)
                        tracing.event(
                            "operator." + name.strip().replace("└─", ""),
                            time_s=round(st.time_s, 6), rows=st.rows)
            # a kill that landed after the LAST operator checkpoint still
            # cancels the statement (the result is discarded) — without
            # this, a kill during the final operator's long tail is
            # silently swallowed at the next statement's flag reset
            self.check_killed()
            names = _schema_names(plan)
            return Result(names=names, chunk=chunk)
        finally:
            if outer is None:
                # a table factor's AS OF TIMESTAMP scopes to its
                # STATEMENT: a nested subquery run must not un-pin the
                # outer statement's historical read view mid-flight
                self._stmt_as_of_ts = None

    def _cached_plan(self, stmt):
        """Prepared-plan cache lookup (reference: planner/core/
        common_plans.go Execute.getPhysicalPlan). Returns (plan|None,
        key|None): a key without a plan means 'cacheable — store after
        planning'. On a hit, the new params are rebound into the cached
        plan's tagged constants and the value-dependent physical stages
        re-run (the rebuildRange analog, planner/plan_cache.py)."""
        from ..planner import plan_cache as pc
        try:
            enabled = str(self.get_sysvar(
                "tidb_enable_prepared_plan_cache")).upper() in ("ON", "1")
        except Exception:
            enabled = False
        if not enabled:
            return None, None
        # the prepared AST is immutable between executions: memoize the
        # cacheability walk and the digest on it (the text-protocol EXECUTE
        # path re-parses, so a fresh AST just re-memoizes)
        cacheable = getattr(stmt, "_pc_cacheable", None)
        if cacheable is None:
            cacheable = pc.is_cacheable(stmt)
            stmt._pc_cacheable = cacheable
        if not cacheable:
            return None, None
        digest = getattr(stmt, "_pc_digest", None)
        if digest is None:
            digest = sql_digest(stmt.restore())
            stmt._pc_digest = digest
        params = self._expr_ctx.params
        # the digest deliberately strips /*+ ... */ (bindings match the
        # unhinted form), so the cache key must carry the hint set
        # explicitly — otherwise a hinted and an unhinted prepared
        # statement share one entry and the hint leaks across them
        hint_fp = tuple(
            (n, tuple(a)) for n, a in getattr(stmt, "hints", []) or [])
        key = (digest, self._db, hint_fp,
               self.infoschema().version, self.domain.stats_version,
               self.domain.bind_handle.version, self.bindings_version,
               self.temp_tables_version, pc.param_kinds(params))
        ent = self.plan_cache.get(key)
        if ent is None:
            return None, key
        plan, consts = ent
        if not pc.rebind_params(consts, params):
            # a recorded refinement doesn't apply to these param values
            # (e.g. unparseable date string): re-plan WITHOUT overwriting
            # the good refined entry — the unrefined plan would downgrade
            # every later execution under the same key
            return None, None
        pc.reprune(plan, self._expr_ctx)
        return plan, key

    def _exec_ctx(self):
        return self

    # -- kill / watchdog (reference: util/expensivequery + the KILL
    #    dispatch in server/conn.go) ----------------------------------------

    def kill(self, query_only: bool = True):
        """Interrupt the in-flight statement; executors poll check_killed
        at their entry points and long loops. KILL CONNECTION also marks
        the session dead — further statements are refused and the wire
        server drops the connection."""
        self.killed = True
        if not query_only:
            self.kill_conn = True

    def check_killed(self):
        if self.killed:
            from ..errors import QueryInterruptedError
            raise QueryInterruptedError(
                "Query execution was interrupted")

    # -- misc statements -----------------------------------------------------

    def _exec_placement_policy(self, stmt) -> Result:
        from ..meta import Meta
        txn = self.store.begin()
        try:
            m = Meta(txn)
            rec = m.get_placement_policy(stmt.name)
            if isinstance(stmt, ast.DropPlacementPolicyStmt):
                if rec is None:
                    if stmt.if_exists:
                        txn.rollback()
                        return Result()
                    raise TiDBError(
                        f"Unknown placement policy '{stmt.name}'",
                        code=ErrCode.PlacementPolicyNotExists)
                m.drop_placement_policy(stmt.name)
            else:
                if rec is not None and not stmt.or_alter:
                    if stmt.if_not_exists:
                        txn.rollback()
                        return Result()
                    raise TiDBError(
                        f"Placement policy '{stmt.name}' already exists",
                        code=ErrCode.PlacementPolicyExists)
                if stmt.or_alter and rec is None:
                    raise TiDBError(
                        f"Unknown placement policy '{stmt.name}'",
                        code=ErrCode.PlacementPolicyNotExists)
                display = (rec or {}).get("display") if stmt.or_alter \
                    else None
                m.set_placement_policy(stmt.name, stmt.options,
                                       display=display)
            txn.commit()
        except Exception:
            if txn.valid:
                txn.rollback()
            raise
        return Result()

    def _exec_set(self, stmt: ast.SetStmt) -> Result:
        from ..expression import ExprBuilder, Schema
        b = ExprBuilder(Schema([]), self._expr_ctx)
        for scope, name, node in stmt.items:
            if scope == "user":
                self.user_vars[name] = b.build(node).eval_scalar()
                continue
            if name == "names":
                continue
            if isinstance(node, ast.DefaultExpr):
                self.set_sysvar(name, None, scope)
                continue
            if isinstance(node, ast.ColumnName) and not node.table:
                # SET var = bare_word — MySQL treats the identifier as a
                # string value (SET tidb_partition_prune_mode = dynamic)
                v = node.name
            else:
                # eval_scalar is scale-faithful (decimals come back as
                # decimal.Decimal), so decimal literals and expressions
                # need no special case
                v = b.build(node).eval_scalar()
            if isinstance(v, bytes):
                v = v.decode()
            self.set_sysvar(name, v, scope)
        return Result()

    def _exec_opt_trace(self, inner) -> Result:
        """TRACE FORMAT='opt' SELECT ... — the optimizer trace: one row per
        logical/physical rule with the plan after that rule (reference:
        planner/core/optimizer.go:93-126 step tracer, dumped over
        /optimize_trace/dump there; a resultset here)."""
        trace: list = []
        undo = None
        if isinstance(inner, (ast.SelectStmt, ast.SetOprStmt)):
            undo = self._apply_binding(inner)
        try:
            self.plan_builds += 1
            builder = PlanBuilder(self._expr_ctx)
            plan = builder.build(inner)
            optimize(plan, self._expr_ctx, trace=trace)
        finally:
            if undo:
                from ..bindinfo import undo_hints
                undo_hints(undo)
        ft = FieldType(tp=TYPE_VARCHAR)
        rows = []
        for i, (rule, rendered) in enumerate(trace):
            for line in rendered.splitlines():
                rows.append((str(i).encode(), rule.encode(), line.encode()))
        return Result(names=["step", "rule", "plan"],
                      chunk=Chunk.from_rows([ft, ft, ft], rows))

    def _exec_plan_replayer(self, stmt: ast.PlanReplayerStmt) -> Result:
        """PLAN REPLAYER DUMP EXPLAIN <stmt> (reference:
        executor/plan_replayer.go): capture everything needed to reproduce
        the plan offline — schemas, ANALYZE stats, session/global vars,
        the SQL, EXPLAIN output and engine version — into one zip; the
        result row carries the token (file path)."""
        import io
        import json
        import os
        import tempfile
        import zipfile

        inner = stmt.stmt
        if not isinstance(inner, (ast.SelectStmt, ast.SetOprStmt)):
            raise TiDBError("PLAN REPLAYER supports SELECT statements")
        # referenced base tables (walk TableName nodes in the AST)
        import dataclasses as _dc
        tables = []
        stack = [inner]
        while stack:
            n = stack.pop()
            if isinstance(n, (list, tuple)):
                stack.extend(n)
                continue
            if isinstance(n, ast.TableName):
                tables.append((n.schema or self.current_db(), n.name))
            if _dc.is_dataclass(n) and isinstance(n, ast.Node):
                for f in _dc.fields(n):
                    stack.append(getattr(n, f.name))
        infos = self.infoschema()
        schema_sql, stats = [], {}
        seen = set()
        for db, name in tables:
            key = (db.lower(), name.lower())
            if key in seen:
                continue
            seen.add(key)
            try:
                info = infos.table_by_name(db, name)
            except Exception:
                continue
            from .show import render_create_table
            schema_sql.append(f"USE `{db}`;\n" + render_create_table(info))
            s = self.domain.stats.get(info.id)
            if s:
                stats[f"{db}.{name}"] = s
        explain_rows = self._exec_explain(
            ast.ExplainStmt(stmt=inner)).rows
        sysvars = {"session": dict(self.session_vars),
                   "global": dict(self.domain.global_vars)}
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("sql/sql_meta.toml", f"sql = '''{inner.restore()}'''\n")
            z.writestr("schema/schema.sql", ";\n".join(schema_sql) + ";\n")
            z.writestr("stats/stats.json", json.dumps(stats, default=str))
            z.writestr("variables.json", json.dumps(sysvars))
            z.writestr("explain.txt", "\n".join(
                " | ".join(str(c) for c in r) for r in explain_rows))
            z.writestr("meta.txt", "tpu-htap plan replayer v1\n")
        token = f"replayer_{sql_digest(inner.restore())[:16]}_" \
                f"{int(time.time())}.zip"
        d = os.path.join(tempfile.gettempdir(), "tidb_tpu_replayer")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, token)
        with open(path, "wb") as fh:
            fh.write(buf.getvalue())
        ft = FieldType(tp=TYPE_VARCHAR)
        return Result(names=["File_token"],
                      chunk=Chunk.from_rows([ft], [(path.encode(),)]))

    def _exec_explain(self, stmt: ast.ExplainStmt) -> Result:
        inner = stmt.stmt
        if not isinstance(inner, (ast.SelectStmt, ast.SetOprStmt)):
            raise TiDBError("EXPLAIN supports SELECT statements only for now")
        plan = self.plan_query(inner)
        ft = FieldType(tp=TYPE_VARCHAR)
        if not stmt.analyze:
            if stmt.format in ("verbose", "cost"):
                # cost column: the physical chooser's estimate for the
                # chosen operator variant plus the candidate set it
                # compared (reference: EXPLAIN FORMAT='verbose' prints
                # estCost, planner/core/explain.go)
                from ..planner.logical import explain_nodes
                rows = []
                for name, info, node in explain_nodes(plan):
                    # one currency end-to-end: every node carries the
                    # DP's accumulated cost (planner/physical.py
                    # _best_cost); candidate sets show the alternatives
                    # the chooser compared at that node
                    cost = getattr(node, "cost", None)
                    if cost is None:
                        cost = getattr(node, "join_cost", None)
                    cands = getattr(node, "cost_candidates", None)
                    if cost is not None and cands:
                        ctext = (f"{cost:g} "
                                 + "{" + ", ".join(
                                     f"{k}:{v:g}" for k, v in
                                     sorted(cands.items())) + "}")
                    elif cost is not None:
                        ctext = f"{cost:g}"
                    else:
                        ctext = "-"
                    rows.append((name.encode(), ctext.encode(),
                                 info.encode()))
                return Result(names=["id", "estCost", "info"],
                              chunk=Chunk.from_rows([ft, ft, ft], rows))
            rows = [(name.encode(), info.encode())
                    for name, info in explain_tree(plan)]
            return Result(names=["id", "info"],
                          chunk=Chunk.from_rows([ft, ft], rows))
        # EXPLAIN ANALYZE: run with a RuntimeStatsColl wired through the
        # executor tree (reference: util/execdetails + executor/explain.go)
        from ..executor import build_executor
        from ..executor.execdetails import RuntimeStatsColl, _fmt_bytes
        from ..planner.logical import explain_nodes
        coll = RuntimeStatsColl()
        exe = build_executor(plan, self._exec_ctx(), stats=coll)
        exe.execute()
        rows = []
        for name, info, node in explain_nodes(plan):
            if coll.has(node):
                st = coll.get(node)
                act = str(st.rows) if st.loops else "-"
                einfo = st.exec_info()
                mem = _fmt_bytes(st.mem_bytes) if st.mem_bytes else "N/A"
            else:
                act, einfo, mem = "-", "-", "N/A"
            rows.append((name.encode(), act.encode(), einfo.encode(),
                         info.encode(), mem.encode()))
        out = Chunk.from_rows([ft] * 5, rows)
        return Result(names=["id", "actRows", "execution info",
                             "operator info", "memory"], chunk=out)

    def _exec_trace(self, stmt: ast.TraceStmt) -> Result:
        """TRACE [FORMAT='row'|'json'] <stmt> — run the statement under a
        FORCED lifecycle trace (session/tracing.py, sampling-independent)
        and render its span tree: the statement root, plan/build/run,
        and every resilience-layer chokepoint the execution crossed —
        admission, compile service (with mode), supervisor deadline,
        device dispatch, backoff sleeps, residency evictions (reference:
        executor/trace.go:50 + util/tracing).  FORMAT='opt' keeps the
        optimizer rule trace."""
        inner = stmt.stmt
        if stmt.format == "opt" and isinstance(
                inner, (ast.SelectStmt, ast.SetOprStmt)):
            return self._exec_opt_trace(inner)
        tr = tracing.active()
        if tr is None:
            # always-on: a TRACE statement never depends on the sampler
            tr = tracing.begin("statement", origin="trace_stmt",
                               conn_id=self.conn_id,
                               stmt=type(inner).__name__)
        succ = False
        try:
            with tracing.span("statement.dispatch"):
                self._dispatch(inner)
            succ = True
        finally:
            # finish UNCONDITIONALLY before rendering: when the sampler
            # already traced this TRACE statement, rendering the live
            # trace would show a '-' root duration and a succ flag that
            # can never be false.  finish() is idempotent, so the
            # statement loop's own finish in _execute_stmt stays a no-op
            tracing.finish(tr, succ=succ)
        ft = FieldType(tp=TYPE_VARCHAR)
        if stmt.format == "json":
            payload = json.dumps(tr.to_dict(), default=str)
            return Result(names=["trace"],
                          chunk=Chunk.from_rows([ft], [(payload.encode(),)]))
        rows = [(op.encode(), start.encode(), dur.encode())
                for op, start, dur in tracing.tree_rows(tr)]
        return Result(names=["operation", "startTS", "duration"],
                      chunk=Chunk.from_rows([ft, ft, ft], rows))

    def _exec_analyze(self, stmt: ast.AnalyzeTableStmt) -> Result:
        """Collect basic stats (reference: executor/analyze.go; histograms
        and sketches land with the stats module)."""
        from ..statistics import analyze_table
        for tn in stmt.tables:
            db = tn.schema or self.current_db()
            info = self.infoschema().table_by_name(db, tn.name)
            analyze_table(self, info)
        return Result()

    def _exec_admin(self, stmt: ast.AdminStmt) -> Result:
        if stmt.kind == "show_telemetry":
            # what WOULD be reported; collection never egresses (reference:
            # ADMIN SHOW TELEMETRY, executor/telemetry.go)
            from .. import telemetry as _tel
            ft_s = FieldType(tp=TYPE_VARCHAR)
            payload = self.domain.telemetry.preview()
            status = b"enabled" if _tel.enabled(self.domain) else b"disabled"
            return Result(names=["TRACKING_ID", "LAST_STATUS", "DATA_PREVIEW"],
                          chunk=Chunk.from_rows(
                              [ft_s, ft_s, ft_s],
                              [(b"local-only", status, payload.encode())]))
        if stmt.kind == "checksum_table":
            # order-independent table checksum over record KVs (reference:
            # distsql.Checksum + executor/checksum.go; XOR of per-kv crcs
            # commutes, so partition/scan order never matters)
            import zlib
            from .. import tablecodec
            ft_s = FieldType(tp=TYPE_VARCHAR)
            ft_i = FieldType(tp=TYPE_LONGLONG)
            rows = []
            txn = self.store.begin()
            try:
                for tn in stmt.tables:
                    db = tn.schema or self.current_db()
                    info = self.infoschema().table_by_name(db, tn.name)
                    phys = ([d.id for d in info.partition.defs]
                            if info.partition is not None else [info.id])
                    acc = 0
                    n_kvs = 0
                    n_bytes = 0
                    for pid in phys:
                        start, end = tablecodec.table_range(pid)
                        for k, v in txn.scan(start, end):
                            acc ^= zlib.crc32(v, zlib.crc32(k))
                            n_kvs += 1
                            n_bytes += len(k) + len(v)
                    rows.append((db.encode(), tn.name.encode(), acc,
                                 n_kvs, n_bytes))
            finally:
                txn.rollback()
            return Result(names=["Db_name", "Table_name", "Checksum_crc64_xor",
                                 "Total_kvs", "Total_bytes"],
                          chunk=Chunk.from_rows(
                              [ft_s, ft_s, ft_i, ft_i, ft_i], rows))
        if stmt.kind == "show_ddl_jobs":
            txn = self.store.begin()
            try:
                m = Meta(txn)
                jobs = m.history_jobs()[-20:]
                jobs.reverse()
            finally:
                txn.rollback()
            from ..model import JobState, SchemaState
            ft_i = FieldType(tp=TYPE_LONGLONG)
            ft_s = FieldType(tp=TYPE_VARCHAR)
            rows = [(j.id, j.type.encode(),
                     SchemaState.NAMES.get(j.schema_state, "?").encode(),
                     j.schema_id, j.table_id, j.row_count,
                     JobState.NAMES.get(j.state, "?").encode())
                    for j in jobs]
            chunk = Chunk.from_rows([ft_i, ft_s, ft_s, ft_i, ft_i, ft_i, ft_s], rows)
            return Result(names=["job_id", "job_type", "schema_state",
                                 "schema_id", "table_id", "row_count", "state"],
                          chunk=chunk)
        if stmt.kind == "check_table":
            from ..executor.admin import check_table
            for tn in stmt.tables:
                db = tn.schema or self.current_db()
                info = self.infoschema().table_by_name(db, tn.name)
                check_table(self, info)
            return Result()
        if stmt.kind == "check_index":
            from ..executor.admin import check_index
            tn = stmt.tables[0]
            db = tn.schema or self.current_db()
            info = self.infoschema().table_by_name(db, tn.name)
            check_index(self, info, stmt.index_name)
            return Result()
        if stmt.kind == "compile":
            # ADMIN COMPILE: background-compile the geometric bucket
            # ladder for every hot fragment recipe and WAIT, so the
            # statement returns a final count (executor/compile_service)
            from ..executor import compile_service
            rep = compile_service.prewarm(ctx=self, wait=True)
            ft_i = FieldType(tp=TYPE_LONGLONG)
            return Result(
                names=["submitted", "prewarmed", "failed"],
                chunk=Chunk.from_rows(
                    [ft_i, ft_i, ft_i],
                    [(rep["submitted"], rep["prewarmed"], rep["failed"])]))
        raise TiDBError(f"unsupported ADMIN {stmt.kind}")

    def _exec_execute(self, stmt: ast.ExecuteStmt) -> Result:
        sql = self.prepared.get(stmt.name)
        if sql is None:
            raise TiDBError(f"Unknown prepared statement handler ({stmt.name})")
        params = []
        for uv in stmt.using:
            params.append(self.user_vars.get(uv))
        inner = self.parser.parse(sql)
        if len(inner) != 1:
            raise TiDBError("prepared statement must be a single statement")
        self._expr_ctx.params = params
        try:
            return self._dispatch(inner[0])
        finally:
            self._expr_ctx.params = None


BOOTSTRAP_VERSION = 3  # v2: grant tables; v3: mysql.db grant_priv column


def bootstrap_domain(store=None) -> Domain:
    """reference: session.BootstrapSession (session.go:2566) — creates system
    databases, the grant tables + root user, and marks the bootstrap
    version (versioned like bootstrap.go's upgrade chain)."""
    from ..kv import new_store
    if store is None:
        store = new_store()
    txn = store.begin()
    m = Meta(txn)
    ver = m.bootstrapped()
    if ver >= BOOTSTRAP_VERSION:
        txn.rollback()
        d = Domain(store)
        d.priv.load()
        return d
    if ver < 1:
        for db_name in ("mysql", "test"):
            db = DBInfo(id=m.gen_global_id(), name=db_name)
            m.create_database(db)
        m.bump_schema_version()
        # mark v1 with the same txn: a crash before v2 completes must not
        # re-run this step (create_database dedups by id, not name)
        m.set_bootstrapped(1)
    txn.commit()
    d = Domain(store)
    if ver < 2:
        # grant tables + root@% with all privileges (bootstrap.go:1739).
        # The bootstrap version is only marked AFTER this succeeds: a crash
        # mid-way re-runs the (idempotent) step instead of permanently
        # skipping it and silently disabling the privilege system
        from ..privilege import BOOTSTRAP_SQL, ROOT_ROW
        s = Session(d)
        s._internal = 1
        try:
            for sql in BOOTSTRAP_SQL:
                s.execute(sql)
            if not s.execute("select 1 from mysql.user where user = 'root'"
                             )[-1].rows:
                s.execute(ROOT_ROW)
        finally:
            s.close()
    elif ver < 3:
        # v3 upgrade: db-scoped grant option column (versioned upgrade
        # chain, reference: bootstrap.go upgradeToVerNN)
        s = Session(d)
        s._internal = 1
        try:
            info = d.infoschema().table_by_name("mysql", "db")
            if info is not None and info.find_column("grant_priv") is None:
                s.execute("alter table mysql.db add column "
                          "grant_priv varchar(1) default 'N'")
        finally:
            s.close()
    txn = store.begin()
    try:
        Meta(txn).set_bootstrapped(BOOTSTRAP_VERSION)
        txn.commit()
    except Exception:
        txn.rollback()
        raise
    d.priv.load()
    d.load_stats()
    return d


def new_session(domain: Domain | None = None) -> Session:
    if domain is None:
        domain = bootstrap_domain()
    return Session(domain)
