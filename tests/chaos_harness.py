"""Seeded chaos harness: a query corpus under deterministic random fault
schedules (reference: the failpoint-driven chaos suites wired through 103
files of the reference codebase; Jepsen-style invariants, in-process).

Every run is driven by ONE integer seed: `random.Random(seed)` picks which
failpoints fire, with which actions, against which query and engine.  The
contract asserted for every operation:

  * a read either matches the fault-free golden result BIT-FOR-BIT, or
    fails with a CLEAN CLASSIFIED error (TiDBError with a code, or the
    injected FailpointError itself) — never a hang, never a silently
    wrong result;
  * a write either commits fully or not at all — the transfer invariant
    (SUM over the ledger is constant) holds after every fault;
  * the cluster recovers: after `failpoint.disable_all()` the corpus
    runs fault-free and exact again.

Usage:  run_seed(seed) -> dict of counters; raises AssertionError on any
invariant violation.  tests/test_chaos.py drives a fixed-seed smoke in
tier-1 and a deeper sweep (CHAOS_SEEDS=n, marked slow) locally.

Both modes inject synthetic HBM exhaustion (`device-upload-oom` with
`oom`/`N*oom` actions): a transient OOM must be absorbed by the
evict-all → retry ladder and a persistent one must degrade to the host
engine (ops/residency.py) — reads stay exact either way, and the
residency byte ledger must show ZERO drift afterwards.

THREADED MODE (`run_threaded_seed`): N worker threads issue concurrent
queries + transfer DML against ONE Domain while a seeded schedule flips
failpoints — including backend-HANG injection (sleep actions under a
small `tidb_device_call_timeout`, exercising the device-runtime
supervisor) and HBM-OOM injection interleaving with the hangs and DML —
closing the ROADMAP "multi-core interleaving fuzzing"
item.  Interleavings are nondeterministic, so the contract is
INVARIANT-ONLY (no bit-for-bit goldens):

  * every operation either succeeds or fails with a CLEAN classified
    error — never an unclassified exception, never a wedge;
  * ledger atomicity: SUM(bal) reads 1000 in every successful snapshot;
  * no leaked failpoints once the threads join;
  * no stuck threads (bounded joins) and no abandoned device calls left
    outstanding after the grace window;
  * breaker-state sanity, and the corpus runs clean on the quiesced
    domain (the process survives and recovers);
  * admission hygiene (executor/scheduler.py): injected queue-full
    refusals degrade reads to the host engine EXACTLY, injected
    admission stalls are absorbed as queue wait, and every ticket is
    completed, degraded or cleanly rejected — the queue drains to zero
    once the schedule ends (both modes assert `verify_drained`).
"""

from __future__ import annotations

import contextlib
import random
import threading
import time

from tidb_tpu.errors import TiDBError
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.failpoint import FailpointError

#: wall-clock ceiling for any single chaos operation — the "never a hang"
#: invariant made checkable (budgeted backoff keeps real runs far below)
OP_TIMEOUT_S = 60.0

# -- fixed, deterministic workload -----------------------------------------

N_ROWS = 384  # small enough to stay fast, large enough to group/join

QUERIES = [
    # fused scan→filter→agg (the device/MPP fragment shape)
    "select grp, sum(val), count(*) from t1 group by grp order by grp",
    "select grp, min(val), max(val) from t1 where val % 3 = 0 "
    "group by grp order by grp",
    # join + agg (device join fragment / broadcast MPP shape)
    "select t1.grp, sum(t2.amt) from t1 join t2 on t1.id = t2.ref "
    "group by t1.grp order by t1.grp",
    # window over partition
    "select id, rank() over (partition by grp order by val) from t1 "
    "where id < 40 order by id",
    # plain row reads
    "select id, val from t1 where grp = 3 order by id",
    "select count(*) from t1 join t2 on t1.id = t2.ref where t2.amt > 50",
]

ENGINES = ["auto", "host", "tpu", "tpu-mpp"]

#: read-path fault catalog: failpoint name -> candidate actions.  N*panic
#: actions are TRANSIENT (retries should absorb them); plain panic is
#: PERSISTENT (the run must degrade or fail classified — never hang).
READ_FAULTS = {
    "device-agg-exec": ["panic", "1*panic", "2*panic"],
    # synthetic HBM RESOURCE_EXHAUSTED at the upload boundary: transient
    # (N*oom) must be absorbed by the evict-all → retry ladder, persistent
    # (oom) must degrade to the host engine — either way the read stays
    # EXACT (ops/residency.py + device_exec.run_device)
    "device-upload-oom": ["oom", "1*oom", "2*oom"],
    # serving admission (executor/scheduler.py): a refused ticket must
    # degrade the fragment to the host engine (exact result, classified),
    # an injected admission stall must be absorbed as queue wait — and
    # the queue must drain to zero by seed end (asserted below)
    "device-admission": ["admission-queue-full", "1*admission-wait(0.05)",
                         "2*admission-wait(0.02)"],
    # compile service (executor/compile_service.py): an injected compile
    # failure must degrade the fragment to the host engine (exact result,
    # classified — the compile breaker, not the fragment breakers,
    # absorbs it), a compile stall is absorbed as build time — and no
    # compile job may leak (compile_service.verify_drained below)
    "device-compile": ["compile-fail", "1*compile-fail", "2*compile-fail",
                       "1*compile-slow(0.02)"],
    # hybrid-join spill writes (storage/paged.SpillSet via
    # executor/hybrid_join.py): an injected spill failure mid-join must
    # degrade the fragment classified with NO spilled pages left on disk
    # (spill_outstanding drained below) and no ledger drift
    "device-join-spill": ["spill-fail", "1*spill-fail"],
    "mpp-exchange-send": ["1*panic", "2*panic", "panic"],
    "mpp-exchange-recv": ["1*panic", "panic"],
    "coordinator-tso-skew": ["return(262144)"],
    "coordinator-campaign-loss": ["return(1)"],
    # a held lease lapsing out from under its owner: the next campaign
    # (any holder) wins and the watchers re-notify — reads must stay
    # exact through the ownership churn
    "coordinator-lease-expire": ["return(1)"],
    "coordinator-heartbeat-lost": ["return(1)"],
    # versioned result cache (fabric/dedup.claim_versioned): skip the
    # claim-time version-vector check once, deliberately serving a
    # version-STALE page into the in-page verify — which must refuse it
    # loudly (cache_stale_reads bumps, local recompute) so the read
    # stays exact; a silent wrong answer here is the one unforgivable
    # cache failure (tests/test_result_cache.py pins the refusal)
    "cache-stale-read": ["1*return(1)", "2*return(1)"],
}

#: write-path fault catalog: 2PC crash windows + WAL failure windows
#: (the chaos store IS durable — see _durable_kit below — so these hit
#: the real append/fsync path: a torn append (`1*return(torn)` writes
#: half a frame, heals and fails the commit) or a failed fsync must
#: roll the txn back CLEANLY, and the end-of-seed recovery-equivalence
#: check proves the log agrees with the live store)
WRITE_FAULTS = {
    "txn-before-prewrite": ["1*panic", "panic"],
    "txn-after-prewrite": ["1*panic", "panic"],
    "txn-before-commit": ["1*panic", "panic"],
    "wal-append-torn": ["1*panic", "1*return(torn)"],
    "wal-fsync-fail": ["1*panic"],
}

#: FLEET-mode fault catalog (process-level faults — these cannot run in
#: the in-process modes above, which ARE the process they would kill):
#: bench_serve's --procs chaos passes them to individual workers via
#: spawn env (TIDB_TPU_FABRIC_FAILPOINTS), seeded by the same rng
#: discipline.  `fabric-kill-worker` with a truthy return payload
#: SIGKILLs the worker MID-QUERY (tidb_tpu/fabric/worker.py); the
#: invariants are the fleet's: parent respawn within the backoff budget,
#: coordination-segment lease reclaim with zero orphaned running
#: counts, a clean classified connection error at the client, and
#: survivors serving throughout (tests/test_fabric.py + bench_serve
#: fleet smoke).
FLEET_FAULTS = {
    "fabric-kill-worker": ["1*return(1)", "2*return(1)"],
    # consistency-contract faults (kv/shared_store.fresh_read_ts):
    # `tail-lag` delays the WAL tailer's apply loop — a reader behind a
    # peer's acked commit must BLOCK on the fleet frontier (bounded
    # freshnessWait budget), never serve a value older than its
    # snapshot's frontier; `frontier-stall` freezes this worker's
    # frontier publication — peers keep reading (the heartbeat
    # republish repairs it), and any wait that exhausts the budget
    # must refuse LOUDLY (FreshnessWaitError 9011) / downgrade to an
    # explicit stale_ok, never answer silently stale
    # (bench_oltp.py asserts read-your-peers'-writes every round)
    "tail-lag": ["sleep(0.05)", "1*sleep(0.2)"],
    "frontier-stall": ["return(1)", "1*return(1)"],
    # stall the leased DDL owner mid-job past the lease timeout: a
    # sibling claims the cell at a newer epoch and the stalled owner's
    # commit-point fence must abort its txn (LeaseExpiredError 8229,
    # tests/test_consistency.py pins the failover)
    "ddl-mid-job": ["1*sleep(2.5)"],
    # kill-at-stage process deaths for the durable store (a `kill`
    # payload SIGKILLs the worker AT the WAL/2PC stage; recovery on
    # respawn must show committed-visible / uncommitted-gone, torn
    # tails CRC-truncated — tests/test_wal.py runs the full matrix,
    # tests/test_fabric.py loops it against a live 4-worker fleet)
    "wal-append-torn": ["1*return(kill)"],
    "wal-fsync-fail": ["1*return(kill)"],
    "store-recover-replay": ["1*return(kill)"],
    "txn-before-commit": ["1*return(kill)"],
    "txn-after-prewrite": ["1*return(kill)"],
    "txn-before-prewrite": ["1*return(kill)"],
}

#: HOST-mode fault catalog (whole-host faults — a step above
#: FLEET_FAULTS: `fabric-kill-host` with a truthy payload SIGKILLs the
#: worker's entire simulated-host PROCESS GROUP mid-query, i.e. every
#: worker the host was running dies at once).  Only bench_serve's
#: multi-host failover mode (--hosts N) may inject it, via spawn env on
#: a fleet started with hosts>1 — each simulated host gets a private
#: process group (fleet.Fleet._popen_worker) so the killpg can never
#: reach the bench itself; in-process seeds cannot run it for the same
#: reason FLEET_FAULTS are bench-only.  The invariants are region
#: failover's: surviving hosts claim the dead host's expired region
#: leases within the lease budget, restore checkpoint+tail from the
#: blob store, and every acked row stays readable fleet-wide
#: (bench_serve.run_failover + tests/test_serve.py).
HOST_FAULTS = {
    "fabric-kill-host": ["1*return(1)"],
}


def _setup(tk: TestKit):
    tk.must_exec("use test")
    tk.must_exec("create table t1 (id int primary key, grp int, val int, "
                 "s varchar(16))")
    tk.must_exec("create table t2 (id int primary key, ref int, amt int)")
    rows1 = ",".join(f"({i},{i % 7},{(i * 37) % 101},'s{i % 11}')"
                     for i in range(N_ROWS))
    rows2 = ",".join(f"({i},{(i * 3) % N_ROWS},{(i * 13) % 97})"
                     for i in range(N_ROWS))
    tk.must_exec(f"insert into t1 values {rows1}")
    tk.must_exec(f"insert into t2 values {rows2}")
    # the transfer ledger for write-atomicity checks
    tk.must_exec("create table ledger (acct int primary key, bal int)")
    tk.must_exec("insert into ledger values (1, 500), (2, 500)")
    # any lock orphaned by an injected crash must surface fast, not eat
    # the schedule's wall clock (the "never a hang" invariant)
    tk.must_exec("set innodb_lock_wait_timeout = 2")


def _goldens(tk: TestKit) -> list:
    """Fault-free reference results, host engine (always-correct path)."""
    tk.must_exec("set tidb_executor_engine = 'host'")
    out = [tuple(map(tuple, tk.must_query(q).rows)) for q in QUERIES]
    tk.must_exec("set tidb_executor_engine = 'auto'")
    return out


def _is_clean(err: Exception) -> bool:
    """A *classified* failure: carries an error code or is the injected
    fault itself.  Anything else (KeyError, AssertionError, ...) is a bug
    the harness must surface."""
    return isinstance(err, (TiDBError, FailpointError))


def _durable_kit():
    """A TestKit over a WAL-backed durable store (kv/shared_store.py):
    the write-fault catalog's wal-* failpoints hit the REAL append /
    fsync path, and _assert_recovery_equivalent can prove at seed end
    that a crash at that instant would lose nothing committed.
    Returns (kit, wal_dir)."""
    import tempfile
    from tidb_tpu.kv import new_store
    from tidb_tpu.session import bootstrap_domain
    wal_dir = tempfile.mkdtemp(prefix="chaos-wal-")
    store = new_store(wal_dir=wal_dir)
    return TestKit(bootstrap_domain(store)), wal_dir


def _assert_recovery_equivalent(tk: TestKit, wal_dir: str, seed: int):
    """THE durability invariant: open a SECOND store on the same WAL
    dir (exactly what a post-SIGKILL restart would do — checkpoint +
    tail replay + CRC truncation) and compare a full live-range scan at
    one snapshot ts against the serving store.  Bit-for-bit equal means
    the log is a faithful journal of everything the store acked."""
    from tidb_tpu.kv import new_store
    live = tk.domain.store
    ts = live.next_ts()
    live_rows = live.get_snapshot(ts).scan(b"", b"")
    recovered = new_store(wal_dir=wal_dir)
    try:
        rec_rows = recovered.get_snapshot(ts).scan(b"", b"")
    finally:
        recovered.close()
    assert rec_rows == live_rows, (
        f"seed {seed}: RECOVERY DIVERGENCE: replayed store has "
        f"{len(rec_rows)} live rows vs {len(live_rows)} in the serving "
        "store — the WAL is not a faithful journal")


def _assert_region_invariants(seed: int):
    """The REGION layer's drain + replication invariants, exercised
    per-seed at the end of both chaos modes: a seeded mini region fleet
    (sharded keyspace over a blob store) must survive a simulated host
    loss — the survivor claims the expired leases, restores
    checkpoint+tail from blobs alone, serves bit-equal data, and fences
    the zombie — then drain clean: no orphaned region lease in the
    coordination segment, and every MANIFEST in the blob store agrees
    with the sealed bytes it references (verify_region_invariants)."""
    import os
    import shutil
    import tempfile
    from tidb_tpu.fabric.blob import LocalDirBlobStore
    from tidb_tpu.fabric.coord import Coordinator
    from tidb_tpu.fabric.region import RegionEpochError, RegionStore, \
        verify_region_invariants
    rng = random.Random(seed ^ 0x5EED)
    root = tempfile.mkdtemp(prefix="chaos-region-")
    coord = Coordinator.create(os.path.join(root, "coord"),
                               nregions=rng.choice([2, 4, 8]))
    try:
        blob = LocalDirBlobStore(os.path.join(root, "blob"))
        coord.claim_slot(0)
        dead = RegionStore(os.path.join(root, "h0"), coord, 0, blob=blob)
        dead.open_regions()
        rows = {rng.randrange(1 << 32).to_bytes(8, "big"):
                b"v%d" % i for i in range(24)}
        for k, v in rows.items():
            dead.raw_put(k, v)
        dead.replicate()
        ts = dead.tso.next_ts()
        before = dead.scan(b"", b"", ts)
        # host 0 "dies": a survivor (lease budget already elapsed from
        # its point of view) fails every region over from the blob
        # store alone and must serve the identical snapshot
        coord.claim_slot(1)
        surv = RegionStore(os.path.join(root, "h1"), coord, 1,
                           blob=blob, lease_timeout_s=0.0)
        took = surv.failover_expired()
        assert took, f"seed {seed}: survivor claimed no expired regions"
        after = surv.scan(b"", b"", ts)
        assert after == before, (
            f"seed {seed}: REGION FAILOVER DIVERGENCE: survivor serves "
            f"{len(after)} rows vs {len(before)} pre-failover")
        # the dead host's appender is a zombie now: epoch-fenced
        try:
            dead.raw_put(next(iter(rows)), b"zombie")
            raise AssertionError(
                f"seed {seed}: zombie write into a failed-over region "
                "was NOT fenced")
        except RegionEpochError:
            pass
        dead.close()   # replicate skips fenced regions (no clobber)
        surv.close()
        coord.release_slot(0)
        coord.release_slot(1)
        inv = verify_region_invariants(coord, blob)
        assert inv["ok"], (
            f"seed {seed}: REGION INVARIANT VIOLATION: {inv}")
        drained = coord.verify_drained()
        assert drained["ok"], (
            f"seed {seed}: region coordinator not drained: {drained}")
    finally:
        with contextlib.suppress(Exception):
            coord.unlink()
        with contextlib.suppress(Exception):
            coord.close()
        shutil.rmtree(root, ignore_errors=True)


def run_seed(seed: int, n_ops: int = 10) -> dict:
    """One deterministic chaos schedule; returns counters for reporting."""
    rng = random.Random(seed)
    # fresh embedded cluster (no cross-seed contamination), DURABLE:
    # the 2PC/WAL write faults hit the real commit path and the seed
    # ends with a crash-equivalent recovery comparison
    tk, wal_dir = _durable_kit()
    failpoint.disable_all()
    stats = {"exact": 0, "clean_errors": 0, "writes_ok": 0,
             "writes_failed": 0}
    try:
        _setup(tk)
        goldens = _goldens(tk)

        # fast breaker so the schedule can see a full open→probe cycle
        tk.must_exec("set global tidb_device_circuit_threshold = 3")
        tk.must_exec("set global tidb_device_circuit_cooldown = 0.05")

        for _op in range(n_ops):
            qi = rng.randrange(len(QUERIES))
            engine = rng.choice(ENGINES)
            # ~1/3 of ops run with span tracing sampled (the recorder
            # rides every failure path; the drain invariant below then
            # actually bites)
            tk.must_exec("set tidb_trace_sampling_rate = "
                         + ("1" if rng.random() < 0.34 else "0"))
            # 1-2 simultaneous faults from the read catalog
            names = rng.sample(sorted(READ_FAULTS), k=rng.choice([1, 1, 2]))
            tk.must_exec(f"set tidb_executor_engine = '{engine}'")
            for name in names:
                failpoint.enable(name, rng.choice(READ_FAULTS[name]))
            t0 = time.monotonic()
            try:
                rows = tuple(map(tuple, tk.must_query(QUERIES[qi]).rows))
            except Exception as e:  # noqa: BLE001 — the assertion IS the point
                assert _is_clean(e), (
                    f"seed {seed}: unclassified failure {type(e).__name__}: "
                    f"{e} (faults {failpoint.list_active()})")
                stats["clean_errors"] += 1
            else:
                assert rows == goldens[qi], (
                    f"seed {seed}: WRONG RESULT under faults "
                    f"{failpoint.list_active()} engine={engine} "
                    f"query={QUERIES[qi]!r}")
                stats["exact"] += 1
            finally:
                failpoint.disable_all()
            assert time.monotonic() - t0 < OP_TIMEOUT_S, (
                f"seed {seed}: op exceeded {OP_TIMEOUT_S}s — hang-adjacent")

        # -- write atomicity under 2PC crash windows -----------------------
        tk.must_exec("set tidb_executor_engine = 'auto'")
        for _w in range(4):
            name = rng.choice(sorted(WRITE_FAULTS) + [None])
            if name is not None:
                failpoint.enable(name, rng.choice(WRITE_FAULTS[name]))
            amt = rng.randrange(1, 50)
            try:
                tk.must_exec("begin")
                tk.must_exec(
                    f"update ledger set bal = bal - {amt} where acct = 1")
                tk.must_exec(
                    f"update ledger set bal = bal + {amt} where acct = 2")
                tk.must_exec("commit")
                stats["writes_ok"] += 1
            except Exception as e:  # noqa: BLE001
                assert _is_clean(e), (
                    f"seed {seed}: unclassified write failure "
                    f"{type(e).__name__}: {e}")
                stats["writes_failed"] += 1
                try:
                    tk.session.rollback()
                except Exception:
                    pass
            finally:
                failpoint.disable_all()
            total = tk.must_query(
                "select sum(bal) from ledger").rows[0][0]
            assert str(total) == "1000", (
                f"seed {seed}: ATOMICITY VIOLATION after {name}: "
                f"ledger sum {total} != 1000")

        # -- recovery: fault-free corpus is exact again --------------------
        for qi, q in enumerate(QUERIES):
            rows = tuple(map(tuple, tk.must_query(q).rows))
            assert rows == goldens[qi], (
                f"seed {seed}: no recovery after faults cleared: {q!r}")

        # -- HBM residency ledger: no budget-counter drift -----------------
        from tidb_tpu.ops import residency
        led = residency.verify_ledger()
        assert led["ok"], (
            f"seed {seed}: HBM LEDGER DRIFT after OOM chaos: {led}")

        # -- admission queue drained: every ticket completed, degraded or
        #    cleanly rejected — no leaked tickets once the schedule ends
        from tidb_tpu.executor import scheduler
        drained = scheduler.verify_drained()
        assert drained["ok"], (
            f"seed {seed}: LEAKED ADMISSION TICKETS: {drained}")

        # -- compile jobs drained: every background compile submitted by
        #    the schedule is accounted completed, failed or discarded —
        #    no job leaked in flight (mirrors the ticket invariant)
        from tidb_tpu.executor import compile_service
        compile_service.wait_idle(timeout_s=10.0)
        cdrained = compile_service.verify_drained()
        assert cdrained["ok"], (
            f"seed {seed}: LEAKED COMPILE JOBS: {cdrained}")

        # -- span traces drained: every trace begun (sampled statements,
        #    TRACE, bg-compile children) was finished — no trace object
        #    left holding span refs after the schedule ends
        from tidb_tpu.session import tracing
        tdrained = tracing.verify_drained()
        assert tdrained["ok"], (
            f"seed {seed}: LEAKED TRACES: {tdrained}")

        # -- hybrid-join spill pages drained: an injected spill failure
        #    (or any abort mid-probe) must delete every partition page
        from tidb_tpu.storage.paged import spill_outstanding
        sp = spill_outstanding()
        assert sp["open_sets"] == 0, (
            f"seed {seed}: LEAKED SPILL PAGES: {sp}")

        # -- durability: a crash RIGHT NOW would lose nothing — reopen
        #    the WAL dir (checkpoint + tail replay + CRC truncation)
        #    and require bit-for-bit equality with the serving store
        _assert_recovery_equivalent(tk, wal_dir, seed)

        # -- region layer: a seeded mini region fleet must fail over a
        #    dead host from the blob store alone, fence the zombie, and
        #    drain with no orphaned region lease and every blob MANIFEST
        #    matching its sealed bytes
        _assert_region_invariants(seed)
    finally:
        failpoint.disable_all()
        with contextlib.suppress(Exception):
            tk.domain.store.close()
        import shutil
        with contextlib.suppress(OSError):
            shutil.rmtree(wal_dir)
    return stats


# -- threaded mode -----------------------------------------------------------

#: read-path fault catalog for the threaded mode: adds the join/MPP
#: fragment hooks and HANG actions (sleep under a small
#: tidb_device_call_timeout → DeviceHangError through the supervisor)
THREADED_FAULTS = {
    # WAL write faults under concurrency: group-commit waiters racing a
    # torn/failed append must all fail classified (or absorb a
    # transient), the ledger stays atomic, and the recovery-equivalence
    # check after the joins must still hold
    "wal-append-torn": ["1*panic", "1*return(torn)"],
    "wal-fsync-fail": ["1*panic"],
    "device-agg-exec": ["panic", "1*panic", "sleep(0.05)"],
    "device-join-exec": ["panic", "1*panic", "sleep(0.05)"],
    "device-mpp-exec": ["1*panic", "sleep(0.05)"],
    # HBM OOM interleaving with hangs and DML: concurrent evict-all /
    # retry / host-degradation must keep the residency byte ledger
    # drift-free (checked after the joins below)
    "device-upload-oom": ["oom", "1*oom", "2*oom"],
    # admission refusals/stalls interleaving with hangs, OOM and DML:
    # tickets must never leak (verify_drained asserted after the joins)
    "device-admission": ["admission-queue-full", "1*admission-wait(0.05)",
                         "2*admission-wait(0.02)"],
    # compile failures/stalls interleaving with hangs, OOM and DML: the
    # fragment degrades to host classified, and no compile job may leak
    # (compile_service.verify_drained asserted after the joins)
    "device-compile": ["compile-fail", "1*compile-fail",
                       "1*compile-slow(0.02)"],
    # spill-write failures interleaving with the rest: the hybrid join
    # aborts classified and drains its pages (spill_outstanding below)
    "device-join-spill": ["spill-fail", "1*spill-fail"],
    "mpp-exchange-send": ["1*panic", "panic"],
    "mpp-exchange-recv": ["1*panic"],
    "coordinator-tso-skew": ["return(262144)"],
    "coordinator-lease-expire": ["return(1)"],
    "coordinator-heartbeat-lost": ["return(1)"],
    "txn-before-prewrite": ["1*panic"],
    "txn-after-prewrite": ["1*panic"],
    "txn-before-commit": ["1*panic"],
    # freshness faults under concurrency (inert against the solo-durable
    # kit — catch_up/publish return before the inject without a
    # coordinator — but live in any fleet-attached in-process store;
    # the full cross-worker semantics run under FLEET_FAULTS in
    # bench_oltp / the bench_serve fleet smoke)
    "tail-lag": ["1*sleep(0.05)"],
    "frontier-stall": ["1*return(1)"],
}

#: join budget per worker thread — a thread alive past this is STUCK
THREAD_JOIN_TIMEOUT_S = 120.0


def run_threaded_seed(seed: int, n_threads: int = 4,
                      n_ops: int = 8) -> dict:
    """One seeded concurrent chaos schedule (invariant-only checks; see
    the module docstring).  Returns aggregate counters."""
    from tidb_tpu.executor import supervisor

    tk, wal_dir = _durable_kit()
    failpoint.disable_all()
    _setup(tk)
    # fast breaker + a visible half-open cycle under contention
    tk.must_exec("set global tidb_device_circuit_threshold = 3")
    tk.must_exec("set global tidb_device_circuit_cooldown = 0.05")
    sup_before = supervisor.snapshot()

    stats = {"reads_ok": 0, "clean_errors": 0, "writes_ok": 0,
             "writes_failed": 0, "ledger_checks": 0}
    mu = threading.Lock()
    violations: list = []
    start = threading.Barrier(n_threads)

    def bump(key, n=1):
        with mu:
            stats[key] += n

    def violate(tid, what, exc=None):
        with mu:
            violations.append(
                f"seed {seed} thread {tid}: {what}"
                + (f" ({type(exc).__name__}: {exc})" if exc else ""))

    def worker(tid):
        try:
            _worker_body(tid)
        except Exception as e:  # noqa: BLE001 — a dead worker IS a finding
            violate(tid, "worker thread died", e)

    def _worker_body(tid):
        rng = random.Random((seed << 8) ^ tid)
        wtk = tk.new_session()
        wtk.must_exec("use test")
        wtk.must_exec("set innodb_lock_wait_timeout = 2")
        start.wait(timeout=30)
        for _op in range(n_ops):
            engine = rng.choice(ENGINES)
            wtk.must_exec(f"set tidb_executor_engine = '{engine}'")
            # half the ops run supervised with a deadline SMALLER than the
            # injected sleep: the hang path must fire concurrently
            wtk.must_exec("set tidb_device_call_timeout = "
                          + ("0.02" if rng.random() < 0.5 else "0"))
            # a third of the ops compile ASYNC: background compile jobs
            # race the injected compile failures/stalls, hangs and DML —
            # the drain invariant below must still hold
            wtk.must_exec("set tidb_compile_async = "
                          + ("'ON'" if rng.random() < 0.35 else "'OFF'"))
            # a third of the ops run SPAN-TRACED: the recorder rides the
            # hang/OOM/admission/compile failure paths concurrently
            # (incl. bg-compile child traces), and the trace drain
            # invariant below must still hold
            wtk.must_exec("set tidb_trace_sampling_rate = "
                          + ("1" if rng.random() < 0.34 else "0"))
            names = rng.sample(sorted(THREADED_FAULTS),
                               k=rng.choice([1, 1, 2]))
            with contextlib.ExitStack() as st:
                for name in names:
                    st.enter_context(failpoint.enabled(
                        name, rng.choice(THREADED_FAULTS[name])))
                if rng.random() < 0.6:  # read op
                    q = QUERIES[rng.randrange(len(QUERIES))]
                    try:
                        wtk.must_query(q)
                        bump("reads_ok")
                    except Exception as e:  # noqa: BLE001
                        if _is_clean(e):
                            bump("clean_errors")
                        else:
                            violate(tid, f"unclassified read failure "
                                    f"on {q!r}", e)
                else:  # transfer write (both updates in acct order: no
                    #     deadlock cycles — lock waits are the chaos)
                    amt = rng.randrange(1, 40)
                    try:
                        wtk.must_exec("begin")
                        wtk.must_exec(f"update ledger set bal = bal - {amt}"
                                      " where acct = 1")
                        wtk.must_exec(f"update ledger set bal = bal + {amt}"
                                      " where acct = 2")
                        wtk.must_exec("commit")
                        bump("writes_ok")
                    except Exception as e:  # noqa: BLE001
                        if _is_clean(e):
                            bump("writes_failed")
                        else:
                            violate(tid, "unclassified write failure", e)
                        try:
                            wtk.session.rollback()
                        except Exception:
                            pass
            # ledger atomicity in THIS thread's next snapshot (host
            # engine: the invariant read must not ride the faulty path)
            try:
                wtk.must_exec("set tidb_executor_engine = 'host'")
                total = wtk.must_query(
                    "select sum(bal) from ledger").rows[0][0]
            except Exception as e:  # noqa: BLE001
                if not _is_clean(e):
                    violate(tid, "unclassified ledger read failure", e)
            else:
                bump("ledger_checks")
                if str(total) != "1000":
                    violate(tid, f"ATOMICITY VIOLATION: ledger sum {total}")

    threads = [threading.Thread(target=worker, args=(tid,), daemon=True,
                                name=f"chaos-{seed}-{tid}")
               for tid in range(n_threads)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(THREAD_JOIN_TIMEOUT_S)
        stuck = [t.name for t in threads if t.is_alive()]
        assert not stuck, (
            f"seed {seed}: STUCK THREADS after "
            f"{THREAD_JOIN_TIMEOUT_S}s: {stuck}")
        # no leaked failpoints: every enabled() context unwound
        leaked = failpoint.list_active()
        assert not leaked, f"seed {seed}: leaked failpoints {leaked}"
        assert not violations, "\n".join(violations)
    finally:
        failpoint.disable_all()

    # abandoned device calls drain: the injected hangs are short sleeps,
    # so every orphaned worker must unblock and decrement the gauge
    deadline = time.monotonic() + 10.0
    while supervisor.abandoned_calls() > 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert supervisor.abandoned_calls() == 0, (
        f"seed {seed}: {supervisor.abandoned_calls()} abandoned device "
        "calls never completed")
    stats["hangs"] = (supervisor.snapshot()["hangs"]
                      - sup_before["hangs"])

    # HBM residency ledger: concurrent upload/evict/OOM-recovery/fence
    # must leave hbm_bytes_cached consistent with the live entries (no
    # budget-counter drift) — THE invariant the residency lock exists for
    from tidb_tpu.ops import residency
    led = residency.verify_ledger()
    assert led["ok"], (
        f"seed {seed}: HBM LEDGER DRIFT after threaded OOM chaos: {led}")
    stats["oom_recoveries"] = residency.snapshot()["hbm_oom_recoveries"]

    # hybrid-join spill pages drained under concurrency: every worker's
    # spill set (incl. aborted ones) must be closed by schedule end
    from tidb_tpu.storage.paged import spill_outstanding
    sp = spill_outstanding()
    assert sp["open_sets"] == 0, (
        f"seed {seed}: LEAKED SPILL PAGES after threaded chaos: {sp}")

    # admission queue drained: no ticket left queued or running once the
    # worker threads have joined — every admit() was paired with a
    # release() or a clean classified rejection (a small grace window:
    # an abandoned supervised call can hold its ticket until it unblocks)
    from tidb_tpu.executor import scheduler
    deadline = time.monotonic() + 10.0
    while (not scheduler.verify_drained()["ok"]
           and time.monotonic() < deadline):
        time.sleep(0.01)
    drained = scheduler.verify_drained()
    assert drained["ok"], (
        f"seed {seed}: LEAKED ADMISSION TICKETS after threaded chaos: "
        f"{drained}")

    # compile jobs drained: concurrent background compiles racing the
    # injected failures/stalls must all land, fail classified, or be
    # discarded — never leak in flight (the PR 6 ticket invariant,
    # applied to the compile service)
    from tidb_tpu.executor import compile_service
    compile_service.wait_idle(timeout_s=10.0)
    cdrained = compile_service.verify_drained()
    assert cdrained["ok"], (
        f"seed {seed}: LEAKED COMPILE JOBS after threaded chaos: "
        f"{cdrained}")

    # span traces drained: every sampled statement's trace AND every
    # bg-compile child trace begun by the schedule was finished — no
    # trace object leaked holding span refs (compile wait_idle above
    # already drained the jobs whose _finish_job retires the children)
    from tidb_tpu.session import tracing
    tdrained = tracing.verify_drained()
    assert tdrained["ok"], (
        f"seed {seed}: LEAKED TRACES after threaded chaos: {tdrained}")

    # breaker-state sanity: legal state, probe slot not wedged
    for shape, br in getattr(tk.domain, "_device_breakers", {}).items():
        snap = br.snapshot()
        assert snap["state"] in ("closed", "open", "half-open"), (
            f"seed {seed}: breaker[{shape}] in bad state {snap}")

    # recovery: the quiesced domain serves the whole corpus cleanly
    tk.must_exec("set tidb_executor_engine = 'auto'")
    tk.must_exec("set tidb_device_call_timeout = 0")
    time.sleep(0.06)  # cooldowns elapse; half-open probes may close
    for q in QUERIES:
        tk.must_query(q)
    total = tk.must_query("select sum(bal) from ledger").rows[0][0]
    assert str(total) == "1000", (
        f"seed {seed}: final ledger sum {total} != 1000")

    # durability under concurrency: the log written by N racing threads
    # (group commits interleaving torn/failed appends) must replay to
    # exactly the serving store's state
    try:
        _assert_recovery_equivalent(tk, wal_dir, seed)
        # region layer invariants hold after threaded chaos too: failover
        # from blobs, zombie fencing, no orphaned lease, manifests honest
        _assert_region_invariants(seed)
    finally:
        with contextlib.suppress(Exception):
            tk.domain.store.close()
        import shutil
        with contextlib.suppress(OSError):
            shutil.rmtree(wal_dir)
    return stats
