"""Chaos harness entry points (see chaos_harness.py for the contract).

Tier-1 runs a small fixed-seed smoke; the deeper sweeps are marked
`slow` and sized by env for local runs:

    CHAOS_SEEDS=50 pytest tests/test_chaos.py -m chaos
    CHAOS_THREAD_SEEDS=20 CHAOS_THREADS=4 pytest tests/test_chaos.py \
        -m chaos_threads
"""

import os

import pytest

from chaos_harness import run_seed, run_threaded_seed

SMOKE_SEEDS = [0, 1, 2, 3]
_DEEP = int(os.environ.get("CHAOS_SEEDS", "20"))
_THREAD_DEEP = int(os.environ.get("CHAOS_THREAD_SEEDS", "20"))
_THREADS = int(os.environ.get("CHAOS_THREADS", "4"))


@pytest.mark.chaos
@pytest.mark.parametrize("seed", SMOKE_SEEDS)
def test_chaos_smoke(seed):
    """Fixed-seed tier-1 smoke: exact-or-classified under faults, ledger
    atomicity, recovery after the schedule."""
    stats = run_seed(seed)
    # the schedule must actually exercise both outcomes over the corpus
    assert stats["exact"] + stats["clean_errors"] > 0
    assert stats["writes_ok"] + stats["writes_failed"] == 4


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(len(SMOKE_SEEDS), _DEEP))
def test_chaos_sweep(seed):
    """Deeper deterministic sweep (excluded from tier-1 by `slow`)."""
    run_seed(seed)


@pytest.mark.chaos_threads
def test_threaded_chaos_smoke():
    """Fixed-seed tier-1 smoke of the CONCURRENT chaos mode: 4 threads,
    bounded ops, invariant-only checks (ledger atomicity, no leaked
    failpoints, no stuck threads, breaker sanity, recovery)."""
    stats = run_threaded_seed(0, n_threads=4, n_ops=5)
    # the schedule must actually exercise concurrency, not no-op through
    assert stats["reads_ok"] + stats["clean_errors"] > 0
    assert stats["writes_ok"] + stats["writes_failed"] > 0
    assert stats["ledger_checks"] > 0


@pytest.mark.chaos_threads
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(1, max(_THREAD_DEEP, 2)))
def test_threaded_chaos_sweep(seed):
    """Seeded concurrent sweep (≥ 20 seeds × ≥ 4 threads locally;
    excluded from tier-1 by `slow`)."""
    run_threaded_seed(seed, n_threads=max(_THREADS, 4), n_ops=8)
