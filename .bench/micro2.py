import os, sys, time
sys.path.insert(0, "/root/repo")
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
import tidb_tpu
import numpy as np, jax.numpy as jnp

n, ndv, cap = 600_000, 150_000, 262_144
rng = np.random.default_rng(0)
key = jnp.asarray(rng.integers(1, ndv+1, n))
val = jnp.asarray(rng.integers(100, 5000, n))
valf = jnp.asarray(rng.random(n))

def timeit(label, f, *a):
    f(*a)
    t0 = time.perf_counter(); r = [f(*a) for _ in range(5)]
    jax.block_until_ready(r)
    print(f"{label}: {(time.perf_counter()-t0)/5*1000:.1f} ms")

timeit("argsort unstable", jax.jit(lambda k: jnp.argsort(k, stable=False)), key)
timeit("argsort stable", jax.jit(lambda k: jnp.argsort(k, stable=True)), key)
timeit("sort only", jax.jit(lambda k: jnp.sort(k)), key)
timeit("segsum i64", jax.jit(lambda v, k: jax.ops.segment_sum(v, k, num_segments=cap)), val, key)
timeit("segsum f64", jax.jit(lambda v, k: jax.ops.segment_sum(v, k, num_segments=cap)), valf, key)
timeit("scatter add", jax.jit(lambda v, k: jnp.zeros(cap, jnp.int64).at[k].add(v)), val, key)
timeit("scatter min", jax.jit(lambda v, k: jnp.full(cap, 2**62, dtype=jnp.int64).at[k].min(v)), val, key)
timeit("scatter set", jax.jit(lambda v, k: jnp.zeros(cap, jnp.int64).at[k].set(v)), val, key)
