"""ANALYZE TABLE (reference: executor/analyze.go + statistics/builder.go).

Builds, per column: null count, NDV, min/max, TopN (most frequent values
with exact counts — reference statistics/cmsketch.go:503 TopN), and an
equal-depth histogram (bucket upper bounds + cumulative counts —
reference statistics/histogram.go:50). The whole pass is vectorized
numpy over the columnar cache (the reference samples per region; here
the column is already materialized host-side)."""

from __future__ import annotations

import numpy as np

from ..meta import Meta

HIST_BUCKETS = 64
TOPN_SIZE = 8
CM_DEPTH = 4
CM_WIDTH = 512

def _cm_indices(key) -> list[int]:
    """One 128-bit hash per value; the depth row indices derive from its
    halves (reference: cmsketch.go hashes once with murmur128 and mixes
    h1 + i*h2). Numeric keys canonicalize so int 2 and float 2.0 collide
    deliberately — query constants may arrive as either type."""
    if isinstance(key, float) and key.is_integer():
        key = int(key)
    if isinstance(key, float):
        key = key.hex()
    import hashlib
    digest = hashlib.blake2b(str(key).encode(), digest_size=16).digest()
    h1 = int.from_bytes(digest[:8], "little")
    h2 = int.from_bytes(digest[8:], "little") | 1
    return [((h1 + d * h2) & 0xFFFFFFFFFFFFFFFF) % CM_WIDTH
            for d in range(CM_DEPTH)]


def build_cmsketch(values, counts) -> list[list[int]]:
    """Count-min sketch over (distinct value, count) pairs (reference:
    statistics/cmsketch.go:46): depth×width counters; lookup takes the
    min across rows — an overestimate, never an underestimate."""
    rows = [[0] * CM_WIDTH for _ in range(CM_DEPTH)]
    for v, c in zip(values, counts):
        for d, idx in enumerate(_cm_indices(_val_key(v))):
            rows[d][idx] += int(c)
    return rows


def cm_query(cm: list[list[int]], key) -> int:
    return min(row[idx] for row, idx in zip(cm, _cm_indices(key)))


def _val_key(v):
    """JSON-able representation of an internal value for TopN matching."""
    if isinstance(v, (bytes, bytearray)):
        return v.decode("utf-8", "surrogateescape")
    if isinstance(v, (np.integer, int)):
        return int(v)
    return float(v)


def _column_stats(col):
    nn = ~col.nulls
    data = col.data[nn]
    cs = {"null_count": int(col.nulls.sum())}
    if not len(data):
        cs["ndv"] = 0
        return cs
    uniques, counts = np.unique(data, return_counts=True)
    cs["ndv"] = int(len(uniques))
    # TopN: exact counts for the most frequent values
    k = min(TOPN_SIZE, len(uniques))
    top = np.argpartition(counts, -k)[-k:]
    top = top[np.argsort(counts[top])[::-1]]
    cs["topn"] = [[_val_key(uniques[i]), int(counts[i])] for i in top]
    # CMSketch over the non-TopN remainder: point estimates for values the
    # TopN missed (reference: cmsketch.go TopN+CMSketch split)
    top_set = set(top.tolist())
    rest = [i for i in range(len(uniques)) if i not in top_set]
    if rest:
        cs["cmsketch"] = build_cmsketch(uniques[rest], counts[rest])
    if data.dtype != object:
        vals = data.astype(np.float64)
        cs["min"] = float(vals.min())
        cs["max"] = float(vals.max())
        # equal-depth histogram over the sorted column: bucket upper
        # bounds at quantile positions + cumulative counts
        nb = min(HIST_BUCKETS, len(uniques))
        if nb >= 2:
            sv = np.sort(vals)
            pos = ((np.arange(1, nb + 1) * len(sv)) // nb) - 1
            bounds = sv[pos]
            cum = np.searchsorted(sv, bounds, side="right")
            cs["hist"] = {"bounds": [float(b) for b in bounds],
                          "cum": [int(c) for c in cum]}
    return cs


def _index_stats(info, cols, chunk):
    """Per-index prefix NDVs (reference: index stats built by ANALYZE in
    statistics/builder.go; consumed by access-path and join cardinality).
    prefix_ndv[k] = NDV of the first k+1 index columns as a tuple, with
    NULL counting as one distinct value. Computed by iterative
    code-densification so intermediate keys never overflow int64."""
    from ..model import SchemaState
    name2pos = {ci.name: i for i, ci in enumerate(cols)}
    out = {}
    n = chunk.num_rows
    for idx in info.indexes:
        if idx.state != SchemaState.PUBLIC:
            continue
        combined = np.zeros(n, dtype=np.int64)
        prefix_ndv = []
        ok = True
        for icol in idx.columns:
            pos = name2pos.get(icol.name)
            if pos is None:
                ok = False
                break
            col = chunk.columns[pos]
            if n:
                u, inv = np.unique(col.data, return_inverse=True)
                inv = inv.astype(np.int64) + 1
                inv[col.nulls] = 0
                combined = combined * (len(u) + 2) + inv
                _, combined = np.unique(combined, return_inverse=True)
                prefix_ndv.append(int(combined.max()) + 1)
            else:
                prefix_ndv.append(0)
        if ok and prefix_ndv:
            out[str(idx.id)] = {"name": idx.name, "prefix_ndv": prefix_ndv}
    return out


#: paged tables larger than this are analyzed from evenly-spaced sample
#: blocks (reference: ANALYZE samples per region rather than full-scanning
#: — statistics/builder.go; a 600M-row memmap must not be np.unique'd)
SAMPLE_CAP = 1 << 22
_SAMPLE_BLOCKS = 16


def _sampled_chunk(chunk, cap):
    """Evenly-spaced contiguous blocks totaling ~cap rows: contiguous
    slices read whole memmap pages (sequential IO), and spacing the blocks
    over the file keeps generation-order skew out of the sample."""
    from ..utils.chunk import concat_chunks
    n = chunk.num_rows
    block = max(cap // _SAMPLE_BLOCKS, 1)
    stride = max(n // _SAMPLE_BLOCKS, block)
    parts = []
    for b in range(_SAMPLE_BLOCKS):
        lo = min(b * stride, n)
        hi = min(lo + block, n)
        if hi > lo:
            parts.append(chunk.slice(lo, hi))
    return concat_chunks(parts)


def _rescale_column_stats(cs, factor, n):
    """Scale sampled per-column stats to the full table. NDV scaling uses
    the key-vs-category heuristic: a sample whose values are mostly
    distinct extrapolates linearly (key-like); a saturated small domain
    stays as observed."""
    if factor <= 1.0:
        return cs
    sample_nonnull = cs.pop("_sample_rows", None)
    cs["null_count"] = int(cs["null_count"] * factor)
    ndv = cs.get("ndv", 0)
    if sample_nonnull and ndv > 0.1 * sample_nonnull:
        cs["ndv"] = min(int(ndv * factor), n)
    if "topn" in cs:
        cs["topn"] = [[v, int(c * factor)] for v, c in cs["topn"]]
    if "hist" in cs:
        cs["hist"]["cum"] = [int(c * factor) for c in cs["hist"]["cum"]]
    return cs


def analyze_table(session, info):
    cache = session.columnar_cache()
    cols = info.public_columns()
    entry = cache.get(info, session.store.begin())
    if entry is not None:
        chunk = cache.project(entry, cols, info)
    else:  # unreachable with a fresh snapshot, but never skip ANALYZE
        from ..table import Table
        chunk = Table(info, session.store.begin()).scan_columnar(
            col_infos=cols)
    n = chunk.num_rows
    from ..storage.paged import chunk_is_paged
    factor = 1.0
    if n > SAMPLE_CAP and chunk_is_paged(chunk):
        chunk = _sampled_chunk(chunk, SAMPLE_CAP)
        factor = n / max(chunk.num_rows, 1)
    stats = {"row_count": int(n), "columns": {}}
    if factor > 1.0:
        stats["sampled_rows"] = int(chunk.num_rows)
    for ci, col in zip(cols, chunk.columns):
        cs = _column_stats(col)
        cs["_sample_rows"] = chunk.num_rows - cs["null_count"]
        stats["columns"][str(ci.id)] = _rescale_column_stats(
            cs, factor, int(n))
        stats["columns"][str(ci.id)].pop("_sample_rows", None)
    stats["indexes"] = _index_stats(info, cols, chunk)
    if factor > 1.0:
        # index prefix NDVs share the column key-vs-category extrapolation
        # (a unique index's sampled NDV ~= sample size must scale to the
        # table, or per-key row estimates inflate by the sample factor)
        sample_n = chunk.num_rows
        for ix in stats["indexes"].values():
            ix["prefix_ndv"] = [
                min(int(v * factor), int(n)) if v > 0.1 * sample_n else v
                for v in ix["prefix_ndv"]]
    txn = session.store.begin()
    try:
        m = Meta(txn)
        m.set_stats(info.id, stats)
        txn.commit()
    except Exception:
        txn.rollback()
        raise
    session.domain.stats[info.id] = stats
    session.domain.stats_version += 1  # invalidate cached plans
    return stats
