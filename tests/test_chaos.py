"""Chaos harness entry points (see chaos_harness.py for the contract).

Tier-1 runs a small fixed-seed smoke; the deeper sweep is marked `slow`
and sized by CHAOS_SEEDS (default 20) for local runs:

    CHAOS_SEEDS=50 pytest tests/test_chaos.py -m chaos
"""

import os

import pytest

from chaos_harness import run_seed

SMOKE_SEEDS = [0, 1, 2, 3]
_DEEP = int(os.environ.get("CHAOS_SEEDS", "20"))


@pytest.mark.chaos
@pytest.mark.parametrize("seed", SMOKE_SEEDS)
def test_chaos_smoke(seed):
    """Fixed-seed tier-1 smoke: exact-or-classified under faults, ledger
    atomicity, recovery after the schedule."""
    stats = run_seed(seed)
    # the schedule must actually exercise both outcomes over the corpus
    assert stats["exact"] + stats["clean_errors"] > 0
    assert stats["writes_ok"] + stats["writes_failed"] == 4


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(len(SMOKE_SEEDS), _DEEP))
def test_chaos_sweep(seed):
    """Deeper deterministic sweep (excluded from tier-1 by `slow`)."""
    run_seed(seed)
