"""ADMIN CHECK TABLE / CHECK INDEX (reference: executor/admin.go — verifies
index KVs are consistent with row data)."""

from __future__ import annotations

from ..errors import TiDBError
from ..model import SchemaState
from ..table import Table
from .. import tablecodec


def check_index(session, info, index_name: str):
    """ADMIN CHECK INDEX t idx (reference: executor/admin.go
    CheckIndexExec): row↔index consistency for one index."""
    idx = info.find_index(index_name)
    if idx is None:
        raise TiDBError(f"index '{index_name}' does not exist on "
                        f"'{info.name}'")
    if idx.state != SchemaState.PUBLIC:
        raise TiDBError(f"index '{index_name}' is not public "
                        f"(state: {SchemaState.NAMES.get(idx.state, '?')})")
    txn = session.store.begin()
    try:
        tbl = Table(info, txn)
        rows = dict(tbl.iter_rows())
        _check_one_index(txn, info, idx, rows)
    finally:
        txn.rollback()


def _check_one_index(txn, info, idx, rows):
    """Scan the index range; every entry must point at a live row, and the
    entry count must equal the row count (each row yields exactly one entry
    per index — null-unique entries carry a handle suffix)."""
    seen = 0
    start, end = tablecodec.index_range(info.id, idx.id)
    for key, value in txn.scan(start, end):
        handle = tablecodec.decode_index_handle(value)
        if handle is None:
            handle = tablecodec.decode_index_values(key)[-1]
        if handle not in rows:
            raise TiDBError(
                f"index '{idx.name}' has orphan entry for handle {handle}")
        seen += 1
    if seen != len(rows):
        raise TiDBError(
            f"index '{idx.name}' count {seen} != row count {len(rows)}")


def check_table(session, info):
    """Every PUBLIC index is checked; in-flight online-DDL indexes are
    legitimately incomplete and skipped (the reference checks via the
    schema the statement resolved, which only has public indexes)."""
    txn = session.store.begin()
    try:
        tbl = Table(info, txn)
        rows = dict(tbl.iter_rows())
        for idx in info.indexes:
            if idx.state != SchemaState.PUBLIC:
                continue
            _check_one_index(txn, info, idx, rows)
    finally:
        txn.rollback()
