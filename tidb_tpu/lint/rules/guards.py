"""Guard-inference layer + the static race rules (the role the Go race
detector plays for the reference codebase's CI, approximated statically).

The review history of PRs 5-10 is one bug class three ways: shared state
touched outside its lock (`_MPP_PLACE_CACHE` check/popitem), check-then-
act splits across lock releases (`obtain()` double-submit, fence-check vs
executable-install under separate `_PIPE_LOCK` holds), and `*_locked`
helpers whose calling contract nothing enforced.  This module turns the
lock model of ``rules/locks.py`` into a guard INFERENCE: for every shared
mutable object in the audited service modules, the lock held at the
MAJORITY of its access sites is inferred to be its guard, and the
minority sites are the findings.

  * ``guarded-state`` — inventory shared mutable state (module-level
    dicts/lists/counters of the audited modules, plus instance attrs of
    classes that own an instance lock), infer each object's guard from
    the majority of its access sites — including call-propagated holds:
    a helper whose every resolved call site takes lock L counts as
    running under L (``locks._Model.entry_held``) — and flag minority
    unguarded reads/writes.  Deliberate GIL-atomic fast paths (e.g.
    ``compile_service.note_hit``) carry reason-mandatory allowlist
    entries, which doubles as the inventory of every lock-free access in
    the repo (README "Concurrency conventions").

  * ``check-then-act`` — a guarded object CHECKED under one ``with
    <lock>`` hold (membership / truth / ``len`` / ``.get``) and then
    MUTATED in a LATER hold of the same lock (or unguarded) in the same
    function, with no re-check before the mutation: the exact shape of
    the ``obtain()`` double-submit and fence/install bugs.  A hold that
    both checks and mutates is one atomic section (clean); an act-hold
    that re-checks any same-lock state first is the sanctioned
    double-check pattern (clean).

  * ``locked-suffix-contract`` — the ``*_locked`` naming convention
    becomes enforced: a ``*_locked`` function may only be called with a
    lock statically held (directly or call-propagated), and a function
    that ACQUIRES the very guard its callers hold must not be named
    ``*_locked``.

Like the lock model underneath, everything here under-approximates:
unresolvable receivers, aliased state smuggled through parameters and
calls through indirection are skipped, never guessed — a finding is
meant to be worth reading.
"""

from __future__ import annotations

import ast

from ..engine import Rule, register
from ._util import call_name
from .locks import _model_for

#: the modules whose shared mutable state is audited: the singleton
#: service layers of the serving stack (ISSUE 11) plus the compiled-
#: fragment caches the historical bugs lived in, plus the lint package
#: itself (self-coverage)
AUDITED = (
    "executor/scheduler.py",
    "executor/supervisor.py",
    "executor/compile_service.py",
    "executor/circuit.py",
    "executor/device_exec.py",
    "executor/hybrid_join.py",
    "executor/mpp_exec.py",
    "ops/residency.py",
    "session/tracing.py",
    "session/observe.py",
    "lint/engine.py",
    "lint/__main__.py",
)

#: constructors whose result is shared-mutable when bound at module level
MUTABLE_CTORS = {"dict", "list", "set", "OrderedDict", "Counter",
                 "deque", "defaultdict", "WeakSet",
                 "WeakValueDictionary"}

#: method calls that mutate their receiver in place
MUTATORS = {"append", "appendleft", "add", "insert", "extend", "update",
            "clear", "pop", "popitem", "popleft", "remove", "discard",
            "setdefault", "move_to_end", "sort", "reverse"}

#: receiver methods that count as CHECKS for check-then-act (probe
#: without structural commitment; setdefault is check+act in one call)
CHECK_CALLS = {"get", "setdefault", "keys", "values", "items", "count",
               "index"}


class _GState:
    """One audited shared-mutable object."""

    __slots__ = ("ident", "rel", "name", "cls", "attr")

    def __init__(self, rel, name, cls=None, attr=None):
        self.rel = rel
        self.name = name          # "NAME" or "Class.attr"
        self.cls = cls
        self.attr = attr
        self.ident = f"{rel}::{name}"


class _Access:
    __slots__ = ("state", "write", "held", "rel", "line", "qual",
                 "exempt", "check", "holds")

    def __init__(self, state, write, held, rel, line, qual, exempt,
                 check, holds):
        self.state = state        # _GState
        self.write = write
        self.held = held          # frozenset of lock idents (effective)
        self.rel = rel
        self.line = line
        self.qual = qual
        self.exempt = exempt      # module scope / owning __init__
        self.check = check        # participates in a test-ish expression
        self.holds = holds        # ((with_id, (locks...)), ...) innermost last


def _local_bound(fn) -> set:
    """Names the function BINDS locally (assignments make them locals, so
    a same-named module state is shadowed) minus explicit globals.
    Nested defs are NOT descended into — their locals are their own."""
    out = set()
    args = fn.args
    for a in (args.args + args.posonlyargs + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        out.add(a.arg)
    globs = set()

    def scan(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                out.add(getattr(child, "name", ""))
                continue
            if isinstance(child, ast.Global):
                globs.update(child.names)
            elif isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                out.add(child.id)
            elif isinstance(child, ast.ExceptHandler) and child.name:
                out.add(child.name)
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                for a in child.names:
                    out.add(a.asname or a.name.split(".")[0])
            scan(child)

    scan(fn)
    out.discard("")
    return out - globs


class _GuardModel:
    """State inventory + access sites + per-state inferred guards, built
    once per Context and shared by the three rules."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.model = _model_for(ctx)
        self.entry = self.model.entry_held()
        # (rel, name) -> _GState for module states;
        # (rel, cls, attr) -> _GState for instance states
        self.mod_states: dict = {}
        self.inst_states: dict = {}
        self.accesses: list[_Access] = []
        # functions defined with the *_locked suffix: key -> (rel, line)
        self.locked_defs: dict = {}
        self._inventory()
        self._collect()
        self.guards = self._infer()

    # -- inventory ------------------------------------------------------

    def _audited(self, rel) -> bool:
        return rel in AUDITED

    def _inventory(self):
        for sf in self.ctx.package_files:
            if not self._audited(sf.rel):
                continue
            for node in sf.tree.body:
                targets = ()
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = (node.target,), node.value
                if not self._mutable_value(value):
                    continue
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        self.mod_states[(sf.rel, tgt.id)] = _GState(
                            sf.rel, tgt.id)
            # instance attrs of classes that own an inventoried lock
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                cls = node.name
                owns_lock = any(
                    ident.startswith(f"{sf.rel}::{cls}.")
                    for ident in self.model.locks
                    if not self.model.locks[ident].module_level)
                if not owns_lock:
                    continue
                for sub in ast.walk(node):
                    tgt = None
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                tgt = t
                    elif isinstance(sub, ast.AnnAssign) and isinstance(
                            sub.target, ast.Attribute) and isinstance(
                            sub.target.value, ast.Name) \
                            and sub.target.value.id == "self":
                        tgt = sub.target
                    if tgt is None:
                        continue
                    ident = f"{sf.rel}::{cls}.{tgt.attr}"
                    if ident in self.model.locks:
                        continue  # the lock itself is not guarded state
                    key = (sf.rel, cls, tgt.attr)
                    if key not in self.inst_states:
                        self.inst_states[key] = _GState(
                            sf.rel, f"{cls}.{tgt.attr}", cls, tgt.attr)

    @staticmethod
    def _mutable_value(value) -> bool:
        if isinstance(value, (ast.Dict, ast.List, ast.Set)):
            return True
        if isinstance(value, ast.Call):
            leaf = call_name(value).rsplit(".", 1)[-1]
            return leaf in MUTABLE_CTORS
        return False

    # -- access collection ----------------------------------------------

    def _collect(self):
        for sf in self.ctx.package_files:
            imports = self.model.imports.get(sf.rel, {})
            # a file that is not audited and imports no audited module
            # cannot reference audited state: only its *_locked defs
            # matter (the full access walk is the expensive part)
            relevant = self._audited(sf.rel) or any(
                isinstance(v, str) and v + ".py" in AUDITED
                for v in imports.values())
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if node.name.endswith("_locked"):
                        key = f"{sf.rel}::{sf.qualname(node)}"
                        self.locked_defs[key] = (sf.rel, node.lineno)
                    if relevant:
                        self._walk_fn(sf, imports, node)
            # module-scope accesses are skipped entirely: import time is
            # single-threaded (publication before sharing)

    def _walk_fn(self, sf, imports, fn):
        key = f"{sf.rel}::{sf.qualname(fn)}"
        entry = self.entry.get(key, frozenset())
        localbound = _local_bound(fn)
        qual = sf.qualname(fn)
        parents = sf.parents()
        in_cls = self.model._enclosing_class(sf, fn)
        is_init = qual.endswith(".__init__")

        def visit(node, held, holds):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs run later, not under these holds
            if isinstance(node, ast.Lambda):
                return  # deferred execution: holds do not apply
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    lock = self.model.resolve_lock(sf, item.context_expr)
                    if lock is not None:
                        acquired.append(lock)
                    visit(item.context_expr, held, holds)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held, holds)
                sub_holds = holds
                if acquired:
                    sub_holds = holds + ((id(node), tuple(acquired)),)
                for child in node.body:
                    visit(child, held + acquired, sub_holds)
                return
            st = self._match(sf, imports, node, localbound, in_cls)
            if st is not None:
                write = self._classify(parents, node)
                check = self._is_check(parents, node)
                exempt = (qual == "<module>"
                          or (st.cls is not None and is_init
                              and in_cls == st.cls))
                self.accesses.append(_Access(
                    st, write == "write",
                    frozenset(held) | entry, sf.rel, node.lineno, qual,
                    exempt, check, holds))
            for child in ast.iter_child_nodes(node):
                visit(child, held, holds)

        for stmt in fn.body:
            visit(stmt, [], ())

    def _match(self, sf, imports, node, localbound, in_cls):
        """The _GState a node refers to, or None.  Matches exactly the
        base reference (bare NAME / module.NAME / self.attr) so each
        textual occurrence is counted once."""
        if isinstance(node, ast.Name):
            if node.id in localbound:
                return None
            return self.mod_states.get((sf.rel, node.id))
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name):
            head = node.value.id
            if head == "self":
                return self.inst_states.get((sf.rel, in_cls, node.attr))
            mod = imports.get(head)
            if mod:
                return self.mod_states.get((mod + ".py", node.attr))
        return None

    @staticmethod
    def _classify(parents, base) -> str:
        node = base
        p = parents.get(id(node))
        while isinstance(p, ast.Subscript) and p.value is node:
            node, p = p, parents.get(id(p))
        ctx = getattr(node, "ctx", None)
        if isinstance(ctx, (ast.Store, ast.Del)):
            return "write"
        if isinstance(p, ast.Attribute) and p.value is node:
            gp = parents.get(id(p))
            if isinstance(gp, ast.Call) and gp.func is p \
                    and p.attr in MUTATORS:
                return "write"
        return "read"

    @staticmethod
    def _is_check(parents, base) -> bool:
        cur = base
        p = parents.get(id(cur))
        while p is not None and not isinstance(p, ast.stmt):
            if isinstance(p, (ast.Compare, ast.BoolOp)):
                return True
            if isinstance(p, ast.UnaryOp) and isinstance(p.op, ast.Not):
                return True
            if isinstance(p, ast.IfExp) and p.test is cur:
                return True
            if isinstance(p, ast.Call) and isinstance(
                    p.func, ast.Attribute) and p.func.attr in CHECK_CALLS:
                return True
            cur, p = p, parents.get(id(p))
        if isinstance(p, (ast.If, ast.While)) and p.test is cur:
            return True
        return isinstance(p, ast.Assert)

    # -- inference ------------------------------------------------------

    def _infer(self) -> dict:
        """state ident -> (guard lock ident, guarded_n, total_n) for
        states where a strict majority of non-exempt access sites hold
        one lock (and at least two sites do — one site is no pattern)."""
        per_state: dict = {}
        for a in self.accesses:
            if a.exempt:
                continue
            per_state.setdefault(a.state.ident, []).append(a)
        # an instance attr only written during __init__ is configuration,
        # not shared-mutable state: reads of it need no guard
        written = {a.state.ident for a in self.accesses
                   if a.write and not a.exempt}
        out = {}
        for ident, sites in per_state.items():
            st = sites[0].state
            if st.cls is not None and ident not in written:
                continue
            votes: dict = {}
            for a in sites:
                for lock in a.held:
                    votes[lock] = votes.get(lock, 0) + 1
            if not votes:
                continue
            guard = max(sorted(votes), key=lambda k: votes[k])
            n = votes[guard]
            if n >= 2 and 2 * n > len(sites):
                out[ident] = (guard, n, len(sites))
        return out


def _guard_model(ctx) -> _GuardModel:
    gm = getattr(ctx, "_guard_model", None)
    if gm is None:
        gm = _GuardModel(ctx)
        ctx._guard_model = gm
    return gm


def _short(ident: str) -> str:
    rel, name = ident.split("::", 1)
    return f"{rel.rsplit('/', 1)[-1][:-3]}.{name}"


class _Deduper:
    def __init__(self):
        self.seen: dict = {}

    def ident(self, base: str) -> str:
        k = self.seen.get(base, 0)
        self.seen[base] = k + 1
        return base + (f"#{k}" if k else "")


@register
class GuardedState(Rule):
    name = "guarded-state"
    title = "shared mutable state is accessed under its inferred guard"

    def prepare(self, ctx):
        _guard_model(ctx)

    def run(self, ctx):
        gm = _guard_model(ctx)
        out = []
        dedup = _Deduper()
        for a in sorted(gm.accesses, key=lambda a: (a.rel, a.line)):
            if a.exempt:
                continue
            info = gm.guards.get(a.state.ident)
            if info is None:
                continue
            guard, n, total = info
            if guard in a.held:
                continue
            kind = "write to" if a.write else "read of"
            out.append(self.finding(
                a.rel, a.line,
                dedup.ident(f"unguarded:{a.state.name}@{a.qual}"),
                f"{kind} {_short(a.state.ident)} without its inferred "
                f"guard {_short(guard)} (held at {n}/{total} access "
                f"sites) — lock it or allowlist the site with the reason "
                "the lock-free access is safe"))
        return out


@register
class CheckThenAct(Rule):
    name = "check-then-act"
    title = "no check under one lock hold acted on in a later hold"

    def prepare(self, ctx):
        _guard_model(ctx)

    def run(self, ctx):
        gm = _guard_model(ctx)
        out = []
        dedup = _Deduper()
        # group accesses per (function, guard lock)
        per_fn: dict = {}
        for a in gm.accesses:
            if a.exempt:
                continue
            info = gm.guards.get(a.state.ident)
            if info is None:
                continue
            per_fn.setdefault((a.rel, a.qual), []).append((a, info[0]))

        for (rel, qual), recs in sorted(per_fn.items()):
            by_lock: dict = {}
            for a, guard in recs:
                by_lock.setdefault(guard, []).append(a)
            for guard, accs in by_lock.items():
                out.extend(self._scan(rel, qual, guard, accs, dedup))
        return out

    def _hold_of(self, a, guard):
        """Innermost explicit with-hold of `guard` the access sits in
        (None = not inside an explicit hold of it)."""
        for wid, locks in reversed(a.holds):
            if guard in locks:
                return wid
        return None

    def _scan(self, rel, qual, guard, accs, dedup):
        # per explicit hold: checks / mutations of each state, in line
        # order; plus each hold's line span
        holds: dict = {}
        loose = []  # accesses under no explicit hold of the guard
        for a in accs:
            wid = self._hold_of(a, guard)
            if wid is None:
                loose.append(a)
                continue
            h = holds.setdefault(wid, {"lines": [], "accs": []})
            h["lines"].append(a.line)
            h["accs"].append(a)
        out = []
        ordered = sorted(holds.values(), key=lambda h: min(h["lines"]))
        for i, h1 in enumerate(ordered):
            # a candidate CHECK hold: checks some state, mutates nothing
            # of it in the same hold
            checked = {a.state.ident for a in h1["accs"] if a.check}
            muted1 = {a.state.ident for a in h1["accs"] if a.write}
            cands = checked - muted1
            if not cands:
                continue
            h1_end = max(h1["lines"])
            for sid in sorted(cands):
                # later hold mutating sid without ANY same-lock re-check
                # before the mutation
                for h2 in ordered[i + 1:]:
                    if min(h2["lines"]) <= h1_end:
                        continue
                    muts = [a for a in h2["accs"]
                            if a.write and a.state.ident == sid]
                    if not muts:
                        continue
                    first_mut = min(a.line for a in muts)
                    rechecked = any(a.check and a.line <= first_mut
                                    for a in h2["accs"])
                    if rechecked:
                        continue
                    st = muts[0].state
                    out.append(self.finding(
                        rel, first_mut,
                        dedup.ident(f"check-then-act:{st.name}@{qual}"),
                        f"{_short(sid)} is checked under one "
                        f"{_short(guard)} hold and mutated in a later "
                        "hold with no re-check — the decision can go "
                        "stale between the two critical sections "
                        "(re-check under the acting hold, or merge the "
                        "sections)"))
                    break
                else:
                    # ... or mutated with the guard not held at all
                    later_unguarded = [
                        a for a in loose
                        if a.write and a.state.ident == sid
                        and a.line > h1_end and guard not in a.held]
                    if later_unguarded:
                        a = later_unguarded[0]
                        out.append(self.finding(
                            rel, a.line,
                            dedup.ident(
                                f"check-then-act:{a.state.name}@{qual}"),
                            f"{_short(sid)} is checked under a "
                            f"{_short(guard)} hold and mutated later "
                            "with no lock held — the check cannot "
                            "protect the mutation"))
        return out


@register
class LockedSuffixContract(Rule):
    name = "locked-suffix-contract"
    title = "*_locked functions are called with their guard held"

    def prepare(self, ctx):
        _guard_model(ctx)

    def run(self, ctx):
        gm = _guard_model(ctx)
        model = gm.model
        out = []
        dedup = _Deduper()
        # call sites grouped per callee
        sites: dict = {}
        for caller, recs in model.call_records.items():
            for held, callee, line in recs:
                if callee in gm.locked_defs:
                    eff = frozenset(held) | gm.entry.get(
                        caller, frozenset())
                    sites.setdefault(callee, []).append(
                        (caller, eff, line))
        for callee, recs in sorted(sites.items()):
            leaf = callee.split("::", 1)[1].rsplit(".", 1)[-1]
            votes: dict = {}
            for _caller, eff, _line in recs:
                for lock in eff:
                    votes[lock] = votes.get(lock, 0) + 1
            for caller, eff, line in recs:
                if eff:
                    continue
                caller_rel, caller_qual = caller.split("::", 1)
                out.append(self.finding(
                    caller_rel, line,
                    dedup.ident(f"unlocked-call:{leaf}@{caller_qual}"),
                    f"{leaf}() is called with no lock statically held — "
                    "the _locked suffix is a contract: every caller "
                    "must hold the guard (or the function is misnamed)"))
            if votes:
                guard = max(sorted(votes), key=lambda k: votes[k])
                drel, dline = gm.locked_defs[callee]
                if guard in model.direct.get(callee, ()):
                    out.append(self.finding(
                        drel, dline, f"acquires-guard:{leaf}",
                        f"{leaf}() itself acquires {_short(guard)}, the "
                        "guard its callers hold — a *_locked function "
                        "must expect the lock held, not take it (rename "
                        "it or drop the acquisition)"))
        return out
