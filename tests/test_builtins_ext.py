"""Extended builtin functions (reference: expression/builtin.go registry;
these are the long-tail scalar builtins added toward the 281-function
surface). One assertion per function, driven through full SQL."""

import pytest

from tidb_tpu.expression.core import supported_scalar_ops
from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    return TestKit()


def q1(tk, expr):
    """SELECT <expr> → single display string (None for NULL)."""
    return tk.must_query(f"select {expr}").rows[0][0]


def test_registry_size():
    # VERDICT round-3 target: >= 250 registered builtins
    # (reference registry: 281, expression/builtin.go:573)
    assert len(supported_scalar_ops()) >= 250


# -- string ------------------------------------------------------------------

def test_ascii(tk):
    assert q1(tk, "ascii('Az')") == "65"

def test_ord(tk):
    assert q1(tk, "ord('A')") == "65"

def test_bin(tk):
    assert q1(tk, "bin(12)") == "1100"

def test_oct(tk):
    assert q1(tk, "oct(12)") == "14"

def test_hex_str_and_int(tk):
    assert q1(tk, "hex('abc')") == "616263"
    assert q1(tk, "hex(255)") == "FF"

def test_unhex(tk):
    assert q1(tk, "unhex('616263')") == "abc"

def test_md5(tk):
    assert q1(tk, "md5('abc')") == "900150983cd24fb0d6963f7d28e17f72"

def test_sha1(tk):
    assert q1(tk, "sha1('abc')") == "a9993e364706816aba3e25717850c26c9cd0d89d"

def test_sha2(tk):
    assert q1(tk, "sha2('abc', 256)").startswith("ba7816bf8f01cfea")

def test_crc32(tk):
    assert q1(tk, "crc32('abc')") == "891568578"

def test_instr(tk):
    assert q1(tk, "instr('foobarbar', 'bar')") == "4"

def test_rpad(tk):
    assert q1(tk, "rpad('hi', 5, '?')") == "hi???"
    assert q1(tk, "rpad('hi', 1, '?')") == "h"

def test_elt(tk):
    assert q1(tk, "elt(2, 'a', 'b', 'c')") == "b"
    assert q1(tk, "elt(9, 'a')") is None

def test_field(tk):
    assert q1(tk, "field('b', 'a', 'b', 'c')") == "2"

def test_find_in_set(tk):
    assert q1(tk, "find_in_set('b', 'a,b,c')") == "2"
    assert q1(tk, "find_in_set('x', 'a,b,c')") == "0"

def test_format(tk):
    assert q1(tk, "format(1234567.891, 2)") == "1,234,567.89"

def test_insert(tk):
    assert q1(tk, "insert('Quadratic', 3, 4, 'What')") == "QuWhattic"

def test_strcmp(tk):
    assert q1(tk, "strcmp('a', 'b')") == "-1"
    assert q1(tk, "strcmp('b', 'b')") == "0"

def test_substring_index(tk):
    assert q1(tk, "substring_index('www.mysql.com', '.', 2)") == "www.mysql"
    assert q1(tk, "substring_index('www.mysql.com', '.', -2)") == "mysql.com"

def test_base64_roundtrip(tk):
    assert q1(tk, "to_base64('abc')") == "YWJj"
    assert q1(tk, "from_base64('YWJj')") == "abc"

def test_quote(tk):
    assert q1(tk, "quote(\"it's\")") == "'it\\'s'"

def test_space(tk):
    assert q1(tk, "space(3)") == "   "

def test_char_fn(tk):
    assert q1(tk, "char(77, 121)") == "My"

def test_bit_length(tk):
    assert q1(tk, "bit_length('abc')") == "24"

def test_conv(tk):
    assert q1(tk, "conv('ff', 16, 10)") == "255"
    assert q1(tk, "conv(10, 10, 2)") == "1010"

def test_soundex(tk):
    assert q1(tk, "soundex('Robert')") == "R163"

def test_lcase_ucase_mid(tk):
    assert q1(tk, "ucase('ab')") == "AB"
    assert q1(tk, "lcase('AB')") == "ab"
    assert q1(tk, "mid('abcdef', 2, 3)") == "bcd"


# -- math --------------------------------------------------------------------

def test_trig(tk):
    assert q1(tk, "round(sin(0), 4)") == "0"
    assert q1(tk, "round(cos(0), 4)") == "1"
    assert q1(tk, "round(tan(0), 4)") == "0"
    assert q1(tk, "round(atan(1) * 4, 4)") == "3.1416"
    assert q1(tk, "round(atan2(1, 1) * 4, 4)") == "3.1416"
    assert q1(tk, "round(asin(1) * 2, 4)") == "3.1416"
    assert q1(tk, "round(acos(0) * 2, 4)") == "3.1416"

def test_cot(tk):
    assert q1(tk, "round(cot(1), 4)") == "0.6421"

def test_pi(tk):
    assert q1(tk, "round(pi(), 4)") == "3.1416"

def test_radians_degrees(tk):
    assert q1(tk, "round(degrees(pi()), 2)") == "180"
    assert q1(tk, "round(radians(180) - pi(), 6)") == "0"

def test_log(tk):
    assert q1(tk, "round(log(2, 8), 4)") == "3"
    assert q1(tk, "round(log(exp(1)), 4)") == "1"
    assert q1(tk, "log(-1)") is None

def test_bit_count(tk):
    assert q1(tk, "bit_count(7)") == "3"

def test_asin_out_of_range_null(tk):
    assert q1(tk, "asin(2)") is None


# -- date / time -------------------------------------------------------------

def test_from_unixtime(tk):
    assert q1(tk, "from_unixtime(0)") == "1970-01-01 00:00:00"

def test_unix_timestamp(tk):
    assert q1(tk, "unix_timestamp('1970-01-02 00:00:00')") == "86400"

def test_time_to_sec(tk):
    assert q1(tk, "time_to_sec('01:00:05')") == "3605"

def test_sec_to_time(tk):
    assert q1(tk, "sec_to_time(3605)") == "01:00:05"

def test_makedate(tk):
    assert q1(tk, "makedate(2011, 32)") == "2011-02-01"

def test_maketime(tk):
    assert q1(tk, "maketime(12, 15, 30)") == "12:15:30"

def test_last_day(tk):
    assert q1(tk, "last_day('2024-02-05')") == "2024-02-29"

def test_dayname_monthname(tk):
    assert q1(tk, "dayname('2024-01-01')") == "Monday"
    assert q1(tk, "monthname('2024-01-01')") == "January"

def test_weekday(tk):
    assert q1(tk, "weekday('2024-01-01')") == "0"  # Monday

def test_weekofyear(tk):
    assert q1(tk, "weekofyear('2024-01-04')") == "1"

def test_yearweek(tk):
    assert q1(tk, "yearweek('2024-01-04')") == "202401"

def test_to_days_from_days(tk):
    days = q1(tk, "to_days('2024-01-01')")
    assert q1(tk, f"from_days({days})") == "2024-01-01"

def test_period_add_diff(tk):
    assert q1(tk, "period_add(202312, 2)") == "202402"
    assert q1(tk, "period_diff(202402, 202312)") == "2"

def test_str_to_date(tk):
    assert q1(tk, "str_to_date('01,5,2013', '%d,%m,%Y')") == "2013-05-01"

def test_timestampdiff(tk):
    assert q1(tk, "timestampdiff(day, '2024-01-01', '2024-02-01')") == "31"
    assert q1(tk, "timestampdiff(month, '2023-01-15', '2024-03-16')") == "14"
    assert q1(tk, "timestampdiff(year, '2020-06-01', '2024-05-31')") == "3"

def test_addtime_subtime(tk):
    assert q1(tk, "addtime('01:00:00', '00:30:30')") == "01:30:30"
    assert q1(tk, "subtime('01:00:00', '00:30:30')") == "00:29:30"

def test_microsecond(tk):
    assert q1(tk, "microsecond('2024-01-01 10:00:00')") == "0"


# -- JSON --------------------------------------------------------------------

def test_json_extract(tk):
    assert q1(tk, "json_extract('{\"a\": {\"b\": 2}}', '$.a.b')") == "2"
    assert q1(tk, "json_extract('[1, 2, 3]', '$[1]')") == "2"

def test_json_unquote(tk):
    assert q1(tk, "json_unquote('\"abc\"')") == "abc"

def test_json_valid(tk):
    assert q1(tk, "json_valid('{\"a\": 1}')") == "1"
    assert q1(tk, "json_valid('nope{')") == "0"

def test_json_length(tk):
    assert q1(tk, "json_length('[1, 2, 3]')") == "3"

def test_json_type(tk):
    assert q1(tk, "json_type('[1]')") == "ARRAY"
    assert q1(tk, "json_type('{}')") == "OBJECT"

def test_json_object_array(tk):
    assert q1(tk, "json_object('k', 1)") == '{"k": 1}'
    assert q1(tk, "json_array(1, 'a')") == '[1, "a"]'

def test_json_keys(tk):
    assert q1(tk, "json_keys('{\"a\": 1, \"b\": 2}')") == '["a", "b"]'

def test_json_contains(tk):
    assert q1(tk, "json_contains('[1, 2, 3]', '2')") == "1"
    assert q1(tk, "json_contains('[1, 2, 3]', '9')") == "0"


# -- network / misc ----------------------------------------------------------

def test_inet_aton_ntoa(tk):
    assert q1(tk, "inet_aton('10.0.5.9')") == "167773449"
    assert q1(tk, "inet_ntoa(167773449)") == "10.0.5.9"

def test_is_ipv4(tk):
    assert q1(tk, "is_ipv4('10.0.5.9')") == "1"
    assert q1(tk, "is_ipv4('10.0.5.256')") == "0"

def test_is_ipv6(tk):
    assert q1(tk, "is_ipv6('::1')") == "1"
    assert q1(tk, "is_ipv6('10.0.0.1')") == "0"

def test_uuid_shape(tk):
    v = q1(tk, "uuid()")
    assert len(v) == 36 and v.count("-") == 4

def test_connection_id(tk):
    assert int(q1(tk, "connection_id()")) > 0

def test_null_propagation(tk):
    assert q1(tk, "md5(NULL)") is None
    assert q1(tk, "instr(NULL, 'a')") is None
    assert q1(tk, "rpad('a', -1, 'x')") is None


def test_functions_over_table_rows(tk):
    """Builtins evaluate per-row over real columns, not just constants."""
    tk.must_exec("create table bx (a int primary key, s varchar(20))")
    tk.must_exec("insert into bx values (1, 'hello'), (2, 'WORLD'), (3, null)")
    tk.must_query(
        "select a, upper(s), instr(s, 'o'), md5(s) is null from bx "
        "order by a").check([
            ("1", "HELLO", "5", "0"),
            ("2", "WORLD", "0", "0"),
            ("3", None, None, "1")])


def test_review_regressions(tk):
    # MySQL day-number epoch
    assert q1(tk, "to_days('1970-01-01')") == "719528"
    assert q1(tk, "from_days(719528)") == "1970-01-01"
    # NULL args to non-propagating builtins return NULL / skip, not crash
    assert q1(tk, "elt(null, 'a', 'b')") is None
    assert q1(tk, "char(65, null, 66)") == "AB"
    assert q1(tk, "field(null, 'a')") == "0"
    assert q1(tk, "json_array(1, null)") == "[1, null]"
    # zero-arg unix_timestamp works
    assert int(q1(tk, "unix_timestamp()")) > 1_700_000_000


def test_rand_seeded_varies_per_row(tk):
    tk.must_exec("create table rnd (a int primary key)")
    tk.must_exec("insert into rnd values (1),(2),(3),(4)")
    r = tk.must_query("select rand(3) from rnd")
    vals = [row[0] for row in r.rows]
    assert len(set(vals)) > 1, "seeded rand constant across rows"
    r2 = tk.must_query("select rand(3) from rnd")
    assert vals == [row[0] for row in r2.rows], "seeded rand not repeatable"


# -- JSON mutation + path functions (reference: types/json + expression
# builtinJSONSet/Insert/Replace/Remove/MergePatch/Quote/Depth) ---------------

def test_json_set_insert_replace(tk):
    assert q1(tk, "json_set('{\"a\": 1}', '$.a', 2)") == '{"a": 2}'
    assert q1(tk, "json_set('{\"a\": 1}', '$.a', 2, '$.b', 'x')") == \
        '{"a": 2, "b": "x"}'
    assert q1(tk, "json_insert('{\"a\": 1}', '$.a', 9, '$.b', 2)") == \
        '{"a": 1, "b": 2}'
    assert q1(tk, "json_replace('{\"a\": 1}', '$.a', 9, '$.b', 2)") == \
        '{"a": 9}'


def test_json_remove_and_array_append(tk):
    assert q1(tk, "json_remove('{\"a\": 1, \"b\": 2}', '$.b')") == '{"a": 1}'
    assert q1(tk, "json_remove('[1, 2, 3]', '$[0]')") == "[2, 3]"
    assert q1(tk, "json_array_append('[1, 2]', '$', 3)") == "[1, 2, 3]"
    assert q1(tk, "json_array_append('{\"a\": [1]}', '$.a', 2)") == \
        '{"a": [1, 2]}'


def test_json_merge_patch(tk):
    assert q1(tk, "json_merge_patch('{\"a\": 1, \"b\": 2}', "
                  "'{\"b\": null, \"c\": 3}')") == '{"a": 1, "c": 3}'
    assert q1(tk, "json_merge_patch('{\"a\": {\"x\": 1}}', "
                  "'{\"a\": {\"y\": 2}}')") == '{"a": {"x": 1, "y": 2}}'


def test_json_quote_depth_contains_path(tk):
    assert q1(tk, "json_quote('ab\"c')") == '"ab\\"c"'
    assert q1(tk, "json_depth('[]')") == "1"
    assert q1(tk, "json_depth('[1]')") == "2"
    assert q1(tk, "json_depth('{\"a\": [1, {\"b\": 2}]}')") == "4"
    assert q1(tk, "json_contains_path('{\"a\": 1}', 'one', '$.a', '$.z')") \
        == "1"
    assert q1(tk, "json_contains_path('{\"a\": 1}', 'all', '$.a', '$.z')") \
        == "0"


def test_json_arrow_operators(tk):
    assert q1(tk, "'{\"a\": {\"b\": 42}}' -> '$.a.b'") == "42"
    assert q1(tk, "'{\"a\": \"str\"}' ->> '$.a'") == "str"


def test_json_column_end_to_end(tk):
    tk.must_exec("create table jdoc (id int primary key, doc json)")
    tk.must_exec("insert into jdoc values "
                 "(1, '{\"name\": \"alice\", \"tags\": [1,2]}'), "
                 "(2, '{\"name\": \"bob\"}')")
    tk.must_query("select doc->>'$.name' from jdoc order by id").check(
        [("alice",), ("bob",)])
    tk.must_exec("update jdoc set doc = json_set(doc, '$.age', 30) "
                 "where id = 1")
    tk.must_query("select doc->'$.age' from jdoc where id = 1").check(
        [("30",)])
    tk.must_query("select id from jdoc where doc->>'$.name' = 'bob'").check(
        [("2",)])
    tk.must_query("select json_length(doc->'$.tags') from jdoc "
                  "where id = 1").check([("2",)])


# -- regexp / crypto / net / time breadth (reference: builtin_regexp.go,
# builtin_encryption.go, builtin_miscellaneous.go) ----------------------------

def test_regexp_functions(tk):
    assert q1(tk, "regexp_like('abc', 'b')") == "1"
    assert q1(tk, "regexp_like('abc', '^c')") == "0"
    assert q1(tk, "regexp_replace('abcabc', 'b', 'X')") == "aXcaXc"
    assert q1(tk, "regexp_substr('hello world', 'w.rld')") == "world"
    assert q1(tk, "regexp_instr('abcabc', 'c')") == "3"


def test_crypto_functions(tk):
    assert q1(tk, "aes_decrypt(aes_encrypt('secret', 'k'), 'k')") == "secret"
    assert q1(tk, "aes_decrypt('garbage', 'k')") is None
    assert q1(tk, "uncompress(compress('hello'))") == "hello"
    assert q1(tk, "uncompressed_length(compress('hello'))") == "5"
    assert q1(tk, "length(random_bytes(8))") == "8"
    assert q1(tk, "password('pw')").startswith("*")


def test_time_breadth(tk):
    assert q1(tk, "timediff('10:00:00', '08:30:00')") == "01:30:00"
    assert q1(tk, "timestampadd(day, 1, '2020-02-28')") == \
        "2020-02-29 00:00:00"
    assert q1(tk, "timestampadd(month, 1, '2020-01-31')") == \
        "2020-02-29 00:00:00"
    assert q1(tk, "time('2020-01-01 10:11:12')") == "10:11:12"
    assert q1(tk, "timestamp('2020-01-01')") == "2020-01-01 00:00:00"
    assert q1(tk, "time_format('10:05:03', '%H:%i')") == "10:05"
    assert q1(tk, "get_format(date, 'ISO')") == "%Y-%m-%d"


def test_misc_breadth(tk):
    assert q1(tk, "octet_length('héllo')") == "6"
    assert q1(tk, "make_set(5, 'a', 'b', 'c')") == "a,c"
    assert q1(tk, "export_set(5, 'Y', 'N', ',', 4)") == "Y,N,Y,N"
    u = "f47ac10b-58cc-4372-a567-0e02b2c3d479"
    assert q1(tk, f"is_uuid('{u}')") == "1"
    assert q1(tk, "is_uuid('nope')") == "0"
    assert q1(tk, f"bin_to_uuid(uuid_to_bin('{u}'))") == u
    assert int(q1(tk, "uuid_short()")) < int(q1(tk, "uuid_short()"))
    assert q1(tk, "inet6_ntoa(inet6_aton('::1'))") == "::1"
    assert q1(tk, "is_ipv4_mapped(inet6_aton('::ffff:1.2.3.4'))") == "1"
    assert q1(tk, "is_ipv4_compat(inet6_aton('::1.2.3.4'))") == "1"
    assert q1(tk, "format_bytes(2048)") == "2.00 KiB"
    assert q1(tk, "benchmark(100, 1+1)") == "0"


def test_builtin_count_floor(tk):
    """Breadth tracker vs the reference's 281-function registry
    (expression/builtin.go:573)."""
    from tidb_tpu.expression.core import supported_scalar_ops
    assert len(supported_scalar_ops()) >= 200


def test_timediff_datetime_args(tk):
    assert q1(tk, "timediff('2020-01-02 10:00:00', "
                  "'2020-01-01 08:00:00')") == "26:00:00"


def test_regexp_replace_pos_occurrence(tk):
    assert q1(tk, "regexp_replace('abcabc', 'b', 'X', 1)") == "aXcaXc"
    assert q1(tk, "regexp_replace('abcabc', 'b', 'X', 1, 2)") == "abcaXc"
    assert q1(tk, "regexp_replace('abcabc', 'b', 'X', 4)") == "abcaXc"


# -- breadth batch r4 --------------------------------------------------------

def test_truncate(tk):
    assert q1(tk, "truncate(123.4567, 2)") == "123.45"
    assert q1(tk, "truncate(-123.4567, 1)") == "-123.4"


def test_interval_fn(tk):
    # the bare INTERVAL keyword is claimed by the date-arith grammar;
    # exercise the function through the dispatch layer directly
    # (reference: MySQL disambiguates in its grammar too)
    import numpy as np
    from tidb_tpu.expression.core import _DISPATCH, Constant, ScalarFunc
    from tidb_tpu.sqltypes import FieldType, TYPE_LONGLONG
    from tidb_tpu.utils.chunk import Chunk, Column
    ll = FieldType(tp=TYPE_LONGLONG)
    one = Chunk([Column(ll, np.zeros(1, dtype=np.int64))])
    sf = ScalarFunc("interval", [Constant(v, ll)
                                 for v in (23, 1, 15, 17, 30, 44, 200)], ll)
    d, n = _DISPATCH["interval"](sf, one)
    assert int(d[0]) == 3 and not n[0]


def test_convert_tz(tk):
    assert q1(tk, "convert_tz('2004-01-01 12:00:00', '+00:00', '+10:00')"
              ) == "2004-01-01 22:00:00"
    assert q1(tk, "convert_tz('2004-01-01 12:00:00', 'bogus', '+10:00')"
              ) is None


def test_to_seconds(tk):
    assert q1(tk, "to_seconds('1970-01-01 00:00:01')") == "62167219201"


def test_json_search(tk):
    assert q1(tk, "json_search('[\"abc\", {\"x\": \"abc\"}]', 'one', 'abc')"
              ) == '"$[0]"'
    assert q1(tk, "json_search('[\"q\"]', 'one', 'abc')") is None


def test_json_overlaps(tk):
    assert q1(tk, "json_overlaps('[1,3,5]', '[2,5,7]')") == "1"
    assert q1(tk, "json_overlaps('[1,3]', '[2,7]')") == "0"


def test_json_pretty(tk):
    assert "\n" in q1(tk, "json_pretty('{\"a\": 1}')")


def test_json_storage_size(tk):
    assert int(q1(tk, "json_storage_size('{\"a\": 1}')")) > 0


def test_json_merge_preserve(tk):
    assert q1(tk, "json_merge_preserve('[1]', '[2]')") == "[1, 2]"
    assert q1(tk, "json_merge('{\"a\": 1}', '{\"a\": 2}')"
              ) == '{"a": [1, 2]}'


def test_json_array_insert(tk):
    assert q1(tk, "json_array_insert('[1, 3]', '$[1]', 2)") == "[1, 2, 3]"


def test_json_member_of(tk):
    assert q1(tk, "json_member_of('3', '[1, 3, 5]')") == "1"


def test_json_value(tk):
    assert q1(tk, "json_value('{\"a\": {\"b\": 7}}', '$.a.b')") == "7"


def test_name_const_any_value(tk):
    assert q1(tk, "name_const('k', 42)") == "42"
    assert q1(tk, "any_value(9)") == "9"


def test_load_file(tk):
    assert q1(tk, "load_file('/etc/passwd')") is None


def test_validate_password_strength(tk):
    assert int(q1(tk, "validate_password_strength('Ab1!efgh')")) == 100
    assert int(q1(tk, "validate_password_strength('ab')")) == 0


def test_charset_collation_coercibility(tk):
    assert q1(tk, "charset('x')") == "utf8mb4"
    assert q1(tk, "collation('x')") == "utf8mb4_bin"
    assert q1(tk, "coercibility('x')") == "2"


def test_advisory_locks(tk):
    assert q1(tk, "get_lock('l1', 0)") == "1"
    assert q1(tk, "is_free_lock('l1')") == "0"
    assert q1(tk, "is_used_lock('l1')") is not None
    assert q1(tk, "release_lock('l1')") == "1"
    assert q1(tk, "is_free_lock('l1')") == "1"
    assert q1(tk, "release_lock('l1')") is None


def test_date_add_sub_fn(tk):
    assert q1(tk, "date_add('2020-01-31', interval 1 month)"
              ).startswith("2020-02-29")
    assert q1(tk, "date_sub('2020-03-01', interval 1 day)"
              ).startswith("2020-02-29")
    assert q1(tk, "adddate('2020-01-01', 5)").startswith("2020-01-06")
    assert q1(tk, "subdate('2020-01-06', 5)").startswith("2020-01-01")
    assert q1(tk, "date_arith_fn('2020-01-31', 1, 'month')"
              ) == "2020-02-29"


def test_localtime_shapes(tk):
    assert len(q1(tk, "localtime()")) == 19
    assert len(q1(tk, "current_time()")) == 8
    assert len(q1(tk, "utc_date()")) == 10
    assert len(q1(tk, "utc_time()")) == 8


def test_position(tk):
    assert q1(tk, "position('b' in 'abc')") == "2"


def test_gtid_functions(tk):
    assert q1(tk, "gtid_subset('a:1-3', 'a:1-5')") == "1"
    assert q1(tk, "gtid_subset('a:7', 'a:1-5')") == "0"
    assert q1(tk, "gtid_subtract('a:1-5', 'a:2-3')") == "a:1:4-5"
    assert q1(tk, "wait_for_executed_gtid_set('a:1', 0)") == "0"
    d = q1(tk, "tidb_encode_sql_digest('select 1')")
    assert len(d) == 64


def test_tidb_info_funcs(tk):
    assert "tpu-htap" in q1(tk, "tidb_version()")
    assert q1(tk, "tidb_is_ddl_owner()") == "1"
    assert q1(tk, "tidb_parse_tso(0)") is None
    assert q1(tk, "tidb_parse_tso(449348000000000000)").startswith("2")
    assert 0 <= int(q1(tk, "tidb_shard(99)")) < 256
    assert q1(tk, "master_pos_wait('f', 'p', 0)") is None


def test_format_nano_time(tk):
    assert q1(tk, "format_nano_time(1500000)") == "1.50ms"


def test_tidb_decode_key(tk):
    from tidb_tpu.tablecodec import record_key
    import binascii
    hexkey = binascii.hexlify(record_key(11, 7)).decode()
    assert '"table_id": 11' in q1(tk, f"tidb_decode_key('{hexkey}')")


def test_aliases(tk):
    assert q1(tk, "ceiling(1.2)") == q1(tk, "ceil(1.2)")
    assert q1(tk, "power(2, 10)") == "1024"
    assert q1(tk, "substr('hello', 2, 3)") == "ell"
    assert q1(tk, "sha('x')") == q1(tk, "sha1('x')")


def test_truncate_exact_decimal(tk):
    assert q1(tk, "truncate(0.29, 2)") == "0.29"


def test_truncate_decimal_keeps_decimal_type(tk):
    # advisor r4: TRUNCATE on decimal input must keep the exact NEWDECIMAL
    # type (MySQL: DECIMAL in → DECIMAL out), not collapse to double
    tk.must_exec("create table trdec (d decimal(30, 6))")
    tk.must_exec("insert into trdec values "
                 "(123456789012345678901.654321), (-9.876543)")
    r = tk.must_query("select truncate(d, 2) from trdec order by d").rows
    assert [x[0] for x in r] == ["-9.87", "123456789012345678901.65"]
    r = tk.must_query("select truncate(d, 0) from trdec order by d").rows
    assert [x[0] for x in r] == ["-9", "123456789012345678901"]
    r = tk.must_query("select truncate(d, -1) from trdec order by d").rows
    assert [x[0] for x in r] == ["0", "123456789012345678900"]
    # int input, negative digits
    assert q1(tk, "truncate(1999, -2)") == "1900"
    assert q1(tk, "truncate(-1999, -2)") == "-1900"


def test_json_search_literal_star(tk):
    assert q1(tk, "json_search('[\"ab\"]', 'one', 'a*')") is None
    assert q1(tk, "json_search('[\"a*\"]', 'one', 'a*')") == '"$[0]"'


def test_json_overlaps_objects(tk):
    assert q1(tk, 'json_overlaps(\'{"a":1,"b":2}\', \'{"a":1}\')') == "1"
    assert q1(tk, 'json_overlaps(\'{"a":1}\', \'{"a":2}\')') == "0"


def test_convert_tz_unsigned_rejected(tk):
    assert q1(tk, "convert_tz('2004-01-01 12:00:00', '+00:00', '10:00')"
              ) is None


def test_advisory_locks_per_session(tk):
    from tidb_tpu.session import new_session
    s2 = new_session(tk.session.domain)
    assert q1(tk, "get_lock('xs', 0)") == "1"
    r2 = None
    for r in s2.execute("select get_lock('xs', 0)"):
        r2 = r.rows[0][0]
    assert r2 == "0"  # a DIFFERENT session on the same thread can't take it
    for r in s2.execute("select release_lock('xs')"):
        assert r.rows[0][0] == "0"  # nor release it
    assert q1(tk, "release_lock('xs')") == "1"


def test_release_all_locks(tk):
    assert q1(tk, "get_lock('ra1', 0)") == "1"
    assert q1(tk, "get_lock('ra2', 0)") == "1"
    assert int(q1(tk, "release_all_locks()")) >= 2
    assert q1(tk, "is_free_lock('ra1')") == "1"


def test_ps_current_thread_id(tk):
    assert int(q1(tk, "ps_current_thread_id()")) > 0


def test_truncate_column_digits_and_overflow(tk):
    # review r5: non-constant digit argument truncates per row; huge
    # negative digits must not overflow int64
    tk.must_exec("create table trn (x decimal(10,2), f double, n int)")
    tk.must_exec("insert into trn values (1.23, 1.29, 1), (9.87, 9.87, 0)")
    r = tk.must_query("select truncate(x, n), truncate(f, n) "
                      "from trn order by x").rows
    assert [tuple(row) for row in r] == [("1.2", "1.2"), ("9", "9")]
    assert q1(tk, "truncate(cast(1.23 as decimal(10,2)), -19)") == "0"
    assert q1(tk, "truncate(5, null)") is None
