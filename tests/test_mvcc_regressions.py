"""Regression tests for review findings: rollback-marker ordering, GC vs
rollback markers, multi-way join reorder, session txn cleanup."""

import pytest

from tidb_tpu.errors import WriteConflictError
from tidb_tpu.kv import new_store


@pytest.fixture(params=["python", "native"], autouse=True)
def kv_backend(request, monkeypatch):
    """Run every kv/mvcc test against BOTH engines: the Python reference
    implementation and the C++ native engine (native/mvcc_engine.cpp)."""
    if request.param == "native":
        from tidb_tpu.kv.native import load_engine
        if load_engine() is None:
            pytest.skip("native toolchain unavailable")
    monkeypatch.setenv("TIDB_TPU_KV_ENGINE", request.param)
from tidb_tpu.kv.mvcc import OP_PUT, OP_ROLLBACK
from tidb_tpu.testkit import TestKit


def test_rollback_marker_does_not_hide_newer_commit():
    """A rollback at an old start_ts must not mask a newer commit from
    write-conflict checks (lost update)."""
    s = new_store()
    t_old = s.begin()          # start_ts = T0
    t_commit = s.begin()
    t_commit.put(b"k", b"v100")
    t_commit.commit()          # commits at T2 > T0
    # the old txn aborts, writing a rollback marker at its old start_ts
    s.mvcc.rollback([b"k"], t_old.start_ts)
    # a mid-age txn must STILL see the newer commit as a conflict
    t_mid = s.begin()
    chain = s.mvcc.debug_chain(b"k")
    assert [op for _c, _s, op, _v in chain].count(OP_ROLLBACK) == 1
    with pytest.raises(WriteConflictError):
        s.mvcc.prewrite([(b"k", OP_PUT, b"lost")], b"k", t_old.start_ts)
    assert s.get_snapshot().get(b"k") == b"v100"


def test_chain_stays_sorted_desc():
    s = new_store()
    tss = []
    for i in range(3):
        t = s.begin()
        t.put(b"k", str(i).encode())
        tss.append(t.start_ts)
        t.commit()
    # rollback marker at the OLDEST start_ts lands in sorted position
    s.mvcc.rollback([b"k"], tss[0])
    chain = s.mvcc.debug_chain(b"k")
    commit_tss = [c for c, _s, _o, _v in chain]
    assert commit_tss == sorted(commit_tss, reverse=True)


def test_gc_keeps_live_put_under_rollback_marker():
    """GC must not treat a rollback marker as the visible version."""
    s = new_store()
    t = s.begin()
    t.put(b"k", b"v1")
    t.commit()
    t2 = s.begin()
    s.mvcc.rollback([b"k"], t2.start_ts)  # newer rollback marker
    s.mvcc.gc(s.next_ts())
    assert s.get_snapshot().get(b"k") == b"v1"


def test_three_way_join_reorder():
    """>=3-table comma joins crashed with RecursionError before the fix."""
    tk = TestKit()
    tk.must_exec("create table a (x int)")
    tk.must_exec("create table b (x int, y int)")
    tk.must_exec("create table c (y int, z int)")
    tk.must_exec("insert into a values (1),(2)")
    tk.must_exec("insert into b values (1,10),(2,20)")
    tk.must_exec("insert into c values (10,100),(20,200),(30,300)")
    tk.must_query(
        "select a.x, c.z from a, b, c where a.x=b.x and b.y=c.y order by a.x"
    ).check([("1", "100"), ("2", "200")])
    # five-way
    tk.must_exec("create table d (z int, w int)")
    tk.must_exec("create table e (w int)")
    tk.must_exec("insert into d values (100,7),(200,8)")
    tk.must_exec("insert into e values (7)")
    tk.must_query(
        "select a.x from a, b, c, d, e where a.x=b.x and b.y=c.y "
        "and c.z=d.z and d.w=e.w"
    ).check([("1",)])


def test_session_recovers_from_internal_error():
    """Non-TiDBError escaping a statement must not leave a dangling txn."""
    tk = TestKit()
    tk.must_exec("create table t (a int primary key)")
    tk.must_exec("insert into t values (1)")
    import tidb_tpu.executor.dml as dml
    orig = dml.InsertExec.execute
    def boom(self):
        self.session.txn_for_write()
        raise ValueError("synthetic executor crash")
    dml.InsertExec.execute = boom
    try:
        with pytest.raises(ValueError):
            tk.session.execute("insert into t values (2)")
    finally:
        dml.InsertExec.execute = orig
    assert tk.session.txn is None  # no dangling txn
    tk.must_exec("insert into t values (3)")
    tk.must_query("select a from t order by a").check([("1",), ("3",)])


def test_membuffer_sorted_invariant():
    s = new_store()
    t = s.begin()
    for k in [b"c", b"a", b"b", b"a"]:
        t.put(k, b"v")
    assert [k for k, _ in t.membuf.items_sorted()] == [b"a", b"b", b"c"]
    sp = t.membuf.savepoint()
    t.put(b"0", b"v")
    t.membuf.rollback_to(sp)
    assert [k for k, _ in t.membuf.items_sorted()] == [b"a", b"b", b"c"]
    assert t.membuf.range_items(b"b", b"c") == [(b"b", b"v")]
