"""Plan bindings (reference: bindinfo/handle.go, planner/optimize.go:147-207
binding match, mysql.bind_info)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t (id int primary key, a int, b int, key ia (a))")
    tk.must_exec("insert into t values "
                 + ",".join(f"({i},{i % 50},{i % 7})" for i in range(500)))
    tk.must_exec("analyze table t")
    return tk


def _explain(tk, sql):
    return "\n".join(" ".join(str(c) for c in r)
                     for r in tk.must_query("EXPLAIN " + sql).rows)


class TestIndexHints:
    def test_force_index(self, tk):
        txt = _explain(tk, "select * from t force index (ia) where a = 3")
        assert "index:ia" in txt

    def test_ignore_index(self, tk):
        txt = _explain(tk, "select * from t ignore index (ia) where a = 3")
        assert "IndexLookUp" not in txt and "TableScan" in txt

    def test_use_index_restricts_candidates(self, tk):
        tk.must_exec("alter table t add index ib (b)")
        txt = _explain(tk, "select * from t use index (ib) where a = 3")
        assert "index:ia" not in txt

    def test_hint_survives_restore(self, tk):
        from tidb_tpu.parser import parse
        s = parse("select * from t force index (ia) where a = 3")[0]
        assert "FORCE INDEX (`ia`)" in s.restore()


class TestSessionBindings:
    def test_binding_changes_plan_and_drops(self, tk):
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        # literals normalize away: different constant still matches
        assert "IndexLookUp" not in _explain(tk, "select * from t where a = 77")
        rows = tk.must_query("show bindings").rows
        assert len(rows) == 1 and "IGNORE INDEX" in str(rows[0][1])
        tk.must_exec("drop session binding for select * from t where a = 3")
        assert "IndexLookUp" in _explain(tk, "select * from t where a = 3")

    def test_session_binding_is_session_local(self, tk):
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        assert "IndexLookUp" in _explain(tk2, "select * from t where a = 3")


class TestGlobalBindings:
    def test_global_binding_applies_across_sessions(self, tk):
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        assert "IndexLookUp" not in _explain(tk2, "select * from t where a = 9")
        assert len(tk.must_query("show global bindings").rows) == 1
        tk.must_exec("drop global binding for select * from t where a = 3")
        assert "IndexLookUp" in _explain(tk2, "select * from t where a = 3")

    def test_global_binding_persists_in_catalog(self, tk):
        """A new BindHandle over the same store sees the binding (the
        mysql.bind_info persistence role)."""
        from tidb_tpu.bindinfo import BindHandle
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t force index (ia) where a = 3")
        fresh = BindHandle(tk.session.domain)
        assert len(fresh.list()) == 1
        tk.must_exec("drop global binding for select * from t where a = 3")

    def test_session_binding_shadows_global(self, tk):
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t force index (ia) where a = 3")
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        assert "IndexLookUp" not in _explain(tk, "select * from t where a = 3")
        tk.must_exec("drop session binding for select * from t where a = 3")
        tk.must_exec("drop global binding for select * from t where a = 3")


class TestBindingValidation:
    def test_binding_without_hints_rejected(self, tk):
        e = tk.exec_error("create session binding for "
                          "select * from t where a = 3 using "
                          "select * from t where a = 3")
        assert "no hints" in str(e)

    def test_mismatched_statements_rejected(self, tk):
        tk.must_exec("create table x (b int, key ib (b))")
        e = tk.exec_error("create session binding for "
                          "select * from t where a = 3 using "
                          "select * from x use index (ib) where b = 2")
        assert "different" in str(e)

    def test_binding_scoped_to_database(self, tk):
        """A binding created in one db must not hijack a same-named table
        in another db."""
        tk.must_exec("create global binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        tk.must_exec("create database otherdb")
        tk.must_exec("use otherdb")
        tk.must_exec("create table t (id int primary key, a int, key ia (a))")
        tk.must_exec("insert into t values "
                     + ",".join(f"({i},{i % 20})" for i in range(300)))
        tk.must_exec("analyze table t")
        assert "IndexLookUp" in _explain(tk, "select * from t where a = 3")
        tk.must_exec("use test")
        tk.must_exec("drop global binding for select * from t where a = 3")

    def test_prepared_stmt_unaffected_after_drop(self, tk):
        """Regression: binding hints must not persist on a cached prepared
        AST after DROP BINDING."""
        sess = tk.session
        stmt_ast, _np = sess.prepare("select * from t where a = 3")
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        sess.execute_prepared(stmt_ast, [])
        tk.must_exec("drop session binding for select * from t where a = 3")
        # re-plan of the SAME ast must use the index again
        plan = sess.plan_query(stmt_ast)
        from tidb_tpu.planner.logical import explain_tree
        txt = "\n".join(f"{a} {b}" for a, b in explain_tree(plan))
        assert "IndexLookUp" in txt


class TestBindingSelfJoin:
    def test_per_occurrence_hints(self, tk):
        """A self-join binding keeps different hints per occurrence."""
        tk.must_exec("create session binding for "
                     "select * from t a, t b where a.id = b.id and a.a = 1 "
                     "using "
                     "select * from t a force index (ia), "
                     "t b ignore index (ia) "
                     "where a.id = b.id and a.a = 1")
        from tidb_tpu.bindinfo import hints_from_record
        rec = next(iter(tk.session.session_bindings.values()))
        verbs = [h[0][0] for _t, h in hints_from_record(rec) if h]
        assert sorted(verbs) == ["force", "ignore"]  # both occurrences kept
        # functional check: a (which carries the sargable filter) goes
        # through ia; b stays a plain scan
        txt = _explain(tk, "select * from t a, t b "
                           "where a.id = b.id and a.a = 5")
        assert txt.count("index:ia") == 1 and "table:a, index:ia" in txt
        tk.must_exec("drop session binding for "
                     "select * from t a, t b where a.id = b.id and a.a = 1")


class TestBindingPrivileges:
    def test_global_binding_requires_super(self, tk):
        tk.must_exec("create user 'plain'@'%'")
        tk.must_exec("grant select on test.* to 'plain'@'%'")
        tk2 = tk.new_session()
        tk2.session.user = "plain@%"
        e = tk2.exec_error("create global binding for "
                           "select * from t where a = 3 using "
                           "select * from t ignore index (ia) where a = 3")
        assert "denied" in str(e).lower()
        # session-scope bindings are allowed for any user
        tk2.must_exec("create session binding for "
                      "select * from t where a = 3 using "
                      "select * from t ignore index (ia) where a = 3")


class TestOptimizerHints:
    """/*+ ... */ hint comments (reference: parser/hintparser.y grammar;
    planner honors them before cost, exhaust_physical_plans.go)."""

    def setup_join(self, tk):
        tk.must_exec("create table j1 (a bigint, b bigint, key (a))")
        tk.must_exec("create table j2 (a bigint, c bigint)")
        tk.must_exec("insert into j1 values " + ",".join(
            f"({i},{i})" for i in range(1, 40)))
        tk.must_exec("insert into j2 values " + ",".join(
            f"({i % 20},{i})" for i in range(80)))

    def test_merge_join_hint_changes_plan(self, tk):
        self.setup_join(tk)
        sql = ("select j1.a, sum(c) from j1, j2 where j1.a = j2.a "
               "group by j1.a")
        assert "MergeJoin" not in _explain(tk, sql)
        assert "MergeJoin" in _explain(
            tk, sql.replace("select ", "select /*+ MERGE_JOIN(j2) */ ", 1))

    def test_stream_agg_hint(self, tk):
        self.setup_join(tk)
        sql = ("select /*+ STREAM_AGG() */ j1.a, count(*) from j1, j2 "
               "where j1.a = j2.a group by j1.a")
        assert "StreamAgg" in _explain(tk, sql)
        # parity with the unhinted plan
        plain = tk.must_query(
            "select j1.a, count(*) from j1, j2 where j1.a = j2.a "
            "group by j1.a order by j1.a").rows
        hinted = tk.must_query(
            "select /*+ STREAM_AGG() */ j1.a, count(*) from j1, j2 "
            "where j1.a = j2.a group by j1.a order by j1.a").rows
        assert plain == hinted

    def test_unknown_hint_ignored(self, tk):
        self.setup_join(tk)
        rows = tk.must_query(
            "select /*+ NO_SUCH_HINT(x) */ count(*) from j1").rows
        assert rows == [("39",)]

    def test_hints_do_not_change_digest(self, tk):
        from tidb_tpu.parser import normalize
        a = normalize("select /*+ HASH_JOIN(t) */ a from t")
        b = normalize("select a from t")
        assert a == b

    def test_engine_pin_hint(self, tk):
        self.setup_join(tk)
        rows = tk.must_query(
            "select /*+ READ_FROM_STORAGE(HOST(j1)) */ j1.a, sum(c) "
            "from j1, j2 where j1.a = j2.a group by j1.a "
            "order by j1.a").rows
        plain = tk.must_query(
            "select j1.a, sum(c) from j1, j2 where j1.a = j2.a "
            "group by j1.a order by j1.a").rows
        assert rows == plain

    def test_binding_with_optimizer_hints(self, tk):
        self.setup_join(tk)
        sql = ("select j1.a, sum(c) from j1, j2 where j1.a = j2.a "
               "group by j1.a")
        tk.must_exec(
            f"create global binding for {sql} using "
            + sql.replace("select ",
                          "select /*+ MERGE_JOIN(j2) STREAM_AGG() */ ", 1))
        try:
            plan = _explain(tk, sql)
            assert "MergeJoin" in plan and "StreamAgg" in plan
        finally:
            tk.must_exec(f"drop global binding for {sql}")
        assert "MergeJoin" not in _explain(tk, sql)


class TestBaselineCapture:
    def test_capture_on_second_execution_and_persistence(self, tk):
        """reference: bindinfo/handle.go:749 auto-capture; the captured
        record persists in the catalog, so a fresh BindHandle (restart
        analog) still serves it."""
        tk.must_exec("create table cap1 (a bigint, b bigint, key (a))")
        tk.must_exec("create table cap2 (a bigint, c bigint)")
        tk.must_exec("insert into cap1 values (1,1),(2,2)")
        tk.must_exec("insert into cap2 values (1,5),(2,6)")
        tk.must_exec("set global tidb_capture_plan_baselines = ON")
        try:
            sql = ("select cap1.a, sum(c) from cap1, cap2 "
                   "where cap1.a = cap2.a group by cap1.a")
            tk.must_query(sql)
            assert not any("cap1" in str(r[0]).lower()
                           for r in tk.must_query(
                               "show global bindings").rows)
            tk.must_query(sql)  # second planning triggers capture
            binds = tk.must_query("show global bindings").rows
            assert any("cap1" in str(r[0]).lower() for r in binds), binds
            captured = next(r for r in binds
                            if "cap1" in str(r[0]).lower())
            assert "/*+" in str(captured[1])  # hinted bind text
            # persistence: a fresh handle over the same store (restart)
            from tidb_tpu.bindinfo import BindHandle, binding_key
            from tidb_tpu.parser import parse
            fresh = BindHandle(tk.session.domain)
            fresh.load()
            from tidb_tpu.bindinfo import normalized_sql
            key = binding_key("test", normalized_sql(parse(sql)[0]))
            assert fresh.match(key) is not None
        finally:
            tk.must_exec("set global tidb_capture_plan_baselines = OFF")
