"""Object-store-shaped blob API for region replication (ISSUE 16).

Region checkpoints and sealed WAL tails replicate to a *blob store* so a
host loss becomes a region failover instead of data loss: the surviving
host restores checkpoint + tail from here and replays.  The interface is
deliberately the GCS/S3 shape — flat string names, whole-object put/get,
prefix list — so the local-directory implementation below can be swapped
for a real bucket later without touching the replication protocol.

Durability contract of :meth:`BlobStore.put` (the property the torn-
upload test pins): an object is visible under its final name only when
its bytes are complete — write to a temp name, fsync, then rename LAST.
A reader can therefore trust any listed object; a crash mid-upload
leaves at most an invisible temp file, never a short object.
"""

from __future__ import annotations

import os
import tempfile

from ..session import tracing


class BlobError(Exception):
    """A blob-store operation failed (missing object, bad name)."""


class BlobStore:
    """The object-store surface the region replicator codes against."""

    def put(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, name: str) -> bytes:
        raise NotImplementedError

    def list(self, prefix: str = "") -> "list[str]":
        raise NotImplementedError

    def exists(self, name: str) -> bool:
        raise NotImplementedError

    def delete(self, name: str) -> None:
        raise NotImplementedError


class LocalDirBlobStore(BlobStore):
    """Blob store over a local directory ("/" in names maps to
    subdirectories).  put() is rename-last: tmp file + fsync +
    ``os.replace`` + directory fsync, so a SIGKILL mid-upload can never
    leave a torn object under its final name."""

    #: temp-upload prefix; never listed, swept lazily
    TMP_PREFIX = ".tmp-"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, name: str) -> str:
        if not name or name.startswith(("/", ".")) or ".." in name.split("/"):
            raise BlobError(f"bad blob name {name!r}")
        return os.path.join(self.root, *name.split("/"))

    def put(self, name: str, data: bytes) -> None:
        tracing.event("blob.put", blob=name, bytes=len(data))
        path = self._path(name)
        d = os.path.dirname(path)
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=self.TMP_PREFIX, dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # rename LAST: visibility == completeness
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        dirfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)

    def get(self, name: str) -> bytes:
        tracing.event("blob.get", blob=name)
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise BlobError(f"no such blob: {name}") from None

    def list(self, prefix: str = "") -> "list[str]":
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            base = "" if rel == "." else rel.replace(os.sep, "/") + "/"
            for fn in files:
                if fn.startswith(self.TMP_PREFIX):
                    continue  # an in-flight (or abandoned) upload
                name = base + fn
                if name.startswith(prefix):
                    out.append(name)
        out.sort()
        return out

    def exists(self, name: str) -> bool:
        return os.path.isfile(self._path(name))

    def delete(self, name: str) -> None:
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass


def open_blob_store(url: str) -> BlobStore:
    """Factory: a plain path or ``file://`` URL opens the local-dir
    implementation; ``gs://`` / ``s3://`` are the same interface backed
    by a real bucket — not wired in this repo (no cloud SDK dependency),
    gated loudly rather than silently falling back."""
    if url.startswith("file://"):
        return LocalDirBlobStore(url[len("file://"):])
    if url.startswith(("gs://", "s3://")):
        raise NotImplementedError(
            f"remote blob store {url!r} needs a cloud SDK this build "
            "does not ship; use a local path (same BlobStore interface)")
    return LocalDirBlobStore(url)
