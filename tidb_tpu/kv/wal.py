"""Write-ahead log for the embedded MVCC store (the durable half of the
paper's "many SQL servers over ONE storage layer").

One append-only file of CRC-framed records::

    FILE HEADER   <8s magic><Q base_lsn>          (16 bytes)
    RECORD        <I payload_len><I crc32(payload)><payload>

``lsn`` is the logical byte position since log birth (monotonic across
truncations): ``physical offset = lsn - base_lsn``.  Payloads are small
pickled tuples — the logical MVCC operations of kv/shared_store.py
(prewrite / commit / rollback / raw puts / delete-range), each stamped
with its origin slot so a fleet worker tailing the log skips its own
records.

Durability contract:

* a record is WRITTEN (OS-buffered) at append time — that is what makes
  it visible to fleet tailers — and DURABLE once fsynced;
* the fsync policy is the ``tidb_wal_fsync`` GLOBAL sysvar:
  ``commit`` (default) — every commit append joins a GROUP fsync: one
  leader fsyncs the file once for every append that landed before it
  took the flush lock, followers whose offset is already covered return
  without syncing; ``interval`` — a background flusher fsyncs every
  ``INTERVAL_S``; ``never`` — no fsync (crash loses the OS buffer tail,
  torn/unsynced records are CRC-truncated at recovery);
* recovery scans from the checkpoint (or base), verifies each frame's
  CRC and TRUNCATES the file at the first torn/short/corrupt record —
  later garbage can never be replayed as data.

Torn-tail fencing in the SHARED (fleet) deployment: appends happen
under the cross-process file lock, and the committed length lives in a
segment cell (fabric/coord.py ``_wal_len``) — every appender first
truncates any garbage a SIGKILLed writer left past the cell, so a torn
record from a dead peer can never sit UNDER a survivor's appends, and
tailers never read past the cell.

Checkpoint: ``checkpoint(state_blob)`` writes the engine snapshot
(tmp + atomic rename) stamped with the current LSN, then truncates the
log tail up to the smallest LSN every live fleet replica has applied —
recovery becomes "load snapshot, replay the short tail".

Region sharding (fabric/region.py): a region-sharded store holds one
WAL PER REGION under ``<root>/region-<rid>/`` (:func:`region_dir` /
:func:`region_ids` name the layout), each wired to a
``RegionCoordView`` whose committed-length/applied-LSN cells are the
region's own segment row — epoch-fenced, so a zombie host's appender
fails loudly (``check_fence`` hook below) instead of writing into a
region that failed over.  :meth:`WAL.tail_bytes` and
:func:`write_wal_files` are the replication unit: the physical framed
tail ships to the blob store and is materialized verbatim on restore.

Failpoints (chaos + crash-matrix hooks): ``wal-append-torn`` (payload
``torn``: write half the frame, heal by truncating back, fail the
append; payload ``kill``: write half the frame and SIGKILL — the torn
bytes stay for recovery to CRC-truncate; ``panic`` action: fail before
writing), ``wal-fsync-fail`` (``panic``: the fsync raises — the commit
fails classified; ``kill``: SIGKILL before the fsync; payload ``eio``:
the fsync itself fails OSError — ``N*return(eio)`` makes the failure
transient, the shape the budgeted ``walSyncRetry`` attempt absorbs).
"""

from __future__ import annotations

import contextlib
import logging
import os
import pickle
import signal
import struct
import threading
import zlib

from ..utils import failpoint
from ..utils.failpoint import FailpointError

log = logging.getLogger("tidb_tpu.kv.wal")

WAL_MAGIC = b"TPUWAL1\0"
_FHDR = struct.Struct("<8sQ")     # magic, base_lsn
_RHDR = struct.Struct("<II")      # payload_len, crc32
#: sanity bound on one record (a corrupt length field must not allocate)
MAX_RECORD = 64 << 20

#: fsync cadence for the ``interval`` policy
INTERVAL_S = 0.02

#: process-wide gauges (every WAL instance bumps these; snapshot() /
#: report_gauges() follow the fabric/state.py surfacing pattern)
STATS = {
    "wal_appends": 0,            # records appended by this process
    "wal_bytes": 0,              # payload+frame bytes appended
    "wal_fsyncs": 0,             # physical fsync calls
    "wal_group_commits": 0,      # commit appends served by a PEER's fsync
    "wal_checkpoints": 0,        # checkpoints written
    "wal_recoveries": 0,         # recovery passes run
    "wal_replayed_records": 0,   # records applied during recovery
    "wal_truncated_records": 0,  # torn/CRC-bad tail records dropped
    "wal_tail_records": 0,       # foreign records applied by the tailer
    "wal_fsync_errors": 0,       # failed fsyncs (commit failed classified)
    "wal_fsync_retries": 0,      # budgeted walSyncRetry attempts that ran
}
_STATS_LOCK = threading.Lock()


def _bump(key: str, n: int = 1):
    with _STATS_LOCK:
        STATS[key] += n


def snapshot() -> dict:
    with _STATS_LOCK:
        return dict(STATS)


def report_gauges() -> dict:
    """EXPLAIN ANALYZE surfacing (fired-only, like fabric/state.py):
    empty when no WAL has ever appended in this process, so ordinary
    in-memory deployments carry zero annotation noise."""
    s = snapshot()
    if not (s["wal_appends"] or s["wal_recoveries"]):
        return {}
    out = {"wal_appends": s["wal_appends"], "wal_fsyncs": s["wal_fsyncs"]}
    for k in ("wal_group_commits", "wal_replayed_records",
              "wal_truncated_records", "wal_tail_records",
              "wal_fsync_errors", "wal_checkpoints"):
        if s[k]:
            out[k] = s[k]
    return out


def reset_for_tests():
    with _STATS_LOCK:
        for k in STATS:
            STATS[k] = 0


def region_dir(root: str, rid: int) -> str:
    """The per-region WAL directory under a sharded store's root."""
    return os.path.join(root, f"region-{rid}")


def region_ids(root: str) -> "list[int]":
    """Region ids with a WAL directory under ``root`` (sorted)."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        if name.startswith("region-"):
            with contextlib.suppress(ValueError):
                out.append(int(name[len("region-"):]))
    out.sort()
    return out


def write_wal_files(dirpath: str, base_lsn: int, tail: bytes,
                    checkpoint: "bytes | None" = None) -> None:
    """Materialize a WAL directory from replicated parts (the restore
    half of region failover): ``wal.log`` = header(base_lsn) + the
    physical framed tail, ``checkpoint.bin`` verbatim (it carries its
    own header + CRC).  Atomic renames + fsync, so a crash mid-restore
    leaves no half-written log for recovery to misread."""
    os.makedirs(dirpath, exist_ok=True)
    if checkpoint is not None:
        tmp = os.path.join(dirpath, f"checkpoint.{os.getpid()}.rst")
        with open(tmp, "wb") as f:
            f.write(checkpoint)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(dirpath, "checkpoint.bin"))
    tmp = os.path.join(dirpath, f"wal.{os.getpid()}.rst")
    with open(tmp, "wb") as f:
        f.write(_FHDR.pack(WAL_MAGIC, base_lsn))
        f.write(tail)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dirpath, "wal.log"))


class WAL:
    """One process's handle on the log directory.

    Files: ``wal.log`` (the framed log), ``checkpoint.bin`` (engine
    snapshot + its LSN), ``wal.lock`` (the cross-process append flock —
    per open file description, so it excludes sibling PROCESSES; the
    in-process ``_lock`` mutex excludes sibling threads).
    """

    def __init__(self, dirpath: str, *, coordinator=None,
                 fsync_default: str = "commit"):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.path = os.path.join(dirpath, "wal.log")
        self.ckpt_path = os.path.join(dirpath, "checkpoint.bin")
        self._coord = coordinator
        self._lock = threading.RLock()
        self._flush_cv = threading.Condition(threading.Lock())
        self._synced_lsn = 0
        self._flushing = False
        self._closed = False
        #: durable-commit frontier plumbing (ISSUE 19): append(...,
        #: commit_ts=) notes (end_lsn, commit_ts) marks here; the fsync
        #: that covers a mark fires ``on_durable(max_commit_ts, lsn)``
        #: exactly once — the hook kv/shared_store.py wires to the
        #: segment's per-slot frontier cell
        self._marks_lock = threading.Lock()
        self._pending_marks = []   # [(end_lsn, commit_ts)], lsn-ordered
        self.on_durable = None
        #: resolved at each decision point: a callable returning the
        #: sysvar string (Domain installs one reading GLOBAL scope);
        #: until then the env/ctor default applies
        self.policy_source = None
        self._fsync_default = os.environ.get("TIDB_TPU_WAL_FSYNC",
                                             fsync_default)
        self._lockf = open(os.path.join(dirpath, "wal.lock"),  # noqa: SIM115
                           "a+b")
        if not os.path.exists(self.path):
            with self._flocked():
                if not os.path.exists(self.path):
                    tmp = self.path + f".{os.getpid()}.init"
                    with open(tmp, "wb") as f:
                        f.write(_FHDR.pack(WAL_MAGIC, 0))
                    os.replace(tmp, self.path)
        self._f = open(self.path, "r+b")  # noqa: SIM115 (held open)
        hdr = self._f.read(_FHDR.size)
        magic, self.base_lsn = _FHDR.unpack(hdr)
        if magic != WAL_MAGIC:
            raise ValueError(f"{self.path}: bad WAL magic {magic!r}")
        self._f.seek(0, os.SEEK_END)
        self._interval_stop = threading.Event()
        self._interval_thread = None

    # -- policy ---------------------------------------------------------------

    def fsync_policy(self) -> str:
        src = self.policy_source
        if src is not None:
            try:
                v = str(src()).lower()
                if v in ("never", "interval", "commit"):
                    return v
            except Exception as e:  # noqa: BLE001 — a torn-down domain
                #   must not fail commits; fall through to the default
                log.debug("wal fsync policy source failed: %s", e)
        return self._fsync_default

    # -- lsn bookkeeping ------------------------------------------------------

    def end_lsn(self) -> int:
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            return self.base_lsn + self._f.tell() - _FHDR.size

    def committed_lsn(self) -> int:
        """The readable frontier: the segment's committed-length cell in
        fleet mode (a torn tail from a dead peer sits past it), the file
        end solo."""
        if self._coord is not None:
            try:
                n = self._coord.wal_len()
                if n:
                    return n
            except Exception as e:  # noqa: BLE001 — segment may be gone
                log.debug("wal committed-length cell unreadable: %s", e)
        return self.end_lsn()

    @contextlib.contextmanager
    def _flocked(self):
        import fcntl
        fcntl.flock(self._lockf, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(self._lockf, fcntl.LOCK_UN)

    # -- append ---------------------------------------------------------------

    def append(self, record: tuple, sync: "bool | None" = None,
               commit_ts: int = 0) -> int:
        """Frame + write one record; returns its END lsn.  ``sync=True``
        (commit records under policy ``commit``) blocks until the bytes
        are fsynced via the group protocol; ``sync=None`` derives from
        the policy.  ``commit_ts`` (commit records) marks the record for
        the durable-frontier hook: the fsync that covers it fires
        ``on_durable`` — under policy ``commit`` that publish therefore
        precedes the client's ack; under ``interval`` it trails by at
        most one flush period (the group-commit window)."""
        from ..session import tracing
        payload = pickle.dumps(record, protocol=4)
        if len(payload) > MAX_RECORD:
            raise ValueError(f"wal record too large: {len(payload)}")
        frame = _RHDR.pack(len(payload), zlib.crc32(payload)) + payload
        policy = self.fsync_policy()
        if sync is None:
            sync = False
        with tracing.span("store.wal_append", bytes=len(frame),
                          sync=bool(sync and policy == "commit")):
            with self._lock, self._flocked():
                if self._closed:
                    raise FailpointError("wal closed")
                # region fencing: a RegionCoordView checks its epoch is
                # still current BEFORE any byte lands — a stale appender
                # (zombie host whose region failed over) dies loudly here
                fence = getattr(self._coord, "check_fence", None)
                if fence is not None:
                    fence()
                end = self._repair_tail_locked()
                fp = failpoint.inject("wal-append-torn")
                if fp:
                    # write HALF the frame — the torn-record shape the
                    # recovery CRC scan must truncate
                    self._f.seek(end - self.base_lsn + _FHDR.size)
                    self._f.write(frame[:max(len(frame) // 2, 1)])
                    self._f.flush()
                    if fp == "kill":
                        os.fsync(self._f.fileno())
                        os.kill(os.getpid(), signal.SIGKILL)
                    # in-process injection: HEAL (truncate back) so later
                    # appends land on a clean tail, then fail the append
                    self._f.truncate(end - self.base_lsn + _FHDR.size)
                    raise FailpointError(
                        "failpoint wal-append-torn triggered")
                self._f.seek(end - self.base_lsn + _FHDR.size)
                self._f.write(frame)
                self._f.flush()
                new_end = end + len(frame)
                if self._coord is not None:
                    self._coord.set_wal_len(new_end)
                if commit_ts and self.on_durable is not None:
                    with self._marks_lock:
                        self._pending_marks.append((new_end,
                                                    int(commit_ts)))
            _bump("wal_appends")
            _bump("wal_bytes", len(frame))
            if policy == "commit" and sync:
                self._sync_to(new_end)
            elif policy == "interval":
                self._ensure_interval_flusher()
            return new_end

    def _revalidate_handle_locked(self):
        """A peer's checkpoint truncation rewrites wal.log via
        os.replace: writing through a handle on the OLD (unlinked)
        inode would durably 'commit' a record no reader can ever see.
        Called under the flock before any write through ``_f``."""
        try:
            if os.stat(self.path).st_ino == os.fstat(
                    self._f.fileno()).st_ino:
                return
        except OSError:
            return
        with contextlib.suppress(OSError):
            self._f.close()
        self._f = open(self.path, "r+b")  # noqa: SIM115 (held open)
        hdr = self._f.read(_FHDR.size)
        _magic, self.base_lsn = _FHDR.unpack(hdr)
        self._f.seek(0, os.SEEK_END)

    def _repair_tail_locked(self) -> int:
        """The shared-log torn-tail fence: truncate any garbage past the
        fleet's committed-length cell (a SIGKILLed peer died mid-append)
        and return the clean end lsn.  Solo (no segment): the file end
        IS the committed end — torn bytes there are handled at
        recovery, and in-process injected tears heal in append()."""
        self._revalidate_handle_locked()
        self._f.seek(0, os.SEEK_END)
        file_end = self.base_lsn + self._f.tell() - _FHDR.size
        if self._coord is None:
            return file_end
        try:
            cell = self._coord.wal_len()
        except Exception as e:  # noqa: BLE001 — segment may be unlinked
            log.debug("wal len cell unreadable at append: %s", e)
            return file_end
        if not cell:
            return file_end
        if file_end > cell:
            self._f.truncate(cell - self.base_lsn + _FHDR.size)
            _bump("wal_truncated_records")
            return cell
        if file_end < cell:
            # a peer wrote the bytes but we raced its cell update, or
            # the file was truncated behind the cell: trust the file
            self._coord.set_wal_len(file_end)
        return file_end

    # -- group fsync ----------------------------------------------------------

    def _sync_to(self, lsn: int):
        """Group commit: one leader fsyncs for every append that landed
        before it took over; followers whose lsn is already covered
        return without a syscall (counted ``wal_group_commits``)."""
        while True:
            with self._flush_cv:
                if self._synced_lsn >= lsn:
                    _bump("wal_group_commits")
                    return
                if self._flushing:
                    self._flush_cv.wait(timeout=1.0)
                    continue
                self._flushing = True
            try:
                self._fsync_once()
            finally:
                with self._flush_cv:
                    self._flushing = False
                    self._flush_cv.notify_all()
            with self._flush_cv:
                if self._synced_lsn >= lsn:
                    return  # leader: own fsync covered it (not a group hit)
            # loop: another append raced past; wait for the next flush

    def _fsync_once(self):
        from ..utils.backoff import Backoffer, BackoffExhaustedError
        # capture the frontier FIRST: the fsync covers at least this
        cover = self.end_lsn()
        bo = None
        while True:
            fp = failpoint.inject("wal-fsync-fail")
            if fp == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            try:
                if fp == "eio":
                    raise OSError(
                        5, "Input/output error (injected by failpoint "
                        "wal-fsync-fail)")
                os.fsync(self._f.fileno())
            except OSError as e:
                _bump("wal_fsync_errors")
                # one budgeted walSyncRetry attempt: a transient
                # EIO/ENOSPC blip must not abort a durable commit, a
                # sick disk must still fail fast (budget, not a spin)
                if bo is None:
                    bo = Backoffer(budget_ms=100.0)
                try:
                    bo.backoff("walSyncRetry", e)
                except BackoffExhaustedError:
                    raise e from None
                _bump("wal_fsync_retries")
                continue
            break
        _bump("wal_fsyncs")
        with self._flush_cv:
            if cover > self._synced_lsn:
                self._synced_lsn = cover
        self._fire_durable(cover)

    def _fire_durable(self, cover: int):
        """Resolve the commit marks an fsync just covered and fire the
        frontier hook once with their max commit_ts.  A hook failure is
        logged, never allowed to fail the commit that drove the fsync —
        the worker heartbeat republishes the frontier every beat, so a
        dropped publish is a lag blip, not a lost gate."""
        if self.on_durable is None:
            return
        with self._marks_lock:
            done = [ts for lsn, ts in self._pending_marks if lsn <= cover]
            if not done:
                return
            self._pending_marks = [(lsn, ts) for lsn, ts
                                   in self._pending_marks if lsn > cover]
        try:
            self.on_durable(max(done), cover)
        except Exception as e:  # noqa: BLE001 — observe/coordination
            #   surface; the durable bytes themselves are already safe
            log.warning("wal durable-frontier hook failed: %s", e)

    def _ensure_interval_flusher(self):
        if self._interval_thread is not None \
                and self._interval_thread.is_alive():
            return
        with self._lock:
            if self._interval_thread is not None \
                    and self._interval_thread.is_alive():
                return

            def loop():
                while not self._interval_stop.wait(INTERVAL_S):
                    try:
                        with self._flush_cv:
                            if self._flushing:
                                continue
                            self._flushing = True
                        try:
                            self._fsync_once()
                        finally:
                            with self._flush_cv:
                                self._flushing = False
                                self._flush_cv.notify_all()
                    except Exception as e:  # noqa: BLE001 — background
                        #   flush failure is surfaced via the gauge; the
                        #   next commit-path fsync re-raises for real
                        log.warning("wal interval fsync failed: %s", e)

            self._interval_thread = threading.Thread(
                target=loop, daemon=True, name="wal-interval-fsync")
            self._interval_thread.start()

    # -- read side ------------------------------------------------------------

    def read_records(self, from_lsn: int, upto_lsn: "int | None" = None):
        """Yield ``(record, end_lsn)`` from ``from_lsn`` to the
        committed frontier (or ``upto_lsn``), stopping CLEANLY at the
        first torn/corrupt frame (the caller decides whether that is a
        recovery-truncation point or simply the current end)."""
        end = self.committed_lsn() if upto_lsn is None else upto_lsn
        if from_lsn >= end:
            return
        with open(self.path, "rb") as f:
            hdr = f.read(_FHDR.size)
            magic, base = _FHDR.unpack(hdr)
            if magic != WAL_MAGIC:
                return
            pos = from_lsn
            if pos < base:
                raise ValueError(
                    f"wal tail starts at {base}, reader wants {pos}: "
                    "replica predates the last truncation")
            f.seek(pos - base + _FHDR.size)
            while pos < end:
                rh = f.read(_RHDR.size)
                if len(rh) < _RHDR.size:
                    return
                plen, crc = _RHDR.unpack(rh)
                if plen > MAX_RECORD or pos + _RHDR.size + plen > end:
                    return
                payload = f.read(plen)
                if len(payload) < plen or zlib.crc32(payload) != crc:
                    return
                try:
                    rec = pickle.loads(payload)
                except Exception as e:  # noqa: BLE001 — crc passed but
                    #   the pickle is bad: treat as torn (stop cleanly)
                    log.warning("wal record at lsn %d undecodable "
                                "(treated as torn tail): %s", pos, e)
                    return
                pos += _RHDR.size + plen
                yield rec, pos

    def scan_valid_end(self) -> int:
        """CRC-scan the physical file and return the lsn of the last
        frame-complete record (the recovery truncation point)."""
        with open(self.path, "rb") as f:
            hdr = f.read(_FHDR.size)
            _magic, base = _FHDR.unpack(hdr)
            f.seek(0, os.SEEK_END)
            file_end = base + f.tell() - _FHDR.size
            pos = base
            f.seek(_FHDR.size)
            while pos < file_end:
                rh = f.read(_RHDR.size)
                if len(rh) < _RHDR.size:
                    break
                plen, crc = _RHDR.unpack(rh)
                if plen > MAX_RECORD or pos + _RHDR.size + plen > file_end:
                    break
                payload = f.read(plen)
                if len(payload) < plen or zlib.crc32(payload) != crc:
                    break
                pos += _RHDR.size + plen
            return pos

    def truncate_torn_tail(self) -> int:
        """Recovery-time torn-tail truncation: cut the file at the last
        valid frame; returns the number of torn bytes dropped."""
        with self._lock, self._flocked():
            self._revalidate_handle_locked()
            good = self.scan_valid_end()
            self._f.seek(0, os.SEEK_END)
            file_end = self.base_lsn + self._f.tell() - _FHDR.size
            torn = file_end - good
            if torn > 0:
                self._f.truncate(good - self.base_lsn + _FHDR.size)
                _bump("wal_truncated_records")
            if self._coord is not None:
                with contextlib.suppress(Exception):
                    cell = self._coord.wal_len()
                    if not cell or cell > good:
                        self._coord.set_wal_len(good)
            return max(torn, 0)

    def tail_bytes(self, from_lsn: "int | None" = None) -> tuple:
        """The physical framed bytes from ``from_lsn`` (default: the
        file base) to the committed frontier, as ``(start_lsn, bytes)``
        — the unit RegionReplicator ships to the blob store.  Reading
        stops at the COMMITTED length, so a torn tail a dying peer left
        past the cell never replicates."""
        with self._lock, self._flocked():
            self._revalidate_handle_locked()
            end = self.committed_lsn()
            start = (self.base_lsn if from_lsn is None
                     else max(from_lsn, self.base_lsn))
            if end <= start:
                return (start, b"")
            self._f.seek(start - self.base_lsn + _FHDR.size)
            data = self._f.read(end - start)
            self._f.seek(0, os.SEEK_END)
            return (start, data)

    # -- checkpoint -----------------------------------------------------------

    def read_checkpoint(self) -> "tuple[int, bytes] | None":
        """-> (lsn, engine-state blob) or None.  CRC-guarded like the
        log itself: a torn checkpoint (crash mid-rename never happens —
        rename is atomic — but a corrupt disk read might) falls back to
        full-log replay."""
        try:
            with open(self.ckpt_path, "rb") as f:
                hdr = f.read(_FHDR.size + _RHDR.size)
                magic, lsn = _FHDR.unpack_from(hdr, 0)
                plen, crc = _RHDR.unpack_from(hdr, _FHDR.size)
                if magic != WAL_MAGIC or plen > (1 << 31):
                    return None
                blob = f.read(plen)
                if len(blob) != plen or zlib.crc32(blob) != crc:
                    return None
                return (lsn, blob)
        except OSError:
            return None

    def checkpoint(self, state_blob: bytes, *, truncate: bool = True) -> int:
        """Write the snapshot at the current committed frontier, then
        truncate the log tail up to the smallest LSN every live fleet
        replica has applied (solo: the checkpoint lsn itself).  Returns
        the checkpoint lsn."""
        with self._lock, self._flocked():
            lsn = self._repair_tail_locked()
            tmp = self.ckpt_path + f".{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(_FHDR.pack(WAL_MAGIC, lsn))
                f.write(_RHDR.pack(len(state_blob), zlib.crc32(state_blob)))
                f.write(state_blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.ckpt_path)
            _bump("wal_checkpoints")
            if truncate:
                floor = lsn
                if self._coord is not None:
                    with contextlib.suppress(Exception):
                        applied = self._coord.min_wal_applied()
                        if applied is not None:
                            floor = min(floor, applied)
                self._truncate_upto_locked(floor)
            return lsn

    def _truncate_upto_locked(self, lsn: int):
        """Drop log records below ``lsn``: rewrite the file as
        header(base_lsn=lsn) + tail, atomic rename.  The held flock
        keeps appenders out; tailers re-resolve offsets from base_lsn."""
        if lsn <= self.base_lsn:
            return
        self._f.seek(0, os.SEEK_END)
        file_end = self.base_lsn + self._f.tell() - _FHDR.size
        lsn = min(lsn, file_end)
        self._f.seek(lsn - self.base_lsn + _FHDR.size)
        tail = self._f.read()
        tmp = self.path + f".{os.getpid()}.trunc"
        with open(tmp, "wb") as f:
            f.write(_FHDR.pack(WAL_MAGIC, lsn))
            f.write(tail)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._f.close()
        self._f = open(self.path, "r+b")  # noqa: SIM115 (held open)
        self._f.seek(0, os.SEEK_END)
        self.base_lsn = lsn

    def reopen_if_truncated(self):
        """Tailer hook: a peer's checkpoint may have rewritten the file
        (new base_lsn).  Cheap stat check; reopen when the inode moved."""
        try:
            if os.stat(self.path).st_ino == os.fstat(
                    self._f.fileno()).st_ino:
                return
        except OSError:
            return
        with self._lock:
            with contextlib.suppress(OSError):
                self._f.close()
            self._f = open(self.path, "r+b")  # noqa: SIM115 (held open)
            hdr = self._f.read(_FHDR.size)
            _magic, self.base_lsn = _FHDR.unpack(hdr)
            self._f.seek(0, os.SEEK_END)

    def close(self):
        self._interval_stop.set()
        with self._lock:
            self._closed = True
            with contextlib.suppress(OSError):
                self._f.flush()
            with contextlib.suppress(OSError):
                self._f.close()
            with contextlib.suppress(OSError):
                self._lockf.close()
