"""CBO statistics (reference: statistics/ — Histogram, CMSketch, TopN,
FMSketch + handle). Round-1: row counts, per-column NDV/min/max/null counts
persisted to meta; equal-depth histograms land with the cost model."""

from .analyze import analyze_table

__all__ = ["analyze_table"]
