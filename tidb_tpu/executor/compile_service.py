"""Compile service: async background compilation, persistent executable
index, prewarmed bucket ladders, resilient remote compile.

Why this exists (ROADMAP open item 5, BENCH_TPU_LIVE.json): the live-TPU
run proved the production enemy is COMPILATION, not execution — Q1 ran
22.7x faster than host but paid 147–379s of XLA compile per query shape,
and one remote-compile "Connection refused" at Q5 zeroed the rest of the
run.  PRs 1–7 made retries, hangs, HBM and admission owned resources;
this module does the same for the compile pipeline, applying the
co-processing principle ("Revisiting Co-Processing for Hash Joins on the
Coupled CPU-GPU Architecture", PAPERS.md) to compilation itself: while
the device's program compiles in the background, the HOST serves the
query — host and device do different useful work concurrently instead of
the query blocking on XLA.

The five layers a device fragment now passes (run_device order):

    1. ADMISSION            may this fragment occupy the device now?
    2. COMPILE SERVICE      is its executable ready?  (this module)
    3. SUPERVISOR deadline  is the backend still responsive?
    4. CIRCUIT BREAKER      is this fragment shape healthy?
    5. RESIDENCY            do its uploads fit the HBM budget?

Model — every compiled-pipeline build routes through :func:`obtain`
(device_exec.acquire_pipeline is the sole caller; the AST lint in
tests/test_compile_service.py confines direct ``jax.jit`` of query
pipelines to this module + ops/device.py):

* **Async compile, host-first serving** (``tidb_compile_async``): a cold
  ``_PIPE_CACHE`` miss SUBMITS the (plan sig, pack sig, bucket shape)
  signature to a bounded compile worker pool and immediately raises
  ``DeviceUnsupported`` — the fragment runs on the host engine (counted
  ``compile_pending_fragments``, NO breaker charge: a pending compile is
  not ill-health).  The worker builds the pipeline and warms it against
  zero-filled arrays of the recorded shapes, so the trace + XLA compile
  happen off the query path; when the executable lands in the shared
  ``_PIPE_CACHE`` the next same-shaped query flips to the device with
  ZERO new traces.  First-query latency is bounded by host speed, never
  by XLA.

* **Persistent executable index**: jax's AOT compilation cache (enabled
  process-wide in tidb_tpu/__init__.py, host-fingerprint-scoped — PR 7)
  persists the serialized executables themselves, for the CPU AND PJRT
  backends; this module adds a SIGNATURE INDEX next to it
  (``<jax-cache-dir>/pipe-index/``, override
  ``TIDB_TPU_COMPILE_INDEX``).  A cold obtain whose signature is in the
  index compiles INLINE even under async — the XLA artifact comes off
  disk, so the "compile" is a deserialize — and counts
  ``compile_persist_hits``: a process restart or a second serving
  process starts warm.

* **Prewarm ladder** (``tidb_compile_prewarm`` at Domain start, the
  ``ADMIN COMPILE`` statement, or :func:`prewarm`): every build registers
  a RECIPE (builder + arg shapes); prewarm background-compiles each hot
  recipe's geometric bucket ladder (the next ``ladder_up`` row buckets
  above the seen shape), so the shapes growing traffic will hit are
  traced before traffic arrives — a delta that crosses a bucket boundary
  re-dispatches a prewarmed program instead of paying a sync compile.
  Fragment signatures with learned capacities (device_join._CAP_STORE)
  are prewarm-priority: they are the shapes real traffic converged on.

* **Resilient remote compile**: a compile worker runs under the PR 3
  supervisor deadline (``tidb_compile_timeout`` — a hung remote compile
  is abandoned and fenced like any other device hang), classified
  compile/transport failures retry on the shared Backoffer's
  ``compileRetry`` curve, and terminal failures charge a COMPILE-SCOPED
  circuit breaker (shape="compile"): a flaky compile service degrades
  fragments to host and recovers via half-open probe instead of killing
  the run (the Q5 failure mode).  Chaos hook: failpoint
  ``device-compile`` with ``compile-fail`` / ``[N*]compile-slow(s)``
  actions, asserted drained by ``verify_drained`` in both chaos modes.

Gauges — ``compile_queue_depth``, ``compile_pending_fragments``,
``compile_bg_seconds``, ``compile_persist_hits`` — surface in EXPLAIN
ANALYZE annotations (plus a per-fragment ``compile_mode``: ``cached`` /
``prewarmed`` / ``async_pending`` / ``sync``), observe gauges,
``/status`` (``device_compiler``), ``/metrics`` and the bench JSON lines
(``sync_compile_s`` vs ``bg_compile_s``).
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import json
import logging
import os
import queue
import threading
import time
import weakref

log = logging.getLogger("tidb_tpu.compile_service")

_LOCK = threading.Lock()

#: in-flight background jobs keyed by job key (pipeline cache key, or
#: (pipeline key, ("ladder", bucket)) for prewarm shape warms)
_JOBS: dict = {}
#: async backlog bound (the bg pool's admission, mirroring the
#: scheduler's bounded queue): every queued join/MPP job pins its builder
#: closure — the host table chunk and its device columns — until the
#: build runs, so an unbounded burst of distinct signatures would bypass
#: the residency ledger and grow host RAM without limit
_BACKLOG_MAX = 32
_JOB_Q: "queue.SimpleQueue" = queue.SimpleQueue()
_WORKERS: list = []
_WORKER_SEQ = itertools.count()

#: how a cached pipeline entry came to exist: "bg" (async background
#: compile) or "prewarm" (ladder warm) — anything absent was built sync.
#: Drives the per-fragment compile_mode annotation on later cache hits.
_ORIGIN: dict = {}
_ORIGIN_MAX = 512

#: prewarm recipes: every first build records its builder + arg shapes so
#: the ladder can re-trace the signature at neighboring bucket shapes
#: (and rebuild it after an off-CPU fence dropped the pipe cache)
_RECIPES: "collections.OrderedDict" = collections.OrderedDict()
_RECIPES_MAX = 128

STATS = {
    "bg_submitted": 0,        # background jobs enqueued
    "bg_completed": 0,        # jobs whose executable landed in the cache
    "bg_failed": 0,           # jobs that failed classified (breaker fed)
    "bg_discarded": 0,        # jobs dropped (stale after an off-CPU fence)
    "sync_compiles": 0,       # builds done inline on the query path
    "compile_pending_fragments": 0,  # dispatches degraded to host because
    #                                  their compile was pending/in flight
    "compile_prewarmed": 0,   # ladder shape warms completed
    "compile_persist_hits": 0,  # cold obtains served warm by the index
    "compile_bg_seconds": 0.0,  # wall seconds spent in background builds
    "breaker_degrades": 0,    # obtains refused by an OPEN compile breaker
    "bg_backlog_rejects": 0,  # submits refused by the _BACKLOG_MAX bound
}
_LAST_ERROR = [""]

#: resolved config (GLOBAL-vars discipline, same as scheduler._refresh_cfg:
#: the worker pool is process-wide, so a session SET must not resize it)
_CFG = {"workers": 2, "timeout_s": 0.0}

#: observe sinks mirroring the gauges (pattern of scheduler/residency)
_SINKS: "weakref.WeakSet" = weakref.WeakSet()


class _Recipe:
    __slots__ = ("key", "build", "spec", "dict_refs", "shape", "sig",
                 "uses", "bucket", "pd")

    def __init__(self, key, build, spec, dict_refs, shape, sig,
                 ladder=True, per_double=2):
        self.key = key
        self.build = build
        self.spec = spec
        self.dict_refs = dict_refs
        self.shape = shape
        self.sig = sig
        self.uses = 1
        # bucket None = no ladder: streamed fragments always dispatch at
        # the FIXED tidb_device_stream_rows block shape, so a
        # bigger-bucket warm could never serve traffic (only the
        # post-eviction rebuild applies to them)
        self.bucket = _base_bucket(spec) if ladder else None
        # the registering session's bucket granularity
        # (tidb_device_shape_buckets): the ladder must climb the SAME
        # curve the dispatch sites bucket on, or every warm is a shape
        # traffic never hits
        self.pd = per_double


class _Job:
    __slots__ = ("jkey", "cache_key", "build", "spec", "dict_refs",
                 "shape", "sig", "br", "sid", "origin", "done", "error",
                 "fence_gen", "tchild")

    def __init__(self, jkey, cache_key, build, spec, dict_refs, shape,
                 sig, br, sid, origin):
        self.jkey = jkey
        self.cache_key = cache_key
        self.build = build          # None: warm an already-cached fn
        self.spec = spec
        self.dict_refs = dict_refs
        self.shape = shape
        self.sig = sig
        self.br = br                # compile-scoped breaker (may be None)
        self.sid = sid              # probe-owner token for the breaker
        self.origin = origin        # "bg" | "prewarm"
        self.done = threading.Event()
        self.error = None
        self.fence_gen = _fence_gen()
        # linked child trace (session/tracing.py link_child): a bg job
        # submitted by a TRACED statement runs under its own trace whose
        # parent_id is the statement's — the async compile's lifetime
        # stays attributable to the query that triggered it
        self.tchild = None


# -- config / small helpers --------------------------------------------------

def _refresh_cfg(ctx):
    src = None
    dom = getattr(ctx, "domain", None)
    if dom is not None:
        gv = dom.global_vars
        src = lambda name, d: gv.get(name, d)  # noqa: E731
    elif ctx is not None:
        src = lambda name, d: ctx.get_sysvar(name)  # noqa: E731
    if src is None:
        return
    # resolve outside _LOCK (sysvar reads do arbitrary session work),
    # publish under it (the pool size and deadline are read under _LOCK
    # by _ensure_workers and the worker loop)
    vals = {}
    try:
        vals["workers"] = max(int(src("tidb_compile_workers", 2)), 1)
    except Exception:
        pass
    try:
        vals["timeout_s"] = max(float(src("tidb_compile_timeout", 0.0)),
                                0.0)
    except Exception:
        pass
    with _LOCK:
        _CFG.update(vals)


def _async_on(ctx) -> bool:
    if ctx is None:
        return False
    try:
        return str(ctx.get_sysvar("tidb_compile_async")).upper() in (
            "ON", "1", "TRUE")
    except Exception:
        return False


def _fence_gen() -> int:
    try:
        from . import supervisor
        return supervisor.fence_generation()
    except Exception:
        return 0


def _remote_serve(key, build, spec, shape, sig, _tsp=None) -> tuple:
    """Resolve a cold pipeline through the separated compile server
    (tidb_tpu/fabric/compile_client.py) when one is configured:
    ``(fn, None)`` on success (artifact deserialize or remote compile),
    ``(None, classified_error)`` when the remote path failed — the
    caller builds inline and charges the compile breaker — and
    ``(None, None)`` when there is no server / nothing exportable."""
    try:
        from ..fabric.compile_client import get_client
        cli = get_client()
    except Exception as e:  # noqa: BLE001 — remote is an optimization
        log.warning("fabric compile client unavailable (building "
                    "locally): %s", e)
        return None, None
    if cli is None:
        return None, None
    fn, err = cli.serve(key, build, spec, shape, sig)
    if _tsp is not None and fn is not None:
        _tsp.tags["remote"] = True
    return fn, err


def _spec_of(args):
    """args pytree (concrete arrays / scalars) → ShapeDtypeStruct pytree.
    Derived at submit time so the job never pins the query's real data.
    Python scalars stay literal zeros of their type: jit traces them
    WEAK-typed, and a strong-typed stand-in would give the warm call a
    different aval than the real dispatch (forcing the very retrace the
    warm exists to avoid)."""
    import jax
    import numpy as np

    def leaf(a):
        if isinstance(a, bool):
            return False
        if isinstance(a, int):
            return 0
        if isinstance(a, float):
            return 0.0
        a = np.asarray(a) if not hasattr(a, "dtype") else a
        return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
    return jax.tree_util.tree_map(leaf, args)


def _zeros_of(spec):
    """Zero-filled concrete arrays matching a spec — the warm call's
    arguments.  Zeros are safe: the pipelines are pure static-shape
    numeric programs (division is where-guarded, padding is masked), and
    the warm result is discarded."""
    import jax
    import numpy as np

    def leaf(s):
        if isinstance(s, jax.ShapeDtypeStruct):
            return np.zeros(s.shape, s.dtype)
        return s  # literal python scalar placeholder (weak-typed arg)
    return jax.tree_util.tree_map(leaf, spec)


def _base_bucket(spec):
    """The single leading dimension shared by every array leaf of the
    spec (the fragment's row bucket), or None when leaves disagree —
    only single-bucket pipelines get a prewarm ladder."""
    import jax
    dims = {s.shape[0] for s in jax.tree_util.tree_leaves(spec)
            if getattr(s, "shape", ()) and len(s.shape) >= 1}
    if len(dims) == 1:
        return next(iter(dims))
    return None


def next_buckets(base: int, count: int, per_double: int = 2) -> list:
    """The `count` geometric row buckets strictly above `base` (the
    prewarm ladder: shapes growing traffic will hit next)."""
    from ..ops.device import bucket_rows
    if per_double <= 0:
        return []  # exact shapes: there is no bucket curve to climb
    out = []
    b = int(base)
    for _ in range(count):
        nb = bucket_rows(b + 1, per_double)
        if nb <= b:
            break
        out.append(nb)
        b = nb
    return out


def _scale_spec(spec, base: int, bucket: int):
    """The recipe's spec with every `base`-length leading dim scaled to
    `bucket` — the ladder shape one step up."""
    import jax

    def leaf(s):
        if getattr(s, "shape", ()) and len(s.shape) >= 1 \
                and s.shape[0] == base:
            return jax.ShapeDtypeStruct((bucket,) + tuple(s.shape[1:]),
                                        s.dtype)
        return s
    return jax.tree_util.tree_map(leaf, spec)


# -- persistent signature index ----------------------------------------------

def _persist_dir():
    """The signature-index directory, or None when persistence is off.
    Lives INSIDE the host-fingerprint-scoped jax compilation cache dir
    (tidb_tpu/__init__.py), so a foreign machine's index — like its
    executables — is unreachable by construction."""
    d = os.environ.get("TIDB_TPU_COMPILE_INDEX", "")
    if d == "off":
        return None
    if d:
        return d
    import jax
    base = getattr(jax.config, "jax_compilation_cache_dir", None)
    if not base:
        return None
    return os.path.join(base, "pipe-index")


def _persist_hash(key) -> str:
    """Stable hash of a pipeline cache key (sig strings / ints / tuples —
    repr-stable by construction) + backend identity: the same signature
    on a different backend or mesh width is a different executable."""
    import jax
    ident = repr((key, jax.default_backend(), jax.device_count()))
    return hashlib.sha1(ident.encode()).hexdigest()


def _persist_lookup(key) -> bool:
    d = _persist_dir()
    if d is None:
        return False
    try:
        return os.path.exists(os.path.join(d, _persist_hash(key) + ".json"))
    except Exception:
        return False


def _persist_record(key, shape: str, sig: str, origin: str):
    """Record that this signature has compiled on this host: the jax AOT
    cache underneath holds the executable bytes, so a later process's
    obtain of the same key is served warm (compile_persist_hits)."""
    d = _persist_dir()
    if d is None:
        return
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, _persist_hash(key) + ".json")
        if os.path.exists(path):
            return
        tmp = path + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            # sig may be a structured tuple (join fragment_sig, window
            # sig) — repr it: the index entry is diagnostic, the HASH in
            # the filename is the lookup key
            json.dump({"shape": shape, "sig": repr(sig)[:512],
                       "origin": origin, "ts": time.time()}, f)
        os.replace(tmp, path)
    except Exception:
        pass  # the index is an optimization; never fail a compile on it


# -- stats plumbing -----------------------------------------------------------

def _mode(mode: str):
    """Record the per-fragment compile mode into the pipe-cache stats
    (process totals + thread-local), riding the supervisor's existing
    TLS bridging so EXPLAIN ANALYZE sees it through worker threads."""
    from .device_exec import _bump
    _bump("mode_" + mode)


def note_hit(key):
    """acquire_pipeline reports a pipe-cache HIT: compile_mode is
    `prewarmed` when the prewarm ladder produced/touched this entry,
    plain `cached` otherwise.  Deliberately LOCK-FREE: this runs on
    every warm fragment dispatch, and serializing all sessions on the
    compile-service lock would contend the steady-state path that does
    zero compile work — plain dict gets are GIL-atomic, and the uses
    bump is a prewarm-ranking heuristic where a lost increment under a
    race only nudges the ordering."""
    origin = _ORIGIN.get(key)
    rec = _RECIPES.get(key)
    if rec is not None:
        rec.uses += 1
    _mode("prewarmed" if origin == "prewarm" else "cached")


def _set_origin(key, origin: str):
    with _LOCK:
        _ORIGIN[key] = origin
        while len(_ORIGIN) > _ORIGIN_MAX:
            _ORIGIN.pop(next(iter(_ORIGIN)))


def _register_recipe(key, build, spec, dict_refs, shape, sig, ladder=True,
                     per_double=2):
    with _LOCK:
        rec = _RECIPES.get(key)
        if rec is not None:
            rec.uses += 1
            _RECIPES.move_to_end(key)
            return
        _RECIPES[key] = _Recipe(key, build, spec, dict_refs, shape, sig,
                                ladder, per_double)
        while len(_RECIPES) > _RECIPES_MAX:
            _RECIPES.popitem(last=False)


# -- the obtain chokepoint ----------------------------------------------------

def obtain(key, build, dict_refs, *, ctx=None, args=None, spec=None,
           shape="agg", sig="", ladder=True):
    """Resolve a compiled pipeline for a ``_PIPE_CACHE`` MISS (the sole
    caller is device_exec.acquire_pipeline, which already tried the
    cache).  Returns the built fn (sync path), or raises
    ``DeviceUnsupported`` when the fragment should run on the host
    engine instead: compile pending in the background, compile breaker
    open, or the build itself failed classified."""
    from ..session import tracing
    # the statement's span tracer: the compile span carries the MODE the
    # service resolved this fragment with (sync / async_pending /
    # persist_hit / breaker_open) — one branch when sampling is off
    with tracing.span("compile.obtain", shape=shape) as _tsp:
        return _obtain_impl(key, build, dict_refs, ctx, args, spec, shape,
                            sig, ladder, _tsp)


def _obtain_impl(key, build, dict_refs, ctx, args, spec, shape, sig,
                 ladder, _tsp):
    from ..ops.device import DeviceUnsupported
    from ..session import tracing
    from ..utils import failpoint
    from ..utils.backoff import classify, CLASS_COMPILE, CLASS_TRANSPORT
    from .circuit import get_breaker
    attach(ctx)
    _refresh_cfg(ctx)
    # a concurrent resolver may have LANDED this key between the caller's
    # cache miss and here (its bg job completed, or another session built
    # it sync): serve the fresh entry instead of rebuilding — on a real
    # TPU a redundant rebuild is minutes of XLA
    fn = _cached_fn(key)
    if fn is not None:
        note_hit(key)
        if _tsp is not None:
            _tsp.tags["mode"] = "cached"
        return fn
    if spec is None and args is not None:
        spec = _spec_of(args)
    if spec is not None:
        # join/MPP builders close over the execution's LEAVES — the full
        # host table chunk and its device-resident columns.  A recipe
        # lives for the process, so retaining such a builder would pin
        # whole tables in RAM and make residency eviction a lie (the
        # ledger drops the entry, the closure keeps the buffer).  Those
        # shapes register builder-less: they still dedup in-flight jobs
        # and count uses; only the post-eviction REBUILD (which needs a
        # builder) is skipped for them.  Agg/window builders close over
        # compiled expression fns only — safe to retain.
        keep = build if shape not in ("join", "mpp") else None
        from ..ops.device import shape_buckets
        _register_recipe(key, keep, spec,
                         dict_refs if keep is not None else (), shape, sig,
                         ladder, shape_buckets(ctx))

    br = get_breaker(ctx, shape="compile")
    sid = getattr(ctx, "conn_id", None)
    group = None
    try:
        from .scheduler import resource_group
        group = resource_group(ctx)
    except Exception:
        pass

    with _LOCK:
        in_flight = key in _JOBS
    if in_flight:
        # the executable is being built right now: this execution (and
        # any concurrent ones) serve host-side until it lands
        with _LOCK:
            STATS["compile_pending_fragments"] += 1
        _mode("async_pending")
        _publish_gauges()
        if _tsp is not None:
            _tsp.tags["mode"] = "async_pending"
        tracing.event("host_degraded", reason="compile_pending",
                      shape=shape)
        raise DeviceUnsupported(
            f"device executable for this {shape} fragment is compiling "
            "in the background (fragment served by the host engine)")

    if not br.allow(session=sid, group=group):
        # compile path unhealthy (the Q5 dead-tunnel mode): don't even
        # queue — degrade instantly, recover via the half-open probe
        with _LOCK:
            STATS["breaker_degrades"] += 1
        if _tsp is not None:
            _tsp.tags["mode"] = "breaker_open"
        tracing.event("host_degraded", reason="compile_breaker_open",
                      shape=shape)
        raise DeviceUnsupported(
            f"compile circuit open for device executables (cooling "
            f"down; {shape} fragment degraded to host engine)")

    persist_warm = _persist_lookup(key)
    if persist_warm:
        with _LOCK:
            STATS["compile_persist_hits"] += 1
        if _tsp is not None:
            _tsp.tags["persist_hit"] = True

    if _async_on(ctx) and spec is not None and not persist_warm:
        # async path: submit and serve this execution host-side.  The
        # probe slot (if this allow() won one) transfers to the job —
        # its verdict is the background compile's outcome.
        job = _Job(key, key, build, spec, dict_refs, shape, sig, br, sid,
                   "bg")
        with _LOCK:
            # re-check ATOMICALLY with the insert: a concurrent miss on
            # the same key between the fast-path check above and here
            # must not double-submit (the overwrite would let the first
            # job's finish pop the second's live entry — leaked-job
            # false positives in verify_drained, and a duplicate
            # minutes-long compile on a real TPU)
            if key in _JOBS:
                job = None
                STATS["compile_pending_fragments"] += 1
            elif len(_JOBS) >= _BACKLOG_MAX:
                # backlog full: degrade to host WITHOUT submitting — the
                # signature re-submits on a later miss once the queue
                # drains (see the _BACKLOG_MAX comment for why the bound
                # exists at all)
                job = None
                STATS["bg_backlog_rejects"] += 1
                STATS["compile_pending_fragments"] += 1
            else:
                _JOBS[key] = job
                STATS["bg_submitted"] += 1
                STATS["compile_pending_fragments"] += 1
        if job is None:
            br.release_probe(session=sid)
            _mode("async_pending")
            _publish_gauges()
            if _tsp is not None:
                _tsp.tags["mode"] = "async_pending"
            tracing.event("host_degraded", reason="compile_pending",
                          shape=shape)
            raise DeviceUnsupported(
                f"device executable for this {shape} fragment is "
                "compiling in the background (fragment served by the "
                "host engine)")
        # linked child trace: the background build's own timeline, tied
        # back to this statement's trace by parent_id (the async
        # compile's lifetime is attributable to the query it serves)
        job.tchild = tracing.link_child("compile.bg", shape=shape)
        _ensure_workers()
        _JOB_Q.put(job)
        _mode("async_pending")
        _publish_gauges()
        if _tsp is not None:
            _tsp.tags["mode"] = "async_submitted"
            if job.tchild is not None:
                _tsp.tags["bg_trace_id"] = job.tchild.trace_id
        tracing.event("host_degraded", reason="compile_submitted",
                      shape=shape)
        raise DeviceUnsupported(
            f"device executable for this {shape} fragment submitted for "
            "background compilation (fragment served by the host engine)")

    # sync path (async off, no shape spec, or a persistent-index hit —
    # the XLA artifact comes off disk, so inline is a deserialize)
    remote_err = None
    try:
        # chaos hook: a compile-fail here models the remote-compile
        # service refusing/failing the build on the query path
        failpoint.inject("device-compile")
        fn, remote_err = _remote_serve(key, build, spec, shape, sig, _tsp)
        if fn is None:
            # no compile server, its shape can't export, or the remote
            # path just failed (remote_err set): build INLINE — the
            # separated compile server degrades to local compilation,
            # never to a failed query
            fn = build()
    except DeviceUnsupported:
        br.release_probe(session=sid)
        raise
    except Exception as e:
        cls = classify(e)
        if cls not in (CLASS_COMPILE, CLASS_TRANSPORT):
            br.release_probe(session=sid)
            raise
        # wrap in the taxonomy's own error (errno 9010): the breaker
        # record, the log chain and any re-classification all see a
        # COMPILE-path failure — a raw transport error from a future
        # remote compiler must not masquerade as an execution fault
        from ..errors import DeviceCompileError
        err = DeviceCompileError(
            f"device compile failed ({cls}): {e}")
        err.__cause__ = e
        br.record_failure(err, session=sid, group=group)
        with _LOCK:
            _LAST_ERROR[0] = f"{cls}: {e}"
        if _tsp is not None:
            _tsp.tags["mode"] = "sync_failed"
        tracing.event("host_degraded", reason="compile_" + cls,
                      shape=shape)
        raise DeviceUnsupported(
            f"device compile failed ({cls}): {e} "
            f"({shape} fragment degraded to host engine)") from err
    if remote_err is not None:
        # the inline build saved the query, but the 9010 breaker must
        # still see the REMOTE failure: enough of these open the compile
        # circuit and obtains degrade up front until the half-open probe
        # finds the server again — a dead compile server degrades
        # workers to inline/host compile, never to failed queries
        br.record_failure(remote_err, session=sid, group=group)
        with _LOCK:
            _LAST_ERROR[0] = f"remote: {remote_err}"
    else:
        br.record_success(session=sid)
    from .device_exec import _pipe_cache_put
    _pipe_cache_put(key, fn, dict_refs)
    with _LOCK:
        STATS["sync_compiles"] += 1
    _mode("sync")
    if _tsp is not None:
        _tsp.tags["mode"] = "sync"
    _persist_record(key, shape, sig, "sync")
    return fn


# -- the worker pool ----------------------------------------------------------

def _ensure_workers():
    with _LOCK:
        want = _CFG["workers"]
        live = [t for t in _WORKERS if t.is_alive()]
        _WORKERS[:] = live
        need = want - len(live)
        for _ in range(max(need, 0)):
            t = threading.Thread(
                target=_worker_loop, daemon=True,
                name=f"compile-worker-{next(_WORKER_SEQ)}")
            _WORKERS.append(t)
            t.start()


def _worker_loop():
    from .device_exec import mark_bg_thread
    mark_bg_thread()  # route this thread's compile stats to the bg_* keys
    while True:
        job = _JOB_Q.get()
        try:
            _run_job(job)
        except BaseException:  # noqa: BLE001 — a worker must never die
            log.exception("compile worker: unexpected job failure")
            _finish_job(job, failed=True)


def _do_compile(job: "_Job"):
    """One build+warm attempt (runs under the supervisor deadline).  The
    warm call triggers the trace and the XLA compile against zero-filled
    arrays of the recorded shapes; the jit cache inside the fn then
    serves the real dispatch with zero new traces."""
    from ..utils import failpoint
    from .device_exec import mark_bg_thread
    # SCOPED bg mark: under tidb_compile_timeout this runs on a REUSED
    # supervisor worker thread, not the compile worker — the charges
    # must still route to the bg_* mirror, and the mark must not outlive
    # the job (that worker serves query fragments next)
    prev = mark_bg_thread()
    try:
        failpoint.inject("device-compile")
        if job.build is not None and job.spec is not None:
            # separated compile server first (when configured): the
            # worker traces, the server pays the XLA compile.  A remote
            # failure logs + counts and falls through to the local
            # build — background jobs already serve host-side, so the
            # right degradation is inline compile, not a failed job.
            fn, rerr = _remote_serve(job.cache_key, job.build, job.spec,
                                     job.shape, job.sig)
            if fn is not None:
                fn(*_zeros_of(job.spec))
                return fn
            if rerr is not None:
                log.warning("bg compile: remote path failed, building "
                            "inline: %s", rerr)
                with _LOCK:
                    _LAST_ERROR[0] = f"remote: {rerr}"
        fn = (job.build() if job.build is not None
              else _cached_fn(job.cache_key))
        if fn is None:
            return None
        zeros = _zeros_of(job.spec)
        fn(*zeros)
        return fn
    finally:
        mark_bg_thread(prev)


def _cached_fn(key):
    from . import device_exec
    with device_exec._PIPE_LOCK:
        hit = device_exec._PIPE_CACHE.get(key)
    return hit[0] if hit is not None else None


def _run_job(job: "_Job"):
    """Build + warm one executable with the full resilience ladder:
    supervisor deadline (a hung remote compile is abandoned + fenced like
    any device hang), compileRetry backoff on classified failures, then
    a terminal verdict into the compile-scoped breaker.  A job carrying a
    linked child trace runs UNDER it, so its supervisor/backoff spans and
    events land on the timeline attributed to the submitting query."""
    if job.tchild is not None:
        from ..session import tracing
        with tracing.adopt(job.tchild):
            return _run_job_traced(job)
    return _run_job_traced(job)


def _run_job_traced(job: "_Job"):
    from ..utils.backoff import (Backoffer, classify, CLASS_COMPILE,
                                 CLASS_DEVICE, CLASS_EXCHANGE, CLASS_HANG,
                                 CLASS_TRANSPORT)
    from . import supervisor
    from ..ops.device import DeviceUnsupported
    t0 = time.perf_counter()
    bo = Backoffer(budget_ms=2000.0)
    fn = None
    while True:
        try:
            with _LOCK:
                deadline = _CFG["timeout_s"]
            fn = supervisor.call_supervised(
                _do_compile, (job,), deadline_s=deadline, ctx=None,
                shape="compile", label=f"bg compile ({job.shape})")
            break
        except DeviceUnsupported:
            # the builder says this fragment can't run on device at all:
            # not a health verdict — drop the job quietly
            if job.br is not None:
                job.br.release_probe(session=job.sid)
            _finish_job(job, failed=True, charge=False)
            return
        except Exception as e:  # noqa: BLE001 — classified below
            cls = classify(e)
            with _LOCK:
                _LAST_ERROR[0] = f"{cls}: {e}"
            if cls not in (CLASS_COMPILE, CLASS_TRANSPORT, CLASS_DEVICE,
                           CLASS_EXCHANGE, CLASS_HANG):
                log.warning("background compile failed unclassified: %s",
                            e, exc_info=True)
                if job.br is not None:
                    job.br.release_probe(session=job.sid)
                _finish_job(job, failed=True, charge=False)
                return
            try:
                bo.backoff("compileRetry", e)
            except Exception:
                # retry budget exhausted: terminal classified failure —
                # the compile breaker opens after enough of these and
                # obtain() degrades fragments without queueing.  Wrapped
                # as DeviceCompileError (9010) so the breaker record and
                # the job's error carry the compile taxonomy class.
                from ..errors import DeviceCompileError
                term = DeviceCompileError(
                    f"background compile failed terminally ({cls}): {e}")
                term.__cause__ = e
                job.error = term
                if job.br is not None:
                    job.br.record_failure(term, session=job.sid)
                _finish_job(job, failed=True)
                return
    elapsed = time.perf_counter() - t0
    with _LOCK:
        STATS["compile_bg_seconds"] += elapsed
    if fn is None:
        # prewarm warm whose cached fn vanished (LRU/fence) and carried
        # no builder: nothing to install
        _finish_job(job, failed=True, charge=False)
        return
    import jax
    from . import device_exec
    stale = False
    with device_exec._PIPE_LOCK:
        # fence-generation read under the SAME lock the fence's cache
        # clear takes (_reinit_backend): either the clear ran first —
        # the generation this read returns is already bumped, so the
        # stale executable is discarded — or our put lands first and
        # the clear removes it.  Without the shared lock a clear could
        # slip between an unlocked gen check and the put, installing an
        # executable that pins the DEAD PJRT client.  (Lock order
        # _PIPE_LOCK → supervisor._LOCK; the supervisor never takes the
        # pipe lock while holding its own.)  The CPU client survives
        # fences, so its warms stay valid.
        if (jax.default_backend() != "cpu"
                and job.fence_gen != _fence_gen()):
            stale = True
        elif job.build is not None:
            device_exec._PIPE_CACHE[job.cache_key] = (fn, job.dict_refs)
            while len(device_exec._PIPE_CACHE) > \
                    device_exec._PIPE_CACHE_MAX:
                device_exec._PIPE_CACHE.popitem(last=False)
    if stale:
        _finish_job(job, discarded=True)
        return
    _set_origin(job.cache_key, job.origin)
    if job.br is not None:
        job.br.record_success(session=job.sid)
    _persist_record(job.cache_key, job.shape, job.sig, job.origin)
    _finish_job(job)
    log.info("background compile landed (%s, %.2fs): next same-shape "
             "query flips to device", job.shape, elapsed)


def _finish_job(job: "_Job", failed: bool = False, discarded: bool = False,
                charge: bool = True):
    with _LOCK:
        _JOBS.pop(job.jkey, None)
        if discarded:
            STATS["bg_discarded"] += 1
        elif failed:
            if charge:
                STATS["bg_failed"] += 1
            else:
                STATS["bg_discarded"] += 1
        else:
            STATS["bg_completed"] += 1
            if job.origin == "prewarm":
                STATS["compile_prewarmed"] += 1
    if job.br is not None:
        # paths that end a job WITHOUT a breaker verdict (fence discard,
        # the worker-loop catch-all) must still free a HALF_OPEN probe
        # slot the job inherited from obtain()'s allow(), or the breaker
        # wedges host-side until the grace reclaim; ownership-checked
        # and a no-op when record_success/failure already resolved it
        job.br.release_probe(session=job.sid)
    if job.tchild is not None:
        # retire the linked child trace on EVERY job outcome (finish is
        # idempotent — the worker-loop catch-all may land here twice)
        from ..session import tracing
        tracing.finish(job.tchild, succ=not failed and not discarded)
    job.done.set()
    _publish_gauges()


# -- prewarm ------------------------------------------------------------------

def _prewarm_claim_fleet(jkey) -> bool:
    """Fleet-wide prewarm dedup (ISSUE 14): N workers prewarming the
    same recipe ladder should trace each rung ONCE across the fleet —
    the persistent pipe-index already dedupes the XLA work, but the
    trace + warm dispatch are per-process; the coordination segment's
    claim makes the submission itself at-most-once.  Always True outside
    a fleet."""
    try:
        from ..fabric import state as fabric_state
        coord = fabric_state.coordinator()
        if coord is None:
            return True
        ident = hashlib.blake2b(repr(jkey).encode(),
                                digest_size=16).digest()
        return coord.prewarm_claim(ident)
    except Exception as e:  # noqa: BLE001 — dedup is best-effort
        log.warning("fleet prewarm claim failed (warming locally): %s", e)
        return True


def prewarm(ctx=None, ladder_up: int = 2, max_recipes: int = 32,
            wait: bool = False, timeout_s: float = 120.0) -> dict:
    """Background-compile the bucket ladder for the hot recipes: for each
    registered fragment signature (most-used first; signatures with
    learned capacities in device_join._CAP_STORE rank hottest — they are
    the shapes traffic converged on), warm the next `ladder_up` row
    buckets above the seen shape, plus rebuild any signature an off-CPU
    fence evicted.  `wait` blocks until the submitted warms finish
    (ADMIN COMPILE uses this so the statement returns a final count)."""
    _refresh_cfg(ctx)
    from .device_join import _CAP_STORE
    # snapshot: concurrent queries mutate the cap store un-locked, and a
    # mid-sort resize would raise out of the priority key function
    try:
        hot_sigs = {k[0] for k in list(_CAP_STORE)}
    except RuntimeError:  # resized mid-snapshot: lose the priority boost
        hot_sigs = set()
    with _LOCK:
        warm0 = STATS["compile_prewarmed"]
        fail0 = STATS["bg_failed"]
        recipes = sorted(
            _RECIPES.values(),
            key=lambda r: (r.sig in hot_sigs if r.sig else False, r.uses),
            reverse=True)[:max_recipes]
    jobs = []
    for rec in recipes:
        targets = []
        if _cached_fn(rec.key) is None and rec.build is not None:
            # evicted/fenced: rebuild at the seen shape first
            # (builder-less join/MPP recipes can't rebuild — skip)
            targets.append((rec.spec, rec.build))
        if rec.bucket is not None:
            for nb in next_buckets(rec.bucket, ladder_up, rec.pd):
                targets.append((_scale_spec(rec.spec, rec.bucket, nb),
                                None))
        for spec, build in targets:
            # a REBUILD installs under the plain cache key, so it takes
            # the plain key as its job key too: a concurrent async
            # obtain() of the same signature then finds it in _JOBS and
            # serves host-side instead of double-submitting the same
            # multi-minute compile.  Pure shape warms (build None, never
            # install a new fn) keep a ladder-scoped key per bucket.
            jkey = (rec.key if build is not None
                    else (rec.key, ("ladder", _base_bucket(spec))))
            if not _prewarm_claim_fleet(jkey):
                continue  # another worker is already warming this rung
            with _LOCK:
                if jkey in _JOBS or rec.key in _JOBS:
                    continue
                job = _Job(jkey, rec.key, build, spec, rec.dict_refs,
                           rec.shape, rec.sig, None, None, "prewarm")
                _JOBS[jkey] = job
                STATS["bg_submitted"] += 1
            jobs.append(job)
            _ensure_workers()
            _JOB_Q.put(job)
    if wait:
        # poll in ticks and consult check_killed so ADMIN COMPILE stays
        # KILL-responsive while compiles run (same convention as the
        # scheduler's queued admission waits: KILL answers in ~a tick,
        # not after timeout_s)
        deadline = time.monotonic() + timeout_s
        check = getattr(ctx, "check_killed", None)
        for job in jobs:
            while (not job.done.wait(0.05)
                   and time.monotonic() < deadline):
                if check is not None:
                    check()
    _publish_gauges()
    with _LOCK:
        # DELTAS since this invocation started: ADMIN COMPILE reports
        # what THIS prewarm did, not process-lifetime totals
        return {"submitted": len(jobs),
                "prewarmed": STATS["compile_prewarmed"] - warm0,
                "failed": STATS["bg_failed"] - fail0}


def maybe_prewarm_on_start(domain):
    """Prewarm kick: called at Domain start and from SET GLOBAL
    ``tidb_compile_prewarm``.  Globals are in-memory only, so at Domain
    START the sysvar is never yet ON — the boot-time opt-in is the
    ``TIDB_TPU_COMPILE_PREWARM=ON`` env var (a serving process restart
    then rebuilds its ladder from the persistent index without waiting
    for a session to SET anything); the sysvar path fires the moment the
    operator SETs it (session/session.py)."""
    try:
        on = str(domain.global_vars.get("tidb_compile_prewarm",
                                        "OFF")).upper() in ("ON", "1")
    except Exception:
        on = False
    if not on:
        on = os.environ.get("TIDB_TPU_COMPILE_PREWARM",
                            "").upper() in ("ON", "1")
    if not on:
        return
    threading.Thread(target=prewarm, kwargs={"wait": False}, daemon=True,
                     name="compile-prewarm").start()


# -- fencing ------------------------------------------------------------------

def on_backend_reinit():
    """The supervisor tore down the backend (off-CPU fence): the pipe
    cache was cleared, so the origin map is stale; recipes stay — they
    are how prewarm rebuilds the ladder against the fresh client."""
    with _LOCK:
        _ORIGIN.clear()


# -- gauges / introspection ---------------------------------------------------

def queue_depth() -> int:
    with _LOCK:
        return len(_JOBS)


def snapshot() -> dict:
    with _LOCK:
        return {"compile_queue_depth": len(_JOBS),
                "recipes": len(_RECIPES),
                "workers": len([t for t in _WORKERS if t.is_alive()]),
                "last_error": _LAST_ERROR[0],
                **{k: (round(v, 3) if isinstance(v, float) else v)
                   for k, v in STATS.items()}}


def report_gauges() -> dict:
    """Surfacing policy shared by EXPLAIN ANALYZE and bench lines (same
    rule as scheduler.report_gauges): queue depth always, the counters
    only once they have ever fired."""
    s = snapshot()
    out = {"compile_queue_depth": s["compile_queue_depth"]}
    for k in ("compile_pending_fragments", "compile_persist_hits",
              "compile_prewarmed", "bg_failed"):
        if s[k]:
            out[k] = s[k]
    if s["compile_bg_seconds"]:
        out["compile_bg_seconds"] = s["compile_bg_seconds"]
    return out


def attach(ctx):
    dom = getattr(ctx, "domain", None)
    obs = getattr(dom, "observe", None)
    if obs is not None and hasattr(obs, "set_gauge"):
        with _LOCK:
            _SINKS.add(obs)


def observe_hist(name, value):
    """Record one latency sample into every attached observe registry
    (device_exec._charge_compile_s feeds `sync_compile_seconds` through
    here — the compile-layer histogram in /metrics)."""
    with _LOCK:
        sinks = list(_SINKS)
    for obs in sinks:
        f = getattr(obs, "observe_hist", None)
        if f is not None:
            f(name, value)


def _publish_gauges():
    with _LOCK:
        if not _SINKS:
            return
        sinks = list(_SINKS)
        vals = {
            "compile_queue_depth": len(_JOBS),
            "compile_pending_fragments":
                STATS["compile_pending_fragments"],
            "compile_bg_seconds": round(STATS["compile_bg_seconds"], 3),
            "compile_persist_hits": STATS["compile_persist_hits"],
        }
    for obs in sinks:
        try:
            for k, v in vals.items():
                obs.set_gauge(k, v)
        except Exception:
            pass


def wait_idle(timeout_s: float = 30.0) -> bool:
    """Block until no background compile is in flight (tests + ADMIN)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        with _LOCK:
            if not _JOBS:
                return True
        time.sleep(0.01)
    with _LOCK:
        return not _JOBS


def verify_drained() -> dict:
    """Chaos invariant (mirrors scheduler.verify_drained and the PR 6
    ticket invariant): once traffic stops, no compile job is leaked —
    nothing in flight, and every submitted job is accounted completed,
    failed or discarded."""
    with _LOCK:
        in_flight = len(_JOBS)
        accounted = (STATS["bg_completed"] + STATS["bg_failed"]
                     + STATS["bg_discarded"])
        return {"ok": in_flight == 0
                and accounted == STATS["bg_submitted"],
                "in_flight": in_flight,
                "submitted": STATS["bg_submitted"],
                "accounted": accounted}


def reset_for_tests():
    """Drop recipes/origins/counters (unit tests only).  In-flight jobs
    are waited out first so a stale worker can't repopulate the stats."""
    wait_idle(timeout_s=10.0)
    with _LOCK:
        _RECIPES.clear()
        _ORIGIN.clear()
        for k in STATS:
            STATS[k] = 0.0 if k == "compile_bg_seconds" else 0
        _LAST_ERROR[0] = ""
