"""Stale reads (reference: sessiontxn/interface.go:48 staleness
providers + executor/stale_txn_test.go): AS OF TIMESTAMP table reads,
START TRANSACTION READ ONLY AS OF TIMESTAMP, the tidb_snapshot sysvar
and tidb_read_staleness — all pin a historical read view; writes under a
stale view fail 1792."""

import time

import pytest

from tidb_tpu.errors import ErrCode, TiDBError
from tidb_tpu.testkit import TestKit


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table sr (a bigint primary key, b bigint)")
    tk.must_exec("insert into sr values (1, 10), (2, 20)")
    time.sleep(0.02)
    tk._t1 = tk.must_query("select now(6)").rows[0][0]
    time.sleep(0.02)
    tk.must_exec("update sr set b = 11 where a = 1")
    tk.must_exec("insert into sr values (3, 30)")
    return tk


class TestStaleRead:
    def test_as_of_table_read(self, tk):
        rows = tk.must_query(
            f"select * from sr as of timestamp '{tk._t1}' "
            "order by a").rows
        assert rows == [("1", "10"), ("2", "20")]
        # live read unaffected afterwards
        assert tk.must_query("select count(*) from sr").rows == [("3",)]

    def test_as_of_with_alias_and_filter(self, tk):
        rows = tk.must_query(
            f"select s.b from sr as of timestamp '{tk._t1}' s "
            "where s.a = 1").rows
        assert rows == [("10",)]

    def test_stale_readonly_txn(self, tk):
        tk.must_exec("start transaction read only as of timestamp "
                     f"'{tk._t1}'")
        assert tk.must_query("select b from sr where a = 1"
                             ).rows == [("10",)]
        assert tk.must_query("select count(*) from sr").rows == [("2",)]
        with pytest.raises(TiDBError) as ei:
            tk.must_exec("insert into sr values (9, 9)")
        assert ei.value.code == ErrCode.CantExecuteInReadOnlyTxn
        tk.must_exec("commit")
        assert tk.must_query("select count(*) from sr").rows == [("3",)]

    def test_tidb_snapshot_sysvar(self, tk):
        tk.must_exec(f"set tidb_snapshot = '{tk._t1}'")
        assert tk.must_query("select count(*) from sr").rows == [("2",)]
        with pytest.raises(TiDBError) as ei:
            tk.must_exec("delete from sr where a = 1")
        assert ei.value.code == ErrCode.CantExecuteInReadOnlyTxn
        tk.must_exec("set tidb_snapshot = ''")
        assert tk.must_query("select count(*) from sr").rows == [("3",)]

    def test_as_of_inside_txn_rejected(self, tk):
        tk.must_exec("begin")
        with pytest.raises(TiDBError) as ei:
            tk.must_query(
                f"select * from sr as of timestamp '{tk._t1}'")
        assert ei.value.code == ErrCode.AsOfInTxn
        tk.must_exec("rollback")

    def test_read_staleness(self, tk):
        """Negative staleness reads a recent-past view; 0 restores live
        reads (exact visible set depends on timing, so assert bounds)."""
        tk.must_exec("set tidb_read_staleness = -1000000")
        # a million seconds ago the table did not exist → no rows resolve
        try:
            n = tk.must_query("select count(*) from sr").rows
            assert n == [("0",)]
        except TiDBError:
            pass  # table-not-found at that ts is also acceptable
        tk.must_exec("set tidb_read_staleness = 0")
        assert tk.must_query("select count(*) from sr").rows == [("3",)]

    def test_as_of_interval_expression(self, tk):
        """AS OF TIMESTAMP NOW() - INTERVAL n SECOND — the idiomatic
        bound — parses and evaluates (temporal binary arithmetic)."""
        rows = tk.must_query(
            "select count(*) from sr as of timestamp now() - interval "
            "1 second").rows
        assert rows[0][0] in ("0", "2", "3")  # bounded by history

    def test_explain_does_not_leak_stale_ts(self, tk):
        """EXPLAIN plans (without running) a stale query; the pinned ts
        must not leak into later statements (regression: writes failed
        1792 after EXPLAIN ... AS OF)."""
        tk.must_exec(f"explain select * from sr as of timestamp "
                     f"'{tk._t1}'")
        tk.must_exec("insert into sr values (50, 500)")
        tk.must_exec("delete from sr where a = 50")
        assert tk.must_query("select count(*) from sr").rows == [("3",)]

    def test_plain_read_only_txn_blocks_writes(self, tk):
        tk.must_exec("start transaction read only")
        assert tk.must_query("select count(*) from sr").rows == [("3",)]
        with pytest.raises(TiDBError) as ei:
            tk.must_exec("insert into sr values (60, 600)")
        assert ei.value.code == ErrCode.CantExecuteInReadOnlyTxn
        tk.must_exec("commit")
        tk.must_exec("insert into sr values (60, 600)")
        tk.must_exec("delete from sr where a = 60")

    def test_now_fsp(self, tk):
        v6 = tk.must_query("select now(6)").rows[0][0]
        v0 = tk.must_query("select now()").rows[0][0]
        assert "." in v6 and len(v6.split(".")[1]) == 6
        assert "." not in v0
