"""ADMIN CHECK TABLE (reference: executor/admin.go — verifies index KVs are
consistent with row data)."""

from __future__ import annotations

from ..errors import TiDBError
from ..table import Table
from .. import tablecodec


def check_table(session, info):
    txn = session.store.begin()
    try:
        tbl = Table(info, txn)
        rows = dict(tbl.iter_rows())
        for idx in info.indexes:
            seen = 0
            start, end = tablecodec.index_range(info.id, idx.id)
            for key, value in txn.scan(start, end):
                if idx.unique and value != b"0":
                    handle = int(value)
                else:
                    handle = tablecodec.decode_index_values(key)[-1]
                if handle not in rows:
                    raise TiDBError(
                        f"index '{idx.name}' has orphan entry for handle {handle}")
                seen += 1
            expected = 0
            for handle, row in rows.items():
                vals = tbl._index_values(idx, row)
                if idx.unique and any(v is None for v in vals):
                    expected += 1  # null uniques stored with handle suffix
                else:
                    expected += 1
            if seen != expected:
                raise TiDBError(
                    f"index '{idx.name}' count {seen} != row count {expected}")
    finally:
        txn.rollback()
