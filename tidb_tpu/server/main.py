"""Server process entry point (reference: tidb-server/main.go:164 — flags →
config, store + domain bootstrap, MySQL wire server + HTTP status server,
signal-driven graceful shutdown).

Run:  python -m tidb_tpu.server [--port 4000] [--config cfg.toml]
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tidb-tpu-server",
        description="TPU-native MySQL-compatible HTAP server")
    p.add_argument("--host", default=None, help="listen host")
    p.add_argument("-P", "--port", type=int, default=None,
                   help="MySQL protocol port (default 4000)")
    p.add_argument("--status-host", default=None)
    p.add_argument("--status-port", type=int, default=None,
                   help="HTTP status port (default 10080; -1 disables)")
    p.add_argument("--store", default=None,
                   help="kv engine: auto | native | python")
    p.add_argument("--config", default=None, help="TOML config file")
    p.add_argument("--config-check", action="store_true",
                   help="validate the config file and exit")
    p.add_argument("-V", "--version", action="store_true")
    return p


def resolve_config(args):
    from ..config import load_config
    cfg = load_config(args.config, strict=args.config_check)
    # CLI flags override the file (reference: main.go overrideConfig)
    if args.host is not None:
        cfg.host = args.host
    if args.port is not None:
        cfg.port = args.port
    if args.status_host is not None:
        cfg.status.status_host = args.status_host
    if args.status_port is not None:
        if args.status_port < 0:
            cfg.status.report_status = False
        else:
            cfg.status.status_port = args.status_port
    if args.store is not None:
        cfg.store = args.store
    return cfg


def make_tls_context(cert_path: str = "", key_path: str = "",
                     auto_dir: str | None = None):
    """ssl.SSLContext for the wire server's in-handshake upgrade
    (reference: server/conn.go:256 upgradeToTLS + security.auto-tls).
    With no cert configured and auto_dir set, generates a self-signed
    RSA cert via the openssl CLI (the reference generates one in-process
    at startup). Returns None when TLS cannot be enabled."""
    import ssl
    import subprocess as sp
    import os as _os
    explicit = bool(cert_path)
    if not cert_path and auto_dir is not None:
        # per-user 0700 directory, ownership-verified: a fixed path in a
        # world-writable tmp would let another local user pre-plant the
        # server's TLS identity
        _os.makedirs(auto_dir, mode=0o700, exist_ok=True)
        st = _os.stat(auto_dir)
        if st.st_uid != _os.getuid() or (st.st_mode & 0o077):
            print(f"[tls] refusing auto-TLS dir {auto_dir}: not owned by "
                  f"this user or too permissive", file=sys.stderr)
            return None
        cert_path = _os.path.join(auto_dir, "auto-tls-cert.pem")
        key_path = _os.path.join(auto_dir, "auto-tls-key.pem")
        if not (_os.path.exists(cert_path) and _os.path.exists(key_path)):
            try:
                sp.run(["openssl", "req", "-x509", "-newkey", "rsa:2048",
                        "-nodes", "-keyout", key_path, "-out", cert_path,
                        "-days", "365", "-subj", "/CN=tidb-tpu"],
                       check=True, capture_output=True, timeout=60)
                _os.chmod(key_path, 0o600)
            except Exception as e:
                print(f"[tls] auto-TLS generation failed: {e}",
                      file=sys.stderr)
                return None
    if not cert_path or not key_path:
        return None
    try:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert_path, key_path)
        return ctx
    except Exception:
        if explicit:
            # a configured cert that fails to load must not silently
            # degrade the server to plaintext
            raise
        return None


def run_server(cfg, ready_event: threading.Event | None = None):
    """Bootstrap and serve until SIGINT/SIGTERM. Returns the exit code."""
    from ..kv import new_store
    from ..session import bootstrap_domain
    from .server import MySQLServer
    from .http_status import StatusServer

    store = new_store(backend=cfg.store)
    domain = bootstrap_domain(store)
    # calibrate the cost-model constants on THIS machine (reference: the
    # tidb_opt_*_factor family is hand-tuned there; here a ~50ms startup
    # micro-bench measures seek/build/sort relative to the vectorized
    # scan and installs the ratios as globals — planner/cost_model.py)
    if cfg.performance.calibrate_costs:
        from ..planner.cost_model import apply_calibration
        apply_calibration(domain)
    for name, val in (
            ("tidb_mem_quota_query", str(cfg.performance.mem_quota_query)),
            ("tidb_executor_engine", cfg.performance.executor_engine),
            ("tidb_mesh_shape", cfg.performance.mesh_shape),
            ("tidb_slow_log_threshold",
             str(cfg.performance.slow_log_threshold_ms))):
        domain.global_vars[name] = val
    if cfg.security.skip_grant_table:
        # sticky: later priv.load() calls (GRANT etc.) must not re-enable
        domain.priv.disabled = True
        domain.priv.enabled = False

    # server mode: liveness is real — re-register with a finite TTL so a
    # wedged process ages out of the registry; the stats worker's periodic
    # sweep heartbeats the lease (domain/infosync keepalive analog). The
    # embedded deployment keeps the infinite-TTL registration from
    # bootstrap (nothing heartbeats an idle library user).
    domain.coordinator.register_server(
        "tidb-0", {"version": "8.0.11-tpu-htap",
                   "status_port": cfg.status.status_port}, ttl_s=60.0)
    domain.stats_worker.start()  # auto-analyze loop (domain.go:1270 analog)
    domain.gc_worker.start()     # MVCC safepoint GC (store/gcworker analog)
    domain.topsql.start()        # CPU attribution sampler (util/topsql)
    ssl_ctx = None
    if cfg.security.ssl_cert or cfg.security.auto_tls:
        import tempfile
        ssl_ctx = make_tls_context(
            cfg.security.ssl_cert, cfg.security.ssl_key,
            auto_dir=(os.path.join(tempfile.gettempdir(),
                                   f"tidb_tpu_tls_{os.getuid()}")
                      if cfg.security.auto_tls else None))
    sql_srv = MySQLServer(domain, host=cfg.host, port=cfg.port,
                          ssl_ctx=ssl_ctx).start()
    status_srv = None
    if cfg.status.report_status:
        status_srv = StatusServer(domain, sql_srv,
                                  host=cfg.status.status_host,
                                  port=cfg.status.status_port).start()
    print(f"[tidb-tpu] SQL listening on {cfg.host}:{sql_srv.port}"
          + (f", status on :{status_srv.port}" if status_srv else ""),
          file=sys.stderr, flush=True)

    stop = threading.Event()

    def on_signal(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    if ready_event is not None:
        ready_event.set()
    stop.wait()
    # graceful: stop accepting, close status, drain (reference:
    # server.go GracefulDown)
    print("[tidb-tpu] shutting down", file=sys.stderr, flush=True)
    if status_srv is not None:
        status_srv.shutdown()
    sql_srv.shutdown()
    domain.ddl_worker.stop()
    domain.stats_worker.stop()
    domain.topsql.stop()
    return 0


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.version:
        print("tidb-tpu-server 8.0.11-tpu-htap")
        return 0
    try:
        cfg = resolve_config(args)
    except (ValueError, OSError) as e:
        print(f"config error: {e}", file=sys.stderr)
        return 1
    if args.config_check:
        print("config OK")
        return 0
    return run_server(cfg)


if __name__ == "__main__":
    sys.exit(main())
