"""Network coordinator: the coordination segment behind a TCP service.

The shared-memory segment (fabric/coord.py) coordinates one MACHINE's
process fleet.  A multi-host region fleet needs the same lease / epoch /
claim / TSO layout reachable across hosts, so this module puts the
Coordinator's public surface behind a small TCP service speaking
fabric/codec's length-prefixed frames — the exact transport the compile
server already proved out.  The segment stays the storage; the service
is a thin op dispatcher over an attached Coordinator, so single-machine
callers keep the mmap hot path and networked callers get the same
semantics through :class:`NetCoordinator`.

Failure discipline (mirrors compile_client): a torn frame stays a loud
``FrameError`` — classified transport, never silently retried into a
half-read stream.  The client retries each call under a ``coordRetry``
Backoffer budget; when the budget exhausts it marks the server down for
a cooldown window and DEGRADES rather than fails: admission ops answer
locally (admit-all, zero vtimes — the single-tenant behaviors), liveness
reads answer empty, and anything that must not guess (TSO leases, region
epochs, lock claims, WAL frontier writes) raises
:class:`CoordUnavailableError` so the caller's own lease/abort paths
run.  Queries never fail on a coordinator blip; durability never
proceeds on one.
"""

from __future__ import annotations

import contextlib
import logging
import socket
import socketserver
import threading
import time

from . import codec
from ..session import tracing
from ..utils.backoff import Backoffer, BackoffExhaustedError

log = logging.getLogger("tidb_tpu.fabric.coord_net")

DOWN_COOLDOWN_S = 5.0
CONNECT_TIMEOUT_S = 5.0
REQUEST_TIMEOUT_S = 10.0
#: per-call retry budget — coordinator ops are tiny; a call that cannot
#: land inside this is a down server, not a slow one
RETRY_BUDGET_MS = 200.0

#: ops a networked peer may invoke — everything stateful goes through
#: the segment's own locking; anything NOT listed (close/unlink/attach,
#: page-path helpers that only make sense machine-locally) is rejected
OPS = frozenset({
    "bump", "counters",
    "claim_slot", "heartbeat", "release_slot", "live_slots",
    "reclaim_expired",
    "try_acquire_running", "release_running", "running_total",
    "peak_running", "vtimes", "vtime_advance", "charge_hbm",
    "hbm_remote_bytes",
    "tso_lease", "publish_schema_version", "schema_version",
    "wal_len", "set_wal_len", "set_min_read_ts", "fleet_min_read_ts",
    "set_wal_applied", "min_wal_applied",
    "set_commit_frontier", "commit_frontiers",
    "ddl_claim", "ddl_heartbeat", "ddl_release", "ddl_check",
    "lock_claim", "lock_release",
    "region_claim", "region_heartbeat", "region_release",
    "region_release_all", "region_check", "region_set_committed",
    "region_committed_len", "region_set_applied", "region_info",
    "regions_expired", "region_owners",
    "dedup_claim", "dedup_publish", "dedup_fail", "dedup_poll",
    "next_result_id", "prewarm_claim", "result_page_path",
    "table_version_advance", "table_versions",
    "set_direct_port", "direct_ports",
    "perf_merge", "perf_rows", "perf_lookup",
    "snapshot", "verify_drained",
})

#: ops that degrade to a local answer inside the client's down-window —
#: the admission/liveness reads where "no coordination" must mean "solo
#: behavior", never a failed query
_DEGRADE = {
    "try_acquire_running": lambda args, kwargs: True,
    "release_running": lambda args, kwargs: None,
    "vtimes": lambda args, kwargs: {g: 0.0 for g in (args[0] if args
                                                     else [])},
    "vtime_advance": lambda args, kwargs: 0.0,
    "charge_hbm": lambda args, kwargs: None,
    "hbm_remote_bytes": lambda args, kwargs: 0,
    "running_total": lambda args, kwargs: 0,
    "peak_running": lambda args, kwargs: 0,
    "live_slots": lambda args, kwargs: [],
    "heartbeat": lambda args, kwargs: None,
    "set_min_read_ts": lambda args, kwargs: None,
    "fleet_min_read_ts": lambda args, kwargs: 0,
    # frontier publish during a down-window: drop it — the appender's
    # frontier is forward-only and the next fsync (or heartbeat
    # republish) repairs the cell.  commit_frontiers is deliberately NOT
    # degradable: an empty answer would read as "nothing to wait for"
    # and turn a down-window into a silent stale read; the reader's
    # entry point catches CoordUnavailableError and downgrades LOUDLY
    # (stale_ok surfaced in EXPLAIN ANALYZE).  The ddl_* lease ops are
    # not degradable either — a lease minted locally fences nothing
    "set_commit_frontier": lambda args, kwargs: None,
    "bump": lambda args, kwargs: 0,
    "counters": lambda args, kwargs: {},
    # result cache during a down-window: version advances are dropped
    # (the committing worker's tailer peers re-publish on apply, and the
    # cache TTL backstops the remainder) and version READS answer empty —
    # "no fleet version known" makes every fragment cache-ineligible,
    # which degrades to plain in-flight dedup, never to a stale hit
    "table_version_advance": lambda args, kwargs: None,
    "table_versions": lambda args, kwargs: {},
    # observability during a down-window: perf samples drop (observe-
    # only data, recomputed forever), peer discovery answers empty (a
    # cluster memtable degrades to local rows, never a failed query)
    "set_direct_port": lambda args, kwargs: None,
    "direct_ports": lambda args, kwargs: {},
    "perf_merge": lambda args, kwargs: 0,
    "perf_rows": lambda args, kwargs: [],
    "perf_lookup": lambda args, kwargs: [],
    # dedup during a down-window: "miss" is the solo answer — compute
    # locally, no claim held, nothing to publish or leak
    "dedup_claim": lambda args, kwargs: ("miss", -1, 0),
    "prewarm_claim": lambda args, kwargs: True,
}


class CoordUnavailableError(ConnectionError):
    """The coordinator service is unreachable and the op cannot degrade
    locally.  Subclasses ConnectionError so utils/backoff classifies it
    ``transport`` without special-casing."""


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        coord = self.server.coordinator  # type: ignore[attr-defined]
        sock = self.request
        sock.settimeout(REQUEST_TIMEOUT_S)
        while True:
            try:
                req = codec.read_frame(sock)
            except codec.FrameError as e:
                # torn/garbage frame: loud, then drop the connection —
                # resynchronizing a pickled stream is how corruption
                # spreads.  A clean EOF between frames ("got 0 of 8")
                # is the client hanging up, not a tear.
                if "got 0 of" not in str(e):
                    log.warning("torn frame from %s: %s",
                                self.client_address, e)
                return
            except OSError:
                return
            op = req.get("op")
            # record the hop into THIS process's ring on the caller's
            # behalf (one branch for untraced requests)
            rtr = tracing.begin_remote(req.pop("trace", None),
                                       f"coord.{op}")
            if op not in OPS:
                resp = {"ok": False, "err": f"op {op!r} not allowed"}
            else:
                try:
                    ret = getattr(coord, op)(*req.get("args", ()),
                                             **req.get("kwargs", {}))
                    resp = {"ok": True, "ret": ret}
                except Exception as e:  # noqa: BLE001 — errors cross the
                    #   wire by type name; the client re-raises loudly
                    resp = {"ok": False, "err": f"{type(e).__name__}: {e}",
                            "err_type": type(e).__name__}
            sub = tracing.finish_remote(rtr, succ=bool(resp.get("ok")))
            if sub is not None:
                resp["_trace"] = sub
            try:
                codec.write_frame(sock, resp)
            except OSError:
                return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class CoordServer:
    """Serve an attached Coordinator over TCP.  One thread per
    connection (coordinator ops are microseconds under the segment
    lock; the thread count is bounded by the fleet size)."""

    def __init__(self, coordinator, host: str = "127.0.0.1",
                 port: int = 0):
        self.coordinator = coordinator
        self._srv = _Server((host, port), _Handler)
        self._srv.coordinator = coordinator
        self.address = "%s:%d" % self._srv.server_address[:2]
        self._thread = None

    def start(self) -> str:
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="coord-server")
        self._thread.start()
        log.info("coordinator service on %s", self.address)
        return self.address

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class NetCoordinator:
    """Client-side Coordinator facade: every segment op becomes one
    framed round trip.  Same method surface as fabric/coord.Coordinator
    (for the allowlisted ops), so RegionStore / DurableMVCCStore /
    admission code cannot tell the difference — except in failure
    behavior, which is the point (see module docstring)."""

    def __init__(self, address: str, *, nregions: "int | None" = None,
                 down_cooldown_s: float = DOWN_COOLDOWN_S):
        self.address = address
        self._down_until = 0.0
        self._down_cooldown = down_cooldown_s
        self._mu = threading.Lock()
        # mirror of Coordinator.nregions for RegionMap sizing; fetched
        # lazily from a snapshot when not given
        self._nregions = nregions

    @property
    def nregions(self) -> int:
        if self._nregions is None:
            snap = self._call("snapshot")
            self._nregions = len(snap.get("regions", []))
        return self._nregions

    def healthy(self) -> bool:
        return time.monotonic() >= self._down_until

    def _mark_down(self):
        self._down_until = time.monotonic() + self._down_cooldown

    def _connect(self):
        host, port = self.address.rsplit(":", 1)
        return socket.create_connection((host, int(port)),
                                        timeout=CONNECT_TIMEOUT_S)

    def _roundtrip(self, req: dict):
        ctx = tracing.wire_ctx()
        if ctx is not None:  # propagate the active trace across the hop
            req["trace"] = ctx
        with self._mu:
            sock = self._connect()
            try:
                sock.settimeout(REQUEST_TIMEOUT_S)
                codec.write_frame(sock, req)
                resp = codec.read_frame(sock)
            finally:
                with contextlib.suppress(OSError):
                    sock.close()
        # graft the coordinator's recorded subtree under the current span
        tracing.attach_remote(resp.pop("_trace", None))
        return resp

    def _call(self, op: str, *args, **kwargs):
        req = {"op": op, "args": args, "kwargs": kwargs}
        if not self.healthy():
            deg = _DEGRADE.get(op)
            if deg is not None:
                return deg(args, kwargs)
            raise CoordUnavailableError(
                f"coordinator {self.address} in down-window")
        bo = Backoffer(budget_ms=RETRY_BUDGET_MS)
        while True:
            try:
                resp = self._roundtrip(req)
                break
            except (OSError, codec.FrameError) as e:
                try:
                    bo.backoff("coordRetry", e)
                except BackoffExhaustedError:
                    self._mark_down()
                    from . import state
                    with contextlib.suppress(Exception):
                        state.bump("fabric_remote_errors")
                    deg = _DEGRADE.get(op)
                    if deg is not None:
                        log.warning("coordinator %s down; %s degrades "
                                    "to local-only", self.address, op)
                        return deg(args, kwargs)
                    raise CoordUnavailableError(
                        f"coordinator {self.address} unreachable: "
                        f"{type(e).__name__}: {e}") from e
        if not resp.get("ok"):
            raise CoordRemoteError(resp.get("err", "unknown error"),
                                   resp.get("err_type"))
        return resp.get("ret")

    #: dedup-claim owner slot (fabric/state.activate).  The server-side
    #: Coordinator instance is SHARED by every TCP client, so claim
    #: ownership cannot live in its instance attribute: remember the
    #: slot here and stamp it onto each dedup_claim request instead —
    #: crash reclaim needs the true owner on every claimed entry
    _owner_slot: "int | None" = None

    def set_claim_owner(self, slot: int):
        self._owner_slot = int(slot)

    def dedup_claim(self, key_hash, ttl_s, vv_hash: int = 0,
                    check_vv: bool = True):
        return self._call("dedup_claim", key_hash, ttl_s,
                          vv_hash=vv_hash, check_vv=check_vv,
                          owner=self._owner_slot)

    def __getattr__(self, name):
        if name.startswith("_") or name not in OPS:
            raise AttributeError(name)

        def call(*args, **kwargs):
            return self._call(name, *args, **kwargs)

        call.__name__ = name
        return call


class CoordRemoteError(RuntimeError):
    """The coordinator executed the op and it raised — a semantic
    failure (bad slot, out-of-range region), not a transport one."""

    def __init__(self, msg: str, err_type: "str | None" = None):
        super().__init__(msg)
        self.err_type = err_type
