"""WITH RECURSIVE fixpoint evaluation (reference: executor/cte.go:60 —
seed + iterate, UNION dedup, cte_max_recursion_depth bound)."""

import pytest

from tidb_tpu.errors import TiDBError
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    return TestKit()


def test_numbers_sequence(tk):
    tk.must_query(
        "with recursive seq (n) as ("
        "  select 1 union all select n + 1 from seq where n < 5) "
        "select n from seq order by n").check(
            [("1",), ("2",), ("3",), ("4",), ("5",)])


def test_union_distinct_terminates_on_cycle(tk):
    """UNION (distinct) reaches a fixpoint even when the recursive part
    would loop forever under UNION ALL."""
    tk.must_query(
        "with recursive c (n) as ("
        "  select 1 union select (n % 3) + 1 from c) "
        "select count(*), min(n), max(n) from c").check([("3", "1", "3")])


def test_recursion_depth_limit(tk):
    tk.must_exec("set cte_max_recursion_depth = 10")
    e = tk.exec_error(
        "with recursive f (n) as ("
        "  select 1 union all select n + 1 from f) select * from f")
    assert "aborted" in str(e)
    tk.must_exec("set cte_max_recursion_depth = 1000")


def test_hierarchy_walk(tk):
    tk.must_exec("create table emp (id int primary key, mgr int, "
                 "name varchar(16))")
    tk.must_exec("insert into emp values (1, null, 'ceo'), (2, 1, 'vp1'), "
                 "(3, 1, 'vp2'), (4, 2, 'eng1'), (5, 4, 'intern')")
    tk.must_query(
        "with recursive chain (id, name, depth) as ("
        "  select id, name, 0 from emp where mgr is null "
        "  union all "
        "  select e.id, e.name, c.depth + 1 from emp e, chain c "
        "  where e.mgr = c.id) "
        "select name, depth from chain order by depth, name").check([
            ("ceo", "0"), ("vp1", "1"), ("vp2", "1"),
            ("eng1", "2"), ("intern", "3")])


def test_fibonacci(tk):
    tk.must_query(
        "with recursive fib (a, b) as ("
        "  select 1, 1 union all select b, a + b from fib where b < 50) "
        "select max(b) from fib").check([("55",)])


def test_recursive_cte_joined_with_table(tk):
    tk.must_exec("create table vals (v int primary key)")
    tk.must_exec("insert into vals values (2), (4), (6)")
    tk.must_query(
        "with recursive seq (n) as ("
        "  select 1 union all select n + 1 from seq where n < 6) "
        "select v from vals, seq where v = n order by v").check(
            [("2",), ("4",), ("6",)])


def test_nonrecursive_with_still_inlines(tk):
    tk.must_exec("create table t0 (a int primary key)")
    tk.must_exec("insert into t0 values (1), (2)")
    tk.must_query(
        "with w as (select a from t0 where a > 1) select * from w").check(
            [("2",)])


def test_missing_seed_rejected(tk):
    e = tk.exec_error(
        "with recursive bad (n) as (select n + 1 from bad) "
        "select * from bad")
    assert "seed" in str(e) or "UNION" in str(e)


def test_string_columns_in_recursion(tk):
    tk.must_query(
        "with recursive p (s) as ("
        "  select 'a' union all select concat(s, 'x') from p "
        "  where length(s) < 3) "
        "select s from p order by length(s)").check(
            [("a",), ("ax",), ("axx",)])


def test_multiple_references(tk):
    tk.must_query(
        "with recursive seq (n) as ("
        "  select 1 union all select n + 1 from seq where n < 3) "
        "select a.n, b.n from seq a, seq b where a.n = b.n "
        "order by a.n").check([("1", "1"), ("2", "2"), ("3", "3")])


def test_without_recursive_keyword_refers_to_real_table(tk):
    """A plain WITH whose body names itself reads the REAL table (MySQL
    scoping); only WITH RECURSIVE makes the name self-visible."""
    tk.must_exec("create table rt (a int primary key)")
    tk.must_exec("insert into rt values (10), (20)")
    tk.must_query(
        "with rt as (select a from rt union all select 99) "
        "select a from rt order by a").check([("10",), ("20",), ("99",)])


def test_limit_terminates_iteration(tk):
    tk.must_query(
        "with recursive s (n) as (select 1 union all "
        "select n + 1 from s where n < 100 limit 5) "
        "select count(*) from s").check([("5",)])


def test_intersect_except_rejected(tk):
    e = tk.exec_error(
        "with recursive s (n) as (select 1 except select 1 union all "
        "select n + 1 from s where n < 3) select * from s")
    assert "UNION" in str(e)


def test_depth_zero_with_unproductive_recursion(tk):
    """An empty final step is termination, not a depth violation."""
    tk.must_exec("set cte_max_recursion_depth = 0")
    tk.must_query(
        "with recursive s (n) as (select 1 union all "
        "select n + 1 from s where n > 99) select * from s").check([("1",)])
    tk.must_exec("set cte_max_recursion_depth = 1000")


def test_multiple_references_single_materialization(tk, monkeypatch):
    """k references to one recursive CTE run the fixpoint ONCE."""
    import tidb_tpu.planner.builder as B
    calls = {"n": 0}
    orig = B.PlanBuilder._build_recursive_cte

    def counting(self, node):
        hit = getattr(self.ctx, "cte_results", {}).get(
            (node.name, node.query.restore()))
        if hit is None:
            calls["n"] += 1
        return orig(self, node)
    monkeypatch.setattr(B.PlanBuilder, "_build_recursive_cte", counting)
    tk.must_query(
        "with recursive seq (n) as ("
        "  select 1 union all select n + 1 from seq where n < 3) "
        "select a.n from seq a, seq b where a.n = b.n order by a.n"
    ).check([("1",), ("2",), ("3",)])
    assert calls["n"] == 1
