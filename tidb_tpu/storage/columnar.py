"""Per-table columnar snapshots.

Scans are the hot read path of the analytical engine; decoding rows per query
would drown the device in host work. The cache materializes a table once per
write-watermark into column arrays (plus the handle column), and serves
projections by column id. Bulk loaders (the Lightning role) can install
columns directly, bypassing row encode/decode entirely.
"""

from __future__ import annotations

import threading

import numpy as np

from ..model import TableInfo
from ..sqltypes import TYPE_LONGLONG, FieldType
from ..table import Table, rows_to_chunk
from ..utils.chunk import Chunk, Column


class _Entry:
    __slots__ = ("version", "col_sig", "columns", "handles", "nrows")

    def __init__(self, version, col_sig, columns, handles, nrows):
        self.version = version
        self.col_sig = col_sig
        self.columns = columns  # {col_id: Column}
        self.handles = handles  # np.int64 array
        self.nrows = nrows


class ColumnarCache:
    def __init__(self, storage):
        self.storage = storage
        self._lock = threading.Lock()
        self._entries: dict[int, _Entry] = {}

    def invalidate(self, table_id: int):
        with self._lock:
            self._entries.pop(table_id, None)

    def get(self, info: TableInfo, snapshot) -> _Entry:
        """Materialized columns for the table at the current write watermark.
        `snapshot` must be a kv view with .scan (Snapshot or Transaction)."""
        tid = info.id
        version = self.storage.mvcc.table_version(tid)
        col_sig = tuple(c.id for c in info.public_columns())
        with self._lock:
            e = self._entries.get(tid)
            if e is not None and e.version == version and e.col_sig == col_sig:
                return e
        e = self._build(info, snapshot, version, col_sig)
        with self._lock:
            self._entries[tid] = e
        return e

    def _build(self, info, snapshot, version, col_sig):
        tbl = Table(info, snapshot)
        cols = info.public_columns()
        handles = []
        rowdicts = []
        for handle, row in tbl.iter_rows():
            handles.append(handle)
            rowdicts.append(row)
        chunk = rows_to_chunk(info, cols, handles, rowdicts)
        columns = {c.id: chunk.columns[i] for i, c in enumerate(cols)}
        return _Entry(version, col_sig, columns,
                      np.array(handles, dtype=np.int64), len(handles))

    def install_bulk(self, info: TableInfo, columns: dict, handles: np.ndarray):
        """Bulk-load path (the Lightning physical-import role): install
        column arrays directly and mark the table version as current."""
        tid = info.id
        version = self.storage.mvcc.table_version(tid)
        col_sig = tuple(c.id for c in info.public_columns())
        e = _Entry(version, col_sig, columns, handles, len(handles))
        with self._lock:
            self._entries[tid] = e
        return e

    def project(self, entry: _Entry, col_infos, info: TableInfo) -> Chunk:
        out = []
        for c in col_infos:
            col = entry.columns.get(c.id)
            if col is None:
                # column added after materialization: all default/null
                from ..utils.chunk import np_dtype_for
                dt = np_dtype_for(c.ftype)
                n = entry.nrows
                if c.default_value is not None:
                    if dt is object:
                        data = np.full(n, c.default_value, dtype=object)
                    else:
                        data = np.full(n, c.default_value, dtype=dt)
                    nulls = np.zeros(n, dtype=bool)
                else:
                    data = (np.full(n, b"", dtype=object) if dt is object
                            else np.zeros(n, dtype=dt))
                    nulls = np.ones(n, dtype=bool)
                col = Column(c.ftype, data, nulls)
            out.append(col)
        return Chunk(out)

    def handle_column(self, entry: _Entry) -> Column:
        return Column(FieldType(tp=TYPE_LONGLONG),
                      entry.handles, np.zeros(entry.nrows, dtype=bool))
