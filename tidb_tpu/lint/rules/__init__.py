"""Rule registry population: importing this package registers every rule
with the engine (tidb_tpu.lint.engine.RULES)."""

from . import confinement  # noqa: F401
from . import exceptions  # noqa: F401
from . import failpoints  # noqa: F401
from . import gauges  # noqa: F401
from . import guards  # noqa: F401
from . import locks  # noqa: F401
from . import sysvar_scope  # noqa: F401
from . import taxonomy  # noqa: F401
from . import trace_cov  # noqa: F401
from . import traced  # noqa: F401
