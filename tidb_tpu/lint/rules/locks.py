"""Static lock-acquisition analysis over the whole package:

  * ``lock-order``: build the lock-acquisition graph from nested
    ``with <lock>:`` scopes plus one level of best-effort call resolution
    (a function called while a lock is held contributes every lock it —
    transitively — acquires), and fail on cycles: two threads taking the
    same pair of locks in opposite orders is the deadlock class the
    threaded chaos harness can only catch probabilistically.  A nested
    ``with`` on the SAME plain (non-reentrant) lock is reported as a
    guaranteed self-deadlock.

  * ``blocking-while-locked``: a blocking operation (device dispatch,
    supervised calls, ``time.sleep``, backoff sleeps, URL fetches)
    performed while holding a MODULE-LEVEL lock serializes every other
    thread in the process behind one slow call — the scheduler/residency
    /compile-service mutexes are meant to guard STATE transitions, not
    I/O.

Lock identity is ``<rel>::<NAME>`` for module-level locks and
``<rel>::<Class>.<attr>`` for ``self.<attr> = threading.Lock()``
instance locks.  ``threading.Condition(existing_lock)`` aliases to the
lock it wraps; a bare ``Condition()`` is reentrant (RLock-backed).
Receivers other than ``self`` resolve only when the attribute name maps
to exactly one known lock package-wide; unresolvable expressions are
skipped (this analysis under-approximates — it must never guess).
"""

from __future__ import annotations

import ast

from ..engine import Rule, register
from ._util import call_name, dotted, import_map

LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": True,
              "Semaphore": False, "BoundedSemaphore": False}

#: call leaf-names that BLOCK (wall-clock waits / device work) — checked
#: while a module-level lock is held.  ``sleep`` must be ``time.sleep``
#: to dodge same-named params; the rest are project-specific enough to
#: match by leaf.
BLOCKING_LEAVES = {"call_supervised", "supervised_call", "run_device",
                   "block_until_ready", "urlopen", "backoff",
                   "to_device_col"}


class _Lock:
    __slots__ = ("ident", "reentrant", "module_level", "rel", "line")

    def __init__(self, ident, reentrant, module_level, rel, line):
        self.ident = ident
        self.reentrant = reentrant
        self.module_level = module_level
        self.rel = rel
        self.line = line


def _lock_ctor(value: ast.AST):
    """(ctor_name, first_arg) when value is threading.<ctor>(...)."""
    if not isinstance(value, ast.Call):
        return None
    name = call_name(value)
    leaf = name.rsplit(".", 1)[-1]
    if leaf in LOCK_CTORS and (name == leaf or
                               name.startswith("threading.")):
        return leaf, (value.args[0] if value.args else None)
    return None


class _Model:
    """Package-wide lock + function-summary tables."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.locks: dict[str, _Lock] = {}
        # per-file: local name -> lock ident (module-level + aliases)
        self.mod_locks: dict[str, dict] = {}
        # instance-lock attr name -> [idents] (for unique-match fallback)
        self.attr_locks: dict[str, list] = {}
        # per-file import map
        self.imports: dict[str, dict] = {}
        # function summaries keyed "rel::qualname"
        self.direct: dict[str, set] = {}
        self.calls_all: dict[str, set] = {}
        self.calls_under: dict[str, list] = {}  # (held, callee, line, name)
        # EVERY resolved call with the locks held at the site (held may be
        # empty) — the guard-inference layer (rules/guards.py) derives
        # entry-held lock sets and the *_locked call contract from this
        self.call_records: dict[str, list] = {}  # (held, callee, line)
        self.blocking: list = []  # findings raw (rel, line, qn, call, lock)
        self.nest_edges: list = []  # (a, b, rel, line, note)
        # name -> [fn keys] for unique-method resolution
        self.fn_by_leaf: dict[str, list] = {}
        self.class_names: dict[str, set] = {}

    # -- phase 1: inventory ---------------------------------------------

    def inventory(self):
        for sf in self.ctx.package_files:
            self.imports[sf.rel] = import_map(sf.tree, sf.rel)
            locals_ = self.mod_locks.setdefault(sf.rel, {})
            classes = self.class_names.setdefault(sf.rel, set())
            for node in sf.tree.body:
                if isinstance(node, ast.Assign):
                    ctor = _lock_ctor(node.value)
                    if ctor:
                        leaf, arg = ctor
                        for tgt in node.targets:
                            if not isinstance(tgt, ast.Name):
                                continue
                            # Condition(existing) aliases the wrapped lock
                            if (leaf == "Condition" and arg is not None
                                    and isinstance(arg, ast.Name)
                                    and arg.id in locals_):
                                locals_[tgt.id] = locals_[arg.id]
                                continue
                            ident = f"{sf.rel}::{tgt.id}"
                            self.locks[ident] = _Lock(
                                ident, LOCK_CTORS[leaf], True, sf.rel,
                                node.lineno)
                            locals_[tgt.id] = ident
                if isinstance(node, ast.ClassDef):
                    classes.add(node.name)
            # ONE full walk: instance locks, nested classes, and the
            # function index for unique-leaf call resolution
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    classes.add(node.name)
                    continue
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    key = f"{sf.rel}::{self._defqual(sf, node)}"
                    self.fn_by_leaf.setdefault(node.name, []).append(key)
                    continue
                if not isinstance(node, ast.Assign):
                    continue
                ctor = _lock_ctor(node.value)
                if not ctor:
                    continue
                leaf, arg = ctor
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        cls = self._enclosing_class(sf, node)
                        if not cls:
                            continue
                        ident = f"{sf.rel}::{cls}.{tgt.attr}"
                        if ident not in self.locks:
                            self.locks[ident] = _Lock(
                                ident, LOCK_CTORS[leaf], False, sf.rel,
                                node.lineno)
                            self.attr_locks.setdefault(
                                tgt.attr, []).append(ident)

    def _defqual(self, sf, node):
        # a def node's engine qualname already includes its own name
        return sf.qualname(node)

    def _enclosing_class(self, sf, node) -> str:
        qn = sf.qualname(node)
        classes = self.class_names.get(sf.rel, set())
        for part in qn.split("."):
            if part in classes:
                return part
        return ""

    # -- lock-expression resolution --------------------------------------

    def resolve_lock(self, sf, expr) -> str | None:
        name = dotted(expr)
        if not name:
            return None
        locals_ = self.mod_locks.get(sf.rel, {})
        if name in locals_:
            return locals_[name]
        if "." in name:
            head, attr = name.split(".", 1)
            if "." in attr:
                # deep chain (self.domain.table_locks_mu): the receiver is
                # some OTHER object, so the class-local rules below do not
                # apply — a unique package-wide attr match is the only
                # safe resolution (ambiguity stays unresolved, never
                # guessed)
                cands = self.attr_locks.get(name.rsplit(".", 1)[-1], [])
                if len(cands) == 1:
                    return cands[0]
                return None
            if head == "self":
                cls = self._enclosing_class(sf, expr)
                ident = f"{sf.rel}::{cls}.{attr}"
                if ident in self.locks:
                    return ident
                # self.<attr> of a class whose lock we did not inventory
                # (assigned via helper): do NOT fall through to the
                # unique-attr match — binding it to ANOTHER class's lock
                # would fabricate self-deadlock/cycle findings
                return None
            # module.NAME via imports
            imp = self.imports.get(sf.rel, {})
            if head in imp:
                mod_rel = imp[head] + ".py"
                target = self.mod_locks.get(mod_rel, {})
                if attr in target:
                    return target[attr]
            # unique instance-attr match package-wide
            cands = self.attr_locks.get(attr, [])
            if len(cands) == 1:
                return cands[0]
        else:
            # bare name imported from another module
            sym = self.imports.get(sf.rel, {}).get(name + "::sym")
            if sym and "::" in sym:
                mod, leaf = sym.split("::", 1)
                target = self.mod_locks.get(mod + ".py", {})
                if leaf in target:
                    return target[leaf]
        return None

    # -- callee resolution ------------------------------------------------

    def resolve_call(self, sf, call: ast.Call) -> str | None:
        name = call_name(call)
        if not name:
            return None
        if "." not in name:
            key = f"{sf.rel}::{name}"
            if key in self.direct:
                return key
            sym = self.imports.get(sf.rel, {}).get(name + "::sym")
            if sym and "::" in sym:
                mod, leaf = sym.split("::", 1)
                key = f"{mod}.py::{leaf}"
                if key in self.direct:
                    return key
            return None
        head, rest = name.split(".", 1)
        if "." in rest:
            return None
        if head == "self":
            cls = self._enclosing_class(sf, call)
            key = f"{sf.rel}::{cls}.{rest}"
            if key in self.direct:
                return key
            return None
        imp = self.imports.get(sf.rel, {})
        if head in imp:
            key = f"{imp[head]}.py::{rest}"
            if key in self.direct:
                return key
        # unique method/function leaf package-wide (obs.set_gauge style)
        cands = self.fn_by_leaf.get(rest, [])
        if len(cands) == 1:
            return cands[0]
        return None

    # -- phase 2: per-function walk ---------------------------------------

    def summarize(self):
        for sf in self.ctx.package_files:
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    key = f"{sf.rel}::{self._defqual(sf, node)}"
                    self.direct.setdefault(key, set())
                    self.calls_all.setdefault(key, set())
                    self.calls_under.setdefault(key, [])
                    self.call_records.setdefault(key, [])
        for sf in self.ctx.package_files:
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    key = f"{sf.rel}::{self._defqual(sf, node)}"
                    self._walk_fn(sf, key, node)

    def _walk_fn(self, sf, key, fn):
        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                # nested defs get their own summary pass; the closure does
                # not RUN at definition time, so held locks do not apply
                return
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    lock = self.resolve_lock(sf, item.context_expr)
                    if lock is not None:
                        # earlier items of the SAME `with A, B:` are
                        # already held when B is taken — they order too
                        for h in held + acquired:
                            self.nest_edges.append(
                                (h, lock, sf.rel, node.lineno, "nested"))
                        self.direct[key].add(lock)
                        acquired.append(lock)
                for child in node.body:
                    visit(child, held + acquired)
                return
            if isinstance(node, ast.Call):
                callee = self.resolve_call(sf, node)
                if callee is not None:
                    self.calls_all[key].add(callee)
                    self.call_records[key].append(
                        (tuple(held), callee, node.lineno))
                    if held:
                        self.calls_under[key].append(
                            (tuple(held), callee, node.lineno,
                             call_name(node)))
                mod_held = [h for h in held
                            if h in self.locks
                            and self.locks[h].module_level]
                if mod_held:
                    cname = call_name(node)
                    leaf = cname.rsplit(".", 1)[-1]
                    if (leaf in BLOCKING_LEAVES
                            or cname in ("time.sleep",)):
                        self.blocking.append(
                            (sf.rel, node.lineno, sf.qualname(node),
                             cname, mod_held[0]))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, [])

    # -- phase 3: closure + edges -----------------------------------------

    def entry_held(self) -> dict:
        """Lock set statically held at ENTRY of every function: the meet
        (intersection) over each resolved call site of (locks held at the
        site ∪ the caller's own entry set), iterated to a fixpoint — the
        call-propagation that makes a ``*_locked`` helper's body count as
        guarded when every caller takes the lock first.  A function with
        no resolved call sites (an entry point, a thread target, anything
        reached only through unresolvable indirection) gets the empty
        set: this analysis under-approximates, it must never guess."""
        # call sites grouped per callee
        sites: dict[str, list] = {}
        for caller, recs in self.call_records.items():
            for held, callee, _line in recs:
                if callee in self.direct:
                    sites.setdefault(callee, []).append((caller, held))
        # no resolved callers = entry point: nothing held.  Called
        # functions start at TOP (None — "every lock", the identity of
        # the meet) and shrink monotonically; a distinct sentinel, not
        # frozenset(all locks), so a function legitimately entered with
        # every lock of a small module held is never mistaken for TOP.
        entry: dict = {k: (None if k in sites else frozenset())
                       for k in self.direct}
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for callee, recs in sites.items():
                acc = None
                for caller, held in recs:
                    ce = entry.get(caller, frozenset())
                    if ce is None:
                        continue  # TOP caller: identity for the meet
                    eff = ce | frozenset(held)
                    acc = eff if acc is None else (acc & eff)
                if acc is not None and acc != entry[callee]:
                    entry[callee] = acc
                    changed = True
        # anything still TOP is reachable only from a closed call cycle
        # with no outside entry — assume nothing held
        return {k: (frozenset() if v is None else v)
                for k, v in entry.items()}

    def effective(self) -> dict:
        eff = {k: set(v) for k, v in self.direct.items()}
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for k, callees in self.calls_all.items():
                for g in callees:
                    extra = eff.get(g, ())
                    if not eff[k].issuperset(extra):
                        eff[k] |= extra
                        changed = True
        return eff

    def edges(self):
        eff = self.effective()
        out = list(self.nest_edges)
        for k, recs in self.calls_under.items():
            for held, callee, line, cname in recs:
                rel = k.split("::", 1)[0]
                for b in eff.get(callee, ()):
                    for a in held:
                        out.append((a, b, rel, line, f"via {cname}()"))
        return out


def _sccs(nodes, adj):
    """Tarjan strongly-connected components."""
    index = {}
    low = {}
    stack, on_stack = [], set()
    sccs = []
    counter = [0]

    def strong(v):
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            succs = adj.get(node, [])
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for v in nodes:
        if v not in index:
            strong(v)
    return sccs


def _model_for(ctx) -> _Model:
    """One inventory+summary pass per Context, shared by both lock rules
    (the model walk is the most expensive analysis in the registry)."""
    model = getattr(ctx, "_lock_model", None)
    if model is None:
        model = _Model(ctx)
        model.inventory()
        model.summarize()
        ctx._lock_model = model
    return model


@register
class LockOrder(Rule):
    name = "lock-order"
    title = "no cycles in the static lock-acquisition graph"

    def prepare(self, ctx):
        _model_for(ctx)

    def run(self, ctx):
        model = _model_for(ctx)
        edges = model.edges()
        out = []

        adj: dict[str, list] = {}
        witness: dict[tuple, tuple] = {}
        self_edges = []
        for a, b, rel, line, note in edges:
            if a == b:
                lk = model.locks.get(a)
                if lk is not None and not lk.reentrant \
                        and note == "nested":
                    # only DIRECT nesting is a guaranteed deadlock; a
                    # call-derived self-edge may be conditional
                    self_edges.append((a, rel, line))
                continue
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
            witness.setdefault((a, b), (rel, line, note))

        for a, rel, line in sorted(set(self_edges)):
            out.append(self.finding(
                rel, line, f"self-deadlock:{_short(a)}",
                f"nested acquisition of non-reentrant lock {a} — "
                "guaranteed self-deadlock"))

        for comp in _sccs(sorted(adj), adj):
            if len(comp) < 2:
                continue
            comp = sorted(comp)
            pairs = [(a, b) for a in comp for b in comp
                     if (a, b) in witness]
            wrel, wline, wnote = witness[pairs[0]] if pairs else ("", 0, "")
            cyc = "->".join(_short(c) for c in comp)
            detail = "; ".join(
                f"{_short(a)}->{_short(b)} at "
                f"{witness[(a, b)][0]}:{witness[(a, b)][1]} "
                f"({witness[(a, b)][2]})" for a, b in pairs[:6])
            out.append(self.finding(
                wrel, wline, f"cycle:{cyc}",
                f"lock-order cycle between {cyc}: {detail}"))
        return out


@register
class BlockingWhileLocked(Rule):
    name = "blocking-while-locked"
    title = "no blocking ops while holding a module-level lock"

    def prepare(self, ctx):
        _model_for(ctx)

    def run(self, ctx):
        model = _model_for(ctx)
        out = []
        seen: dict[str, int] = {}
        for rel, line, qn, cname, lock in sorted(model.blocking):
            base = f"{cname.rsplit('.', 1)[-1]}@{qn}"
            k = seen.get(base, 0)
            seen[base] = k + 1
            ident = f"blocking:{base}" + (f"#{k}" if k else "")
            out.append(self.finding(
                rel, line, ident,
                f"blocking call {cname}() while holding module-level "
                f"lock {_short(lock)} — serializes every thread behind "
                "one slow operation"))
        return out


def _short(ident: str) -> str:
    rel, name = ident.split("::", 1)
    mod = rel.rsplit("/", 1)[-1][:-3]
    return f"{mod}.{name}"
