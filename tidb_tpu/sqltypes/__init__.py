"""SQL type system (reference: types/ — FieldType, Datum, MyDecimal, Time).

Design difference from the reference: values are stored *columnar-first*.
The per-value "Datum" of the reference becomes plain Python values at the
edges (parser literals, row codec, protocol) and numpy arrays inside the
engine. Physical device representations are chosen for TPU friendliness:

- integers            -> int64   (unsigned carried in int64, flag-checked)
- DECIMAL(p<=18, s)   -> scaled int64 ("scale" in FieldType); exact sums on
                         device use int64 accumulators (x64 enabled)
- FLOAT/DOUBLE        -> float32/float64
- DATE                -> int32 days since 1970-01-01
- DATETIME/TIMESTAMP  -> int64 microseconds since epoch (naive / UTC)
- TIME (duration)     -> int64 microseconds
- CHAR/VARCHAR/BLOB   -> host: numpy object array of bytes; device:
                         dictionary codes (int32) or padded u8 matrices
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field

# ---------------------------------------------------------------------------
# MySQL protocol type codes (reference: parser/mysql/type.go)
# ---------------------------------------------------------------------------
TYPE_DECIMAL = 0x00
TYPE_TINY = 0x01
TYPE_SHORT = 0x02
TYPE_LONG = 0x03
TYPE_FLOAT = 0x04
TYPE_DOUBLE = 0x05
TYPE_NULL = 0x06
TYPE_TIMESTAMP = 0x07
TYPE_LONGLONG = 0x08
TYPE_INT24 = 0x09
TYPE_DATE = 0x0A
TYPE_DURATION = 0x0B
TYPE_DATETIME = 0x0C
TYPE_YEAR = 0x0D
TYPE_NEWDATE = 0x0E
TYPE_VARCHAR = 0x0F
TYPE_BIT = 0x10
TYPE_JSON = 0xF5
TYPE_NEWDECIMAL = 0xF6
TYPE_ENUM = 0xF7
TYPE_SET = 0xF8
TYPE_TINY_BLOB = 0xF9
TYPE_MEDIUM_BLOB = 0xFA
TYPE_LONG_BLOB = 0xFB
TYPE_BLOB = 0xFC
TYPE_VAR_STRING = 0xFD
TYPE_STRING = 0xFE
TYPE_GEOMETRY = 0xFF

INT_TYPES = frozenset({TYPE_TINY, TYPE_SHORT, TYPE_INT24, TYPE_LONG, TYPE_LONGLONG, TYPE_YEAR, TYPE_BIT})
FLOAT_TYPES = frozenset({TYPE_FLOAT, TYPE_DOUBLE})
STRING_TYPES = frozenset({
    TYPE_VARCHAR, TYPE_VAR_STRING, TYPE_STRING, TYPE_BLOB, TYPE_TINY_BLOB,
    TYPE_MEDIUM_BLOB, TYPE_LONG_BLOB, TYPE_ENUM, TYPE_SET, TYPE_JSON,
})
TIME_TYPES = frozenset({TYPE_DATE, TYPE_NEWDATE, TYPE_DATETIME, TYPE_TIMESTAMP})

_TYPE_NAMES = {
    TYPE_TINY: "tinyint", TYPE_SHORT: "smallint", TYPE_INT24: "mediumint",
    TYPE_LONG: "int", TYPE_LONGLONG: "bigint", TYPE_FLOAT: "float",
    TYPE_DOUBLE: "double", TYPE_NEWDECIMAL: "decimal", TYPE_VARCHAR: "varchar",
    TYPE_STRING: "char", TYPE_VAR_STRING: "varchar", TYPE_BLOB: "text",
    TYPE_DATE: "date", TYPE_NEWDATE: "date", TYPE_DATETIME: "datetime",
    TYPE_TIMESTAMP: "timestamp", TYPE_DURATION: "time", TYPE_YEAR: "year",
    TYPE_JSON: "json", TYPE_BIT: "bit", TYPE_NULL: "null",
    TYPE_ENUM: "enum", TYPE_SET: "set",
}

# Column flags (reference: parser/mysql/const.go)
FLAG_NOT_NULL = 1
FLAG_PRI_KEY = 2
FLAG_UNIQUE_KEY = 4
FLAG_MULTIPLE_KEY = 8
FLAG_UNSIGNED = 32
FLAG_BINARY = 128
FLAG_AUTO_INCREMENT = 512

# Integer ranges by type code (signed_min, signed_max, unsigned_max)
INT_RANGES = {
    TYPE_TINY: (-128, 127, 255),
    TYPE_SHORT: (-32768, 32767, 65535),
    TYPE_INT24: (-8388608, 8388607, 16777215),
    TYPE_LONG: (-2147483648, 2147483647, 4294967295),
    TYPE_LONGLONG: (-(2**63), 2**63 - 1, 2**64 - 1),
    TYPE_YEAR: (1901, 2155, 2155),
    TYPE_BIT: (0, 2**63 - 1, 2**64 - 1),
}

UNSPECIFIED_LENGTH = -1
DEFAULT_DIV_PRECISION_INCREMENT = 4  # reference: mysql div_precision_increment
MAX_DECIMAL_SCALE = 30
MAX_DECIMAL_WIDTH = 65


@dataclass
class FieldType:
    """Column type descriptor (reference: parser/types/field_type.go)."""

    tp: int = TYPE_NULL
    flen: int = UNSPECIFIED_LENGTH
    decimal: int = UNSPECIFIED_LENGTH  # scale for DECIMAL / fsp for time types
    flag: int = 0
    charset: str = "utf8mb4"
    collate: str = "utf8mb4_bin"
    elems: tuple = ()  # enum/set elements

    @property
    def is_unsigned(self) -> bool:
        return bool(self.flag & FLAG_UNSIGNED)

    @property
    def not_null(self) -> bool:
        return bool(self.flag & FLAG_NOT_NULL)

    @property
    def scale(self) -> int:
        if self.tp == TYPE_NEWDECIMAL:
            return 0 if self.decimal in (None, UNSPECIFIED_LENGTH) else self.decimal
        return 0

    def type_name(self) -> str:
        return _TYPE_NAMES.get(self.tp, "unknown")

    def sql_string(self) -> str:
        """Render as DDL type string, e.g. ``decimal(15,2)`` (reference: parser/types restore)."""
        name = self.type_name()
        if self.tp == TYPE_NEWDECIMAL:
            p = self.flen if self.flen != UNSPECIFIED_LENGTH else 10
            s = self.decimal if self.decimal != UNSPECIFIED_LENGTH else 0
            name = f"decimal({p},{s})"
        elif self.tp in (TYPE_VARCHAR, TYPE_VAR_STRING) and self.flen != UNSPECIFIED_LENGTH:
            name = f"varchar({self.flen})"
        elif self.tp == TYPE_STRING and self.flen != UNSPECIFIED_LENGTH:
            name = f"char({self.flen})"
        elif self.tp in INT_TYPES and self.flen not in (None, UNSPECIFIED_LENGTH):
            name = f"{name}({self.flen})"
        if self.is_unsigned:
            name += " unsigned"
        return name

    def clone(self) -> "FieldType":
        return FieldType(self.tp, self.flen, self.decimal, self.flag,
                         self.charset, self.collate, self.elems)


def new_int_type(tp=TYPE_LONGLONG, unsigned=False) -> FieldType:
    ft = FieldType(tp=tp)
    if unsigned:
        ft.flag |= FLAG_UNSIGNED
    return ft


def new_decimal_type(precision=10, scale=0) -> FieldType:
    return FieldType(tp=TYPE_NEWDECIMAL, flen=precision, decimal=scale)


def new_string_type(flen=UNSPECIFIED_LENGTH, tp=TYPE_VARCHAR) -> FieldType:
    return FieldType(tp=tp, flen=flen)


def new_double_type() -> FieldType:
    return FieldType(tp=TYPE_DOUBLE)


def new_date_type() -> FieldType:
    return FieldType(tp=TYPE_DATE)


def new_datetime_type(fsp=0) -> FieldType:
    return FieldType(tp=TYPE_DATETIME, decimal=fsp)


# ---------------------------------------------------------------------------
# Scalar value helpers. Internal scalar conventions ("datum" at the edges):
#   int/bool -> int ; DECIMAL -> ("dec", scaled_int, scale) tuple is avoided —
#   decimals are plain Python ints at a known column scale, or Decimal-like
#   strings at the parser edge. DATE -> int days; DATETIME -> int micros.
#   strings -> bytes. NULL -> None.
# ---------------------------------------------------------------------------

_EPOCH = _dt.date(1970, 1, 1)
_EPOCH_DT = _dt.datetime(1970, 1, 1)

POW10 = [10 ** i for i in range(38)]


def date_to_days(y: int, m: int, d: int) -> int:
    return (_dt.date(y, m, d) - _EPOCH).days


def days_to_date(days: int) -> _dt.date:
    return _EPOCH + _dt.timedelta(days=int(days))


def datetime_to_micros(dt: _dt.datetime) -> int:
    delta = dt - _EPOCH_DT
    return (delta.days * 86400 + delta.seconds) * 1_000_000 + delta.microseconds


def micros_to_datetime(us: int) -> _dt.datetime:
    return _EPOCH_DT + _dt.timedelta(microseconds=int(us))


def parse_date_str(s: str) -> int:
    """'1995-03-15' -> days since epoch. Raises ValueError on bad input."""
    parts = s.strip().split("-")
    if len(parts) != 3:
        raise ValueError(f"invalid date literal: {s!r}")
    return date_to_days(int(parts[0]), int(parts[1]), int(parts[2]))


def parse_datetime_str(s: str) -> int:
    """'1995-03-15 10:30:00[.ffffff]' -> micros since epoch."""
    s = s.strip()
    if " " in s or "T" in s:
        sep = " " if " " in s else "T"
        d, t = s.split(sep, 1)
    else:
        d, t = s, "00:00:00"
    y, m, dd = (int(x) for x in d.split("-"))
    frac = 0
    if "." in t:
        t, fs = t.split(".", 1)
        frac = int((fs + "000000")[:6])
    hh, mm, ss = (int(x) for x in (t.split(":") + ["0", "0"])[:3])
    return datetime_to_micros(_dt.datetime(y, m, dd, hh, mm, ss, frac))


def dec_round_div(num: int, den: int) -> int:
    """Round-half-away-from-zero integer division (MySQL decimal rounding,
    reference: types/mydecimal.go Round)."""
    if den == 0:
        raise ZeroDivisionError("decimal division by zero")
    neg = (num < 0) != (den < 0)
    num, den = abs(num), abs(den)
    q, r = divmod(num, den)
    if r * 2 >= den:
        q += 1
    return -q if neg else q


def dec_rescale(v: int, from_scale: int, to_scale: int) -> int:
    """Change scale of a scaled-int decimal with MySQL half-up rounding."""
    if to_scale == from_scale:
        return v
    if to_scale > from_scale:
        return v * POW10[to_scale - from_scale]
    return dec_round_div(v, POW10[from_scale - to_scale])


def str_to_decimal(s: str, scale: int) -> int:
    """Parse a decimal literal to a scaled int at `scale` (half-up rounding)."""
    s = s.strip()
    neg = s.startswith("-")
    if s and s[0] in "+-":
        s = s[1:]
    if "e" in s or "E" in s:
        # scientific notation: go through float-free expansion
        mant, exp = s.lower().split("e")
        exp = int(exp)
        if "." in mant:
            ip, fp = mant.split(".", 1)
        else:
            ip, fp = mant, ""
        digits = (ip + fp) or "0"
        point = len(ip) + exp
        if point >= len(digits):
            digits += "0" * (point - len(digits))
            ip, fp = digits, ""
        elif point <= 0:
            ip, fp = "0", "0" * (-point) + digits
        else:
            ip, fp = digits[:point], digits[point:]
    elif "." in s:
        ip, fp = s.split(".", 1)
    else:
        ip, fp = s, ""
    ip = ip or "0"
    fp = fp or ""
    v = int(ip) * POW10[scale] if scale < len(POW10) else int(ip) * 10 ** scale
    if fp:
        if len(fp) <= scale:
            v += int(fp) * POW10[scale - len(fp)]
        else:
            keep, rest = fp[:scale], fp[scale:]
            v += int(keep) if keep else 0
            if rest and int(rest[0]) >= 5:
                v += 1
    return -v if neg else v


def decimal_to_str(v: int, scale: int) -> str:
    """Render a scaled-int decimal as MySQL does (fixed scale, no exponent)."""
    if scale <= 0:
        return str(v)
    neg = v < 0
    v = abs(v)
    ip, fp = divmod(v, POW10[scale])
    s = f"{ip}.{fp:0{scale}d}"
    return "-" + s if neg else s


def format_value(val, ft: FieldType):
    """Render an internal value as the MySQL text-protocol string (or None)."""
    if val is None:
        return None
    tp = ft.tp
    if tp == TYPE_NEWDECIMAL:
        return decimal_to_str(int(val), ft.scale)
    if tp in INT_TYPES:
        if ft.is_unsigned and val < 0:
            return str(int(val) + 2**64)
        return str(int(val))
    if tp in FLOAT_TYPES:
        f = float(val)
        if f == int(f) and abs(f) < 1e15:
            return str(int(f))
        return repr(f)
    if tp in (TYPE_DATE, TYPE_NEWDATE):
        return days_to_date(val).isoformat()
    if tp in (TYPE_DATETIME, TYPE_TIMESTAMP):
        dt = micros_to_datetime(val)
        fsp = ft.decimal if ft.decimal not in (None, UNSPECIFIED_LENGTH) else 0
        base = dt.strftime("%Y-%m-%d %H:%M:%S")
        if fsp > 0:
            base += "." + f"{dt.microsecond:06d}"[:fsp]
        return base
    if tp == TYPE_DURATION:
        us = int(val)
        neg = us < 0
        us = abs(us)
        ss, us_ = divmod(us, 1_000_000)
        hh, rem = divmod(ss, 3600)
        mm, ss = divmod(rem, 60)
        s = f"{'-' if neg else ''}{hh:02d}:{mm:02d}:{ss:02d}"
        fsp = ft.decimal if ft.decimal not in (None, UNSPECIFIED_LENGTH) else 0
        if fsp > 0:
            s += "." + f"{us_:06d}"[:fsp]
        return s
    if isinstance(val, bytes):
        return val.decode("utf-8", "replace")
    return str(val)
