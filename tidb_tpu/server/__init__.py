"""MySQL wire protocol server (reference: server/ — protocol at conn.go,
packet framing at packetio.go, resultset encode at conn.go:2096)."""

from .server import MySQLServer

__all__ = ["MySQLServer"]
