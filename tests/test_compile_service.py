"""Compile service (executor/compile_service.py): async background
compilation with host-first serving, prewarmed bucket ladders, the
persistent signature index, classified compile-failure chaos with
breaker recovery, gauge surfacing, and the jax.jit confinement lint.

The tier-1 acceptance pins (ISSUE 8):
  * with ``tidb_compile_async=ON`` a cold-cache query returns a correct
    HOST-served result without blocking on XLA, and a repeat of the same
    bucket shape executes on device with ZERO new traces;
  * injected ``compile-fail`` chaos yields exact-or-classified results
    only, and the compile breaker recovers via half-open;
  * no compile job leaks (``verify_drained``).
"""

import ast
import json
import os
import time
import urllib.request

import pytest

from tidb_tpu.executor import compile_service
from tidb_tpu.executor.device_exec import pipe_cache_stats
from tidb_tpu.testkit import TestKit
from tidb_tpu.utils import failpoint


@pytest.fixture(scope="module")
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table cs (id int primary key, g int, v int, "
                 "w int)")
    rows = ",".join(f"({i},{i % 7},{(i * 37) % 101},{(i * 13) % 89})"
                    for i in range(300))
    tk.must_exec(f"insert into cs values {rows}")
    tk.must_exec("set tidb_executor_engine = 'tpu'")
    yield tk
    failpoint.disable_all()
    tk.must_exec("set tidb_executor_engine = 'auto'")
    tk.must_exec("set tidb_compile_async = 'OFF'")


def _host_rows(tk, q):
    tk.must_exec("set tidb_executor_engine = 'host'")
    try:
        return tk.must_query(q).rows
    finally:
        tk.must_exec("set tidb_executor_engine = 'tpu'")


# -- unit helpers -------------------------------------------------------------

class TestHelpers:
    def test_next_buckets_geometric(self):
        # the ladder climbs the ops/device bucket_rows curve (powers of
        # sqrt(2) at per_double=2), strictly increasing
        out = compile_service.next_buckets(363, 3)
        assert out == sorted(set(out)) and len(out) == 3
        assert out[0] > 363
        from tidb_tpu.ops.device import bucket_rows
        for b in out:
            assert bucket_rows(b) == b

    def test_spec_roundtrip_preserves_weak_scalars(self):
        import jax
        import numpy as np
        args = ({"x": (np.arange(8), np.zeros(8, bool))}, np.int64(5), 3)
        spec = compile_service._spec_of(args)
        env, n_live, lit = spec
        assert isinstance(env["x"][0], jax.ShapeDtypeStruct)
        assert n_live.shape == () and n_live.dtype == np.int64
        assert lit == 0  # python scalar stays a weak-typed literal
        zeros = compile_service._zeros_of(spec)
        assert zeros[0]["x"][0].shape == (8,)
        assert zeros[2] == 0

    def test_base_bucket_and_scale(self):
        import numpy as np
        spec = compile_service._spec_of(
            ({"x": (np.zeros(16), np.zeros(16, bool))}, np.int64(3)))
        assert compile_service._base_bucket(spec) == 16
        scaled = compile_service._scale_spec(spec, 16, 23)
        assert compile_service._base_bucket(scaled) == 23
        # disagreeing leading dims: no single fragment bucket, no ladder
        spec2 = compile_service._spec_of(
            ({"x": (np.zeros(16), np.zeros(16, bool)),
              "y": (np.zeros(8), np.zeros(8, bool))},))
        assert compile_service._base_bucket(spec2) is None


# -- async compile, host-first serving (tier-1 acceptance) --------------------

class TestAsyncFlip:
    def test_cold_query_host_served_then_flips_to_device(self, tk,
                                                           monkeypatch):
        # a populated persistent index would (correctly) compile this
        # signature INLINE as a warm deserialize — disable it so the
        # test pins the cold-miss async path deterministically
        monkeypatch.setenv("TIDB_TPU_COMPILE_INDEX", "off")
        q = ("select g, sum(v), min(w) from cs where v > 5 "
             "group by g order by g")
        golden = _host_rows(tk, q)
        tk.must_exec("set tidb_compile_async = 'ON'")
        try:
            st0 = pipe_cache_stats(thread_local=True)
            snap0 = compile_service.snapshot()
            rows = tk.must_query(q).rows
            st1 = pipe_cache_stats(thread_local=True)
            # correct result, and the query path paid ZERO XLA compiles:
            # the executable is building in the background while the
            # host engine served this execution
            assert rows == golden
            assert st1["traces"] - st0["traces"] == 0
            assert st1["compile_s"] - st0["compile_s"] == 0.0
            assert st1["mode_async_pending"] - st0["mode_async_pending"] >= 1
            assert compile_service.snapshot()["bg_submitted"] \
                > snap0["bg_submitted"]

            assert compile_service.wait_idle(60.0), "bg compile stuck"
            snap1 = compile_service.snapshot()
            assert snap1["bg_completed"] > snap0["bg_completed"]
            assert snap1["compile_bg_seconds"] > 0

            # the flip: same bucket shape now executes ON DEVICE with
            # zero new traces (the background warm absorbed the compile)
            st0 = pipe_cache_stats(thread_local=True)
            rows2 = tk.must_query(q).rows
            st1 = pipe_cache_stats(thread_local=True)
            assert rows2 == golden
            assert st1["traces"] - st0["traces"] == 0
            assert st1["hits"] - st0["hits"] >= 1
            assert st1["mode_async_pending"] == st0["mode_async_pending"]
        finally:
            tk.must_exec("set tidb_compile_async = 'OFF'")

    def test_pending_compile_serves_host_without_resubmit(self, tk,
                                                            monkeypatch):
        monkeypatch.setenv("TIDB_TPU_COMPILE_INDEX", "off")
        q = ("select g, max(v), count(w) from cs where w > 3 "
             "group by g order by g")
        golden = _host_rows(tk, q)
        tk.must_exec("set tidb_compile_async = 'ON'")
        try:
            with failpoint.enabled("device-compile",
                                   "1*compile-slow(0.4)"):
                snap0 = compile_service.snapshot()
                rows = tk.must_query(q).rows          # submits, host serves
                assert rows == golden
                rows2 = tk.must_query(q).rows         # still in flight
                assert rows2 == golden
                snap1 = compile_service.snapshot()
                # ONE job submitted; the second execution counted as a
                # pending-fragment degrade, not a duplicate submit
                assert snap1["bg_submitted"] == snap0["bg_submitted"] + 1
                assert snap1["compile_pending_fragments"] \
                    >= snap0["compile_pending_fragments"] + 2
            assert compile_service.wait_idle(60.0)
            st0 = pipe_cache_stats(thread_local=True)
            assert tk.must_query(q).rows == golden
            st1 = pipe_cache_stats(thread_local=True)
            assert st1["traces"] - st0["traces"] == 0
        finally:
            tk.must_exec("set tidb_compile_async = 'OFF'")


# -- prewarm ladder -----------------------------------------------------------

class TestPrewarmLadder:
    def test_admin_compile_prewarms_next_buckets(self, tk):
        # drop recipes accumulated by earlier suites: ADMIN COMPILE
        # prewarms EVERY hot recipe, and this test times its own
        compile_service.reset_for_tests()
        q = ("select g, sum(w), count(*) from cs where v < 90 "
             "group by g order by g")
        tk.must_query(q)  # registers the recipe at the 300-row bucket
        rep = tk.must_query("admin compile").rows
        assert len(rep) == 1 and int(rep[0][0]) >= 1  # submitted
        # INSERT across the bucket boundary, inside the warmed ladder
        # (300 rows sit in bucket 363; 600 lands in 725 — two rungs up)
        more = ",".join(
            f"({i},{i % 7},{(i * 37) % 101},{(i * 13) % 89})"
            for i in range(300, 600))
        tk.must_exec(f"insert into cs values {more}")
        golden = _host_rows(tk, q)
        st0 = pipe_cache_stats(thread_local=True)
        rows = tk.must_query(q).rows
        st1 = pipe_cache_stats(thread_local=True)
        assert rows == golden
        # the prewarmed rung serves the grown shape: ZERO sync compiles
        assert st1["traces"] - st0["traces"] == 0
        assert st1["compile_s"] - st0["compile_s"] == 0.0
        # restore the module fixture's row count for later tests
        tk.must_exec("delete from cs where id >= 300")

    def test_prewarm_reports_counts(self, tk):
        rep = compile_service.prewarm(ctx=tk.session, ladder_up=1,
                                      max_recipes=4, wait=True,
                                      timeout_s=60.0)
        assert rep["submitted"] >= 0
        assert compile_service.verify_drained()["ok"]


# -- classified compile failures + breaker ------------------------------------

class TestCompileFailChaos:
    def test_sync_compile_fail_degrades_exact(self, tk):
        q = ("select g, min(v), max(w) from cs where v > 50 "
             "group by g order by g")
        golden = _host_rows(tk, q)
        agg_br = tk.domain._device_breakers.get("agg")
        agg_fail0 = agg_br.snapshot()["failures"] if agg_br else 0
        with failpoint.enabled("device-compile", "compile-fail"):
            rows = tk.must_query(q).rows
        assert rows == golden
        br = tk.domain._device_breakers["compile"]
        assert br.snapshot()["failures"] >= 1
        # the COMPILE breaker absorbed it — the agg fragment breaker
        # must not be charged for a compile-path failure
        if agg_br is not None:
            assert agg_br.snapshot()["failures"] == agg_fail0

    def test_bg_transient_fail_absorbed_by_retry(self, tk, monkeypatch):
        monkeypatch.setenv("TIDB_TPU_COMPILE_INDEX", "off")
        q = ("select g, sum(v + w) from cs where w > 42 "
             "group by g order by g")
        golden = _host_rows(tk, q)
        tk.must_exec("set tidb_compile_async = 'ON'")
        try:
            snap0 = compile_service.snapshot()
            with failpoint.enabled("device-compile", "1*compile-fail"):
                assert tk.must_query(q).rows == golden
                assert compile_service.wait_idle(60.0)
            snap1 = compile_service.snapshot()
            # the first build attempt failed injected; the compileRetry
            # curve absorbed it — the job still LANDED
            assert snap1["bg_completed"] == snap0["bg_completed"] + 1
            assert snap1["bg_failed"] == snap0["bg_failed"]
            st0 = pipe_cache_stats(thread_local=True)
            assert tk.must_query(q).rows == golden
            assert pipe_cache_stats(
                thread_local=True)["traces"] == st0["traces"]
        finally:
            tk.must_exec("set tidb_compile_async = 'OFF'")

    def test_breaker_opens_and_recovers_half_open(self, tk):
        tk.must_exec("set global tidb_device_circuit_threshold = 2")
        # cooldown long enough that the open-state degrade below cannot
        # race into a premature HALF_OPEN probe
        tk.must_exec("set global tidb_device_circuit_cooldown = 0.5")
        try:
            qs = [(f"select g, count(*) from cs where v > {k} "
                   "group by g order by g") for k in (71, 72, 73, 74)]
            goldens = [_host_rows(tk, q) for q in qs]
            with failpoint.enabled("device-compile", "compile-fail"):
                for q, g in zip(qs[:2], goldens[:2]):
                    assert tk.must_query(q).rows == g  # host degrade
            br = tk.domain._device_breakers["compile"]
            assert br.snapshot()["state"] == "open"
            # open breaker: a cold obtain degrades WITHOUT queueing
            deg0 = compile_service.snapshot()["breaker_degrades"]
            assert tk.must_query(qs[2]).rows == goldens[2]
            assert compile_service.snapshot()["breaker_degrades"] \
                == deg0 + 1
            # failpoint cleared + cooldown elapsed: the half-open probe
            # compiles for real and CLOSES the breaker
            time.sleep(0.55)
            assert tk.must_query(qs[3]).rows == goldens[3]
            assert br.snapshot()["state"] == "closed"
        finally:
            tk.must_exec("set global tidb_device_circuit_threshold = 5")
            tk.must_exec("set global tidb_device_circuit_cooldown = 30")

    def test_no_leaked_compile_jobs(self, tk):
        assert compile_service.wait_idle(30.0)
        drained = compile_service.verify_drained()
        assert drained["ok"], drained


# -- persistent signature index ----------------------------------------------

class TestPersistIndex:
    def test_record_then_lookup(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TIDB_TPU_COMPILE_INDEX", str(tmp_path))
        key = ("sig-a", 64, None, ("sum",))
        assert not compile_service._persist_lookup(key)
        compile_service._persist_record(key, "agg", "sig-a", "sync")
        assert compile_service._persist_lookup(key)
        assert not compile_service._persist_lookup(("sig-b", 64))
        # the index entry is valid JSON with the recorded metadata
        fname = compile_service._persist_hash(key) + ".json"
        blob = json.loads((tmp_path / fname).read_text())
        assert blob["shape"] == "agg" and blob["origin"] == "sync"

    def test_off_disables(self, monkeypatch):
        monkeypatch.setenv("TIDB_TPU_COMPILE_INDEX", "off")
        assert compile_service._persist_dir() is None
        compile_service._persist_record(("k",), "agg", "", "sync")
        assert not compile_service._persist_lookup(("k",))

    def test_backend_identity_in_hash(self):
        # same signature on a different backend/mesh is a DIFFERENT
        # executable: the hash must not collide across device counts
        h1 = compile_service._persist_hash(("sig", 64))
        assert h1 == compile_service._persist_hash(("sig", 64))
        assert h1 != compile_service._persist_hash(("sig", 128))


# -- gauges / annotations -----------------------------------------------------

class TestGaugesSurfaced:
    def test_explain_observe_status_and_metrics(self, tk, monkeypatch):
        monkeypatch.setenv("TIDB_TPU_COMPILE_INDEX", "off")
        q = ("select g, sum(v), max(v + w) from cs where w < 80 "
             "group by g order by g")
        tk.must_exec("set tidb_compile_async = 'ON'")
        try:
            tk.must_query(q)                       # async submit
            assert compile_service.wait_idle(60.0)
            tk.must_query(q)                       # device flip
        finally:
            tk.must_exec("set tidb_compile_async = 'OFF'")

        # EXPLAIN ANALYZE annotates the service gauges + compile_mode
        rows = tk.must_query(f"explain analyze {q}").rows
        blob = "\n".join(" ".join(str(c) for c in r) for r in rows)
        assert "compile_queue_depth" in blob
        assert "compile_mode" in blob

        # observe gauges (the sink obtain() registered for this Domain)
        g = tk.domain.observe.gauge_snapshot()
        assert "compile_queue_depth" in g
        assert g.get("compile_bg_seconds", 0) > 0

        # HTTP /status JSON + /metrics exposition
        from tidb_tpu.server.http_status import StatusServer
        srv = StatusServer(tk.domain, port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status = json.load(urllib.request.urlopen(f"{base}/status"))
            cs = status["device_compiler"]
            assert cs["bg_completed"] >= 1
            assert cs["compile_bg_seconds"] > 0
            assert "compile" in status["device_breakers"]
            metrics = urllib.request.urlopen(f"{base}/metrics").read()
            assert b"compile_queue_depth" in metrics
            assert b"compile_bg_seconds" in metrics
            assert b"compile_pending_fragments" in metrics
        finally:
            srv.shutdown()

    def test_bg_attribution_survives_supervisor_deadline(self, tk,
                                                         monkeypatch):
        """With tidb_compile_timeout > 0 the background build runs on a
        REUSED supervisor worker thread — its compile charges must still
        route to the bg_* mirror (scoped mark in _do_compile), and the
        worker must not stay marked when it serves query fragments
        next."""
        monkeypatch.setenv("TIDB_TPU_COMPILE_INDEX", "off")
        tk.must_exec("set tidb_compile_async = 'ON'")
        tk.must_exec("set global tidb_compile_timeout = 30")
        try:
            q = ("select g, sum(w + 2) from cs where v < 77 "
                 "group by g order by g")
            s0 = pipe_cache_stats()
            tk.must_query(q)
            assert compile_service.wait_idle(60.0)
            s1 = pipe_cache_stats()
            assert s1["bg_compile_s"] > s0["bg_compile_s"]
            assert s1["compile_s"] == s0["compile_s"]
        finally:
            tk.must_exec("set global tidb_compile_timeout = 0")
            tk.must_exec("set tidb_compile_async = 'OFF'")
        # the same supervisor worker now serves a supervised QUERY
        # dispatch: its sync compile must hit the sync meter
        tk.must_exec("set tidb_device_call_timeout = 5")
        try:
            q2 = ("select g, min(w + 3) from cs where v < 76 "
                  "group by g order by g")
            s0 = pipe_cache_stats()
            tk.must_query(q2)
            s1 = pipe_cache_stats()
            assert s1["compile_s"] > s0["compile_s"]
        finally:
            tk.must_exec("set tidb_device_call_timeout = 0")

    def test_bg_compile_charged_to_bg_mirror(self, tk):
        # process totals split sync vs background compile seconds: the
        # flip test above compiled in the BACKGROUND, so the bg mirror
        # is nonzero and per-query compile_s stayed the sync cost
        st = pipe_cache_stats()
        assert st["bg_compile_s"] > 0
        assert st["bg_traces"] >= 1


# -- lint: jax.jit of query pipelines is confined -----------------------------

class TestJitConfinementLint:
    def test_direct_jit_confined_to_compile_layer(self):
        """Registry rule (tidb_tpu/lint rules/confinement.py): raw
        jax.jit (and AOT .lower()/.compile() chains) outside the compile
        layer bypass async compilation, the compile breaker and trace
        accounting."""
        from tidb_tpu.lint import run_rule
        findings = run_rule("jit-confinement")
        assert not findings, [f.to_json() for f in findings]
