"""The coordination layer — single-host PD/etcd analog (reference:
pd TSO `tidb-server/main.go:74`, owner election `owner/manager.go:48`,
infosync registry, PD service safepoints)."""

import threading

import pytest

from tidb_tpu.coordinator import Coordinator
from tidb_tpu.testkit import TestKit


def test_tso_monotonic_across_threads():
    c = Coordinator(tso_batch=8)  # tiny batch: force many range renewals
    out = []
    mu = threading.Lock()

    def grab():
        local = [c.tso() for _ in range(500)]
        with mu:
            out.extend(local)

    ts = [threading.Thread(target=grab) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(set(out)) == len(out), "duplicate timestamps"
    # each thread's local sequence was increasing and globally unique
    assert max(out) > min(out)


def test_tso_range_batches_do_not_overlap():
    c = Coordinator()
    lo1, hi1 = c.tso_range(100)
    lo2, hi2 = c.tso_range(100)
    assert hi1 <= lo2 and hi1 - lo1 == 100 and hi2 - lo2 == 100
    assert c.tso() >= hi2


def test_election_campaign_resign_ttl():
    c = Coordinator()
    assert c.campaign("ddl", "a", ttl_s=60)
    assert not c.campaign("ddl", "b", ttl_s=60)  # live foreign lease
    assert c.leader("ddl") == "a"
    assert c.campaign("ddl", "a", ttl_s=0.01)    # holder renews (shorter)
    import time
    time.sleep(0.03)
    assert c.leader("ddl") is None               # lease lapsed
    assert c.campaign("ddl", "b")                # now up for grabs
    assert c.resign("ddl", "b")
    assert c.leader("ddl") is None


def test_leader_watch_events():
    c = Coordinator()
    events = []
    cancel = c.watch("leader/ddl", lambda k, v: events.append(v))
    c.campaign("ddl", "a")
    c.resign("ddl", "a")
    assert events == ["a", None]
    cancel()
    c.campaign("ddl", "b")
    assert events == ["a", None]  # cancelled watcher sees nothing


def test_registry_heartbeat_and_expiry():
    import time
    c = Coordinator()
    c.register_server("s1", {"port": 4000}, ttl_s=0.05)
    assert "s1" in c.servers()
    time.sleep(0.03)
    assert c.heartbeat("s1")
    time.sleep(0.03)
    assert "s1" in c.servers()  # heartbeat extended the lease
    time.sleep(0.06)
    assert "s1" not in c.servers()
    assert not c.heartbeat("unknown")


def test_safepoints_min_and_clear():
    c = Coordinator()
    c.set_safepoint("gc", 100)
    c.set_safepoint("br", 40)
    assert c.global_safepoint() == 40
    assert c.min_pin_excluding("gc") == 40
    c.clear_safepoint("br")
    assert c.global_safepoint() == 100
    # safepoints never regress
    c.set_safepoint("gc", 50)
    assert c.safepoints()["gc"] == 100


class TestEngineIntegration:
    def test_domain_registers_server(self):
        tk = TestKit()
        assert "tidb-0" in tk.session.domain.coordinator.servers()

    def test_br_pin_blocks_gc_advance(self, tmp_path):
        """A BR service safepoint must cap the GC safepoint while a backup
        snapshot is live (reference: br/pkg/task/backup.go PD service
        safepoint)."""
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table gpin (a bigint)")
        tk.must_exec("insert into gpin values (1)")
        dom = tk.session.domain
        coord = dom.coordinator
        coord.set_safepoint("br", 7)  # simulate an in-flight backup pin
        try:
            res = dom.gc_worker.run_once()
            assert res["safe_point"] <= 7
        finally:
            coord.clear_safepoint("br")

    def test_backup_pins_and_releases(self, tmp_path):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table bk (a bigint)")
        tk.must_exec("insert into bk values (1), (2)")
        from tidb_tpu.br import backup_database
        meta = backup_database(tk.session, "test", str(tmp_path / "b"))
        assert meta["tables"]
        # the pin released at the end of the backup
        assert "br" not in tk.session.domain.coordinator.safepoints()
