"""Fleet observability plane (ISSUE 18): the shared fragment
performance store math + merge (fabric/perf.py, coord PERF section),
the DIAG statement, the cluster memtables with their ``peer-lost``
contract, the information_schema.tidb_fragment_perf surface, and trace
propagation under process chaos (a killed + a wedged worker must show
up as tagged rows and trace marks, never as a hang)."""

import json
import os
import pathlib
import signal
import socket
import sys
import time

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tidb_tpu.fabric import perf  # noqa: E402
from tidb_tpu.fabric import state as fabric_state  # noqa: E402
from tidb_tpu.fabric.coord import (PERF_BASE_S,  # noqa: E402
                                   PERF_SKETCH_N, Coordinator)
from tidb_tpu.testkit import TestKit  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_perf():
    perf.reset_for_tests()
    yield
    perf.reset_for_tests()


class TestFragmentPerfMath:
    """The store's pure math: sketch buckets, percentiles, dispatch
    keys, the describe() line — no coordinator involved."""

    def test_sketch_bucket_edges(self):
        assert perf.sketch_bucket(0.0) == 0
        assert perf.sketch_bucket(PERF_BASE_S) == 0
        assert perf.sketch_bucket(PERF_BASE_S * 1.01) == 1
        assert perf.sketch_bucket(1e9) == PERF_SKETCH_N - 1

    def test_percentile_upper_bounds(self):
        sketch = [0] * PERF_SKETCH_N
        sketch[2] = 50
        sketch[5] = 50
        assert perf.percentile(sketch, 100, 0.50) == PERF_BASE_S * 4
        assert perf.percentile(sketch, 100, 0.99) == PERF_BASE_S * 32
        assert perf.percentile(sketch, 0, 0.5) is None

    def test_dispatch_key_forms(self):
        # batch key with an int row-bucket tail: structural prefix
        # hashes, the tail IS the bucket
        sig, bucket = perf.dispatch_key(("agg", ("sum",), 128))
        assert bucket == 128 and sig == perf.sig_hash(("agg", ("sum",)))
        # no int tail: whole key hashes, bucket 0
        assert perf.dispatch_key(("agg", "x")) == (
            perf.sig_hash(("agg", "x")), 0)
        # keyless dispatch degrades to the fragment shape
        assert perf.dispatch_key(None, shape="join") == (
            perf.sig_hash(("shape", "join")), 0)

    def test_note_accumulates_and_describe_renders(self):
        for d in (0.01, 0.02, 0.04):
            perf.note("sigA", 64, "device", "dispatch", d)
        perf.note("sigA", 64, "host", "dispatch", 0.5)
        rows = perf.local_rows()
        dev = [r for r in rows if r["backend"] == 0]
        assert len(dev) == 1 and dev[0]["count"] == 3
        assert abs(dev[0]["sum_s"] - 0.07) < 1e-9
        assert dev[0]["max_s"] == 0.04
        line = perf.describe(perf.lookup("sigA", 64))
        assert line.startswith("n=4")
        assert "device p50/p99" in line and "host p50/p99" in line
        # compile/admission samples don't count into the dispatch line
        perf.note("sigA", 64, "device", "compile", 9.0)
        assert perf.describe(perf.lookup("sigA", 64)).startswith("n=4")
        assert perf.describe([]) == ""

    def test_flush_without_fleet_keeps_local_mirror(self):
        perf.note("sigB", 0, "device", "dispatch", 0.01)
        assert perf.flush() == 0  # no coordinator: local-only
        st = perf.stats()
        assert st["perf_notes"] == 1 and st["perf_flushes"] == 1
        assert st["perf_buffered_rows"] == 0
        assert st["perf_local_rows"] == 1
        # the read surface still answers from the mirror
        assert perf.fleet_rows()[0]["count"] == 1

    def test_unknown_backend_or_kind_is_dropped(self):
        perf.note("sigC", 0, "gpu", "dispatch", 0.1)
        perf.note("sigC", 0, "device", "teleport", 0.1)
        assert perf.local_rows() == []


class TestFragmentPerfFleet:
    """Merge semantics against a real segment: two workers' samples
    aggregate; the fleet row strictly exceeds any single worker's."""

    def test_two_slot_merge_exceeds_any_local(self, tmp_path):
        coord = Coordinator.create(str(tmp_path / "coord.json"), nslots=4)
        coord.claim_slot(0)
        fabric_state.activate(coord, 0, lease_hbm=False)
        try:
            for _ in range(3):
                perf.note("sigF", 32, "device", "dispatch", 0.02)
            assert perf.flush() == 1  # one row merged
            # the other worker's share arrives through the same op the
            # segment serves every peer with
            key = (perf.sig_hash("sigF"), 32, 0, perf.KINDS.index(
                "dispatch"))
            sk = [0] * PERF_SKETCH_N
            sk[perf.sketch_bucket(0.08)] = 2
            assert coord.perf_merge([key + (2, 0.16, 0.08, sk)]) == 1
            rows = perf.fleet_rows()
            assert len(rows) == 1
            r = rows[0]
            assert r["count"] == 5                 # 3 local + 2 remote
            assert abs(r["sum_s"] - 0.22) < 1e-6
            assert abs(r["max_s"] - 0.08) < 1e-9
            local = perf.local_rows()[0]["count"]
            assert r["count"] > local == 3
            assert perf.stats()["perf_merged"] >= 1
        finally:
            fabric_state.deactivate()
            coord.unlink()

    def test_fragment_perf_memtable_rows(self, tmp_path):
        coord = Coordinator.create(str(tmp_path / "coord.json"), nslots=4)
        coord.claim_slot(0)
        fabric_state.activate(coord, 0, lease_hbm=False)
        try:
            tk = TestKit()
            perf.note("sigM", 16, "device", "dispatch", 0.01)
            perf.note("sigM", 16, "device", "dispatch", 0.03)
            key = (perf.sig_hash("sigM"), 16, 0, perf.KINDS.index(
                "dispatch"))
            sk = [0] * PERF_SKETCH_N
            sk[perf.sketch_bucket(0.05)] = 4
            coord.perf_merge([key + (4, 0.2, 0.05, sk)])
            r = tk.must_query(
                "select sig_hash, backend, kind, count, local_count, "
                "p99_s from information_schema.tidb_fragment_perf")
            assert len(r.rows) == 1
            sig_hex, backend, kind, count, local, p99 = r.rows[0]
            assert sig_hex == f"{perf.sig_hash('sigM'):016x}"
            assert (backend, kind) == ("device", "dispatch")
            assert int(count) == 6 and int(local) == 2
            assert int(count) > int(local)  # fleet > this worker alone
            assert float(p99) > 0.0
        finally:
            fabric_state.deactivate()
            coord.unlink()


class TestDiagStatement:
    """DIAG over a plain session: every kind answers one JSON cell."""

    def _diag(self, tk, stmt):
        r = tk.must_query(stmt)
        assert r.result.names == ["diag"]
        return json.loads(r.rows[0][0])

    def test_metrics_kind(self):
        tk = TestKit()
        out = self._diag(tk, "DIAG metrics")
        assert out["kind"] == "metrics"
        assert "counters" in out
        assert "ring_dropped" in out["tracing"]

    def test_table_kinds_mirror_memtables(self):
        tk = TestKit()
        tk.must_exec("set tidb_trace_sampling_rate = 1")
        tk.must_query("select 1")
        tk.must_exec("set tidb_trace_sampling_rate = 0")
        out = self._diag(tk, "DIAG statements")
        assert out["kind"] == "statements"
        assert out["rows"], "no statement history after a query"
        # cols and rows stay aligned with the base memtable schema
        assert all(len(r) == len(out["cols"]) for r in out["rows"])
        traces = self._diag(tk, "DIAG traces")
        assert traces["kind"] == "traces" and traces["rows"]

    def test_perf_kind_and_status_kind(self):
        tk = TestKit()
        perf.note("sigD", 8, "host", "dispatch", 0.2)
        out = self._diag(tk, "DIAG perf")
        assert out["kind"] == "perf"
        assert out["local"][0]["count"] == 1
        assert out["stats"]["perf_notes"] == 1
        st = self._diag(tk, "DIAG status")
        assert st["kind"] == "status" and "fabric" in st

    def test_unknown_kind_is_a_clean_error(self):
        from tidb_tpu.errors import TiDBError
        tk = TestKit()
        with pytest.raises(TiDBError):
            tk.must_query("DIAG warp")

    def test_non_diag_text_passes_through(self):
        tk = TestKit()
        # a table named diagnostics must not trip the intercept
        tk.must_exec("use test")
        tk.must_exec("create table diagnostics (a int primary key)")
        tk.must_exec("insert into diagnostics values (7)")
        assert tk.must_query(
            "select a from diagnostics").rows == [("7",)]


class TestClusterMemtables:
    """The fan-out contract: live peers contribute their rows, a dead
    peer contributes exactly one ``peer-lost`` row within the budget,
    and the statement's trace carries the hop marks."""

    def test_no_fleet_answers_local(self):
        tk = TestKit()
        tk.must_query("select 1")
        rows = tk.must_query(
            "select instance, error from "
            "information_schema.cluster_statements_summary").rows
        assert rows
        assert all(r[0] == "local" and not r[1] for r in rows)

    def test_dead_peer_tagged_and_traced(self, tmp_path):
        from tidb_tpu.server.server import MySQLServer
        from tidb_tpu.session.diag import PEER_TIMEOUT_S
        coord = Coordinator.create(str(tmp_path / "coord.json"), nslots=4)
        tk = TestKit()
        srv = None
        try:
            # slot 0: THIS process, reachable on a real direct port
            coord.claim_slot(0)
            fabric_state.activate(coord, 0, lease_hbm=False)
            srv = MySQLServer(tk.domain, port=0, users={}).start()
            coord.set_direct_port(0, srv.port)
            # slot 1: a peer that died mid-statement — lease still
            # fresh, direct port refusing connections
            coord.claim_slot(1)
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
            s.close()
            coord.set_direct_port(1, dead_port)
            tk.must_query("select 1")  # statement history to serve
            coord.heartbeat(0)
            coord.heartbeat(1)
            t0 = time.monotonic()
            tree = json.loads(tk.must_query(
                "trace format='json' select instance, error from "
                "information_schema.cluster_statements_summary"
            ).rows[0][0])
            wall = time.monotonic() - t0
            assert wall < PEER_TIMEOUT_S + 3.0, (
                f"cluster query took {wall:.1f}s — a dead peer must "
                "cost its budget, not a hang")
            blob = json.dumps(tree)
            assert "cluster.fanout" in blob
            assert "peer-lost" in blob, (
                "dead peer's hop left no mark on the stitched trace")
            # ...and the memtable rows carry the tagged error cell
            rows = tk.must_query(
                "select instance, error from "
                "information_schema.cluster_statements_summary").rows
            by_inst = {}
            for inst, err in rows:
                by_inst.setdefault(inst, []).append(err or "")
            live = by_inst[f"slot0:{srv.port}"]
            assert live and all(not e for e in live)
            lost = by_inst[f"slot1:{dead_port}"]
            assert len(lost) == 1
            assert lost[0].startswith("peer-lost:"), lost
        finally:
            fabric_state.deactivate()
            if srv is not None:
                srv.shutdown()
            coord.unlink()


@pytest.mark.chaos_threads
class TestClusterChaosTrace:
    """Trace propagation under real process chaos (the ISSUE 18
    satellite): one worker SIGKILLed mid-statement via the
    chaos-harness fleet fault, one wedged (SIGSTOP — alive socket,
    dead service).  The survivor's cluster query must complete within
    the per-peer budget with the lost peer as a ``peer-lost`` row AND
    a peer-lost mark on the stitched trace — never a hang, never a
    dropped trace."""

    def test_survivor_trace_marks_lost_peers(self, tmp_path):
        from tests.chaos_harness import FLEET_FAULTS
        from tidb_tpu.fabric.client import FleetClient, WireError
        from tidb_tpu.fabric.fleet import Fleet
        from tidb_tpu.session.diag import PEER_TIMEOUT_S
        kill_action = FLEET_FAULTS["fabric-kill-worker"][0]
        fleet = Fleet(
            3, compile_server=False, run_dir=str(tmp_path / "fleet"),
            slot_env={0: {"TIDB_TPU_FABRIC_FAILPOINTS":
                          f"fabric-kill-worker={kill_action}"}})
        fleet.start(timeout_s=240.0)
        stopped_pid = None
        try:
            # statement history on the workers that will answer (slot
            # 0's armed failpoint fires on its FIRST query — don't
            # spend it on the warm-up)
            for slot in (1, 2):
                c = FleetClient(fleet.direct_port(slot))
                c.must_query("select 1")
                c.close()
            old_pid = fleet.worker_pid(0)
            # worker 1 wedges: process alive (no respawn, lease goes
            # stale on its own clock), service dead — its direct port
            # still connects (kernel backlog) but DIAG never answers
            stopped_pid = fleet.worker_pid(1)
            os.kill(stopped_pid, signal.SIGSTOP)
            # worker 0 dies MID-STATEMENT on its armed fault
            with pytest.raises(WireError):
                FleetClient(fleet.direct_port(0)).must_query("select 1")
            # the survivor's cluster view, traced — within the wedged
            # peer's lease window so its port is still advertised
            c2 = FleetClient(fleet.direct_port(2))
            t0 = time.monotonic()
            tree = json.loads(c2.must_query(
                "trace format='json' select instance, error from "
                "information_schema.cluster_statements_summary"
            )[1][0][0])
            wall = time.monotonic() - t0
            assert wall < 2 * PEER_TIMEOUT_S + 4.0, (
                f"survivor's cluster query took {wall:.1f}s with dead "
                "peers — the per-peer budget did not hold")
            assert tree["duration_s"] is not None, (
                "survivor's trace not finished")

            # the fan-out's span events are the statement's own record
            # of which peers answered: the wedged worker must be a
            # peer-lost mark, the survivor an ok one
            def _fanout_events(node, acc):
                if isinstance(node, dict):
                    for ev in node.get("events", []):
                        if ev.get("name") == "cluster.fanout":
                            acc.append(ev.get("tags", {}))
                    for ch in node.get("children", []):
                        _fanout_events(ch, acc)
                return acc

            evs = _fanout_events(tree.get("root", {}), [])
            assert evs, "no cluster.fanout events on the stitched trace"
            assert any(t.get("status") == "peer-lost" for t in evs), (
                f"no peer-lost mark on the survivor's trace: {evs}")
            assert any(t.get("status") == "ok"
                       and t.get("instance", "").startswith("slot2:")
                       for t in evs), evs
            # a later plain query still answers (lost peers may have
            # aged out of the peer list by now — any that remain must
            # be tagged, never silently absent rows mid-list)
            rows = c2.must_query(
                "select instance, error from "
                "information_schema.cluster_statements_summary")[1]
            ok_insts = {r[0] for r in rows if not r[1]}
            assert any(i.startswith("slot2:") for i in ok_insts), rows
            assert all((e or "").startswith("peer-lost:")
                       for _i, e in rows if e)
            c2.close()
            os.kill(stopped_pid, signal.SIGCONT)
            stopped_pid = None
            assert fleet.wait_respawn(0, old_pid, 30.0), (
                "no respawn within the backoff budget")
            # the fleet converges: every slot serves again (the
            # respawned incarnation's failpoint is NOT re-armed)
            deadline = time.monotonic() + 30.0
            for slot in range(3):
                while True:
                    try:
                        c = FleetClient(fleet.direct_port(slot))
                        c.must_query("select 1")
                        c.close()
                        break
                    except (WireError, OSError):
                        assert time.monotonic() < deadline, (
                            f"slot {slot} never recovered")
                        time.sleep(0.25)
        finally:
            if stopped_pid is not None:
                os.kill(stopped_pid, signal.SIGCONT)
            drained = fleet.shutdown()
        assert drained and drained["ok"], drained
