"""Prepared-plan cache (reference: planner/core/cache.go CacheKey,
common_plans.go Execute.getPhysicalPlan/rebuildRange,
planner/core/cacheable_checker.go Cacheable)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    tk.must_exec("create table t (id int primary key, a int, b int, "
                 "d date, key ia (a))")
    tk.must_exec("insert into t values "
                 + ",".join(f"({i},{i % 50},{i % 7},"
                            f"'199{i % 9}-0{i % 9 + 1}-11')"
                            for i in range(500)))
    tk.must_exec("analyze table t")
    return tk


def _prep(tk, sql):
    return tk.session.prepare(sql)[0]


def _exec(tk, stmt_ast, params):
    return [tuple(v) for v in
            tk.session.execute_prepared(stmt_ast, params).internal_rows]


class TestPlanCacheHit:
    def test_repeat_execute_skips_planning(self, tk):
        s = _prep(tk, "select a, b from t where a = ? order by id")
        sess = tk.session
        r1 = _exec(tk, s, [3])
        built = sess.plan_builds
        r2 = _exec(tk, s, [3])
        assert sess.plan_builds == built  # cache hit: no re-plan
        assert r1 == r2
        assert sess.plan_cache.hits >= 1

    def test_rebound_params_give_correct_results(self, tk):
        s = _prep(tk, "select count(1) from t where a = ?")
        assert _exec(tk, s, [3]) == [(10,)]
        built = tk.session.plan_builds
        assert _exec(tk, s, [7]) == [(10,)]
        assert _exec(tk, s, [999]) == [(0,)]
        assert tk.session.plan_builds == built

    def test_point_get_rebinds_handle(self, tk):
        # access path (PointGet handle) must follow the new param, not the
        # first execution's (rebuildRange analog)
        s = _prep(tk, "select id, a from t where id = ?")
        assert _exec(tk, s, [5]) == [(5, 5)]
        built = tk.session.plan_builds
        assert _exec(tk, s, [123]) == [(123, 23)]
        assert _exec(tk, s, [499]) == [(499, 49)]
        assert tk.session.plan_builds == built

    def test_date_param_refinement_rebinds(self, tk):
        # string param refined to a date constant at plan time must re-refine
        # per execution
        s = _prep(tk, "select count(1) from t where d < ?")
        all_rows = _exec(tk, s, ["2001-01-01"])[0][0]
        none_rows = _exec(tk, s, ["1980-01-01"])[0][0]
        assert all_rows == 500 and none_rows == 0

    def test_data_changes_visible_through_cached_plan(self, tk):
        s = _prep(tk, "select count(1) from t where a = ?")
        assert _exec(tk, s, [3]) == [(10,)]
        tk.must_exec("insert into t values (1000, 3, 0, '1999-01-01')")
        assert _exec(tk, s, [3]) == [(11,)]

    def test_param_type_change_replans(self, tk):
        s = _prep(tk, "select count(1) from t where a = ?")
        assert _exec(tk, s, [3]) == [(10,)]
        built = tk.session.plan_builds
        # float param: fresh plan (different coercions), still correct
        assert _exec(tk, s, [3.0]) == [(10,)]
        assert tk.session.plan_builds == built + 1

    def test_lru_capacity_bounds_entries(self, tk):
        tk.must_exec("set tidb_prepared_plan_cache_size = 2")
        stmts = [_prep(tk, f"select {i}, count(1) from t where a = ?")
                 for i in range(4)]
        for s in stmts:
            _exec(tk, s, [1])
        assert len(tk.session.plan_cache._lru) <= 2


class TestPlanCacheInvalidation:
    def test_ddl_invalidates(self, tk):
        s = _prep(tk, "select a from t where id = ?")
        assert _exec(tk, s, [7]) == [(7,)]
        built = tk.session.plan_builds
        tk.must_exec("alter table t add column c int")
        # schema version changed: re-plan, and the result stays correct
        assert _exec(tk, s, [7]) == [(7,)]
        assert tk.session.plan_builds > built

    def test_analyze_invalidates(self, tk):
        s = _prep(tk, "select count(1) from t where a = ?")
        _exec(tk, s, [3])
        built = tk.session.plan_builds
        tk.must_exec("analyze table t")
        _exec(tk, s, [3])
        assert tk.session.plan_builds > built

    def test_binding_invalidates(self, tk):
        s = _prep(tk, "select * from t where a = ?")
        _exec(tk, s, [3])
        built = tk.session.plan_builds
        tk.must_exec("create session binding for "
                     "select * from t where a = 3 using "
                     "select * from t ignore index (ia) where a = 3")
        _exec(tk, s, [3])
        assert tk.session.plan_builds > built

    def test_disable_sysvar(self, tk):
        tk.must_exec("set tidb_enable_prepared_plan_cache = OFF")
        s = _prep(tk, "select count(1) from t where a = ?")
        _exec(tk, s, [3])
        built = tk.session.plan_builds
        _exec(tk, s, [3])
        assert tk.session.plan_builds == built + 1  # re-planned


class TestUncacheable:
    def _replans(self, tk, sql, params):
        s = _prep(tk, sql)
        _exec(tk, s, params)
        built = tk.session.plan_builds
        _exec(tk, s, params)
        return tk.session.plan_builds == built + 1

    def test_now_is_uncacheable(self, tk):
        assert self._replans(
            tk, "select count(1) from t where d < now() and a = ?", [3])

    def test_subquery_is_uncacheable(self, tk):
        assert self._replans(
            tk, "select count(1) from t where a = ? and "
                "id in (select id from t where b = 1)", [3])

    def test_param_in_limit_is_uncacheable(self, tk):
        s = _prep(tk, "select id from t order by id limit ?")
        assert _exec(tk, s, [3]) == [(0,), (1,), (2,)]
        assert _exec(tk, s, [1]) == [(0,)]  # must not freeze first limit

    def test_param_in_in_list_is_uncacheable(self, tk):
        s = _prep(tk, "select count(1) from t where a in (?, ?)")
        assert _exec(tk, s, [3, 4]) == [(20,)]
        assert _exec(tk, s, [5, 6]) == [(20,)]
        assert _exec(tk, s, [3, 3]) == [(10,)]

    def test_param_like_pattern_is_uncacheable(self, tk):
        tk.must_exec("create table ts (v varchar(20))")
        tk.must_exec("insert into ts values ('apple'), ('banana'), ('apri')")
        s = _prep(tk, "select count(1) from ts where v like ?")
        assert _exec(tk, s, ["ap%"]) == [(2,)]
        assert _exec(tk, s, ["ban%"]) == [(1,)]

    def test_uservar_is_uncacheable(self, tk):
        tk.must_exec("set @x = 3")
        s = _prep(tk, "select count(1) from t where a = @x and b < ?")
        assert _exec(tk, s, [100]) == [(10,)]
        tk.must_exec("set @x = 4")
        assert _exec(tk, s, [100]) == [(10,)]


class TestSeekValueDomains:
    """Eq/range seek keys must live in the indexed column's value domain
    (review findings: bytes / decimal-literal / float constants against an
    int or decimal indexed column must not seek impossible keys)."""

    @pytest.fixture()
    def utk(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table u (id int primary key, a int, "
                     "unique key ua (a))")
        tk.must_exec("insert into u values "
                     + ",".join(f"({i},{i})" for i in range(200)))
        tk.must_exec("analyze table u")
        return tk

    def test_string_eq_on_int_unique_index(self, utk):
        # MySQL coerces 'garbage' to 0.0 → matches a=0
        assert utk.must_query(
            "select count(1) from u where a = 'garbage'").rows == [("1",)]
        assert utk.must_query(
            "select count(1) from u where a = '3'").rows == [("1",)]
        assert utk.must_query(
            "select count(1) from u where a = '3x'").rows == [("1",)]  # →3.0

    def test_decimal_eq_on_int_unique_index(self, utk):
        assert utk.must_query(
            "select count(1) from u where a = 3.0").rows == [("1",)]
        assert utk.must_query(
            "select count(1) from u where a = 3.5").rows == [("0",)]

    def test_prepared_string_params_order_independent(self, utk):
        s = _prep(utk, "select count(1) from u where a = ?")
        assert _exec(utk, s, ["garbage"]) == [(1,)]  # coerces to 0
        assert _exec(utk, s, ["3"]) == [(1,)]
        assert _exec(utk, s, ["garbage"]) == [(1,)]
        assert _exec(utk, s, ["3"]) == [(1,)]

    def test_date_param_garbage_then_valid(self, tk):
        s = _prep(tk, "select count(1) from t where d < ?")
        assert _exec(tk, s, ["2001-01-01"]) == [(500,)]
        _exec(tk, s, ["garbage"])  # unrefinable: re-plans, must not poison
        assert _exec(tk, s, ["2001-01-01"]) == [(500,)]
        assert _exec(tk, s, ["1980-01-01"]) == [(0,)]

    def test_int_range_on_decimal_index(self):
        tk = TestKit()
        tk.must_exec("use test")
        tk.must_exec("create table dpr (id int primary key, "
                     "p decimal(10,2), key ip (p))")
        tk.must_exec("insert into dpr values "
                     + ",".join(f"({i},{i}.25)" for i in range(500)))
        tk.must_exec("analyze table dpr")
        # hi bound 100 must scale to the decimal key domain (10000), not
        # cut the scan at scaled key 100 (= 1.00)
        assert tk.must_query(
            "select count(1) from dpr where p < 100").rows == [("100",)]
        assert tk.must_query(
            "select count(1) from dpr where p > 400.5 and p < 402").rows \
            == [("1",)]


class TestPartitionReprune:
    def test_partition_pruning_follows_param(self, tk):
        tk.must_exec("""
            create table p (id int, v int)
            partition by range (id) (
              partition p0 values less than (100),
              partition p1 values less than (200),
              partition p2 values less than maxvalue)""")
        tk.must_exec("insert into p values (50, 1), (150, 2), (250, 3)")
        s = _prep(tk, "select v from p where id = ?")
        assert _exec(tk, s, [50]) == [(1,)]
        built = tk.session.plan_builds
        # different partitions must be re-pruned per execution on a hit
        assert _exec(tk, s, [150]) == [(2,)]
        assert _exec(tk, s, [250]) == [(3,)]
        assert tk.session.plan_builds == built
