"""errno / taxonomy consistency:

  * engine-owned error codes (>= 9000, the 9005-9010+ band PRs 1-8 grew)
    must be UNIQUE across ErrCode constants and inline ``code = NNNN``
    class attributes — two errors sharing a code would be
    indistinguishable to tests and the wire protocol;
  * every code >= 9005 must be referenced by at least one error class
    (a reserved-but-orphaned code is a taxonomy hole);
  * every ``CLASS_*`` constant in utils/backoff.py must be RETURNED by
    ``classify`` (a class no error can ever get is dead taxonomy);
  * every ``Device*Error`` class in errors.py must appear inside
    ``classify`` (a device-path error the classifier does not know falls
    through to 'other' and skips its breaker/retry ladder).
"""

from __future__ import annotations

import ast

from ..engine import Rule, register

ERRORS_REL = "errors.py"
BACKOFF_REL = "utils/backoff.py"
ENGINE_CODE_MIN = 9000
REFERENCED_MIN = 9005


def _errcode_constants(errors_tree):
    """(name, value, lineno) of ErrCode integer class attributes."""
    out = []
    for node in ast.walk(errors_tree):
        if isinstance(node, ast.ClassDef) and node.name == "ErrCode":
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, int)):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            out.append((tgt.id, stmt.value.value,
                                        stmt.lineno))
    return out


def _inline_codes(sf):
    """(class_name, value, lineno) for ``code = <int>`` class attrs."""
    out = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, int)):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "code":
                        out.append((node.name, stmt.value.value,
                                    stmt.lineno))
    return out


@register
class TaxonomyConsistency(Rule):
    name = "taxonomy-consistency"
    title = "errno uniqueness + backoff taxonomy completeness"

    def run(self, ctx):
        out = []
        errors_sf = ctx.file(ERRORS_REL)
        backoff_sf = ctx.file(BACKOFF_REL)
        if errors_sf is None or backoff_sf is None:
            return out  # fixture tree without the taxonomy spine

        # -- engine-code uniqueness across the whole package ----------------
        by_code: dict[int, list] = {}
        for name, val, line in _errcode_constants(errors_sf.tree):
            if val >= ENGINE_CODE_MIN:
                by_code.setdefault(val, []).append(
                    (errors_sf.rel, f"ErrCode.{name}", line))
        for sf in ctx.package_files:
            for cls, val, line in _inline_codes(sf):
                if val >= ENGINE_CODE_MIN:
                    by_code.setdefault(val, []).append(
                        (sf.rel, cls, line))
        referenced_names = set()
        for sf in ctx.package_files:
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "ErrCode"):
                    referenced_names.add(node.attr)
        for val, owners in sorted(by_code.items()):
            # an ErrCode constant plus the ONE class that binds it via
            # ``code = ErrCode.X`` is the normal pairing; duplicates are
            # two *distinct* names/classes on one code
            distinct = {o[1] for o in owners}
            if len(distinct) > 1:
                rel, ident_owner, line = owners[0]
                out.append(self.finding(
                    rel, line, f"dup-code:{val}",
                    f"engine error code {val} bound by multiple owners: "
                    f"{sorted(distinct)}"))
        for name, val, line in _errcode_constants(errors_sf.tree):
            if val >= REFERENCED_MIN and name not in referenced_names:
                out.append(self.finding(
                    errors_sf.rel, line, f"orphan-code:{name}",
                    f"ErrCode.{name} ({val}) is reserved but no error "
                    "class or raise site references it"))

        # -- backoff CLASS_* completeness -----------------------------------
        classes, classify_fn = {}, None
        for node in backoff_sf.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Name)
                            and tgt.id.startswith("CLASS_")):
                        classes[tgt.id] = node.lineno
            if (isinstance(node, ast.FunctionDef)
                    and node.name == "classify"):
                classify_fn = node
        returned = set()
        classify_names = set()
        if classify_fn is not None:
            for node in ast.walk(classify_fn):
                if isinstance(node, ast.Return) and isinstance(
                        node.value, ast.Name):
                    returned.add(node.value.id)
                if isinstance(node, ast.Name):
                    classify_names.add(node.id)
        for cname, line in sorted(classes.items()):
            if cname not in returned:
                out.append(self.finding(
                    backoff_sf.rel, line, f"dead-class:{cname}",
                    f"taxonomy constant {cname} is never returned by "
                    "classify() — no error can ever carry it"))

        # -- Device*Error classes known to classify -------------------------
        for node in ast.walk(errors_sf.tree):
            if (isinstance(node, ast.ClassDef)
                    and node.name.startswith("Device")
                    and node.name.endswith("Error")
                    and node.name not in classify_names):
                out.append(self.finding(
                    errors_sf.rel, node.lineno,
                    f"unclassified:{node.name}",
                    f"{node.name} is not referenced by backoff.classify() "
                    "— it would fall through to 'other' and skip its "
                    "breaker/retry ladder"))
        return out
