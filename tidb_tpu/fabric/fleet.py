"""The fleet parent supervisor: spawn N worker processes behind one
advertised port, restart crashed workers with backoff, drain on
shutdown.

The parent owns the shared pieces — the coordination segment
(fabric/coord.py), the advertised port reservation (a bound
SO_REUSEPORT socket that never listens, so the number stays ours while
only the workers' listening sockets receive connections), and optionally
the separated compile server subprocess — and supervises worker
lifecycles:

* **ready protocol**: each worker prints one ``fabric_worker_ready``
  JSON line (slot, pid, shared port, direct port); a per-child reader
  thread collects it plus the drain-time summary line.  Every other
  stdout line is forwarded to :attr:`Fleet.lines` for the bench.
* **restart-on-crash**: a worker exiting outside a shutdown is
  reclaimed (its segment lease + running counts zeroed, counted in
  ``fabric_lease_reclaims``) and respawned after an exponential backoff
  (`BACKOFF_BASE_S * 2^k`, capped) — `RESPAWN_LIMIT` consecutive fast
  deaths park the slot instead of hot-looping a crashing binary.
  Respawns count into the segment (``fabric_respawns``) so every worker
  and the bench see the same number.
* **drain-on-shutdown**: SIGTERM → workers stop accepting, finish
  in-flight connections, emit summaries, release leases; stragglers are
  SIGKILLed after the grace window and force-reclaimed.  The segment's
  :meth:`~tidb_tpu.fabric.coord.Coordinator.verify_drained` is captured
  before unlink so callers can assert zero leaked leases/tickets.
* **simulated hosts** (``hosts=N``): workers are partitioned into N
  process groups, one per "host" (slot `i` lives on host ``i % N``); the
  first live worker of a host is its group leader.  :meth:`Fleet.kill_host`
  SIGKILLs the whole group at once — the chaos shape where an entire
  machine (every region lease it held) vanishes mid-commit, which is
  what region failover (fabric/region.py) must survive.  ``nregions``
  sizes the segment's region table so those leases exist to lose.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

from .coord import Coordinator

log = logging.getLogger("tidb_tpu.fabric.fleet")

BACKOFF_BASE_S = 0.2
BACKOFF_CAP_S = 2.0
#: consecutive crash-respawns before a slot is parked (a worker that
#: lives longer than STABLE_S resets its slot's crash counter)
RESPAWN_LIMIT = 5
STABLE_S = 10.0
#: a lease older than this is a dead worker (worker.HEARTBEAT_S * 8)
LEASE_TIMEOUT_S = 2.0


class _Slot:
    def __init__(self, idx: int):
        self.idx = idx
        self.proc = None
        self.pid = 0
        self.direct_port = 0
        self.ready = threading.Event()
        self.summary = None
        self.crashes = 0          # consecutive fast deaths
        self.started_at = 0.0
        self.parked = False


class Fleet:
    def __init__(self, procs: int, *, init: str = "",
                 sysvars: "dict | None" = None,
                 compile_server: bool = True,
                 run_dir: "str | None" = None,
                 env_extra: "dict | None" = None,
                 slot_env: "dict | None" = None,
                 durable: bool = True,
                 hosts: int = 1,
                 nregions: int = 0,
                 net_coord: bool = False):
        """`init`: a "module:callable" data-seeding hook — under the
        durable store (the default) it runs ONCE fleet-wide (the first
        worker seeds, the rest replay the shared log); with
        ``durable=False`` every worker runs it against an independent
        in-memory Domain (the pre-ISSUE-15 topology).  `sysvars`:
        GLOBAL sysvars every worker applies at boot.  `slot_env`:
        {slot: {ENV: val}} extras for individual workers (the chaos
        schedule's door: e.g.
        ``{2: {"TIDB_TPU_FABRIC_FAILPOINTS": "fabric-kill-worker=1*return(1)"}}``).
        `hosts`: partition workers into this many per-host process
        groups (1 = the classic single-host fleet, no extra groups).
        `nregions`: region cells to allocate in the segment.
        `net_coord`: serve the segment over a CoordServer and point the
        workers at it (TIDB_TPU_FABRIC_COORD_ADDR) — every coordinator
        op becomes a traced TCP hop into the parent process, the
        topology the distributed-trace stitching bench asserts on.  The
        parent keeps its direct segment handle either way."""
        self.procs = procs
        self.hosts = max(int(hosts), 1)
        self.nregions = int(nregions)
        self._host_pgid: dict[int, int] = {}
        self.init = init
        self.durable = durable
        self.sysvars = dict(sysvars or {})
        self.with_compile_server = compile_server
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="tpufab-")
        self.env_extra = dict(env_extra or {})
        self.slot_env = {int(k): dict(v) for k, v in
                         (slot_env or {}).items()}
        self.slots = [_Slot(i) for i in range(procs)]
        self.lines: list = []      # non-protocol worker stdout lines
        self.net_coord = bool(net_coord)
        self.coord_server = None
        self.coord_addr = ""
        self.coord: "Coordinator | None" = None
        self.compile_server_proc = None
        self.compile_server_addr = ""
        self.port = 0
        self._reserve_sock = None
        self._stopping = threading.Event()
        self._monitor = None
        self._mu = threading.Lock()
        self.final_drained: "dict | None" = None

    # -- lifecycle -----------------------------------------------------------

    def start(self, timeout_s: float = 120.0) -> "Fleet":
        os.makedirs(self.run_dir, exist_ok=True)
        self.coord = Coordinator.create(
            os.path.join(self.run_dir, "coord.json"),
            nslots=max(self.procs, 2), nregions=self.nregions)
        if self.net_coord:
            from .coord_net import CoordServer
            self.coord_server = CoordServer(self.coord)
            self.coord_addr = self.coord_server.start()
        self._reserve_port()
        if self.with_compile_server:
            self._spawn_compile_server(timeout_s)
        for s in self.slots:
            self._spawn(s)
        deadline = time.monotonic() + timeout_s
        for s in self.slots:
            if not s.ready.wait(max(deadline - time.monotonic(), 0.1)):
                raise RuntimeError(
                    f"fabric worker slot {s.idx} not ready within "
                    f"{timeout_s}s (see its stderr above)")
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="fabric-fleet-monitor")
        self._monitor.start()
        return self

    def _reserve_port(self):
        """Hold the advertised number with a bound, never-listening
        SO_REUSEPORT socket: only LISTENING sockets receive connections,
        so the kernel balances purely across the workers."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind(("127.0.0.1", 0))
        self._reserve_sock = s
        self.port = s.getsockname()[1]

    def _spawn_compile_server(self, timeout_s: float):
        addr = os.path.join(self.run_dir, "compile.sock")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tidb_tpu.fabric.compile_server",
             "--socket", addr],
            env=self._base_env(), stdout=subprocess.PIPE,
            text=True, cwd=os.getcwd())
        self.compile_server_proc = proc
        # BOUNDED ready wait (a wedged server must fail start, not hang
        # it): the readline happens on a reaper-able thread and the
        # spawner waits on an event with the boot budget
        ready_evt = threading.Event()
        first_line = [""]

        def _read_first():
            first_line[0] = proc.stdout.readline()
            ready_evt.set()
            self._drain_stdout(proc)

        threading.Thread(target=_read_first, daemon=True,
                         name="fabric-compile-server-read").start()
        if not ready_evt.wait(timeout_s):
            with _suppress():
                proc.kill()
            raise RuntimeError(
                f"compile server not ready within {timeout_s}s")
        try:
            ready = json.loads(first_line[0])
            assert ready.get("metric") == "compile_server_ready"
        except Exception as e:
            raise RuntimeError(
                f"compile server failed to start: {first_line[0]!r}") \
                from e
        self.compile_server_addr = addr

    def _drain_stdout(self, proc):
        for line in proc.stdout:
            with self._mu:
                self.lines.append(line.rstrip("\n"))

    def _base_env(self) -> dict:
        env = dict(os.environ)
        env.update(self.env_extra)
        env["PYTHONPATH"] = (os.getcwd() + os.pathsep
                             + env.get("PYTHONPATH", ""))
        return env

    def _spawn(self, s: _Slot):
        env = self._base_env()
        env["TIDB_TPU_FABRIC_COORD"] = self.coord.path
        if self.coord_addr:
            env["TIDB_TPU_FABRIC_COORD_ADDR"] = self.coord_addr
        env["TIDB_TPU_FABRIC_SLOT"] = str(s.idx)
        env["TIDB_TPU_FABRIC_PORT"] = str(self.port)
        if self.durable:
            # the shared durable store: one WAL + checkpoint dir for the
            # whole fleet (kv/shared_store.py picks up the coordination
            # segment for TSO/locks/tailing from the worker's fabric
            # activation)
            env["TIDB_TPU_WAL_DIR"] = os.path.join(self.run_dir, "wal")
        if self.init:
            env["TIDB_TPU_FABRIC_INIT"] = self.init
        if self.sysvars:
            env["TIDB_TPU_FABRIC_GLOBALS"] = ";".join(
                f"{k}={v}" for k, v in self.sysvars.items())
        if self.compile_server_addr:
            env["TIDB_TPU_COMPILE_SERVER"] = self.compile_server_addr
        # slot extras apply to the FIRST incarnation only: a chaos
        # failpoint that kills the worker must not re-arm on every
        # respawn (the fleet would park the slot after RESPAWN_LIMIT
        # scripted deaths and call it a crash loop)
        env.update(self.slot_env.pop(s.idx, {}))
        s.ready.clear()
        s.started_at = time.monotonic()
        s.proc = self._popen_worker(s, env)
        threading.Thread(target=self._read_worker, args=(s, s.proc),
                         daemon=True, name=f"fabric-read-{s.idx}").start()

    def _popen_worker(self, s: _Slot, env: dict):
        argv = [sys.executable, "-m", "tidb_tpu.fabric.worker"]
        if self.hosts <= 1:
            return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                                    text=True, cwd=os.getcwd())
        # multi-host: the worker joins its host's process group (the
        # first live worker of the host leads a fresh group), so
        # kill_host / the fabric-kill-host failpoint can take out the
        # whole "machine" with one killpg
        host = self.host_of(s.idx)
        env["TIDB_TPU_FABRIC_HOST"] = str(host)
        pgid = self._host_pgid.get(host, 0)
        if pgid and not _pg_alive(pgid):
            pgid = 0  # the old leader's group is gone: lead a new one
        try:
            proc = subprocess.Popen(
                argv, env=env, stdout=subprocess.PIPE, text=True,
                cwd=os.getcwd(),
                preexec_fn=_setpgid_fn(pgid))  # noqa: PLW1509 — single-
            #   threaded child pre-exec; only setpgid runs
        except (OSError, subprocess.SubprocessError):
            if not pgid:
                raise
            # the leader died between the aliveness probe and the fork:
            # this worker becomes the host's new group leader
            proc = subprocess.Popen(
                argv, env=env, stdout=subprocess.PIPE, text=True,
                cwd=os.getcwd(), preexec_fn=_setpgid_fn(0))  # noqa: PLW1509
            pgid = 0
        if not pgid:
            self._host_pgid[host] = proc.pid
        return proc

    def host_of(self, slot: int) -> int:
        return slot % self.hosts

    def host_slots(self, host: int) -> list:
        return [s.idx for s in self.slots if self.host_of(s.idx) == host]

    def kill_host(self, host: int, sig=signal.SIGKILL):
        """The host-loss chaos primitive: SIGKILL the whole simulated
        host's process group — every worker on it dies at once, leases
        and all, exactly like a machine losing power."""
        pgid = self._host_pgid.get(host)
        if pgid and _pg_alive(pgid):
            with _suppress():
                os.killpg(pgid, sig)
            return
        # no live group (group leader already gone): kill stragglers
        # individually so the semantic stays "the host is down"
        for idx in self.host_slots(host):
            self.kill_worker(idx, sig)

    def _read_worker(self, s: _Slot, proc):
        for line in proc.stdout:
            line = line.rstrip("\n")
            try:
                obj = json.loads(line)
            except ValueError:
                obj = None
            if isinstance(obj, dict) and obj.get("metric") == \
                    "fabric_worker_ready":
                s.pid = obj["pid"]
                s.direct_port = obj["direct_port"]
                s.ready.set()
            elif isinstance(obj, dict) and obj.get("metric") == \
                    "fabric_worker_summary":
                s.summary = obj
                with self._mu:
                    self.lines.append(line)
            else:
                with self._mu:
                    self.lines.append(line)

    # -- supervision ---------------------------------------------------------

    def _monitor_loop(self):
        while not self._stopping.is_set():
            for s in self.slots:
                p = s.proc
                if p is None or s.parked:
                    continue
                rc = p.poll()
                if rc is None:
                    if s.crashes and \
                            time.monotonic() - s.started_at > STABLE_S:
                        s.crashes = 0  # lived long enough: forgiven
                    continue
                if self._stopping.is_set():
                    break
                # unexpected death: reclaim its segment state NOW (the
                # lease would expire anyway; the parent knows sooner),
                # then respawn with backoff
                try:
                    self.coord.release_slot(s.idx)
                    self.coord.bump("fabric_lease_reclaims")
                except Exception as e:  # noqa: BLE001 — peers re-reclaim
                    log.warning("segment reclaim for dead slot %d failed "
                                "(lease expiry will finish it): %s",
                                s.idx, e)
                s.crashes += 1
                if s.crashes > RESPAWN_LIMIT:
                    s.parked = True
                    with self._mu:
                        self.lines.append(json.dumps({
                            "metric": "fabric_slot_parked",
                            "slot": s.idx, "exit": rc,
                            "crashes": s.crashes}))
                    continue
                delay = min(BACKOFF_BASE_S * (2 ** (s.crashes - 1)),
                            BACKOFF_CAP_S)
                with self._mu:
                    self.lines.append(json.dumps({
                        "metric": "fabric_worker_respawn",
                        "slot": s.idx, "exit": rc,
                        "backoff_s": round(delay, 3)}))
                if self._stopping.wait(delay):
                    break
                try:
                    self.coord.bump("fabric_respawns")
                except Exception as e:  # noqa: BLE001 — counter only
                    log.warning("respawn counter bump failed: %s", e)
                self._spawn(s)
            self._stopping.wait(0.05)

    @property
    def respawns(self) -> int:
        try:
            return self.coord.counters()["fabric_respawns"]
        except Exception as e:  # noqa: BLE001 — gauge read post-unlink
            log.debug("respawn counter unreadable: %s", e)
            return 0

    def direct_port(self, slot: int) -> int:
        return self.slots[slot].direct_port

    def worker_pid(self, slot: int) -> int:
        return self.slots[slot].pid

    def kill_worker(self, slot: int, sig=signal.SIGKILL):
        """The chaos primitive: hard-kill one worker."""
        p = self.slots[slot].proc
        if p is not None and p.poll() is None:
            os.kill(p.pid, sig)

    def wait_respawn(self, slot: int, old_pid: int,
                     timeout_s: float = 30.0) -> bool:
        """Block until `slot` is serving again under a NEW pid."""
        s = self.slots[slot]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if s.ready.is_set() and s.pid and s.pid != old_pid \
                    and s.proc is not None and s.proc.poll() is None:
                return True
            time.sleep(0.05)
        return False

    # -- shutdown ------------------------------------------------------------

    def shutdown(self, drain: bool = True,
                 timeout_s: float = 20.0) -> "dict | None":
        """Stop the fleet; returns the segment's final verify_drained
        (captured before unlink) — the no-leaked-leases invariant the
        bench and the chaos tests assert."""
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(5.0)
        procs = [s.proc for s in self.slots if s.proc is not None]
        if drain:
            for p in procs:
                if p.poll() is None:
                    with _suppress():
                        p.send_signal(signal.SIGTERM)
            deadline = time.monotonic() + timeout_s
            for p in procs:
                with _suppress():
                    p.wait(max(deadline - time.monotonic(), 0.1))
        for p in procs:
            if p.poll() is None:
                with _suppress():
                    p.kill()
                with _suppress():
                    p.wait(5.0)
        # a SIGKILLed straggler never released its lease: reclaim so the
        # drained verdict reflects reality, not the straggler's rudeness
        with _suppress():
            self.coord.reclaim_expired(0.0)
        with _suppress():
            self.final_drained = self.coord.verify_drained()
        if self.compile_server_proc is not None:
            with _suppress():
                self.compile_server_proc.send_signal(signal.SIGTERM)
            with _suppress():
                self.compile_server_proc.wait(5.0)
            if self.compile_server_proc.poll() is None:
                with _suppress():
                    self.compile_server_proc.kill()
        if self._reserve_sock is not None:
            with _suppress():
                self._reserve_sock.close()
        if self.coord_server is not None:
            with _suppress():
                self.coord_server.stop()
        with _suppress():
            self.coord.unlink()
        return self.final_drained


def _suppress():
    import contextlib
    return contextlib.suppress(Exception)


def _pg_alive(pgid: int) -> bool:
    """Is any process left in this group?  Signal 0 probes without
    delivering."""
    try:
        os.killpg(pgid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _setpgid_fn(pgid: int):
    """Child-side pre-exec: join (or, with 0, lead) a process group —
    Python 3.10 has no Popen(process_group=...) yet."""
    def fn():
        os.setpgid(0, pgid)
    return fn
