"""Multi-chip MPP operators on the virtual 8-device CPU mesh
(conftest forces xla_force_host_platform_device_count=8)."""

import numpy as np
import jax

from tidb_tpu.parallel import (
    make_mesh, dist_agg_step, dist_join_agg_step, shard_batch)


def _numpy_groupby(keys, valid, vals, kinds):
    out = {}
    for k in np.unique(keys[valid]):
        m = valid & (keys == k)
        row = []
        for v, kind in zip(vals, kinds):
            if kind in ("sum", "count"):
                row.append(v[m].sum())
            elif kind == "min":
                row.append(v[m].min())
            elif kind == "max":
                row.append(v[m].max())
        out[int(k)] = row
    return out


def test_dist_agg_matches_numpy():
    rng = np.random.default_rng(7)
    n = 10_000
    keys = rng.integers(0, 37, n)
    valid = rng.random(n) < 0.8
    sums = rng.integers(-100, 100, n)
    ones = np.ones(n, dtype=np.int64)
    mins = rng.integers(0, 10**6, n)

    mesh = make_mesh(8)
    kinds = ("sum", "count", "min", "max")
    step = dist_agg_step(mesh, kinds, capacity=64)
    (arrs, pad_valid) = shard_batch(mesh, keys, valid, sums, ones, mins, mins)
    k, v, s, o, mn, mx = arrs
    fk, fouts, fvalid, n_groups, overflow = step(
        k, v & pad_valid, s, o, mn, mx)
    assert not bool(overflow)
    got = {}
    fk = np.asarray(fk)
    fvalid = np.asarray(fvalid)
    for i in range(int(n_groups)):
        assert fvalid[i]
        got[int(fk[i])] = [int(np.asarray(f)[i]) for f in fouts]
    want = _numpy_groupby(keys, valid, [sums, ones, mins, mins], kinds)
    assert got == want


def test_dist_agg_overflow_flag():
    mesh = make_mesh(8)
    step = dist_agg_step(mesh, ("sum",), capacity=8)
    n = 1024
    keys = np.arange(n, dtype=np.int64)  # 1024 groups > capacity 8
    (arrs, pad_valid) = shard_batch(mesh, keys, np.ones(n, bool),
                                    np.ones(n, dtype=np.int64))
    k, v, s = arrs
    *_rest, overflow = step(k, v & pad_valid, s)
    assert bool(overflow)


def test_dist_join_agg_matches_numpy():
    rng = np.random.default_rng(11)
    nb, npr = 3_000, 9_000
    bk = rng.integers(0, 500, nb)
    bv = rng.integers(1, 50, nb)
    bvalid = rng.random(nb) < 0.7
    pk = rng.integers(0, 700, npr)
    pv = rng.integers(1, 50, npr)
    pvalid = rng.random(npr) < 0.9

    mesh = make_mesh(8)
    cap = 4096  # per-destination bucket capacity, ample for this size
    step = dist_join_agg_step(mesh, cap)
    (ba, bval_pad) = shard_batch(mesh, bk, bvalid, bv)
    (pa, pval_pad) = shard_batch(mesh, pk, pvalid, pv)
    total, pairs, dropped = step(ba[0], ba[2], ba[1] & bval_pad,
                                 pa[0], pa[2], pa[1] & pval_pad)
    assert int(dropped) == 0

    want_total = 0
    want_pairs = 0
    bsum = {}
    bcnt = {}
    for k, v, ok in zip(bk, bv, bvalid):
        if ok:
            bsum[k] = bsum.get(k, 0) + v
            bcnt[k] = bcnt.get(k, 0) + 1
    for k, v, ok in zip(pk, pv, pvalid):
        if ok and k in bsum:
            want_total += v * bsum[k]
            want_pairs += bcnt[k]
    assert int(total) == want_total
    assert int(pairs) == want_pairs


def test_join_agg_bucket_overflow_reported():
    mesh = make_mesh(8)
    step = dist_join_agg_step(mesh, cap=2)
    n = 512
    keys = np.zeros(n, dtype=np.int64)  # all rows hash to one bucket
    ones = np.ones(n, dtype=np.int64)
    (arrs, pad) = shard_batch(mesh, keys, np.ones(n, bool), ones)
    k, v, o = arrs
    _total, _pairs, dropped = step(k, o, v & pad, k, o, v & pad)
    assert int(dropped) > 0


class _SupCtx:
    """Minimal embedder context for the dist_* ctx= hook: just the
    sysvars effective_deadline reads (no Domain, no session)."""

    def __init__(self, timeout_s):
        self._t = timeout_s

    def get_sysvar(self, name, *a, **kw):
        if name == "tidb_device_call_timeout":
            return self._t
        if name == "max_execution_time":
            return 0
        raise KeyError(name)


def test_dist_agg_step_supervised_ctx_matches_inline():
    """ctx= routes the exchange dispatch through the device-runtime
    supervisor (worker thread + deadline) with identical results — the
    library embedder's hang guard (executor/supervisor.py)."""
    rng = np.random.default_rng(11)
    n = 4096
    keys = rng.integers(0, 17, n)
    vals = rng.integers(-50, 50, n)
    mesh = make_mesh(8)
    plain = dist_agg_step(mesh, ("sum",), capacity=32)
    sup = dist_agg_step(mesh, ("sum",), capacity=32,
                        ctx=_SupCtx(timeout_s=30.0))
    (arrs, pad) = shard_batch(mesh, keys, np.ones(n, bool), vals)
    k, v, s = arrs
    a = plain(k, v & pad, s)
    b = sup(k, v & pad, s)
    assert np.asarray(a[0]).tolist() == np.asarray(b[0]).tolist()
    assert np.asarray(a[1][0]).tolist() == np.asarray(b[1][0]).tolist()
    assert int(a[3]) == int(b[3])


def test_dist_agg_step_supervised_ctx_hang_deadline():
    """A stalled supervised dispatch raises DeviceHangError instead of
    blocking the embedder forever (stall injected at the wrapper level —
    a real PJRT hang blocks the same worker thread the same way)."""
    import time as _time

    import pytest as _pytest

    from tidb_tpu.errors import DeviceHangError
    from tidb_tpu.parallel.mpp import _supervised_step

    def stalls(*_a):
        _time.sleep(0.5)
        return "never used"

    wrapped = _supervised_step(stalls, _SupCtx(timeout_s=0.05))
    t0 = _time.monotonic()
    with _pytest.raises(DeviceHangError):
        wrapped()
    assert _time.monotonic() - t0 < 0.4
