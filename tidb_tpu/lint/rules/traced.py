"""Traced-value hazards inside jit-built fragment bodies — the static
counterpart of the zero-recompile regression tests.

A function handed to ``observed_jit`` / ``jax.jit`` (directly, via
decorator, or via ``partial(jax.jit, ...)``) runs under trace: its
non-static parameters are tracers.  Python-level control flow on a
tracer's VALUE either raises ConcretizationTypeError at trace time or —
when the value sneaks in as a Python scalar — silently bakes the value
into the compiled program and recompiles on every change (the exact
regression class PRs 2/7/8 burned down: live counts, n_valid,
capacities must ride as traced operands, not cache keys).

Flagged inside jit bodies, for any non-static parameter ``p``:

  * ``if p`` / ``while p`` / ``assert p`` / ternary tests referencing
    ``p``'s value (``p.shape``/``p.ndim``/``p.dtype``/``p.size`` and
    ``len(p)`` are static and fine),
  * ``int(p)`` / ``float(p)`` / ``bool(p)`` / ``p.item()`` concretization,
  * ``range(p)`` / ``for ... in p`` Python iteration,
  * ``np.asarray(p)`` / ``np.array(p)`` host materialization.

Parameters named by ``static_argnames``/``static_argnums`` are excluded
(they are compile-time constants by contract — branching on them is the
bucketing design working as intended).
"""

from __future__ import annotations

import ast

from ..engine import Rule, register
from ._util import call_name, const_str

JIT_WRAPPERS = {"observed_jit", "_observed_jit"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type",
                "sharding"}
NUMPY_ALIASES = {"np", "numpy", "onp"}


def _is_jit_call(call: ast.Call) -> bool:
    name = call_name(call)
    leaf = name.rsplit(".", 1)[-1]
    return leaf in JIT_WRAPPERS or name in ("jax.jit", "jit")


def _static_params(call_kwargs, fn: ast.FunctionDef) -> set:
    """Parameter names excluded by static_argnames/static_argnums."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out = set()
    for kw in call_kwargs:
        if kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                s = const_str(v)
                if s:
                    out.add(s)
        elif kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, int) and v.value < len(params):
                    out.add(params[v.value])
    return out


def _jit_targets(sf) -> list:
    """(FunctionDef, static_param_names) for every jit-built body in the
    file: decorator forms and name-passed-to-wrapper forms."""
    defs_by_name: dict[str, list] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, []).append(node)
    out = []
    seen = set()

    def add(fn, statics):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append((fn, statics))

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, (ast.Name, ast.Attribute)) and \
                        _is_jit_call(ast.Call(func=dec, args=[],
                                              keywords=[])):
                    add(node, set())
                elif isinstance(dec, ast.Call):
                    dn = call_name(dec)
                    if _is_jit_call(dec):
                        add(node, _static_params(dec.keywords, node))
                    elif dn.rsplit(".", 1)[-1] == "partial" and dec.args \
                            and isinstance(dec.args[0],
                                           (ast.Name, ast.Attribute)) \
                            and _is_jit_call(ast.Call(
                                func=dec.args[0], args=[], keywords=[])):
                        add(node, _static_params(dec.keywords, node))
        if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            arg0 = node.args[0]
            if isinstance(arg0, ast.Name):
                for fn in defs_by_name.get(arg0.id, []):
                    add(fn, _static_params(node.keywords, fn))
    return out


def _refs_value(node, traced: set) -> bool:
    """Does this expression depend on a traced parameter's VALUE (shape/
    dtype/len derivations are static and do not count)?"""
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return _refs_value(node.value, traced)
    if isinstance(node, ast.Call):
        leaf = call_name(node).rsplit(".", 1)[-1]
        if leaf == "len":
            return False
        return _refs_value(node.func, traced) or \
            any(_refs_value(a, traced) for a in node.args) or \
            any(_refs_value(kw.value, traced) for kw in node.keywords)
    return any(_refs_value(c, traced)
               for c in ast.iter_child_nodes(node))


@register
class TracedValueHazard(Rule):
    name = "traced-value-hazard"
    title = "no Python control flow on traced values in jit bodies"

    def run(self, ctx):
        out = []
        for sf in ctx.package_files:
            for fn, statics in _jit_targets(sf):
                traced = {a.arg for a in (fn.args.posonlyargs
                                          + fn.args.args
                                          + fn.args.kwonlyargs)}
                traced -= statics
                traced.discard("self")
                if not traced:
                    continue
                out.extend(self._scan(sf, fn, traced))
        return out

    def _scan(self, sf, fn, traced):
        out = []
        seen: dict[str, int] = {}

        def emit(node, kind, msg):
            qn = f"{sf.qualname(fn)}"
            base = f"{kind}@{qn}"
            k = seen.get(base, 0)
            seen[base] = k + 1
            ident = base + (f"#{k}" if k else "")
            out.append(self.finding(sf.rel, node.lineno, ident, msg))

        def visit(node, traced):
            if isinstance(node, ast.FunctionDef) and node is not fn:
                # nested def: shadowed names are its own params
                inner = traced - {a.arg for a in (
                    node.args.posonlyargs + node.args.args
                    + node.args.kwonlyargs)}
                for c in node.body:
                    visit(c, inner)
                return
            if isinstance(node, (ast.If, ast.While)) and _refs_value(
                    node.test, traced):
                emit(node, "branch",
                     "Python control flow on a traced value inside a jit "
                     "body — concretization error or silent recompile "
                     "per value (mask with jnp.where / lax.cond)")
            if isinstance(node, ast.IfExp) and _refs_value(
                    node.test, traced):
                emit(node, "branch",
                     "ternary on a traced value inside a jit body — use "
                     "jnp.where")
            if isinstance(node, ast.Assert) and _refs_value(
                    node.test, traced):
                emit(node, "branch",
                     "assert on a traced value inside a jit body")
            if isinstance(node, ast.Call):
                leaf = call_name(node).rsplit(".", 1)[-1]
                head = call_name(node).split(".", 1)[0]
                if leaf in ("int", "float", "bool") and "." not in \
                        call_name(node) and any(
                            _refs_value(a, traced) for a in node.args):
                    emit(node, f"concretize-{leaf}",
                         f"{leaf}() on a traced value inside a jit body "
                         "— concretization; keep it a traced operand")
                if leaf == "item" and _refs_value(node.func, traced):
                    emit(node, "item",
                         ".item() on a traced value inside a jit body")
                if leaf == "range" and any(
                        _refs_value(a, traced) for a in node.args):
                    emit(node, "iterate",
                         "range() over a traced value inside a jit body "
                         "— loop bound becomes a compile-time constant")
                if leaf in ("asarray", "array") and head in \
                        NUMPY_ALIASES and any(
                            _refs_value(a, traced) for a in node.args):
                    emit(node, "asarray",
                         "numpy materialization of a traced value inside "
                         "a jit body")
            if isinstance(node, ast.For) and _refs_value(
                    node.iter, traced) and not (
                    isinstance(node.iter, ast.Call)
                    and call_name(node.iter).rsplit(".", 1)[-1] ==
                    "range"):
                # (a traced range() bound is the Call check's finding)
                emit(node, "iterate",
                     "Python iteration over a traced value inside a jit "
                     "body")
            for c in ast.iter_child_nodes(node):
                visit(c, traced)

        for stmt in fn.body:
            visit(stmt, traced)
        return out
