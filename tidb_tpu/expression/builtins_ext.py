"""Extended builtin function library (reference: expression/builtin.go:573
registry — 281 functions; this module grows the engine's dispatch table
toward it: string, math, date/time, JSON and network/misc functions).

Implementation style: row-wise Python kernels behind a tiny spec-driven
adapter (`_pyfn`). These are host-side scalar builtins — the vectorized hot
path (comparisons, arithmetic, LIKE, date parts) stays in core.py and the
device compiler; functions here are the long tail where per-row Python cost
is acceptable (reference analog: builtinXxxSig.evalString row loops, which
are likewise scalar)."""

from __future__ import annotations

import base64
import binascii
import calendar
import datetime as _dt
import hashlib
import json as _json
import math
import struct
import zlib

import numpy as np

from ..sqltypes import (FieldType, TYPE_LONGLONG, TYPE_VARCHAR, TYPE_DOUBLE,
                        TYPE_DATE, TYPE_DATETIME)
from .core import (_DISPATCH, _as_float, _cast_to, _to_dateparts)

_S = FieldType(tp=TYPE_VARCHAR)
_I = FieldType(tp=TYPE_LONGLONG)


def _conv_arg(a, chunk, kind):
    d, n = a.eval(chunk)
    if kind == "s":
        d, n = _cast_to(d, n, a.ftype, _S)
    elif kind == "i":
        d, n = _cast_to(d, n, a.ftype, _I)
    elif kind == "f":
        d = _as_float(d, a.ftype)
    elif kind == "d":  # datetime parts (datetime|None objects)
        d = _to_dateparts(a, chunk)
        n = np.array([p is None for p in d]) | n
    return d, n


def _pyfn(spec, fn, out="s", null_propagate=True):
    """Adapter: convert args per `spec` ('s' bytes, 'i' int, 'f' float,
    'd' datetime, 'r' raw), run `fn` per row, box the result. fn returning
    None yields NULL. spec may be longer than the actual args (optionals);
    a trailing '*' repeats the previous kind."""

    def ev(sf, chunk):
        kinds = []
        si = 0
        for _a in sf.args:
            k = spec[si] if si < len(spec) else kinds[-1]
            if k == "*":
                k = kinds[-1]
            kinds.append(k)
            if si < len(spec) - 1 or (si < len(spec) and spec[si] != "*"):
                si += 1
        arrs, nls = [], []
        for a, k in zip(sf.args, kinds):
            d, n2 = _conv_arg(a, chunk, k)
            arrs.append(d)
            nls.append(n2)
        m = max((len(x) for x in arrs), default=chunk.num_rows)
        nulls = np.zeros(m, dtype=bool)
        if null_propagate:
            for n2 in nls:
                nulls = nulls | n2
        if out == "s":
            data = np.full(m, b"", dtype=object)
        elif out == "i":
            data = np.zeros(m, dtype=np.int64)
        elif out == "f":
            data = np.zeros(m, dtype=np.float64)
        else:
            data = np.full(m, b"", dtype=object)
        for i in range(m):
            if nulls[i]:
                continue
            try:
                if null_propagate:
                    v = fn(*[arr[i] for arr in arrs])
                else:
                    v = fn(*[None if nl[i] else arr[i]
                             for arr, nl in zip(arrs, nls)])
            except (ValueError, OverflowError, ZeroDivisionError,
                    ArithmeticError, binascii.Error, KeyError, IndexError,
                    struct.error, UnicodeDecodeError, TypeError,
                    AttributeError):
                v = None
            if v is None:
                nulls[i] = True
            else:
                data[i] = v
        return data, nulls

    return ev


def _u(b: bytes) -> str:
    return b.decode("utf-8", "replace")


# -- string ------------------------------------------------------------------

def _soundex(b):
    s = "".join(ch for ch in _u(b).upper() if ch.isalpha())
    if not s:
        return b""
    codes = {**dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
             **dict.fromkeys("DT", "3"), "L": "4",
             **dict.fromkeys("MN", "5"), "R": "6"}
    out = [s[0]]
    last = codes.get(s[0], "")
    for ch in s[1:]:
        c = codes.get(ch, "")
        if c and c != last:
            out.append(c)
        last = c
    return ("".join(out) + "000")[:4].encode()


def _substring_index(s, delim, count):
    if not delim:
        return b""
    parts = s.split(delim)
    if count > 0:
        return delim.join(parts[:count])
    if count < 0:
        return delim.join(parts[count:])
    return b""


def _format_num(v, nd):
    nd = max(int(nd), 0)
    return f"{v:,.{nd}f}".encode()


def _insert_fn(s, pos, ln, news):
    if pos < 1 or pos > len(s):
        return s
    return s[:pos - 1] + news + s[pos - 1 + max(ln, 0):]


class _SqlCrypt:
    """ENCODE()/DECODE() stream cipher (reference: util/encrypt/crypt.go —
    MySQL's pre-8.0 obfuscation: a password-seeded pair of LCGs drives a
    255-entry substitution box plus a running xor shift). Kept for SQL
    compatibility only; not secure."""

    def __init__(self, password: bytes):
        nr, add, nr2 = 1345345333, 7, 0x12345671
        for ch in password:
            if ch in (0x20, 0x09):
                continue
            nr ^= (((nr & 63) + add) * ch + (nr << 8)) & 0xFFFFFFFF
            nr &= 0xFFFFFFFF
            nr2 = (nr2 + ((nr2 << 8) ^ nr)) & 0xFFFFFFFF
            add = (add + ch) & 0xFFFFFFFF
        self.max_value = 0x3FFFFFFF
        self.seed1 = (nr & 0x7FFFFFFF) % self.max_value
        self.seed2 = (nr2 & 0x7FFFFFFF) % self.max_value
        dec = bytearray(range(256))
        for i in range(256):
            idx = int(self._rand() * 255.0)
            dec[idx], dec[i] = dec[i], dec[idx]
        enc = bytearray(256)
        for i in range(256):
            enc[dec[i]] = i
        self.dec, self.enc = bytes(dec), bytes(enc)
        self.shift = 0

    def _rand(self) -> float:
        self.seed1 = (self.seed1 * 3 + self.seed2) % self.max_value
        self.seed2 = (self.seed1 + self.seed2 + 33) % self.max_value
        return self.seed1 / self.max_value

    def encode(self, data: bytes) -> bytes:
        out = bytearray(len(data))
        for i, ch in enumerate(data):
            self.shift ^= int(self._rand() * 255.0)
            out[i] = self.enc[ch] ^ (self.shift & 0xFF)
            self.shift ^= ch
        return bytes(out)

    def decode(self, data: bytes) -> bytes:
        out = bytearray(len(data))
        for i, ch in enumerate(data):
            self.shift ^= int(self._rand() * 255.0)
            out[i] = self.dec[ch ^ (self.shift & 0xFF)]
            self.shift ^= out[i]
        return bytes(out)


def _vitess_hash(v) -> int:
    """VITESS_HASH(shard_key) (reference: util/vitess/vitess_hash.go):
    DES-ECB over the big-endian uint64 with an all-zero key — expressed
    here as 3DES with three null keys (K1=K2=K3 degenerates to DES)."""
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        try:
            from cryptography.hazmat.decrepit.ciphers.algorithms import (
                TripleDES)
        except ImportError:  # older library layout
            from cryptography.hazmat.primitives.ciphers.algorithms import (
                TripleDES)
        from cryptography.hazmat.primitives.ciphers import Cipher, modes
    enc = Cipher(TripleDES(b"\0" * 24), modes.ECB()).encryptor()
    h = enc.update(struct.pack(">Q", int(v) & (2**64 - 1)))
    u = struct.unpack(">Q", h)[0]
    # wrap into int64 storage; the builder's UNSIGNED flag restores the
    # uint64 on render
    return u - (1 << 64) if u >= 1 << 63 else u


def _conv_base(s, from_b, to_b):
    try:
        v = int(_u(s).strip() or "0", int(from_b))
    except ValueError:
        v = 0
    to_b = int(to_b)
    neg = v < 0
    v = abs(v)
    digits = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    if v == 0:
        return b"0"
    out = ""
    while v:
        v, r = divmod(v, abs(to_b))
        out = digits[r] + out
    return (("-" if neg and to_b < 0 else "") + out).encode()


_STRING_FUNCS = {
    "ascii": _pyfn("s", lambda s: s[0] if s else 0, out="i"),
    "ord": _pyfn("s", lambda s: int.from_bytes(
        s[:max(1, (s[0] >> 4 == 0xF) * 4 or (s[0] >> 5 == 7) * 3
               or (s[0] >> 6 == 3) * 2 or 1)], "big") if s else 0, out="i"),
    "bin": _pyfn("i", lambda v: format(v & (2**64 - 1) if v < 0 else v,
                                       "b").encode()),
    "oct": _pyfn("i", lambda v: format(v & (2**64 - 1) if v < 0 else v,
                                       "o").encode()),
    "unhex": _pyfn("s", lambda s: binascii.unhexlify(
        (b"0" + s) if len(s) % 2 else s)),
    "md5": _pyfn("s", lambda s: hashlib.md5(s).hexdigest().encode()),
    "encode": _pyfn("ss", lambda s, pw: _SqlCrypt(pw).encode(s)),
    "decode": _pyfn("ss", lambda s, pw: _SqlCrypt(pw).decode(s)),
    "vitess_hash": _pyfn("i", _vitess_hash, out="i"),
    "sha1": _pyfn("s", lambda s: hashlib.sha1(s).hexdigest().encode()),
    "sha2": _pyfn("si", lambda s, n: hashlib.new(
        {0: "sha256", 224: "sha224", 256: "sha256", 384: "sha384",
         512: "sha512"}[int(n)], s).hexdigest().encode()),
    "crc32": _pyfn("s", lambda s: zlib.crc32(s) & 0xFFFFFFFF, out="i"),
    "instr": _pyfn("ss", lambda s, sub: s.find(sub) + 1, out="i"),
    "rpad": _pyfn("sis", lambda s, n, pad:
                  None if n < 0 else
                  (s[:n] if len(s) >= n else
                   (s + pad * n)[:n] if pad else None)),
    "elt": _pyfn("is*", lambda n, *ss:
                 ss[n - 1] if n is not None and 1 <= n <= len(ss) else None,
                 null_propagate=False),
    "field": _pyfn("ss*", lambda t, *ss:
                   0 if t is None else
                   next((i + 1 for i, s in enumerate(ss) if s == t), 0),
                   out="i", null_propagate=False),
    "find_in_set": _pyfn("ss", lambda t, st:
                         ([b""] + st.split(b",")).index(t)
                         if t in st.split(b",") else 0, out="i"),
    "format": _pyfn("fi", _format_num),
    "insert": _pyfn("siis", _insert_fn),
    "strcmp": _pyfn("ss", lambda a, b: (a > b) - (a < b), out="i"),
    "substring_index": _pyfn("ssi", _substring_index),
    "to_base64": _pyfn("s", lambda s: base64.b64encode(s)),
    "from_base64": _pyfn("s", lambda s: base64.b64decode(s, validate=True)),
    "quote": _pyfn("s", lambda s: b"'" + s.replace(b"\\", b"\\\\")
                   .replace(b"'", b"\\'") + b"'"),
    "space": _pyfn("i", lambda n: b" " * min(max(n, 0), 1 << 20)),
    "char": _pyfn("i*", lambda *vs: b"".join(
        int(v).to_bytes(max((int(v).bit_length() + 7) // 8, 1), "big")
        for v in vs if v is not None), null_propagate=False),
    "bit_length": _pyfn("s", lambda s: 8 * len(s), out="i"),
    "conv": _pyfn("sii", _conv_base),
    "soundex": _pyfn("s", _soundex),
    "hex": _pyfn("r", lambda v: (binascii.hexlify(v).upper() if
                                 isinstance(v, (bytes, bytearray)) else
                                 format(int(v) & (2**64 - 1), "X").encode())),
}


# -- math --------------------------------------------------------------------

def _math1(fn):
    return _pyfn("f", lambda v: _finite(fn(v)), out="f")


def _finite(v):
    return v if v is not None and math.isfinite(v) else None


_MATH_FUNCS = {
    "sin": _math1(math.sin), "cos": _math1(math.cos),
    "tan": _math1(math.tan),
    "asin": _math1(lambda v: math.asin(v) if -1 <= v <= 1 else None),
    "acos": _math1(lambda v: math.acos(v) if -1 <= v <= 1 else None),
    "atan": _math1(math.atan),
    "cot": _math1(lambda v: 1.0 / math.tan(v) if math.tan(v) != 0 else None),
    "atan2": _pyfn("ff", lambda a, b: math.atan2(a, b), out="f"),
    "radians": _math1(math.radians), "degrees": _math1(math.degrees),
    "pi": _pyfn("", lambda: math.pi, out="f"),
    "rand": None,  # replaced below: needs one RNG per CALL, not per row
    "log": _pyfn("ff", lambda a, *b:
                 _finite(math.log(b[0], a) if b else math.log(a))
                 if a > 0 and (not b or b[0] > 0) else None,
                 out="f", null_propagate=False),
    "exp": _math1(lambda v: math.exp(v) if v < 700 else None),
    "bit_count": _pyfn("i", lambda v: bin(int(v) & (2**64 - 1)).count("1"),
                       out="i"),
}


def _eval_rand(sf, chunk):
    """rand([seed]): one RNG per evaluation — a seeded call yields MySQL's
    repeatable-but-varying per-row sequence, not one constant."""
    n = chunk.num_rows
    if sf.args:
        d, nl = _conv_arg(sf.args[0], chunk, "i")
        seed = int(d[0]) if len(d) and not nl[0] else 0
        rng = np.random.default_rng(seed)
    else:
        rng = np.random.default_rng()
    return rng.random(n), np.zeros(n, dtype=bool)


_MATH_FUNCS["rand"] = _eval_rand


# -- date / time -------------------------------------------------------------

_EPOCH = _dt.datetime(1970, 1, 1)


def _from_unixtime(ts):
    try:
        return (_EPOCH + _dt.timedelta(seconds=int(ts))
                ).strftime("%Y-%m-%d %H:%M:%S").encode()
    except OverflowError:
        return None


def _parse_time_b(b):
    """HH:MM:SS[.f] / HHH:MM:SS → seconds (sign-aware)."""
    s = _u(b).strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    parts = s.split(":")
    if len(parts) == 1:
        v = float(parts[0] or 0)
        h, rem = divmod(int(v), 10000)
        mnt, sec = divmod(rem, 100)
        total = h * 3600 + mnt * 60 + sec
    else:
        nums = [float(p or 0) for p in parts[:3]] + [0.0] * (3 - len(parts))
        total = nums[0] * 3600 + nums[1] * 60 + nums[2]
    return -total if neg else total


def _sec_to_time(v):
    neg = v < 0
    v = abs(int(v))
    h, rem = divmod(v, 3600)
    mnt, sec = divmod(rem, 60)
    return f"{'-' if neg else ''}{h:02d}:{mnt:02d}:{sec:02d}".encode()


_STRPTIME_MAP = {
    "%Y": "%Y", "%y": "%y", "%m": "%m", "%c": "%m", "%d": "%d", "%e": "%d",
    "%H": "%H", "%k": "%H", "%h": "%I", "%I": "%I", "%i": "%M", "%s": "%S",
    "%S": "%S", "%p": "%p", "%M": "%B", "%b": "%b", "%j": "%j",
    "%W": "%A", "%a": "%a", "%T": "%H:%M:%S", "%%": "%%",
}


def _str_to_date(s, fmt):
    pyfmt = ""
    f = _u(fmt)
    i = 0
    while i < len(f):
        if f[i] == "%" and i + 1 < len(f):
            tok = f[i:i + 2]
            pyfmt += _STRPTIME_MAP.get(tok, tok[1])
            i += 2
        else:
            pyfmt += f[i]
            i += 1
    try:
        dt = _dt.datetime.strptime(_u(s).strip(), pyfmt)
    except ValueError:
        return None
    if ("%H" in pyfmt or "%I" in pyfmt or "%M" in pyfmt or "%S" in pyfmt):
        return dt.strftime("%Y-%m-%d %H:%M:%S").encode()
    return dt.strftime("%Y-%m-%d").encode()


_DATE_FUNCS = {
    "from_unixtime": _pyfn("i", _from_unixtime),
    "unix_timestamp": _pyfn("d", lambda p: int(
        (p - _EPOCH).total_seconds()), out="i"),
    "time_to_sec": _pyfn("s", lambda b: int(_parse_time_b(b)), out="i"),
    "sec_to_time": _pyfn("i", _sec_to_time),
    "makedate": _pyfn("ii", lambda y, d: (
        _dt.date(int(y), 1, 1) + _dt.timedelta(days=int(d) - 1)
    ).strftime("%Y-%m-%d").encode() if d > 0 else None),
    "maketime": _pyfn("iii", lambda h, m, s:
                      f"{h:02d}:{m:02d}:{s:02d}".encode()
                      if 0 <= m < 60 and 0 <= s < 60 else None),
    "last_day": _pyfn("d", lambda p: p.replace(
        day=calendar.monthrange(p.year, p.month)[1]
    ).strftime("%Y-%m-%d").encode()),
    "dayname": _pyfn("d", lambda p: p.strftime("%A").encode()),
    "monthname": _pyfn("d", lambda p: p.strftime("%B").encode()),
    "weekday": _pyfn("d", lambda p: p.weekday(), out="i"),
    "weekofyear": _pyfn("d", lambda p: p.isocalendar()[1], out="i"),
    "yearweek": _pyfn("d", lambda p: p.isocalendar()[0] * 100
                      + p.isocalendar()[1], out="i"),
    # MySQL day numbers count from year 0: python ordinal (0001-01-01=1)
    # is 365 behind
    "to_days": _pyfn("d", lambda p: p.toordinal() + 365, out="i"),
    "from_days": _pyfn("i", lambda n: _dt.date.fromordinal(
        int(n) - 365).strftime("%Y-%m-%d").encode() if n > 730 else None),
    "period_add": _pyfn("ii", lambda p, n: (lambda y, m:
                        ((y * 12 + m - 1 + int(n)) // 12) * 100
                        + ((y * 12 + m - 1 + int(n)) % 12) + 1)(
                            int(p) // 100, int(p) % 100), out="i"),
    "period_diff": _pyfn("ii", lambda a, b:
                         (int(a) // 100 * 12 + int(a) % 100)
                         - (int(b) // 100 * 12 + int(b) % 100), out="i"),
    "str_to_date": _pyfn("ss", _str_to_date),
    "microsecond": _pyfn("d", lambda p: getattr(p, "microsecond", 0),
                         out="i"),
    "addtime": _pyfn("ss", lambda a, b: _sec_to_time(
        _parse_time_b(a) + _parse_time_b(b))),
    "subtime": _pyfn("ss", lambda a, b: _sec_to_time(
        _parse_time_b(a) - _parse_time_b(b))),
    "timestampdiff": _pyfn("sdd", lambda unit, a, b: _tsdiff(
        _u(unit).lower(), a, b), out="i"),
}


def _tsdiff(unit, a, b):
    delta = b - a
    if unit == "second":
        return int(delta.total_seconds())
    if unit == "minute":
        return int(delta.total_seconds() // 60)
    if unit == "hour":
        return int(delta.total_seconds() // 3600)
    if unit == "day":
        return delta.days
    if unit == "week":
        return delta.days // 7
    months = (b.year - a.year) * 12 + (b.month - a.month)
    if (b.day, getattr(b, "hour", 0)) < (a.day, getattr(a, "hour", 0)):
        months -= 1
    if unit == "month":
        return months
    if unit == "quarter":
        return months // 3
    if unit == "year":
        return months // 12
    return None


# -- JSON --------------------------------------------------------------------

def _json_load(b):
    return _json.loads(b.decode("utf-8"))


def _json_dump(v) -> bytes:
    return _json.dumps(v, separators=(", ", ": "), ensure_ascii=False
                       ).encode()


def _json_path_get(doc, path: bytes):
    """Subset of MySQL JSON path: $, .key, ."quoted", [n], [*], .*."""
    p = _u(path).strip()
    if not p.startswith("$"):
        return None, False
    i = 1
    cur = [doc]
    while i < len(p):
        if p[i] == ".":
            i += 1
            if i < len(p) and p[i] == "*":
                i += 1
                nxt = []
                for c in cur:
                    if isinstance(c, dict):
                        nxt.extend(c.values())
                cur = nxt
                continue
            if i < len(p) and p[i] == '"':
                j = p.index('"', i + 1)
                key = p[i + 1:j]
                i = j + 1
            else:
                j = i
                while j < len(p) and p[j] not in ".[":
                    j += 1
                key = p[i:j]
                i = j
            cur = [c[key] for c in cur if isinstance(c, dict) and key in c]
        elif p[i] == "[":
            j = p.index("]", i)
            tok = p[i + 1:j].strip()
            i = j + 1
            if tok == "*":
                nxt = []
                for c in cur:
                    if isinstance(c, list):
                        nxt.extend(c)
                cur = nxt
            else:
                n = int(tok)
                cur = [c[n] for c in cur
                       if isinstance(c, list) and -len(c) <= n < len(c)]
        else:
            return None, False
    if not cur:
        return None, False
    return (cur[0] if len(cur) == 1 else cur), True


def _json_extract(doc_b, *paths):
    doc = _json_load(doc_b)
    vals = []
    for p in paths:
        v, ok = _json_path_get(doc, p)
        if ok:
            vals.append(v)
    if not vals:
        return None
    return _json_dump(vals[0] if len(paths) == 1 and len(vals) == 1
                      else vals)


def _json_type(b):
    v = _json_load(b)
    return {dict: b"OBJECT", list: b"ARRAY", str: b"STRING", bool: b"BOOLEAN",
            int: b"INTEGER", float: b"DOUBLE",
            type(None): b"NULL"}[type(v)]


_JSON_FUNCS = {
    "json_extract": _pyfn("ss*", _json_extract),
    "json_unquote": _pyfn("s", lambda b: (
        _json_load(b).encode() if b[:1] == b'"' else b)),
    "json_valid": _pyfn("s", lambda b: _json_valid(b), out="i"),
    "json_length": _pyfn("s", lambda b: (
        lambda v: len(v) if isinstance(v, (dict, list)) else 1)(
            _json_load(b)), out="i"),
    "json_type": _pyfn("s", _json_type),
    "json_object": _pyfn("ss*", lambda *kv: _json_dump(
        {_u(kv[i]): _try_json(kv[i + 1]) for i in range(0, len(kv), 2)})),
    "json_array": _pyfn("s*", lambda *vs: _json_dump(
        [_try_json(v) for v in vs]), null_propagate=False),
    "json_keys": _pyfn("s", lambda b: (
        lambda v: _json_dump(list(v.keys())) if isinstance(v, dict)
        else None)(_json_load(b))),
    "json_contains": _pyfn("ss", lambda doc, cand: int(
        _json_contains(_json_load(doc), _json_load(cand))), out="i"),
    "json_quote": _pyfn("s", lambda b: _json_dump(_u(b))),
}


def _json_depth(v) -> int:
    if isinstance(v, dict):
        return 1 + max((_json_depth(x) for x in v.values()), default=0)
    if isinstance(v, list):
        return 1 + max((_json_depth(x) for x in v), default=0)
    return 1


def _json_merge_patch_all(docs):
    """RFC 7396 merge patch folded left over the args (reference:
    types/json json_merge_patch)."""
    out = docs[0]
    for patch in docs[1:]:
        out = _merge_patch(out, patch)
    return out


def _merge_patch(target, patch):
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out


def _json_contains_path(doc_b, one_or_all, *paths):
    doc = _json_load(doc_b)
    mode = _u(one_or_all).lower()
    hits = [_json_path_get(doc, p)[1] for p in paths]
    if mode == "one":
        return int(any(hits))
    return int(all(hits))


def _json_path_tokens(path: bytes):
    """Parse a wildcard-free JSON path into [("key", k) | ("idx", n)]
    (MySQL rejects wildcards in mutation paths too)."""
    p = _u(path).strip()
    if not p.startswith("$"):
        return None
    toks = []
    i = 1
    while i < len(p):
        if p[i] == ".":
            i += 1
            if i < len(p) and p[i] == '"':
                j = p.index('"', i + 1)
                toks.append(("key", p[i + 1:j]))
                i = j + 1
            else:
                j = i
                while j < len(p) and p[j] not in ".[":
                    j += 1
                if p[i:j] == "*":
                    return None
                toks.append(("key", p[i:j]))
                i = j
        elif p[i] == "[":
            j = p.index("]", i)
            tok = p[i + 1:j].strip()
            if tok == "*":
                return None
            toks.append(("idx", int(tok)))
            i = j + 1
        else:
            return None
    return toks


def _to_json_value(v):
    """SQL internal value → JSON value (reference: types/json CreateBinary
    from a datum): strings become JSON strings, numbers numbers."""
    if v is None:
        return None
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).decode("utf-8", "replace")
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        return float(v)
    return str(v)


def _json_modify(doc, toks, value, mode):
    """Apply one (path, value) to doc. mode: set | insert | replace |
    append (json_array_append) | remove (value ignored)."""
    if toks is None:
        raise ValueError("bad json path")
    if not toks:  # path is "$"
        if mode == "remove":
            raise ValueError("cannot remove the root")
        if mode == "append":
            return doc + [value] if isinstance(doc, list) else [doc, value]
        if mode == "insert":
            return doc
        return value
    parent = doc
    for kind, k in toks[:-1]:
        if kind == "key":
            if not isinstance(parent, dict) or k not in parent:
                return doc  # missing intermediate: no-op (MySQL behavior)
            parent = parent[k]
        else:
            if not isinstance(parent, list) or not (
                    -len(parent) <= k < len(parent)):
                return doc
            parent = parent[k]
    kind, k = toks[-1]
    if kind == "key":
        if not isinstance(parent, dict):
            return doc
        exists = k in parent
        if mode == "remove":
            parent.pop(k, None)
        elif mode == "append":
            if exists:
                cur = parent[k]
                parent[k] = (cur + [value] if isinstance(cur, list)
                             else [cur, value])
        elif (mode == "set" or (mode == "insert" and not exists)
                or (mode == "replace" and exists)):
            parent[k] = value
    else:
        if not isinstance(parent, list):
            return doc
        exists = -len(parent) <= k < len(parent)
        if mode == "remove":
            if exists:
                del parent[k]
        elif mode == "append":
            if exists:
                cur = parent[k]
                parent[k] = (cur + [value] if isinstance(cur, list)
                             else [cur, value])
        elif mode == "replace":
            if exists:
                parent[k] = value
        elif mode in ("set", "insert"):
            if exists:
                if mode == "set":
                    parent[k] = value
            else:
                parent.append(value)
    return doc


def _json_mut_fn(mode, pairwise=True):
    """Evaluator for json_set/insert/replace/array_append (doc, path, val,
    ...) and json_remove (doc, path, ...)."""
    def ev(sf, chunk):
        doc_d, doc_n = _conv_arg(sf.args[0], chunk, "s")
        rest = []
        for i, a in enumerate(sf.args[1:]):
            kind = "s" if (not pairwise or i % 2 == 0) else "r"
            rest.append(_conv_arg(a, chunk, kind))
        m = len(doc_d)
        out = np.full(m, b"", dtype=object)
        nulls = doc_n.copy()
        step = 2 if pairwise else 1
        for r in range(m):
            if nulls[r]:
                continue
            try:
                doc = _json_load(doc_d[r])
                for pi in range(0, len(rest), step):
                    pd, pn = rest[pi]
                    if pn[r]:
                        raise ValueError("null path")
                    toks = _json_path_tokens(pd[r])
                    if pairwise:
                        vd, vn = rest[pi + 1]
                        val = None if vn[r] else _to_json_value(vd[r])
                    else:
                        val = None
                    doc = _json_modify(doc, toks, val, mode)
                out[r] = _json_dump(doc)
            except Exception:
                nulls[r] = True
        return out, nulls
    return ev


_JSON_FUNCS.update({
    "json_set": _json_mut_fn("set"),
    "json_insert": _json_mut_fn("insert"),
    "json_replace": _json_mut_fn("replace"),
    "json_array_append": _json_mut_fn("append"),
    "json_remove": _json_mut_fn("remove", pairwise=False),
    "json_depth": _pyfn("s", lambda b: _json_depth(_json_load(b)), out="i"),
    "json_merge_patch": _pyfn("ss*", lambda *docs: _json_dump(
        _json_merge_patch_all([_json_load(d) for d in docs]))),
    "json_contains_path": _pyfn("sss*", _json_contains_path, out="i"),
})


def _json_valid(b) -> int:
    try:
        _json_load(b)
        return 1
    except (ValueError, UnicodeDecodeError):
        return 0


def _try_json(b):
    if b is None:
        return None  # SQL NULL → JSON null
    try:
        return _json_load(b)
    except Exception:
        return _u(b)


def _json_contains(doc, cand):
    if isinstance(doc, list):
        if isinstance(cand, list):
            return all(_json_contains(doc, c) for c in cand)
        return any(_json_contains(d, cand) for d in doc) or doc == cand
    if isinstance(doc, dict) and isinstance(cand, dict):
        return all(k in doc and _json_contains(doc[k], v)
                   for k, v in cand.items())
    return doc == cand


# -- network / misc ----------------------------------------------------------

def _inet_aton(b):
    parts = _u(b).split(".")
    if not 1 <= len(parts) <= 4:
        return None
    try:
        nums = [int(p) for p in parts]
    except ValueError:
        return None
    if any(not 0 <= n <= 255 for n in nums[:-1]) or nums[-1] < 0:
        return None
    v = 0
    for n in nums[:-1]:
        v = (v << 8) | n
    shift = 8 * (4 - len(parts) + 1)
    if nums[-1] >= (1 << shift):
        return None
    return (v << shift) | nums[-1]


def _is_ipv6(b):
    import ipaddress
    try:
        return int(isinstance(ipaddress.ip_address(_u(b)),
                              ipaddress.IPv6Address))
    except ValueError:
        return 0


_MISC_FUNCS = {
    "is_ipv4": _pyfn("s", lambda b: int(_inet_aton(b) is not None
                                        and _u(b).count(".") == 3), out="i"),
    "is_ipv6": _pyfn("s", _is_ipv6),
    "inet_aton": _pyfn("s", _inet_aton, out="i"),
    "inet_ntoa": _pyfn("i", lambda v: ".".join(
        str((int(v) >> s) & 0xFF) for s in (24, 16, 8, 0)).encode()
        if 0 <= int(v) <= 0xFFFFFFFF else None),
    "sleep": _pyfn("f", lambda v: __import__("time").sleep(
        min(max(v, 0), 5)) or 0, out="i"),
    "uuid": _pyfn("", lambda: str(__import__("uuid").uuid4()).encode()),
}


# -- regexp family (reference: expression/builtin_regexp.go; MySQL 8 ICU
# regexes approximated with Python re) ---------------------------------------

def _re(pat):
    import re
    return re.compile(_u(pat), re.DOTALL)


def _regexp_substr(s, pat, pos=1, occ=1):
    m = None
    it = _re(pat).finditer(_u(s), int(pos) - 1)
    for i, mm in enumerate(it, 1):
        if i == int(occ):
            m = mm
            break
    return m.group(0).encode() if m else None


def _regexp_replace(s, pat, rep, pos=1, occ=0):
    """pos: 1-based start; occ: 0 = replace all from pos, n = only the n-th
    occurrence (reference: builtinRegexpReplace)."""
    txt = _u(s)
    head, tail = txt[:int(pos) - 1], txt[int(pos) - 1:]
    r = _re(pat)
    if int(occ) == 0:
        return (head + r.sub(_u(rep), tail)).encode()
    out = []
    last = 0
    for i, m in enumerate(r.finditer(tail), 1):
        if i == int(occ):
            out.append(tail[last:m.start()])
            out.append(m.expand(_u(rep)))
            last = m.end()
            break
    out.append(tail[last:])
    return (head + "".join(out)).encode()


def _regexp_instr(s, pat, pos=1, occ=1, ret=0):
    for i, mm in enumerate(_re(pat).finditer(_u(s), int(pos) - 1), 1):
        if i == int(occ):
            return mm.end() + 1 if int(ret) else mm.start() + 1
    return 0


_REGEXP_FUNCS = {
    "regexp_like": _pyfn("ss", lambda s, p: int(
        _re(p).search(_u(s)) is not None), out="i"),
    "regexp_replace": _pyfn("sssii", lambda s, p, r, pos=1, occ=0:
                            _regexp_replace(s, p, r, pos, occ)),
    "regexp_substr": _pyfn("ssii", _regexp_substr),
    "regexp_instr": _pyfn("ssiii", _regexp_instr, out="i"),
}


# -- encryption / compression (reference: expression/builtin_encryption.go) --

def _aes_key(key: bytes) -> bytes:
    """MySQL aes key folding: XOR the key into a 16-byte buffer."""
    out = bytearray(16)
    for i, b in enumerate(key):
        out[i % 16] ^= b
    return bytes(out)


def _aes_ecb(data: bytes, key: bytes, encrypt: bool):
    # AES-128-ECB with PKCS7, implemented over the stdlib-free path: a
    # pure-python AES would be slow and long; use hashlib-based fallback is
    # wrong — so implement via the one-block primitives in `cryptography`
    # if present, else a minimal pure-python AES core.
    try:
        from cryptography.hazmat.primitives.ciphers import (
            Cipher, algorithms, modes)
        c = Cipher(algorithms.AES(_aes_key(key)), modes.ECB())
        if encrypt:
            pad = 16 - len(data) % 16
            data = data + bytes([pad]) * pad
            e = c.encryptor()
            return e.update(data) + e.finalize()
        d = c.decryptor()
        out = d.update(data) + d.finalize()
        if not out or not 1 <= out[-1] <= 16:
            return None
        return out[:-out[-1]]
    except ImportError:  # no cipher backend in this image: NULL like MySQL
        return None      # does for malformed input (gated, not stubbed)


def _compress(b: bytes) -> bytes:
    import struct
    import zlib
    if not b:
        return b""
    return struct.pack("<I", len(b)) + zlib.compress(b)


def _uncompress(b: bytes):
    import zlib
    if not b:
        return b""
    if len(b) < 5:
        return None
    try:
        return zlib.decompress(b[4:])
    except zlib.error:
        return None


_CRYPTO_FUNCS = {
    "aes_encrypt": _pyfn("ss", lambda d, k: _aes_ecb(d, k, True)),
    "aes_decrypt": _pyfn("ss", lambda d, k: _aes_ecb(d, k, False)),
    "compress": _pyfn("s", _compress),
    "uncompress": _pyfn("s", _uncompress),
    "uncompressed_length": _pyfn("s", lambda b: (
        0 if not b else int.from_bytes(b[:4], "little")), out="i"),
    "random_bytes": _pyfn("i", lambda n: __import__("os").urandom(
        min(max(int(n), 1), 1024))),
    "password": _pyfn("s", lambda b: (
        "*" + __import__("hashlib").sha1(__import__("hashlib").sha1(
            b).digest()).hexdigest().upper()).encode()),
}


# -- extra string / time / uuid breadth --------------------------------------

def _make_set(bits, *strs):
    out = [(_u(s)) for i, s in enumerate(strs)
           if s is not None and (int(bits) >> i) & 1]
    return ",".join(out).encode()


def _export_set(bits, on, off, sep=b",", width=64):
    parts = [(_u(on) if (int(bits) >> i) & 1 else _u(off))
             for i in range(min(int(width), 64))]
    return _u(sep).join(parts).encode()


def _time_or_dt_secs(b):
    """Seconds for a TIME string, or epoch-seconds for a DATETIME/DATE
    string (TIMEDIFF accepts both forms — reference: builtin_time.go)."""
    s = _u(b).strip()
    if "-" in s.lstrip("-"):
        from ..sqltypes import parse_datetime_str
        return parse_datetime_str(s) / 1_000_000
    return _parse_time_b(b)


def _timediff(a, b):
    return _sec_to_time(_time_or_dt_secs(a) - _time_or_dt_secs(b))


def _tsadd(unit, n, dt):
    import datetime as _dtm
    n = int(n)
    if unit in ("microsecond", "second", "minute", "hour", "day", "week"):
        mult = {"microsecond": 1e-6, "second": 1, "minute": 60,
                "hour": 3600, "day": 86400, "week": 604800}[unit]
        r = dt + _dtm.timedelta(seconds=n * mult)
    else:
        months = {"month": 1, "quarter": 3, "year": 12}[unit] * n
        y = dt.year + (dt.month - 1 + months) // 12
        m = (dt.month - 1 + months) % 12 + 1
        import calendar
        d = min(dt.day, calendar.monthrange(y, m)[1])
        r = dt.replace(year=y, month=m, day=d)
    return r.strftime("%Y-%m-%d %H:%M:%S").encode()


_EXTRA_FUNCS = {
    "octet_length": _pyfn("s", lambda b: len(b), out="i"),
    "make_set": _pyfn("is*", _make_set, null_propagate=False),
    "export_set": _pyfn("isssi", _export_set),
    "timediff": _pyfn("ss", _timediff),
    "timestampadd": _pyfn("sid", lambda unit, n, dt: _tsadd(
        _u(unit).lower(), n, dt)),
    "time": _pyfn("s", lambda b: (
        _u(b).split(" ", 1)[1].encode() if " " in _u(b)
        else _sec_to_time(_parse_time_b(b)))),
    "timestamp": _pyfn("d", lambda dt: dt.strftime(
        "%Y-%m-%d %H:%M:%S").encode()),
    "time_format": _pyfn("ss", lambda t, f: _time_format(t, f)),
    "get_format": _pyfn("ss", lambda k, r: _GET_FORMATS.get(
        (_u(k).lower(), _u(r).lower()))),
    "uuid_short": _pyfn("", lambda: _uuid_short(), out="i"),
    "is_uuid": _pyfn("s", lambda b: _is_uuid(b), out="i"),
    "uuid_to_bin": _pyfn("s", lambda b: (
        __import__("uuid").UUID(_u(b)).bytes if _is_uuid(b) else None)),
    "bin_to_uuid": _pyfn("s", lambda b: (
        str(__import__("uuid").UUID(bytes=bytes(b))).encode()
        if len(b) == 16 else None)),
    "benchmark": _pyfn("if", lambda n, v: 0, out="i"),
    "format_bytes": _pyfn("f", lambda v: _format_bytes(v)),
    "inet6_aton": _pyfn("s", _inet6_aton := (lambda b: (
        lambda ip: ip.packed if ip is not None else None)(
        _ip_or_none(b)))),
    "inet6_ntoa": _pyfn("s", lambda b: _inet6_ntoa(b)),
    "is_ipv4_compat": _pyfn("s", lambda b: int(
        len(b) == 16 and bytes(b[:12]) == b"\x00" * 12
        and bytes(b[12:16]) != b"\x00\x00\x00\x00"), out="i"),
    "is_ipv4_mapped": _pyfn("s", lambda b: int(
        len(b) == 16 and bytes(b[:12]) == b"\x00" * 10 + b"\xff\xff"),
        out="i"),
    "weight_string": _pyfn("s", lambda b: b),  # binary collation weight
}


def _ip_or_none(b):
    import ipaddress
    try:
        return ipaddress.ip_address(_u(b))
    except ValueError:
        return None


def _inet6_ntoa(b):
    import ipaddress
    try:
        if len(b) == 16:
            return str(ipaddress.IPv6Address(bytes(b))).encode()
        if len(b) == 4:
            return str(ipaddress.IPv4Address(bytes(b))).encode()
    except ipaddress.AddressValueError:
        pass
    return None


_UUID_SHORT_STATE = [None]


def _uuid_short():
    import threading
    import time as _t
    if _UUID_SHORT_STATE[0] is None:
        _UUID_SHORT_STATE[0] = [threading.Lock(), int(_t.time()) << 24]
    lock, _v = _UUID_SHORT_STATE[0]
    with lock:
        _UUID_SHORT_STATE[0][1] += 1
        return _UUID_SHORT_STATE[0][1]


def _is_uuid(b) -> int:
    import uuid as _uuid
    try:
        _uuid.UUID(_u(b))
        return 1
    except (ValueError, AttributeError):
        return 0


def _format_bytes(v: float):
    for unit in ("bytes", "KiB", "MiB", "GiB", "TiB", "PiB"):
        if abs(v) < 1024 or unit == "PiB":
            if unit == "bytes":
                return f"{int(v)} {unit}".encode()
            return f"{v:.2f} {unit}".encode()
        v /= 1024
    return None


def _time_format(t, f):
    secs = _parse_time_b(t)
    neg = secs < 0
    v = abs(int(secs))
    h, rem = divmod(v, 3600)
    mnt, sec = divmod(rem, 60)
    out = _u(f)
    for k, s in (("%H", f"{h:02d}"), ("%k", str(h)), ("%i", f"{mnt:02d}"),
                 ("%s", f"{sec:02d}"), ("%S", f"{sec:02d}"),
                 ("%f", "000000"), ("%p", "AM" if h % 24 < 12 else "PM")):
        out = out.replace(k, s)
    return (("-" if neg else "") + out).encode()


_GET_FORMATS = {
    ("date", "iso"): b"%Y-%m-%d", ("date", "usa"): b"%m.%d.%Y",
    ("date", "jis"): b"%Y-%m-%d", ("date", "eur"): b"%d.%m.%Y",
    ("date", "internal"): b"%Y%m%d",
    ("datetime", "iso"): b"%Y-%m-%d %H:%i:%s",
    ("datetime", "usa"): b"%Y-%m-%d %H.%i.%s",
    ("datetime", "jis"): b"%Y-%m-%d %H:%i:%s",
    ("datetime", "eur"): b"%Y-%m-%d %H.%i.%s",
    ("datetime", "internal"): b"%Y%m%d%H%i%s",
    ("time", "iso"): b"%H:%i:%s", ("time", "usa"): b"%h:%i:%s %p",
    ("time", "jis"): b"%H:%i:%s", ("time", "eur"): b"%H.%i.%s",
    ("time", "internal"): b"%H%i%s",
}


# -- breadth batch 2 (r4): the remaining registry gap vs builtin.go:573 ------

def _truncate_num(v, places):
    # Decimal, not float: trunc(0.29 * 100) is 28 in binary floating
    # point — digit-exact truncation needs exact decimal scaling
    from decimal import Decimal, ROUND_DOWN
    q = Decimal(1).scaleb(-int(places))
    return float(Decimal(repr(float(v))).quantize(q, rounding=ROUND_DOWN))


def _interval_fn(n, *bounds):
    if n is None:
        return -1
    i = 0
    for b in bounds:
        if b is not None and float(n) < float(b):
            break
        i += 1
    return i


def _convert_tz(dt, frm, to):
    def off(z):
        z = _u(z).strip().upper()
        if z in ("SYSTEM", "UTC", "GMT"):
            return _dt.timedelta(0)
        if not z.startswith(("+", "-")):
            return None  # offsets must be signed; named zones unsupported
        sign = 1 if z.startswith("+") else -1
        try:
            hh, mm = z[1:].split(":")
            return sign * _dt.timedelta(hours=int(hh), minutes=int(mm))
        except Exception:
            return None
    a, b = off(frm), off(to)
    if a is None or b is None:
        return None
    return (dt - a + b).strftime("%Y-%m-%d %H:%M:%S").encode()


def _to_seconds(dt):
    return ((dt.date() - _dt.date(1, 1, 1)).days + 366) * 86400 + \
        dt.hour * 3600 + dt.minute * 60 + dt.second


def _json_search(doc_b, one_all, target, *rest):
    doc = _json_load(doc_b)
    mode = _u(one_all).lower()
    if mode not in ("one", "all"):
        raise ValueError("json_search mode")
    import re as _re
    # MySQL wildcard semantics: ONLY % and _ are wildcards; everything
    # else (incl. * ? [ ]) is literal
    pat = _re.compile("^" + "".join(
        ".*" if ch == "%" else "." if ch == "_" else _re.escape(ch)
        for ch in _u(target)) + "$", _re.S)
    hits = []

    def rec(v, path):
        if isinstance(v, str) and pat.match(v):
            hits.append(path)
        elif isinstance(v, dict):
            for k, c in v.items():
                rec(c, f'{path}."{k}"' if ("." in k or " " in k)
                    else f"{path}.{k}")
        elif isinstance(v, list):
            for i, c in enumerate(v):
                rec(c, f"{path}[{i}]")
    rec(doc, "$")
    if not hits:
        return None
    if mode == "one":
        return _json.dumps(hits[0]).encode()
    return _json_dump(hits if len(hits) > 1 else hits[0])


def _json_overlaps(a_b, b_b):
    a, b = _json_load(a_b), _json_load(b_b)
    if isinstance(a, dict) and isinstance(b, dict):
        # two objects overlap on any shared key-value PAIR
        return int(any(k in b and b[k] == v for k, v in a.items()))
    la = a if isinstance(a, list) else [a]
    lb = b if isinstance(b, list) else [b]
    return int(any(x == y for x in la for y in lb))


def _json_merge_preserve(*docs):
    def merge(a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            out = dict(a)
            for k, v in b.items():
                out[k] = merge(out[k], v) if k in out else v
            return out
        la = a if isinstance(a, list) else [a]
        lb = b if isinstance(b, list) else [b]
        return la + lb
    cur = _json_load(docs[0])
    for d in docs[1:]:
        cur = merge(cur, _json_load(d))
    return _json_dump(cur)


def _json_array_insert(doc_b, *pairs):
    doc = _json_load(doc_b)
    for i in range(0, len(pairs), 2):
        toks = _json_path_tokens(pairs[i])
        if not toks or toks[-1][0] != "idx":
            raise ValueError("json_array_insert needs an array-cell path")
        val = _to_json_value(pairs[i + 1])
        parent_toks, (_k, pos) = toks[:-1], toks[-1]
        cur = doc
        ok = True
        for t, v in parent_toks:
            if t == "key" and isinstance(cur, dict) and v in cur:
                cur = cur[v]
            elif t == "idx" and isinstance(cur, list) and v < len(cur):
                cur = cur[v]
            else:
                ok = False
                break
        if ok and isinstance(cur, list):
            cur.insert(min(pos, len(cur)), val)
    return _json_dump(doc)


def _json_value(doc_b, path_b):
    doc = _json_load(doc_b)
    v, ok = _json_path_get(doc, path_b)
    if not ok or v is None:
        return None
    if isinstance(v, (dict, list)):
        return _json_dump(v)
    if isinstance(v, bool):
        return b"true" if v else b"false"
    return str(v).encode()


def _password_strength(p):
    s = _u(p)
    if len(s) < 4:
        return 0
    if len(s) < 8:
        return 25
    score = 25
    if any(c.isdigit() for c in s):
        score += 25
    if any(c.islower() for c in s) and any(c.isupper() for c in s):
        score += 25
    if any(not c.isalnum() for c in s):
        score += 25
    return score


_MORE_FUNCS = {
    "truncate": _pyfn("fi", _truncate_num, out="f"),
    "interval": _pyfn("ff*", _interval_fn, out="i",
                      null_propagate=False),
    "convert_tz": _pyfn("dss", _convert_tz),
    "to_seconds": _pyfn("d", _to_seconds, out="i"),
    "utc_date": _pyfn("", lambda: _dt.datetime.utcnow().strftime(
        "%Y-%m-%d").encode()),
    "utc_time": _pyfn("", lambda: _dt.datetime.utcnow().strftime(
        "%H:%M:%S").encode()),
    "json_search": _pyfn("sss*", _json_search),
    "json_overlaps": _pyfn("ss", _json_overlaps, out="i"),
    "json_pretty": _pyfn("s", lambda b: _json.dumps(
        _json_load(b), indent=2, ensure_ascii=False).encode()),
    "json_storage_size": _pyfn("s", lambda b: len(_json.dumps(
        _json_load(b), separators=(",", ":"))), out="i"),
    "json_merge_preserve": _pyfn("ss*", _json_merge_preserve),
    "json_array_insert": _pyfn("ssr*", _json_array_insert),
    "json_member_of": _pyfn("ss", lambda v, arr: int(
        _json_load(v) in (lambda a: a if isinstance(a, list) else [a])(
            _json_load(arr))), out="i"),
    "json_value": _pyfn("ss", _json_value),
    # name_const/any_value resolve in the BUILDER (to the value
    # expression itself) — no dispatch entries, one implementation
    "load_file": _pyfn("s", lambda _p: None),  # FILE priv never granted
    "validate_password_strength": _pyfn("s", _password_strength, out="i"),
    "charset": _pyfn("r", lambda _v: b"utf8mb4"),
    "collation": _pyfn("r", lambda _v: b"utf8mb4_bin"),
    "coercibility": _pyfn("r", lambda _v: 2, out="i"),
}

# -- advisory locks (reference: builtin_miscellaneous.go GET_LOCK et al.;
# single-process engine = the cross-session lock table IS process-global) --

import threading as _threading

_USER_LOCKS: dict = {}          # name -> (owner token, count)
_USER_LOCKS_MU = _threading.Lock()

#: current lock owner: the SESSION sets its identity here around each
#: statement (session.execute) — advisory locks are per-connection in
#: MySQL, and an in-process embedding serves many sessions per thread
_LOCK_OWNER = _threading.local()


def set_lock_owner(token):
    _LOCK_OWNER.token = token


def _owner():
    return getattr(_LOCK_OWNER, "token", None) or _threading.get_ident()


def _get_lock(name, _timeout):
    me = _owner()
    with _USER_LOCKS_MU:
        cur = _USER_LOCKS.get(_u(name))
        if cur is None or cur[0] == me:
            _USER_LOCKS[_u(name)] = (me, (cur[1] + 1) if cur else 1)
            return 1
    return 0  # held elsewhere; no blocking wait (timeout honored as 0)


def _release_lock(name):
    me = _owner()
    with _USER_LOCKS_MU:
        cur = _USER_LOCKS.get(_u(name))
        if cur is None:
            return None
        if cur[0] != me:
            return 0
        if cur[1] > 1:
            _USER_LOCKS[_u(name)] = (me, cur[1] - 1)
        else:
            del _USER_LOCKS[_u(name)]
        return 1


def _release_all_locks():
    me = _owner()
    with _USER_LOCKS_MU:
        mine = [k for k, (o, _c) in _USER_LOCKS.items() if o == me]
        n = sum(_USER_LOCKS[k][1] for k in mine)
        for k in mine:
            del _USER_LOCKS[k]
    return n


def _is_free_lock(name):
    with _USER_LOCKS_MU:
        return int(_u(name) not in _USER_LOCKS)


def _is_used_lock(name):
    with _USER_LOCKS_MU:
        cur = _USER_LOCKS.get(_u(name))
        return cur[0] if cur else None


def _date_arith_std(dt, n, unit, sign):
    """DATE_ADD/DATE_SUB as standalone registry entries (the parser's
    INTERVAL syntax routes through core._eval_date_arith; these serve the
    function-call forms)."""
    unit = _u(unit).lower() if isinstance(unit, (bytes, bytearray)) else unit
    days = {"day": 1, "week": 7}.get(unit)
    if days is not None:
        out = dt + _dt.timedelta(days=sign * int(n) * days)
    elif unit in ("hour", "minute", "second"):
        out = dt + _dt.timedelta(**{unit + "s": sign * int(n)})
    elif unit in ("month", "quarter", "year"):
        months = sign * int(n) * {"month": 1, "quarter": 3, "year": 12}[unit]
        y = dt.year + (dt.month - 1 + months) // 12
        m = (dt.month - 1 + months) % 12 + 1
        d = min(dt.day, calendar.monthrange(y, m)[1])
        out = dt.replace(year=y, month=m, day=d)
    else:
        return None
    if (dt.hour, dt.minute, dt.second) == (0, 0, 0) and unit in (
            "day", "week", "month", "quarter", "year"):
        return out.strftime("%Y-%m-%d").encode()
    return out.strftime("%Y-%m-%d %H:%M:%S").encode()


def _gtid_parse(s):
    """'uuid:1-5:8,uuid2:3' → {uuid: set of txn ids} (reference:
    builtin_miscellaneous.go gtidSubset — MySQL GTID set algebra)."""
    out = {}
    for part in _u(s).replace("\n", "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        sid = bits[0].strip().lower()
        ids = out.setdefault(sid, set())
        for rng in bits[1:]:
            if "-" in rng:
                lo, hi = rng.split("-")
                ids.update(range(int(lo), int(hi) + 1))
            else:
                ids.add(int(rng))
    return out


def _gtid_subset(a, b):
    ga, gb = _gtid_parse(a), _gtid_parse(b)
    return int(all(ids <= gb.get(sid, set()) for sid, ids in ga.items()))


def _gtid_format(g):
    parts = []
    for sid in sorted(g):
        ids = sorted(g[sid])
        if not ids:
            continue
        rngs = []
        lo = prev = ids[0]
        for v in ids[1:] + [None]:
            if v is not None and v == prev + 1:
                prev = v
                continue
            rngs.append(f"{lo}-{prev}" if prev > lo else f"{lo}")
            if v is not None:
                lo = prev = v
        parts.append(sid + ":" + ":".join(rngs))
    return ",".join(parts).encode()


def _gtid_subtract(a, b):
    ga, gb = _gtid_parse(a), _gtid_parse(b)
    return _gtid_format({sid: ids - gb.get(sid, set())
                         for sid, ids in ga.items()})


def _tidb_decode_key(hexkey):
    """Hex-encoded engine key → JSON description (reference:
    expression/builtin_info.go tidbDecodeKey over tablecodec layouts)."""
    from ..tablecodec import (INDEX_SEP, _dec_i64, decode_index_values,
                              decode_record_key)
    raw = binascii.unhexlify(hexkey)
    try:
        tid, h = decode_record_key(raw)
        return _json.dumps({"table_id": tid, "handle": h}).encode()
    except Exception:
        pass
    try:
        if INDEX_SEP in raw:
            tid = _dec_i64(raw[1:9])
            iid = _dec_i64(raw[11:19])
            vals = decode_index_values(raw)
            return _json.dumps({
                "table_id": tid, "index_id": iid,
                "index_vals": [repr(v) for v in vals]}).encode()
    except Exception:
        pass
    return hexkey


def _translate(s, frm, to):
    """Per-character mapping; characters in `frm` beyond len(to) are
    DELETED (Oracle semantics the reference implements)."""
    src = _u(s)
    f = _u(frm)
    t = _u(to)
    table = {}
    for i, ch in enumerate(f):
        if ord(ch) not in table:  # first occurrence in `from` wins
            table[ord(ch)] = t[i] if i < len(t) else None
    return src.translate(table).encode()


def _eval_decode_sql_digests(sf, chunk):
    """JSON array of digests → JSON array of normalized sample SQL (null
    for unknown digests), resolved via the statements summary the builder
    attached as extra (reference: builtin_info.go tidbDecodeSQLDigests)."""
    import json as _json
    d, nl = sf.args[0].eval(chunk)
    n = len(d)
    out = np.empty(n, dtype=object)
    out[:] = b""
    nulls = np.array(nl, dtype=bool, copy=True)
    summary = getattr(sf, "extra", None)  # digest -> StmtSummary
    for i in range(n):
        if nulls[i]:
            continue
        try:
            digests = _json.loads(_u(d[i]))
            if not isinstance(digests, list):
                raise ValueError
        except Exception:
            nulls[i] = True
            continue
        res = []
        for dg in digests:
            st = summary.get(str(dg)) if summary is not None else None
            res.append(st.sample_sql if st is not None else None)
        out[i] = _json.dumps(res).encode()
    return out, nulls


_TIDB_FUNCS = {
    # reference-dialect admin builtins (expression/builtin_info.go)
    "tidb_version": _pyfn("", lambda: b"8.0.11-tpu-htap"),
    "tidb_is_ddl_owner": _pyfn("", lambda: 1, out="i"),
    # TSO = (ms since epoch) << 18 | logical (reference:
    # builtin_info.go tidbParseTso)
    "tidb_parse_tso": _pyfn("i", lambda tso: None if tso <= 0 else
                            _dt.datetime.fromtimestamp(
                                (int(tso) >> 18) / 1000.0).strftime(
                                "%Y-%m-%d %H:%M:%S.%f").encode()),
    "tidb_decode_key": _pyfn("s", lambda k: _tidb_decode_key(k)),
    "master_pos_wait": _pyfn("ssi", lambda _f, _p, _t: None,
                             null_propagate=False),
    "tidb_shard": _pyfn("i", lambda v: hash(int(v)) % 256, out="i"),
    "format_nano_time": _pyfn("f", lambda ns: (
        f"{ns:.0f}ns" if ns < 1e3 else f"{ns / 1e3:.2f}µs" if ns < 1e6
        else f"{ns / 1e6:.2f}ms" if ns < 1e9
        else f"{ns / 1e9:.2f}s").encode()),
    "gtid_subset": _pyfn("ss", _gtid_subset, out="i"),
    "gtid_subtract": _pyfn("ss", _gtid_subtract),
    "wait_for_executed_gtid_set": _pyfn("sf", lambda _g, *_t: 0, out="i"),
    "tidb_encode_sql_digest": _pyfn("s", lambda sql: __import__(
        "tidb_tpu.parser.digester", fromlist=["digest"]).digest(
        _u(sql)).encode()),
    "get_lock": _pyfn("si", _get_lock, out="i"),
    "release_lock": _pyfn("s", _release_lock, out="i"),
    "release_all_locks": _pyfn("", _release_all_locks, out="i"),
    "ps_current_thread_id": _pyfn("", lambda: _threading.get_ident()
                                  & 0xFFFFFFFF, out="i"),
    "is_free_lock": _pyfn("s", _is_free_lock, out="i"),
    "is_used_lock": _pyfn("s", _is_used_lock, out="i"),
    # date_add/date_sub/adddate/subdate reach the engine through the
    # parser's INTERVAL grammar -> core date_arith; _date_arith_std backs
    # the month-clamp tests directly
    "date_arith_fn": _pyfn("dis", lambda dt, n, u: _date_arith_std(
        dt, n, u, 1)),
    "localtime": _pyfn("", lambda: _dt.datetime.now().strftime(
        "%Y-%m-%d %H:%M:%S").encode()),
    "localtimestamp": _pyfn("", lambda: _dt.datetime.now().strftime(
        "%Y-%m-%d %H:%M:%S").encode()),
    "current_time": _pyfn("", lambda: _dt.datetime.now().strftime(
        "%H:%M:%S").encode()),
    # TRANSLATE(str, from, to) — per-character mapping (reference:
    # builtin_string.go translate, Oracle-compat mode)
    "translate": _pyfn("sss", _translate),
    # bounded-staleness resolver (reference: builtin_time.go
    # tidb_bounded_staleness): the freshest safe ts within [lo, hi] — a
    # single-node store is always resolved, so clamp now() into the range
    "tidb_bounded_staleness": _pyfn("dd", lambda lo, hi: max(
        lo, min(hi, _dt.datetime.now())).strftime(
        "%Y-%m-%d %H:%M:%S.%f").encode()),
    # plan/digest decoders (reference: builtin_info.go tidbDecodePlan /
    # tidbDecodeSQLDigests) — plans are stored plain here, so decode is
    # identity; digests resolve through the statements summary
    "tidb_decode_plan": _pyfn("s", lambda p: p),
    "tidb_decode_sql_digests": _eval_decode_sql_digests,
    # IS TRUE with NULL propagation (reference: builtin_op.go
    # isTrueWithNull — unlike IS TRUE, NULL stays NULL)
    "istrue_with_null": _pyfn("f", lambda v: 1 if v != 0 else 0, out="i"),
}

#: pure aliases — separate registry entries in the reference too
#: (builtin.go maps lcase/ucase/... onto the same function classes)
_ALIASES = {
    "ceiling": "ceil", "power": "pow", "lcase": "lower", "ucase": "upper",
    "mid": "substring", "substr": "substring", "sha": "sha1",
    "json_merge": "json_merge_preserve", "day": "dayofmonth",
    "json_append": "json_array_append", "curtime": "current_time",
    "character_length": "char_length",
}


def register_all():
    for table in (_STRING_FUNCS, _MATH_FUNCS, _DATE_FUNCS, _JSON_FUNCS,
                  _MISC_FUNCS, _REGEXP_FUNCS, _CRYPTO_FUNCS, _EXTRA_FUNCS,
                  _MORE_FUNCS, _TIDB_FUNCS):
        for name, fn in table.items():
            _DISPATCH.setdefault(name, fn)
    for alias, target in _ALIASES.items():
        if target is not None and target in _DISPATCH:
            _DISPATCH.setdefault(alias, _DISPATCH[target])


register_all()
