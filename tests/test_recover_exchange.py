"""Delayed delete-ranges: RECOVER/FLASHBACK TABLE and EXCHANGE PARTITION
(reference: ddl/delete_range.go, ddl_api.go RecoverTable,
partition.go onExchangeTablePartition, gc_worker.go:691 deleteRanges)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


class TestRecoverTable:
    def test_recover_restores_schema_and_data(self, tk):
        tk.must_exec("create table t (id int primary key, v varchar(6), "
                     "key iv (v))")
        tk.must_exec("insert into t values (1,'a'),(2,'b')")
        tk.must_exec("drop table t")
        assert "doesn't exist" in str(tk.exec_error("select * from t"))
        tk.must_exec("recover table t")
        tk.must_query("select v from t order by id").check([("a",), ("b",)])
        # index survives too
        tk.must_query("select id from t where v = 'b'").check([("2",)])
        # the table is fully writable again
        tk.must_exec("insert into t values (3, 'c')")
        tk.must_query("select count(*) from t").check([("3",)])

    def test_flashback_to_new_name(self, tk):
        tk.must_exec("create table t (id int primary key)")
        tk.must_exec("insert into t values (7)")
        tk.must_exec("drop table t")
        tk.must_exec("flashback table t to t2")
        tk.must_query("select id from t2").check([("7",)])
        assert "doesn't exist" in str(tk.exec_error("select * from t"))

    def test_recover_blocked_when_name_taken(self, tk):
        tk.must_exec("create table t (id int primary key)")
        tk.must_exec("drop table t")
        tk.must_exec("create table t (x int)")
        e = tk.exec_error("recover table t")
        assert "already exists" in str(e)
        tk.must_exec("flashback table t to t_old")  # rename form still works

    def test_partitioned_table_recovers(self, tk):
        tk.must_exec("create table p (a int) partition by hash (a) "
                     "partitions 2")
        tk.must_exec("insert into p values (1),(2),(3)")
        tk.must_exec("drop table p")
        tk.must_exec("recover table p")
        tk.must_query("select count(*) from p").check([("3",)])

    def test_gc_makes_recovery_impossible_and_purges(self, tk):
        tk.must_exec("create table g (id int primary key)")
        tk.must_exec("insert into g values (9)")
        tk.must_exec("drop table g")
        store = tk.session.store
        res = tk.session.domain.gc_worker.run_once(
            safe_point=store.next_ts())
        assert res["delete_ranges"] >= 2  # record + index ranges
        e = tk.exec_error("recover table g")
        assert "GC safe point" in str(e)

    def test_drop_before_safepoint_survives_gc(self, tk):
        """A drop NEWER than the safepoint stays recoverable after a GC
        round."""
        tk.must_exec("create table keepme (id int primary key)")
        tk.must_exec("insert into keepme values (1)")
        store = tk.session.store
        sp = store.next_ts()
        tk.must_exec("drop table keepme")  # drop_ts > sp
        tk.session.domain.gc_worker.run_once(safe_point=sp)
        tk.must_exec("recover table keepme")
        tk.must_query("select id from keepme").check([("1",)])


class TestExchangePartition:
    def test_swap_is_o1_and_bidirectional(self, tk):
        tk.must_exec("create table pt (a int, v int) "
                     "partition by range (a) "
                     "(partition p0 values less than (10), "
                     "partition p1 values less than (20))")
        tk.must_exec("insert into pt values (1, 100), (15, 200)")
        tk.must_exec("create table swap (a int, v int)")
        tk.must_exec("insert into swap values (5, 999)")
        tk.must_exec("alter table pt exchange partition p0 with table swap")
        tk.must_query("select v from pt order by a").check(
            [("999",), ("200",)])
        tk.must_query("select v from swap").check([("100",)])
        # swap back
        tk.must_exec("alter table pt exchange partition p0 with table swap")
        tk.must_query("select v from pt order by a").check(
            [("100",), ("200",)])

    def test_validation_rejects_out_of_range_rows(self, tk):
        tk.must_exec("create table pt (a int, v int) "
                     "partition by range (a) "
                     "(partition p0 values less than (10), "
                     "partition p1 values less than (20))")
        tk.must_exec("create table bad (a int, v int)")
        tk.must_exec("insert into bad values (50, 1)")  # outside p0
        e = tk.exec_error(
            "alter table pt exchange partition p0 with table bad")
        assert "does not match the partition" in str(e)
        # WITHOUT VALIDATION skips the scan (operator's responsibility)
        tk.must_exec("alter table pt exchange partition p0 with table bad "
                     "without validation")
        # WITH VALIDATION parses too
        tk.must_exec("alter table pt exchange partition p0 with table bad "
                     "with validation")

    def test_index_set_must_match(self, tk):
        tk.must_exec("create table pt (a int, v int) partition by hash (a) "
                     "partitions 2")
        tk.must_exec("create table noidx (a int, v int, key iv (v))")
        e = tk.exec_error(
            "alter table pt exchange partition p0 with table noidx")
        assert "different definitions" in str(e)

    def test_exchange_preserves_autoincrement(self, tk):
        tk.must_exec("create table pt (id int primary key auto_increment, "
                     "v int) partition by hash (id) partitions 2")
        tk.must_exec("create table sw (id int primary key auto_increment, "
                     "v int)")
        tk.must_exec("insert into sw (v) values (1), (2), (3)")
        tk.must_exec("alter table pt exchange partition p0 with table sw "
                     "without validation")
        # the exchanged-out table keeps allocating past its old rows
        tk.must_exec("insert into sw (v) values (4)")
        ids = [int(r[0]) for r in tk.must_query(
            "select id from sw order by id").rows]
        assert ids[-1] >= 4 and len(ids) == len(set(ids))

    def test_exchange_requires_privs_on_other_table(self, tk):
        tk.must_exec("create table pt (a int) partition by hash (a) "
                     "partitions 2")
        tk.must_exec("create table victim (a int)")
        tk.must_exec("create user 'alt'@'%'")
        tk.must_exec("grant select, alter on test.pt to 'alt'@'%'")
        tk2 = tk.new_session()
        tk2.session.user = "alt@%"
        e = tk2.exec_error(
            "alter table pt exchange partition p0 with table victim")
        assert "denied" in str(e).lower()

    def test_recover_requires_privs(self, tk):
        tk.must_exec("create table secret (id int primary key)")
        tk.must_exec("drop table secret")
        tk.must_exec("create user 'nop'@'%'")
        tk.must_exec("grant select on test.* to 'nop'@'%'")
        tk2 = tk.new_session()
        tk2.session.user = "nop@%"
        e = tk2.exec_error("flashback table secret to mine")
        assert "denied" in str(e).lower()

    def test_schema_mismatch_rejected(self, tk):
        tk.must_exec("create table pt (a int) partition by hash (a) "
                     "partitions 2")
        tk.must_exec("create table bad (a int, extra varchar(4))")
        e = tk.exec_error(
            "alter table pt exchange partition p0 with table bad")
        assert "different definitions" in str(e)

    def test_partitioned_exchange_target_rejected(self, tk):
        tk.must_exec("create table pt (a int) partition by hash (a) "
                     "partitions 2")
        tk.must_exec("create table pt2 (a int) partition by hash (a) "
                     "partitions 2")
        e = tk.exec_error(
            "alter table pt exchange partition p0 with table pt2")
        assert "plain base table" in str(e)
