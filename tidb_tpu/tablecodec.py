"""Table/index key-value layout + row value codec.

Key layout mirrors the reference (tablecodec/tablecodec.go:49-51,86,104):

    record key:  t{tableID}_r{handle}          (ints memcomparable-encoded)
    index key:   t{tableID}_i{indexID}{vals...}[{handle}]

Row values use a compact varint format playing the role of row format v2
(reference: util/rowcodec/common.go): sorted column IDs, per-column type tag.
Values are *internal* representations (decimal already scaled-int, dates as
day numbers), so decode is allocation-light and columnar assembly is a
straight loop.
"""

from __future__ import annotations

import struct

from .utils import codec

TABLE_PREFIX = b"t"
RECORD_SEP = b"_r"
INDEX_SEP = b"_i"
META_PREFIX = b"m"


def _enc_i64(v: int) -> bytes:
    return struct.pack(">Q", (v & 0xFFFFFFFFFFFFFFFF) ^ 0x8000000000000000)


def _dec_i64(b: bytes) -> int:
    (u,) = struct.unpack(">Q", b)
    v = u ^ 0x8000000000000000
    return v - (1 << 64) if v >= 1 << 63 else v


def record_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + _enc_i64(table_id) + RECORD_SEP


def record_key(table_id: int, handle: int) -> bytes:
    return record_prefix(table_id) + _enc_i64(handle)


def decode_record_key(key: bytes):
    """-> (table_id, handle); raises ValueError if not a record key."""
    if not key.startswith(TABLE_PREFIX) or key[9:11] != RECORD_SEP:
        raise ValueError("not a record key")
    return _dec_i64(key[1:9]), _dec_i64(key[11:19])


#: unique-index entries (no handle in the key) store the handle in the value
#: as b"u<decimal>"; handle-suffixed entries store the b"0" marker. The "u"
#: tag disambiguates handle 0 from the marker (reference: tablecodec encodes
#: the handle as a fixed 8-byte value — same role, printable here).
INDEX_VALUE_MARKER = b"0"


def encode_index_handle(handle: int) -> bytes:
    return b"u%d" % handle


def decode_index_handle(value: bytes):
    """-> handle int for a unique entry value, None for the b"0" marker."""
    if value[:1] == b"u":
        return int(value[1:])
    return None


def index_prefix(table_id: int, index_id: int) -> bytes:
    return TABLE_PREFIX + _enc_i64(table_id) + INDEX_SEP + _enc_i64(index_id)


def index_key(table_id: int, index_id: int, values, handle: int | None = None) -> bytes:
    """Unique index leaves handle out of the key (stored in value); non-unique
    appends it for uniqueness (reference: tablecodec EncodeIndexSeekKey)."""
    key = index_prefix(table_id, index_id) + codec.encode_key(values)
    if handle is not None:
        buf = bytearray()
        codec.encode_int(buf, handle)
        key += bytes(buf)
    return key


def decode_index_values(key: bytes):
    """Strip the prefix, decode datums (last may be the handle)."""
    return codec.decode_key(key[19:])


def table_range(table_id: int):
    """Whole-table record range [start, end)."""
    return record_prefix(table_id), record_prefix(table_id) + b"\xff" * 9


def index_range(table_id: int, index_id: int):
    p = index_prefix(table_id, index_id)
    return p, p + b"\xff" * 16


# -- row value codec --------------------------------------------------------

_T_NULL = 0
_T_INT = 1
_T_FLOAT = 2
_T_BYTES = 3

ROW_VERSION = 128  # row format version tag (reference: rowcodec CodecVer=128)


def encode_row(col_ids, values) -> bytes:
    """Encode parallel lists of column IDs and internal values."""
    buf = bytearray([ROW_VERSION])
    pairs = sorted(zip(col_ids, values))
    codec.write_uvarint(buf, len(pairs))
    for cid, v in pairs:
        codec.write_uvarint(buf, cid)
        if v is None:
            buf.append(_T_NULL)
        elif isinstance(v, bool):
            buf.append(_T_INT)
            codec.write_varint(buf, int(v))
        elif isinstance(v, int):
            buf.append(_T_INT)
            codec.write_varint(buf, v)
        elif isinstance(v, float):
            buf.append(_T_FLOAT)
            buf += struct.pack("<d", v)
        elif isinstance(v, (bytes, bytearray)):
            buf.append(_T_BYTES)
            codec.write_uvarint(buf, len(v))
            buf += v
        elif isinstance(v, str):
            b = v.encode("utf-8")
            buf.append(_T_BYTES)
            codec.write_uvarint(buf, len(b))
            buf += b
        else:
            raise TypeError(f"cannot encode row datum {type(v)}")
    return bytes(buf)


def decode_row(data: bytes) -> dict:
    """-> {col_id: value}."""
    if not data:
        return {}
    if data[0] != ROW_VERSION:
        raise ValueError(f"bad row version {data[0]}")
    pos = 1
    n, pos = codec.read_uvarint(data, pos)
    out = {}
    for _ in range(n):
        cid, pos = codec.read_uvarint(data, pos)
        tag = data[pos]
        pos += 1
        if tag == _T_NULL:
            out[cid] = None
        elif tag == _T_INT:
            v, pos = codec.read_varint(data, pos)
            out[cid] = v
        elif tag == _T_FLOAT:
            (out[cid],) = struct.unpack("<d", data[pos:pos + 8])
            pos += 8
        elif tag == _T_BYTES:
            ln, pos = codec.read_uvarint(data, pos)
            out[cid] = bytes(data[pos:pos + ln])
            pos += ln
        else:
            raise ValueError(f"bad row tag {tag}")
    return out
