"""The MPP engine through the FULL SQL path: `set tidb_executor_engine =
'tpu-mpp'` routes eligible scan/join/agg fragments onto the 8-device
virtual CPU mesh (conftest) as ONE shard_map-jitted SPMD program —
sharded fact scan, broadcast dimension joins, partial aggregation,
all_gather exchange, replicated final merge.

Each test asserts host-engine parity AND (for eligible shapes) that the
mesh path actually executed, via mpp_exec.MPP_STATS — silent fallback
to the single-chip or host path would otherwise pass parity trivially.
Reference: planner/core/fragment.go:37,64 (fragments at exchange
boundaries), store/copr/mpp.go:65, executor/mpp_gather.go:102."""

import pytest

from tidb_tpu.executor.mpp_exec import MPP_STATS

from test_tpch import make_tpch_tk


@pytest.fixture(scope="module")
def tk():
    t = make_tpch_tk(db="tpch_mpp")
    t.must_exec("set tidb_mpp_devices = 8")
    return t


def mpp_vs_host(tk, sql, expect_mpp=True):
    tk.must_exec("set tidb_executor_engine = 'host'")
    host = tk.must_query(sql).rows
    before = MPP_STATS["fragments"]
    tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
    mpp = tk.must_query(sql).rows
    ran_mpp = MPP_STATS["fragments"] - before
    tk.must_exec("set tidb_executor_engine = 'auto'")
    assert host == mpp, (f"mpp/host divergence\nhost({len(host)}): "
                         f"{host[:5]}\nmpp({len(mpp)}): {mpp[:5]}")
    if expect_mpp:
        assert ran_mpp > 0, "query never reached the mesh path"
    return host


def test_q1_scan_agg(tk):
    rows = mpp_vs_host(tk, """
        select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
               sum(l_extendedprice) as sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
               avg(l_quantity) as avg_qty, count(1) as count_order
        from lineitem where l_shipdate <= '1998-09-02'
        group by l_returnflag, l_linestatus
        order by l_returnflag, l_linestatus""")
    assert rows


def test_q6_global_agg(tk):
    rows = mpp_vs_host(tk, """
        select sum(l_extendedprice * l_discount) as revenue from lineitem
        where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01'
          and l_discount between 0.02 and 0.08 and l_quantity < 24""")
    assert len(rows) == 1


def test_q3_join_agg(tk):
    mpp_vs_host(tk, """
        select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as rev,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < '1995-03-15'
          and l_shipdate > '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by rev desc, o_orderdate limit 10""")


def test_q5_multiway_join_agg(tk):
    mpp_vs_host(tk, """
        select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
        from customer, orders, lineitem, supplier, nation, region
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and l_suppkey = s_suppkey and c_nationkey = s_nationkey
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'ASIA' and o_orderdate >= '1994-01-01'
          and o_orderdate < date_add('1994-01-01', interval 1 year)
        group by n_name order by revenue desc""")


def test_q9_expr_group_key(tk):
    mpp_vs_host(tk, """
        select nationx, o_year, sum(amount) as sum_profit
        from (select n_name as nationx, year(o_orderdate) as o_year,
                     l_extendedprice * (1 - l_discount)
                     - ps_supplycost * l_quantity as amount
              from part, supplier, lineitem, partsupp, orders, nation
              where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
                and ps_partkey = l_partkey and p_partkey = l_partkey
                and o_orderkey = l_orderkey and s_nationkey = n_nationkey
                and p_name like '%thing%'
             ) as profit
        group by nationx, o_year order by nationx, o_year desc""")


def test_q10_wide_group_keys(tk):
    mpp_vs_host(tk, """
        select c_custkey, c_name,
               sum(l_extendedprice * (1 - l_discount)) as revenue,
               c_acctbal, n_name, c_address, c_phone, c_comment
        from customer, orders, lineitem, nation
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and o_orderdate >= '1993-10-01'
          and o_orderdate < date_add('1993-10-01', interval 3 month)
          and l_returnflag = 'R' and c_nationkey = n_nationkey
        group by c_custkey, c_name, c_acctbal, c_phone, n_name,
                 c_address, c_comment
        order by revenue desc limit 20""")


def test_q18_semi_join_fallback(tk):
    """Q18's IN-subquery becomes a semi join — outside the broadcast-MPP
    fragment language, so it must FALL BACK cleanly with exact parity
    (the subquery's own group-by still rides the mesh)."""
    mpp_vs_host(tk, """
        select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity)
        from customer, orders, lineitem
        where o_orderkey in (select l_orderkey from lineitem
                             group by l_orderkey
                             having sum(l_quantity) > 100)
          and c_custkey = o_custkey and o_orderkey = l_orderkey
        group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        order by o_totalprice desc, o_orderdate limit 100""",
        expect_mpp=False)


def test_min_max_first_aggs(tk):
    mpp_vs_host(tk, """
        select l_returnflag, min(l_quantity), max(l_extendedprice),
               min(l_shipdate), max(l_shipdate), count(l_comment)
        from lineitem group by l_returnflag order by l_returnflag""")


def test_agg_retry_capacity_overflow(tk):
    """High-cardinality group key forces the bounded partial state to
    overflow and the host to retry with doubled capacity."""
    before = MPP_STATS["fragments"]
    mpp_vs_host(tk, """
        select l_orderkey, l_linenumber, count(1), sum(l_quantity)
        from lineitem group by l_orderkey, l_linenumber
        order by l_orderkey, l_linenumber limit 50""")
    assert MPP_STATS["fragments"] > before


class TestShuffleJoin:
    """Hash-shuffle (all_to_all) MPP join, SQL-reachable: when the build
    side exceeds tidb_broadcast_join_threshold_count, BOTH sides are
    hash-repartitioned over the mesh by join key before the local join
    (reference: planner/core/fragment.go Hash exchange type,
    store/copr/mpp.go:65; exhaust_physical_plans.go broadcast-vs-shuffle
    by build size)."""

    def _shuffle_vs_host(self, tk, sql, threshold):
        tk.must_exec("set tidb_executor_engine = 'host'")
        host = tk.must_query(sql).rows
        before = MPP_STATS["shuffle_joins"]
        tk.must_exec(f"set tidb_broadcast_join_threshold_count = {threshold}")
        tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
        try:
            mpp = tk.must_query(sql).rows
        finally:
            tk.must_exec("set tidb_executor_engine = 'auto'")
            tk.must_exec("set tidb_broadcast_join_threshold_count = 10240")
        ran = MPP_STATS["shuffle_joins"] - before
        assert host == mpp, (f"shuffle/host divergence\nhost({len(host)}): "
                             f"{host[:5]}\nmpp({len(mpp)}): {mpp[:5]}")
        return ran

    def test_q18_shape_fact_fact_shuffle(self, tk):
        # lineitem |><| orders, both above the (lowered) threshold: the
        # Q18 inner join shape the broadcast path cannot afford at scale
        ran = self._shuffle_vs_host(tk, """
            select o_orderstatus, count(1), sum(l_quantity)
            from orders, lineitem where o_orderkey = l_orderkey
            group by o_orderstatus order by o_orderstatus""", threshold=50)
        assert ran > 0, "build side above threshold never took shuffle"

    def test_below_threshold_stays_broadcast(self, tk):
        ran = self._shuffle_vs_host(tk, """
            select o_orderstatus, count(1), sum(l_quantity)
            from orders, lineitem where o_orderkey = l_orderkey
            group by o_orderstatus order by o_orderstatus""",
            threshold=1000000)
        assert ran == 0, "tiny build side must stay broadcast"

    def test_shuffle_with_filters_and_dims(self, tk):
        # shuffle bottom join + broadcast dimension above it + leaf conds
        # (pre-exchange filters) — the Q3-with-big-orders shape
        ran = self._shuffle_vs_host(tk, """
            select c_mktsegment, sum(l_extendedprice * (1 - l_discount))
            from customer, orders, lineitem
            where c_custkey = o_custkey and l_orderkey = o_orderkey
              and l_shipdate > '1995-03-15'
            group by c_mktsegment order by c_mktsegment""", threshold=50)
        assert ran > 0

    def test_shuffle_multi_key_join(self, tk):
        ran = self._shuffle_vs_host(tk, """
            select count(1), sum(ps_availqty)
            from partsupp, lineitem
            where ps_partkey = l_partkey and ps_suppkey = l_suppkey""",
            threshold=40)
        assert ran > 0


def test_q18_full_shape_on_mesh(tk):
    """The complete Q18 shape — semi-filter subquery + 3-table join +
    wide group keys + TopN — end-to-end with the mesh engine selected
    (VERDICT r3 #7). The outer join+agg fragment must execute on the
    mesh; the ORDER BY/LIMIT runs over the replicated merged result."""
    rows = mpp_vs_host(tk, """
        select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity)
        from customer, orders, lineitem
        where o_orderkey in (select l_orderkey from lineitem
                             group by l_orderkey
                             having sum(l_quantity) > 60)
          and c_custkey = o_custkey and o_orderkey = l_orderkey
        group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        order by o_totalprice desc, o_orderdate limit 20""")
    assert rows


class TestSkewExchange:
    def test_adversarial_skew_falls_back_to_broadcast(self, tk):
        """A hash exchange with one key owning half the build rows would
        funnel half the table into one shard's bucket; the host-side skew
        guard (join-index max_cnt vs the even share) must route the join
        to the Broadcast exchange instead — and parity must hold."""
        tk.must_exec("create table skewb (k bigint, v bigint)")
        vals = ",".join(
            f"({1 if i % 2 == 0 else i}, {i})" for i in range(800))
        tk.must_exec(f"insert into skewb values {vals}")
        tk.must_exec("create table skewp (k bigint, w bigint)")
        vals = ",".join(f"({i % 400}, {i})" for i in range(1600))
        tk.must_exec(f"insert into skewp values {vals}")
        tk.must_exec("set tidb_broadcast_join_threshold_count = 50")
        tk.must_exec("set tidb_executor_engine = 'host'")
        sql = ("select count(1), sum(skewp.w + skewb.v) from skewp, skewb "
               "where skewp.k = skewb.k")
        host = tk.must_query(sql).rows
        before_skew = MPP_STATS["skew_broadcasts"]
        before_frag = MPP_STATS["fragments"]
        tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
        try:
            mpp = tk.must_query(sql).rows
        finally:
            tk.must_exec("set tidb_executor_engine = 'auto'")
            tk.must_exec("set tidb_broadcast_join_threshold_count = 10240")
        assert host == mpp, (host, mpp)
        assert MPP_STATS["fragments"] > before_frag
        assert MPP_STATS["skew_broadcasts"] > before_skew, \
            "hot-key build side took the Hash exchange anyway"

    def test_mild_skew_keeps_hash_exchange(self, tk):
        """Near-uniform keys must NOT trip the skew guard — the Hash
        exchange stays (it's the scalable path)."""
        tk.must_exec("create table evenb (k bigint, v bigint)")
        vals = ",".join(f"({i % 200}, {i})" for i in range(800))
        tk.must_exec(f"insert into evenb values {vals}")
        tk.must_exec("create table evenp (k bigint, w bigint)")
        vals = ",".join(f"({i % 200}, {i})" for i in range(1600))
        tk.must_exec(f"insert into evenp values {vals}")
        tk.must_exec("set tidb_broadcast_join_threshold_count = 50")
        tk.must_exec("set tidb_executor_engine = 'host'")
        sql = ("select count(1), sum(evenp.w + evenb.v) from evenp, evenb "
               "where evenp.k = evenb.k")
        host = tk.must_query(sql).rows
        before_sh = MPP_STATS["shuffle_joins"]
        tk.must_exec("set tidb_executor_engine = 'tpu-mpp'")
        try:
            mpp = tk.must_query(sql).rows
        finally:
            tk.must_exec("set tidb_executor_engine = 'auto'")
            tk.must_exec("set tidb_broadcast_join_threshold_count = 10240")
        assert host == mpp, (host, mpp)
        assert MPP_STATS["shuffle_joins"] > before_sh, \
            "uniform keys should keep the Hash exchange"
