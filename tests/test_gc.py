"""MVCC GC worker: safepoint computation, version pruning, stale-lock
resolution (reference: store/gcworker/gc_worker.go)."""

import pytest

from tidb_tpu.kv.gcworker import GCWorker, parse_duration
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


class TestParseDuration:
    def test_formats(self):
        assert parse_duration("10m0s") == 600.0
        assert parse_duration("30m") == 1800.0
        assert parse_duration("1h10m") == 4200.0
        assert parse_duration("50s") == 50.0
        assert parse_duration("500ms") == 0.5
        assert parse_duration("90") == 90.0
        with pytest.raises(ValueError):
            parse_duration("10x")
        with pytest.raises(ValueError):
            parse_duration("")


class TestGCVersionPruning:
    def test_old_versions_pruned_latest_kept(self, tk):
        tk.must_exec("create table t (id int primary key, v int)")
        tk.must_exec("insert into t values (1, 10)")
        for i in range(5):
            tk.must_exec(f"update t set v = {20 + i} where id = 1")
        store = tk.session.store
        before = store.mvcc.key_count()
        # safepoint "now": everything older than the newest version goes
        gc = tk.session.domain.gc_worker
        res = gc.run_once(safe_point=store.next_ts())
        assert not res["skipped"]
        tk.must_query("select v from t where id = 1").check([("24",)])
        assert store.mvcc.key_count() <= before

    def test_gc_respects_open_snapshot(self, tk):
        """The safepoint is floored below the oldest live txn start_ts."""
        tk.must_exec("create table t (id int primary key, v int)")
        tk.must_exec("insert into t values (1, 10)")
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk2.must_exec("begin")
        tk2.must_query("select v from t where id = 1").check([("10",)])
        tk.must_exec("update t set v = 99 where id = 1")
        gc = tk.session.domain.gc_worker
        gc.domain.global_vars["tidb_gc_life_time"] = "10s"
        sp = gc.compute_safepoint()
        assert sp < tk2.session.txn.start_ts
        # the open snapshot still reads its version after a GC round
        gc.run_once()
        tk2.must_query("select v from t where id = 1").check([("10",)])
        tk2.must_exec("commit")

    def test_disable_via_sysvar(self, tk):
        tk.must_exec("set global tidb_gc_enable = OFF")
        gc = tk.session.domain.gc_worker
        res = gc.run_once()
        assert res["skipped"]
        tk.must_exec("set global tidb_gc_enable = ON")


class TestGCLockResolution:
    def _stale_lock(self, tk, committed):
        """Simulate a crashed txn: prewrite without commit (and optionally
        commit only the primary)."""
        from tidb_tpu import tablecodec
        store = tk.session.store
        info = tk.session.infoschema().table_by_name("test", "t")
        primary = tablecodec.record_key(info.id, 100)
        secondary = tablecodec.record_key(info.id, 101)
        start = store.next_ts()
        row = tablecodec.encode_row([1], [100])
        row2 = tablecodec.encode_row([1], [101])
        store.mvcc.prewrite([(primary, 0, row), (secondary, 0, row2)],
                            primary, start)
        if committed:
            commit_ts = store.next_ts()
            store.mvcc.commit([primary], start, commit_ts)
        return primary, secondary, start

    def test_uncommitted_stale_lock_rolled_back(self, tk):
        tk.must_exec("create table t (id int primary key)")
        primary, secondary, start = self._stale_lock(tk, committed=False)
        gc = tk.session.domain.gc_worker
        sp = tk.session.store.next_ts()
        res = gc.run_once(safe_point=sp)
        assert res["resolved_locks"] == 2
        # no row became visible
        tk.must_query("select count(*) from t").check([("0",)])

    def test_committed_primary_commits_secondary(self, tk):
        tk.must_exec("create table t (id int primary key)")
        primary, secondary, start = self._stale_lock(tk, committed=True)
        gc = tk.session.domain.gc_worker
        sp = tk.session.store.next_ts()
        res = gc.run_once(safe_point=sp)
        assert res["resolved_locks"] == 1  # only the secondary was locked
        tk.must_query("select count(*) from t").check([("2",)])

    def test_scan_locks_both_engines(self, tk):
        from tidb_tpu import tablecodec
        tk.must_exec("create table t (id int primary key)")
        store = tk.session.store
        info = tk.session.infoschema().table_by_name("test", "t")
        k = tablecodec.record_key(info.id, 7)
        start = store.next_ts()
        store.mvcc.prewrite([(k, 0, b"x")], k, start)
        locks = store.mvcc.scan_locks(store.next_ts())
        assert (k, start, k) in locks
        store.mvcc.rollback([k], start)


class TestGCWorkerLoop:
    def test_background_loop_runs(self, tk):
        import time
        tk.must_exec("create table t (id int primary key, v int)")
        tk.must_exec("insert into t values (1, 1)")
        tk.must_exec("update t set v = 2 where id = 1")
        gc = tk.session.domain.gc_worker
        gc.domain.global_vars["tidb_gc_life_time"] = "10s"
        gc.domain.global_vars["tidb_gc_run_interval"] = "1s"
        # life_time floor keeps the safepoint behind "now", so force a run
        # with an explicit safepoint through the loop-owned state instead
        gc.start(interval=0.05)
        try:
            deadline = time.time() + 3
            while gc.status()["runs"] == 0 and time.time() < deadline:
                time.sleep(0.05)
        finally:
            gc.stop()
        # loop may legitimately skip (safepoint behind floor) — at minimum
        # it must have ticked without crashing and status() stays coherent
        st = gc.status()
        assert st["run_interval_s"] == 1.0
        tk.must_query("select v from t where id = 1").check([("2",)])


class TestSafepointReadGuard:
    def test_read_below_safepoint_rejected(self, tk):
        """reference: store/driver ErrGCTooEarly (9006)."""
        store = tk.session.store
        old_ts = store.next_ts()
        tk.session.domain.gc_worker.run_once(safe_point=store.next_ts())
        import pytest as _pytest
        from tidb_tpu.errors import TiDBError
        with _pytest.raises(TiDBError) as ei:
            store.begin(start_ts=old_ts)
        assert ei.value.code == 9006
        store.begin()  # fresh read views still fine


def test_gc_status_memtable(tk):
    tk.session.domain.gc_worker.run_once(
        safe_point=tk.session.store.next_ts())
    rows = dict(tk.must_query(
        "select variable_name, variable_value from "
        "information_schema.gc_status").rows)
    assert int(rows["tikv_gc_safe_point"]) > 0
    assert int(rows["tikv_gc_runs"]) >= 1
