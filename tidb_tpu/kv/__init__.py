"""Storage-engine-agnostic KV abstraction (reference: kv/kv.go —
Storage/Transaction/Snapshot/Iterator/MemBuffer interfaces).

The embedded store lives in ``mvcc.py`` (the reference's unistore role,
store/mockstore/unistore/tikv/mvcc.go). A later round replaces the Python
sorted-map internals with the C++ engine behind the same interface.
"""

from .mvcc import MVCCStore, Lock, TSOracle, Region
from .store import Storage, Snapshot, Transaction, MemBuffer, new_store

__all__ = [
    "MVCCStore", "Lock", "TSOracle", "Region",
    "Storage", "Snapshot", "Transaction", "MemBuffer", "new_store",
]
