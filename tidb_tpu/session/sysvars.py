"""System variable registry (reference: sessionctx/variable/sysvar.go — 248
registered variables; the registry pattern is kept, population grows with the
engine)."""

from __future__ import annotations

from ..errors import TiDBError, ErrCode

SCOPE_NONE = 0
SCOPE_SESSION = 1
SCOPE_GLOBAL = 2
SCOPE_BOTH = 3


class SysVar:
    __slots__ = ("name", "scope", "default", "kind", "min", "max", "choices")

    def __init__(self, name, scope=SCOPE_BOTH, default="", kind="str",
                 vmin=None, vmax=None, choices=None):
        self.name = name
        self.scope = scope
        self.default = default
        self.kind = kind  # str | int | bool | enum | float
        self.min = vmin
        self.max = vmax
        self.choices = choices

    def validate(self, value):
        v = value.decode() if isinstance(value, bytes) else str(value)
        if self.kind == "bool":
            u = v.upper()
            if u in ("ON", "1", "TRUE"):
                return "ON"
            if u in ("OFF", "0", "FALSE"):
                return "OFF"
            raise TiDBError(f"Variable '{self.name}' can't be set to the value of '{v}'")
        if self.kind == "int":
            try:
                i = int(v)
            except ValueError:
                raise TiDBError(f"Incorrect argument type to variable '{self.name}'")
            if self.min is not None and i < self.min:
                i = self.min
            if self.max is not None and i > self.max:
                i = self.max
            return str(i)
        if self.kind == "float":
            import math
            try:
                f = float(v)
            except ValueError:
                raise TiDBError(
                    f"Incorrect argument type to variable '{self.name}'")
            if not math.isfinite(f):
                # nan compares False against any bound, sailing past the
                # clamp — and a NaN cooldown wedges the circuit breaker
                raise TiDBError(
                    f"Variable '{self.name}' can't be set to the value "
                    f"of '{v}'")
            if self.min is not None and f < self.min:
                return str(self.min)
            if self.max is not None and f > self.max:
                return str(self.max)
            return v  # keep the user's spelling (SHOW round-trips)
        if self.kind == "enum":
            if self.choices and v.lower() not in self.choices:
                raise TiDBError(f"Variable '{self.name}' can't be set to the value of '{v}'")
            # store normalized: every reader compares lowercase literals
            # (SET tidb_device_compact = OFF must actually disable it)
            return v.lower()
        return v


_REGISTRY: dict[str, SysVar] = {}


def register(var: SysVar):
    _REGISTRY[var.name] = var


def get_registry():
    return _REGISTRY


for _v in [
    SysVar("autocommit", SCOPE_BOTH, "ON", "bool"),
    SysVar("sql_mode", SCOPE_BOTH, "ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES,"
           "NO_ZERO_IN_DATE,NO_ZERO_DATE,ERROR_FOR_DIVISION_BY_ZERO,"
           "NO_ENGINE_SUBSTITUTION"),
    SysVar("max_execution_time", SCOPE_BOTH, "0", "int", 0),
    SysVar("max_allowed_packet", SCOPE_BOTH, "67108864", "int", 1024),
    SysVar("time_zone", SCOPE_BOTH, "SYSTEM"),
    SysVar("tx_isolation", SCOPE_BOTH, "REPEATABLE-READ"),
    SysVar("transaction_isolation", SCOPE_BOTH, "REPEATABLE-READ"),
    SysVar("transaction_read_only", SCOPE_BOTH, "0", "bool"),
    SysVar("character_set_client", SCOPE_BOTH, "utf8mb4"),
    SysVar("character_set_connection", SCOPE_BOTH, "utf8mb4"),
    SysVar("character_set_results", SCOPE_BOTH, "utf8mb4"),
    SysVar("collation_connection", SCOPE_BOTH, "utf8mb4_bin"),
    SysVar("names", SCOPE_SESSION, "utf8mb4"),
    SysVar("wait_timeout", SCOPE_BOTH, "28800", "int", 0),
    SysVar("interactive_timeout", SCOPE_BOTH, "28800", "int", 1),
    SysVar("max_connections", SCOPE_GLOBAL, "0", "int", 0, 100000),
    SysVar("version_comment", SCOPE_NONE, "tpu-htap"),
    SysVar("port", SCOPE_NONE, "4000", "int"),
    SysVar("socket", SCOPE_NONE, ""),
    SysVar("datadir", SCOPE_NONE, "/tmp/tpu-htap"),
    SysVar("last_insert_id", SCOPE_SESSION, "0", "int"),
    SysVar("hostname", SCOPE_NONE, "localhost"),
    # engine knobs (the tidb_* namespace of the reference)
    SysVar("tidb_executor_engine", SCOPE_BOTH, "auto", "enum",
           choices=("auto", "host", "tpu", "tpu-mpp")),
    SysVar("tidb_mpp_devices", SCOPE_BOTH, "0", "int", 0),
    # engine tuning knobs (VERDICT r3: hardcoded thresholds must be
    # bench-time tunable): the auto-mode device dispatch row floor
    # 0 = derive the auto-mode dispatch floor from the calibrated cost
    # constants (planner/cost_model.py device_breakeven_rows); a positive
    # value overrides it
    SysVar("tidb_device_dispatch_rows", SCOPE_BOTH, "0", "int", 0),
    # plan-baseline auto capture (reference: bindinfo/handle.go:749)
    SysVar("tidb_capture_plan_baselines", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_mem_quota_query", SCOPE_BOTH, str(1 << 30), "int", 0),
    SysVar("tidb_max_chunk_size", SCOPE_BOTH, "65536", "int", 32),
    SysVar("tidb_snapshot_isolation", SCOPE_BOTH, "ON", "bool"),
    # the fleet's version-stamped fragment result cache
    # (executor/agg_cache.py); OFF pins every agg to a fresh compute —
    # the bench's bit-equality oracle for a delta-folded page
    SysVar("tidb_result_cache", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_build_stats_concurrency", SCOPE_BOTH, "4", "int", 1),
    SysVar("tidb_distsql_scan_concurrency", SCOPE_BOTH, "15", "int", 1),
    SysVar("tidb_executor_concurrency", SCOPE_BOTH, "5", "int", 1),
    SysVar("tidb_txn_mode", SCOPE_BOTH, "pessimistic", "enum",
           choices=("pessimistic", "optimistic")),
    SysVar("tidb_retry_limit", SCOPE_BOTH, "10", "int", 0),
    # prepared-plan cache (reference: planner/core/cache.go; v5 config
    # prepared-plan-cache {enabled, capacity})
    SysVar("tidb_enable_prepared_plan_cache", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_prepared_plan_cache_size", SCOPE_BOTH, "100", "int", 0),
    # TopSQL sampling (reference: tidb_enable_top_sql, default OFF)
    SysVar("tidb_enable_top_sql", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_enable_window_function", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_topn_push_down", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_mesh_shape", SCOPE_BOTH, "1", "str"),
    # streamed device pipeline batch bound: bounds HBM + transfer memory
    # for larger-than-memory inputs at the cost of re-transfer per run
    # (0 = off: whole-table transfers, HBM-resident column cache)
    SysVar("tidb_device_stream_rows", SCOPE_BOTH, "0", "int", 0),
    # shape-canonicalization granularity: geometric row buckets per
    # doubling that device uploads pad to (ops/device.py bucket_rows) so
    # compiled XLA programs are reusable across deltas/tables/scale
    # factors. 2 = powers of sqrt(2) (<=19% padding), 1 = powers of 2,
    # 0 = exact shapes (recompile per row count)
    SysVar("tidb_device_shape_buckets", SCOPE_BOTH, "2", "int", 0, 8),
    # post-join compaction in device fragments: auto = CPU backend only
    SysVar("tidb_device_compact", SCOPE_BOTH, "auto", "enum",
           choices=("auto", "on", "off")),
    SysVar("tidb_slow_log_threshold", SCOPE_BOTH, "300", "int", 0),
    # query-lifecycle span tracing (session/tracing.py): fraction of
    # statements sampled into a full span trace (0 = off, the default —
    # one branch per chokepoint; 1 = every statement).  TRACE statements
    # are always-on regardless of this rate.
    SysVar("tidb_trace_sampling_rate", SCOPE_BOTH, "0", "float", 0, 1),
    SysVar("cte_max_recursion_depth", SCOPE_BOTH, "1000", "int", 0, 4294967295),
    SysVar("tidb_auto_analyze_ratio", SCOPE_GLOBAL, "0.5", "float"),
    SysVar("tidb_enable_auto_analyze", SCOPE_GLOBAL, "ON", "bool"),
    SysVar("tidb_record_plan_in_slow_log", SCOPE_BOTH, "ON", "bool"),
    # write-ahead-log fsync policy (kv/wal.py, durable stores only):
    # `commit` (default) = every commit joins a GROUP fsync before it
    # acks; `interval` = a background flusher fsyncs every ~20ms (a
    # crash loses at most the unsynced window); `never` = OS-buffered
    # only (the fleet still replicates via the log, but a host crash
    # loses the buffer tail).  GLOBAL: the log is process-wide, so a
    # session SET must not weaken durability another session relies on
    SysVar("tidb_wal_fsync", SCOPE_GLOBAL, "commit", "enum",
           choices=("never", "interval", "commit")),
    # MVCC GC (reference: gc_worker.go gcLifeTimeKey/gcRunIntervalKey)
    SysVar("tidb_gc_life_time", SCOPE_GLOBAL, "10m0s"),
    SysVar("tidb_gc_run_interval", SCOPE_GLOBAL, "10m0s"),
    SysVar("tidb_gc_enable", SCOPE_GLOBAL, "ON", "bool"),
    # telemetry is local-only and OFF by default (reference default ON,
    # but this build never egresses)
    SysVar("tidb_enable_telemetry", SCOPE_GLOBAL, "OFF", "bool"),
    # -- MySQL-compat breadth (reference: sysvar.go registers 248;
    #    clients and ORMs read/SET these at connect time) ---------------
    SysVar("auto_increment_increment", SCOPE_BOTH, "1", "int", 1, 65535),
    SysVar("auto_increment_offset", SCOPE_BOTH, "1", "int", 1, 65535),
    SysVar("block_encryption_mode", SCOPE_BOTH, "aes-128-ecb"),
    SysVar("character_set_database", SCOPE_BOTH, "utf8mb4"),
    SysVar("character_set_server", SCOPE_BOTH, "utf8mb4"),
    SysVar("character_set_system", SCOPE_NONE, "utf8mb4"),
    SysVar("collation_database", SCOPE_BOTH, "utf8mb4_bin"),
    SysVar("collation_server", SCOPE_BOTH, "utf8mb4_bin"),
    SysVar("default_week_format", SCOPE_BOTH, "0", "int", 0, 7),
    SysVar("div_precision_increment", SCOPE_BOTH, "4", "int", 0, 30),
    SysVar("foreign_key_checks", SCOPE_BOTH, "OFF", "bool"),
    SysVar("group_concat_max_len", SCOPE_BOTH, "1024", "int", 4),
    SysVar("innodb_lock_wait_timeout", SCOPE_BOTH, "50", "int", 1),
    SysVar("lc_time_names", SCOPE_BOTH, "en_US"),
    SysVar("license", SCOPE_NONE, "Apache License 2.0"),
    SysVar("lower_case_table_names", SCOPE_NONE, "2", "int", 0, 2),
    SysVar("max_sort_length", SCOPE_BOTH, "1024", "int", 4),
    SysVar("net_buffer_length", SCOPE_BOTH, "16384", "int", 1024),
    SysVar("net_read_timeout", SCOPE_BOTH, "30", "int", 1),
    SysVar("net_write_timeout", SCOPE_BOTH, "60", "int", 1),
    SysVar("performance_schema", SCOPE_NONE, "OFF", "bool"),
    SysVar("protocol_version", SCOPE_NONE, "10", "int"),
    SysVar("query_cache_size", SCOPE_GLOBAL, "0", "int", 0),
    SysVar("query_cache_type", SCOPE_BOTH, "OFF", "bool"),
    SysVar("read_only", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("sql_safe_updates", SCOPE_BOTH, "OFF", "bool"),
    SysVar("sql_select_limit", SCOPE_BOTH, str(2**64 - 1), "str"),
    SysVar("system_time_zone", SCOPE_NONE, "UTC"),
    SysVar("table_definition_cache", SCOPE_GLOBAL, "2000", "int", 400),
    SysVar("thread_cache_size", SCOPE_GLOBAL, "9", "int", 0),
    SysVar("tmp_table_size", SCOPE_BOTH, "16777216", "int", 1024),
    SysVar("unique_checks", SCOPE_BOTH, "ON", "bool"),
    SysVar("version", SCOPE_NONE, "8.0.11-tpu-htap"),
    SysVar("version_compile_machine", SCOPE_NONE, "tpu"),
    SysVar("version_compile_os", SCOPE_NONE, "Linux"),
    SysVar("warning_count", SCOPE_SESSION, "0", "int"),
    SysVar("error_count", SCOPE_SESSION, "0", "int"),
    SysVar("default_authentication_plugin", SCOPE_GLOBAL,
           "mysql_native_password"),
    SysVar("init_connect", SCOPE_GLOBAL, ""),
    SysVar("have_openssl", SCOPE_NONE, "DISABLED"),
    SysVar("have_ssl", SCOPE_NONE, "DISABLED"),
    SysVar("max_user_connections", SCOPE_BOTH, "0", "int", 0, 100000),
    SysVar("max_prepared_stmt_count", SCOPE_GLOBAL, "16382", "int", -1),
    SysVar("binlog_format", SCOPE_BOTH, "ROW"),
    SysVar("log_bin", SCOPE_NONE, "OFF", "bool"),
    SysVar("timestamp", SCOPE_SESSION, "0"),
    SysVar("profiling", SCOPE_BOTH, "OFF", "bool"),
    SysVar("optimizer_switch", SCOPE_BOTH, "index_merge=on"),
    # -- tidb_* engine knobs (reference names, same semantics) ----------
    SysVar("tidb_allow_batch_cop", SCOPE_BOTH, "1", "int", 0, 2),
    SysVar("tidb_allow_mpp", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_auto_analyze_start_time", SCOPE_GLOBAL, "00:00 +0000"),
    SysVar("tidb_auto_analyze_end_time", SCOPE_GLOBAL, "23:59 +0000"),
    SysVar("tidb_backoff_weight", SCOPE_BOTH, "2", "int", 1),
    # -- resilience layer (utils/backoff.py + executor/circuit.py) ------
    # classified device failures before the device→host breaker OPENs
    # (0 disables the breaker entirely)
    SysVar("tidb_device_circuit_threshold", SCOPE_BOTH, "5", "int", 0,
           10000),
    # seconds the breaker stays OPEN before a HALF_OPEN probe fragment
    SysVar("tidb_device_circuit_cooldown", SCOPE_BOTH, "30", "float", 0),
    # hard wall-clock deadline (seconds) for ONE device call through the
    # supervisor (executor/supervisor.py): expiry raises DeviceHangError
    # (errno 9008), abandons the call, fences/reinitializes the backend
    # and counts toward the circuit breaker. 0 = unsupervised inline
    # dispatch (the remaining max_execution_time window still supervises,
    # but ITS expiry is QueryInterrupted — a user limit, not a hang).
    # Set it ABOVE the workload's worst-case cold-compile time: off-CPU
    # the deadline covers compilation, and a too-small value re-fences
    # (re-colds) the very compile it then times out again
    SysVar("tidb_device_call_timeout", SCOPE_BOTH, "0", "float", 0),
    # HBM residency budget in BYTES (ops/residency.py): cached device
    # uploads (Column._device, join-leaf dcols) are byte-accounted against
    # it and evicted LRU-first under pressure. 0 = auto: the jax-reported
    # device memory limit off-CPU, unlimited on the in-process CPU
    # backend (host RAM is governed by tidb_mem_quota_query/MemTracker).
    # Read from GLOBAL scope (SET GLOBAL), same discipline as the
    # breaker knobs: the ledger is process-wide, so a session-scoped SET
    # must not clobber the budget another session configured
    SysVar("tidb_device_mem_budget", SCOPE_BOTH, "0", "int", 0),
    # -- serving front end (executor/scheduler.py) ----------------------
    # the session's tenant identity for device admission, WFQ scheduling,
    # per-tenant residency shares and breaker/scheduler stat lines
    SysVar("tidb_resource_group", SCOPE_SESSION, "default", "str"),
    # bounded fragment-admission queue depth (total queued tickets across
    # all tenants); a full queue refuses admission with a classified
    # DeviceAdmissionError (9009) and the fragment degrades to the host
    # engine. 0 disables the admission layer entirely (pass-through).
    # GLOBAL-scope read, same discipline as the breaker/residency knobs
    SysVar("tidb_device_sched_queue_depth", SCOPE_BOTH, "64", "int", 0,
           100000),
    # seconds a fragment may wait in the admission queue before the
    # refusal (9009) degrades it to the host engine; 0 = wait forever
    SysVar("tidb_device_admission_timeout", SCOPE_BOTH, "5", "float", 0),
    # max fragments of ONE resource group running on the device at once
    # (0 = unlimited): a heavy analytical tenant cannot occupy every slot
    SysVar("tidb_device_tenant_running_cap", SCOPE_BOTH, "4", "int", 0,
           10000),
    # WFQ weights, "group:weight,group2:weight" (unlisted groups weigh 1):
    # each grant advances the tenant's virtual clock by 1/weight, lowest
    # clock goes next — heavier tenants get proportionally more slots
    SysVar("tidb_device_wfq_weights", SCOPE_BOTH, "", "str"),
    # -- compile service (executor/compile_service.py) ------------------
    # ON: a cold compiled-pipeline cache miss submits the fragment
    # signature to the background compile pool and THIS execution serves
    # from the host engine (no breaker charge) — first-query latency is
    # bounded by host speed, never by XLA; when the executable lands,
    # same-shaped queries flip to the device with zero new traces.
    # OFF (default): cache misses compile inline as before (still
    # breaker-guarded + persisted through the compile service)
    SysVar("tidb_compile_async", SCOPE_BOTH, "OFF", "bool"),
    # SET GLOBAL ... = ON kicks a background prewarm of every registered
    # fragment recipe's bucket ladder, immediately and on any later
    # Domain start in this process (globals are in-memory, so the SET is
    # when the intent exists; see ADMIN COMPILE for the waiting form)
    SysVar("tidb_compile_prewarm", SCOPE_BOTH, "OFF", "bool"),
    # background compile worker threads (process-wide pool, GLOBAL-scope
    # read: a session SET must not resize the shared pool)
    SysVar("tidb_compile_workers", SCOPE_BOTH, "2", "int", 1, 64),
    # wall-clock deadline (seconds) for ONE background compile attempt,
    # enforced by the device-runtime supervisor: a hung remote compile is
    # abandoned + fenced like any device hang, then retried on the
    # compileRetry curve. 0 = no deadline (the default: CPU-backend
    # builds are in-process and cannot tunnel-hang)
    SysVar("tidb_compile_timeout", SCOPE_BOTH, "0", "float", 0),
    SysVar("tidb_broadcast_join_threshold_size", SCOPE_BOTH,
           str(100 * 1024 * 1024), "int", 0),
    SysVar("tidb_broadcast_join_threshold_count", SCOPE_BOTH,
           str(10 * 1024), "int", 0),
    SysVar("tidb_checksum_table_concurrency", SCOPE_BOTH, "4", "int", 1),
    SysVar("tidb_constraint_check_in_place", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_current_ts", SCOPE_SESSION, "0", "int"),
    SysVar("tidb_ddl_error_count_limit", SCOPE_GLOBAL, "512", "int", 0),
    SysVar("tidb_ddl_reorg_batch_size", SCOPE_GLOBAL, "256", "int", 32),
    SysVar("tidb_ddl_reorg_worker_cnt", SCOPE_GLOBAL, "4", "int", 1),
    SysVar("tidb_disable_txn_auto_retry", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_cascades_planner", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_enable_chunk_rpc", SCOPE_SESSION, "ON", "bool"),
    SysVar("tidb_enable_clustered_index", SCOPE_BOTH, "INT_ONLY"),
    SysVar("tidb_enable_collect_execution_info", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_fast_analyze", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_enable_index_merge", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_noop_functions", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_enable_parallel_apply", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_enable_slow_log", SCOPE_GLOBAL, "ON", "bool"),
    SysVar("tidb_enable_stmt_summary", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_table_partition", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_vectorized_expression", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_force_priority", SCOPE_SESSION, "NO_PRIORITY"),
    SysVar("tidb_general_log", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_hash_join_concurrency", SCOPE_BOTH, "5", "int", 1),
    SysVar("tidb_window_concurrency", SCOPE_BOTH, "4", "int", 1),
    # rows below which ShuffleExec-style host parallelism is skipped
    SysVar("tidb_shuffle_min_rows", SCOPE_BOTH, "8192", "int", 0),
    SysVar("tidb_hashagg_final_concurrency", SCOPE_BOTH, "5", "int", 1),
    SysVar("tidb_hashagg_partial_concurrency", SCOPE_BOTH, "5", "int", 1),
    SysVar("tidb_index_join_batch_size", SCOPE_BOTH, "25000", "int", 1),
    SysVar("tidb_index_lookup_concurrency", SCOPE_BOTH, "4", "int", 1),
    SysVar("tidb_index_lookup_size", SCOPE_BOTH, "20000", "int", 1),
    SysVar("tidb_index_serial_scan_concurrency", SCOPE_BOTH, "1", "int", 1),
    SysVar("tidb_init_chunk_size", SCOPE_BOTH, "32", "int", 1, 32),
    SysVar("tidb_isolation_read_engines", SCOPE_SESSION, "tpu,host"),
    SysVar("tidb_low_resolution_tso", SCOPE_SESSION, "OFF", "bool"),
    SysVar("tidb_max_delta_schema_count", SCOPE_GLOBAL, "1024", "int", 100),
    SysVar("tidb_mem_oom_action", SCOPE_GLOBAL, "CANCEL", "enum",
           choices=("cancel", "log")),
    SysVar("tidb_mem_quota_apply_cache", SCOPE_BOTH,
           str(32 << 20), "int", 0),
    SysVar("tidb_opt_agg_push_down", SCOPE_BOTH, "OFF", "bool"),
    # calibrated cost-model constants (planner/cost_model.py): one
    # currency for access-path, join-variant and engine-placement choice;
    # apply_calibration() overwrites the globals with measured values
    # (reference: the tidb_opt_*_factor family, sessionctx/variable)
    SysVar("tidb_opt_scan_row_cost", SCOPE_BOTH, "1.0", "float"),
    SysVar("tidb_opt_seek_cost", SCOPE_BOTH, "8.0", "float"),
    SysVar("tidb_opt_seek_base", SCOPE_BOTH, "30.0", "float"),
    SysVar("tidb_opt_hash_build_cost", SCOPE_BOTH, "2.0", "float"),
    SysVar("tidb_opt_merge_sort_cost", SCOPE_BOTH, "0.05", "float"),
    SysVar("tidb_opt_agg_row_cost", SCOPE_BOTH, "2.0", "float"),
    SysVar("tidb_opt_device_row_cost", SCOPE_BOTH, "0.02", "float"),
    SysVar("tidb_opt_device_dispatch_cost", SCOPE_BOTH, "195000.0",
           "float"),
    SysVar("tidb_opt_correlation_threshold", SCOPE_BOTH, "0.9", "float"),
    # reference cost-factor family (sessionctx/variable/sysvar.go) — kept
    # alongside the calibrated tidb_opt_*_cost constants for SQL compat
    SysVar("tidb_opt_cpu_factor", SCOPE_BOTH, "3.0", "float"),
    SysVar("tidb_opt_copcpu_factor", SCOPE_BOTH, "3.0", "float"),
    SysVar("tidb_opt_scan_factor", SCOPE_BOTH, "1.5", "float"),
    SysVar("tidb_opt_desc_factor", SCOPE_BOTH, "3.0", "float"),
    SysVar("tidb_opt_seek_factor", SCOPE_BOTH, "20.0", "float"),
    SysVar("tidb_opt_memory_factor", SCOPE_BOTH, "0.001", "float"),
    SysVar("tidb_opt_disk_factor", SCOPE_BOTH, "1.5", "float"),
    SysVar("tidb_opt_network_factor", SCOPE_BOTH, "1.0", "float"),
    SysVar("tidb_opt_concurrency_factor", SCOPE_BOTH, "3.0", "float"),
    SysVar("tidb_opt_tiflash_concurrency_factor", SCOPE_BOTH, "24.0",
           "float"),
    SysVar("tidb_opt_correlation_exp_factor", SCOPE_BOTH, "1", "int", 0),
    SysVar("tidb_opt_enable_correlation_adjustment", SCOPE_BOTH, "ON",
           "bool"),
    SysVar("tidb_opt_limit_push_down_threshold", SCOPE_BOTH, "100", "int",
           0),
    SysVar("tidb_opt_prefer_range_scan", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_opt_broadcast_join", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_opt_broadcast_cartesian_join", SCOPE_BOTH, "1", "int", 0,
           2),
    SysVar("tidb_opt_mpp_outer_join_fixed_build_side", SCOPE_BOTH, "OFF",
           "bool"),
    SysVar("tidb_optimizer_selectivity_level", SCOPE_SESSION, "0", "int",
           0),
    SysVar("tidb_regard_null_as_point", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_opt_distinct_agg_push_down", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_opt_insubq_to_join_and_agg", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_opt_join_reorder_threshold", SCOPE_BOTH, "0", "int", 0, 63),
    SysVar("tidb_opt_write_row_id", SCOPE_SESSION, "OFF", "bool"),
    SysVar("tidb_projection_concurrency", SCOPE_BOTH, "-1", "int", -1),
    # breadth batch (reference sessionctx/variable/sysvar.go, matching
    # scopes/defaults; consumed where the engine has the corresponding
    # subsystem, SELECT/SET-compatible knobs otherwise)
    SysVar("allow_auto_random_explicit_insert", SCOPE_BOTH, "OFF", "bool"),
    SysVar("ddl_slow_threshold", SCOPE_GLOBAL, "300", "int", 0),
    SysVar("identity", SCOPE_SESSION, "0", "int"),
    SysVar("last_plan_from_binding", SCOPE_SESSION, "OFF", "bool"),
    SysVar("last_plan_from_cache", SCOPE_SESSION, "OFF", "bool"),
    SysVar("plugin_dir", SCOPE_GLOBAL, "/data/deploy/plugin", "str"),
    SysVar("plugin_load", SCOPE_GLOBAL, "", "str"),
    SysVar("rand_seed1", SCOPE_SESSION, "0", "int", 0),
    SysVar("rand_seed2", SCOPE_SESSION, "0", "int", 0),
    SysVar("skip_name_resolve", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_allow_fallback_to_tikv", SCOPE_BOTH, "", "str"),
    SysVar("tidb_allow_function_for_expression_index", SCOPE_GLOBAL,
           "json_extract, lower, md5, reverse, upper", "str"),
    SysVar("tidb_allow_remove_auto_inc", SCOPE_SESSION, "OFF", "bool"),
    SysVar("tidb_analyze_version", SCOPE_BOTH, "2", "int", 1, 2),
    SysVar("tidb_backoff_lock_fast", SCOPE_BOTH, "10", "int", 1),
    SysVar("tidb_batch_commit", SCOPE_SESSION, "OFF", "bool"),
    SysVar("tidb_batch_delete", SCOPE_SESSION, "OFF", "bool"),
    SysVar("tidb_batch_insert", SCOPE_SESSION, "OFF", "bool"),
    SysVar("tidb_check_mb4_value_in_utf8", SCOPE_GLOBAL, "ON", "bool"),
    SysVar("tidb_config", SCOPE_SESSION, "", "str"),
    SysVar("tidb_ddl_reorg_priority", SCOPE_SESSION, "PRIORITY_LOW",
           "str"),
    SysVar("tidb_dml_batch_size", SCOPE_BOTH, "0", "int", 0),
    SysVar("tidb_enable_1pc", SCOPE_GLOBAL, "ON", "bool"),
    SysVar("tidb_enable_amend_pessimistic_txn", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_enable_async_commit", SCOPE_GLOBAL, "ON", "bool"),
    SysVar("tidb_enable_auto_increment_in_generated", SCOPE_BOTH, "OFF",
           "bool"),
    SysVar("tidb_enable_change_multi_schema", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_enable_column_tracking", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_enable_exchange_partition", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_enable_extended_stats", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_enable_historical_stats", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_enable_index_merge_join", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_enable_list_partition", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_ordered_result_mode", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_enable_paging", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_pipelined_window_function", SCOPE_BOTH, "ON",
           "bool"),
    SysVar("tidb_enable_point_get_cache", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_enable_pseudo_for_outdated_stats", SCOPE_BOTH, "ON",
           "bool"),
    SysVar("tidb_enable_rate_limit_action", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_enable_strict_double_type_check", SCOPE_BOTH, "ON",
           "bool"),
    SysVar("tidb_enforce_mpp", SCOPE_SESSION, "OFF", "bool"),
    SysVar("tidb_evolve_plan_baselines", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_evolve_plan_task_end_time", SCOPE_GLOBAL, "23:59 +0000",
           "str"),
    SysVar("tidb_evolve_plan_task_max_time", SCOPE_GLOBAL, "600", "int",
           0),
    SysVar("tidb_evolve_plan_task_start_time", SCOPE_GLOBAL,
           "00:00 +0000", "str"),
    SysVar("tidb_expensive_query_time_threshold", SCOPE_GLOBAL, "60",
           "int", 10),
    SysVar("tidb_gc_concurrency", SCOPE_GLOBAL, "-1", "int", -1, 256),
    SysVar("tidb_gc_scan_lock_mode", SCOPE_GLOBAL, "LEGACY", "str"),
    SysVar("tidb_guarantee_linearizability", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_hash_exchange_with_new_collation", SCOPE_BOTH, "ON",
           "bool"),
    SysVar("tidb_index_lookup_join_concurrency", SCOPE_BOTH, "-1", "int",
           -1),
    SysVar("tidb_last_query_info", SCOPE_SESSION, "", "str"),
    SysVar("tidb_last_txn_info", SCOPE_SESSION, "", "str"),
    SysVar("tidb_log_file_max_days", SCOPE_GLOBAL, "0", "int", 0),
    SysVar("tidb_mem_quota_hashjoin", SCOPE_SESSION, str(32 << 30),
           "int", 0),
    SysVar("tidb_mem_quota_indexlookupjoin", SCOPE_SESSION, str(32 << 30),
           "int", 0),
    SysVar("tidb_mem_quota_indexlookupreader", SCOPE_SESSION,
           str(32 << 30), "int", 0),
    SysVar("tidb_mem_quota_mergejoin", SCOPE_SESSION, str(32 << 30),
           "int", 0),
    SysVar("tidb_mem_quota_sort", SCOPE_SESSION, str(32 << 30), "int", 0),
    SysVar("tidb_mem_quota_topn", SCOPE_SESSION, str(32 << 30), "int", 0),
    SysVar("tidb_memory_usage_alarm_ratio", SCOPE_SESSION, "0.8", "float"),
    SysVar("tidb_merge_join_concurrency", SCOPE_BOTH, "1", "int", 1),
    SysVar("tidb_metric_query_range_duration", SCOPE_SESSION, "60", "int",
           10),
    SysVar("tidb_metric_query_step", SCOPE_SESSION, "60", "int", 10),
    SysVar("tidb_mpp_store_fail_ttl", SCOPE_BOTH, "60s", "str"),
    SysVar("tidb_multi_statement_mode", SCOPE_BOTH, "OFF", "enum",
           choices=("off", "on", "warn")),
    SysVar("tidb_partition_prune_mode", SCOPE_BOTH, "static", "enum",
           choices=("static", "dynamic", "static-only", "dynamic-only")),
    SysVar("tidb_persist_analyze_options", SCOPE_GLOBAL, "ON", "bool"),
    SysVar("tidb_placement_mode", SCOPE_BOTH, "STRICT", "enum",
           choices=("strict", "ignore")),
    SysVar("tidb_pprof_sql_cpu", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_read_consistency", SCOPE_SESSION, "strict", "enum",
           choices=("strict", "weak")),
    SysVar("tidb_redact_log", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_restricted_read_only", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_shard_allocate_step", SCOPE_SESSION, str(1 << 30), "int",
           1),
    SysVar("tidb_skip_ascii_check", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_stats_load_pseudo_timeout", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_stats_load_sync_wait", SCOPE_SESSION, "0", "int", 0),
    SysVar("tidb_stmt_summary_history_size", SCOPE_BOTH, "24", "int", 0,
           255),
    SysVar("tidb_stmt_summary_internal_query", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_stmt_summary_max_sql_length", SCOPE_BOTH, "4096", "int",
           0),
    SysVar("tidb_stmt_summary_refresh_interval", SCOPE_BOTH, "1800",
           "int", 1),
    SysVar("tidb_streamagg_concurrency", SCOPE_BOTH, "1", "int", 1),
    SysVar("tidb_table_cache_lease", SCOPE_GLOBAL, "3", "int", 1, 10),
    SysVar("tidb_tmp_table_max_size", SCOPE_SESSION, str(64 << 20), "int",
           1 << 20),
    SysVar("tidb_top_sql_max_collect", SCOPE_GLOBAL, "10000", "int", 1),
    SysVar("tidb_top_sql_max_statement_count", SCOPE_GLOBAL, "200", "int",
           0, 5000),
    SysVar("tidb_top_sql_precision_seconds", SCOPE_GLOBAL, "1", "int", 1),
    SysVar("tidb_top_sql_report_interval_seconds", SCOPE_GLOBAL, "60",
           "int", 1),
    SysVar("tidb_track_aggregate_memory_usage", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_tso_client_batch_max_wait_time", SCOPE_GLOBAL, "0.0",
           "float"),
    SysVar("tidb_use_plan_baselines", SCOPE_BOTH, "ON", "bool"),
    SysVar("tx_isolation_one_shot", SCOPE_SESSION, "", "str"),
    SysVar("tx_read_ts", SCOPE_SESSION, "0", "int", 0),
    SysVar("txn_scope", SCOPE_SESSION, "global", "str"),
    SysVar("windowing_use_high_precision", SCOPE_BOTH, "ON", "bool"),
    SysVar("tidb_query_log_max_len", SCOPE_GLOBAL, "4096", "int", 0),
    SysVar("tidb_read_staleness", SCOPE_SESSION, "0", "int"),
    # historical read view: every read runs at this datetime until unset
    # (reference: sessionctx/variable tidb_snapshot + stale-read txns)
    SysVar("tidb_snapshot", SCOPE_SESSION, "", "str"),
    SysVar("tidb_replica_read", SCOPE_SESSION, "leader"),
    SysVar("tidb_row_format_version", SCOPE_GLOBAL, "2", "int", 1, 2),
    SysVar("tidb_scatter_region", SCOPE_GLOBAL, "OFF", "bool"),
    SysVar("tidb_skip_isolation_level_check", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_skip_utf8_check", SCOPE_BOTH, "OFF", "bool"),
    SysVar("tidb_slow_query_file", SCOPE_SESSION, ""),
    SysVar("tidb_stmt_summary_max_stmt_count", SCOPE_GLOBAL, "3000",
           "int", 1),
    SysVar("tidb_store_limit", SCOPE_BOTH, "0", "int", 0),
    SysVar("tidb_txn_assertion_level", SCOPE_BOTH, "FAST"),
    SysVar("tidb_wait_split_region_finish", SCOPE_SESSION, "ON", "bool"),
    SysVar("tidb_wait_split_region_timeout", SCOPE_SESSION, "300", "int", 1),
    SysVar("tidb_window_concurrency", SCOPE_BOTH, "-1", "int", -1),
    SysVar("tx_read_only", SCOPE_BOTH, "0", "bool"),
    SysVar("sql_log_bin", SCOPE_SESSION, "ON", "bool"),
    SysVar("sql_notes", SCOPE_BOTH, "ON", "bool"),
    SysVar("sql_quote_show_create", SCOPE_BOTH, "ON", "bool"),
    SysVar("sql_warnings", SCOPE_BOTH, "OFF", "bool"),
]:
    register(_v)
