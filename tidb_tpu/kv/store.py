"""Storage / Transaction / Snapshot over the MVCC store.

Plays the role of tikv/client-go/v2 (2PC driver) + kv/kv.go interfaces: the
transaction accumulates mutations in a MemBuffer (reference: kv.MemBuffer)
and commits via Percolator 2PC against the embedded store. In-process there
is no RPC; the commit protocol is kept (prewrite → TSO → commit) because DDL
/ txn semantics and the test matrix depend on its failure modes.
"""

from __future__ import annotations

import threading

from ..errors import ErrCode, LockedError, TiDBError, WriteConflictError
from .mvcc import MVCCStore, OP_AMEND_FLAG, OP_DEL, OP_LOCK, OP_PUT

_MISSING = object()


def _inject_2pc(name: str):
    """2PC-stage failpoint with process-kill payloads: the usual
    actions (panic / N*panic / sleep) behave as before; a
    ``return(kill)`` payload SIGKILLs the process AT the stage — the
    crash-recovery matrix's per-stage death hook (tests/test_wal.py,
    the fleet durability chaos)."""
    from ..utils import failpoint
    if failpoint.inject(name) == "kill":
        import os
        import signal
        os.kill(os.getpid(), signal.SIGKILL)


class MemBuffer:
    """Ordered txn-local write buffer with savepoints ("staging" in the
    reference, kv/memdb). dict + bisect-maintained sorted key list so range
    probes (txn_dirty, scans) are O(log n + k), not a full re-sort."""

    def __init__(self):
        import bisect as _b
        self._bisect = _b
        self._data: dict[bytes, bytes | None] = {}  # None = tombstone
        self._keys: list[bytes] = []                # sorted keys present
        self._ops: list[tuple[bytes, bytes | None]] = []  # undo log for savepoints

    def _write(self, key: bytes, value):
        self._ops.append((key, self._data.get(key, _MISSING)))
        if key not in self._data:
            self._bisect.insort(self._keys, key)
        self._data[key] = value

    def put(self, key: bytes, value: bytes):
        self._write(key, value)

    def delete(self, key: bytes):
        self._write(key, None)

    def get(self, key: bytes, default=_MISSING):
        return self._data.get(key, default)

    def __contains__(self, key):
        return key in self._data

    def __len__(self):
        return len(self._data)

    def keys_since(self, sp: int) -> set:
        """Keys written after savepoint sp (the statement's write set)."""
        return {k for k, _prev in self._ops[sp:]}

    def savepoint(self) -> int:
        return len(self._ops)

    def rollback_to(self, sp: int):
        while len(self._ops) > sp:
            key, old = self._ops.pop()
            if old is _MISSING:
                del self._data[key]
                i = self._bisect.bisect_left(self._keys, key)
                if i < len(self._keys) and self._keys[i] == key:
                    del self._keys[i]
            else:
                self._data[key] = old

    def items_sorted(self):
        return [(k, self._data[k]) for k in self._keys]

    def range_items(self, start: bytes, end: bytes):
        lo = self._bisect.bisect_left(self._keys, start)
        hi = self._bisect.bisect_left(self._keys, end) if end else len(self._keys)
        return [(k, self._data[k]) for k in self._keys[lo:hi]]


class Snapshot:
    """Point-in-time read view (reference: kv.Snapshot).

    Reads encountering another transaction's prewrite lock back off and
    retry until the lock clears (the client-go resolveLocks + backoff
    role): a committing writer holds its data locks only for the prewrite→
    commit window, so readers wait it out instead of failing. A lock still
    held past LOCK_WAIT_S is surfaced (abandoned txn — the GC worker's
    stale-lock resolution owns those)."""

    #: max seconds a read waits on a prewrite lock before surfacing it
    LOCK_WAIT_S = 5.0

    def __init__(self, store: "Storage", ts: int, own_start_ts: int = 0):
        self.store = store
        self.ts = ts
        self.own_start_ts = own_start_ts
        # fleet read-view anchor: how many foreign commits the replica
        # had applied when this view was captured.  Writers hand it to
        # lock/prewrite so a peer commit applied AFTER these reads (but
        # with a commit_ts a pure ts comparison would pass) still
        # raises a write conflict (kv/shared_store._view_conflict).
        # None on engines without the hazard (solo / region view).
        rvs = getattr(store.mvcc, "read_view_seq", None)
        self.view_seq = rvs() if rvs is not None else None

    def _wait_out_lock(self, bo, err):
        """One budgeted backoff step of the lock-wait loop (reference:
        boTxnLockFast through the per-request Backoffer).  Budget
        exhaustion re-raises the LOCK error, not a generic timeout: a
        lock still held past the budget is an abandoned txn, and the GC
        worker's stale-lock resolution owns those."""
        if bo is None:
            from ..utils.backoff import Backoffer
            bo = Backoffer(budget_ms=self.LOCK_WAIT_S * 1000,
                           wall_clock=True)
        from ..errors import BackoffExhaustedError
        try:
            bo.backoff("txnLockFast", err)
        except BackoffExhaustedError:
            raise err
        return bo

    def get(self, key: bytes):
        bo = None
        while True:
            try:
                return self.store.mvcc.get(key, self.ts,
                                           own_start_ts=self.own_start_ts)
            except LockedError as e:
                bo = self._wait_out_lock(bo, e)

    def batch_get(self, keys):
        bo = None
        while True:
            try:
                return {k: v for k in keys
                        if (v := self.store.mvcc.get(
                            k, self.ts, own_start_ts=self.own_start_ts))
                        is not None}
            except LockedError as e:
                bo = self._wait_out_lock(bo, e)

    def scan(self, start: bytes, end: bytes, limit: int = 0):
        bo = None
        while True:
            try:
                return self.store.mvcc.scan(
                    start, end, self.ts, limit=limit,
                    own_start_ts=self.own_start_ts)
            except LockedError as e:
                bo = self._wait_out_lock(bo, e)


class Transaction:
    """Buffered txn with 2PC commit (reference: kv.Transaction + client-go)."""

    def __init__(self, store: "Storage", start_ts: int):
        self.store = store
        self.start_ts = start_ts
        self.membuf = MemBuffer()
        self.snapshot = Snapshot(store, start_ts, own_start_ts=start_ts)
        self.valid = True
        self.locked_keys: set[bytes] = set()
        self.touched_tables: set[int] = set()
        self.schema_fps: dict[int, tuple] = {}  # tid -> table.schema_fp()
        #: keys whose prewrite skips the ts-conflict check (schema-amender
        #: injected index mutations; see mvcc.OP_AMEND_FLAG)
        self.amend_keys: set[bytes] = set()
        self.committed_versions: dict[int, int] = {}  # tid -> post-commit ver
        self.for_update_ts = start_ts

    # reads see own writes first (union of membuffer and snapshot,
    # reference: executor/union_scan.go does this at executor level too)
    def get(self, key: bytes):
        v = self.membuf.get(key, _MISSING)
        if v is not _MISSING:
            return v
        return self.snapshot.get(key)

    def scan(self, start: bytes, end: bytes):
        snap = dict(self.snapshot.scan(start, end))
        for k, v in self.membuf.range_items(start, end):
            if v is None:
                snap.pop(k, None)
            else:
                snap[k] = v
        return sorted(snap.items())

    def put(self, key: bytes, value: bytes):
        self.membuf.put(key, value)

    def delete(self, key: bytes):
        self.membuf.delete(key)

    def lock_keys(self, keys, for_update_ts: int):
        self.for_update_ts = max(self.for_update_ts, for_update_ts)
        primary = next(iter(keys), None)
        if primary is None:
            return
        self.store.mvcc.acquire_pessimistic_lock(
            list(keys), primary, self.start_ts, for_update_ts,
            view_seq=getattr(self.snapshot, "view_seq", None))
        self.locked_keys.update(keys)

    def lock_keys_wait(self, keys, for_update_ts: int, timeout_s: float = 50.0):
        """Pessimistic lock with budgeted backoff while another txn holds
        a lock, raising LockWaitTimeout once the budget is spent
        (reference: client-go pessimistic lock waiting through boTxnLock +
        innodb_lock_wait_timeout).  Deadlocks and write conflicts
        propagate immediately."""
        from ..errors import (BackoffExhaustedError, LockedError, TiDBError,
                              ErrCode)
        from ..utils.backoff import Backoffer
        keys = list(keys)
        if not keys:
            return
        bo = Backoffer(budget_ms=timeout_s * 1000, wall_clock=True)
        while True:
            try:
                self.lock_keys(keys, for_update_ts)
                return
            except LockedError as e:
                try:
                    bo.backoff("txnLock", e)
                except BackoffExhaustedError:
                    # drop our wait-for edge: a timed-out waiter is no
                    # longer waiting, and a stale edge would make the
                    # detector see phantom cycles for innocent sessions
                    self.store.mvcc.clear_wait(self.start_ts)
                    raise TiDBError(
                        "Lock wait timeout exceeded; try restarting "
                        "transaction", code=ErrCode.LockWaitTimeout)

    def commit(self) -> int:
        """2PC: prewrite all → get commit_ts → commit. Returns commit_ts."""
        if not self.valid:
            raise TiDBError("transaction is not valid")
        self.valid = False
        muts = []
        for key, value in self.membuf.items_sorted():
            op = OP_DEL if value is None else OP_PUT
            if key in self.amend_keys:
                op |= OP_AMEND_FLAG
            muts.append((key, op, value))
        for key in self.locked_keys:
            if key not in self.membuf:
                muts.append((key, OP_LOCK, None))
        if not muts:
            self.store.mvcc.clear_wait(self.start_ts)
            return self.start_ts
        primary = muts[0][0]
        try:
            # the inject must sit INSIDE the rollback guard: self.valid is
            # already False, so a failure here that skipped the rollback
            # would orphan the txn's pessimistic locks forever (the caller's
            # rollback() no-ops) — the next writer would wait out its whole
            # lock budget against a dead txn
            _inject_2pc("txn-before-prewrite")
            # the view anchor is the txn's begin snapshot: optimistic
            # writes computed from it must conflict with any peer commit
            # applied since; pessimistically locked keys are exempt
            # inside the check (their anchor was the lock-time
            # for-update view)
            self.store.mvcc.prewrite(
                muts, primary, self.start_ts,
                view_seq=getattr(self.snapshot, "view_seq", None))
        except Exception:
            self.store.mvcc.rollback([m[0] for m in muts], self.start_ts)
            raise
        # crash window: locks written, nothing committed. An IN-PROCESS
        # failure here must release the locks (self.valid is already False,
        # so the caller's rollback would no-op and orphan them); a real
        # process crash instead leaves them for the resolve-lock path.
        try:
            _inject_2pc("txn-after-prewrite")
            commit_ts = self.store.next_ts()
            # fault point between TSO grant and the commit write — the
            # widest crash window of the 2PC protocol (chaos harness)
            _inject_2pc("txn-before-commit")
        except BaseException:
            self.store.mvcc.rollback([m[0] for m in muts], self.start_ts)
            raise
        self.store.mvcc.commit([m[0] for m in muts], self.start_ts, commit_ts)
        self.store.mvcc.clear_wait(self.start_ts)
        for tid in self.touched_tables:
            self.committed_versions[tid] = \
                self.store.mvcc.bump_table_version(tid, commit_ts)
        return commit_ts

    def rollback(self):
        if not self.valid:
            return
        self.valid = False
        keys = [k for k, _ in self.membuf.items_sorted()] + list(self.locked_keys)
        if keys:
            self.store.mvcc.rollback(keys, self.start_ts)
        self.store.mvcc.clear_wait(self.start_ts)


class Storage:
    """Process-wide storage handle (reference: kv.Storage).

    backend: "native" (C++ engine, native/mvcc_engine.cpp), "python"
    (kv/mvcc.py), or "auto" (native when buildable, else python) — the
    reference's store registry role (store.Register/New).

    ``wal_dir`` (or env ``TIDB_TPU_WAL_DIR``) makes the store DURABLE:
    the python engine wrapped in kv/shared_store.DurableMVCCStore —
    write-ahead logged, crash-recovered, and fleet-coherent when the
    fabric coordination segment is active (the durable substrate owns
    the version-chain format, so it pins the python engine; a native
    checkpoint codec is an open ROADMAP corner).

    ``mvcc`` injects a prebuilt engine directly — the region-sharded
    router (fabric/region.RegionStore) plugs in here so Transaction /
    Snapshot run unchanged over a keyspace split across region WALs."""

    def __init__(self, backend: str = "auto",
                 wal_dir: "str | None" = None, mvcc=None):
        if mvcc is not None:
            self.mvcc = mvcc
        elif wal_dir:
            from .shared_store import open_durable_mvcc
            self.mvcc = open_durable_mvcc(wal_dir)
        else:
            self.mvcc = _new_engine(backend)
        self.backend = type(self.mvcc).__name__
        self._lock = threading.Lock()

    def next_ts(self) -> int:
        return self.mvcc.tso.next_ts()

    def _catch_up(self):
        """Fleet read coherence: a new read view first applies every
        peer commit already in the log, so a statement begun after a
        sibling worker's commit returned always sees it."""
        cu = getattr(self.mvcc, "catch_up", None)
        if cu is not None:
            cu()

    def _fresh_read_ts(self) -> int:
        """Default-ts acquisition for a new read view.  A fleet-attached
        durable engine routes through kv/shared_store.fresh_read_ts —
        the ts is fenced above every live peer's durable commit frontier
        and the call blocks until the local replica applied through it
        (the cross-worker linearizability point).  Engines without the
        method (solo / in-memory / native) just mint a ts."""
        fresh = getattr(self.mvcc, "fresh_read_ts", None)
        if fresh is not None:
            return fresh()
        return self.next_ts()

    def begin(self, start_ts: int | None = None) -> Transaction:
        self._catch_up()
        if start_ts is not None:
            self._check_safepoint(start_ts)
        return Transaction(self, start_ts if start_ts is not None else self._fresh_read_ts())

    def get_snapshot(self, ts: int | None = None) -> Snapshot:
        self._catch_up()
        if ts is not None:
            self._check_safepoint(ts)
        return Snapshot(self, ts if ts is not None else self._fresh_read_ts())

    def close(self):
        """Release durable-store resources (tailer thread + WAL fds);
        a plain in-memory engine has nothing to release."""
        c = getattr(self.mvcc, "close", None)
        if c is not None:
            c()

    def _check_safepoint(self, ts: int):
        """A read view below the GC safepoint would see a history that GC
        already pruned (reference: store/driver checks GC safepoint and
        returns ErrGCTooEarly 9006)."""
        sp = getattr(self.mvcc, "safe_point", 0)
        if sp and ts < sp:
            raise TiDBError(
                "GC life time is shorter than transaction duration",
                code=ErrCode.GCTooEarly)

    def current_version(self) -> int:
        return self.next_ts()


def _new_engine(backend: str):
    import os
    if backend == "auto":  # env only decides the unspecified case;
        backend = os.environ.get("TIDB_TPU_KV_ENGINE", "auto")
    if backend == "python":
        return MVCCStore()
    from .native import NativeMVCCStore, load_engine
    if backend == "native":
        return NativeMVCCStore()
    return NativeMVCCStore() if load_engine() is not None else MVCCStore()


def new_store(backend: str = "auto",
              wal_dir: "str | None" = None) -> Storage:
    """reference: store.New("unistore://...").  ``wal_dir`` (or env
    ``TIDB_TPU_WAL_DIR``, the fabric worker's spawn contract) opens the
    durable write-ahead-logged store instead of the in-memory engine."""
    if wal_dir is None:
        import os
        wal_dir = os.environ.get("TIDB_TPU_WAL_DIR") or None
    return Storage(backend=backend, wal_dir=wal_dir)
