"""Observability state shared by all sessions of a Domain: slow-query log,
statement summary and a metrics registry.

Reference roles: slow log (`executor/slow_query.go` + SlowLogFormat in
sessionctx/variable/session.go), statement summary
(`util/stmtsummary/statement_summary.go`), Prometheus metrics
(`metrics/metrics.go:169`). All three are fed from one hook in the
session statement loop and read back through information_schema memtables,
keeping the reference's "observability is SQL-queryable" property."""

from __future__ import annotations

import collections
import threading
import time


class SlowQueryItem:
    __slots__ = ("ts", "user", "db", "duration_s", "digest", "sql",
                 "rows", "succ", "plan")

    def __init__(self, ts, user, db, duration_s, digest, sql, rows, succ,
                 plan=""):
        self.ts = ts
        self.user = user
        self.db = db
        self.duration_s = duration_s
        self.digest = digest
        self.sql = sql
        self.rows = rows
        self.succ = succ
        self.plan = plan


class StmtSummary:
    """Per-digest aggregate (reference: stmtSummaryByDigest)."""

    __slots__ = ("digest", "sample_sql", "db", "exec_count", "sum_latency",
                 "max_latency", "min_latency", "sum_rows", "first_seen",
                 "last_seen", "err_count")

    def __init__(self, digest, sample_sql, db):
        self.digest = digest
        self.sample_sql = sample_sql
        self.db = db
        self.exec_count = 0
        self.sum_latency = 0.0
        self.max_latency = 0.0
        self.min_latency = float("inf")
        self.sum_rows = 0
        self.first_seen = time.time()
        self.last_seen = self.first_seen
        self.err_count = 0

    def add(self, latency_s, rows, succ):
        self.exec_count += 1
        self.sum_latency += latency_s
        self.max_latency = max(self.max_latency, latency_s)
        self.min_latency = min(self.min_latency, latency_s)
        self.sum_rows += rows
        self.last_seen = time.time()
        if not succ:
            self.err_count += 1


class Observability:
    def __init__(self, slow_log_cap=1024, summary_cap=512):
        self._lock = threading.Lock()
        self.slow_queries = collections.deque(maxlen=slow_log_cap)
        self.stmt_summary: "collections.OrderedDict[str, StmtSummary]" = \
            collections.OrderedDict()
        self._summary_cap = summary_cap
        # metrics: flat counter/gauge registry (reference: metrics/metrics.go)
        self.counters = collections.Counter()
        # gauges are SET, not incremented: point-in-time values like the
        # supervisor's "abandoned device calls outstanding"
        # (executor/supervisor.py publishes into every registered sink)
        self.gauges: dict = {}

    def inc(self, name, n=1):
        with self._lock:
            self.counters[name] += n

    def set_gauge(self, name, value):
        with self._lock:
            self.gauges[name] = value

    def gauge_snapshot(self) -> dict:
        with self._lock:
            return dict(self.gauges)

    def observe_stmt(self, *, user, db, sql, digest, latency_s, rows, succ,
                     slow_threshold_s, plan=""):
        with self._lock:
            st = self.stmt_summary.get(digest)
            if st is None:
                while len(self.stmt_summary) >= self._summary_cap:
                    self.stmt_summary.popitem(last=False)
                st = self.stmt_summary[digest] = StmtSummary(digest, sql, db)
            st.add(latency_s, rows, succ)
            self.counters["executor_statement_total"] += 1
            if not succ:
                self.counters["executor_statement_error_total"] += 1
            if latency_s >= slow_threshold_s:
                self.slow_queries.append(SlowQueryItem(
                    time.time(), user, db, latency_s, digest, sql, rows,
                    succ, plan))
