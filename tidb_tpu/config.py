"""Layered server configuration (reference: config/config.go — TOML file →
CLI flag override → dynamic sysvars; config-check mode).

Only the knobs this engine actually consumes are modeled; unknown TOML keys
fail loudly under --config-check (reference config-strict behavior) and
warn otherwise."""

from __future__ import annotations

import dataclasses
import sys


@dataclasses.dataclass
class StatusConfig:
    report_status: bool = True
    status_host: str = "127.0.0.1"
    status_port: int = 10080


@dataclasses.dataclass
class PerformanceConfig:
    mem_quota_query: int = 1 << 30
    executor_engine: str = "auto"      # auto | host | tpu | tpu-mpp
    mesh_shape: str = "1"
    slow_log_threshold_ms: int = 300
    #: startup cost-model micro-bench (planner/cost_model.py): measures
    #: seek/hash-build/sort constants relative to the vectorized scan on
    #: this machine and installs them as the tidb_opt_* globals
    calibrate_costs: bool = True


@dataclasses.dataclass
class SecurityConfig:
    skip_grant_table: bool = False
    #: PEM cert/key enabling the wire protocol's in-handshake TLS upgrade
    ssl_cert: str = ""
    ssl_key: str = ""
    #: generate a self-signed cert at startup when no cert is configured
    #: (reference: security.auto-tls)
    auto_tls: bool = False


@dataclasses.dataclass
class Config:
    host: str = "127.0.0.1"
    port: int = 4000
    store: str = "auto"                # auto | native | python (kv engine)
    path: str = ""                     # reserved: persistent store path
    status: StatusConfig = dataclasses.field(default_factory=StatusConfig)
    performance: PerformanceConfig = dataclasses.field(
        default_factory=PerformanceConfig)
    security: SecurityConfig = dataclasses.field(
        default_factory=SecurityConfig)

    def apply_toml(self, data: dict, strict: bool = False):
        unknown = []

        def fill(obj, d, prefix=""):
            names = {f.name: f for f in dataclasses.fields(obj)}
            for k, v in d.items():
                key = k.replace("-", "_")
                if key not in names:
                    unknown.append(prefix + k)
                    continue
                cur = getattr(obj, key)
                if dataclasses.is_dataclass(cur):
                    if isinstance(v, dict):
                        fill(cur, v, prefix + k + ".")
                    else:  # scalar assigned to a [section]: invalid
                        unknown.append(f"{prefix}{k} (expected a table)")
                else:
                    setattr(obj, key, type(cur)(v) if cur is not None else v)

        fill(self, data)
        if unknown:
            msg = f"unknown config keys: {', '.join(unknown)}"
            if strict:
                raise ValueError(msg)
            print(f"[warn] {msg}", file=sys.stderr)
        return self


def load_config(path: str | None, strict: bool = False) -> Config:
    cfg = Config()
    if path:
        import tomllib
        with open(path, "rb") as f:
            cfg.apply_toml(tomllib.load(f), strict=strict)
    return cfg
