"""MPP SQL execution: fused scan/join/agg fragments run SPMD over a
device mesh — the reference's MPP fragment execution wired into the SQL
path (planner/core/fragment.go cuts plans at exchange boundaries;
store/copr/mpp.go:65 constructs per-node tasks; executor/mpp_gather.go
streams fragments back; unistore/cophandler/mpp_exec.go runs them).

TPU-native translation: one `shard_map`-jitted SPMD program per fragment.
- The probe-spine fact table is row-sharded over the mesh axis (the
  reference's region sharding, §2.2 DP); every dimension table is
  replicated (broadcast hash join — the PhysicalExchangeSender Broadcast
  type).
- Each shard runs the SAME fused scan→filter→join→partial-agg body the
  single-chip path compiles (device_join.compile_fragment), producing a
  `capacity`-bounded partial aggregate state.
- Exchange = `all_gather` of the bounded partial states over ICI; the
  final merge is simply a second `_agg_impl` over the gathered partials
  (partial/final parallel hash agg, executor/aggregate.go:85-165),
  replicated on every shard. No host hop anywhere inside the fragment.

The single-chip compile-amortization stack carries across the mesh
(ROADMAP item 1):
- **Bucketed shard shapes**: per-shard leaf placements pad to geometric
  row buckets (ops/device.py bucket_rows applied per shard), replicated
  dimensions pad to whole-table buckets, and every leaf's LIVE row count
  is a TRACED scalar null-masked in-program — a within-bucket INSERT
  re-dispatches the already-compiled SPMD program with ZERO new XLA
  compiles.
- **Compiled-fragment cache**: pipelines key on (mesh shape, per-leaf
  bucket tuple, fragment signature incl. dictionary-CONTENT sigs,
  capacities) and flow through the shared _PIPE_CACHE with its
  hit/miss/compile_s stats; converged capacities are LEARNED per
  signature (device_join._CAP_STORE) so repeat executions start tight.
- **Residency + epoch fencing**: every mesh placement registers its
  bytes in the ops/residency.py ledger via a CacheOwner (per-group
  charging, LRU eviction, OOM evict-all) and carries the device epoch —
  a post-fence/restart mesh can never serve stale shards.
- **Radix-partitioned exchange**: the shuffle join's repartition is a
  two-level radix partition (mix64 high bits → destination shard, low
  bits → cap-bounded sub-buckets; "Efficient Multiway Hash Join on
  Reconfigurable Hardware", PAPERS.md) through ONE tiled lax.all_to_all,
  reporting the exact worst-bucket count so an overflow retry jumps
  straight to the required capacity.

Static shapes throughout: join expansions and agg states are capacity-
bounded with overflow flags `pmax`-reduced across the mesh; the host
retries with grown capacities — one extra compile, never wrong results.
"""

from __future__ import annotations

import collections
import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.jaxcompat import shard_map

from ..ops import device as dev
from ..ops.device import DeviceUnsupported
from ..parallel.mpp import RADIX_SUB, _mix64, _radix_bucket
from .device_exec import (
    _assemble_agg, _estimate_groups, _plan_agg, acquire_pipeline,
    engine_mode)
from .device_join import (
    _CAP_STORE, _JoinNode, _Leaf, _cap_store_put, _combined_join_keys,
    _join_expand, _shift_expr, collect_tree, fragment_sig)

AXIS = "part"

#: merge op per partial op for the final stage: partial counts re-sum,
#: partial sums re-sum, min/max merge with themselves, first takes any
_MERGE_OP = {"count": "sum_i", "sum_i": "sum_i", "sum_f": "sum_f",
             "min": "min", "max": "max", "first": "first"}

#: observability: fragments actually executed through the mesh path.
#: exchange_retries = transport faults re-dispatched on the same shapes;
#: exchange_overflow_retries = radix sub-bucket overflow recompiles at a
#: larger exchange capacity (the hot-key convergence counter);
#: retries = all capacity-growth recompiles (joins, agg, exchange).
MPP_STATS = {"fragments": 0, "retries": 0, "shuffle_joins": 0,
             "skew_broadcasts": 0, "exchange_retries": 0,
             "exchange_overflow_retries": 0}

_MESH_CACHE: dict[int, object] = {}


def mpp_mesh(ctx):
    """The session's mesh, or None when the MPP engine isn't selected.
    `tidb_mpp_devices` = 0 means every visible device."""
    if engine_mode(ctx) != "tpu-mpp":
        return None
    try:
        n = int(ctx.get_sysvar("tidb_mpp_devices"))
    except Exception:
        n = 0
    ndev = len(jax.devices())
    if n <= 0:
        n = ndev
    n = min(n, ndev)
    if n < 2:
        return None  # nothing to distribute over
    mesh = _MESH_CACHE.get(n)
    if mesh is None:
        from ..parallel import make_mesh
        mesh = make_mesh(n, axis=AXIS)
        _MESH_CACHE[n] = mesh
    return mesh


# ---------------------------------------------------------------------------
# mesh placement cache (the HBM-resident working set, per mesh) — every
# entry's bytes live on the ops/residency.py ledger through a CacheOwner:
# per-tenant charging, LRU eviction under budget pressure, the OOM
# evict-all ladder, and the device epoch all apply to mesh shards exactly
# as to single-chip Column uploads.  An epoch bump (backend fence, OOM
# recovery) invalidates every placement: residency.lookup refuses the
# stale entry and the next dispatch re-places from the host columns.
# ---------------------------------------------------------------------------

#: (id(col), id(mesh), sharded, total_rows) → (CacheOwner, pinned col).
#: The pinned Column keeps the id() key sound (a live object never shares
#: its id with a new allocation) — same convention as _PIPE_CACHE's
#: dict_refs.  The cached device arrays themselves live on the owner via
#: the residency manager, NOT here, so eviction works owner-by-owner.
_MPP_PLACE_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_PLACE_CACHE_MAX = 128
_PLACE_LOCK = threading.Lock()


def _place_col(col, data, nulls, mesh, sharded, total):
    """Pad `col`'s host arrays to `total` rows and device_put them onto
    the mesh (row-sharded over AXIS or replicated), cached through the
    residency ledger.  `total` is a bucket shape (multiple of the mesh
    size when sharded): a within-bucket delta re-places (new column
    identity) but re-dispatches the same compiled program."""
    from ..ops import residency
    key = (id(col), id(mesh), sharded, total)
    with _PLACE_LOCK:
        hit = _MPP_PLACE_CACHE.get(key)
        if hit is not None:
            _MPP_PLACE_CACHE.move_to_end(key)
            owner = hit[0]
        else:
            owner = residency.CacheOwner()
            _MPP_PLACE_CACHE[key] = (owner, col)
            while len(_MPP_PLACE_CACHE) > _PLACE_CACHE_MAX:
                _MPP_PLACE_CACHE.popitem(last=False)
    cached = residency.lookup(owner, total)
    if cached is None:
        d = dev.pad_host(np.asarray(data), total)
        nl = dev.pad_host(np.asarray(nulls), total, True)
        spec = NamedSharding(mesh, P(AXIS) if sharded else P())
        built = (jax.device_put(d, spec), jax.device_put(nl, spec))
        # compare-and-keep publish: a racing placement's loser arrays are
        # accounted as immediately evicted, never leaked off-ledger
        cached = residency.publish(owner, *built)
    return cached


def place_cache_bytes() -> int:
    """Bytes of mesh placements currently live on the residency ledger
    (the ``mpp_place_bytes`` gauge).  Reads through the ledger so the
    value can never drift from what verify_ledger() accounts."""
    return _place_cache_view()[1]


def _place_cache_view():
    """(entry count, ledger bytes) from ONE placement-lock acquisition
    (and one ledger-lock acquisition inside resident_nbytes_total) — the
    gauge pass runs per query and per /status//metrics scrape."""
    from ..ops import residency
    with _PLACE_LOCK:
        owners = [ent[0] for ent in _MPP_PLACE_CACHE.values()]
    return len(owners), residency.resident_nbytes_total(owners)


def snapshot() -> dict:
    """MPP observability snapshot for /status and bench lines."""
    entries, nbytes = _place_cache_view()
    return {**MPP_STATS, "place_entries": entries,
            "mpp_place_bytes": nbytes}


def report_gauges() -> dict:
    """Surfacing policy shared by EXPLAIN ANALYZE / bench lines (mirrors
    residency.report_gauges): placement bytes always once the mesh path
    has run, counters only when they have ever fired."""
    s = snapshot()
    if not s["fragments"] and not s["mpp_place_bytes"]:
        return {}
    out = {"mpp_place_bytes": s["mpp_place_bytes"],
           "mpp_fragments": s["fragments"]}
    for k in ("retries", "exchange_retries", "exchange_overflow_retries",
              "shuffle_joins", "skew_broadcasts"):
        if s[k]:
            out["mpp_" + k] = s[k]
    return out


def _publish_gauges(ctx):
    obs = getattr(getattr(ctx, "domain", None), "observe", None)
    if obs is not None and hasattr(obs, "set_gauge"):
        try:
            for k, v in report_gauges().items():
                obs.set_gauge(k, v)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# radix hash-shuffle exchange (the Hash exchange type — reference:
# planner/core/fragment.go:37,64 ExchangeSender{HashPartition},
# store/copr/mpp.go:65; here: two-level radix bucketize + one tiled
# lax.all_to_all over ICI; partition shape per "Efficient Multiway Hash
# Join on Reconfigurable Hardware")
# ---------------------------------------------------------------------------

def _dest_hash(key_ds):
    """mix64 fold of the (multi-)column join key. Both join sides use the
    same fold, so equal keys land on the same shard; the HIGH bits pick
    the destination and the LOW bits the radix sub-bucket (independent
    for a well-mixed hash)."""
    h = jnp.zeros(key_ds[0].shape[0], dtype=jnp.uint64)
    for d in key_ds:
        h = _mix64(h ^ _mix64(d.astype(jnp.int64)))
    return h


def _exchange_leaf(col_pairs, h, valid, n_shards, n_sub, cap):
    """Repartition one leaf's per-shard rows by the key hash `h`:
    two-level radix partition (high bits → destination shard, low bits →
    one of `n_sub` sub-buckets, each `cap`-bounded) via a sort-based
    gather (no scatter), then one tiled all_to_all per column so each
    shard ends up holding exactly the rows hashed to it.

    col_pairs: [(data, nulls)] local slices; returns (new_col_pairs,
    new_valid, need) with n_shards*n_sub*cap rows per shard — each
    destination's region is the contiguous, equal-sized [n_sub, cap]
    block the tiled all_to_all splits on.  `need` is the EXACT worst
    sub-bucket row count: when it exceeds `cap` rows were dropped and the
    host retries with capacity next_pow2(need) — one jump, not a blind
    doubling ladder under a hot key."""
    m = valid.shape[0]
    bucket, nb = _radix_bucket(h, valid, n_shards, n_sub)
    order = jnp.argsort(bucket)
    sb = bucket[order]
    bucket_ids = jnp.arange(nb, dtype=sb.dtype)
    starts = jnp.searchsorted(sb, bucket_ids, side="left")
    cnt = jnp.searchsorted(sb, bucket_ids, side="right") - starts
    need = jnp.max(cnt)
    b_grid = jnp.repeat(bucket_ids, cap)
    c_grid = jnp.tile(jnp.arange(cap, dtype=sb.dtype), nb)
    src = jnp.clip(starts[b_grid] + c_grid, 0, jnp.maximum(m - 1, 0))
    rows = order[src]
    slot_valid = c_grid < cnt[b_grid]

    def x(a):
        return jax.lax.all_to_all(a, AXIS, 0, 0, tiled=True)

    out_cols = [(x(d[rows]), x(nl[rows])) for d, nl in col_pairs]
    return out_cols, x(slot_valid), need


# ---------------------------------------------------------------------------
# the SPMD fragment program
# ---------------------------------------------------------------------------

def _build_mpp_pipeline(mesh, leaves, joins, root, sharded_ids, leaf_cond_fns,
                        cond_fns, key_fns, n_keys, val_plan, agg_ops,
                        capacity, key_pack, env_specs, shuffle=None):
    """shard_map + jit the whole fragment: per-shard fused body → partial
    agg → all_gather → replicated final merge. Same body structure as
    device_join.compile_fragment but per-shard shapes come from the traced
    env and each leaf masks its rows at its TRACED live count (`n_lives`,
    one scalar per leaf): env arrays are bucket-padded past the live rows,
    and padding can never survive a filter, an exchange, a join probe or
    the aggregate — the single-chip bucketing invariant, meshwide.

    shuffle: None (broadcast join) or (node, left_leaf, right_leaf,
    cap_l, cap_r) — radix-repartition BOTH sides of `node` by join key
    over the mesh before the local join (the Hash exchange type); cap_*
    bound each radix SUB-bucket."""
    merge_ops = tuple(_MERGE_OP[o] for o in agg_ops)
    n_joins = len(joins)
    n_shards = mesh.shape[AXIS]
    n_xovf = 2 if shuffle is not None else 0
    sharded_set = frozenset(sharded_ids)
    n_sub = RADIX_SUB

    def body(env, n_lives):
        overflows = []
        span_ovfs = []
        env = dict(env)
        leaf_valid = {}
        conds_consumed = set()
        xneeds = []

        def base_mask(leaf, n):
            # the bucketed-shape live mask: a sharded leaf holds rows
            # [i*psb, (i+1)*psb) of the padded global array, so its live
            # rows are the ones whose GLOBAL index is < the traced count
            nl = n_lives[leaf.leaf_id]
            if leaf.leaf_id in sharded_set:
                off = jax.lax.axis_index(AXIS).astype(jnp.int64) * n
                return off + jnp.arange(n) < nl
            return jnp.arange(n) < nl

        if shuffle is not None:
            node, llid, rlid, cap_l, cap_r = shuffle
            for leaf_id, kfns, xcap in ((llid, node._lk_fns, cap_l),
                                        (rlid, node._rk_fns, cap_r)):
                leaf = leaves[leaf_id]
                n = env[leaf.offset][0].shape[0]
                valid = base_mask(leaf, n)
                # pre-exchange filter: leaf conds cut exchange volume
                for f in leaf_cond_fns[leaf_id]:
                    d, nl = f(env)
                    valid = valid & jnp.broadcast_to((d != 0) & ~nl, (n,))
                conds_consumed.add(leaf_id)
                kds, knulls = zip(*[dev.broadcast_1d(*f(env), n)
                                    for f in kfns])
                for nl in knulls:
                    valid = valid & ~nl    # null keys never match: drop
                h = _dest_hash(kds)
                cols = [env[leaf.offset + i] for i in range(leaf.ncols)]
                out_cols, out_valid, need = _exchange_leaf(
                    cols, h, valid, n_shards, n_sub, xcap)
                for i in range(leaf.ncols):
                    env[leaf.offset + i] = out_cols[i]
                leaf_valid[leaf_id] = out_valid
                xneeds.append(need)

        def leaf_rel(leaf):
            n = env[leaf.offset][0].shape[0]
            mask = leaf_valid.get(leaf.leaf_id)
            if mask is None:
                mask = base_mask(leaf, n)
            if leaf.leaf_id not in conds_consumed:
                for f in leaf_cond_fns[leaf.leaf_id]:
                    d, nl = f(env)
                    mask = mask & jnp.broadcast_to((d != 0) & ~nl, (n,))
            return {leaf.leaf_id: jnp.arange(n)}, mask

        def gather_env(idxmap, node):
            out = {}
            for leaf in leaves:
                if leaf.leaf_id in idxmap:
                    if not (node.offset <= leaf.offset
                            < node.offset + node.ncols):
                        continue
                    idx = idxmap[leaf.leaf_id]
                    for i in range(leaf.ncols):
                        d, nl = env[leaf.offset + i]
                        out[leaf.offset + i] = (d[idx], nl[idx])
            return out

        def eval_node(node):
            if isinstance(node, _Leaf):
                return leaf_rel(node)
            lidx, lvalid = eval_node(node.left)
            ridx, rvalid = eval_node(node.right)
            lenv = gather_env(lidx, node.left)
            renv = gather_env(ridx, node.right)
            lkds, lknulls = zip(*[
                dev.broadcast_1d(*f(lenv), lvalid.shape[0])
                for f in node._lk_fns])
            rkds, rknulls = zip(*[
                dev.broadcast_1d(*f(renv), rvalid.shape[0])
                for f in node._rk_fns])
            pk_d, pvalid, bk_d, bvalid, sovf = _combined_join_keys(
                lkds, lknulls, lvalid, rkds, rknulls, rvalid)
            span_ovfs.append(sovf)
            pi, bi, valid, ovf = _join_expand(
                bk_d, bvalid, pk_d, pvalid, node.cap)
            overflows.append(ovf)
            idxmap = {k: v[pi] for k, v in lidx.items()}
            idxmap.update({k: v[bi] for k, v in ridx.items()})
            if node._oc_fns:
                jenv = gather_env(idxmap, node)
                for f in node._oc_fns:
                    d, nl = f(jenv)
                    valid = valid & (d != 0) & ~nl
            return idxmap, valid

        idxmap, valid = eval_node(root)
        fenv = gather_env(idxmap, root)
        mask = valid
        for f in cond_fns:
            d, nl = f(fenv)
            mask = mask & (d != 0) & ~nl
        n_out = mask.shape[0]
        key_cols, key_nulls = [], []
        for f in key_fns:
            d, nl = dev.broadcast_1d(*f(fenv), n_out)
            key_cols.append(d.astype(jnp.int64))
            key_nulls.append(nl)
        if not key_cols:
            key_cols = [jnp.zeros(n_out, dtype=jnp.int64)]
            key_nulls = [jnp.zeros(n_out, dtype=bool)]
        val_cols, val_nulls = [], []
        for f, conv in val_plan:
            d, nl = dev.broadcast_1d(*f(fenv), n_out)
            if conv == "int":
                d = d.astype(jnp.int64)
            val_cols.append(d)
            val_nulls.append(nl)

        # stage 1: per-shard partial aggregation into bounded state
        pk, pkn, pres, presn, png, pvalid = dev._agg_impl(
            tuple(key_cols), tuple(key_nulls),
            tuple(val_cols), tuple(val_nulls), mask,
            n_keys=n_keys, agg_ops=agg_ops, capacity=capacity,
            pack=key_pack)

        # exchange: every shard's bounded partial state (capacity rows —
        # tiny next to N) rides ICI to every shard
        def g(x):
            return jax.lax.all_gather(x, AXIS, tiled=True)

        gk = tuple(g(k) for k in pk)
        gkn = tuple(g(k) for k in pkn)
        gres = tuple(g(r) for r in pres)
        gresn = tuple(g(r) for r in presn)
        gvalid = g(pvalid)

        # stage 2: replicated final merge — just another _agg_impl over
        # the gathered partials with partial→merge op mapping
        f_out = dev._agg_impl(gk, gkn, gres, gresn, gvalid,
                              n_keys=n_keys, agg_ops=merge_ops,
                              capacity=capacity, pack=key_pack)
        png_max = jax.lax.pmax(png, AXIS)
        # exact per-join required totals (pmax: worst shard governs the
        # static capacity); int64 — totals exceed int32 at TPC-H scale
        ovfs = tuple(jax.lax.pmax(o.astype(jnp.int64), AXIS)
                     for o in overflows)
        sovfs = tuple(jax.lax.pmax(o.astype(jnp.int32), AXIS)
                      for o in span_ovfs)
        # exact worst radix sub-bucket counts (not booleans): the retry
        # jumps straight to next_pow2(need)
        xneeds_out = tuple(jax.lax.pmax(o.astype(jnp.int64), AXIS)
                           for o in xneeds)
        return f_out, png_max, ovfs, sovfs, xneeds_out

    n_res = len(val_plan)
    out_specs = (
        ((P(),) * n_keys, (P(),) * n_keys, (P(),) * n_res, (P(),) * n_res,
         P(), P()),
        P(),
        (P(),) * n_joins,
        (P(),) * n_joins,
        (P(),) * n_xovf,
    )
    wrapped = shard_map(
        body, mesh=mesh,
        in_specs=(env_specs, (P(),) * len(leaves)),
        out_specs=out_specs, check_vma=False)

    def entry(env, n_lives):
        # trace marker OUTSIDE the shard_map body (which tracing may
        # evaluate more than once): mpp fragment compiles meter into the
        # same pipe-cache stats as the single-chip pipelines
        dev._note_trace()
        return wrapped(env, n_lives)

    return dev.observed_jit(entry)


# ---------------------------------------------------------------------------
# host entry points
# ---------------------------------------------------------------------------

def mpp_agg(plan, chunk, conds, ctx, mesh):
    """scan→filter→group-by fragment over the mesh (partition-parallel
    partial agg + collective merge — the shuffle-agg MPP fragment)."""
    if chunk.num_rows == 0:
        raise DeviceUnsupported("empty input")
    leaf = _Leaf(0, chunk, list(conds), 0)
    return _run_mpp(plan, [], leaf, [leaf], [], ctx, mesh)


def mpp_join_agg(agg_plan, agg_conds, child_exec, ctx, mesh):
    """join-tree→group-by fragment over the mesh: probe spine sharded,
    build sides broadcast (the broadcast hash join MPP variant)."""
    root, leaves, joins = collect_tree(child_exec)
    if any(jn.kind != "inner" for jn in joins):
        # the mesh fragment compiler shards/broadcasts inner joins only
        raise DeviceUnsupported("non-inner join in MPP fragment")
    from ..storage.paged import chunk_is_paged
    from .device_join import _col_row_bytes
    paged_est = 0
    for leaf in leaves:
        if not chunk_is_paged(leaf.chunk):
            continue
        paged_est += sum(_col_row_bytes(c)
                         for c in leaf.chunk.columns) * leaf.chunk.num_rows
    if paged_est:
        # paged leaves ARE legal on the mesh now (the last PR 7 gap) —
        # placement materializes their pages into per-shard slices, so
        # the whole placed footprint must fit the residency budget (the
        # same threshold the single-chip resident-build path uses); a
        # bigger disk table still streams through the single-chip paged
        # pipeline or the hybrid partitioned join instead
        from .device_join import _dim_resident_budget
        if paged_est > _dim_resident_budget():
            raise DeviceUnsupported(
                "paged leaves exceed the mesh residency budget")
    return _run_mpp(agg_plan, agg_conds, root, leaves, joins, ctx, mesh)


def _build_key_leaf(node, leaves):
    """The leaf inside `node`'s build (right) subtree holding ALL of the
    right-key columns — the one a Hash exchange must repartition; None
    when the keys span leaves (or reference none)."""
    used = set()
    for k in node.right_keys:
        k.columns_used(used)
    if not used:
        return None
    gls = {node.right.offset + u for u in used}
    for leaf in leaves:
        if (leaf.offset >= node.right.offset
                and leaf.offset + leaf.ncols
                <= node.right.offset + node.right.ncols
                and all(leaf.offset <= g < leaf.offset + leaf.ncols
                        for g in gls)):
            return leaf
    return None


def _run_mpp(plan, agg_conds, root, leaves, joins, ctx, mesh):
    # span tracing (session/tracing.py): one span per MPP fragment
    # dispatch, tagged with the mesh width — per-shard placement, the
    # radix exchange and the SPMD dispatch all happen inside it, and the
    # supervisor's thread-hop propagation keeps worker-side events
    # (backoff sleeps, exchange retries) on this timeline
    from ..session import tracing
    with tracing.span("mpp.fragment", shards=mesh.shape[AXIS],
                      leaves=len(leaves), joins=len(joins)):
        return _run_mpp_impl(plan, agg_conds, root, leaves, joins, ctx,
                             mesh)


def _run_mpp_impl(plan, agg_conds, root, leaves, joins, ctx, mesh):
    from ..utils import failpoint as _fp
    # chaos/supervisor hook: a `sleep(...)` here models a hung collective
    # at the MPP fragment boundary (the exchange-dispatch analog of
    # device-agg-exec / device-join-exec)
    _fp.inject("device-mpp-exec")
    n_shards = mesh.shape[AXIS]

    # The shard leaf must sit on the probe (left) spine: every join's
    # build side must be complete on every shard. Orient the tree so the
    # LARGEST table is that leaf — inner-join probe/build sides are a
    # physical choice (swapping is legal), and the global column offsets
    # are untouched (a node's column range spans both subtrees either
    # way). This also minimizes broadcast volume: big table sharded,
    # dimensions replicated.
    bottom = None
    if joins:
        target = max(leaves, key=lambda lf: lf.chunk.num_rows).leaf_id
        node = root
        prev = None
        while isinstance(node, _JoinNode):
            if target in node.right.leaf_ids:
                node.left, node.right = node.right, node.left
                node.left_keys, node.right_keys = (
                    node.right_keys, node.left_keys)
            prev = node
            node = node.left
        shard_leaf = node.leaf_id
        bottom = prev  # the spine join directly over the sharded leaf
    else:
        shard_leaf = root.leaf_id
    shard_rows = leaves[shard_leaf].chunk.num_rows
    if shard_rows < n_shards:
        raise DeviceUnsupported("too few rows to shard over the mesh")

    # broadcast-vs-shuffle for the bottom join (reference: the planner
    # picks Broadcast vs HashPartition exchange by build-side size,
    # exhaust_physical_plans.go MPP join variants): when the build-key
    # leaf is itself fact-sized, replicating it per shard would blow
    # HBM — hash-repartition it (and the probe fact) over the mesh
    # instead. The exchanged leaf is the one holding ALL the bottom
    # join's right-key columns; any other build-subtree leaves stay
    # replicated, so the subtree's local joins remain co-partitioned
    # by the exchanged key.
    shuffle_build = None
    if bottom is not None:
        bleaf = _build_key_leaf(bottom, leaves)
        if bleaf is not None:
            try:
                bc_rows = int(ctx.get_sysvar(
                    "tidb_broadcast_join_threshold_count"))
            except Exception:
                bc_rows = 10 * 1024
            build_rows = bleaf.chunk.num_rows
            if (bc_rows > 0 and build_rows > bc_rows
                    and build_rows >= n_shards):
                shuffle_build = bleaf.leaf_id
                # skew guard (SURVEY §7 "MPP shuffle skew"): a Hash
                # exchange sends every row of a key to ONE shard, so a
                # hot key turns balanced buckets into one overflowing
                # bucket — capacity growth chases the hottest key while
                # the other shards idle. The host knows the hottest
                # key's row count from the build-side join index
                # (numpy, cached per table version); when it dwarfs the
                # uniform share, fall back to the Broadcast exchange
                # (reference: the planner picks Broadcast vs
                # HashPartition by cost, exhaust_physical_plans.go MPP
                # variants — skew is a cost input here)
                from .device_join import _leaf_index
                # right_keys are subtree-relative; rebase to bleaf-local
                local = [_shift_expr(k, bottom.right.offset - bleaf.offset)
                         for k in bottom.right_keys]
                bidx = _leaf_index(bleaf, local)
                if bidx is not None:
                    even_share = max(build_rows // n_shards, 1)
                    if bidx.max_cnt > 4 * even_share:
                        shuffle_build = None
                        MPP_STATS["skew_broadcasts"] = (
                            MPP_STATS.get("skew_broadcasts", 0) + 1)
    sharded_ids = [shard_leaf] + (
        [shuffle_build] if shuffle_build is not None else [])

    # canonical BUCKET shapes per leaf (ops/device.py bucket_rows carried
    # across the mesh): a sharded leaf buckets its PER-SHARD row count
    # (total = psb * n_shards keeps the shard split exact); a replicated
    # leaf buckets its whole length.  Uploads pad to the bucket and the
    # compiled program masks each leaf at its traced live count, so a
    # within-bucket INSERT re-dispatches with zero new XLA compiles.
    per_double = dev.shape_buckets(ctx)
    leaf_total = {}
    leaf_psb = {}
    for leaf in leaves:
        rows = leaf.chunk.num_rows
        if leaf.leaf_id in sharded_ids:
            per_shard = -(-rows // n_shards)
            psb = dev.bucket_rows(per_shard, per_double)
            leaf_psb[leaf.leaf_id] = psb
            leaf_total[leaf.leaf_id] = psb * n_shards
        else:
            leaf_total[leaf.leaf_id] = dev.bucket_rows(rows, per_double)

    # metadata-only planning view (no uploads — placement happens once,
    # below, straight onto the mesh): the expression compiler and agg
    # planner read only ftype/dictionary/host_col
    host_cols = {}
    dcols = {}
    leaf_metas = []
    for leaf in leaves:
        metas = {}
        for i, c in enumerate(leaf.chunk.columns):
            dc, (hd, hn) = dev.meta_device_col(c)
            metas[i] = dc
            dcols[leaf.offset + i] = dc
            host_cols[leaf.offset + i] = (c, hd, hn)
        leaf_metas.append(metas)

    key_fns, key_meta, key_pack, val_plan, agg_ops, slots = _plan_agg(
        plan, dcols)
    n_keys = max(len(key_fns), 1)
    if any(op not in _MERGE_OP for op in agg_ops):
        # cnt_dist partial states don't merge across shards (counts, not
        # sets) — single-chip kernel handles distinct
        raise DeviceUnsupported("non-mergeable agg on the mesh path")

    leaf_cond_fns = [
        [dev.compile_expr(_shift_expr(c, leaf.offset),
                          {leaf.offset + i: dc
                           for i, dc in leaf_metas[leaf.leaf_id].items()})
         for c in leaf.conds] for leaf in leaves]
    for jn in joins:
        jn._lk_fns = [dev.compile_expr(_shift_expr(k, jn.left.offset), dcols)
                      for k in jn.left_keys]
        jn._rk_fns = [dev.compile_expr(_shift_expr(k, jn.right.offset), dcols)
                      for k in jn.right_keys]
        jn._oc_fns = [dev.compile_expr(_shift_expr(c, jn.offset), dcols)
                      for c in jn.other_conds]
    cond_fns = [dev.compile_expr(c, dcols) for c in agg_conds]

    # mesh placement: sharded fact (and shuffled build) columns +
    # replicated dimensions, bucket-padded, residency-ledgered
    env, env_specs = {}, {}
    for leaf in leaves:
        sharded = leaf.leaf_id in sharded_ids
        spec = (P(AXIS), P(AXIS)) if sharded else (P(), P())
        for i in range(leaf.ncols):
            c, hd, hn = host_cols[leaf.offset + i]
            env[leaf.offset + i] = _place_col(
                c, hd, hn, mesh, sharded, leaf_total[leaf.leaf_id])
            env_specs[leaf.offset + i] = spec
    # per-leaf LIVE row counts as TRACED scalars (leaf_id order): the
    # program masks padding in-body, so a row-count change inside the
    # bucket is a re-dispatch, never a retrace
    n_lives = tuple(np.int64(leaf.chunk.num_rows) for leaf in leaves)

    # the cache signature carries the mesh shape, the per-leaf bucket
    # tuple and (inside fragment_sig) every dictionary CONTENT sig — the
    # exact identity of the compiled SPMD program
    sig = ("mpp", n_shards, str(mesh.devices.flat[0].platform),
           fragment_sig(leaves, joins, agg_conds, plan),
           tuple(sharded_ids),
           tuple(leaf_total[leaf.leaf_id] for leaf in leaves))
    dict_refs = tuple(dc.dictionary for dc in dcols.values()
                      if dc.dictionary is not None)
    bottom_idx = joins.index(bottom) if bottom is not None else -1

    # static capacities: per-shard bucketed probe rows bound the bottom
    # join; each join's output bounds the next (FK heuristic, grown on
    # overflow). With shuffle, each exchanged side gets a per-SUB-bucket
    # capacity (~2x the uniform share), and the bottom join's probe side
    # becomes the post-exchange n_shards*RADIX_SUB*cap_l rows.  All of
    # them start from the LEARNED converged values when this signature
    # has run before (device_join._CAP_STORE): a repeat execution reuses
    # the cached compiled pipeline with zero discovery retries.
    per_shard_b = leaf_psb[shard_leaf]  # always sharded: filled above
    xcaps = None
    if shuffle_build is not None:
        learned_x = _CAP_STORE.get((sig, "xcaps"))
        if learned_x is not None:
            xcaps = list(learned_x)
        else:
            nb = n_shards * RADIX_SUB
            build_psb = leaf_psb[shuffle_build]
            xcaps = [dev.next_pow2(max(2 * (-(-per_shard_b // nb)), 8)),
                     dev.next_pow2(max(2 * (-(-build_psb // nb)), 8))]

    def leaf_rows(nd):
        if xcaps is not None and nd.leaf_id == shard_leaf:
            return n_shards * RADIX_SUB * xcaps[0]
        if nd.leaf_id == shard_leaf:
            return per_shard_b
        return leaf_total[nd.leaf_id]

    def est_rows(nd):
        # FK-join heuristic: output ≈ larger input, composed over the
        # subtree (see device_join.py est_rows) — starting from the probe
        # side alone needed a recompile per doubling to reach fact scale
        if isinstance(nd, _Leaf):
            return max(leaf_rows(nd), 8)
        return max(est_rows(nd.left), est_rows(nd.right))

    def init_caps():
        caps = []
        for jn in joins:
            jn.cap = dev.next_pow2(est_rows(jn))
            caps.append(jn.cap)
        return caps

    learned_caps = _CAP_STORE.get((sig, "caps"))
    if learned_caps is not None and len(learned_caps) == len(joins):
        caps = list(learned_caps)
    else:
        caps = init_caps()
    n_frag = caps[-1] if caps else per_shard_b
    learned_cap = _CAP_STORE.get((sig, "agg"))
    if learned_cap is not None:
        capacity = learned_cap
    else:
        est = _estimate_groups(plan, n_frag, ctx)
        capacity = dev.next_pow2(min(max(n_frag, 16), max(est, 16)))

    # retry discipline (reference: the Backoffer every coprocessor/MPP
    # dispatch carries, store/tikv/backoff.go): exchange transport faults
    # back off and retry on the SAME capacities; bucket/group overflow
    # "retries" are recompiles at larger capacity and draw from a separate
    # attempt budget.  Exhausting the transport budget surfaces a
    # classified BackoffExhaustedError (and trips the device breaker);
    # exhausting the growth budget degrades to the host engine.
    from ..utils import failpoint
    from ..utils.backoff import (Backoffer, ExchangeError)
    from ..utils.failpoint import FailpointError
    from ..errors import BackoffExhaustedError
    bo = Backoffer.for_session(ctx)
    while True:
        for jn, cap in zip(joins, caps):
            jn.cap = cap
        shuffle = None
        if shuffle_build is not None:
            shuffle = (bottom, shard_leaf, shuffle_build,
                       xcaps[0], xcaps[1])
        key = (sig, tuple(caps), tuple(xcaps or ()), capacity, key_pack,
               tuple(agg_ops))

        def build(shuffle=shuffle, cap=capacity):
            return _build_mpp_pipeline(
                mesh, leaves, joins, root, sharded_ids, leaf_cond_fns,
                cond_fns, key_fns, n_keys, val_plan, tuple(agg_ops),
                cap, key_pack, env_specs, shuffle=shuffle)
        # mesh pipelines compile SYNC through the service (no arg spec):
        # a background warm would dispatch zero-filled HOST arrays against
        # a shard_map program traced for mesh-placed shardings — a
        # different program than the one traffic dispatches.  The compile
        # still gets the breaker/persist/failpoint ladder.
        fn = acquire_pipeline(key, build, dict_refs, ctx=ctx,
                              shape="mpp", sig=sig)
        try:
            failpoint.inject("mpp-exchange-send")
            agg_out, png_d, ovfs_d, sovfs_d, xneeds_d = fn(env, n_lives)
            from .device_exec import AggFetch
            f = AggFetch(agg_out, extras=(png_d, ovfs_d, sovfs_d, xneeds_d))
            failpoint.inject("mpp-exchange-recv")
        except (FailpointError, ExchangeError, ConnectionError,
                TimeoutError) as e:
            # narrow on purpose: FileNotFoundError-class OSErrors are
            # bugs, not transient exchange weather — they must surface
            exc = (e if isinstance(e, ExchangeError)
                   else ExchangeError(f"mpp exchange failed: {e}"))
            try:
                bo.backoff("exchangeRetry", exc)
            except BackoffExhaustedError:
                from .circuit import get_breaker
                # same SESSION owner token AND the same fragment shape
                # run_device's allow() used (join trees dispatch under
                # shape="join" — charging "agg" would open the healthy
                # agg breaker and orphan the join probe's verdict); the
                # session token stays valid even though a supervised
                # dispatch runs this on a worker thread
                get_breaker(ctx,
                            shape="join" if joins else "agg").record_failure(
                    exc, session=getattr(ctx, "conn_id", None))
                raise
            MPP_STATS["exchange_retries"] += 1
            continue
        png, ovfs, sovfs, xneeds = f.extras
        fng = f.ng
        if any(int(s) for s in sovfs):
            raise DeviceUnsupported(
                "multi-key join value ranges exceed int64 packing")
        retry = False
        x_grew = False
        for i, need in enumerate(xneeds):
            if int(need) > xcaps[i]:
                # jump straight to the worst sub-bucket's exact
                # requirement (≥ a doubling — caps are powers of two):
                # one retry converges even under a dominant hot key
                xcaps[i] = dev.next_pow2(int(need))
                retry = True
                x_grew = True
                MPP_STATS["exchange_overflow_retries"] += 1
        if x_grew:
            # the bottom join's probe side grew with the exchange bucket
            caps[bottom_idx] = max(
                caps[bottom_idx],
                dev.next_pow2(max(n_shards * RADIX_SUB * xcaps[0], 8)))
        for i, o in enumerate(ovfs):
            if int(o) > caps[i]:
                # jump to the worst shard's exact requirement in one step
                caps[i] = dev.next_pow2(int(o))
                retry = True
        max_ng = max(int(png), int(fng))
        if max_ng > capacity:
            capacity = dev.next_pow2(max_ng)
            retry = True
        if not retry:
            break
        MPP_STATS["retries"] += 1
        try:
            bo.backoff("exchangeGrow")
        except BackoffExhaustedError as e:
            raise DeviceUnsupported(
                "mpp fragment capacities did not converge") from e
    # remember the converged shapes per signature: the next execution —
    # another session, the warm bench round, the post-INSERT re-run —
    # starts at these exact capacities and hits the compiled pipeline
    _cap_store_put((sig, "caps"), tuple(caps))
    if xcaps is not None:
        _cap_store_put((sig, "xcaps"), tuple(xcaps))
    _cap_store_put((sig, "agg"), capacity)
    ng = int(fng)
    if ng == 0 and not plan.group_exprs:
        raise DeviceUnsupported("empty global aggregate")
    MPP_STATS["fragments"] += 1
    if shuffle_build is not None:
        MPP_STATS["shuffle_joins"] += 1
    _publish_gauges(ctx)
    key_out, key_null_out, results, result_nulls = f.body()
    return _assemble_agg(plan, key_meta, slots, dcols,
                         (key_out, key_null_out, results, result_nulls), ng)
