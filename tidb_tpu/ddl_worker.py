"""Online DDL worker — the F1 schema-state machine with an async owner
worker and checkpointed backfill.

Reference: ddl/ddl_worker.go:155,502,728 (owner loop + runDDLJob),
ddl/index.go:519-541 (none → delete-only → write-only → write-reorganization
→ public), ddl/backfilling.go:142,290 (batched snapshot backfill with the
progress handle checkpointed in the job), ddl/rollingback.go (unique-key
violation rolls the index add back), ddl/callback.go (test hooks between
states).

Single-process adaptation: the schema cache is one Domain, so a state
transition commits + reloads the domain schema instead of waiting 2×lease
for peers; everything else — job queue in the meta KV, per-transition schema
versions, batch txns that atomically advance the checkpoint, concurrent DML
maintaining the index according to its state — keeps the reference shape.
"""

from __future__ import annotations

import logging
import threading

from . import tablecodec
from .errors import DupEntryError, ErrCode, TiDBError, WriteConflictError
from .meta import Meta
from .model import Job, JobState, SchemaState
from .table import Table

MIN_HANDLE = -(1 << 63)
DEFAULT_REORG_BATCH = 256

_log = logging.getLogger("tidb_tpu.ddl")


class DDLWorker:
    """The DDL owner role: drains the meta job queue in a background thread;
    sessions enqueue and block on completion (reference: doDDLJob blocks,
    the owner executes)."""

    def __init__(self, domain):
        self.domain = domain
        self.hooks = []           # [(event:str, job:Job) -> None]
        self.batch_size = DEFAULT_REORG_BATCH
        self._thread = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._done: dict[int, tuple[threading.Event, str | None]] = {}
        self._lock = threading.Lock()

    # -- hooks (reference: ddl/callback.go) ---------------------------------

    def on_event(self, fn):
        self.hooks.append(fn)

    def _fire(self, event: str, job: Job):
        for fn in list(self.hooks):
            fn(event, job)

    # -- session-facing API --------------------------------------------------

    def run_job(self, job_id: int, timeout: float = 120.0):
        """Wake the worker and block until the job finishes; re-raise its
        terminal error in the caller (reference: ddl.go:551 doDDLJob).

        The waiter registers AFTER the job is already visible in the queue,
        so the worker may finish it before _signal has anyone to notify —
        the wait loop therefore also polls the queue and falls back to the
        job's recorded history error."""
        import time as _time
        ev = threading.Event()
        with self._lock:
            self._done[job_id] = (ev, None)
        self._ensure_thread()
        self._wake.set()
        deadline = _time.monotonic() + timeout
        err = None
        while True:
            if ev.wait(timeout=0.05):
                with self._lock:
                    _ev, err = self._done.pop(job_id)
                break
            if not self._is_queued(job_id):
                with self._lock:
                    self._done.pop(job_id, None)
                err = self._job_error(job_id)
                break
            if _time.monotonic() > deadline:
                with self._lock:
                    self._done.pop(job_id, None)
                raise TiDBError(f"DDL job {job_id} timed out")
        if err:
            if "Duplicate entry" in err:
                raise DupEntryError(err)
            raise TiDBError(err)

    def _is_queued(self, job_id: int) -> bool:
        txn = self.domain.store.begin()
        try:
            return any(j.id == job_id for j in Meta(txn).queued_jobs())
        finally:
            txn.rollback()

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="ddl-worker", daemon=True)
                self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            try:
                self.run_pending()
            except Exception as e:
                # job-level errors are recorded on the job itself; errors
                # escaping the queue drain are worker-health signals and
                # must not vanish (satellite: classified, logged swallows)
                from .utils.backoff import classify
                _log.warning("ddl worker queue drain failed (%s): %s",
                             classify(e), e)

    # -- queue processing ----------------------------------------------------

    def run_pending(self):
        """Drain the queue (each step is its own txn; re-entrant).
        Fleet: the drain holds the segment-leased DDL owner cell,
        renewed per job — a lost lease aborts the drain loudly (the
        new owner re-drives the queue; steps are re-entrant) instead
        of letting two owners interleave one state machine."""
        from .ddl import ddl_lease_heartbeat, ddl_owner_lease
        with self.domain.ddl_lock, ddl_owner_lease() as epoch:
            while True:
                if not ddl_lease_heartbeat(epoch):
                    from .utils.backoff import LeaseExpiredError
                    raise LeaseExpiredError(
                        "ddl owner lease lost mid-drain; remaining "
                        "jobs yield to the new owner")
                job = self._peek()
                if job is None:
                    return
                try:
                    self._run_one(job)
                except Exception as e:
                    self._fail_job(job, str(e))
                    self._signal(job.id, str(e))

    def _peek(self):
        txn = self.domain.store.begin()
        try:
            return Meta(txn).peek_job()
        finally:
            txn.rollback()

    def _run_one(self, job: Job):
        steppers = {"add_index": self.step_add_index,
                    "drop_index": self.step_drop_index,
                    "add_column": self.step_add_column}
        step = steppers.get(job.type)
        if step is None:
            raise TiDBError(f"worker cannot run job type {job.type}")
        while not step(job.id):
            pass
        self._signal(job.id, self._job_error(job.id))

    def _signal(self, job_id: int, err: str | None):
        with self._lock:
            ent = self._done.get(job_id)
            if ent is not None:
                self._done[job_id] = (ent[0], err)
                ent[0].set()

    def _job_error(self, job_id: int) -> str | None:
        txn = self.domain.store.begin()
        try:
            for j in Meta(txn).history_jobs():
                if j.id == job_id:
                    return j.error or None
        finally:
            txn.rollback()
        return None

    def _fail_job(self, job: Job, err: str):
        """Terminal failure: cancel the job AND undo any half-built schema
        object — a non-public index left behind would be unreadable yet
        maintained by every DML forever, and would block a retry by name
        (reference: ddl/rollingback.go)."""
        txn = self.domain.store.begin()
        idx_id = None
        try:
            m = Meta(txn)
            phys_ids = [job.table_id]
            if job.type == "add_index":
                t = m.get_table(job.schema_id, job.table_id)
                if t is not None:
                    from .partition import index_phys_ids
                    phys_ids = index_phys_ids(t)
                    name = job.args.get("index_name", "")
                    idx = t.find_index(name)
                    if idx is not None and idx.state != SchemaState.PUBLIC:
                        idx_id = idx.id
                        t.indexes = [i for i in t.indexes if i.id != idx.id]
                        m.update_table(job.schema_id, t)
                        m.bump_schema_version()
            elif job.type == "drop_index":
                # roll FORWARD: past write-only the entries are already
                # missing for new rows — restoring PUBLIC would serve a
                # corrupt index, so a failed drop completes the removal
                t = m.get_table(job.schema_id, job.table_id)
                if t is not None:
                    from .partition import index_phys_ids
                    phys_ids = index_phys_ids(t)
                    idx = t.find_index(job.args.get("index_name", ""))
                    if idx is not None:
                        idx_id = idx.id
                        t.indexes = [i for i in t.indexes if i.id != idx_id]
                        m.update_table(job.schema_id, t)
                        m.bump_schema_version()
            elif job.type == "add_column":
                # a half-added (non-public) column must not survive the
                # cancel — it would be maintained by DML yet unreadable
                t = m.get_table(job.schema_id, job.table_id)
                if t is not None:
                    name = (job.args.get("column") or {}).get("name", "")
                    col = t.find_column(name)
                    if col is not None and col.state != SchemaState.PUBLIC:
                        t.columns = [c for c in t.columns if c is not col]
                        for off, c in enumerate(t.columns):
                            c.offset = off
                        m.update_table(job.schema_id, t)
                        m.bump_schema_version()
            job.state = JobState.CANCELLED
            job.error = err
            m.finish_job(job)
            txn.commit()
        except Exception as e:
            txn.rollback()
            # the cancel record could not persist: the job will be
            # re-peeked and re-failed next drain — log so a cancel stuck
            # in a persist-fail loop is visible
            from .utils.backoff import classify
            _log.warning("ddl job %s cancel persist failed (%s): %s",
                         job.id, classify(e), e)
        if idx_id is not None:
            for pid in phys_ids:
                start, end = tablecodec.index_range(pid, idx_id)
                self.domain.store.mvcc.raw_delete_range(start, end)
        self.domain.reload_schema()

    # -- ADD INDEX state machine (reference: ddl/index.go:519-541) ----------

    def step_add_index(self, job_id: int) -> bool:
        """One state transition (or one backfill batch). Returns True when
        the job has reached a terminal state. Public so tests can interleave
        DML between arbitrary states and simulate crashes mid-backfill."""
        store = self.domain.store
        txn = store.begin()
        m = Meta(txn)
        job = next((j for j in m.queued_jobs() if j.id == job_id), None)
        if job is None:
            txn.rollback()
            return True  # finished (or cancelled) already
        t = m.get_table(job.schema_id, job.table_id)
        if t is None:
            self._cancel_job(m, job, "table dropped during DDL")
            txn.commit()
            self.domain.reload_schema()
            return True
        name = job.args["index_name"]
        idx = t.find_index(name)
        try:
            if idx is None:
                # none → delete-only: the index object appears; DML removes
                # stale entries but does not write new ones
                from .ddl import _build_index_info
                idx = _build_index_info(
                    t, name, [(c, l) for c, l in job.args["columns"]],
                    bool(job.args.get("unique")), m)
                idx.state = SchemaState.DELETE_ONLY
                t.indexes.append(idx)
                return self._transition(m, txn, job, t,
                                        SchemaState.DELETE_ONLY)
            if idx.state == SchemaState.DELETE_ONLY:
                idx.state = SchemaState.WRITE_ONLY
                return self._transition(m, txn, job, t,
                                        SchemaState.WRITE_ONLY)
            if idx.state == SchemaState.WRITE_ONLY:
                idx.state = SchemaState.WRITE_REORG
                job.reorg_handle = MIN_HANDLE
                return self._transition(m, txn, job, t,
                                        SchemaState.WRITE_REORG)
            if idx.state == SchemaState.WRITE_REORG:
                txn.rollback()  # backfill batches run their own txns
                return self._backfill_batch(job, t, idx)
            # unexpected state (e.g. a racing CREATE INDEX already drove an
            # index of this name to PUBLIC): the job MUST leave the queue,
            # or run_pending would peek it forever
            self._cancel_job(
                m, job, f"Duplicate key name '{name}'")
            txn.commit()
            self.domain.reload_schema()
            return True
        except Exception:
            if txn.valid:
                txn.rollback()
            raise

    # -- DROP INDEX state machine (reference: ddl/index.go onDropIndex:
    #    public → write-only → delete-only → none + delete-range) ---------

    def step_drop_index(self, job_id: int) -> bool:
        """One state transition of an online DROP INDEX. The walk DOWN the
        F1 ladder mirrors ADD INDEX's walk up: at write-only the index
        stops serving reads, at delete-only DML stops inserting entries,
        then the object disappears and the key range is purged. A drop
        past write-only only rolls FORWARD (entries are already missing
        for new rows — restoring PUBLIC would serve a corrupt index)."""
        store = self.domain.store
        txn = store.begin()
        m = Meta(txn)
        job = next((j for j in m.queued_jobs() if j.id == job_id), None)
        if job is None:
            txn.rollback()
            return True
        t = m.get_table(job.schema_id, job.table_id)
        if t is None:
            self._cancel_job(m, job, "table dropped during DDL")
            txn.commit()
            self.domain.reload_schema()
            return True
        idx = t.find_index(job.args["index_name"])
        if idx is None:  # re-entry after the final step, or never existed
            job.state = JobState.SYNCED
            job.schema_state = SchemaState.NONE
            job.schema_version = m.bump_schema_version()
            m.finish_job(job)
            txn.commit()
            self.domain.reload_schema()
            return True
        try:
            if idx.state == SchemaState.PUBLIC:
                idx.state = SchemaState.WRITE_ONLY
                return self._transition(m, txn, job, t,
                                        SchemaState.WRITE_ONLY)
            if idx.state == SchemaState.WRITE_ONLY:
                idx.state = SchemaState.DELETE_ONLY
                return self._transition(m, txn, job, t,
                                        SchemaState.DELETE_ONLY)
            # delete-only → gone: drop the object, purge the key range
            from .partition import index_phys_ids
            phys_ids = index_phys_ids(t)
            idx_id = idx.id
            t.indexes = [i for i in t.indexes if i.id != idx_id]
            m.update_table(job.schema_id, t)
            job.state = JobState.SYNCED
            job.schema_state = SchemaState.NONE
            job.schema_version = m.bump_schema_version()
            m.finish_job(job)
            txn.commit()
            for pid in phys_ids:
                start, end = tablecodec.index_range(pid, idx_id)
                store.mvcc.raw_delete_range(start, end)
            self.domain.reload_schema()
            self._fire("none", job)
            return True
        except Exception:
            if txn.valid:
                txn.rollback()
            raise

    # -- ADD COLUMN state machine (reference: ddl/column.go onAddColumn:
    #    none → delete-only → write-only → public, no backfill — defaults
    #    materialize at read) --------------------------------------------

    def step_add_column(self, job_id: int) -> bool:
        from .model import ColumnInfo
        store = self.domain.store
        txn = store.begin()
        m = Meta(txn)
        job = next((j for j in m.queued_jobs() if j.id == job_id), None)
        if job is None:
            txn.rollback()
            return True
        t = m.get_table(job.schema_id, job.table_id)
        if t is None:
            self._cancel_job(m, job, "table dropped during DDL")
            txn.commit()
            self.domain.reload_schema()
            return True
        name = job.args["column"]["name"]
        col = t.find_column(name)
        try:
            if col is None:
                ci = ColumnInfo.from_json(job.args["column"])
                t.max_col_id += 1
                ci.id = t.max_col_id
                ci.state = SchemaState.DELETE_ONLY
                pos = job.args.get("pos")
                if pos == ["first"]:
                    t.columns.insert(0, ci)
                elif pos and pos[0] == "after":
                    ref = t.find_column(pos[1])
                    t.columns.insert(t.columns.index(ref) + 1, ci)
                else:
                    t.columns.append(ci)
                for off, c in enumerate(t.columns):
                    c.offset = off
                return self._transition(m, txn, job, t,
                                        SchemaState.DELETE_ONLY)
            if col.state == SchemaState.DELETE_ONLY:
                col.state = SchemaState.WRITE_ONLY
                return self._transition(m, txn, job, t,
                                        SchemaState.WRITE_ONLY)
            if col.state == SchemaState.WRITE_ONLY:
                col.state = SchemaState.PUBLIC
                m.update_table(job.schema_id, t)
                job.state = JobState.SYNCED
                job.schema_state = SchemaState.PUBLIC
                job.schema_version = m.bump_schema_version()
                m.finish_job(job)
                txn.commit()
                store.mvcc.bump_table_version(t.id)
                self.domain.reload_schema()
                self._fire("public", job)
                return True
            # PUBLIC already (e.g. raced duplicate): leave the queue
            self._cancel_job(m, job,
                             f"Duplicate column name '{name}'")
            txn.commit()
            self.domain.reload_schema()
            return True
        except Exception:
            if txn.valid:
                txn.rollback()
            raise

    def _transition(self, m: Meta, txn, job: Job, t, new_state: int) -> bool:
        m.update_table(job.schema_id, t)
        job.state = JobState.RUNNING
        job.schema_state = new_state
        job.schema_version = m.bump_schema_version()
        m.update_job(job)
        txn.commit()
        self.domain.reload_schema()
        self._fire(SchemaState.NAMES.get(new_state, str(new_state)), job)
        return False

    def _backfill_batch(self, job: Job, t, idx) -> bool:
        """One checkpointed batch (reference: backfilling.go:290): scan
        records after the checkpoint handle, write their index KVs, and
        advance the checkpoint — all in ONE txn, so a crash between batches
        loses nothing and repeats nothing.

        Partitioned tables backfill partition-by-partition: the checkpoint is
        (args["reorg_part"], reorg_handle) and index entries are written
        under each partition's physical id."""
        from .utils import failpoint
        store = self.domain.store
        # physical scan targets: the table itself, or each partition
        if t.partition is not None:
            from .partition import partition_view
            phys = [partition_view(t, d) for d in t.partition.defs]
        else:
            phys = [t]
        from .errors import BackoffExhaustedError
        from .utils.backoff import Backoffer
        bo = Backoffer()
        while True:
            failpoint.inject("ddl-backfill-batch")
            txn = store.begin()
            try:
                m = Meta(txn)
                cur = next((j for j in m.queued_jobs() if j.id == job.id),
                           None)
                if cur is None:
                    txn.rollback()
                    return True
                job = cur
                part = int(job.args.get("reorg_part", 0))
                if part >= len(phys):
                    return self._finish_reorg(m, txn, job, t, idx)
                pt = phys[part]
                start = (tablecodec.record_prefix(pt.id)
                         if job.reorg_handle == MIN_HANDLE else
                         tablecodec.record_key(pt.id, job.reorg_handle) + b"\x00")
                end = tablecodec.record_prefix(pt.id) + b"\xff" * 9
                items = txn.snapshot.scan(start, end, limit=self.batch_size)
                if not items:
                    if part + 1 < len(phys):
                        # this partition is drained: checkpoint to the next
                        job.args["reorg_part"] = part + 1
                        job.reorg_handle = MIN_HANDLE
                        m.update_job(job)
                        txn.commit()
                        self._fire("reorg_batch", job)
                        return False
                    return self._finish_reorg(m, txn, job, t, idx)
                tbl = Table(pt, txn)
                last = job.reorg_handle
                for key, value in items:
                    _tid, handle = tablecodec.decode_record_key(key)
                    row = tablecodec.decode_row(value)
                    self._backfill_put(txn, tbl, idx, row, handle)
                    last = handle
                job.reorg_handle = last
                job.row_count += len(items)
                m.update_job(job)
                txn.commit()
                self._fire("reorg_batch", job)
                return False
            except WriteConflictError as e:
                txn.rollback()
                try:  # concurrent DML touched a scanned row: retry batch
                    bo.backoff("ddlBackfill", e)
                except BackoffExhaustedError as be:
                    raise TiDBError(
                        "backfill batch: too many write conflicts",
                        code=ErrCode.BackoffExhausted) from be
            except DupEntryError as e:
                txn.rollback()
                self._rollback_index(job, t, idx, str(e))
                return True
            except Exception:
                if txn.valid:
                    txn.rollback()
                raise

    @staticmethod
    def _backfill_put(txn, tbl: Table, idx, row, handle):
        """Write one backfilled index entry. Concurrent DML (the index is
        write-only+) may have written this row's entry already — same handle
        is fine (idempotent), a different handle is a real uniqueness
        violation (reference: index backfill's mergeDupKey handling)."""
        vals = tbl._index_values(idx, row)
        if idx.unique and not any(v is None for v in vals):
            key = tablecodec.index_key(tbl.info.id, idx.id, vals)
            existing = txn.get(key)
            if existing is not None:
                if tablecodec.decode_index_handle(existing) != handle:
                    raise DupEntryError(
                        "Duplicate entry '%s' for key '%s'" % (
                            "-".join(str(v) for v in vals), idx.name))
                return
            txn.put(key, tablecodec.encode_index_handle(handle))
        else:
            key = tablecodec.index_key(tbl.info.id, idx.id, vals,
                                       handle=handle)
            txn.put(key, tablecodec.INDEX_VALUE_MARKER)

    def _finish_reorg(self, m: Meta, txn, job: Job, t, idx) -> bool:
        idx.state = SchemaState.PUBLIC
        m.update_table(job.schema_id, t)
        job.state = JobState.SYNCED
        job.schema_state = SchemaState.PUBLIC
        job.schema_version = m.bump_schema_version()
        m.finish_job(job)
        txn.commit()
        self.domain.reload_schema()
        self._fire("public", job)
        return True

    def _rollback_index(self, job: Job, t, idx, err: str):
        """Unique violation during backfill: remove the half-built index
        (reference: ddl/rollingback.go convertAddIdxJob2RollbackJob)."""
        store = self.domain.store
        txn = store.begin()
        try:
            m = Meta(txn)
            cur_t = m.get_table(job.schema_id, job.table_id)
            if cur_t is not None:
                cur_t.indexes = [i for i in cur_t.indexes if i.id != idx.id]
                m.update_table(job.schema_id, cur_t)
            job.state = JobState.ROLLBACK_DONE
            job.error = err
            job.schema_state = SchemaState.NONE
            job.schema_version = m.bump_schema_version()
            m.finish_job(job)
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        from .partition import index_phys_ids
        for pid in index_phys_ids(t):
            start, end = tablecodec.index_range(pid, idx.id)
            store.mvcc.raw_delete_range(start, end)
        self.domain.reload_schema()
        self._fire("rollback_done", job)

    def _cancel_job(self, m: Meta, job: Job, err: str):
        """Cancel under the caller's open meta TXN (which the caller
        commits).  Formerly `_cancel_locked` — renamed because the
        `_locked` suffix is reserved for "caller holds the threading
        guard" (lint: locked-suffix-contract); the exclusivity here is
        txn ownership, not a mutex."""
        job.state = JobState.CANCELLED
        job.error = err
        m.finish_job(job)
