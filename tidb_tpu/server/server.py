"""MySQL protocol server (reference: server/server.go accept loop +
server/conn.go clientConn.Run dispatch loop / handshake at conn.go:256,810,
resultset streaming at conn.go:2096).

Threaded TCP server; each connection owns a Session over the shared
Domain — the reference's per-conn goroutine becomes a thread. Prepared
statements parse once at PREPARE ('?' lexes to real ParamMarker nodes)
and bind decoded binary parameters through the session's parameter
pathway at EXECUTE (binary row encoding is a follow-up)."""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

from ..errors import TiDBError
from ..session import new_session
from . import protocol as P
from .packet import (PacketIO, lenenc_int, read_lenenc_int, read_nul_str)


class MySQLServer:
    def __init__(self, domain, host="127.0.0.1", port=4000, users=None,
                 ssl_ctx=None, reuse_port=False):
        """users: optional static {user: password} map override. Default
        (None) authenticates against the mysql.user grant tables (falling
        back to empty-password root when the domain has no grant tables).
        Pass users={} to explicitly accept any login (hermetic tests).
        ssl_ctx: an ssl.SSLContext enabling the in-handshake TLS upgrade
        (reference: server/conn.go:256 upgradeToTLS; see make_tls_context
        / auto-TLS in server/main.py).
        reuse_port: bind with SO_REUSEPORT so N fabric worker processes
        (tidb_tpu/fabric) can listen behind ONE advertised port — the
        kernel load-balances incoming connections across the fleet.

        Connection ids come from the Session allocator (session.py),
        which a fabric worker prefixes with its process-slot base —
        fleet-UNIQUE ids, so KILL and information_schema attribution
        resolve to the owning process (a per-server counter here would
        let two workers mint the same id)."""
        self.domain = domain
        self.users = users
        self.ssl_ctx = ssl_ctx
        self._lock = threading.Lock()
        self.connections = {}

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                outer._handle_conn(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

            def server_bind(self):
                if reuse_port:
                    self.socket.setsockopt(socket.SOL_SOCKET,
                                           socket.SO_REUSEPORT, 1)
                super().server_bind()

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = None

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def shutdown(self):
        """Graceful-ish shutdown (reference: server.go GracefulDown)."""
        self._server.shutdown()
        self._server.server_close()

    # -- connection ---------------------------------------------------------

    def _handle_conn(self, sock: socket.socket):
        io = PacketIO(sock)
        # the session is created BEFORE the handshake so the conn id the
        # client displays is the one KILL resolves in domain.sessions —
        # two counters here meant KILL <shown id> hit the wrong session
        session = new_session(self.domain)
        conn_id = session.conn_id
        salt = P.new_salt()
        extra = P.CLIENT_SSL if self.ssl_ctx is not None else 0
        io.write_packet(P.build_handshake(conn_id, salt, extra))
        try:
            resp = io.read_packet()
            caps0 = (struct.unpack_from("<I", resp, 0)[0]
                     if len(resp) >= 4 else 0)
            if (self.ssl_ctx is not None and (caps0 & P.CLIENT_SSL)
                    and len(resp) <= 32):
                # SSLRequest: upgrade the conn IN the handshake, then the
                # client resends the full response encrypted (reference:
                # server/conn.go:256 upgradeToTLS)
                sock = self.ssl_ctx.wrap_socket(sock, server_side=True)
                io.sock = sock
                resp = io.read_packet()
            user, db, auth, client_plugin = \
                self._parse_handshake_response(resp)
        except ConnectionError:
            session.close()
            return
        except Exception:
            # garbage from a non-MySQL client (port scan, HTTP, TLS probe)
            try:
                io.write_packet(P.build_err(1043, "Bad handshake", b"08S01"))
            except Exception:
                pass
            session.close()
            return
        try:
            peer = sock.getpeername()[0]
        except OSError:
            peer = "%"
        # authentication plugins decide first (reference: plugin auth
        # sub-manifest consulted before the grant tables)
        plug = getattr(self.domain, "plugins", None)
        decided = plug.authenticate(user, peer, auth) if plug else None
        if decided is False:
            matched_host = None
        elif decided is True:
            matched_host = "%"
        else:
            # when the account's auth plugin differs from what the client
            # used, ask it to re-scramble (AuthSwitchRequest — reference:
            # server/conn.go:810 handleAuthPlugin/authSwitchRequest); this
            # is how caching_sha2_password accounts log in from clients
            # that defaulted to mysql_native_password and vice versa
            rec_plugin = self._account_plugin(user, peer)
            if rec_plugin is not None and rec_plugin != client_plugin:
                try:
                    io.write_packet(P.build_auth_switch(rec_plugin, salt))
                    auth = io.read_packet()
                    client_plugin = rec_plugin
                except Exception:
                    auth = b""
            matched_host = self._check_auth(user, auth, salt, peer)
            fast_auth = (matched_host is not None
                         and client_plugin == "caching_sha2_password")
            if fast_auth:
                io.write_packet(P.FAST_AUTH_SUCCESS)
        if matched_host is None:
            if plug:
                plug.audit_connection({"user": user, "host": peer},
                                      "ConnectionReject")
            io.write_packet(P.build_err(
                1045, f"Access denied for user '{user}'", b"28000"))
            session.close()
            return
        session.user = f"{user}@{matched_host}"
        if plug:
            plug.audit_connection(
                {"user": user, "host": peer, "conn_id": session.conn_id},
                "Connect")
        if db:
            try:
                session.execute(f"use `{db}`")
            except TiDBError as e:
                io.write_packet(P.build_err(
                    getattr(e, "code", 1049) or 1049, str(e)))
                session.close()
                return
        io.write_packet(P.build_ok())
        self.connections[conn_id] = session
        try:
            self._command_loop(io, session)
        finally:
            self.connections.pop(conn_id, None)
            if plug:
                plug.audit_connection(
                    {"user": user, "host": peer,
                     "conn_id": session.conn_id}, "Disconnect")
            session.close()

    def _parse_handshake_response(self, buf: bytes):
        caps = struct.unpack_from("<I", buf, 0)[0]
        pos = 4 + 4 + 1 + 23  # caps, max packet, charset, filler
        user, pos = read_nul_str(buf, pos)
        if caps & P.CLIENT_SECURE_CONNECTION:
            alen = buf[pos]
            pos += 1
            auth = buf[pos:pos + alen]
            pos += alen
        else:
            auth, pos = read_nul_str(buf, pos)
        db = b""
        if caps & P.CLIENT_CONNECT_WITH_DB and pos < len(buf):
            db, pos = read_nul_str(buf, pos)
        plugin = b"mysql_native_password"
        if caps & P.CLIENT_PLUGIN_AUTH and pos < len(buf):
            plugin, pos = read_nul_str(buf, pos)
        return user.decode(), db.decode(), auth, plugin.decode()

    def _account_plugin(self, user: str, peer: str) -> str | None:
        """The grant-table account's auth plugin, or None when auth is
        driven by the users dict / bootstrap fallback (native only)."""
        if self.users is not None:
            return None
        priv = getattr(self.domain, "priv", None)
        if priv is not None and priv.enabled:
            rec = priv.match_user(user, peer)
            return rec.plugin if rec is not None else None
        return None

    def _check_auth(self, user: str, auth: bytes, salt: bytes,
                    peer: str = "%") -> str | None:
        """-> the matched account's host scope, or None on rejection."""
        if self.users == {}:
            return "%"  # explicit opt-in: accept any login
        if self.users is not None:
            if user not in self.users:
                return None
            expected = P.native_password_hash(
                self.users[user].encode(), salt)
            return "%" if auth == expected else None
        # grant tables (reference: privileges.ConnectionVerification)
        priv = getattr(self.domain, "priv", None)
        if priv is not None and priv.enabled:
            rec = priv.check_password_response(user, salt[:20], auth, peer)
            return rec.host if rec is not None else None
        # no grant tables: bootstrap behavior, empty-password root only
        return "%" if (user == "root" and not auth) else None

    # -- command dispatch ---------------------------------------------------

    def _command_loop(self, io: PacketIO, session):
        stmts = {}  # stmt_id -> [ast, n_params, types]
        long_data = {}  # (stmt_id, param_idx) -> bytearray
        cursors = {}  # stmt_id -> [rows, ftypes, pos]
        next_stmt = 0
        while True:
            io.reset_seq()
            try:
                pkt = io.read_packet()
            except ConnectionError:
                return
            if not pkt:
                io.write_packet(P.build_err(1047, "empty command", b"08S01"))
                continue
            cmd, payload = pkt[0], pkt[1:]
            try:
                if cmd == P.COM_QUIT:
                    return
                elif cmd == P.COM_PING:
                    io.write_packet(P.build_ok())
                elif cmd == P.COM_INIT_DB:
                    session.execute(f"use `{payload.decode()}`")
                    io.write_packet(P.build_ok())
                elif cmd == P.COM_QUERY:
                    self._run_query(io, session, payload.decode("utf-8"))
                elif cmd == P.COM_FIELD_LIST:
                    io.write_packet(P.build_eof())
                elif cmd == P.COM_STMT_PREPARE:
                    sql = payload.decode("utf-8")
                    next_stmt += 1
                    sid = next_stmt
                    # parse ONCE: '?' are real ParamMarker nodes, so the
                    # count follows SQL lexing (strings/comments excluded)
                    ast_stmt, n_params = session.prepare(sql)
                    col_names, col_fts = session.prepared_schema(
                        ast_stmt, n_params)
                    stmts[sid] = [ast_stmt, n_params, None]
                    out = (b"\x00" + struct.pack("<I", sid)
                           + struct.pack("<H", len(col_names))
                           + struct.pack("<H", n_params)
                           + b"\x00" + struct.pack("<H", 0))
                    io.write_packet(out)
                    for _ in range(n_params):
                        io.write_packet(P.column_def(
                            "?", _param_ftype()))
                    if n_params:
                        io.write_packet(P.build_eof())
                    for name, ft in zip(col_names, col_fts):
                        io.write_packet(P.column_def(name, ft))
                    if col_names:
                        io.write_packet(P.build_eof())
                elif cmd == P.COM_STMT_EXECUTE:
                    self._stmt_execute(io, session, stmts, payload,
                                       long_data, cursors)
                elif cmd == P.COM_STMT_SEND_LONG_DATA:
                    # append-only, NO response (reference:
                    # server/conn_stmt.go handleStmtSendLongData)
                    sid = struct.unpack_from("<I", payload, 0)[0]
                    pid = struct.unpack_from("<H", payload, 4)[0]
                    long_data.setdefault((sid, pid),
                                         bytearray()).extend(payload[6:])
                elif cmd == P.COM_STMT_FETCH:
                    self._stmt_fetch(io, session, cursors, payload)
                elif cmd == P.COM_STMT_RESET:
                    sid = struct.unpack_from("<I", payload, 0)[0]
                    for k in [k for k in long_data if k[0] == sid]:
                        long_data.pop(k, None)
                    cursors.pop(sid, None)
                    io.write_packet(P.build_ok())
                elif cmd == P.COM_STMT_CLOSE:
                    sid = struct.unpack_from("<I", payload, 0)[0]
                    stmts.pop(sid, None)
                    cursors.pop(sid, None)
                    for k in [k for k in long_data if k[0] == sid]:
                        long_data.pop(k, None)
                else:
                    io.write_packet(P.build_err(
                        1047, f"Unknown command {cmd:#x}", b"08S01"))
            except TiDBError as e:
                io.write_packet(P.build_err(
                    getattr(e, "code", 1105) or 1105, str(e)))
            except Exception as e:  # never kill the conn loop on a bug
                io.write_packet(P.build_err(1105, f"internal: {e}"))
            if getattr(session, "kill_conn", False):
                return  # KILL CONNECTION: drop the wire connection

    def _run_query(self, io, session, sql: str):
        results = session.execute(sql)
        if not results:
            io.write_packet(P.build_ok())
            return
        for i, res in enumerate(results):
            more = i < len(results) - 1
            status = P.SERVER_STATUS_AUTOCOMMIT | (
                P.SERVER_MORE_RESULTS_EXISTS if more else 0)
            if res.chunk is None:
                io.write_packet(P.build_ok(
                    affected=res.affected,
                    last_insert_id=res.last_insert_id, status=status))
                continue
            self._write_resultset(io, res, status)

    @staticmethod
    def _session_status(session) -> int:
        """Real connection status flags for EOF/OK packets (reference:
        server status bits in conn.go writeOK): autocommit + in-txn."""
        status = 0
        try:
            if session.autocommit():
                status |= P.SERVER_STATUS_AUTOCOMMIT
            if session.txn is not None and session.txn.valid:
                status |= P.SERVER_STATUS_IN_TRANS
        except Exception:
            status = P.SERVER_STATUS_AUTOCOMMIT
        return status

    def _write_result_header(self, io, res, status):
        """column count + defs + EOF — shared by the immediate resultset
        path and the server-side cursor open."""
        io.write_packet(lenenc_int(len(res.names)))
        for name, ft in zip(res.names, res.ftypes):
            io.write_packet(P.column_def(name, ft))
        io.write_packet(P.build_eof(status=status))

    def _write_resultset(self, io, res, status, binary=False):
        """binary=True after COM_STMT_EXECUTE: the binary protocol requires
        Protocol::BinaryResultsetRow, not text rows (reference:
        server/conn_stmt.go handleStmtExecute → writeResultset(binary))."""
        fts = res.ftypes
        self._write_result_header(io, res, status)
        if binary:
            for row in res.rows:
                io.write_packet(P.binary_row(row, fts))
        else:
            for row in res.rows:
                io.write_packet(P.text_row(row))
        io.write_packet(P.build_eof(status=status))

    def _stmt_execute(self, io, session, stmts, payload, long_data=None,
                      cursors=None):
        sid = struct.unpack_from("<I", payload, 0)[0]
        if sid not in stmts:
            io.write_packet(P.build_err(1243, "Unknown prepared statement"))
            return
        ast_stmt, n_params, bound_types = stmts[sid]
        if cursors is not None:
            # a new execution supersedes any open cursor on this stmt id
            # (the reference closes the prior cursor on execute)
            cursors.pop(sid, None)
        cursor_flags = payload[4]
        pos = 4 + 1 + 4  # id, flags, iteration count
        args = []
        long_data = long_data if long_data is not None else {}
        if n_params:
            nullmap_len = (n_params + 7) // 8
            nullmap = payload[pos:pos + nullmap_len]
            pos += nullmap_len
            new_bound = payload[pos]
            pos += 1
            if new_bound:
                types = []
                for _ in range(n_params):
                    types.append((payload[pos], payload[pos + 1]))
                    pos += 2
                stmts[sid][2] = types  # persist: later executes send no types
            else:
                types = bound_types
            if not types:
                raise TiDBError("prepared statement executed with no "
                                "parameter types bound")
            for i in range(n_params):
                ld = long_data.get((sid, i))
                if ld is not None:
                    # long-data params carry no value in the execute
                    # payload (reference: conn_stmt.go parseExecArgs)
                    args.append(bytes(ld))
                    continue
                if nullmap[i // 8] & (1 << (i % 8)):
                    args.append(None)
                    continue
                tp, flags = types[i]
                v, pos = _decode_binary_value(payload, pos, tp, flags)
                args.append(v)
        res = session.execute_prepared(ast_stmt, args)
        status = self._session_status(session)
        if res.chunk is None:
            io.write_packet(P.build_ok(
                affected=res.affected,
                last_insert_id=res.last_insert_id, status=status))
            return
        if (cursor_flags & P.CURSOR_TYPE_READ_ONLY) and cursors is not None:
            # server-side cursor: column defs now, rows via COM_STMT_FETCH
            # (reference: server/conn_stmt.go useCursor branch)
            cursors[sid] = [list(res.rows), res.ftypes, 0]
            self._write_result_header(
                io, res, status | P.SERVER_STATUS_CURSOR_EXISTS)
            return
        self._write_resultset(io, res, status, binary=True)

    def _stmt_fetch(self, io, session, cursors, payload):
        """COM_STMT_FETCH: next n rows of an open cursor (reference:
        server/conn_stmt.go handleStmtFetch)."""
        sid = struct.unpack_from("<I", payload, 0)[0]
        n = struct.unpack_from("<I", payload, 4)[0]
        cur = cursors.get(sid)
        if cur is None:
            io.write_packet(P.build_err(
                1243, "Unknown prepared statement (no open cursor)"))
            return
        rows, fts, pos = cur
        end = min(pos + max(n, 1), len(rows))
        for row in rows[pos:end]:
            io.write_packet(P.binary_row(row, fts))
        cur[2] = end
        status = self._session_status(session) \
            | P.SERVER_STATUS_CURSOR_EXISTS
        if end >= len(rows):
            status |= P.SERVER_STATUS_LAST_ROW_SENT
        io.write_packet(P.build_eof(status=status))


def _param_ftype():
    from ..sqltypes import FieldType, TYPE_VARCHAR
    return FieldType(tp=TYPE_VARCHAR)


def _decode_binary_value(buf, pos, tp, flags=0):
    """Binary protocol parameter decode (reference: server/conn_stmt.go
    parseExecArgs)."""
    unsigned = bool(flags & 0x80)
    if tp == 0x01:                          # TINY
        return struct.unpack_from("<B" if unsigned else "<b",
                                  buf, pos)[0], pos + 1
    if tp in (0x02, 0x0D):                  # SHORT / YEAR
        return struct.unpack_from("<H" if unsigned else "<h",
                                  buf, pos)[0], pos + 2
    if tp in (0x03, 0x09):                  # LONG / INT24
        return struct.unpack_from("<I" if unsigned else "<i",
                                  buf, pos)[0], pos + 4
    if tp == 0x08:                          # LONGLONG
        return struct.unpack_from("<Q" if unsigned else "<q",
                                  buf, pos)[0], pos + 8
    if tp == 0x04:                          # FLOAT
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if tp == 0x05:                          # DOUBLE
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tp == 0x06:                          # NULL
        return None, pos
    if tp in (0x07, 0x0A, 0x0C):            # TIMESTAMP / DATE / DATETIME
        n = buf[pos]
        pos += 1
        f = buf[pos:pos + n]
        pos += n
        if n == 0:
            return "0000-00-00", pos
        y, mo, d = struct.unpack_from("<H", f, 0)[0], f[2], f[3]
        if n == 4:
            return f"{y:04d}-{mo:02d}-{d:02d}", pos
        h, mi, sec = f[4], f[5], f[6]
        if n == 7:
            return f"{y:04d}-{mo:02d}-{d:02d} {h:02d}:{mi:02d}:{sec:02d}", pos
        us = struct.unpack_from("<I", f, 7)[0]
        return (f"{y:04d}-{mo:02d}-{d:02d} "
                f"{h:02d}:{mi:02d}:{sec:02d}.{us:06d}"), pos
    if tp == 0x0B:                          # TIME
        n = buf[pos]
        pos += 1
        f = buf[pos:pos + n]
        pos += n
        if n == 0:
            return "00:00:00", pos
        sign = "-" if f[0] else ""
        days = struct.unpack_from("<I", f, 1)[0]
        h, mi, sec = f[5], f[6], f[7]
        h += days * 24
        base = f"{sign}{h:02d}:{mi:02d}:{sec:02d}"
        if n > 8:
            us = struct.unpack_from("<I", f, 8)[0]
            base += f".{us:06d}"
        return base, pos
    n, pos = read_lenenc_int(buf, pos)
    return buf[pos:pos + n], pos + n


