"""Worker side of the fleet fragment performance store (ISSUE 18).

The coordination segment holds the fleet accumulators
(fabric/coord.py PERF section: count / sum / max / log2 duration sketch
per ``(fragment sig, row bucket, backend, duration kind)``).  This
module is everything around it:

* :func:`note` — the chokepoint feed.  Each timed span at a dispatch
  chokepoint (sync compile, admission wait, device dispatch, host
  fallback) adds its duration to a PROCESS-LOCAL buffer: one small dict
  update under a local lock, no segment round trip on the hot path.
* :func:`flush` — drains the buffer into the segment (one locked merge
  for all rows), driven by the worker heartbeat.  Outside a fleet the
  buffer drains into the local mirror only — the single-process
  deployment keeps the same EXPLAIN/memtable surface over its own
  samples.
* :func:`lookup` / :func:`fleet_rows` — the read side EXPLAIN ANALYZE,
  ``/status`` and ``information_schema.tidb_fragment_perf`` render.
* :func:`percentile` — sketch → seconds.  The sketch is 16 power-of-two
  buckets over ``coord.PERF_BASE_S``; a percentile answers with the
  bucket's upper bound, so p50/p99 are ~2× granular — plenty to rank
  device vs host, which is all ROADMAP item 4 will ask of it.

Observe-only by design: nothing in this module makes or influences a
routing decision.  The numbers a future cost-based router will use
become visible and regression-tested first.
"""

from __future__ import annotations

import hashlib
import logging
import threading

from .coord import PERF_BASE_S, PERF_SKETCH_N

log = logging.getLogger("tidb_tpu.fabric.perf")

#: duration kinds, in segment-encoding order
KINDS = ("compile", "admission_wait", "dispatch")
#: backends, in segment-encoding order.  Host fallback is
#: (backend="host", kind="dispatch") — the same fragment's host and
#: device dispatch rows sit side by side, which is exactly the
#: comparison EXPLAIN ANALYZE renders
BACKENDS = ("device", "host")

_LOCK = threading.Lock()
#: pending deltas: key -> [count, sum_s, max_s, sketch list]
_BUF: dict = {}
#: process-local cumulative mirror (same row shape the segment serves):
#: the read surface outside a fleet, and the "this worker's share"
#: column next to the fleet aggregate inside one
_LOCAL: dict = {}

STATS = {
    "perf_notes": 0,     # samples recorded at chokepoints
    "perf_flushes": 0,   # buffer drains (heartbeat-driven)
    "perf_merged": 0,    # rows merged into the segment
}


def sig_hash(sig) -> int:
    """64-bit stable hash of a fragment signature (any repr-able key —
    callers pass the compiled-pipeline batch key's structural prefix)."""
    if isinstance(sig, int):
        return sig & (2**64 - 1)
    return int.from_bytes(
        hashlib.blake2b(repr(sig).encode(), digest_size=8).digest(),
        "little")


def dispatch_key(batch_key, shape: str = "agg"):
    """(sig, bucket) for the perf store from a dispatch site's admission
    batch key: the structural prefix hashes to the fragment sig, the
    trailing row bucket (device_exec.agg_batch_key's last element) is
    the bucket.  Batch-key-less dispatches key by fragment shape —
    coarser, but every dispatch still lands in the store."""
    if (isinstance(batch_key, tuple) and batch_key
            and isinstance(batch_key[-1], int)):
        return sig_hash(batch_key[:-1]), batch_key[-1]
    if batch_key is not None:
        return sig_hash(batch_key), 0
    return sig_hash(("shape", shape)), 0


def sketch_bucket(dur_s: float) -> int:
    """The sketch bucket a duration lands in: bucket i counts durations
    <= PERF_BASE_S * 2**i (the last bucket is the +Inf tail)."""
    edge = PERF_BASE_S
    for i in range(PERF_SKETCH_N - 1):
        if dur_s <= edge:
            return i
        edge *= 2.0
    return PERF_SKETCH_N - 1


def percentile(sketch, count: int, q: float) -> "float | None":
    """The q-quantile (0..1) upper-bound in seconds, or None when the
    sketch is empty."""
    if count <= 0:
        return None
    rank = max(1, int(q * count + 0.999999))
    seen = 0
    for i, c in enumerate(sketch):
        seen += c
        if seen >= rank:
            return PERF_BASE_S * (2.0 ** i)
    return PERF_BASE_S * (2.0 ** (PERF_SKETCH_N - 1))


def note(sig, bucket: int, backend: str, kind: str, dur_s: float):
    """Record one span duration.  Hot-path cost: one hash + one dict
    update under the process-local lock — the segment is never touched
    here (flush() batches that)."""
    try:
        key = (sig_hash(sig), int(bucket) & (2**32 - 1),
               BACKENDS.index(backend), KINDS.index(kind))
    except ValueError:
        log.debug("perf.note: unknown backend/kind (%s, %s)", backend,
                  kind)
        return
    d = float(dur_s)
    sb = sketch_bucket(d)
    with _LOCK:
        STATS["perf_notes"] += 1
        for table in (_BUF, _LOCAL):
            row = table.get(key)
            if row is None:
                row = table[key] = [0, 0.0, 0.0, [0] * PERF_SKETCH_N]
            row[0] += 1
            row[1] += d
            row[2] = max(row[2], d)
            row[3][sb] += 1


def flush() -> int:
    """Drain the buffer into the segment (when a fleet is active).
    Heartbeat-driven; never raises — a coordinator blip drops this
    beat's deltas back into the buffer for the next one."""
    from . import state
    with _LOCK:
        if not _BUF:
            return 0
        pending = dict(_BUF)
        _BUF.clear()
        STATS["perf_flushes"] += 1
    coord = state.coordinator()
    if coord is None:
        return 0  # local-only deployment: the _LOCAL mirror is the store
    rows = [(k[0], k[1], k[2], k[3], r[0], r[1], r[2], r[3])
            for k, r in pending.items()]
    try:
        n = coord.perf_merge(rows)
    except Exception as e:  # noqa: BLE001 — observe-only: drop back
        log.debug("perf flush failed (rebuffering): %s", e)
        with _LOCK:
            for k, r in pending.items():
                row = _BUF.get(k)
                if row is None:
                    _BUF[k] = r
                else:
                    row[0] += r[0]
                    row[1] += r[1]
                    row[2] = max(row[2], r[2])
                    row[3] = [a + b for a, b in zip(row[3], r[3])]
        return 0
    with _LOCK:
        STATS["perf_merged"] += n
    return n


def _rows_from(table: dict) -> list:
    return [{"sig_hash": k[0], "bucket": k[1], "backend": k[2],
             "kind": k[3], "count": r[0], "sum_s": r[1], "max_s": r[2],
             "sketch": list(r[3])}
            for k, r in sorted(table.items())]


def local_rows() -> list:
    """This process's cumulative samples (buffered + flushed)."""
    with _LOCK:
        return _rows_from(_LOCAL)


def fleet_rows() -> list:
    """The fleet store's rows — segment-backed inside a fleet, the
    local mirror outside one (same shape either way)."""
    from . import state
    coord = state.coordinator()
    if coord is not None:
        try:
            return coord.perf_rows()
        except Exception as e:  # noqa: BLE001 — segment may be unlinked
            log.debug("fleet perf rows unreadable: %s", e)
    return local_rows()


def lookup(sig, bucket: int) -> list:
    """Perf rows for one (fragment sig, row bucket) — the EXPLAIN
    ANALYZE fleet-line feed.  Flushes first so the asking statement's
    own just-recorded samples are visible."""
    flush()
    h = sig_hash(sig)
    from . import state
    coord = state.coordinator()
    if coord is not None:
        try:
            return coord.perf_lookup(h, int(bucket))
        except Exception as e:  # noqa: BLE001
            log.debug("fleet perf lookup failed: %s", e)
    with _LOCK:
        return [{"backend": k[2], "kind": k[3], "count": r[0],
                 "sum_s": r[1], "max_s": r[2], "sketch": list(r[3])}
                for k, r in sorted(_LOCAL.items())
                if k[0] == h and k[1] == int(bucket)]


def describe(rows) -> str:
    """One EXPLAIN ANALYZE line from lookup() rows:
    ``fleet: n=…, device p50/p99 …/…, host p50/p99 …/…`` (only the
    backends that have dispatch samples appear)."""
    parts = []
    total = 0
    for bi, bname in enumerate(BACKENDS):
        agg = [r for r in rows
               if r["backend"] == bi and r["kind"] == KINDS.index(
                   "dispatch")]
        if not agg:
            continue
        count = sum(r["count"] for r in agg)
        sketch = [sum(r["sketch"][i] for r in agg)
                  for i in range(PERF_SKETCH_N)]
        total += count
        p50 = percentile(sketch, count, 0.50)
        p99 = percentile(sketch, count, 0.99)
        parts.append(f"{bname} p50/p99 {_fmt(p50)}/{_fmt(p99)}")
    if not parts:
        return ""
    return f"n={total}, " + ", ".join(parts)


def _fmt(s: "float | None") -> str:
    if s is None:
        return "-"
    if s >= 1.0:
        return f"{s:.2f}s"
    return f"{s * 1e3:.1f}ms"


def stats() -> dict:
    """The /status ``device_perf_store`` payload."""
    with _LOCK:
        out = dict(STATS)
        out["perf_local_rows"] = len(_LOCAL)
        out["perf_buffered_rows"] = len(_BUF)
    return out


def reset_for_tests():
    with _LOCK:
        _BUF.clear()
        _LOCAL.clear()
        for k in STATS:
            STATS[k] = 0
