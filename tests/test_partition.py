"""Partitioned tables: RANGE/HASH/LIST routing, pruning, partition
management DDL (reference: table/tables/partition.go,
planner/core/rule_partition_processor.go, ddl/partition.go)."""

import pytest

from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec("use test")
    return tk


def _explain_text(tk, sql):
    return "\n".join(" ".join(str(c) for c in r)
                     for r in tk.must_query("EXPLAIN " + sql).rows)


class TestRangePartition:
    def test_route_and_scan(self, tk):
        tk.must_exec("""create table s (id int, amount int)
            partition by range (amount) (
              partition p0 values less than (100),
              partition p1 values less than (200),
              partition pmax values less than maxvalue)""")
        tk.must_exec("insert into s values (1,50),(2,150),(3,250),(4,90)")
        tk.must_query("select id from s order by id").check(
            [("1",), ("2",), ("3",), ("4",)])
        tk.must_query("select id from s partition (p0) order by id").check(
            [("1",), ("4",)])
        tk.must_query("select id from s partition (p1, pmax) order by id"
                      ).check([("2",), ("3",)])

    def test_no_partition_for_value(self, tk):
        tk.must_exec("""create table s (a int) partition by range (a)
            (partition p0 values less than (10))""")
        e = tk.exec_error("insert into s values (10)")
        assert "no partition" in str(e)

    def test_pruning_eq_and_range(self, tk):
        tk.must_exec("""create table s (id int, amount int)
            partition by range (amount) (
              partition p0 values less than (100),
              partition p1 values less than (200),
              partition pmax values less than maxvalue)""")
        tk.must_exec("insert into s values (1,50),(2,150),(3,250)")
        txt = _explain_text(tk, "select * from s where amount = 150")
        assert "partition:p1" in txt
        txt = _explain_text(tk, "select * from s where amount < 100")
        assert "partition:p0" in txt and "p1" not in txt
        txt = _explain_text(tk, "select * from s where amount >= 200")
        assert "partition:pmax" in txt and "p0" not in txt
        # results stay correct under pruning
        tk.must_query("select count(*) from s where amount = 150").check(
            [("1",)])
        tk.must_query("select count(*) from s where amount < 100").check(
            [("1",)])

    def test_update_moves_row_between_partitions(self, tk):
        tk.must_exec("""create table s (id int, amount int)
            partition by range (amount) (
              partition p0 values less than (100),
              partition p1 values less than (200))""")
        tk.must_exec("insert into s values (1, 50)")
        tk.must_exec("update s set amount = 150 where id = 1")
        tk.must_query("select count(*) from s partition (p0)").check([("0",)])
        tk.must_query("select id from s partition (p1)").check([("1",)])

    def test_null_routes_to_first(self, tk):
        tk.must_exec("""create table s (a int) partition by range (a)
            (partition p0 values less than (10),
             partition p1 values less than (20))""")
        tk.must_exec("insert into s values (null)")
        tk.must_query("select count(*) from s partition (p0)").check([("1",)])

    def test_year_func_partitioning(self, tk):
        tk.must_exec("""create table o (d date, v int)
            partition by range (year(d)) (
              partition y94 values less than (1995),
              partition y95 values less than (1996),
              partition ymax values less than maxvalue)""")
        tk.must_exec("insert into o values ('1994-03-01',1),"
                     "('1995-07-01',2),('1999-01-01',3)")
        tk.must_query("select count(*) from o partition (y95)").check(
            [("1",)])
        tk.must_query("select v from o where d = '1995-07-01'").check(
            [("2",)])

    def test_range_not_increasing_rejected(self, tk):
        e = tk.exec_error("""create table s (a int) partition by range (a)
            (partition p0 values less than (20),
             partition p1 values less than (10))""")
        assert "strictly increasing" in str(e)


class TestHashPartition:
    def test_route_and_point_read(self, tk):
        tk.must_exec("""create table h (id int primary key, v int)
            partition by hash (id) partitions 4""")
        tk.must_exec("insert into h values (1,10),(2,20),(3,30),(4,40),(5,50)")
        tk.must_query("select v from h where id = 3").check([("30",)])
        tk.must_query("select count(*) from h").check([("5",)])

    def test_rows_spread_across_partitions(self, tk):
        tk.must_exec("""create table h (id int primary key)
            partition by hash (id) partitions 2""")
        tk.must_exec("insert into h values (1),(2),(3),(4)")
        tk.must_query("select count(*) from h partition (p0)").check([("2",)])
        tk.must_query("select count(*) from h partition (p1)").check([("2",)])


class TestListPartition:
    def test_route_and_null(self, tk):
        tk.must_exec("""create table l (r int, v int)
            partition by list (r) (
              partition pa values in (1, 2),
              partition pb values in (3, null))""")
        tk.must_exec("insert into l values (1,1),(3,3),(null,9)")
        tk.must_query("select count(*) from l partition (pb)").check(
            [("2",)])
        e = tk.exec_error("insert into l values (7,7)")
        assert "no partition" in str(e)

    def test_pruning_eq(self, tk):
        tk.must_exec("""create table l (r int) partition by list (r) (
            partition pa values in (1), partition pb values in (2))""")
        txt = _explain_text(tk, "select * from l where r = 2")
        assert "partition:pb" in txt


class TestPartitionDDL:
    def test_add_partition(self, tk):
        tk.must_exec("""create table s (a int) partition by range (a)
            (partition p0 values less than (10))""")
        tk.must_exec("alter table s add partition "
                     "(partition p1 values less than (20))")
        tk.must_exec("insert into s values (15)")
        tk.must_query("select count(*) from s partition (p1)").check(
            [("1",)])
        # after MAXVALUE: rejected
        tk.must_exec("alter table s add partition "
                     "(partition pm values less than maxvalue)")
        e = tk.exec_error("alter table s add partition "
                          "(partition px values less than (99))")
        assert "strictly increasing" in str(e)

    def test_drop_partition(self, tk):
        tk.must_exec("""create table s (a int) partition by range (a)
            (partition p0 values less than (10),
             partition p1 values less than (20))""")
        tk.must_exec("insert into s values (5), (15)")
        tk.must_exec("alter table s drop partition p0")
        tk.must_query("select a from s").check([("15",)])
        e = tk.exec_error("alter table s drop partition p1")
        assert "Cannot remove all partitions" in str(e)

    def test_truncate_partition(self, tk):
        tk.must_exec("""create table s (a int) partition by range (a)
            (partition p0 values less than (10),
             partition p1 values less than (20))""")
        tk.must_exec("insert into s values (5), (15)")
        tk.must_exec("alter table s truncate partition p0")
        tk.must_query("select a from s").check([("15",)])

    def test_unique_key_must_cover_partition_col(self, tk):
        e = tk.exec_error("""create table bad (a int primary key, b int)
            partition by range (b) (partition p0 values less than (10))""")
        assert "PRIMARY KEY" in str(e)
        e = tk.exec_error("""create table bad2 (a int, b int, unique key(a))
            partition by hash (b) partitions 2""")
        assert "UNIQUE INDEX" in str(e)

    def test_show_create_table_includes_partitions(self, tk):
        tk.must_exec("""create table s (a int) partition by range (a)
            (partition p0 values less than (10))""")
        ddl = tk.must_query("show create table s").rows[0][1]
        if isinstance(ddl, bytes):
            ddl = ddl.decode()
        assert "PARTITION BY RANGE" in ddl and "`p0`" in ddl

    def test_truncate_table_reallocates_partition_ids(self, tk):
        tk.must_exec("""create table s (a int) partition by hash (a)
            partitions 2""")
        tk.must_exec("insert into s values (1),(2),(3)")
        tk.must_exec("truncate table s")
        tk.must_query("select count(*) from s").check([("0",)])
        tk.must_exec("insert into s values (9)")
        tk.must_query("select count(*) from s").check([("1",)])

    def test_partition_mgmt_on_nonpartitioned(self, tk):
        tk.must_exec("create table plain (a int)")
        e = tk.exec_error("alter table plain drop partition p0")
        assert "not partitioned" in str(e)
        e = tk.exec_error("select * from plain partition (p0)")
        assert "PARTITION" in str(e)


class TestPartitionIndexes:
    def test_add_index_backfills_all_partitions(self, tk):
        """Regression: backfill must scan partition physical ids, not the
        logical table id (which holds no rows)."""
        tk.must_exec("""create table t (id int, v int)
            partition by range (id) (
              partition p0 values less than (10),
              partition p1 values less than (20))""")
        tk.must_exec("insert into t values (1, 100), (15, 200)")
        tk.must_exec("alter table t add index iv (id)")
        tk.must_query("select v from t where id = 1").check([("100",)])
        tk.must_query("select v from t where id = 15").check([("200",)])

    def test_unique_index_must_cover_partition_col(self, tk):
        tk.must_exec("""create table t (id int, v int)
            partition by range (id) (
              partition p0 values less than (10),
              partition p1 values less than (20))""")
        e = tk.exec_error("alter table t add unique index uv (v)")
        assert "partitioning function" in str(e)
        # covering the partition column is fine
        tk.must_exec("alter table t add unique index uid (id)")

    def test_drop_index_cleans_partition_ranges(self, tk):
        tk.must_exec("""create table t (id int, v int)
            partition by hash (id) partitions 2""")
        tk.must_exec("insert into t values (1,10),(2,20)")
        tk.must_exec("alter table t add index iv (v)")
        tk.must_exec("alter table t drop index iv")
        # re-creating and using the index works (no stale entries)
        tk.must_exec("alter table t add index iv (v)")
        tk.must_query("select id from t where v = 20").check([("2",)])

    def test_stats_delta_rolls_up_to_logical_table(self, tk):
        tk.must_exec("""create table t (id int) partition by hash (id)
            partitions 2""")
        tk.must_exec("insert into t values (1),(2),(3)")
        infos = tk.session.infoschema()
        logical = infos.table_by_name("test", "t")
        counts = tk.session.domain.stats_worker.modify_counts
        assert counts.get(logical.id, 0) >= 3
        for d in logical.partition.defs:
            assert d.id not in counts


class TestPartitionTxn:
    def test_uncommitted_writes_visible_and_rollback(self, tk):
        tk.must_exec("""create table h (id int primary key)
            partition by hash (id) partitions 2""")
        tk.must_exec("insert into h values (1),(2)")
        tk.must_exec("begin")
        tk.must_exec("insert into h values (3)")
        tk.must_query("select count(*) from h").check([("3",)])
        tk.must_exec("rollback")
        tk.must_query("select count(*) from h").check([("2",)])

    def test_isolation_across_sessions(self, tk):
        tk.must_exec("""create table h (id int primary key)
            partition by hash (id) partitions 2""")
        tk2 = tk.new_session()
        tk2.must_exec("use test")
        tk.must_exec("begin")
        tk.must_exec("insert into h values (1)")
        tk2.must_query("select count(*) from h").check([("0",)])
        tk.must_exec("commit")
        tk2.must_query("select count(*) from h").check([("1",)])


class TestPartitionBackup:
    def test_physical_backup_restore_roundtrip(self, tk, tmp_path):
        from tidb_tpu import br
        tk.must_exec("""create table s (a int) partition by hash (a)
            partitions 2""")
        tk.must_exec("insert into s values (1),(2),(3),(4)")
        meta = br.backup_database(tk.session, "test", str(tmp_path / "b"))
        t = next(x for x in meta["tables"] if x["name"] == "s")
        assert t["rows"] == 4
        tk.must_exec("create database r2")
        br.restore_database(tk.session, str(tmp_path / "b"), "r2")
        tk.must_query("select count(*) from r2.s").check([("4",)])
        # both tables remain independently writable (fresh physical ids)
        tk.must_exec("insert into r2.s values (5)")
        tk.must_query("select count(*) from test.s").check([("4",)])


class TestPartitionAggDevicePath:
    def test_group_by_over_partitions(self, tk):
        tk.must_exec("""create table s (id int, grp int, amount int)
            partition by range (amount) (
              partition p0 values less than (100),
              partition p1 values less than (200),
              partition pmax values less than maxvalue)""")
        rows = []
        for i in range(300):
            rows.append(f"({i}, {i % 3}, {i})")
        tk.must_exec("insert into s values " + ",".join(rows))
        tk.must_query(
            "select grp, count(*), sum(amount) from s group by grp "
            "order by grp").check(
            [("0", "100", str(sum(range(0, 300, 3)))),
             ("1", "100", str(sum(range(1, 300, 3)))),
             ("2", "100", str(sum(range(2, 300, 3))))])


class TestColumnsPartitioning:
    """RANGE/LIST COLUMNS(c) — typed single-column partitioning (strings,
    dates compare in the column domain, no integer function required;
    reference: ddl/partition.go checkColumnsPartition,
    rule_partition_processor.go). Multi-column COLUMNS tuples are not
    supported (single-column covers the dominant usage)."""

    def test_range_columns_string(self, tk):
        tk.must_exec("""create table rcs (name varchar(20), v bigint)
            partition by range columns(name) (
              partition pa values less than ('h'),
              partition pm values less than ('q'),
              partition pz values less than (maxvalue))""")
        tk.must_exec("insert into rcs values ('alice', 1), ('mike', 2), "
                     "('zara', 3)")
        tk.must_query("select name from rcs partition (pa)").check(
            [("alice",)])
        tk.must_query("select name from rcs partition (pz)").check(
            [("zara",)])
        # pruning: a range predicate narrows to one partition
        plan = "\n".join(" ".join(map(str, r)) for r in tk.must_query(
            "explain select * from rcs where name < 'b'").rows)
        assert "partition:pa" in plan, plan

    def test_range_columns_date(self, tk):
        tk.must_exec("""create table rcd (d date, v bigint)
            partition by range columns(d) (
              partition p1 values less than ('2020-01-01'),
              partition p2 values less than (maxvalue))""")
        tk.must_exec("insert into rcd values ('2019-06-01', 1), "
                     "('2021-06-01', 2)")
        tk.must_query("select v from rcd partition (p1)").check([("1",)])
        tk.must_query("select v from rcd partition (p2)").check([("2",)])

    def test_list_columns_string(self, tk):
        tk.must_exec("""create table lcs (region varchar(10), v bigint)
            partition by list columns(region) (
              partition pe values in ('east', 'ne'),
              partition pw values in ('west'))""")
        tk.must_exec("insert into lcs values ('east', 1), ('ne', 2), "
                     "('west', 3)")
        tk.must_query("select sum(v) from lcs partition (pe)").check(
            [("3",)])
        e = tk.exec_error("insert into lcs values ('south', 9)")
        assert "partition" in str(e).lower()
