"""BACKUP / RESTORE, logical dump, checkpointed import (reference:
br/pkg/task/backup.go, dumpling/export/dump.go, lightning checkpoints)."""

import json
import os

import pytest

from tidb_tpu.errors import TiDBError
from tidb_tpu import br
from tidb_tpu.testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.must_exec(
        "create table items (id int primary key, price decimal(10,2), "
        "name varchar(40), ts datetime, note varchar(40))")
    tk.must_exec(
        "insert into items values "
        "(1, 19.99, 'widget', '2024-05-01 10:30:00', null), "
        "(2, 0.50, 'it''s', '2024-05-02 00:00:00', 'line1\\nline2'), "
        "(3, -7.25, 'naïve', '2024-05-03 23:59:59', '')")
    tk.must_exec("create index i_name on items (name)")
    tk.must_exec("create table empty_t (a int primary key)")
    return tk


EXPECT = [("1", "19.99", "widget", "2024-05-01 10:30:00", None),
          ("2", "0.50", "it's", "2024-05-02 00:00:00", "line1\nline2"),
          ("3", "-7.25", "naïve", "2024-05-03 23:59:59", "")]


def test_backup_restore_roundtrip(tk, tmp_path):
    d = str(tmp_path / "bk")
    r = tk.must_query(f"backup database test to '{d}'")
    assert ("items", "3") in {tuple(x) for x in r.rows}
    assert os.path.exists(os.path.join(d, "backupmeta.json"))
    # restore into a fresh database
    tk.must_query(f"restore database test2 from '{d}'")
    tk.must_query("select * from test2.items order by id").check(EXPECT)
    # indexes restored and consistent
    tk.must_exec("use test2")
    tk.must_exec("admin check table items")
    tk.must_exec("analyze table items")
    r = tk.must_query("explain select * from items where name = 'widget'")
    # the restored index exists in the catalog
    info = tk.session.infoschema().table_by_name("test2", "items")
    assert info.find_index("i_name") is not None
    tk.must_query("select count(*) from test2.empty_t").check([("0",)])


def test_restore_refuses_overwrite(tk, tmp_path):
    d = str(tmp_path / "bk2")
    tk.must_exec(f"backup database test to '{d}'")
    e = tk.exec_error(f"restore database test from '{d}'")
    assert "already exists" in str(e)


def test_backup_is_snapshot_consistent(tk, tmp_path):
    """Writes racing the backup don't leak into it (one read snapshot)."""
    d = str(tmp_path / "bk3")
    meta = br.backup_database(tk.session, "test", d)
    tk.must_exec("insert into items values (99, 1, 'post', null, null)")
    rows = sum(t["rows"] for t in meta["tables"])
    assert rows == 3


def test_dump_sql_and_reimport(tk, tmp_path):
    d = str(tmp_path / "dump")
    out = br.dump_database(tk.session, "test", d, fmt="sql")
    assert {"name": "items", "rows": 3} in out["tables"]
    assert os.path.exists(os.path.join(d, "test.items-schema.sql"))
    res = br.import_dump(tk.session, d, db_name="test3")
    tk.must_query("select * from test3.items order by id").check(EXPECT)


def test_dump_csv(tk, tmp_path):
    d = str(tmp_path / "csv")
    br.dump_database(tk.session, "test", d, fmt="csv")
    body = open(os.path.join(d, "test.items.csv")).read()
    assert "widget" in body and "\\N" in body  # NULL marker


def test_import_crash_resume(tk, tmp_path):
    """Crash mid-import; a re-run resumes from the checkpoint without
    duplicating committed rows."""
    tk.must_exec("create table big (a int primary key, b int)")
    vals = ",".join(f"({i}, {i * 3})" for i in range(900))
    tk.must_exec(f"insert into big values {vals}")
    d = str(tmp_path / "dump2")
    br.dump_database(tk.session, "test", d, fmt="sql")
    with pytest.raises(TiDBError):
        br.import_dump(tk.session, d, db_name="t4", crash_after_batches=2)
    ck = os.path.join(d, "_import_checkpoint.json")
    assert os.path.exists(ck)
    assert json.load(open(ck))["stmts_done"] >= 1
    br.import_dump(tk.session, d, db_name="t4")  # resume
    assert not os.path.exists(ck)
    tk.must_query("select count(*), sum(b) from t4.big").check(
        [(str(900), str(sum(i * 3 for i in range(900))))])
    tk.must_query("select count(*) from t4.items").check([("3",)])


def test_backup_requires_super(tk, tmp_path):
    from tidb_tpu.session import Session
    tk.must_exec("create user 'nob'@'%'")
    tk.must_exec("grant select on test.* to 'nob'@'%'")
    s = Session(tk.session.domain)
    s.user = "nob@%"
    with pytest.raises(TiDBError):
        s.execute(f"backup database test to '{tmp_path}/x'")


def test_csv_dump_import_roundtrip(tk, tmp_path):
    """CSV-format dump loads back through the checkpointed importer
    (reference: lightning/mydump csv path)."""
    from tidb_tpu import br
    tk.must_exec("create table cx (id int primary key, nm varchar(8), v int)")
    tk.must_exec("insert into cx values (1,'a',10),(2,NULL,20)")
    br.dump_database(tk.session, "test", str(tmp_path / "d"), fmt="csv")
    tk.must_exec("create database csvr")
    br.import_dump(tk.session, str(tmp_path / "d"), "csvr")
    tk.must_query("select id, nm, v from csvr.cx order by id").check(
        [("1", "a", "10"), ("2", None, "20")])


def test_csv_tricky_values_roundtrip(tk, tmp_path):
    """Regression: float-lookalike strings, leading zeros, and the literal
    NULL sentinel must survive a csv dump/import round trip."""
    from tidb_tpu import br
    tk.must_exec("create table tricky (id int primary key, s varchar(12))")
    tk.must_exec("insert into tricky values "
                 "(1,'nan'),(2,'0010'),(3,'12_3'),(4,'\\\\N'),(5,NULL)")
    br.dump_database(tk.session, "test", str(tmp_path / "d"), fmt="csv")
    tk.must_exec("create database trickyr")
    br.import_dump(tk.session, str(tmp_path / "d"), "trickyr")
    tk.must_query("select s from trickyr.tricky order by id").check(
        [("nan",), ("0010",), ("12_3",), ("\\N",), (None,)])


def test_sql_dump_quotes_float_lookalikes(tk, tmp_path):
    from tidb_tpu import br
    tk.must_exec("create table tq (id int primary key, s varchar(8))")
    tk.must_exec("insert into tq values (1,'nan'),(2,'0010')")
    br.dump_database(tk.session, "test", str(tmp_path / "d2"))
    tk.must_exec("create database tqr")
    br.import_dump(tk.session, str(tmp_path / "d2"), "tqr")
    tk.must_query("select s from tqr.tq order by id").check(
        [("nan",), ("0010",)])
