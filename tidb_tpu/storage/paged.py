"""Disk-backed paged column storage — the larger-than-memory scan path.

The reference streams arbitrarily large operands through cop paging
(reference kv/kv.go:349-350 Paging{MinPagingSize,MaxPagingSize}) and
chunk spill files (reference util/chunk/disk.go:34 ListInDisk); its scans
never require a table to fit in RAM. This engine's analog: a table's
columns live in append-only binary files on disk, readers map them with
``np.memmap`` (read-only), and the device pipelines slice fixed-size row
pages out of the maps — each slice reads only its file pages, the OS page
cache owns residency, and peak query RSS is bounded by
``pages_in_flight x page_bytes`` instead of the table size.

Write path (bulk load / datagen, the Lightning physical-import role):
``PagedTableWriter`` appends page batches column-by-column; ``finalize``
installs memmap-backed Columns into the columnar cache, so every existing
executor (host or device) sees an ordinary ``_View`` — paging is a
storage property, not a new executor protocol.

String columns are stored dictionary-encoded (int32 code files + a
dictionary sidecar) and surface as ``LazyDictColumn``: device paths read
the codes directly; only a host-side row access materializes bytes.
"""

from __future__ import annotations

import itertools
import json
import os
import pickle
import threading

import numpy as np

from ..utils.chunk import Column, LazyDictColumn, false_nulls, np_dtype_for

#: default rows per page streamed through the device pipeline — 4M rows
#: x ~40B/row ~ 160MB per in-flight block: big enough to amortize the
#: dispatch/tunnel overhead, small enough that double-buffered transfer +
#: partial-agg state stays far under one chip's HBM.
DEFAULT_PAGE_ROWS = 1 << 22


class _ColWriter:
    __slots__ = ("path", "dtype", "f", "n")

    def __init__(self, path: str, dtype):
        self.path = path
        self.dtype = np.dtype(dtype)
        self.f = open(path, "wb")
        self.n = 0

    def append(self, arr: np.ndarray):
        a = np.ascontiguousarray(arr, dtype=self.dtype)
        a.tofile(self.f)
        self.n += len(a)

    def close(self):
        self.f.close()


class PagedTableWriter:
    """Append page batches for one table; finalize into memmap Columns.

    Usage::

        w = PagedTableWriter(dir, info)            # schema from TableInfo
        w.append({"l_orderkey": arr, ...})         # one page at a time
        w.set_dictionary("l_returnflag", [b"A", b"N", b"R"])  # str cols
        columns, handles = w.finalize()            # memmap-backed

    String columns append int32 CODES into their (sorted, deduplicated)
    dictionary — exactly the Column.set_dict contract, so device
    compare/IN/min-max over codes stays order-faithful.
    """

    def __init__(self, root: str, info):
        os.makedirs(root, exist_ok=True)
        self.root = root
        self.info = info
        self._cols = {}      # name -> ColumnInfo
        self._writers = {}   # name -> _ColWriter
        self._dicts = {}     # name -> np.ndarray(object), sorted
        for c in info.public_columns():
            self._cols[c.name] = c

    def _writer(self, name: str) -> _ColWriter:
        w = self._writers.get(name)
        if w is None:
            c = self._cols[name]
            dt = np_dtype_for(c.ftype)
            if dt is object:
                dt = np.int32  # dictionary codes
            w = _ColWriter(os.path.join(self.root, f"{name}.bin"), dt)
            self._writers[name] = w
        return w

    def set_dictionary(self, name: str, values):
        u = np.asarray(values, dtype=object)
        if len(u) > 1 and not all(u[i] < u[i + 1] for i in range(len(u) - 1)):
            raise ValueError("paged string dictionary must be sorted "
                             "and deduplicated")
        self._dicts[name] = u

    def append(self, data: dict):
        """One page: {col_name: np array} — codes for string columns."""
        for name, arr in data.items():
            self._writer(name).append(arr)

    def finalize(self):
        """Close files, write the manifest, and return
        ({col_id: Column}, handles) ready for install_bulk. Handles are a
        lazily-materialized 1..N range (row ids are dense by
        construction in the bulk-load path)."""
        n = None
        manifest = {"columns": {}}
        for name, w in self._writers.items():
            w.close()
            if n is None:
                n = w.n
            elif w.n != n:
                raise ValueError(
                    f"paged column {name} has {w.n} rows, expected {n}")
            if (np_dtype_for(self._cols[name].ftype) is object
                    and name not in self._dicts):
                # codes without a dictionary would silently surface as
                # integers on every read path — refuse at load time
                raise ValueError(
                    f"string column {name} was appended without "
                    f"set_dictionary()")
            manifest["columns"][name] = {"dtype": w.dtype.str, "rows": w.n}
        n = n or 0
        for name, u in self._dicts.items():
            with open(os.path.join(self.root, f"{name}.dict"), "wb") as f:
                pickle.dump(u, f)
        with open(os.path.join(self.root, "MANIFEST.json"), "w") as f:
            json.dump(manifest, f)
        return open_paged_columns(self.root, self.info), _range_handles(n)


class LazyRangeHandles:
    """Dense 1..n handle vector that materializes only when numpy touches
    it (writes/tombstones/_tidb_rowid access — never a plain scan). A
    600M-row bulk load must not pin a 4.8GB arange just to exist."""

    __slots__ = ("n", "_arr")

    def __init__(self, n: int):
        self.n = n
        self._arr = None

    def __len__(self):
        return self.n

    def _mat(self) -> np.ndarray:
        if self._arr is None:
            self._arr = np.arange(1, self.n + 1, dtype=np.int64)
        return self._arr

    def __array__(self, dtype=None, copy=None):
        a = self._mat()
        return a if dtype is None else a.astype(dtype)

    def __getitem__(self, i):
        return self._mat()[i]

    @property
    def dtype(self):
        return np.dtype(np.int64)


def _range_handles(n: int):
    return LazyRangeHandles(n)


def open_paged_columns(root: str, info) -> dict:
    """{col_id: Column} over the table's on-disk column files (read-only
    memmaps; zero bytes resident until a page is touched)."""
    with open(os.path.join(root, "MANIFEST.json")) as f:
        manifest = json.load(f)
    out = {}
    for c in info.public_columns():
        spec = manifest["columns"].get(c.name)
        if spec is None:
            continue
        mm = np.memmap(os.path.join(root, f"{c.name}.bin"), mode="r",
                       dtype=np.dtype(spec["dtype"]), shape=(spec["rows"],))
        dict_path = os.path.join(root, f"{c.name}.dict")
        if os.path.exists(dict_path):
            with open(dict_path, "rb") as f:
                uniques = pickle.load(f)
            out[c.id] = LazyDictColumn(c.ftype, mm, uniques)
        else:
            out[c.id] = Column(c.ftype, mm, false_nulls(spec["rows"]))
    return out


# ---------------------------------------------------------------------------
# hybrid-join spill pages (executor/hybrid_join.py)
# ---------------------------------------------------------------------------

#: process-wide registry of open spill sets: the chaos invariant is that
#: this drains to ZERO after every query — a fence/OOM/injected fault
#: mid-probe must not leak partition pages on disk (tests/chaos_harness
#: asserts spill_outstanding() between seeds)
_SPILL_LOCK = threading.Lock()
_SPILL_OPEN: dict[int, "SpillSet"] = {}
_SPILL_SEQ = itertools.count(1)

SPILL_STATS = {
    "spill_sets_opened": 0,   # lifetime SpillSets created
    "spill_writes": 0,        # partition pages written
    "spill_bytes_written": 0,  # lifetime bytes through the spill path
}


class SpillSet:
    """Host columnar pages for the hybrid hash join's OVERFLOW build
    partitions: the radix partitions that do not fit the residency
    ledger's free share are gathered column-by-column into per-partition
    binary page files (one compact sequential file per column — a
    memmap-backed fact's random partition rows become sequential reads
    for the host probe pass) and read back as read-only memmaps.

    Dictionary-encoded string columns spill their int CODES (the caller
    keeps the dictionary — same contract as the paged table format
    above).  ``close()`` deletes every page and unregisters the set; the
    drained invariant (spill_outstanding) is chaos-checked."""

    def __init__(self, tag: str = ""):
        import tempfile
        self.root = tempfile.mkdtemp(prefix=f"tidb-hj-spill-{tag}-")
        self.token = next(_SPILL_SEQ)
        self.bytes = 0
        self._parts: dict[int, dict] = {}  # pid -> {key: (path, dtype, n)}
        self._closed = False
        with _SPILL_LOCK:
            _SPILL_OPEN[self.token] = self
            SPILL_STATS["spill_sets_opened"] += 1

    def write(self, pid: int, arrays: dict):
        """Spill one partition: arrays maps a caller key (the leaf-local
        column index) -> (data, nulls) numpy arrays (codes for dict
        columns — object arrays are a caller bug and refused)."""
        from ..utils import failpoint
        # chaos hook: a `spill-fail` action models a disk-full / IO error
        # mid-spill — the join must abort classified with pages drained
        failpoint.inject("device-join-spill")
        part = self._parts.setdefault(pid, {})
        written = 0
        for key, (data, nulls) in arrays.items():
            d = np.ascontiguousarray(data)
            if d.dtype == object:
                raise ValueError(
                    "object array reached the spill writer (dictionary "
                    "columns must spill their codes)")
            nl = np.ascontiguousarray(nulls, dtype=bool)
            dp = os.path.join(self.root, f"p{pid}c{key}.bin")
            npth = os.path.join(self.root, f"p{pid}c{key}.null")
            d.tofile(dp)
            nl.tofile(npth)
            part[key] = (dp, npth, d.dtype.str, len(d))
            written += d.nbytes + nl.nbytes
        self.bytes += written
        with _SPILL_LOCK:
            SPILL_STATS["spill_writes"] += 1
            SPILL_STATS["spill_bytes_written"] += written

    def read(self, pid: int) -> dict:
        """{key: (data, nulls)} read-only memmaps of one spilled
        partition's pages."""
        out = {}
        for key, (dp, npth, dt, n) in self._parts.get(pid, {}).items():
            d = np.memmap(dp, mode="r", dtype=np.dtype(dt), shape=(n,))
            nl = np.memmap(npth, mode="r", dtype=np.bool_, shape=(n,))
            out[key] = (d, nl)
        return out

    def close(self):
        """Delete every page and unregister (idempotent).  Called from
        the hybrid join's ``finally`` so an abort at ANY point — fence,
        OOM, injected spill failure, kill — drains the pages."""
        if self._closed:
            return
        self._closed = True
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)
        self._parts.clear()
        with _SPILL_LOCK:
            _SPILL_OPEN.pop(self.token, None)


def spill_outstanding() -> dict:
    """{"open_sets": n, "open_bytes": b} — the drained invariant reads
    zero/zero between queries."""
    with _SPILL_LOCK:
        sets = list(_SPILL_OPEN.values())
    return {"open_sets": len(sets),
            "open_bytes": sum(s.bytes for s in sets)}


def is_paged(col: Column) -> bool:
    """True when the column's backing array is a disk memmap (scans must
    stream pages rather than materialize/transfer the whole column)."""
    d = col._dict[0] if isinstance(col, LazyDictColumn) else col.data
    return isinstance(d, np.memmap)


def chunk_is_paged(chunk) -> bool:
    return any(is_paged(c) for c in chunk.columns)
