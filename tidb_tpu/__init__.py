"""tpu-htap: a TPU-native distributed SQL engine with TiDB's capability surface.

Architecture (see SURVEY.md §7): the control plane — MySQL-dialect parser,
cost-based planner, MVCC transactions, online DDL, catalog — runs host-side in
Python (C++ for the hot codecs/storage in later rounds); the data plane
executes columnar batches as JAX/XLA kernels, with ``shard_map`` collectives
over ICI/DCN taking the role of the reference's MPP exchanges
(reference: planner/core/fragment.go, store/copr/mpp.go) and coprocessor
fan-out (reference: store/copr/coprocessor.go).

Import side effect: enables jax x64 so decimal aggregation (scaled int64) is
exact on device — the north star requires bit-exact parity (BASELINE.md).
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
